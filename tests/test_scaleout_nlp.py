"""Distributed NLP tests — reference `DistributedWord2VecTest`,
`DistributedGloveTest`, `WordCountTest` parity (in-process rig,
`BaseTestDistributed.java:34-98` style) + config registry
(`TestZookeeperRegister` parity)."""

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.config_registry import (
    ConfigRegistry, ConfigRegistryServer, RemoteConfigRegistry)
from deeplearning4j_tpu.scaleout import (
    DistributedGlove, DistributedWord2Vec, distributed_word_count)

CORPUS = [
    "the king rules the kingdom with a crown",
    "the queen rules the kingdom with grace",
    "king and queen sit on the royal throne",
    "the cat chases the mouse in the house",
    "a cat and a mouse live in the old house",
    "dogs chase cats and cats chase mice daily",
    "the king wears the royal crown of gold",
    "the queen wears a golden crown today",
    "mouse and cat play in the house garden",
    "royal king and royal queen rule together",
] * 6


class TestDistributedWordCount:
    def test_counts_match_serial(self):
        c = distributed_word_count(CORPUS, n_workers=3)
        assert c.get_count("the") > 0
        # spot check against direct count
        want = sum(s.split().count("king") for s in CORPUS)
        assert c.get_count("king") == want


class TestDistributedWord2Vec:
    @pytest.mark.parametrize("hogwild", [False, True])
    def test_trains_and_matches_single_process_quality(self, hogwild):
        w2v = DistributedWord2Vec(
            CORPUS, vector_length=24, window=4, min_word_frequency=2,
            negative=3, epochs=6, batch_size=256, seed=7,
            n_workers=3, hogwild=hogwild)
        w2v.fit()
        # related words should be closer than unrelated ones
        assert w2v.similarity("king", "queen") > w2v.similarity(
            "king", "mouse")
        v = w2v.vector("king")
        assert v.shape == (24,) and np.all(np.isfinite(np.asarray(v)))

    def test_distributed_adagrad_merges_history_and_converges(self):
        """use_adagrad must reach the distributed path too (r3 review):
        worker h-deltas (sums of g^2) merge additively into shared
        accumulators, and quality still holds."""
        w2v = DistributedWord2Vec(
            CORPUS, vector_length=24, window=4, min_word_frequency=2,
            negative=3, epochs=6, batch_size=256, seed=7,
            n_workers=3, use_adagrad=True)
        w2v.fit()
        assert w2v.similarity("king", "queen") > w2v.similarity(
            "king", "mouse")

    def test_tracker_saw_jobs(self):
        from deeplearning4j_tpu.parallel.coordinator import StateTracker
        tr = StateTracker()
        DistributedWord2Vec(CORPUS[:20], vector_length=8, epochs=1,
                            min_word_frequency=2, n_workers=2,
                            tracker=tr).fit()
        assert tr.count("jobs_done") > 0


class TestDistributedGlove:
    def test_trains_sane_vectors(self):
        g = DistributedGlove(CORPUS, vector_length=16, window=6,
                             epochs=8, lr=0.05, seed=3, n_workers=3)
        g.fit()
        v = g.vector("king")
        assert v.shape == (16,) and np.all(np.isfinite(np.asarray(v)))
        assert g.similarity("king", "queen") > g.similarity("king", "mouse")


class TestConfigRegistry:
    def test_file_backed_roundtrip(self, tmp_path):
        reg = ConfigRegistry(str(tmp_path))
        reg.register("host1/2510/conf", {"lr": 0.1, "layers": [4, 3]})
        back = reg.retrieve("host1/2510/conf")
        assert back == {"lr": 0.1, "layers": [4, 3]}
        assert reg.list_keys() == ["host1/2510/conf"]
        reg.delete("host1/2510/conf")
        assert reg.retrieve("host1/2510/conf") is None

    def test_http_server_roundtrip(self, tmp_path):
        srv = ConfigRegistryServer(str(tmp_path)).start()
        try:
            client = RemoteConfigRegistry(srv.url)
            client.register("job/42", {"batch": 128})
            assert client.retrieve("job/42") == {"batch": 128}
            assert "job/42" in client.list_keys()
            assert client.retrieve("missing") is None
        finally:
            srv.stop()
