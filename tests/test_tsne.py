"""t-SNE smoke tests — reference `plot/TsneTest.java` /
`BarnesHutTsneTest.java` parity: small real data, check the embedding
separates structure and the loss decreases."""

import numpy as np

from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne


def _blob_data(n_per=20, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(n_per, 10) * 0.3
    b = rng.randn(n_per, 10) * 0.3 + 4.0
    x = np.vstack([a, b]).astype(np.float32)
    labels = np.array([0] * n_per + [1] * n_per)
    return x, labels


def _separation(y, labels):
    ya, yb = y[labels == 0], y[labels == 1]
    between = np.linalg.norm(ya.mean(0) - yb.mean(0))
    within = (np.linalg.norm(ya - ya.mean(0), axis=1).mean() +
              np.linalg.norm(yb - yb.mean(0), axis=1).mean()) / 2
    return between / max(within, 1e-9)


class TestTsne:
    def test_p_rows_sum_and_symmetry(self):
        x, _ = _blob_data()
        t = Tsne(perplexity=10.0)
        p = np.asarray(t.compute_p(x))
        assert np.allclose(p, p.T, atol=1e-7)
        assert np.isclose(p.sum(), 1.0, atol=1e-5)
        assert np.all(np.diag(p) < 1e-6)

    def test_embedding_separates_blobs(self):
        # small-n settings: big-lr + 0.8 momentum defaults are tuned for
        # thousands of points and oscillate at n=40
        x, labels = _blob_data()
        t = Tsne(max_iter=600, perplexity=10.0, seed=0, learning_rate=10.0,
                 final_momentum=0.5, stop_lying_iter=100, exaggeration=4.0)
        y = t.calculate(x)
        assert y.shape == (40, 2)
        assert np.all(np.isfinite(y))
        assert _separation(y, labels) > 2.0
        # KL decreased over the run
        assert t.kl_history[-1] < t.kl_history[0]


class TestBarnesHutTsne:
    def test_sparse_p_valid(self):
        x, _ = _blob_data(n_per=15)
        bh = BarnesHutTsne(perplexity=5.0)
        rows, cols, vals = bh.compute_gaussian_perplexity(x)
        assert rows[-1] == len(cols) == len(vals)
        assert np.isclose(vals.sum(), 1.0, atol=1e-6)
        assert np.all(vals >= 0)

    def test_embedding_separates_blobs(self):
        x, labels = _blob_data(n_per=15, seed=1)
        bh = BarnesHutTsne(max_iter=150, perplexity=5.0, theta=0.5, seed=0)
        y = bh.calculate(x)
        assert y.shape == (30, 2)
        assert np.all(np.isfinite(y))
        assert _separation(y, labels) > 2.0
        assert bh.params() is y
