"""Solvers: SGD/GD/CG/LBFGS minimize quadratics + updater chain behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, OptimizationAlgorithm
from deeplearning4j_tpu.optimize.solver import Objective, from_loss, optimize
from deeplearning4j_tpu.optimize.updater import adjust_gradient, init_updater

KEY = jax.random.PRNGKey(0)

# ill-conditioned quadratic: f(x) = 0.5 x^T A x - b^T x
_A = jnp.diag(jnp.array([1.0, 10.0, 100.0]))
_B = jnp.array([1.0, -2.0, 3.0])
_XSTAR = jnp.linalg.solve(_A, _B)


def _quad_loss(params, key):
    x = params["x"]
    return 0.5 * x @ _A @ x - _B @ x


@pytest.mark.parametrize("algo", [
    OptimizationAlgorithm.GRADIENT_DESCENT,
    OptimizationAlgorithm.CONJUGATE_GRADIENT,
    OptimizationAlgorithm.LBFGS,
    OptimizationAlgorithm.HESSIAN_FREE,  # falls back to CG this round
])
def test_line_searched_solvers_minimize_quadratic(algo):
    conf = NeuralNetConfiguration(optimization_algo=algo, num_iterations=100, lr=0.009)
    params = {"x": jnp.array([5.0, 5.0, 5.0])}
    out, scores = optimize(from_loss(_quad_loss), params, conf, KEY)
    f_out = float(_quad_loss(out, None))
    f_star = float(_quad_loss({"x": _XSTAR}, None))
    f_0 = float(_quad_loss(params, None))
    if algo == OptimizationAlgorithm.GRADIENT_DESCENT:
        # plain GD on a kappa=100 quadratic converges linearly at best;
        # expect a large relative reduction, not the optimum
        assert (f_out - f_star) < (f_0 - f_star) * 1e-2, (algo, f_out)
    else:
        assert f_out < f_star + 1e-2, (algo, f_out, f_star)


def test_sgd_solver_minimizes():
    conf = NeuralNetConfiguration(
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        num_iterations=300, lr=0.5, use_adagrad=True, momentum=0.0)
    params = {"x": jnp.array([5.0, 5.0, 5.0])}
    out, scores = optimize(from_loss(_quad_loss), params, conf, KEY)
    assert float(_quad_loss(out, None)) < float(_quad_loss(params, None))


def test_cg_beats_gd_on_ill_conditioned():
    def run(algo, iters):
        conf = NeuralNetConfiguration(optimization_algo=algo, num_iterations=iters, lr=0.009)
        out, _ = optimize(from_loss(_quad_loss), {"x": jnp.array([5.0, 5.0, 5.0])}, conf, KEY)
        return float(_quad_loss(out, None))

    f_star = float(_quad_loss({"x": _XSTAR}, None))
    assert run(OptimizationAlgorithm.CONJUGATE_GRADIENT, 60) - f_star < 1e-3


def test_updater_adagrad_and_momentum_schedule():
    conf = NeuralNetConfiguration(lr=0.1, use_adagrad=True, momentum=0.5,
                                  momentum_after=((10, 0.9),))
    params = {"w": jnp.ones(4)}
    grads = {"w": jnp.full(4, 2.0)}
    state = init_updater(params)
    step, state = adjust_gradient(conf, 0, grads, params, state)
    # adagrad first step: lr * g / (|g| + eps) ~= lr * sign(g), then momentum adds
    np.testing.assert_allclose(step["w"], 0.1 * np.ones(4), rtol=1e-4)
    # momentum schedule switches at iteration 10
    step2, _ = adjust_gradient(conf, 20, grads, params, state)
    assert np.all(np.asarray(step2["w"]) > np.asarray(step["w"]) * 0.9)


def test_unit_norm_constraint():
    conf = NeuralNetConfiguration(lr=1.0, use_adagrad=False, momentum=0.0,
                                  constrain_gradient_to_unit_norm=True)
    params = {"w": jnp.ones(4)}
    grads = {"w": jnp.full(4, 100.0)}
    step, _ = adjust_gradient(conf, 0, grads, params, init_updater(params))
    assert float(jnp.linalg.norm(step["w"])) == pytest.approx(1.0, rel=1e-4)


def test_custom_grad_objective_rbm_style():
    """Solvers accept Objectives that are not jax.grad of a loss (CD-k path)."""

    def gs(params, key):
        x = params["x"]
        return {"x": _A @ x - _B}, _quad_loss(params, key)

    def sc(params, key):
        return _quad_loss(params, key)

    conf = NeuralNetConfiguration(
        optimization_algo=OptimizationAlgorithm.CONJUGATE_GRADIENT,
        num_iterations=50)
    out, _ = optimize(Objective(gs, sc), {"x": jnp.zeros(3)}, conf, KEY)
    np.testing.assert_allclose(out["x"], _XSTAR, atol=3e-2)


@pytest.mark.parametrize("upd", ["adam", "nesterov", "rmsprop"])
def test_new_updaters_minimize_quadratic(upd):
    """Parity-plus updaters (VERDICT r1 #5): each drives the quadratic
    toward its minimum via the SGD solver path."""
    conf = NeuralNetConfiguration(
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        lr=0.1 if upd != "nesterov" else 0.01, num_iterations=400,
        updater=upd, momentum=0.9, termination_conditions=())
    params, scores = optimize(from_loss(_quad_loss),
                              {"x": jnp.zeros(3)}, conf, KEY)
    assert np.isfinite(np.asarray(scores)).all()
    np.testing.assert_allclose(np.asarray(params["x"]), np.asarray(_XSTAR),
                               atol=0.15)


def test_adam_bias_correction_first_step():
    """First Adam step ~= lr * sign(g) (bias-corrected), not lr*(1-b1)*g."""
    conf = NeuralNetConfiguration(updater="adam", lr=0.1)
    params = {"x": jnp.array([1.0, -2.0])}
    grads = {"x": jnp.array([0.5, -0.5])}
    step, state = adjust_gradient(conf, jnp.asarray(0), grads, params,
                                  init_updater(params))
    np.testing.assert_allclose(np.asarray(step["x"]),
                               [0.1, -0.1], rtol=1e-3)


def test_termination_conditions_pluggable():
    """Empty termination tuple runs all iterations; eps stops early."""
    conf_all = NeuralNetConfiguration(
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        lr=0.001, num_iterations=50, use_adagrad=False, momentum=0.0,
        termination_conditions=())
    conf_eps = conf_all.replace(termination_conditions=("eps",),
                                termination_eps=1e-2)
    _, s_all = optimize(from_loss(_quad_loss), {"x": jnp.zeros(3)},
                        conf_all, KEY)
    _, s_eps = optimize(from_loss(_quad_loss), {"x": jnp.zeros(3)},
                        conf_eps, KEY)
    # eps run freezes its score trace once |delta| < 1e-2; the free run
    # keeps strictly improving to the end
    assert float(s_all[-1]) < float(s_eps[-1])
    # conf round-trips the new fields through JSON
    c2 = NeuralNetConfiguration.from_json(conf_eps.to_json())
    assert c2.termination_conditions == ("eps",)
    assert c2.updater == conf_eps.updater


def test_step_function_variants_applied():
    """negative_default inverts the step: the objective must not decrease."""
    from deeplearning4j_tpu.optimize.solver import apply_step

    conf = NeuralNetConfiguration(step_function="negative_default")
    x = jnp.array([1.0, 1.0])
    d = jnp.array([1.0, 0.0])
    out = apply_step(conf, x, d, 0.5)
    np.testing.assert_allclose(np.asarray(out), [0.5, 1.0])
    conf_g = NeuralNetConfiguration(step_function="gradient")
    np.testing.assert_allclose(
        np.asarray(apply_step(conf_g, x, d, 0.5)), [2.0, 1.0])


def test_listener_dispatch_and_composition(caplog):
    """ScoreIterationListener logs every N iterations; Composable fans
    out; dispatch skips non-finite scores (reference
    ScoreIterationListener.java:43-46 / IterationListener contract)."""
    import logging

    import numpy as np

    from deeplearning4j_tpu.optimize.listeners import (
        ComposableIterationListener, IterationListener,
        ScoreIterationListener, dispatch)

    seen = []

    class Recorder(IterationListener):
        def iteration_done(self, model, iteration, score):
            seen.append((iteration, score))

    rec = Recorder()
    combo = ComposableIterationListener(
        [ScoreIterationListener(print_iterations=2), rec])
    scores = np.array([3.0, np.nan, 1.0, np.inf, 0.5])
    with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
        dispatch([combo], model=None, scores=scores)
    # nan/inf iterations skipped; recorder saw the finite ones
    assert seen == [(0, 3.0), (2, 1.0), (4, 0.5)]
    # the score logger printed for iterations 0, 2, 4 (every 2nd)
    assert sum("Score at iteration" in r.getMessage()
               for r in caplog.records) == 3
