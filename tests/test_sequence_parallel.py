"""Sequence/context parallelism: blockwise, ring, Ulysses attention.

Distributed cases run on the 8-device virtual CPU mesh from conftest (the
analog of the reference's in-JVM rig, `BaseTestDistributed.java:34-98`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.sequence import (
    blockwise_attention, full_attention, make_context_parallel_attention,
    ring_attention, ulysses_attention)

B, S, H, D = 2, 32, 4, 8


def _qkv(seed=0):
    k = jax.random.PRNGKey(seed)
    kq, kk, kv = jax.random.split(k, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    kk_ = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)
    return q, kk_, v


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_full(causal):
    q, k, v = _qkv()
    ref = full_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, block_size=8, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_ragged_tail_exact(causal):
    q, k, v = _qkv()
    out = blockwise_attention(q, k, v, block_size=5, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_attention_layer_rejects_bad_n_out():
    from deeplearning4j_tpu.nn.conf import LayerType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import get_layer

    conf = NeuralNetConfiguration(layer_type=LayerType.ATTENTION, n_in=16,
                                  n_out=32, n_heads=4)
    with pytest.raises(ValueError, match="residual"):
        get_layer(conf.layer_type).init(jax.random.PRNGKey(0), conf)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    mesh = make_mesh({"sp": 8})
    q, k, v = _qkv(1)
    ref = full_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, axis="sp", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    mesh = make_mesh({"sp": 4})  # heads=4 must be divisible by axis
    q, k, v = _qkv(2)
    ref = full_attention(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh, axis="sp", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_flow():
    mesh = make_mesh({"sp": 8})
    q, k, v = _qkv(3)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_full = jax.grad(loss_full)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=1e-3, atol=1e-3)


def test_make_context_parallel_attention_jits():
    mesh = make_mesh({"sp": 8})
    fn = make_context_parallel_attention(mesh, kind="ring", causal=True)
    q, k, v = _qkv(4)
    out = fn(q, k, v)
    assert out.shape == (B, S, H, D)
    assert np.isfinite(np.asarray(out)).all()


def test_attention_layer_in_network():
    from deeplearning4j_tpu.nn.conf import LayerType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import get_layer

    conf = NeuralNetConfiguration(layer_type=LayerType.ATTENTION, n_in=16,
                                  n_out=16, n_heads=4, causal=True,
                                  attention_block_size=8)
    layer = get_layer(conf.layer_type)
    params = layer.init(jax.random.PRNGKey(0), conf)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 12, 16))
    y = jax.jit(lambda p, x: layer.forward(p, conf, x))(params, x)
    assert y.shape == x.shape
    # conf round-trips through JSON with the new fields
    conf2 = NeuralNetConfiguration.from_json(conf.to_json())
    assert conf2.n_heads == 4 and conf2.causal and conf2.attention_block_size == 8


def test_char_transformer_lm_learns():
    """Flagship transformer LM: learns a deterministic char pattern."""
    import numpy as np

    from deeplearning4j_tpu.models.zoo import char_transformer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    vocab, seq, batch = 5, 16, 8
    rng = np.random.RandomState(0)
    # cyclic pattern: next char = (char + 1) % vocab
    starts = rng.randint(0, vocab, batch)
    seqs = (starts[:, None] + np.arange(seq + 1)) % vocab
    x = jnp.asarray(seqs[:, :-1])
    y = jax.nn.one_hot(jnp.asarray(seqs[:, 1:]).reshape(-1), vocab)

    conf = char_transformer(vocab, d_model=32, n_blocks=1, n_heads=4,
                            max_seq_len=seq, lr=0.01, iterations=150)
    net = MultiLayerNetwork(conf, seed=0).init()
    net.fit(x, y)
    out = np.asarray(net.output(x)).reshape(batch, seq, vocab)
    pred = out.argmax(-1)
    acc = (pred == np.asarray(seqs[:, 1:])).mean()
    assert acc > 0.95, f"transformer LM failed to learn cycle: acc={acc}"
