"""Expert-parallel MoE vs the dense single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.expert import (init_moe_params, moe_ffn,
                                                moe_ffn_dense)
from deeplearning4j_tpu.parallel.mesh import make_mesh

T, D, H, E = 64, 8, 16, 8


def _setup(seed=0):
    kp, kx = jax.random.split(jax.random.PRNGKey(seed))
    params = init_moe_params(kp, D, H, E)
    x = jax.random.normal(kx, (T, D), jnp.float32)
    return params, x


def test_dense_moe_routes_and_transforms():
    params, x = _setup()
    y, aux = moe_ffn_dense(params, x, capacity_factor=8.0)
    assert y.shape == x.shape
    assert float(aux) > 0
    assert not np.allclose(np.asarray(y), np.asarray(x))  # experts acted


def test_ep_matches_dense_with_ample_capacity():
    mesh = make_mesh({"ep": 8})
    params, x = _setup(1)
    # capacity high enough that neither variant drops any token
    y_dense, _ = moe_ffn_dense(params, x, capacity_factor=float(E))
    y_ep, _ = moe_ffn(params, x, mesh, capacity_factor=float(E))
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                               rtol=2e-4, atol=2e-4)


def test_ep_capacity_drops_fall_through_residual():
    mesh = make_mesh({"ep": 8})
    params, x = _setup(2)
    # capacity 1 forces drops: dropped tokens must equal their input
    y, _ = moe_ffn(params, x, mesh, capacity_factor=0.01)
    diff = np.abs(np.asarray(y) - np.asarray(x)).sum(axis=1)
    assert (diff < 1e-6).any(), "expected some tokens to ride the residual"


def test_ep_grads_flow_and_aux_loss_balances():
    mesh = make_mesh({"ep": 8})
    params, x = _setup(3)

    def loss(p):
        y, aux = moe_ffn(p, x, mesh, capacity_factor=float(E))
        return jnp.mean(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for k in ("router", "W1", "W2"):
        assert np.isfinite(np.asarray(g[k])).all()
        assert float(jnp.abs(g[k]).sum()) > 0, f"zero grad for {k}"
