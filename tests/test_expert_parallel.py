"""Expert-parallel MoE vs the dense single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.expert import (init_moe_params, moe_ffn,
                                                moe_ffn_dense)
from deeplearning4j_tpu.parallel.mesh import make_mesh

T, D, H, E = 64, 8, 16, 8


def _setup(seed=0):
    kp, kx = jax.random.split(jax.random.PRNGKey(seed))
    params = init_moe_params(kp, D, H, E)
    x = jax.random.normal(kx, (T, D), jnp.float32)
    return params, x


def test_dense_moe_routes_and_transforms():
    params, x = _setup()
    y, aux = moe_ffn_dense(params, x, capacity_factor=8.0)
    assert y.shape == x.shape
    assert float(aux) > 0
    assert not np.allclose(np.asarray(y), np.asarray(x))  # experts acted


def test_ep_matches_dense_with_ample_capacity():
    mesh = make_mesh({"ep": 8})
    params, x = _setup(1)
    # capacity high enough that neither variant drops any token
    y_dense, aux_dense = moe_ffn_dense(params, x, capacity_factor=float(E))
    y_ep, aux_ep = moe_ffn(params, x, mesh, capacity_factor=float(E))
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                               rtol=2e-4, atol=2e-4)
    # Aux loss must equal the DENSE global statistic, not a mean of
    # per-shard products (the r3 MULTICHIP failure mode).
    np.testing.assert_allclose(float(aux_ep), float(aux_dense), rtol=1e-5)


def test_ep_full_loss_and_grads_match_dense():
    """(y, aux) AND router/W1/W2/b1/b2 grads must match dense at 1e-4."""
    mesh = make_mesh({"ep": 8})
    params, x = _setup(4)

    def make_loss(fn):
        def loss(p):
            y, aux = fn(p)
            return jnp.mean(y ** 2) + 0.01 * aux
        return loss

    loss_ep = make_loss(lambda p: moe_ffn(p, x, mesh,
                                          capacity_factor=float(E)))
    loss_de = make_loss(lambda p: moe_ffn_dense(p, x,
                                                capacity_factor=float(E)))
    v_ep, g_ep = jax.value_and_grad(loss_ep)(params)
    v_de, g_de = jax.value_and_grad(loss_de)(params)
    np.testing.assert_allclose(float(v_ep), float(v_de), rtol=1e-4)
    for k in ("router", "W1", "b1", "W2", "b2"):
        np.testing.assert_allclose(
            np.asarray(g_ep[k]), np.asarray(g_de[k]),
            rtol=1e-4, atol=1e-6, err_msg=f"grad mismatch for {k}")


def test_ep_capacity_drops_fall_through_residual():
    mesh = make_mesh({"ep": 8})
    params, x = _setup(2)
    # capacity 1 forces drops: dropped tokens must equal their input
    y, _ = moe_ffn(params, x, mesh, capacity_factor=0.01)
    diff = np.abs(np.asarray(y) - np.asarray(x)).sum(axis=1)
    assert (diff < 1e-6).any(), "expected some tokens to ride the residual"


def test_ep_grads_flow_and_aux_loss_balances():
    mesh = make_mesh({"ep": 8})
    params, x = _setup(3)

    def loss(p):
        y, aux = moe_ffn(p, x, mesh, capacity_factor=float(E))
        return jnp.mean(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for k in ("router", "W1", "W2"):
        assert np.isfinite(np.asarray(g[k])).all()
        assert float(jnp.abs(g[k]).sum()) > 0, f"zero grad for {k}"
