"""ShardPlan (ISSUE 17): mesh-spec parsing, per-leaf PartitionSpec
rules, and — the contract the whole refactor hangs on — 1-D and
single-chip plan fingerprints **byte-identical** to the pre-plan cache
keys, so disk artifacts written before the plan existed stay pure hits
(`fresh_compiles == 0`, zero evictions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.models.zoo import char_transformer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.step_cache import arg_signature
from deeplearning4j_tpu.parallel.plan import (
    ShardPlan, parse_mesh_spec, plan_mesh)

VOCAB = 32


def _net():
    conf = char_transformer(VOCAB, d_model=16, n_blocks=2, n_heads=2,
                            max_seq_len=32)
    return MultiLayerNetwork(conf, seed=0).init()


class TestParseMeshSpec:
    def test_empty_and_all_mean_default(self):
        assert parse_mesh_spec("") == {}
        assert parse_mesh_spec("all") == {}
        assert parse_mesh_spec(None) == {}

    def test_explicit_axes(self):
        assert parse_mesh_spec("batch=2,model=4") == {"batch": 2,
                                                      "model": 4}
        assert parse_mesh_spec("model=4") == {"model": 4}

    def test_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_mesh_spec("bogus")
        with pytest.raises(ValueError):
            parse_mesh_spec("batch=x")
        with pytest.raises(ValueError):
            parse_mesh_spec("batch=0")
        with pytest.raises(ValueError):
            parse_mesh_spec("batch=2,batch=4")


class TestPlanMesh:
    def test_default_is_one_d_batch(self):
        mesh = plan_mesh({})
        assert mesh.axis_names == ("batch",)
        assert mesh.devices.size == jax.device_count()

    def test_two_d_shape(self):
        mesh = plan_mesh({"batch": 2, "model": 4})
        assert mesh.axis_names == ("batch", "model")
        assert tuple(mesh.devices.shape) == (2, 4)

    def test_model_only_defaults_batch_to_one(self):
        mesh = plan_mesh({"model": 4})
        assert mesh.axis_names == ("batch", "model")
        assert tuple(mesh.devices.shape) == (1, 4)

    def test_minus_one_fills(self):
        mesh = plan_mesh({"batch": 2, "model": -1})
        assert tuple(mesh.devices.shape) == (2, jax.device_count() // 2)


class TestParamSpecs:
    def test_transformer_split_rules(self):
        net = _net()
        plan = ShardPlan(mesh=plan_mesh({"batch": 2, "model": 4}))
        specs = plan.param_pspecs(net.params)
        flat, _ = jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))
        by_name = {}
        for path, spec in flat:
            name = str(getattr(path[-1], "key", path[-1]))
            by_name.setdefault(name, set()).add(spec)
        # QKV and first-FFN kernels column-split over the model axis
        assert by_name["Wqkv"] == {P(None, "model")}
        assert by_name["W1"] == {P(None, "model")}
        # output / second-FFN projections row-split (all-reduce after)
        assert by_name["Wo"] == {P("model", None)}
        assert by_name["W2"] == {P("model", None)}
        # biases and layer-norm scales stay replicated
        for name in ("bqkv", "bo", "b1", "b2", "ln_g", "ln_b"):
            assert by_name[name] == {P()}

    def test_indivisible_leaves_stay_replicated(self):
        plan = ShardPlan(mesh=plan_mesh({"batch": 2, "model": 4}))
        # 5 divides by neither axis ordering: replicated, never an error
        assert plan._param_spec("W", (5, 7)) == P()

    def test_zero1_composes_batch_axis(self):
        net = _net()
        plan = ShardPlan(mesh=plan_mesh({"batch": 2, "model": 4}))
        specs = plan.zero1_pspecs(net.params)
        flat, _ = jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))
        by_name = {str(getattr(p[-1], "key", p[-1])): s for p, s in flat}
        # a column-split kernel gains the batch axis on its leading dim
        assert by_name["Wqkv"] == P("batch", "model")


class TestKeyByteIdentity:
    """The tentpole invariant: for single-chip and 1-D plans the keys
    the plan emits are byte-for-byte the pre-PR tuples, hand-built here
    from the old schema."""

    def test_single_chip_output_key(self):
        net = _net()
        x = np.ones((8, 16), np.int32)
        net.infer_cache.output(net.conf, net.params, x,
                               compile_only=True)
        ic = net.infer_cache
        xp = jnp.zeros((ic._serve_bucket(8), 16), jnp.int32)
        expected = ("output", ic._fingerprint(net.conf),
                    arg_signature(xp), "single")
        assert expected in ic._programs

    def test_one_d_mesh_output_key(self):
        net = _net()
        mesh = net.set_serve_mesh()  # 1-D batch mesh, pre-plan pattern
        x = np.ones((8, 16), np.int32)
        net.infer_cache.output(net.conf, net.params, x,
                               compile_only=True)
        ic = net.infer_cache
        xp = jnp.zeros((ic._serve_bucket(8), 16), jnp.int32)
        expected = ("output", ic._fingerprint(net.conf),
                    arg_signature(xp),
                    ("mesh", tuple(mesh.axis_names),
                     tuple(int(d) for d in mesh.devices.shape)))
        assert expected in ic._programs

    def test_decode_keys_stay_single_under_one_d_mesh(self):
        # generation is single-chip under a 1-D (or no) mesh: the key
        # keeps the pre-plan "single" tag so warmed decode programs
        # survive flipping `--mesh` on
        net = _net()
        net.set_serve_mesh()
        net.warmup_generate(slots=2, max_seq=16, prompt_buckets=(4,))
        decode_keys = [k for k in net.infer_cache._programs
                       if k[0] in ("decode", "prefill")]
        assert decode_keys
        assert all(k[3] == "single" for k in decode_keys)

    def test_decode_keys_carry_plan_tag_with_model_axis(self):
        net = _net()
        net.set_serve_mesh(spec="batch=2,model=2")
        net.warmup_generate(slots=2, max_seq=16, prompt_buckets=(4,))
        decode_keys = [k for k in net.infer_cache._programs
                       if k[0] == "decode"]
        assert decode_keys
        assert all(k[3] == ("mesh", ("batch", "model"), (2, 2))
                   for k in decode_keys)

    def test_policy_suffix_unchanged(self):
        plan = ShardPlan()
        assert plan.policy_suffix() == ()
        assert ShardPlan(policy="bf16").policy_suffix() == \
            (("policy", "bf16"),)


class TestDiskBackCompat:
    def test_pre_plan_disk_cache_warms_with_zero_fresh_compiles(
            self, tmp_path):
        """A disk store written by one process (byte-identical keys to
        the pre-plan schema, per TestKeyByteIdentity) warms a second
        process with fresh_compiles == 0 and zero evictions."""
        cache_dir = str(tmp_path / "cc")
        warm = _net()
        warm.set_compile_cache(cache_dir)
        warm.warmup([8], entries=("output",))
        assert warm.infer_cache.stats.misses == 1  # the one real compile

        cold = _net()
        store = cold.set_compile_cache(cache_dir)
        cold.warmup([8], entries=("output",))
        assert cold.infer_cache.stats.misses == 0  # fresh_compiles == 0
        assert cold.infer_cache.stats.disk_hits == 1
        assert store.evictions == 0

    def test_mesh_and_single_programs_coexist_on_disk(self, tmp_path):
        cache_dir = str(tmp_path / "cc")
        net = _net()
        net.set_compile_cache(cache_dir)
        net.warmup([8], entries=("output",))
        net.set_serve_mesh(spec="batch=2,model=4")
        net.warmup([8], entries=("output",))
        assert net.infer_cache.stats.misses == 2  # one per sharding

        net2 = _net()
        net2.set_compile_cache(cache_dir)
        net2.set_serve_mesh(spec="batch=2,model=4")
        net2.warmup([8], entries=("output",))
        net2.infer_cache.set_mesh(None)  # back to 1-chip: still a hit
        net2.warmup([8], entries=("output",))
        assert net2.infer_cache.stats.misses == 0
        assert net2.infer_cache.stats.disk_hits == 2
