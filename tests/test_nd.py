"""nd runtime: activations + derivatives, losses, rng, weight init."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nd import losses as L
from deeplearning4j_tpu.nd import random as ndr
from deeplearning4j_tpu.nd.ops import activate, activation_derivative
from deeplearning4j_tpu.nn.weights import WeightInit, init_weights


def test_activations_match_closed_forms():
    x = jnp.linspace(-3, 3, 13)
    np.testing.assert_allclose(activate("sigmoid", x), 1 / (1 + np.exp(-np.asarray(x))), rtol=1e-5, atol=5e-5)
    np.testing.assert_allclose(activate("tanh", x), np.tanh(np.asarray(x)), rtol=1e-5, atol=5e-5)
    np.testing.assert_allclose(activate("relu", x), np.maximum(0, np.asarray(x)), rtol=1e-6, atol=0)
    sm = activate("softmax", jnp.ones((2, 4)))
    np.testing.assert_allclose(sm, 0.25 * np.ones((2, 4)), rtol=1e-6, atol=1e-7)


def test_activation_derivatives_autodiff():
    x = jnp.linspace(-2, 2, 9)
    s = np.asarray(activate("sigmoid", x))
    np.testing.assert_allclose(activation_derivative("sigmoid", x), s * (1 - s), rtol=1e-5, atol=1e-4)
    t = np.tanh(np.asarray(x))
    np.testing.assert_allclose(activation_derivative("tanh", x), 1 - t * t, rtol=1e-5, atol=1e-4)


def test_unknown_activation_raises():
    with pytest.raises(KeyError):
        activate("nope", jnp.zeros(3))


def test_losses_basic_values():
    y = jnp.array([[1.0, 0.0], [0.0, 1.0]])
    perfect = jnp.array([[1.0, 0.0], [0.0, 1.0]])
    bad = jnp.array([[0.5, 0.5], [0.5, 0.5]])
    assert float(L.mcxent(y, perfect)) < float(L.mcxent(y, bad))
    assert float(L.mse(y, perfect)) == pytest.approx(0.0, abs=1e-6)
    assert float(L.squared_loss(y, bad)) == pytest.approx(0.5, abs=1e-5)
    # every registered loss is finite and differentiable
    for lf in L.LossFunction:
        fn = L.get_loss(lf)
        val = fn(y, jnp.clip(bad, 0.01, 0.99))
        assert np.isfinite(float(val)), lf
        g = jax.grad(lambda p: fn(y, p))(bad)
        assert np.all(np.isfinite(np.asarray(g))), lf


def test_rng_samplers():
    key = jax.random.PRNGKey(0)
    b = ndr.binomial(key, 0.7, (10000,))
    assert abs(float(b.mean()) - 0.7) < 0.03
    n = ndr.normal(key, 2.0, 0.5, (10000,))
    assert abs(float(n.mean()) - 2.0) < 0.05
    mask = ndr.dropout_mask(key, 0.5, (10000,))
    assert abs(float(mask.mean()) - 1.0) < 0.1  # inverted dropout preserves scale


def test_weight_init_schemes():
    key = jax.random.PRNGKey(1)
    shape = (100, 50)
    for scheme in WeightInit:
        if scheme == WeightInit.DISTRIBUTION:
            w = init_weights(key, shape, scheme, lambda k, s: jax.random.normal(k, s))
        else:
            w = init_weights(key, shape, scheme)
        assert w.shape == shape
        assert np.all(np.isfinite(np.asarray(w)))
    assert float(jnp.abs(init_weights(key, shape, "zero")).max()) == 0.0
    vi = init_weights(key, shape, "vi")
    r = np.sqrt(6) / np.sqrt(sum(shape) + 1)
    assert float(jnp.abs(vi).max()) <= r + 1e-6
