"""Cluster provisioning + blob store (AWS-module analog)."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.scaleout.provision import (BlobDataSetIterator,
                                                   BlobModelSaver,
                                                   ClusterSpec,
                                                   HostProvisioner, HostSpec,
                                                   LocalBlobStore)


def _spec():
    return ClusterSpec(hosts=[HostSpec("10.0.0.1"), HostSpec("10.0.0.2")],
                       coordinator_port=9000)


def test_cluster_spec_roundtrip_and_env():
    spec = _spec()
    spec2 = ClusterSpec.from_json(spec.to_json())
    assert spec2.coordinator_address == "10.0.0.1:9000"
    env = spec2.distributed_env(1)
    assert env["JAX_PROCESS_ID"] == "1"
    assert env["JAX_NUM_PROCESSES"] == "2"
    assert env["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:9000"


def test_provisioner_dry_run_generates_commands():
    prov = HostProvisioner(_spec(), dry_run=True)
    prov.provision_all("/tmp/framework")
    prov.launch_workers("python worker.py")
    rsyncs = [c for c in prov.executed if c[0] == "rsync"]
    sshes = [c for c in prov.executed if c[0] == "ssh"]
    assert len(rsyncs) == 2 and len(sshes) == 2
    # each worker gets its own process id in the env prefix
    assert "JAX_PROCESS_ID=0" in sshes[0][-1]
    assert "JAX_PROCESS_ID=1" in sshes[1][-1]
    assert "JAX_COORDINATOR_ADDRESS=10.0.0.1:9000" in sshes[1][-1]


def test_ssh_launcher_commands_include_workdir_cd():
    from deeplearning4j_tpu.scaleout.provision import SshLauncher

    prov = HostProvisioner(_spec(), launcher=SshLauncher(dry_run=True))
    prov.launch_workers("python worker.py")
    sshes = [c for c in prov.executed if c[0] == "ssh"]
    assert len(sshes) == 2
    assert sshes[0][-1].startswith("cd /opt/dl4j_tpu && ")


def test_local_launcher_runs_real_fleet(tmp_path):
    """VERDICT r4 next-#8: the SAME ClusterSpec drives a real fleet via
    the pluggable launcher; here the second host is stood in by local
    subprocesses.  Each worker writes its jax.distributed env + cwd to a
    shared file — proving per-host env wiring AND per-host sandboxes."""
    import json

    from deeplearning4j_tpu.scaleout.provision import LocalLauncher

    launcher = LocalLauncher(str(tmp_path / "fleet"))
    prov = HostProvisioner(_spec(), launcher=launcher)

    # provision: pushed artifact lands in each host's sandbox workdir
    src = tmp_path / "pkg"
    src.mkdir()
    (src / "marker.txt").write_text("v1")
    prov.provision_all(str(src))

    out = tmp_path / "out.jsonl"
    entry = (f"python -c \"import os, json; "
             f"open({str(out)!r}, 'a').write(json.dumps("
             f"{{'pid': os.environ['JAX_PROCESS_ID'], "
             f"'n': os.environ['JAX_NUM_PROCESSES'], "
             f"'coord': os.environ['JAX_COORDINATOR_ADDRESS'], "
             f"'cwd': os.getcwd()}}) + chr(10))\"")
    prov.launch_workers(entry)
    rcs = prov.wait(timeout=60)
    assert rcs == [0, 0]

    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert {r["pid"] for r in rows} == {"0", "1"}
    assert all(r["n"] == "2" for r in rows)
    assert all(r["coord"] == "10.0.0.1:9000" for r in rows)
    # two distinct per-host sandboxes, both under the fleet dir
    cwds = {r["cwd"] for r in rows}
    assert len(cwds) == 2
    assert all("fleet" in c and c.endswith("opt/dl4j_tpu") for c in cwds)
    # provisioning landed the artifact in each sandbox
    for host in _spec().hosts:
        d = launcher.host_dir(host)
        assert os.path.isfile(
            os.path.join(d, "opt/dl4j_tpu/pkg/marker.txt"))


def test_local_blob_store_roundtrip(tmp_path):
    store = LocalBlobStore(str(tmp_path / "store"))
    src = tmp_path / "a.txt"
    src.write_text("hello")
    store.upload("artifacts/a.txt", str(src))
    assert store.exists("artifacts/a.txt")
    assert store.list("artifacts/") == ["artifacts/a.txt"]
    dst = tmp_path / "b.txt"
    store.download("artifacts/a.txt", str(dst))
    assert dst.read_text() == "hello"
    store.delete("artifacts/a.txt")
    assert not store.exists("artifacts/a.txt")


def test_blob_store_rejects_escaping_keys(tmp_path):
    store = LocalBlobStore(str(tmp_path / "store"))
    import pytest
    with pytest.raises(ValueError, match="escapes"):
        store.upload("../evil", __file__)


def test_blob_model_saver_roundtrip(tmp_path):
    store = LocalBlobStore(str(tmp_path / "store"))
    params = ({"W": jnp.arange(6.0).reshape(2, 3)},)
    saver = BlobModelSaver(store, key="models/mlp")
    saver.save(params, step=7)
    restored, updater, meta = saver.load(like_params=params)
    np.testing.assert_allclose(np.asarray(restored[0]["W"]),
                               np.arange(6.0).reshape(2, 3))
    assert updater is None
    assert meta["step"] == 7


def test_blob_dataset_iterator(tmp_path):
    store = LocalBlobStore(str(tmp_path / "store"))
    for i in range(3):
        p = tmp_path / f"part{i}.npz"
        np.savez(p, features=np.full((4, 2), i, np.float32),
                 labels=np.eye(4, 3, dtype=np.float32))
        store.upload(f"data/part{i}.npz", str(p))
    it = BlobDataSetIterator(store, prefix="data/")
    parts = list(it)
    assert len(parts) == 3
    assert parts[1].features.shape == (4, 2)
    np.testing.assert_allclose(parts[2].features, 2.0)


def test_blob_store_rejects_sibling_prefix_escape(tmp_path):
    import pytest
    store = LocalBlobStore(str(tmp_path / "store"))
    with pytest.raises(ValueError, match="escapes"):
        store.upload("../store-evil/f", __file__)
