"""Pipeline parallelism: forward equivalence and training on the 8-dev mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import (make_pipeline_train_step,
                                                  pipeline_apply)

D = 16


def _stage(params, x):
    return jnp.tanh(x @ params["W"] + params["b"])


def _stack_params(key, n_stages):
    ks = jax.random.split(key, n_stages)
    return {
        "W": jnp.stack([jax.random.normal(k, (D, D)) * 0.3 for k in ks]),
        "b": jnp.zeros((n_stages, D)),
    }


def _sequential(params, x):
    for s in range(params["W"].shape[0]):
        x = _stage({"W": params["W"][s], "b": params["b"][s]}, x)
    return x


def test_pipeline_matches_sequential():
    mesh = make_mesh({"pp": 4})
    params = _stack_params(jax.random.PRNGKey(0), 4)
    x = jax.random.normal(jax.random.PRNGKey(1), (6, 5, D))  # 6 microbatches
    out = pipeline_apply(_stage, params, x, mesh, axis="pp")
    ref = jnp.stack([_sequential(params, x[i]) for i in range(6)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grads_match_sequential():
    mesh = make_mesh({"pp": 4})
    params = _stack_params(jax.random.PRNGKey(2), 4)
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 3, D))
    y = jax.random.normal(jax.random.PRNGKey(4), (4, 3, D))

    def loss_pipe(p):
        return jnp.mean((pipeline_apply(_stage, p, x, mesh) - y) ** 2)

    def loss_seq(p):
        out = jnp.stack([_sequential(p, x[i]) for i in range(4)])
        return jnp.mean((out - y) ** 2)

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in g_pipe:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_training_reduces_loss():
    mesh = make_mesh({"pp": 8})
    params = _stack_params(jax.random.PRNGKey(5), 8)
    x = jax.random.normal(jax.random.PRNGKey(6), (8, 4, D))
    y = 0.5 * x  # learnable target

    step = make_pipeline_train_step(
        _stage, lambda out, tgt: jnp.mean((out - tgt) ** 2), mesh, lr=0.3)
    params, loss0 = step(params, x, y)
    for _ in range(30):
        params, loss = step(params, x, y)
    assert float(loss) < float(loss0) * 0.5, (float(loss0), float(loss))
