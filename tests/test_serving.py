"""Micro-batching serving gateway (ISSUE 4 tentpole): multithreaded
bitwise correctness vs direct `net.output()`, flush policy (full bucket
vs deadline), bounded-queue backpressure, the HTTP endpoints, the
zero-fresh-compile warmed-server criterion, and a closed-loop load test
(slow)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import MicroBatcher, ServerOverloaded

N_IN, N_OUT = 6, 3


def _net(seed=0):
    return MultiLayerNetwork(mlp(n_in=N_IN, hidden=[8], n_out=N_OUT,
                                 lr=0.05), seed=seed).init()


def _x(rows, seed):
    rng = np.random.RandomState(seed)
    return rng.randn(rows, N_IN).astype(np.float32)


def _http(url, body=None):
    req = urllib.request.Request(
        url, data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="GET" if body is None else "POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


# -- acceptance criterion: interleaved concurrent ragged requests return
# bitwise the same outputs as direct net.output() per request ---------------

def test_gateway_bitwise_matches_direct_under_concurrency():
    net = _net()
    sizes = [1, 2, 3, 5, 7, 4, 1, 6]
    xs = [_x(r, seed=i) for i, r in enumerate(sizes)]
    # direct per-request reference, computed single-threaded up front
    direct = [np.asarray(net.output(x)) for x in xs]

    batcher = MicroBatcher(net, max_delay_ms=5.0, max_batch_rows=16)
    errors, lock = [], threading.Lock()

    def client(i):
        try:
            for _ in range(5):  # interleave repeatedly
                got = batcher.predict(xs[i], timeout=30.0)
                np.testing.assert_array_equal(direct[i], got)
        except BaseException as e:  # noqa: BLE001
            with lock:
                errors.append((i, e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(sizes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
        assert not t.is_alive(), "client thread hung"
    batcher.stop()
    assert not errors, errors
    st = batcher.stats()
    assert st["requests"] == 5 * len(sizes)
    assert st["rows"] == 5 * sum(sizes)


def test_full_bucket_flush_coalesces_before_deadline():
    net = _net()
    net.warmup([8])  # declares the row bucket the gateway targets
    # deadline far away: completion proves the full-bucket trigger fired
    batcher = MicroBatcher(net, max_delay_ms=5000.0)
    assert batcher._target_rows() == 8
    results = [None] * 8

    def client(i):
        results[i] = batcher.predict(_x(1, seed=i), timeout=30.0)

    t0 = time.monotonic()
    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive()
    assert time.monotonic() - t0 < 60.0
    batcher.stop()
    assert all(r is not None and r.shape == (1, N_OUT) for r in results)
    hist = batcher.stats()["batch_rows_hist"]
    # the 8 single-row requests coalesced (an 8-row flush exists; exact
    # splits below 8 depend on thread arrival order)
    assert "8" in hist, hist


def test_deadline_flush_serves_partial_batch():
    net = _net()
    batcher = MicroBatcher(net, max_delay_ms=20.0, max_batch_rows=64)
    out = batcher.predict(_x(3, seed=1), timeout=30.0)  # alone: no co-riders
    batcher.stop()
    assert out.shape == (3, N_OUT)
    assert batcher.stats()["batch_rows_hist"] == {"3": 1}


def test_backpressure_fails_fast_beyond_max_pending():
    net = _net()
    # dispatcher NOT running: requests stay queued
    batcher = MicroBatcher(net, max_pending=2, auto_start=False,
                           max_delay_ms=1.0)
    done = []
    threads = [threading.Thread(
        target=lambda i=i: done.append(
            (i, batcher.predict(_x(1, seed=i), timeout=30.0))))
        for i in range(2)]
    for t in threads:
        t.start()
    deadline = time.time() + 5.0
    while batcher.queue_depth() < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert batcher.queue_depth() == 2
    with pytest.raises(ServerOverloaded):
        batcher.predict(_x(1, seed=99))
    batcher.start()  # dispatcher drains the queue; blocked clients finish
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive()
    batcher.stop()
    assert len(done) == 2


def test_stop_drains_queued_requests():
    net = _net()
    batcher = MicroBatcher(net, max_delay_ms=5000.0, auto_start=False)
    got = []
    t = threading.Thread(
        target=lambda: got.append(batcher.predict(_x(2, seed=0),
                                                  timeout=30.0)))
    t.start()
    deadline = time.time() + 5.0
    while batcher.queue_depth() < 1 and time.time() < deadline:
        time.sleep(0.01)
    batcher.start()
    batcher.stop()  # drain-on-stop: the queued request is served, not lost
    t.join(timeout=30.0)
    assert not t.is_alive()
    assert got and got[0].shape == (2, N_OUT)


def test_dispatcher_error_delivered_to_caller():
    net = _net()
    batcher = MicroBatcher(net, max_delay_ms=5.0)
    with pytest.raises(Exception):
        # feature width mismatch: the device call fails, and the error
        # must surface at the caller instead of hanging it
        batcher.predict(np.zeros((2, N_IN + 1), np.float32), timeout=30.0)
    batcher.stop()


# -- HTTP server -------------------------------------------------------------

def test_model_server_predict_and_stats_endpoints():
    net = _net()
    net.warmup([8])
    server = net.serve(max_delay_ms=2.0)
    try:
        x = _x(3, seed=7)
        direct = np.asarray(net.output(x))
        code, body = _http(server.url + "/v1/predict",
                           {"features": x.tolist()})
        assert code == 200 and body["rows"] == 3
        np.testing.assert_array_equal(
            direct, np.asarray(body["output"], np.float32))

        # single unbatched example is promoted to a 1-row batch
        code, body = _http(server.url + "/v1/predict",
                           {"features": x[0].tolist()})
        assert code == 200 and body["rows"] == 1

        code, stats = _http(server.url + "/v1/stats")
        assert code == 200
        for key in ("queue_depth", "batch_rows_hist", "latency_ms",
                    "rows_per_sec", "fresh_compiles", "cache", "batching"):
            assert key in stats, key
        assert stats["requests"] >= 2
        assert "disk_hits" in stats["cache"]  # observable in one curl
        assert stats["latency_ms"]["p50"] <= stats["latency_ms"]["p99"]
    finally:
        server.stop()


def test_model_server_error_codes():
    net = _net()
    server = net.serve()
    try:
        for path, body in [("/v1/predict", {"wrong_key": []}),
                           ("/nope", None), ("/nope", {"features": []})]:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _http(server.url + path, body)
            assert ei.value.code in (400, 404)
    finally:
        server.stop()


def test_server_overload_returns_503():
    net = _net()
    server = net.serve(max_pending=1, max_delay_ms=1.0)
    server.batcher.stop()  # wedge the gateway so the queue stays full
    try:
        def fill():
            try:  # never served: the gateway is wedged; times out quietly
                server.batcher.predict(_x(1, seed=0), timeout=5.0)
            except TimeoutError:
                pass

        filler = threading.Thread(target=fill)
        filler.start()
        deadline = time.time() + 5.0
        while server.batcher.queue_depth() < 1 and time.time() < deadline:
            time.sleep(0.01)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _http(server.url + "/v1/predict",
                  {"features": _x(1, seed=1).tolist()})
        assert ei.value.code == 503
        filler.join(timeout=30.0)
    finally:
        server.stop()


# -- acceptance criterion: a server started against a warmed compile cache
# serves its first request with zero fresh compiles --------------------------

def test_warmed_server_first_request_zero_fresh_compiles(tmp_path):
    cache_dir = str(tmp_path / "compile-cache")
    conf = mlp(n_in=N_IN, hidden=[8], n_out=N_OUT, lr=0.05)

    warm = MultiLayerNetwork(conf, seed=0).init()
    warm.set_compile_cache(cache_dir)
    warm.warmup([4, 8])
    assert warm.infer_cache.stats.misses == 2  # the compiles we prepaid

    # a FRESH process-alike: new network, same conf, same cache dir
    net = MultiLayerNetwork(conf, seed=0).init()
    net.set_compile_cache(cache_dir)
    net.warmup([4, 8])  # disk restores, not compiles
    server = net.serve(max_delay_ms=2.0)
    try:
        code, body = _http(server.url + "/v1/predict",
                           {"features": _x(3, seed=3).tolist()})
        assert code == 200
        _, stats = _http(server.url + "/v1/stats")
        assert stats["fresh_compiles"] == 0, stats
        assert stats["cache"]["disk_hits"] == 2, stats
    finally:
        server.stop()


def test_serve_cli_parser_and_builder(tmp_path):
    from deeplearning4j_tpu.cli.driver import _build_server, build_parser
    from deeplearning4j_tpu.parallel import checkpoint

    net = _net()
    ckpt = str(tmp_path / "ckpt")
    checkpoint.save(ckpt, net.params, conf=net.conf)

    args = build_parser().parse_args(
        ["serve", "--model", ckpt, "--shapes", "8",
         "--max-delay-ms", "2.0", "--max-pending", "16"])
    assert args.fn.__name__ == "cmd_serve"
    srv_net, server, summary = _build_server(args)
    try:
        assert summary["url"] == server.url
        assert summary["warmed"] == [(8, N_IN)]
        assert summary["batching"] is True
        code, body = _http(server.url + "/v1/predict",
                           {"features": _x(2, seed=5).tolist()})
        assert code == 200 and body["rows"] == 2
        np.testing.assert_array_equal(
            np.asarray(srv_net.output(_x(2, seed=5))),
            np.asarray(body["output"], np.float32))
    finally:
        server.stop()


def test_serve_generate_warmed_zero_fresh_compiles(tmp_path):
    """ISSUE 14 satellite: `warmup --generate` prepays the decode +
    prefill compiles into the persistent store; a fresh-process `serve
    --generate` with the same gen_* flags starts from disk restores and
    streams its first generation with fresh_compiles == 0."""
    from deeplearning4j_tpu.cli.driver import _build_server, build_parser
    from deeplearning4j_tpu.models.zoo import char_lstm
    from deeplearning4j_tpu.parallel import checkpoint

    cache_dir = str(tmp_path / "compile-cache")
    conf = char_lstm(11, hidden=12, n_layers=1)
    warm = MultiLayerNetwork(conf, seed=0).init()
    ckpt = str(tmp_path / "ckpt")
    checkpoint.save(ckpt, warm.params, conf=warm.conf)
    warm.set_compile_cache(cache_dir)
    warm.warmup_generate(slots=2, max_seq=16, prompt_buckets=(8,))
    assert warm.infer_cache.stats.misses > 0  # the compiles we prepaid

    args = build_parser().parse_args(
        ["serve", "--model", ckpt, "--compile-cache", cache_dir,
         "--shapes", "", "--generate", "--gen-slots", "2",
         "--gen-max-seq", "16", "--gen-prompt-buckets", "8"])
    srv_net, server, summary = _build_server(args)
    try:
        assert summary["fresh_compiles"] == 0, summary
        assert summary["generation"]["prompt_buckets"] == [8], summary
        req = urllib.request.Request(
            server.url + "/v1/generate",
            data=json.dumps({"prompt": [1, 2], "max_new_tokens": 4}
                            ).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            lines = [json.loads(ln) for ln in
                     r.read().decode().strip().splitlines()]
        assert sum(1 for ln in lines if "token" in ln) == 4
        _, stats = _http(server.url + "/v1/stats")
        assert stats["generation"]["fresh_compiles"] == 0, stats
        assert srv_net.infer_cache.stats.misses == 0
    finally:
        server.stop()


# -- closed-loop load (CI satellite: slow, mirrors bench_serve) --------------

@pytest.mark.slow
def test_closed_loop_load_batches_and_stays_bitwise():
    net = _net()
    net.warmup([32])
    batcher = MicroBatcher(net, max_delay_ms=3.0)
    xs = [_x(1 + i % 3, seed=i) for i in range(16)]
    direct = [np.asarray(net.output(x)) for x in xs]
    errors, lock = [], threading.Lock()

    def client(i):
        try:
            for _ in range(20):
                np.testing.assert_array_equal(
                    direct[i], batcher.predict(xs[i], timeout=60.0))
        except BaseException as e:  # noqa: BLE001
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(xs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300.0)
        assert not t.is_alive()
    batcher.stop()
    assert not errors, errors[:3]
    st = batcher.stats()
    # closed-loop concurrency actually coalesced: fewer device calls
    # than requests
    flushes = sum(st["batch_rows_hist"].values())
    assert flushes < st["requests"], st


# -- deadline-heap eviction order (ISSUE 19 satellite) ------------------------

def test_eviction_order_is_deadline_then_fifo():
    """The dispatcher's deadline heap pins eviction order to
    (deadline, t_enqueue): earliest deadline first, FIFO within a tie —
    independent of arrival order.  Driven directly against the enqueue
    plumbing so deadlines and enqueue times are exact, not wall-clock."""
    import heapq
    from collections import deque

    from deeplearning4j_tpu.serving.batcher import _Pending

    batcher = MicroBatcher(_net(), auto_start=False)
    evicted = []

    class _Recorder:
        def __init__(self, name):
            self.name = name

        def set(self):
            evicted.append(self.name)

    def enqueue(name, t_enqueue, deadline):
        req = _Pending(_x(1, seed=0))
        req.t_enqueue = t_enqueue
        req.deadline = deadline
        req.done = _Recorder(name)
        key = (req.x.shape[1:], str(req.x.dtype))
        with batcher._cv:
            batcher._queues.setdefault(key, deque()).append(req)
            batcher._seq += 1
            heapq.heappush(batcher._arrival_heap,
                           (req.t_enqueue, batcher._seq, key, req))
            heapq.heappush(batcher._deadline_heap,
                           (req.deadline, req.t_enqueue, batcher._seq,
                            key, req))
            batcher._pending += 1
            batcher._pending_by[req.priority] += 1
        return req

    # arrival order a, b, c, d — NOT the eviction order
    enqueue("a", t_enqueue=1.0, deadline=30.0)   # latest deadline
    enqueue("b", t_enqueue=2.0, deadline=10.0)   # deadline tie with c,
    enqueue("c", t_enqueue=3.0, deadline=10.0)   # broken by t_enqueue
    enqueue("d", t_enqueue=4.0, deadline=5.0)       # earliest deadline
    with batcher._cv:
        batcher._evict_expired_locked(now=20.0)  # a's deadline unexpired
    assert evicted == ["d", "b", "c"]
    assert batcher.queue_depth() == 1
    assert batcher.stats()["deadline_misses"] == 3
    # the survivor is still dispatchable: its heap entries are live
    with batcher._cv:
        assert batcher._earliest_deadline_locked() == 30.0
        assert batcher._oldest_key() is not None
    batcher.stop()
