"""Compiled train-step cache: compile-once semantics, bucketed padding
exactness, and numerical identity with the uncached solver path."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.conf import (LayerType, MultiLayerConfiguration,
                                        NeuralNetConfiguration,
                                        OptimizationAlgorithm)
from deeplearning4j_tpu.nn.multilayer import (MultiLayerNetwork,
                                              make_finetune_loss)
from deeplearning4j_tpu.optimize import solver as solver_mod
from deeplearning4j_tpu.optimize.step_cache import (TrainStepCache,
                                                    conf_fingerprint)

KEY = jax.random.PRNGKey(7)


def _data(n, n_in=6, n_out=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.randint(0, n_out, n)]
    return jnp.asarray(x), jnp.asarray(y)


def _mlp_conf(algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
              iters=3):
    conf = mlp(n_in=6, hidden=[8, 8], n_out=3, lr=0.05)  # 3-layer MLP
    return conf.replace(confs=tuple(
        c.replace(optimization_algo=algo, num_iterations=iters)
        for c in conf.confs))


def _bn_conf(iters=3):
    confs = (
        NeuralNetConfiguration(layer_type=LayerType.BATCH_NORM, n_in=6,
                               n_out=6),
        NeuralNetConfiguration(
            layer_type=LayerType.OUTPUT, n_in=6, n_out=3,
            num_iterations=iters,
            optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT),
    )
    return MultiLayerConfiguration(confs=confs)


# -- compile-once semantics (acceptance criterion) --------------------------

def test_four_equal_batches_compile_exactly_once():
    """3-layer MLP over 4 equal-shape fit batches: ONE compile, 3 hits."""
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    x, y = _data(64)
    for i in range(4):
        net.fit(x[i * 16:(i + 1) * 16], y[i * 16:(i + 1) * 16])
    st = net.step_cache.stats
    assert st.misses == 1, st
    assert st.hits == 3, st
    assert st.steps == 4, st
    assert len(net.step_cache) == 1


def test_mixed_size_epoch_compiles_at_most_n_buckets():
    """Epoch [16, 16, 10]: the tail pads into the 16-bucket — one program
    total, and the cache never saw a second shape."""
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    x, y = _data(42)
    for lo, hi in ((0, 16), (16, 32), (32, 42)):
        net.fit(x[lo:hi], y[lo:hi])
    st = net.step_cache.stats
    assert st.misses == 1, st
    assert st.hits == 2, st
    assert net.step_cache.buckets == (16,)


def test_different_shapes_compile_separately():
    """A batch LARGER than every known bucket registers a new bucket and
    compiles its own program."""
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    x, y = _data(48)
    net.fit(x[:16], y[:16])
    net.fit(x[:48], y[:48])      # 48 > 16: new bucket, new compile
    net.fit(x[16:32], y[16:32])  # 16 again: hit
    st = net.step_cache.stats
    assert st.misses == 2, st
    assert st.hits == 1, st
    assert net.step_cache.buckets == (16, 48)


def test_conf_change_compiles_separately():
    """Different configs never alias a compiled program (fingerprint key)."""
    cache = TrainStepCache()
    c1 = _mlp_conf(iters=2)
    c2 = _mlp_conf(iters=4)
    assert conf_fingerprint(c1) != conf_fingerprint(c2)
    x, y = _data(8)
    p1 = MultiLayerNetwork(c1, seed=0).init().params
    cache.finetune(c1, p1, x, y, KEY)
    cache.finetune(c2, p1, x, y, KEY)
    assert cache.stats.misses == 2


# -- numerical identity with the uncached path ------------------------------

@pytest.mark.parametrize("algo", [
    OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
    OptimizationAlgorithm.CONJUGATE_GRADIENT,
    OptimizationAlgorithm.LBFGS,
])
def test_cached_step_matches_uncached_optimize(algo):
    """The cached program computes exactly what `solver_mod.optimize` on a
    closure of the same loss computes — same params, same score trace."""
    conf = _mlp_conf(algo=algo, iters=4)
    out_conf = conf.conf(conf.n_layers - 1)
    params0 = MultiLayerNetwork(conf, seed=3).init().params
    x, y = _data(12)
    w = jnp.ones(12, jnp.float32)

    cached_p, cached_s = TrainStepCache().finetune(conf, params0, x, y, KEY)

    loss = make_finetune_loss(conf)
    objective = solver_mod.from_loss(lambda p, k: loss(p, x, y, w, k)[0])
    ref_p, ref_s = solver_mod.optimize(objective, params0, out_conf, KEY)

    np.testing.assert_array_equal(np.asarray(cached_s), np.asarray(ref_s))
    for lc, lr in zip(cached_p, ref_p):
        for name in lc:
            np.testing.assert_array_equal(np.asarray(lc[name]),
                                          np.asarray(lr[name]), err_msg=name)


# -- bucketed remainder exactness (acceptance criterion) --------------------

def test_padded_tail_matches_unpadded_tail_bitforbit():
    """A 10-row tail padded into a 16-bucket trains to the SAME float32
    params as the unpadded 10-row batch (row-weight masking exactness)."""
    conf = _mlp_conf(iters=4)
    params0 = MultiLayerNetwork(conf, seed=5).init().params
    x, y = _data(10, seed=2)

    padded_cache = TrainStepCache()
    assert padded_cache.bucket_rows(16) == 16  # pre-register the bucket
    p_pad, s_pad = padded_cache.finetune(conf, params0, x, y, KEY)
    assert padded_cache.buckets == (16,)

    plain_cache = TrainStepCache()  # no bucket >= 10 known: runs unpadded
    p_ref, s_ref = plain_cache.finetune(conf, params0, x, y, KEY)
    assert plain_cache.buckets == (10,)

    np.testing.assert_array_equal(np.asarray(s_pad), np.asarray(s_ref))
    for lc, lr in zip(p_pad, p_ref):
        for name in lc:
            a, b = np.asarray(lc[name]), np.asarray(lr[name])
            assert a.dtype == np.float32
            np.testing.assert_array_equal(a, b, err_msg=name)


def test_padded_tail_batchnorm_stats_match_unpadded():
    """BatchNorm path: pad rows must not leak into the batch statistics —
    padded and unpadded tails produce identical EMA entries."""
    conf = _bn_conf(iters=3)
    params0 = MultiLayerNetwork(conf, seed=1).init().params
    x, y = _data(10, seed=4)

    padded = TrainStepCache()
    padded.bucket_rows(16)
    p_pad, _ = padded.finetune(conf, params0, x, y, KEY)
    p_ref, _ = TrainStepCache().finetune(conf, params0, x, y, KEY)

    for name in ("ema_mean", "ema_var", "ema_w"):
        np.testing.assert_array_equal(np.asarray(p_pad[0][name]),
                                      np.asarray(p_ref[0][name]),
                                      err_msg=name)
    # and the stats are REAL: ema mean tracks the batch mean
    mean = np.asarray(p_pad[0]["ema_mean"]) / float(p_pad[0]["ema_w"])
    np.testing.assert_allclose(mean, np.asarray(x).mean(0), atol=0.2)


def test_conv_batchnorm_padded_tail_bitforbit():
    """Conv/NCHW nets keep the padded-remainder guarantee: an 11-row tail
    padded into a 16-bucket trains a conv+BatchNorm+pool stack to the SAME
    float32 params as the unpadded run (the 4-D BN moment/affine path is
    gemm-contracted like the 2-D one — see layers/base.py)."""
    from deeplearning4j_tpu.models.zoo import _base
    from deeplearning4j_tpu.nn.conf import (Activation, LossFunction,
                                            PoolingType)

    b = _base(lr=0.05, iters=2)
    confs = (
        b.replace(layer_type=LayerType.CONVOLUTION, n_channels=1, n_out=4,
                  kernel_size=(3, 3), stride=(1, 1)),
        b.replace(layer_type=LayerType.BATCH_NORM, n_in=4, n_out=4),
        b.replace(layer_type=LayerType.SUBSAMPLING, kernel_size=(2, 2),
                  stride=(2, 2), pooling=PoolingType.MAX),
        b.replace(layer_type=LayerType.OUTPUT, n_in=4 * 3 * 3, n_out=3,
                  activation=Activation.SOFTMAX,
                  loss_function=LossFunction.MCXENT),
    )
    conf = MultiLayerConfiguration(
        confs=confs, pretrain=False, backprop=True,
        input_preprocessors=((0, "ff_to_conv:1:8:8"), (3, "conv_to_ff")))
    params0 = MultiLayerNetwork(conf, seed=3).init().params
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(11, 64).astype(np.float32))
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 11)])

    padded = TrainStepCache()
    padded.bucket_rows(16)  # pre-register: the 11-row tail pads into it
    p_pad, s_pad = padded.finetune(conf, params0, x, y, KEY)
    p_ref, s_ref = TrainStepCache().finetune(conf, params0, x, y, KEY)

    np.testing.assert_array_equal(np.asarray(s_pad), np.asarray(s_ref))
    for lc, lr in zip(p_pad, p_ref):
        for name in lc:
            np.testing.assert_array_equal(np.asarray(lc[name]),
                                          np.asarray(lr[name]),
                                          err_msg=name)


def test_bn_fit_skips_second_forward_ema_pass():
    """fit() on a BN net through the cache advances the EMA inside the
    compiled step (no legacy `update_bn_ema` recompute) and still lands
    near the batch mean."""
    net = MultiLayerNetwork(_bn_conf(iters=5), seed=0).init()
    rng = np.random.RandomState(0)
    x = (rng.rand(32, 6).astype(np.float32) * 5 + 3)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    net.fit(x, y)
    assert net._bn_in_step  # the compiled step owned the EMA update
    assert net._bn_ema_fn is None  # legacy path never compiled
    p = net.params[0]
    mean = np.asarray(p["ema_mean"]) / max(float(p["ema_w"]), 1e-8)
    assert np.all(np.abs(mean - x.mean(0)) < 0.5)


# -- pretraining path -------------------------------------------------------

def test_pretrain_layers_cache_by_layer_index():
    """DBN pretraining: each layer's solver program compiles once and is
    keyed by layer index; a second pretrain pass over the same shapes is
    all hits."""
    from deeplearning4j_tpu.models.zoo import dbn

    conf = dbn(n_in=6, hidden=[8, 4], n_out=3, iterations=2,
               finetune_iterations=2)
    net = MultiLayerNetwork(conf, seed=0).init()
    x, y = _data(16)
    net.fit(x, y)
    first = net.step_cache.stats.misses
    assert first >= 3  # two RBM layers + the finetune program
    net.fit(x, y)
    assert net.step_cache.stats.misses == first  # second epoch: all hits


# -- observability ----------------------------------------------------------

def test_compile_seconds_recorded_and_misses_logged(caplog):
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    x, y = _data(8)
    with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
        net.fit(x, y)
        net.fit(x, y)
    st = net.step_cache.stats
    assert st.total_compile_seconds > 0
    assert len(st.compile_seconds) == 1
    misses_logged = [r for r in caplog.records
                     if "step-cache miss" in r.getMessage()]
    assert len(misses_logged) == 1  # the hit did NOT log
    d = st.as_dict()
    assert d["hits"] == 1 and d["misses"] == 1 and d["steps"] == 2


def test_use_step_cache_false_restores_legacy_path():
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    net.use_step_cache = False
    x, y = _data(8)
    net.fit(x, y)
    assert net.step_cache.stats.steps == 0
    assert np.isfinite(net.score(x, y))


def test_listener_dispatch_truncates_frozen_tail():
    """dispatch replays the real final iteration of an early-terminated
    trace once, not every masked post-termination copy."""
    from deeplearning4j_tpu.optimize.listeners import (IterationListener,
                                                       dispatch)

    seen = []

    class Rec(IterationListener):
        def iteration_done(self, model, iteration, score):
            seen.append((iteration, score))

    dispatch([Rec()], None, np.array([5.0, 4.0, 3.0, 2.0, 2.0, 2.0, 2.0]))
    assert seen == [(0, 5.0), (1, 4.0), (2, 3.0), (3, 2.0)]

    seen.clear()  # no trailing run: nothing truncated
    dispatch([Rec()], None, np.array([3.0, 2.0, 1.0]))
    assert seen == [(0, 3.0), (1, 2.0), (2, 1.0)]
