"""Embedding model tests — word2vec/glove/paragraph vectors.

Per SURVEY §7 hard-part 3: convergence is validated on similarity behavior
(words that share contexts end up close), not bitwise vs the reference's
HogWild loop.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.models.embeddings import (read_word_vectors,
                                                  write_word_vectors)
from deeplearning4j_tpu.models.glove import Glove
from deeplearning4j_tpu.models.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.models.word2vec import Word2Vec


def _corpus(n=200, seed=0):
    """Two topic clusters: {cat,dog,pet} vs {car,truck,road} — words inside
    a cluster co-occur, across clusters they never do."""
    rng = np.random.RandomState(seed)
    animals = ["cat", "dog", "pet", "fur", "paw"]
    vehicles = ["car", "truck", "road", "wheel", "engine"]
    out = []
    for _ in range(n):
        pool = animals if rng.rand() < 0.5 else vehicles
        out.append(" ".join(rng.choice(pool, size=8)))
    return out


def test_word2vec_trains_and_clusters():
    w2v = Word2Vec(vector_length=24, window=4, min_word_frequency=1,
                   negative=4, epochs=4, batch_size=256, seed=1)
    w2v.fit(_corpus())
    assert w2v.vector("cat").shape == (24,)
    same = w2v.similarity("cat", "dog")
    cross = w2v.similarity("cat", "truck")
    assert same > cross, (same, cross)
    near = [w for w, _ in w2v.words_nearest("cat", top=4)]
    assert any(w in ("dog", "pet", "fur", "paw") for w in near)


def test_word2vec_hs_only():
    w2v = Word2Vec(vector_length=16, window=3, min_word_frequency=1,
                   negative=0, use_hierarchical_softmax=True, epochs=3,
                   batch_size=128, seed=2)
    w2v.fit(_corpus(120))
    assert w2v.similarity("car", "truck") > w2v.similarity("car", "dog")


def test_word2vec_adagrad_changes_trajectory_and_converges():
    """VERDICT r2 weak #6: use_adagrad must not be a dead parameter. The
    per-word AdaGrad path (ref InMemoryLookupTable.java AdaGrad) must
    (a) produce different vectors than plain SGD and (b) still converge."""
    sgd = Word2Vec(vector_length=16, window=3, min_word_frequency=1,
                   negative=3, epochs=3, batch_size=128, seed=2)
    sgd.fit(_corpus(120))
    ada = Word2Vec(vector_length=16, window=3, min_word_frequency=1,
                   negative=3, epochs=3, batch_size=128, seed=2,
                   use_adagrad=True)
    ada.fit(_corpus(120))
    assert not np.allclose(np.asarray(sgd.table.syn0),
                           np.asarray(ada.table.syn0))
    assert ada.similarity("car", "truck") > ada.similarity("car", "dog")


def test_word2vec_pair_generation_vectorized_semantics():
    """The vectorized pair grid must honor sentence boundaries, dynamic
    window reach in [1, window], and exclude self-pairs."""
    w2v = Word2Vec(vector_length=8, window=2, min_word_frequency=1, seed=0)
    w2v.build_vocab([["a", "b", "c"], ["d", "e"]])
    ids = [np.asarray([w2v.cache.index_of(t) for t in s], np.int32)
           for s in (["a", "b", "c"], ["d", "e"])]
    centers, contexts = w2v._pairs(ids)
    assert len(centers) == len(contexts) > 0
    # no self pairs at distance 0 and no cross-sentence pairs
    s1 = {w2v.cache.index_of(t) for t in ("a", "b", "c")}
    s2 = {w2v.cache.index_of(t) for t in ("d", "e")}
    for c, x in zip(centers, contexts):
        assert (c in s1) == (x in s1), "cross-sentence pair leaked"
    # each center appears with at most window-distance contexts
    assert set(centers.tolist()) <= s1 | s2


def test_word2vec_epoch_stochasticity_and_exact_update_counts(monkeypatch):
    """VERDICT r3 weak #5 done-criteria: (a) epoch 2 trains on a DIFFERENT
    pair draw than epoch 1 (window shrink + subsampling re-rolled per pass,
    as Word2Vec.java skipGram re-rolls b = rand % window per visit), and
    (b) every generated pair is applied EXACTLY once per epoch — the old
    np.resize tail wrap double-counted head pairs."""
    import deeplearning4j_tpu.models.word2vec as w2v_mod

    recorded = []
    real_epoch = w2v_mod._w2v_epoch

    def spy(tables, centers_all, contexts_all, weights_all, *a, **kw):
        batch_idx = a[3]
        recorded.append((np.asarray(centers_all), np.asarray(contexts_all),
                         np.asarray(weights_all), np.asarray(batch_idx)))
        return real_epoch(tables, centers_all, contexts_all, weights_all,
                          *a, **kw)

    monkeypatch.setattr(w2v_mod, "_w2v_epoch", spy)
    # batch 64 with a corpus producing n_pairs not divisible by 64, to
    # exercise the padded tail; subsampling on to exercise its re-roll too
    w2v = Word2Vec(vector_length=8, window=4, min_word_frequency=1,
                   negative=2, epochs=3, batch_size=64, seed=7, sample=1e-2)
    w2v.fit(_corpus(60))
    assert len(recorded) == 3
    pair_sets = []
    for centers, contexts, weights, batch_idx in recorded:
        cap = len(centers)
        n_real = int(weights.sum())
        assert 0 < n_real <= cap
        # (b) the batch index grid is a permutation of the capacity: with
        # the 0/1 weights this means each real pair is seen exactly once
        assert sorted(batch_idx.ravel().tolist()) == list(range(cap))
        assert set(np.unique(weights)) <= {0.0, 1.0}
        # real pairs occupy the weight-1 slots
        pair_sets.append(sorted(zip(centers[:n_real].tolist(),
                                    contexts[:n_real].tolist())))
    # (a) at least one later epoch differs from epoch 1's draw
    assert any(ps != pair_sets[0] for ps in pair_sets[1:]), \
        "every epoch reused the identical pair draw"


def test_word2vec_padded_pairs_contribute_nothing():
    """A weight-0 padding slot must not move any table row: compare one
    step on [pair, pad] against one step on [pair, pair] with weight
    [1, 0] — identical result proves padding is inert."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.word2vec import _w2v_step_impl
    from deeplearning4j_tpu.text.vocab import Huffman

    w2v = Word2Vec(vector_length=8, window=2, min_word_frequency=1, seed=0)
    w2v.build_vocab([["a", "b", "c", "d"]])
    codes, points, mask = Huffman.padded_arrays(w2v.cache)
    tables = {
        "syn0": jnp.asarray(w2v.table.syn0, jnp.float32),
        "syn1": jnp.asarray(w2v.table.syn1, jnp.float32),
        "syn1neg": jnp.asarray(w2v.table.syn1neg, jnp.float32),
    }
    neg_table = jnp.asarray(w2v.table.unigram_table())
    key = jax.random.PRNGKey(0)

    def step(centers, contexts, weights):
        c = jnp.asarray(centers, jnp.int32)
        x = jnp.asarray(contexts, jnp.int32)
        return _w2v_step_impl(
            dict(tables), c, x, jnp.asarray(codes)[x],
            jnp.asarray(points)[x], jnp.asarray(mask)[x], neg_table, key,
            0.05, 2, weights=jnp.asarray(weights, jnp.float32))

    out_pad, _ = step([0, 3], [1, 2], [1.0, 0.0])
    out_solo, _ = step([0, 0], [1, 1], [1.0, 0.0])
    for k in ("syn0", "syn1", "syn1neg"):
        np.testing.assert_allclose(np.asarray(out_pad[k]),
                                   np.asarray(out_solo[k]), rtol=1e-6,
                                   err_msg=f"padding leaked into {k}")


def test_word2vec_serialization_roundtrip(tmp_path):
    w2v = Word2Vec(vector_length=8, min_word_frequency=1, epochs=1,
                   batch_size=64, seed=3)
    w2v.fit(_corpus(40))
    path = str(tmp_path / "vectors.txt")
    write_word_vectors(w2v.table, path)
    table = read_word_vectors(path)
    v1, v2 = w2v.vector("cat"), table.vector("cat")
    np.testing.assert_allclose(v1, v2, rtol=1e-4, atol=1e-5)


def test_glove_trains_and_clusters():
    g = Glove(vector_length=16, window=5, min_word_frequency=1,
              epochs=20, seed=1)
    g.fit(_corpus(150))
    assert g.similarity("cat", "dog") > g.similarity("cat", "engine")


def test_paragraph_vectors():
    docs = ["cat dog pet fur paw cat dog", "car truck road wheel engine",
            "dog pet paw fur cat pet", "truck car engine wheel road"]
    pv = ParagraphVectors(vector_length=16, window=3, min_word_frequency=1,
                          negative=3, epochs=8, batch_size=64, seed=4,
                          labels=["an1", "ve1", "an2", "ve2"])
    pv.fit(docs)
    assert pv.doc_vector("an1").shape == (16,)
    assert pv.doc_similarity("an1", "an2") > pv.doc_similarity("an1", "ve1")


def test_word2vec_analogy_api():
    w2v = Word2Vec(vector_length=8, min_word_frequency=1, epochs=1,
                   batch_size=32, seed=5)
    w2v.fit(_corpus(30))
    out = w2v.analogy("cat", "dog", "car", top=3)
    assert isinstance(out, list)  # API shape; semantics need a real corpus


def test_word2vec_c_binary_round_trip(tmp_path):
    """VERDICT r1 #8: the word2vec C binary format (WordVectorSerializer
    loadGoogleModel path) round-trips vectors and vocab exactly."""
    import numpy as np

    from deeplearning4j_tpu.models.embeddings import (
        InMemoryLookupTable, read_word_vectors_binary,
        write_word_vectors_binary)
    from deeplearning4j_tpu.text.vocab import VocabCache

    words = ["alpha", "beta", "gamma", "delta", "epsilon"]
    cache = VocabCache()
    cache.fit([words])
    table = InMemoryLookupTable(cache, 7, seed=3)
    path = str(tmp_path / "vecs.bin")
    write_word_vectors_binary(table, path)

    loaded = read_word_vectors_binary(path)
    assert sorted(loaded.cache.words()) == sorted(words)
    for w in words:
        np.testing.assert_allclose(loaded.vector(w), table.vector(w),
                                   rtol=1e-6)
    # nearest-neighbor queries behave identically on the loaded table
    assert (loaded.words_nearest("alpha", top=2)[0][0]
            == table.words_nearest("alpha", top=2)[0][0])


def test_word2vec_binary_handles_multibyte_words(tmp_path):
    import numpy as np

    from deeplearning4j_tpu.models.embeddings import (
        InMemoryLookupTable, read_word_vectors_binary,
        write_word_vectors_binary)
    from deeplearning4j_tpu.text.vocab import VocabCache

    words = ["café", "naïve", "中文"]
    cache = VocabCache()
    cache.fit([words])
    table = InMemoryLookupTable(cache, 4, seed=1)
    path = str(tmp_path / "mb.bin")
    write_word_vectors_binary(table, path)
    loaded = read_word_vectors_binary(path)
    for w in words:
        np.testing.assert_allclose(loaded.vector(w), table.vector(w),
                                   rtol=1e-6)


def test_word2vec_dataset_iterator():
    """`Word2VecDataSetIterator.java` parity: moving windows over a
    label-aware sentence iterator, featurized by the trained w2v vectors,
    batched with one-hot window labels."""
    import numpy as np

    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.models.word2vec_iterator import (
        Word2VecDataSetIterator)
    from deeplearning4j_tpu.text.sentence_iterator import (
        LabelAwareSentenceIterator)

    sents = ["the cat sat", "dogs run fast", "cats nap"]
    w2v = Word2Vec(vector_length=8, window=3, negative=2,
                   min_word_frequency=1, epochs=1, seed=0,
                   batch_size=32).fit([s.split() for s in sents])
    it = Word2VecDataSetIterator(
        w2v, LabelAwareSentenceIterator(sents, ["A", "B", "A"]),
        labels=["A", "B"], batch=4, window=3)
    assert it.input_columns() == 3 * 8
    batches = list(it)
    n_rows = sum(len(b.features) for b in batches)
    assert n_rows == 8  # 3 + 3 + 2 windows
    assert all(b.features.shape[1] == 24 for b in batches)
    # every row's label is one-hot over {A, B}
    for b in batches:
        assert np.allclose(b.labels.sum(axis=1), 1.0)
    # the middle sentence's windows carry label B (index 1)
    all_labels = np.concatenate([b.labels for b in batches])
    assert all_labels[:3, 0].all() and all_labels[3:6, 1].all()
    # iterating again after implicit reset yields the same count
    assert sum(len(b.features) for b in it) == 8


def test_rntn_eval_confusion():
    """`RNTNEval.java` parity: per-node confusion counts over forwarded
    trees, surfaced through the framework Evaluation."""
    from deeplearning4j_tpu.models.rntn import RNTN, TreeNode
    from deeplearning4j_tpu.models.rntn_eval import RNTNEval

    pos = TreeNode(label=1, left=TreeNode(label=1, word="good"),
                   right=TreeNode(label=1, word="great"))
    neg = TreeNode(label=0, left=TreeNode(label=0, word="bad"),
                   right=TreeNode(label=0, word="awful"))
    model = RNTN(dim=6, n_classes=2, max_nodes=8, lr=0.1, seed=0)
    model.fit([pos, neg], epochs=150)
    ev = RNTNEval()
    ev.eval(model, [pos, neg])
    assert ev.evaluation.confusion.total() == 2  # two non-leaf nodes
    assert ev.accuracy() >= 0.5
    assert "Accuracy" in ev.stats() or "accuracy" in ev.stats().lower()


def test_word2vec_data_fetcher(tmp_path):
    """`Word2VecDataFetcher.java` parity: labeled-markup text files ->
    w2v-featurized window DataSets with one-hot span labels."""
    import numpy as np

    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.models.word2vec_iterator import (
        Word2VecDataFetcher)

    (tmp_path / "a.txt").write_text(
        "the <PER> john smith </PER> visited <LOC> paris </LOC>\n"
        "<PER> mary </PER> stayed home\n")
    corpus = [["the", "john", "smith", "visited", "paris"],
              ["mary", "stayed", "home"]]
    w2v = Word2Vec(vector_length=6, window=3, negative=2,
                   min_word_frequency=1, epochs=1, seed=0,
                   batch_size=16).fit(corpus)
    f = Word2VecDataFetcher(w2v, str(tmp_path), ["NONE", "PER", "LOC"],
                            window=3)
    # spans: NONE[the](1) PER[john,smith](2) NONE[visited](1) LOC[paris](1)
    #        PER[mary](1) NONE[stayed,home](2) -> 8 windows
    assert f.total_examples() == 8
    assert f.input_columns() == 18 and f.total_outcomes() == 3
    ds = f.fetch(5)
    assert ds.features.shape == (5, 18) and ds.labels.shape == (5, 3)
    assert np.allclose(ds.labels.sum(axis=1), 1.0)
    # the two PER windows of sentence 1 are rows 1-2
    assert ds.labels[1, 1] == 1.0 and ds.labels[2, 1] == 1.0
    rest = f.fetch(100)
    assert len(rest.features) == 3 and not f.has_more()
    assert f.fetch(1) is None
    f.reset()
    assert f.has_more() and len(f.fetch(100).features) == 8


def test_word2vec_data_fetcher_guards(tmp_path):
    """Unknown markup labels raise; malformed non-corpus lines are
    skipped with a warning; fetch(0) raises."""
    import pytest

    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.models.word2vec_iterator import (
        Word2VecDataFetcher)

    w2v = Word2Vec(vector_length=4, window=3, negative=2,
                   min_word_frequency=1, epochs=1, seed=0,
                   batch_size=8).fit([["a", "b", "c"]])
    d = tmp_path / "c1"
    d.mkdir()
    (d / "good.txt").write_text("<PER> a </PER> b\n")
    (d / "README.html").write_text("some </b> broken markup\n")
    f = Word2VecDataFetcher(w2v, str(d), ["NONE", "PER"], window=3)
    assert f.total_examples() == 2  # PER[a] + NONE[b]; html line skipped
    with pytest.raises(ValueError, match="num_examples"):
        f.fetch(0)

    d2 = tmp_path / "c2"
    d2.mkdir()
    (d2 / "typo.txt").write_text("<PERSON> a </PERSON>\n")
    with pytest.raises(ValueError, match="PERSON"):
        Word2VecDataFetcher(w2v, str(d2), ["NONE", "PER"], window=3)
