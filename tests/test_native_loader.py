"""Native C++ IO library vs the Python parsing paths."""

import gzip
import os
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.native import (get_library, native_read_csv,
                                       native_read_idx)

needs_native = pytest.mark.skipif(get_library() is None,
                                  reason="g++/toolchain unavailable")


def _write_idx(path, arr: np.ndarray):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x0800 | arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.astype(np.uint8).tobytes())


@needs_native
def test_native_idx_roundtrip(tmp_path):
    arr = (np.arange(3 * 5 * 4) % 251).astype(np.uint8).reshape(3, 5, 4)
    p = str(tmp_path / "images.idx3-ubyte")
    _write_idx(p, arr)
    out = native_read_idx(p)
    assert out is not None
    np.testing.assert_array_equal(out, arr)


@needs_native
def test_read_idx_native_and_python_agree(tmp_path):
    from deeplearning4j_tpu.datasets.mnist import read_idx

    arr = (np.arange(7 * 9) % 256).astype(np.uint8).reshape(7, 9)
    p = str(tmp_path / "labels.idx2-ubyte")
    _write_idx(p, arr)
    via_native = read_idx(p)  # native path (file exists, uncompressed)
    # gz variant exercises the pure-Python branch
    with open(p, "rb") as f:
        raw = f.read()
    pgz = str(tmp_path / "z.idx2-ubyte")
    with gzip.open(pgz + ".gz", "wb") as f:
        f.write(raw)
    via_python = read_idx(pgz)
    np.testing.assert_array_equal(via_native, via_python)


@needs_native
def test_native_csv_parse(tmp_path):
    rng = np.random.RandomState(0)
    arr = rng.randn(200, 7).astype(np.float32)
    p = str(tmp_path / "data.csv")
    with open(p, "w") as f:
        f.write("a,b,c,d,e,f,g\n")  # header
        for row in arr:
            f.write(",".join(f"{v:.6f}" for v in row) + "\n")
    out = native_read_csv(p, skip_header=True)
    assert out is not None
    np.testing.assert_allclose(out, arr, rtol=0, atol=1e-5)


@needs_native
def test_native_csv_rejects_non_numeric(tmp_path):
    p = str(tmp_path / "bad.csv")
    with open(p, "w") as f:
        f.write("1.0,2.0\n3.0,setosa\n")
    assert native_read_csv(p) is None


@needs_native
def test_csv_fetcher_uses_native(tmp_path):
    from deeplearning4j_tpu.datasets.fetchers import CSVDataFetcher

    p = str(tmp_path / "train.csv")
    rng = np.random.RandomState(1)
    X = rng.rand(50, 4)
    y = rng.randint(0, 3, 50)
    with open(p, "w") as f:
        for xi, yi in zip(X, y):
            f.write(",".join(f"{v:.5f}" for v in xi) + f",{yi}\n")
    ds = CSVDataFetcher(p, label_column=-1).fetch()
    assert ds.features.shape == (50, 4)
    assert ds.labels.shape == (50, 3)
    np.testing.assert_allclose(np.asarray(ds.features), X, atol=1e-4)


def test_python_fallback_when_native_disabled(tmp_path, monkeypatch):
    from deeplearning4j_tpu.datasets.fetchers import CSVDataFetcher

    monkeypatch.setenv("DL4J_TPU_NO_NATIVE", "1")
    import deeplearning4j_tpu.native as nat
    monkeypatch.setattr(nat, "_lib", None)
    monkeypatch.setattr(nat, "_load_failed", False)
    p = str(tmp_path / "train.csv")
    with open(p, "w") as f:
        f.write("0.1,0.2,0\n0.3,0.4,1\n")
    ds = CSVDataFetcher(p, label_column=-1).fetch()
    assert ds.features.shape == (2, 2)
    monkeypatch.setattr(nat, "_load_failed", False)  # restore probe state


@needs_native
def test_native_csv_rejects_empty_trailing_field(tmp_path):
    # strtod must not cross the newline and parse the next row's value
    p = str(tmp_path / "ragged.csv")
    with open(p, "w") as f:
        f.write("1.0,\n2.0,3.0\n")
    assert native_read_csv(p) is None
