"""CIFAR-10 + curves data-path tests (VERDICT r3 next-round #4 and
missing #3/#4): fixture-backed download, loader parity, and a VGG
convergence smoke on class-separable data — all hermetic."""

import hashlib
import io
import os
import pickle
import tarfile
import threading
from http.server import HTTPServer, SimpleHTTPRequestHandler

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import cifar
from deeplearning4j_tpu.datasets.fetch import fetch_cifar10, fetch_curves
from deeplearning4j_tpu.datasets.fetchers import (Cifar10DataFetcher,
                                                  CurvesDataFetcher)


def _cifar_tgz(rng, n_per_batch=8) -> bytes:
    """Structurally-valid cifar-10-python.tar.gz with tiny batches."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name in list(cifar.TRAIN_BATCHES) + [cifar.TEST_BATCH]:
            payload = pickle.dumps({
                b"data": rng.randint(0, 256, (n_per_batch, 3072),
                                     dtype=np.uint8),
                b"labels": rng.randint(0, 10, n_per_batch).tolist(),
            })
            info = tarfile.TarInfo(f"{cifar.BATCH_DIR}/{name}")
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))
    return buf.getvalue()


@pytest.fixture()
def file_server(tmp_path):
    srv_dir = tmp_path / "srv"
    srv_dir.mkdir()

    class Handler(SimpleHTTPRequestHandler):
        def __init__(self, *a, **kw):
            super().__init__(*a, directory=str(srv_dir), **kw)

        def log_message(self, *a):
            pass

    httpd = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield srv_dir, f"http://127.0.0.1:{httpd.server_port}/"
    finally:
        httpd.shutdown()


def test_fetch_cifar10_downloads_untars_and_caches(file_server, tmp_path):
    srv_dir, base = file_server
    blob = _cifar_tgz(np.random.RandomState(0))
    (srv_dir / "cifar-10-python.tar.gz").write_bytes(blob)
    cache = str(tmp_path / "cache")

    root = fetch_cifar10(cache_dir=cache,
                         url=base + "cifar-10-python.tar.gz",
                         sha256=hashlib.sha256(blob).hexdigest())
    X, y = cifar.load_real_cifar10(root, train=True)
    assert X.shape == (40, 3072) and X.dtype == np.float32
    assert X.max() <= 1.0 and y.shape == (40,)
    Xt, yt = cifar.load_real_cifar10(root, train=False)
    assert Xt.shape == (8, 3072)

    # second fetch is served from cache: poison the server to prove no
    # re-download happens
    (srv_dir / "cifar-10-python.tar.gz").write_bytes(b"poison")
    root2 = fetch_cifar10(cache_dir=cache,
                          url=base + "cifar-10-python.tar.gz")
    assert root2 == root


def test_fetch_cifar10_rejects_bad_checksum(file_server, tmp_path):
    from deeplearning4j_tpu.datasets.fetch import ChecksumError

    srv_dir, base = file_server
    (srv_dir / "cifar-10-python.tar.gz").write_bytes(
        _cifar_tgz(np.random.RandomState(1)))
    with pytest.raises(ChecksumError):
        fetch_cifar10(cache_dir=str(tmp_path / "c2"),
                      url=base + "cifar-10-python.tar.gz",
                      sha256="0" * 64)


def test_cifar10_fetcher_real_data_via_env(file_server, tmp_path,
                                           monkeypatch):
    """End-to-end fetcher gating: $CIFAR10_DIR with real batches wins over
    the synthetic fallback."""
    srv_dir, base = file_server
    blob = _cifar_tgz(np.random.RandomState(2))
    (srv_dir / "cifar-10-python.tar.gz").write_bytes(blob)
    cache = str(tmp_path / "cache3")
    fetch_cifar10(cache_dir=cache, url=base + "cifar-10-python.tar.gz",
                  sha256=None)
    monkeypatch.setenv("CIFAR10_DIR", cache)
    ds = Cifar10DataFetcher().fetch(16)
    assert ds.features.shape == (16, 3072)
    assert ds.labels.shape == (16, 10)
    # matches the on-disk bytes, proving the real path was taken
    X, _ = cifar.load_real_cifar10(os.path.join(cache, cifar.BATCH_DIR))
    np.testing.assert_allclose(ds.features, X[:16])


def test_cifar10_synthetic_is_deterministic_and_separable():
    X1, y1 = cifar.synthetic_cifar10(64)
    X2, y2 = cifar.synthetic_cifar10(64)
    np.testing.assert_array_equal(X1, X2)
    np.testing.assert_array_equal(y1, y2)
    assert X1.shape == (64, 3072) and 0.0 <= X1.min() and X1.max() <= 1.0
    # class templates are distinguishable: nearest-template classification
    # on clean templates beats chance by a wide margin
    Xa, ya = cifar.synthetic_cifar10(256, seed=11)
    centroids = np.stack([Xa[ya == c].mean(0) for c in range(10)])
    pred = np.argmin(((Xa[:, None] - centroids[None]) ** 2).sum(-1), axis=1)
    assert (pred == ya).mean() > 0.9


def test_curves_fetcher_real_npz_via_env(tmp_path, monkeypatch):
    """VERDICT r3 missing #4: the curves corpus rides the checksummed
    download/cache infra; a cached .npz in $CURVES_DIR is loaded for real
    instead of the synthetic generator."""
    rng = np.random.RandomState(3)
    X = rng.rand(32, 784).astype(np.float32)
    np.savez(tmp_path / "curves.npz", features=X)
    monkeypatch.setenv("CURVES_DIR", str(tmp_path))
    ds = CurvesDataFetcher().fetch(20)
    np.testing.assert_allclose(ds.features, X[:20])
    np.testing.assert_allclose(ds.labels, X[:20])  # autoencoder-style


def test_fetch_curves_downloads_npz(file_server, tmp_path):
    srv_dir, base = file_server
    buf = io.BytesIO()
    np.savez(buf, features=np.zeros((4, 784), np.float32))
    (srv_dir / "curves.npz").write_bytes(buf.getvalue())
    path = fetch_curves(cache_dir=str(tmp_path / "cv"),
                        url=base + "curves.npz")
    with np.load(path) as z:
        assert z["features"].shape == (4, 784)


@pytest.mark.slow
def test_vgg_cifar10_converges_on_separable_data():
    """BASELINE configs[2] convergence evidence: a narrow VGG on the
    class-separable synthetic CIFAR-10 drives loss down and beats chance
    accuracy by a wide margin (the reference's ConvolutionLayer is
    stubbed — it could never run this)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.zoo import vgg_cifar10
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    ds = Cifar10DataFetcher().fetch(256)
    net = MultiLayerNetwork(vgg_cifar10(lr=0.05, iterations=30, width=4),
                            seed=0).init()
    x = jnp.asarray(ds.features)
    y = jnp.asarray(ds.labels)
    loss0 = float(net.score(x, y))
    net.fit(x, y)
    loss1 = float(net.score(x, y))
    assert loss1 < loss0 * 0.7, (loss0, loss1)
    acc = (np.asarray(net.output(x)).argmax(1)
           == np.asarray(ds.labels).argmax(1)).mean()
    assert acc > 0.5, acc  # chance is 0.1
