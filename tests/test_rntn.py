"""RNTN: tree parsing, scan-based forward, and sentiment learning."""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.models.rntn import (RNTN, parse_tree, plan_tree,
                                            tree_tokens)

# tiny synthetic sentiment corpus: label 1 = positive words, 0 = negative;
# root label = majority sentiment
POS = ["(1 (1 good) (1 great))",
       "(1 (1 (1 nice) (1 fine)) (1 good))",
       "(1 (1 happy) (1 (1 good) (1 great)))"]
NEG = ["(0 (0 bad) (0 awful))",
       "(0 (0 (0 poor) (0 bad)) (0 awful))",
       "(0 (0 sad) (0 (0 bad) (0 poor)))"]


def test_parse_tree_structure():
    t = parse_tree("(3 (2 the) (4 (3 very) (4 good)))")
    assert not t.is_leaf and t.label == 3
    assert t.left.is_leaf and t.left.word == "the" and t.left.label == 2
    assert t.right.right.word == "good" and t.right.right.label == 4
    assert tree_tokens(t) == ["the", "very", "good"]


def test_parse_tree_unary_collapse():
    t = parse_tree("(2 (3 word))")
    assert t.is_leaf and t.word == "word" and t.label == 2


def test_plan_tree_postorder():
    t = parse_tree("(1 (0 a) (1 b))")
    plan = plan_tree(t, {"<unk>": 0, "a": 1, "b": 2}, max_nodes=8)
    assert plan.n_nodes == 3
    # post-order: leaves first, root last; root children point at them
    assert list(plan.is_leaf[:3]) == [True, True, False]
    assert plan.left[2] == 0 and plan.right[2] == 1
    assert plan.label[2] == 1


def test_plan_tree_overflow_raises():
    t = parse_tree("(1 (0 a) (1 b))")
    with pytest.raises(ValueError, match="max_nodes"):
        plan_tree(t, {"<unk>": 0}, max_nodes=2)


def test_rntn_learns_tiny_sentiment():
    model = RNTN(dim=8, n_classes=2, max_nodes=16, lr=0.1, seed=0)
    trees = POS + NEG
    loss = model.fit(trees, epochs=150)
    assert np.isfinite(loss)
    assert model.accuracy(trees, root_only=True) == 1.0
    # per-node accuracy should also be high on this separable corpus
    assert model.accuracy(trees, root_only=False) > 0.9


def test_rntn_predict_unseen_composition():
    model = RNTN(dim=8, n_classes=2, max_nodes=16, lr=0.1, seed=1)
    model.fit(POS + NEG, epochs=150)
    # novel tree built from seen vocabulary
    root_pred, node_preds = model.predict("(1 (1 great) (1 happy))")
    assert root_pred == 1
    assert len(node_preds) == 3


def test_rntn_refit_grows_vocab():
    model = RNTN(dim=8, n_classes=2, max_nodes=16, lr=0.1, seed=0)
    model.fit(POS, epochs=20)
    n0 = model.params["E"].shape[0]
    model.fit(NEG, epochs=20)  # new words must extend the embedding table
    assert model.params["E"].shape[0] == len(model.vocab) > n0
    assert model._hist["E"].shape == model.params["E"].shape
