"""PoS tagging/filtering + sentiment lexicon (row-24 text infra)."""

from deeplearning4j_tpu.text.pos import PosFilterTokenizerFactory, PosTagger
from deeplearning4j_tpu.text.sentiment_lexicon import SentimentLexicon
from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory


def test_pos_tagger_basic_tags():
    tags = PosTagger().tag("the quick dogs quickly running jumped over 42"
                           .split())
    assert tags[0] == "DT"
    assert tags[2] == "NNS"      # dogs
    assert tags[3] == "RB"       # quickly
    assert tags[4] == "VBG"      # running
    assert tags[5] == "VBD"      # jumped
    assert tags[6] == "IN"       # over
    assert tags[7] == "CD"       # 42


def test_pos_filter_tokenizer_keeps_allowed():
    f = PosFilterTokenizerFactory(DefaultTokenizerFactory(),
                                  allowed_tags={"NN", "NNS"})
    toks = f.tokenize("the creation of several dogs quickly")
    assert "creation" in toks and "dogs" in toks
    assert "the" not in toks and "quickly" not in toks
    # create() returns a Tokenizer over the filtered stream
    assert f.create("the dogs").get_tokens() == ["dogs"]


def test_sentiment_lexicon_builtin():
    lex = SentimentLexicon()
    assert lex.score("great") > 0 > lex.score("awful")
    assert lex.score("zyzzyva") == 0.0
    assert lex.label("great") == 1 and lex.label("awful") == 0
    assert lex.label("table", n_classes=3) == 1  # neutral


def test_bundled_lexicon_is_scored_not_membership():
    """VERDICT r3 next-#8: the default lexicon loads the bundled SWN3-format
    TSV — hundreds of entries with GRADED pos/neg strengths, not a
    hand-list membership check (reference: corpora/sentiwordnet/SWN3.java)."""
    lex = SentimentLexicon()
    assert len(lex.scores) >= 300
    # graded strengths: superlatives outscore mild words on both poles
    assert lex.score("excellent") > lex.score("decent") > 0
    assert lex.score("atrocious") < lex.score("dull") < 0
    # distinct strength levels exist (a membership list would be 2-valued)
    assert len({abs(s) for s in lex.scores.values()}) >= 5


def test_bundled_lexicon_file_is_swn3_format():
    import os

    from deeplearning4j_tpu.text import sentiment_lexicon as sl

    assert os.path.exists(sl._BUNDLED)
    lex = SentimentLexicon.from_sentiwordnet(sl._BUNDLED)
    assert lex.scores == SentimentLexicon().scores
    with open(sl._BUNDLED) as f:
        data_lines = [l for l in f if l.strip() and not l.startswith("#")]
    parts = data_lines[0].rstrip("\n").split("\t")
    # POS ID PosScore NegScore SynsetTerms [Gloss] — the standard
    # SentiWordNet 3.x layout has a 6th gloss column; the bundled file
    # omits it, and the parser accepts either
    assert len(parts) >= 5
    float(parts[2]), float(parts[3])


def test_sentiwordnet_file_parsing(tmp_path):
    """SWN3.java:64-126 aggregation: per `word#POS` key the synset scores
    land at their sense rank and are harmonically weighted
    (sum_i v[i]/(i+1) / sum_{i=1..n} 1/i); extract() sums across POS."""
    p = tmp_path / "swn.txt"
    p.write_text(
        "# SentiWordNet comment\n"
        "a\t00001740\t0.75\t0\tgood#1 great#2\n"
        "a\t00002098\t0\t0.875\tbad#1\n"
        "a\t00002312\t0.25\t0.125\tgood#3\n")
    lex = SentimentLexicon.from_sentiwordnet(str(p))
    # good#a senses: rank1=0.75, rank2 absent (0), rank3=0.125
    want_good = (0.75 / 1 + 0.0 / 2 + 0.125 / 3) / (1 + 1 / 2 + 1 / 3)
    assert abs(lex.score("good") - want_good) < 1e-9
    assert lex.score("bad") == -0.875
    # great#a rank2 only: vector [0, 0.75] -> (0.75/2) / (1 + 1/2)
    assert abs(lex.score("great") - (0.75 / 2) / 1.5) < 1e-9


def test_sentiment_negation_flip():
    """SWN3.scoreTokens parity: a negation word flips the span score."""
    lex = SentimentLexicon()
    pos = lex.score_tokens(["a", "good", "movie"])
    neg = lex.score_tokens(["not", "a", "good", "movie"])
    assert pos > 0 and abs(neg + pos) < 1e-9


def test_sentiment_malformed_rank_skipped(tmp_path):
    """A non-positive sense rank (foo#0) is skipped like other malformed
    fields instead of crashing the lexicon load."""
    p = tmp_path / "bad.txt"
    p.write_text("a\t1\t0.5\t0\tfoo#0 bar#1\n")
    lex = SentimentLexicon.from_sentiwordnet(str(p))
    assert lex.score("bar") == 0.5 and lex.score("foo") == 0.0


def test_neutral_sentinel_honored_in_three_class_mode():
    assert SentimentLexicon.label_for_score(0.0, 3, neutral=-1) == -1
    assert SentimentLexicon.label_for_score(0.05, 3) == 1  # band neutral


def test_sentiment_multisense_gloss_column(tmp_path):
    """Standard 6-column SentiWordNet rows (trailing gloss) parse too."""
    p = tmp_path / "swn6.txt"
    p.write_text("a\t1\t0.5\t0\thappy#1\tenjoying well-being\n")
    lex = SentimentLexicon.from_sentiwordnet(str(p))
    assert lex.score("happy") == 0.5


def test_lexicon_labels_trees_for_rntn():
    from deeplearning4j_tpu.text.tree_parser import TreeParser

    lex = SentimentLexicon()
    parser = TreeParser(strategy="balanced", label_fn=lex.label_fn(2))
    t = parser.parse("great wonderful day")
    from deeplearning4j_tpu.models.rntn import tree_tokens
    assert tree_tokens(t) == ["great", "wonderful", "day"]
    assert t.left.label == 1  # "great" positive


def test_hmm_tagger_context_disambiguation():
    """The bundled trained HMM model (VERDICT r2 missing #4 / weak: rule
    stub) must tag the SAME word differently by context — impossible for
    the per-word rule lexicon."""
    from deeplearning4j_tpu.text.hmm_pos import bundled_tagger

    t = bundled_tagger()
    assert t.tag("she can open the can".split()) == \
        ["PRP", "MD", "VB", "DT", "NN"]
    assert t.tag("the plants grow quickly".split()) == \
        ["DT", "NNS", "VBP", "RB"]
    assert t.tag("she plants trees".split()) == ["PRP", "VBZ", "NNS"]


def test_hmm_tagger_unknown_words_via_suffix():
    from deeplearning4j_tpu.text.hmm_pos import bundled_tagger

    t = bundled_tagger()
    tags = t.tag("an unknown zorbification happened".split())
    assert tags[-2:] == ["NN", "VBD"]  # -tion noun, -ed past verb


def test_hmm_tagger_train_roundtrip(tmp_path):
    from deeplearning4j_tpu.text.hmm_pos import HmmPosTagger

    corpus = [[("dogs", "NNS"), ("run", "VBP")],
              [("the", "DT"), ("dog", "NN"), ("runs", "VBZ")]]
    t = HmmPosTagger().train(corpus)
    p = tmp_path / "m.json"
    t.save(str(p))
    t2 = HmmPosTagger.load(str(p))
    assert t2.tag(["the", "dog"]) == t.tag(["the", "dog"]) == ["DT", "NN"]


def test_pos_filter_uses_trained_tagger_by_default():
    from deeplearning4j_tpu.text.hmm_pos import HmmPosTagger
    from deeplearning4j_tpu.text.pos import PosFilterTokenizerFactory
    from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory

    f = PosFilterTokenizerFactory(DefaultTokenizerFactory(), {"NN", "NNS"})
    assert isinstance(f.tagger, HmmPosTagger)
    # "can" kept only where it is a noun
    assert f.tokenize("she can open the can") == ["can"]
