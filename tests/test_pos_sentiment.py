"""PoS tagging/filtering + sentiment lexicon (row-24 text infra)."""

from deeplearning4j_tpu.text.pos import PosFilterTokenizerFactory, PosTagger
from deeplearning4j_tpu.text.sentiment_lexicon import SentimentLexicon
from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory


def test_pos_tagger_basic_tags():
    tags = PosTagger().tag("the quick dogs quickly running jumped over 42"
                           .split())
    assert tags[0] == "DT"
    assert tags[2] == "NNS"      # dogs
    assert tags[3] == "RB"       # quickly
    assert tags[4] == "VBG"      # running
    assert tags[5] == "VBD"      # jumped
    assert tags[6] == "IN"       # over
    assert tags[7] == "CD"       # 42


def test_pos_filter_tokenizer_keeps_allowed():
    f = PosFilterTokenizerFactory(DefaultTokenizerFactory(),
                                  allowed_tags={"NN", "NNS"})
    toks = f.tokenize("the creation of several dogs quickly")
    assert "creation" in toks and "dogs" in toks
    assert "the" not in toks and "quickly" not in toks
    # create() returns a Tokenizer over the filtered stream
    assert f.create("the dogs").get_tokens() == ["dogs"]


def test_sentiment_lexicon_builtin():
    lex = SentimentLexicon()
    assert lex.score("great") > 0 > lex.score("awful")
    assert lex.score("zyzzyva") == 0.0
    assert lex.label("great") == 1 and lex.label("awful") == 0
    assert lex.label("table", n_classes=3) == 1  # neutral


def test_bundled_lexicon_is_scored_not_membership():
    """VERDICT r3 next-#8: the default lexicon loads the bundled SWN3-format
    TSV — hundreds of entries with GRADED pos/neg strengths, not a
    hand-list membership check (reference: corpora/sentiwordnet/SWN3.java)."""
    lex = SentimentLexicon()
    assert len(lex.scores) >= 300
    # graded strengths: superlatives outscore mild words on both poles
    assert lex.score("excellent") > lex.score("decent") > 0
    assert lex.score("atrocious") < lex.score("dull") < 0
    # distinct strength levels exist (a membership list would be 2-valued)
    assert len({abs(s) for s in lex.scores.values()}) >= 5


def test_bundled_lexicon_file_is_swn3_format():
    import os

    from deeplearning4j_tpu.text import sentiment_lexicon as sl

    assert os.path.exists(sl._BUNDLED)
    lex = SentimentLexicon.from_sentiwordnet(sl._BUNDLED)
    assert lex.scores == SentimentLexicon().scores
    with open(sl._BUNDLED) as f:
        data_lines = [l for l in f if l.strip() and not l.startswith("#")]
    parts = data_lines[0].rstrip("\n").split("\t")
    assert len(parts) == 5  # POS  ID  PosScore  NegScore  SynsetTerms
    float(parts[2]), float(parts[3])


def test_sentiwordnet_file_parsing(tmp_path):
    p = tmp_path / "swn.txt"
    p.write_text(
        "# SentiWordNet comment\n"
        "a\t00001740\t0.75\t0\tgood#1 great#2\n"
        "a\t00002098\t0\t0.875\tbad#1\n"
        "a\t00002312\t0.25\t0.125\tgood#3\n")
    lex = SentimentLexicon.from_sentiwordnet(str(p))
    assert abs(lex.score("good") - (0.75 + 0.125) / 2) < 1e-9
    assert lex.score("bad") == -0.875
    assert lex.score("great") == 0.75


def test_lexicon_labels_trees_for_rntn():
    from deeplearning4j_tpu.text.tree_parser import TreeParser

    lex = SentimentLexicon()
    parser = TreeParser(strategy="balanced", label_fn=lex.label_fn(2))
    t = parser.parse("great wonderful day")
    from deeplearning4j_tpu.models.rntn import tree_tokens
    assert tree_tokens(t) == ["great", "wonderful", "day"]
    assert t.left.label == 1  # "great" positive


def test_hmm_tagger_context_disambiguation():
    """The bundled trained HMM model (VERDICT r2 missing #4 / weak: rule
    stub) must tag the SAME word differently by context — impossible for
    the per-word rule lexicon."""
    from deeplearning4j_tpu.text.hmm_pos import bundled_tagger

    t = bundled_tagger()
    assert t.tag("she can open the can".split()) == \
        ["PRP", "MD", "VB", "DT", "NN"]
    assert t.tag("the plants grow quickly".split()) == \
        ["DT", "NNS", "VBP", "RB"]
    assert t.tag("she plants trees".split()) == ["PRP", "VBZ", "NNS"]


def test_hmm_tagger_unknown_words_via_suffix():
    from deeplearning4j_tpu.text.hmm_pos import bundled_tagger

    t = bundled_tagger()
    tags = t.tag("an unknown zorbification happened".split())
    assert tags[-2:] == ["NN", "VBD"]  # -tion noun, -ed past verb


def test_hmm_tagger_train_roundtrip(tmp_path):
    from deeplearning4j_tpu.text.hmm_pos import HmmPosTagger

    corpus = [[("dogs", "NNS"), ("run", "VBP")],
              [("the", "DT"), ("dog", "NN"), ("runs", "VBZ")]]
    t = HmmPosTagger().train(corpus)
    p = tmp_path / "m.json"
    t.save(str(p))
    t2 = HmmPosTagger.load(str(p))
    assert t2.tag(["the", "dog"]) == t.tag(["the", "dog"]) == ["DT", "NN"]


def test_pos_filter_uses_trained_tagger_by_default():
    from deeplearning4j_tpu.text.hmm_pos import HmmPosTagger
    from deeplearning4j_tpu.text.pos import PosFilterTokenizerFactory
    from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory

    f = PosFilterTokenizerFactory(DefaultTokenizerFactory(), {"NN", "NNS"})
    assert isinstance(f.tagger, HmmPosTagger)
    # "can" kept only where it is a noun
    assert f.tokenize("she can open the can") == ["can"]
