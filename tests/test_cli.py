"""CLI tests — reference `deeplearning4j-cli` test parity (flag parsing)
plus real end-to-end exec, which the reference stubs out
(`Train.java:55-57`)."""

import csv
import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.cli.driver import build_parser, main
from deeplearning4j_tpu.cli.schemes import load_input
from deeplearning4j_tpu.nn.conf import (
    LayerType, NeuralNetConfiguration, OptimizationAlgorithm, list_builder)


@pytest.fixture(scope="module")
def iris_conf_json(tmp_path_factory):
    base = NeuralNetConfiguration(
        activation="tanh", lr=0.1,
        optimization_algo=OptimizationAlgorithm.CONJUGATE_GRADIENT,
        num_iterations=40, seed=1)
    conf = (list_builder(base, 2).hidden_layer_sizes([10], n_in=4, n_out=3)
            .override(1, layer_type=LayerType.OUTPUT).build())
    p = tmp_path_factory.mktemp("conf") / "iris.json"
    p.write_text(conf.to_json())
    return str(p)


class TestFlags:
    def test_parser_requires_subcommand(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args([])

    def test_train_flags(self):
        args = build_parser().parse_args(
            ["train", "--input", "iris", "--model", "m.json",
             "--output", "out", "--runtime", "mesh",
             "--properties", "epochs=2,batch=32"])
        assert args.runtime == "mesh"
        assert args.input == "iris"

    def test_bad_runtime_rejected(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args(
                ["train", "--input", "iris", "--model", "m", "--output",
                 "o", "--runtime", "spark"])


class TestSchemes:
    def test_builtin_iris(self):
        d = load_input("iris")
        assert d.features.shape == (150, 4)
        assert d.labels.shape == (150, 3)

    def test_builtin_with_count(self):
        d = load_input("iris:50")
        assert d.features.shape[0] == 50

    def test_csv_scheme(self, tmp_path):
        p = tmp_path / "d.csv"
        with open(p, "w", newline="") as f:
            w = csv.writer(f)
            for i in range(10):
                w.writerow([i * 0.1, i * 0.2, i % 2])
        d = load_input(f"csv:{p}:2")
        assert d.features.shape == (10, 2)
        assert d.labels.shape == (10, 2)

    def test_unknown_scheme_raises(self):
        with pytest.raises(ValueError):
            load_input("ftp://nope")


class TestEndToEnd:
    def test_train_test_predict_cycle(self, iris_conf_json, tmp_path,
                                      capsys):
        out = str(tmp_path / "model")
        rc = main(["train", "--input", "iris", "--model", iris_conf_json,
                   "--output", out, "--normalize"])
        assert rc == 0
        saved = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert saved["saved"] == out
        assert os.path.isdir(out)

        rc = main(["test", "--input", "iris", "--model", out,
                   "--normalize"])
        assert rc == 0
        stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert stats["accuracy"] > 0.9

        pred_csv = str(tmp_path / "preds.csv")
        rc = main(["predict", "--input", "iris", "--model", out,
                   "--normalize", "--output", pred_csv])
        assert rc == 0
        with open(pred_csv) as f:
            rows = list(csv.reader(f))
        assert rows[0][0] == "prediction"
        assert len(rows) == 151
        preds = np.array([int(r[0]) for r in rows[1:]])
        assert set(preds.tolist()) <= {0, 1, 2}

    def test_train_mesh_runtime(self, iris_conf_json, tmp_path, capsys):
        out = str(tmp_path / "model-mesh")
        rc = main(["train", "--input", "iris:144", "--model", iris_conf_json,
                   "--output", out, "--runtime", "mesh", "--normalize",
                   "--properties", "epochs=30,batch=48"])
        assert rc == 0
        assert os.path.isdir(out)
        rc = main(["test", "--input", "iris", "--model", out, "--normalize"])
        assert rc == 0
        stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert stats["accuracy"] > 0.7


def test_cli_north_star_lenet_and_lstm_from_cli(tmp_path):
    """BASELINE north star: LeNet-MNIST and a 4-layer LSTM end-to-end
    from the CLI (zoo configs, no hand-written JSON)."""
    from deeplearning4j_tpu.cli.driver import main

    out1 = str(tmp_path / "lenet_ckpt")
    rc = main(["train", "--zoo", "lenet5:lr=0.05", "--input", "mnist:64",
               "--output", out1, "--properties", "epochs=1"])
    assert rc == 0

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("hello world " * 200)
    out2 = str(tmp_path / "lstm_ckpt")
    rc = main(["train", "--zoo", "char_lstm:layers=4,hidden=32,lr=0.1",
               "--input", f"text:{corpus}:16", "--num-examples", "32",
               "--output", out2])
    assert rc == 0
    import os
    assert os.path.isdir(out1) and os.path.isdir(out2)


def test_cli_train_requires_model_or_zoo(tmp_path):
    import pytest

    from deeplearning4j_tpu.cli.driver import main

    with pytest.raises(SystemExit, match="--model|--zoo"):
        main(["train", "--input", "iris:30",
              "--output", str(tmp_path / "x")])


def test_cli_char_transformer_trains_with_adam(tmp_path):
    """VERDICT r1 #5 done-criterion: the transformer zoo config trains with
    Adam from the CLI."""
    import os

    from deeplearning4j_tpu.cli.driver import main
    from deeplearning4j_tpu.models.zoo import char_transformer

    assert char_transformer(10).confs[0].updater == "adam"

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("hello world " * 100)
    out = str(tmp_path / "xf_ckpt")
    rc = main(["train", "--zoo", "char_transformer:d_model=16,blocks=1,heads=2",
               "--input", f"text:{corpus}:16", "--num-examples", "16",
               "--output", out])
    assert rc == 0
    assert os.path.isdir(out)


def test_mesh_runtime_rejects_pretrain_workflows(tmp_path):
    """--runtime mesh with a pretrain config must refuse loudly: the dp
    step is gradient-only and would silently skip CD-k/AE pretraining."""
    import pytest

    from deeplearning4j_tpu.cli.driver import main

    with pytest.raises(SystemExit, match="pretraining"):
        main(["train", "--input", "iris:", "--zoo", "dbn:hidden=8x4",
              "--runtime", "mesh", "--output", str(tmp_path / "x")])


def test_reconstruction_conf_via_model_json(tmp_path):
    """A deep-AE conf loaded through --model JSON (not --zoo) is detected
    by MECHANISM (reconstruction loss + AE pretrain stack): trains and
    scores against the inputs instead of crashing on label width."""
    import json as json_mod

    from deeplearning4j_tpu.cli.driver import main
    from deeplearning4j_tpu.models.zoo import deep_autoencoder

    conf = deep_autoencoder(4, hidden=(3,), iterations=3,
                            finetune_iterations=5)
    cj = tmp_path / "conf.json"
    cj.write_text(conf.to_json())
    rc = main(["train", "--input", "iris:", "--model", str(cj),
               "--output", str(tmp_path / "dae"), "--scale-01"])
    assert rc == 0
