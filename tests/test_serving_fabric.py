"""Multi-replica serving fabric (ISSUE 7 tentpole b): priority classes
in the coalescing queue, Prometheus /metrics conformance on replica and
router, the router's routing/ejection/drain behavior over real
`ModelServer`s, and the 2-replica CLI smoke — subprocess replicas on a
shared warmed compile cache, SIGTERM drain with a fault-harness delay
holding a request in flight, exit 0.

Tier-1: CPU-only; the subprocess smoke uses short drain timeouts."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import checkpoint
from deeplearning4j_tpu.serving import (PRIORITIES, FleetSupervisor,
                                        MicroBatcher, Router,
                                        parse_prometheus_text,
                                        replica_metrics, router_metrics)

N_IN, N_OUT = 6, 3


def _net(seed=0):
    return MultiLayerNetwork(mlp(n_in=N_IN, hidden=[8], n_out=N_OUT,
                                 lr=0.05), seed=seed).init()


def _x(rows, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(rows, N_IN).astype(np.float32)


def _http(url, body=None, timeout=30):
    req = urllib.request.Request(
        url, data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# -- priority classes in the coalescing queue --------------------------------

def test_priority_validation():
    b = MicroBatcher(_net(), auto_start=False)
    with pytest.raises(ValueError):
        b.predict(_x(1), priority="urgent")


def test_interactive_preempts_queued_batch():
    """With the dispatcher parked, enqueue batch-class requests then an
    interactive one: the queue must hold [interactive, batch, batch] so
    the next flush serves the user-facing rows first."""
    b = MicroBatcher(_net(), auto_start=False)  # dispatcher never starts
    done = []

    def enqueue(prio, i):
        try:
            b.predict(_x(1, seed=i), timeout=30.0, priority=prio)
            done.append((prio, i))
        except Exception:  # noqa: BLE001 — drain answers them later
            pass

    threads = []
    for i, prio in enumerate(["batch", "batch", "interactive", "batch"]):
        t = threading.Thread(target=enqueue, args=(prio, i))
        t.start()
        threads.append(t)
        deadline = time.monotonic() + 10.0
        while b.queue_depth() < i + 1 and time.monotonic() < deadline:
            time.sleep(0.005)
    assert b.queue_depth() == 4
    with b._cv:
        (q,) = b._queues.values()
        order = [r.priority for r in q]
    assert order == ["interactive", "batch", "batch", "batch"]
    st = b.stats()
    assert st["priorities"]["interactive"]["queue_depth"] == 1
    assert st["priorities"]["batch"]["queue_depth"] == 3
    b.start()
    b.stop()  # drain-on-stop answers everyone
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive()
    assert len(done) == 4


def test_per_priority_latency_histograms_accumulate():
    net = _net()
    net.warmup([4])
    b = MicroBatcher(net, max_delay_ms=1.0).start()
    try:
        b.predict(_x(2), timeout=30.0, priority="interactive")
        b.predict(_x(2), timeout=30.0, priority="batch")
    finally:
        b.stop()
    st = b.stats()
    for prio in PRIORITIES:
        h = st["priorities"][prio]["latency_hist_s"]
        assert sum(h["counts"]) + h["inf"] == h["count"] == 1
        assert h["sum"] > 0.0
        assert st["priorities"][prio]["requests"] == 1


# -- Prometheus text-format conformance --------------------------------------

def _assert_monotonic(before: dict, after: dict):
    """Every counter/histogram-cumulative series only ever moves up."""
    for name, series in before.items():
        if not (name.endswith("_total") or name.endswith("_bucket")
                or name.endswith("_count") or name.endswith("_sum")):
            continue
        for labels, value in series.items():
            assert after[name][labels] >= value, (name, labels)


def test_replica_metrics_conformance_and_monotonic_counters():
    net = _net()
    net.warmup([4])
    server = net.serve(max_delay_ms=1.0)
    try:
        _http(server.url + "/v1/predict",
              {"features": _x(2, seed=1).tolist(), "priority": "batch"})
        code, text1 = _http(server.url + "/metrics")
        assert code == 200
        parsed1 = parse_prometheus_text(text1)  # raises on any bad line
        for family in ("dl4j_serving_queue_depth",
                       "dl4j_serving_batch_rows_bucket",
                       "dl4j_serving_request_latency_seconds_bucket",
                       "dl4j_serving_breaker_state",
                       "dl4j_serving_cache_hits_total",
                       "dl4j_serving_cache_disk_hits_total",
                       "dl4j_serving_cache_fetch_hits_total",
                       "dl4j_serving_cache_fetch_corrupt_total"):
            assert family in parsed1, family
        # priority label present on the latency histogram
        lat = parsed1["dl4j_serving_request_latency_seconds_bucket"]
        prios = {dict(lbl).get("priority") for lbl in lat}
        assert prios == set(PRIORITIES)
        _http(server.url + "/v1/predict",
              {"features": _x(3, seed=2).tolist()})
        code, text2 = _http(server.url + "/metrics")
        parsed2 = parse_prometheus_text(text2)
        _assert_monotonic(parsed1, parsed2)
        # and the second scrape actually observed the new request
        key = (("priority", "interactive"),)
        assert (parsed2["dl4j_serving_requests_total"][key]
                > parsed1["dl4j_serving_requests_total"][key])
    finally:
        server.stop()


def test_metrics_content_type_and_histogram_shape():
    net = _net()
    net.warmup([4])
    text = replica_metrics(net.serve(max_delay_ms=1.0).stats())
    parsed = parse_prometheus_text(text)
    buckets = parsed["dl4j_serving_batch_rows_bucket"]
    infs = [v for lbl, v in buckets.items() if dict(lbl)["le"] == "+Inf"]
    assert len(infs) == 1
    assert infs[0] == parsed["dl4j_serving_batch_rows_count"][()]


def test_quarantine_gauge_and_multihost_families_conformance():
    """ISSUE 20 satellite: `dl4j_fleet_quarantine_remaining_seconds`
    counts down on the supervisor's own (injected) clock,
    `quarantined_until` appears in stats(), and every new multi-host
    family — fleet partition/failover counters, per-agent lease
    families, per-host router rollups — renders to strictly parseable
    text whose counters only move up across scrapes."""

    class _Clock:
        t = 100.0

        def __call__(self):
            return self.t

    class _DeadHandle:
        """A handle that is dead on arrival: one tick books the death
        and (max_restarts=1) quarantines the slot."""

        url = None
        summary = None

        def poll(self):
            return 1

        def wait_ready(self):
            return {}

        def terminate(self):
            pass

        def kill(self):
            pass

        def wait(self, timeout=None):
            return 1

    clk = _Clock()
    # one never-polled replica keeps the per-host rollup non-empty; the
    # agent URL is unreachable, so the first heartbeat partitions it
    # (lease_misses=1) — every new family gets a non-trivial value
    router = Router(["http://127.0.0.1:9/dead"],
                    poll_interval_s=3600.0).start()
    sup = FleetSupervisor(spawn_fn=_DeadHandle, router=router,
                          initial=[_DeadHandle()], min_replicas=1,
                          max_replicas=1, max_restarts=1,
                          restart_window_s=1000.0, quarantine_s=60.0,
                          agents=["http://127.0.0.1:9/agent"],
                          remote_argv=["serve"], lease_misses=1,
                          agent_failover_s=1e9, clock=clk)
    try:
        sup.tick()
        st = sup.stats()
        assert st["states"]["quarantined"] == 1
        slot = st["slots"][0]
        assert slot["quarantined_until"] == pytest.approx(160.0)
        assert slot["quarantine_remaining_s"] == pytest.approx(60.0)
        assert st["agents"][0]["state"] == "partitioned"
        router.attach_fleet(sup)
        text1 = router_metrics(router.stats())
        parsed1 = parse_prometheus_text(text1)  # strict: raises on junk
        for fam in ("dl4j_fleet_quarantine_remaining_seconds",
                    "dl4j_fleet_partitions_total",
                    "dl4j_fleet_failovers_total",
                    "dl4j_router_host_replicas",
                    "dl4j_router_host_breaker_opens_total",
                    "dl4j_agent_up", "dl4j_agent_replicas",
                    "dl4j_agent_partitions_total",
                    "dl4j_agent_reconciles_total",
                    "dl4j_agent_adopted_total",
                    "dl4j_agent_orphans_stopped_total",
                    "dl4j_agent_failovers_total"):
            assert fam in parsed1, fam
        q = parsed1["dl4j_fleet_quarantine_remaining_seconds"]
        assert q[(("slot", "0"),)] == pytest.approx(60.0)
        assert parsed1["dl4j_fleet_partitions_total"][()] == 1
        assert parsed1["dl4j_router_host_replicas"][
            (("host", "local"),)] == 1
        (agent_lbl,) = parsed1["dl4j_agent_up"]
        assert dict(agent_lbl).keys() == {"agent"}   # label set stable
        assert parsed1["dl4j_agent_up"][agent_lbl] == 0  # partitioned
        # the gauge counts DOWN on the supervisor's clock while every
        # counter stays monotonic
        clk.t += 25.0
        sup.tick()
        parsed2 = parse_prometheus_text(router_metrics(router.stats()))
        _assert_monotonic(parsed1, parsed2)
        assert parsed2["dl4j_fleet_quarantine_remaining_seconds"][
            (("slot", "0"),)] == pytest.approx(35.0)
        assert (sup.stats()["slots"][0]["quarantined_until"]
                == pytest.approx(160.0))
    finally:
        sup.stop()
        router.stop()


def test_generation_metrics_conformance_and_monotonic(tmp_path):
    """The ISSUE 14 families — tokens counter, TTFT histogram, decode
    slot gauge — render to strictly-parseable text and the counters
    only move up across scrapes with traffic in between."""
    from deeplearning4j_tpu.models.zoo import char_lstm

    net = MultiLayerNetwork(char_lstm(11, hidden=12, n_layers=1),
                            seed=0).init()
    net.warmup_generate(slots=2, max_seq=16, prompt_buckets=(8,))
    server = net.serve(generate=True, gen_slots=2, gen_max_seq=16,
                       gen_prompt_buckets=(8,))
    try:
        _http(server.url + "/v1/generate",
              {"prompt": [1, 2], "max_new_tokens": 4})
        code, text1 = _http(server.url + "/metrics")
        assert code == 200
        parsed1 = parse_prometheus_text(text1)  # raises on any bad line
        for family in ("dl4j_serving_tokens_total",
                       "dl4j_serving_ttft_seconds_bucket",
                       "dl4j_serving_ttft_seconds_count",
                       "dl4j_serving_decode_slots"):
            assert family in parsed1, family
        # the slot gauge carries the state label, both states present
        states = {dict(lbl).get("state")
                  for lbl in parsed1["dl4j_serving_decode_slots"]}
        assert states == {"active", "free"}
        # one completed 4-token stream is on the counter and histogram
        assert list(parsed1["dl4j_serving_tokens_total"].values())[0] >= 4
        assert list(
            parsed1["dl4j_serving_ttft_seconds_count"].values())[0] >= 1
        _http(server.url + "/v1/generate",
              {"prompt": [3], "max_new_tokens": 3})
        code, text2 = _http(server.url + "/metrics")
        parsed2 = parse_prometheus_text(text2)
        _assert_monotonic(parsed1, parsed2)
        assert (list(parsed2["dl4j_serving_tokens_total"].values())[0]
                > list(parsed1["dl4j_serving_tokens_total"].values())[0])
    finally:
        server.stop()


def test_decode_accelerator_metrics_conformance_and_monotonic():
    """The ISSUE 16 families — KV page-pool gauge, prefix-cache hit and
    miss counters, accepted-tokens-per-step histogram — render to
    strictly-parseable text with exactly the declared label sets, the
    counters only move up across scrapes, and the pre-existing
    generation families keep their label sets untouched."""
    from deeplearning4j_tpu.models.zoo import char_lstm

    net = MultiLayerNetwork(char_lstm(11, hidden=12, n_layers=1),
                            seed=0).init()
    draft = MultiLayerNetwork(char_lstm(11, hidden=8, n_layers=1),
                              seed=1).init()
    net.warmup_generate(slots=2, max_seq=16, prompt_buckets=(8,),
                        page_size=4, prefix_cache=True, draft_net=draft,
                        spec_k=2)
    server = net.serve(generate=True, gen_slots=2, gen_max_seq=16,
                       gen_prompt_buckets=(8,), gen_page_size=4,
                       gen_prefix_cache=True, gen_draft=draft,
                       gen_spec_k=2)
    try:
        _http(server.url + "/v1/generate",
              {"prompt": [1, 2], "max_new_tokens": 4})
        code, text1 = _http(server.url + "/metrics")
        assert code == 200
        parsed1 = parse_prometheus_text(text1)  # raises on any bad line
        for family in ("dl4j_serving_kv_pages",
                       "dl4j_serving_prefix_cache_hits_total",
                       "dl4j_serving_prefix_cache_misses_total",
                       "dl4j_serving_accepted_tokens_per_step_bucket",
                       "dl4j_serving_accepted_tokens_per_step_count"):
            assert family in parsed1, family
        # the page gauge carries the state label, both states present
        states = {dict(lbl).get("state")
                  for lbl in parsed1["dl4j_serving_kv_pages"]}
        assert states == {"free", "live"}
        # the pre-existing slot gauge kept its exact label set
        assert {dict(lbl).get("state")
                for lbl in parsed1["dl4j_serving_decode_slots"]} == {
                    "active", "free"}
        misses1 = list(
            parsed1["dl4j_serving_prefix_cache_misses_total"].values())[0]
        assert misses1 >= 1  # the cold first prompt
        # the same prompt again: a prefix hit, and every counter and
        # cumulative histogram series only moved up
        _http(server.url + "/v1/generate",
              {"prompt": [1, 2], "max_new_tokens": 4})
        code, text2 = _http(server.url + "/metrics")
        parsed2 = parse_prometheus_text(text2)
        _assert_monotonic(parsed1, parsed2)
        assert list(
            parsed2["dl4j_serving_prefix_cache_hits_total"].values())[0] >= 1
        assert (list(
            parsed2["dl4j_serving_accepted_tokens_per_step_count"].values()
        )[0] >= list(
            parsed1["dl4j_serving_accepted_tokens_per_step_count"].values()
        )[0])
    finally:
        server.stop()


def test_parser_rejects_malformed_lines():
    with pytest.raises(ValueError):
        parse_prometheus_text("this is not a metric line\n")
    with pytest.raises(ValueError):
        parse_prometheus_text("# TYPE foo widget\n")
    with pytest.raises(ValueError):
        parse_prometheus_text("ok_metric 1\nok_metric 2\n")  # dup series


# -- serve-precision policy on the wire (ISSUE 8) ----------------------------

def test_precision_policy_metrics_and_label_conformance():
    """The policy shows up as an info gauge, a per-policy row counter,
    and a `policy=` label on the cache/latency families — while the
    pre-existing request/batch families keep their exact label sets."""
    net = _net()
    net.set_serve_precision("int8", measure=False)
    net.warmup([4])
    server = net.serve(max_delay_ms=1.0)
    try:
        code, body = _http(server.url + "/v1/predict",
                           {"features": _x(2, seed=1).tolist()})
        assert code == 200, body
        code, text = _http(server.url + "/metrics")
        assert code == 200
        parsed = parse_prometheus_text(text)
        assert parsed["dl4j_serving_precision_policy_info"][
            (("policy", "int8"),)] == 1
        assert parsed["dl4j_serving_policy_rows_total"][
            (("policy", "int8"),)] >= 2
        for fam in ("dl4j_serving_cache_hits_total",
                    "dl4j_serving_cache_misses_total",
                    "dl4j_serving_cache_disk_hits_total",
                    "dl4j_serving_cache_io_errors_total"):
            assert set(parsed[fam]) == {(("policy", "int8"),)}, fam
        for lbl in parsed["dl4j_serving_request_latency_seconds_count"]:
            d = dict(lbl)
            assert d["policy"] == "int8" and d["priority"] in PRIORITIES
        # unchanged families: priority-only requests, unlabeled batch rows
        assert set(parsed["dl4j_serving_requests_total"]) == {
            (("priority", "interactive"),), (("priority", "batch"),)}
        assert set(parsed["dl4j_serving_batch_rows_count"]) == {()}
    finally:
        server.stop()


def test_stats_programs_block_lists_policy_tuples():
    net = _net()
    net.warmup([4])
    net.set_serve_precision("bf16", measure=False)
    net.warmup([4])
    server = net.serve(max_delay_ms=1.0)
    try:
        code, body = _http(server.url + "/v1/stats")
        assert code == 200
        st = json.loads(body)
        rows = {(p["entry"], p["bucket"], p["sharding"], p["policy"])
                for p in st["programs"]}
        assert ("output", 4, "single", "f32") in rows
        assert ("output", 4, "single", "bf16") in rows
        assert st["precision"]["policy"] == "bf16"
    finally:
        server.stop()


def test_router_preserves_policy_label_and_aggregates_rows():
    nets = [_net(seed=0), _net(seed=0)]
    for n in nets:
        n.set_serve_precision("bf16", measure=False)
        n.warmup([4])
    servers = [n.serve(max_delay_ms=1.0) for n in nets]
    router = Router([s.url for s in servers],
                    poll_interval_s=3600.0).start()
    try:
        for i in range(4):
            code, body = _http(router.url + "/v1/predict",
                               {"features": _x(2, seed=i).tolist()})
            assert code == 200, body
        router.poll_once()
        st = router.stats()
        assert st["rows_by_policy"] == {"bf16": 8}
        parsed = parse_prometheus_text(router_metrics(st))
        assert parsed["dl4j_router_policy_rows_total"][
            (("policy", "bf16"),)] == 8
        # replica re-export keeps the policy label alongside `replica`
        info = parsed["dl4j_serving_precision_policy_info"]
        assert {dict(lbl)["policy"] for lbl in info} == {"bf16"}
        assert {dict(lbl)["replica"] for lbl in info} == {"0", "1"}
    finally:
        router.stop()
        for s in servers:
            s.stop()


# -- router over in-process ModelServers -------------------------------------

def _start_pair(poll_interval_s=0.1):
    nets = [_net(seed=0), _net(seed=0)]
    for n in nets:
        n.warmup([4])
    servers = [n.serve(max_delay_ms=1.0) for n in nets]
    router = Router([s.url for s in servers],
                    poll_interval_s=poll_interval_s).start()
    return servers, router


def test_router_routes_and_reports():
    servers, router = _start_pair()
    try:
        for i in range(4):
            code, body = _http(router.url + "/v1/predict",
                               {"features": _x(2, seed=i).tolist(),
                                "priority": PRIORITIES[i % 2]})
            assert code == 200, body
            assert len(json.loads(body)["output"]) == 2
        st = router.stats()
        assert st["healthy_replicas"] == 2
        assert sum(p["requests"] for p in st["priorities"].values()) == 4
        # round-robin actually spread the work
        per_replica = [r["stats"]["requests"] if r["stats"] else 0
                       for r in st["replicas"]]
        router.poll_once()
        per_replica = [r["stats"]["requests"] if r["stats"] else 0
                       for r in router.stats()["replicas"]]
        assert all(n >= 1 for n in per_replica), per_replica
        code, text = _http(router.url + "/metrics")
        assert code == 200
        parsed = parse_prometheus_text(text)
        assert "dl4j_router_requests_total" in parsed
        # replica-labeled re-export of the serving families
        reps = {dict(lbl).get("replica")
                for lbl in parsed["dl4j_serving_rows_total"]}
        assert reps == {"0", "1"}
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_router_ejects_dead_replica_and_recovers_traffic():
    # background poller parked (huge interval): health transitions are
    # driven deterministically by poll_once(), with no stale in-flight
    # poll racing the assertions below
    servers, router = _start_pair(poll_interval_s=3600.0)
    try:
        servers[0].stop()          # replica 0 gone: connections refused
        router.poll_once()
        assert router.healthy_count() == 1
        # every request still lands (on replica 1), possibly via retry
        for i in range(4):
            code, body = _http(router.url + "/v1/predict",
                               {"features": _x(1, seed=i).tolist()})
            assert code == 200, body
        assert router.is_ready()   # 1 healthy replica keeps readyz 200
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_router_drain_stops_admission():
    servers, router = _start_pair()
    try:
        router.drain(timeout_s=5.0)
        # replicas outlive the router drain (the CLI terminates them
        # afterwards, so their own drains can finish queued work)
        assert all(s.is_ready() for s in servers)
        with pytest.raises((urllib.error.URLError, ConnectionError)):
            _http(router.url + "/readyz")  # front door is closed
    finally:
        for s in servers:
            s.stop()


def test_router_503_when_no_replica():
    net = _net()
    net.warmup([4])
    server = net.serve(max_delay_ms=1.0)
    # poller parked — poll_once() drives health (see ejection test)
    router = Router([server.url], poll_interval_s=3600.0).start()
    try:
        server.stop()
        assert router.poll_once() == 0
        code, body = _http(router.url + "/v1/predict",
                           {"features": _x(1).tolist()})
        assert code == 503, body
        assert router.stats()["unroutable"] >= 1
        code, _ = _http(router.url + "/readyz")
        assert code == 503
    finally:
        router.stop()


def test_router_metrics_parse_without_traffic():
    servers, router = _start_pair()
    try:
        parsed = parse_prometheus_text(router_metrics(router.stats()))
        assert parsed["dl4j_router_replicas_healthy"][()] == 2
    finally:
        router.stop()
        for s in servers:
            s.stop()


# -- the real thing: 2-replica CLI router, warmed cache, SIGTERM drain -------

def test_cli_router_two_replicas_warmed_drain_exit_zero(tmp_path):
    """The ISSUE 7 acceptance smoke: shared warmed disk cache -> both
    replicas start with fresh_compiles == 0; a fault-harness delay keeps
    a request in flight when SIGTERM lands; the router+replicas drain
    answering every accepted request and exit 0."""
    net = _net()
    ckpt = str(tmp_path / "model")
    cache = str(tmp_path / "cache")
    checkpoint.save(ckpt, net.params, conf=net.conf)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           # PR 5 fault harness: every dispatcher execute sleeps 200ms,
           # so the straggler below is genuinely in flight at SIGTERM
           "DL4J_FAULT_PLAN": "dispatcher.execute=delay:0.2"}
    subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "warmup",
         "--model", ckpt, "--compile-cache", cache, "--shapes", "4"],
        check=True, capture_output=True, cwd=repo, env=env, timeout=300)
    proc = subprocess.Popen(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "serve",
         "--model", ckpt, "--compile-cache", cache, "--shapes", "4",
         "--replicas", "2", "--port", "0", "--max-delay-ms", "50",
         "--drain-timeout", "10"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=repo, env=env)
    try:
        watchdog = threading.Timer(240.0, proc.kill)
        watchdog.start()
        try:
            summary = json.loads(proc.stdout.readline())
        finally:
            watchdog.cancel()
        url = summary["url"]
        assert len(summary["replicas"]) == 2
        # the acceptance bar: warmed shared cache, zero fresh compiles
        assert summary["fresh_compiles"] == [0, 0]

        code, body = _http(url + "/v1/predict",
                           {"features": _x(2, seed=1).tolist()}, timeout=60)
        assert code == 200 and json.loads(body)["rows"] == 2

        # metrics scrape parses and counters are monotonic across scrapes
        code, text1 = _http(url + "/metrics", timeout=60)
        assert code == 200
        parsed1 = parse_prometheus_text(text1)
        _http(url + "/v1/predict",
              {"features": _x(1, seed=3).tolist(), "priority": "batch"},
              timeout=60)
        code, text2 = _http(url + "/metrics", timeout=60)
        parsed2 = parse_prometheus_text(text2)
        _assert_monotonic(parsed1, parsed2)

        # leave a request IN FLIGHT (50ms coalesce + 200ms fault delay)
        # when the SIGTERM lands: the fleet drain must still answer it
        inflight = {}

        def straggler():
            try:
                inflight["resp"] = _http(
                    url + "/v1/predict",
                    {"features": _x(1, seed=2).tolist()}, timeout=60)
            except Exception as e:  # noqa: BLE001
                inflight["error"] = e

        t = threading.Thread(target=straggler)
        t.start()
        time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        t.join(timeout=90.0)
        assert not t.is_alive()
        assert "resp" in inflight, inflight.get("error")
        assert inflight["resp"][0] == 200

        out, err = proc.communicate(timeout=180)
        assert proc.returncode == 0, (out, err)
        drained = json.loads(out.strip().splitlines()[-1])
        assert drained["drained"] is True
        assert drained["replica_exit_codes"] == [0, 0]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
