"""Distributed runtime tests on the virtual 8-device CPU mesh.

The analog of the reference's in-JVM distributed tests
(`BaseTestDistributed.java:34-98`, `TestDistributed`, `IRUnitDriver`):
real mesh, real collectives, no pod.
"""

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (LayerType, NeuralNetConfiguration,
                                        OptimizationAlgorithm, list_builder)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (DataParallelTrainer, average_pytrees,
                                         make_mesh, merge,
                                         ParameterAggregator)
from deeplearning4j_tpu.parallel import checkpoint as ckpt
from deeplearning4j_tpu.parallel.coordinator import (LocalRunner, StateTracker,
                                                     start_rest_api)
from deeplearning4j_tpu.parallel.data_parallel import (init_train_state,
                                                       make_sharded_train_step,
                                                       shard_train_state)


def _mlp_conf(n_in=4, n_hidden=8, n_out=3, **kw):
    base = NeuralNetConfiguration(
        n_in=n_in, n_out=n_out, lr=0.1,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        num_iterations=5, **kw)
    return (list_builder(base, 2)
            .hidden_layer_sizes([n_hidden], n_in, n_out)
            .override(1, layer_type=LayerType.OUTPUT)
            .pretrain(False).backprop(True).build())


def _toy_data(n=64, n_in=4, n_out=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in).astype(np.float32)
    w = rng.randn(n_in, n_out)
    y = np.eye(n_out, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    mesh2 = make_mesh({"dp": 2, "tp": -1})
    assert mesh2.shape["dp"] == 2 and mesh2.shape["tp"] == 4
    # dp is outer, tp inner
    assert mesh2.axis_names == ("dp", "tp")


def test_averaging_helpers():
    a = {"W": jnp.ones((2, 2)), "b": jnp.zeros(2)}
    b = {"W": 3 * jnp.ones((2, 2)), "b": 2 * jnp.ones(2)}
    avg = average_pytrees([a, b])
    assert np.allclose(avg["W"], 2.0) and np.allclose(avg["b"], 1.0)
    m = merge(a, b, 2)  # a += (b-a)/2
    assert np.allclose(m["W"], 2.0)
    agg = ParameterAggregator()
    agg.accumulate(a)
    agg.accumulate(b)
    assert np.allclose(agg.aggregate()["W"], 2.0)
    assert agg.count == 2


def test_dp_sync_training_decreases_loss():
    mesh = make_mesh({"dp": 8})
    conf = _mlp_conf()
    net = MultiLayerNetwork(conf).init()
    x, y = _toy_data()
    trainer = DataParallelTrainer(net, mesh, mode="sync")
    first = None
    for _ in range(30):
        s = trainer.fit([(x, y)])
        if first is None:
            first = s
    assert s < first


def test_dp_sync_matches_single_device_gradients():
    """One dp-sync step == one full-batch step on a single device."""
    conf = _mlp_conf()
    x, y = _toy_data(n=32)
    net1 = MultiLayerNetwork(conf, seed=7).init()
    net2 = MultiLayerNetwork(conf, seed=7).init()
    mesh8 = make_mesh({"dp": 8})
    mesh1 = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    t8 = DataParallelTrainer(net1, mesh8, mode="sync")
    t1 = DataParallelTrainer(net2, mesh1, mode="sync")
    s8 = t8.fit([(x, y)])
    s1 = t1.fit([(x, y)])
    for p8, p1 in zip(jax.tree_util.tree_leaves(t8.state.params),
                      jax.tree_util.tree_leaves(t1.state.params)):
        np.testing.assert_allclose(np.asarray(p8), np.asarray(p1),
                                   rtol=2e-4, atol=2e-5)


def test_bsp_averaging_mode():
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    conf = _mlp_conf()
    net = MultiLayerNetwork(conf).init()
    x, y = _toy_data()
    trainer = DataParallelTrainer(net, mesh, mode="averaging", local_steps=3)
    s0 = trainer.fit([(x, y)])
    for _ in range(15):
        s = trainer.fit([(x, y)])
    assert s < s0
    # params come out fully replicated (the pmean out_spec): every shard
    # holds the same averaged values
    for leaf in jax.tree_util.tree_leaves(trainer.state.params):
        assert leaf.sharding.is_fully_replicated


def test_sharded_tp_step_runs():
    """pjit path with tensor-parallel weight sharding compiles + steps."""
    mesh = make_mesh({"dp": 2, "tp": 4})
    conf = _mlp_conf(n_in=4, n_hidden=8, n_out=4)
    net = MultiLayerNetwork(conf).init()
    state = shard_train_state(init_train_state(net), mesh)
    step = make_sharded_train_step(conf, mesh)
    x, y = _toy_data(n=16, n_out=4)
    xs = jax.device_put(jnp.asarray(x), jax.NamedSharding(
        mesh, jax.sharding.PartitionSpec("dp")))
    ys = jax.device_put(jnp.asarray(y), jax.NamedSharding(
        mesh, jax.sharding.PartitionSpec("dp")))
    state2, score = step(state, xs, ys, jax.random.PRNGKey(0))
    assert np.isfinite(float(score))
    assert int(state2.step) == 1


def test_state_tracker_and_reaper():
    st = StateTracker(stale_after_s=0.0)
    st.add_worker("w0")
    st.add_worker("w1")
    assert set(st.workers()) == {"w0", "w1"}
    from deeplearning4j_tpu.parallel.coordinator import Job
    assert st.route_job("w0", Job(work=1))
    assert not st.route_job("w0", Job(work=2))  # AlreadyWorking
    stale = st.reap_stale()
    assert set(stale) == {"w0", "w1"}
    # orphaned pending job was requeued
    assert st.take_unclaimed() is not None
    st.increment("x", 2.0)
    assert st.count("x") == 2.0


def test_local_runner_bsp_and_rest():
    def perform(w):
        return {"v": jnp.asarray(float(w))}

    def aggregate(results):
        return average_pytrees(results) if results else None

    runner = LocalRunner(perform, aggregate, n_workers=3)
    out = runner.run(range(9))
    # average of the last BSP wave or of all, depending on wave bookkeeping;
    # all 9 results retained across waves
    assert out is not None and np.isfinite(float(out["v"]))
    assert runner.tracker.count("jobs_done") == 9

    server, port = start_rest_api(runner.tracker)
    try:
        body = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statetracker", timeout=5).read())
        assert body["counters"]["jobs_done"] == 9
        one = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statetracker/numbatchessofar",
            timeout=5).read())
        assert "numbatchessofar" in one
    finally:
        server.shutdown()


def test_local_runner_hogwild():
    seen = []

    def perform(w):
        seen.append(w)
        return {"v": jnp.asarray(1.0)}

    runner = LocalRunner(perform, lambda rs: len(rs), n_workers=2,
                         hogwild=True)
    out = runner.run(range(5))
    assert len(seen) == 5


def test_checkpoint_roundtrip(tmp_path):
    conf = _mlp_conf()
    net = MultiLayerNetwork(conf, seed=3).init()
    state = init_train_state(net)
    d = str(tmp_path / "ckpt")
    ckpt.save(d, state.params, state.updater, conf=conf, step=42,
              data_cursor={"epoch": 1, "batch": 7})
    params, updater, meta = ckpt.load(d, like_params=state.params,
                                      like_updater=state.updater)
    assert meta["step"] == 42
    assert meta["data_cursor"]["batch"] == 7
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    conf2 = ckpt.load_conf(d)
    assert conf2.n_layers == conf.n_layers


def test_checkpoint_async(tmp_path):
    conf = _mlp_conf()
    net = MultiLayerNetwork(conf, seed=3).init()
    d = str(tmp_path / "ck2")
    t = ckpt.save_async(d, net.params, conf=conf, step=1)
    t.join(timeout=30)
    params, _, meta = ckpt.load(d, like_params=net.params)
    assert meta["step"] == 1


def test_local_runner_retains_all_results_per_job():
    """Results are keyed per job, not per worker: 9 jobs / 1 worker."""
    runner = LocalRunner(lambda w: w, lambda rs: rs, n_workers=1)
    out = runner.run(range(1, 10))
    assert sorted(out) == list(range(1, 10))


def test_local_runner_poisoned_job_terminates():
    def perform(w):
        if w == 3:
            raise ValueError("poison")
        return w

    runner = LocalRunner(perform, lambda rs: rs, n_workers=2)
    out = runner.run(range(6))
    assert 3 not in out and len(out) == 5
    assert runner.tracker.count("jobs_failed") >= 1


def test_remainder_batch_pad_and_mask_consumes_all_samples():
    """VERDICT r1 #9: a batch not divisible by dp must not drop samples —
    the masked step on dp=8 must equal a full-batch step on one device."""
    conf = _mlp_conf()
    x, y = _toy_data(n=30)  # 30 % 8 = 6 -> old path dropped 6 samples
    net1 = MultiLayerNetwork(conf, seed=7).init()
    net2 = MultiLayerNetwork(conf, seed=7).init()
    mesh8 = make_mesh({"dp": 8})
    mesh1 = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    t8 = DataParallelTrainer(net1, mesh8, mode="sync")
    t1 = DataParallelTrainer(net2, mesh1, mode="sync")
    t8.fit([(x, y)])
    t1.fit([(x, y)])
    for p8, p1 in zip(jax.tree_util.tree_leaves(t8.state.params),
                      jax.tree_util.tree_leaves(t1.state.params)):
        np.testing.assert_allclose(np.asarray(p8), np.asarray(p1),
                                   rtol=2e-4, atol=2e-5)


def test_remainder_batch_smaller_than_mesh():
    """Even a batch smaller than the dp axis (some shards all-pad) trains."""
    conf = _mlp_conf()
    x, y = _toy_data(n=6)  # 6 < dp=8
    net = MultiLayerNetwork(conf, seed=3).init()
    trainer = DataParallelTrainer(net, make_mesh({"dp": 8}), mode="sync")
    before = jax.tree_util.tree_leaves(trainer.state.params)[0].copy()
    s = trainer.fit([(x, y)])
    after = jax.tree_util.tree_leaves(trainer.state.params)[0]
    assert np.isfinite(s)
    assert not np.allclose(np.asarray(before), np.asarray(after))


def test_remainder_batch_averaging_mode():
    """Masked averaging round: remainder batches update and stay finite."""
    conf = _mlp_conf()
    x, y = _toy_data(n=30)
    net = MultiLayerNetwork(conf, seed=5).init()
    trainer = DataParallelTrainer(net, make_mesh({"dp": 8}),
                                  mode="averaging", local_steps=2)
    s0 = trainer.fit([(x, y)])
    for _ in range(10):
        s = trainer.fit([(x, y)])
    assert np.isfinite(s) and s < s0


def test_checkpoint_listener_kill_and_resume(tmp_path):
    """VERDICT r1 #7: a CheckpointListener persists params+updater+step
    every N iterations from the training loop; a fresh trainer restores
    them and continues exactly where the dead one stopped."""
    from deeplearning4j_tpu.optimize.listeners import CheckpointListener

    conf = _mlp_conf()
    x, y = _toy_data(n=32)
    ckpt_dir = str(tmp_path / "auto_ckpt")
    mesh = make_mesh({"dp": 8})

    listener = CheckpointListener(ckpt_dir, save_every_n=1,
                                  asynchronous=False)
    t1 = DataParallelTrainer(MultiLayerNetwork(conf, seed=11).init(), mesh,
                             mode="sync", listeners=[listener])
    for _ in range(5):
        t1.fit([(x, y)])
    assert listener.saves >= 5  # invoked periodically from the loop

    # "kill": new process stands in as a brand-new trainer + restore
    t2 = DataParallelTrainer(MultiLayerNetwork(conf, seed=99).init(), mesh,
                             mode="sync")
    step = t2.restore(ckpt_dir)
    assert step == int(t1.state.step)
    for a, b in zip(jax.tree_util.tree_leaves(t1.state.params),
                    jax.tree_util.tree_leaves(t2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(t1.state.updater),
                    jax.tree_util.tree_leaves(t2.state.updater)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resumed trainer keeps training from the restored state
    s = t2.fit([(x, y)])
    assert np.isfinite(s)
    assert int(t2.state.step) == step + 1


def test_state_tracker_update_spill_survives_restart(tmp_path):
    """VERDICT r1 #5: updates spill through the disk queue, so a master
    restart mid-round recovers every banked update."""
    spill = str(tmp_path / "updates")
    t1 = StateTracker(update_dir=spill)
    t1.add_worker("w0")
    t1.add_worker("w1")
    t1.add_update("w0", np.arange(4.0))
    t1.add_update("w1", np.arange(4.0) * 2)
    del t1  # master dies mid-round, aggregation not yet run

    t2 = StateTracker(update_dir=spill)  # restart over the same spill dir
    ups = t2.updates()
    assert len(ups) == 2
    np.testing.assert_array_equal(ups[0], np.arange(4.0))
    np.testing.assert_array_equal(ups[1], np.arange(4.0) * 2)
    # aggregation clears both memory and the spill
    t2.clear_updates()
    assert t2.updates() == []
    t3 = StateTracker(update_dir=spill)
    assert t3.updates() == []


def test_grad_accum_matches_plain_step():
    """grad_accum=k: one update from k microbatch fwd/bwds equals the
    plain step's gradient exactly (mean of equal-size microbatch means),
    at ~1/k the peak activation memory."""
    import numpy as np

    from deeplearning4j_tpu.models.zoo import mlp
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
    from deeplearning4j_tpu.parallel.mesh import make_mesh, shard_batch

    mesh = make_mesh({"dp": len(jax.devices())})
    conf = mlp(12, [16], 3)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(64, 12), jnp.float32)
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 64)])
    x, y = shard_batch(mesh, (x, y), "dp")
    key = jax.random.PRNGKey(0)

    t1 = DataParallelTrainer(MultiLayerNetwork(conf, seed=0).init(), mesh)
    t4 = DataParallelTrainer(MultiLayerNetwork(conf, seed=0).init(), mesh,
                             grad_accum=4)
    s1, sc1 = t1._step(t1.state, x, y, key)
    s4, sc4 = t4._step(t4.state, x, y, key)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    assert abs(float(sc1) - float(sc4)) < 1e-4


def test_grad_accum_rejects_batchnorm_and_masked():
    import pytest

    from deeplearning4j_tpu.models.zoo import vgg_cifar10
    from deeplearning4j_tpu.parallel.data_parallel import make_dp_train_step
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": len(jax.devices())})
    conf = vgg_cifar10(width=8)  # BatchNorm-heavy
    with pytest.raises(ValueError, match="grad_accum"):
        make_dp_train_step(conf, mesh, grad_accum=2)

    from deeplearning4j_tpu.models.zoo import mlp
    with pytest.raises(ValueError, match="grad_accum"):
        make_dp_train_step(mlp(4, [8], 2), mesh, masked=True, grad_accum=2)


def test_grad_accum_guards():
    """Indivisible per-shard batch raises clearly at trace time;
    mode='averaging' rejects grad_accum."""
    import numpy as np
    import pytest

    from deeplearning4j_tpu.models.zoo import mlp
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
    from deeplearning4j_tpu.parallel.mesh import make_mesh, shard_batch

    mesh = make_mesh({"dp": len(jax.devices())})
    conf = mlp(4, [8], 2)
    with pytest.raises(ValueError, match="mode='sync'"):
        DataParallelTrainer(MultiLayerNetwork(conf, seed=0).init(), mesh,
                            mode="averaging", grad_accum=2)
    t = DataParallelTrainer(MultiLayerNetwork(conf, seed=0).init(), mesh,
                            grad_accum=3)
    rng = np.random.RandomState(0)
    n = len(jax.devices()) * 4  # per-shard 4, not divisible by 3
    x = jnp.asarray(rng.rand(n, 4), jnp.float32)
    y = jnp.asarray(np.eye(2, dtype=np.float32)[rng.randint(0, 2, n)])
    x, y = shard_batch(mesh, (x, y), "dp")
    with pytest.raises(ValueError, match="not divisible by grad_accum"):
        t._step(t.state, x, y, jax.random.PRNGKey(0))


def test_zero1_matches_plain_dp_and_shards_updater_state():
    """ZeRO-1 step (GSPMD-annotated optimizer-state sharding): the param
    trajectory matches the shard_map dp step, and the updater state is
    genuinely dp-sharded on device."""
    import numpy as np

    from deeplearning4j_tpu.models.zoo import mlp
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.data_parallel import (
        DataParallelTrainer, init_train_state, make_zero1_train_step,
        zero1_shard_state)
    from deeplearning4j_tpu.parallel.mesh import make_mesh, shard_batch

    mesh = make_mesh({"dp": len(jax.devices())})
    conf = mlp(16, [32], 4)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(64, 16), jnp.float32)
    y = jnp.asarray(np.eye(4, dtype=np.float32)[rng.randint(0, 4, 64)])
    xs, ys = shard_batch(mesh, (x, y), "dp")
    key = jax.random.PRNGKey(0)

    ref = DataParallelTrainer(MultiLayerNetwork(conf, seed=0).init(), mesh)
    z_step = make_zero1_train_step(conf, mesh)
    z_state = zero1_shard_state(
        init_train_state(MultiLayerNetwork(conf, seed=0).init()), mesh)

    for _ in range(3):
        ref.state, ref_score = ref._step(ref.state, xs, ys, key)
        z_state, z_score = z_step(z_state, xs, ys, key)
    assert abs(float(ref_score) - float(z_score)) < 1e-4
    for a, b in zip(jax.tree_util.tree_leaves(ref.state.params),
                    jax.tree_util.tree_leaves(z_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-6)

    # the optimizer state is actually sharded: a [16, 32] leaf's first
    # dim splits over the 8-device dp axis
    leaf = jax.tree_util.tree_leaves(z_state.updater.adagrad_hist)[0]
    spec = leaf.sharding.spec
    assert "dp" in str(spec), spec

    # BatchNorm nets are rejected (they need per-batch shard_map stats)
    import pytest

    from deeplearning4j_tpu.models.zoo import vgg_cifar10

    with pytest.raises(ValueError, match="zero1"):
        make_zero1_train_step(vgg_cifar10(width=8), mesh)


def test_dp_sync_matches_single_device_plain_sgd():
    """Regression (check_vma transpose-psum): with PLAIN SGD (no adagrad
    — whose sign-like first step hides gradient scale) the dp-8 step must
    equal the single-device step. Under check_vma, differentiating
    w.r.t. the replicated params returns grads already psummed over dp;
    without the varying-params fix the update came out n_dp too large."""
    import numpy as np

    from deeplearning4j_tpu.models.zoo import mlp
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.data_parallel import (
        init_train_state, make_dp_train_step)
    from deeplearning4j_tpu.parallel.mesh import make_mesh, shard_batch

    conf = mlp(4, [8], 3)  # zoo _base: use_adagrad=False -> plain chain
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.rand(32, 4), jnp.float32)
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)])
    key = jax.random.PRNGKey(0)
    mesh8 = make_mesh({"dp": 8})
    mesh1 = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    s8 = init_train_state(MultiLayerNetwork(conf, seed=7).init())
    s1 = init_train_state(MultiLayerNetwork(conf, seed=7).init())
    s8b, _ = make_dp_train_step(conf, mesh8)(
        s8, *shard_batch(mesh8, (x, y), "dp"), key)
    s1b, _ = make_dp_train_step(conf, mesh1)(
        s1, *shard_batch(mesh1, (x, y), "dp"), key)
    for a, b in zip(jax.tree_util.tree_leaves(s8b.params),
                    jax.tree_util.tree_leaves(s1b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_trainer_fit_with_grad_accum_trains():
    """DataParallelTrainer.fit end-to-end with grad_accum: loss falls."""
    import numpy as np

    from deeplearning4j_tpu.models.zoo import mlp
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": len(jax.devices())})
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(64, 8), jnp.float32)
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 64)])
    t = DataParallelTrainer(MultiLayerNetwork(mlp(8, [16], 3), seed=0).init(),
                            mesh, grad_accum=2)
    first = None
    for _ in range(25):
        s = t.fit([(x, y)])
        first = first if first is not None else s
    assert s < first
