"""Multi-PROCESS distributed integration tests (VERDICT r1 missing #2).

The reference runs its distributed stack for real in tests —
`BaseTestDistributed.java:34-98` (in-JVM Hazelcast+Akka) and
`IRUnitDriver.java:51` (in-JVM YARN master + workers).  These tests go one
step further and cross real OS process boundaries: a ParameterServer in
this process, N `ps_worker` subprocesses training real MultiLayerNetworks
over HTTP, and a 2-process `jax.distributed` CPU cluster wired purely from
the env vars `provision.ClusterSpec` exports.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker_env():
    """Env for spawned workers: framework on path, CPU platform, no axon."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # workers don't need the 8-device mesh
    return env


def _mlp_conf_json():
    from deeplearning4j_tpu.models.zoo import mlp

    conf = mlp(4, [8], 3, lr=0.5)
    confs = tuple(c.replace(num_iterations=20, use_adagrad=False,
                            momentum=0.0) for c in conf.confs)
    return conf.replace(confs=confs).to_json()


@pytest.mark.slow
def test_multiprocess_param_server_training_converges(tmp_path):
    """3 worker processes x 4 BSP rounds against a live HTTP parameter
    server: protocol carries startup/update/fetch/progress/metrics/complete
    across process boundaries and the averaged model actually learns."""
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.datasets.fetchers import IrisDataFetcher
    from deeplearning4j_tpu.scaleout.param_server import ParameterServer

    n_workers, rounds = 3, 4
    conf_json = _mlp_conf_json()
    conf_path = tmp_path / "conf.json"
    conf_path.write_text(conf_json)

    # master holds the initial model; workers all start from it via /fetch
    net0 = MultiLayerNetwork(
        MultiLayerConfiguration.from_json(conf_json), seed=7).init()
    ps = ParameterServer(np.asarray(net0.params_flat()), n_workers,
                         iterations=rounds)
    port = ps.serve(0)
    procs = []
    try:
        for i in range(n_workers):
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "deeplearning4j_tpu.scaleout.ps_worker",
                 "--server", f"http://127.0.0.1:{port}",
                 "--worker-id", f"w{i}", "--conf", str(conf_path),
                 "--rounds", str(rounds)],
                env=_worker_env(), cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        ps.shutdown()

    assert ps.round == rounds
    assert ps.completed == {f"w{i}" for i in range(n_workers)}
    assert not ps.errors
    assert ps.metrics.get("rounds") == float(n_workers * rounds)
    assert len(ps.progress) == n_workers  # every worker reported progress

    # the averaged parameters are a trained model, not noise
    data = IrisDataFetcher().fetch(150).normalize_zero_mean_unit_variance()
    net0.set_params_flat(ps.current)
    acc = (net0.predict(data.features)
           == np.asarray(data.labels).argmax(-1)).mean()
    s0 = MultiLayerNetwork(
        MultiLayerConfiguration.from_json(conf_json), seed=7).init()
    assert net0.score(data.features, data.labels) < \
        s0.score(data.features, data.labels)
    assert acc > 0.85, f"averaged model failed to learn: acc={acc}"


@pytest.mark.slow
def test_provision_env_wiring_two_process_jax_distributed():
    """`ClusterSpec.distributed_env` + `initialize_distributed()` (env
    path) bring up a REAL 2-process jax.distributed CPU cluster — the DCN
    control plane that replaces Hazelcast/Zookeeper membership.  Each
    process asserts global visibility of both processes."""
    import socket

    from deeplearning4j_tpu.scaleout.provision import ClusterSpec, HostSpec

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    spec = ClusterSpec(hosts=[HostSpec(address="127.0.0.1"),
                              HostSpec(address="127.0.0.1")],
                       coordinator_port=port)

    child = r"""
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.scaleout.provision import initialize_distributed
assert initialize_distributed() is True, "env wiring did not initialize"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()   # 1 CPU dev per proc
assert len(jax.local_devices()) == 1
print("proc", jax.process_index(), "OK")
"""
    procs = []
    try:
        for pid in range(2):
            env = _worker_env()
            env.update(spec.distributed_env(pid))
            procs.append(subprocess.Popen(
                [sys.executable, "-c", child], env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, err.decode()[-2000:]
            assert b"OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


@pytest.mark.slow
def test_multiprocess_async_hogwild_with_straggler(tmp_path):
    """Async (HogWild) mode across real OS processes (VERDICT r2 missing
    #2): workers ship deltas the master applies immediately, fetch never
    gates, and a deliberately slow worker neither blocks the fast ones nor
    prevents convergence. Ref: HogWildWorkRouter vs
    IterativeReduceWorkRouter.java:48-59."""
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.datasets.fetchers import IrisDataFetcher
    from deeplearning4j_tpu.scaleout.param_server import ParameterServer

    n_workers, rounds = 3, 4
    conf_json = _mlp_conf_json()
    conf_path = tmp_path / "conf.json"
    conf_path.write_text(conf_json)

    net0 = MultiLayerNetwork(
        MultiLayerConfiguration.from_json(conf_json), seed=7).init()
    ps = ParameterServer(np.asarray(net0.params_flat()), n_workers,
                         iterations=rounds, mode="async")
    port = ps.serve(0)
    procs = []
    exit_order = []
    try:
        for i in range(n_workers):
            slow = "4.0" if i == n_workers - 1 else "0.0"
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "deeplearning4j_tpu.scaleout.ps_worker",
                 "--server", f"http://127.0.0.1:{port}",
                 "--worker-id", f"w{i}", "--conf", str(conf_path),
                 "--rounds", str(rounds), "--slow", slow],
                env=_worker_env(), cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        deadline = time.time() + 300
        live = dict(enumerate(procs))
        while live and time.time() < deadline:
            for i in list(live):
                if live[i].poll() is not None:
                    exit_order.append(f"w{i}")
                    del live[i]
            time.sleep(0.1)
        for i, p in enumerate(procs):
            out, err = p.communicate(timeout=10)
            assert p.returncode == 0, err.decode()[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        ps.shutdown()

    # every update was applied individually: rounds = total updates, not
    # barrier count (BSP would show ps.round == rounds)
    assert ps.round == n_workers * rounds
    assert ps.completed == {f"w{i}" for i in range(n_workers)}
    assert not ps.errors
    # the straggler (rounds x 4s of forced sleep) must exit LAST; under
    # BSP the fast workers would be round-gated behind it and exit with it
    assert exit_order[-1] == f"w{n_workers - 1}", (
        f"straggler did not finish last: {exit_order}")

    # the hogwild-merged parameters are a trained model, not noise
    data = IrisDataFetcher().fetch(150).normalize_zero_mean_unit_variance()
    net0.set_params_flat(ps.current)
    acc = (net0.predict(data.features)
           == np.asarray(data.labels).argmax(-1)).mean()
    assert acc > 0.85, f"hogwild model failed to learn: acc={acc}"
