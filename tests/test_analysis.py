"""Static-analysis layer (ISSUE 12): report model, program auditor,
repo-convention linter, fault-point conformance, and the CLI gate.

Fixture philosophy: every rule is proven twice — a seeded violation
produces exactly the expected Finding, and the equivalent clean program
or source produces none.  The repo itself is asserted clean at the end
(the same invariant the tier-1 `analyze` gate enforces).
"""

import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.analysis.program_audit import (
    SEQ_THRESHOLD,
    assert_no_materialized_scores,
    audit_attention_structure,
    audit_cache,
    audit_fn,
    audit_jaxpr,
    collect_shapes,
    iter_eqns,
)
from deeplearning4j_tpu.analysis.report import (
    REPORT_VERSION,
    Finding,
    at_or_above,
    counts,
    to_report,
)
from deeplearning4j_tpu.analysis.repo_lint import (
    lint_file,
    lint_package,
    lint_source,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return [f.rule for f in findings]


# -- report model ------------------------------------------------------------

def test_finding_rejects_unknown_severity():
    with pytest.raises(ValueError):
        Finding("r", "fatal", "x:1", "m")


def test_report_schema_and_severity_ordering():
    fs = [Finding("a", "info", "x:1", "m"),
          Finding("b", "error", "y:2", "m"),
          Finding("c", "warn", "z:3", "m")]
    rep = to_report(fs, {"files": 3})
    assert rep["version"] == REPORT_VERSION
    assert rep["counts"] == {"info": 1, "warn": 1, "error": 1}
    assert rep["checked"] == {"files": 3}
    assert [f["severity"] for f in rep["findings"]] == \
        ["error", "warn", "info"]
    assert set(rep["findings"][0]) == {"rule", "severity", "location",
                                       "message"}
    assert _rules(at_or_above(fs, "warn")) == ["b", "c"]
    assert counts([]) == {"info": 0, "warn": 0, "error": 0}


# -- program auditor: seeded violations --------------------------------------

def test_f64_op_detected_and_f32_clean():
    from jax.experimental import enable_x64
    with enable_x64():
        bad = jax.make_jaxpr(lambda x: x * 2.0)(
            jnp.zeros((3,), jnp.float64))
    assert _rules(audit_jaxpr(bad, where="f64")) == ["f64-op"]
    good = jax.make_jaxpr(lambda x: x * 2.0)(jnp.zeros((3,), jnp.float32))
    assert audit_jaxpr(good, where="f32") == []


def test_dtype_promotion_against_bf16_policy():
    fn = lambda x: x.astype(jnp.float32) * 2  # noqa: E731
    args = (jnp.zeros((2,), jnp.bfloat16),)
    fs = audit_fn(fn, args, where="promo", policy="bf16")
    assert _rules(fs) == ["dtype-promotion"]
    assert fs[0].severity == "warn"
    # the same program is legal under the f32 policy
    assert audit_fn(fn, args, where="promo", policy="f32") == []


def test_materialized_scores_in_full_attention_only():
    S, D = 600, 8
    q = jax.ShapeDtypeStruct((S, D), jnp.float32)

    def full_attention(q, k, v):
        scores = jnp.einsum("sd,td->st", q, k) / np.sqrt(D).astype("f")
        return jax.nn.softmax(scores, axis=-1) @ v

    fs = audit_fn(full_attention, (q, q, q), where="naive",
                  seq_threshold=512)
    assert "materialized-scores" in _rules(fs)
    with pytest.raises(AssertionError):
        assert_no_materialized_scores(full_attention, (q, q, q),
                                      seq_threshold=512, where="naive")
    # the flash kernels at S=1024 (fwd AND bwd) carry no [S,S]
    assert audit_attention_structure(S=1024) == []


def test_host_callback_detected():
    def cb(x):
        return jax.pure_callback(
            lambda a: np.asarray(a),
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    assert _rules(audit_fn(cb, (jnp.ones(3),), where="cb")) == \
        ["host-callback"]


def test_collective_flagged_only_when_single_chip():
    fn = jax.vmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")
    args = (jnp.ones((2, 3)),)
    assert _rules(audit_fn(fn, args, where="c", single_chip=True)) == \
        ["collective-in-single-chip"]
    assert audit_fn(fn, args, where="c", single_chip=False) == []


def test_folded_constant_detected_above_threshold():
    big = np.zeros((600, 600), np.float32)  # 1.44 MB > 1 MiB
    fs = audit_fn(lambda x: x + big, (jnp.zeros((600, 600)),),
                  where="const")
    assert _rules(fs) == ["folded-constant"]
    small = np.zeros((8, 8), np.float32)
    assert audit_fn(lambda x: x + small, (jnp.zeros((8, 8)),),
                    where="const") == []


def test_undonated_step_via_cache_records():
    class FakeCache:
        def __init__(self, recs):
            self._recs = recs

        def audit_records(self):
            return list(self._recs)

    aval = jax.ShapeDtypeStruct((2, 2), jnp.float32)
    rec = {"key": ("step", (("policy", "f32"),)), "kind": "step-cache",
           "build": lambda: (lambda p, x: p + x),
           "abstract": (aval, aval), "donate_argnums": (), "mesh": False}
    fs = audit_cache(FakeCache([rec]), expect_donation=True)
    assert _rules(fs) == ["undonated-step"]
    # donation present, or donation not expected (CPU): clean
    assert audit_cache(FakeCache([dict(rec, donate_argnums=(0,))]),
                       expect_donation=True) == []
    assert audit_cache(FakeCache([rec]), expect_donation=False) == []


def test_undonated_kv_cache_via_cache_records():
    class FakeCache:
        def __init__(self, recs):
            self._recs = recs

        def audit_records(self):
            return list(self._recs)

    aval = jax.ShapeDtypeStruct((2, 2), jnp.float32)
    rec = {"key": ("decode", "fp", ((2,), "int32"), "single"),
           "kind": "infer-cache",
           "build": lambda: (lambda p, s: p + s),
           "abstract": (aval, aval), "donate_argnums": (), "mesh": False}
    fs = audit_cache(FakeCache([rec]), expect_donation=True)
    assert _rules(fs) == ["undonated-kv-cache"]
    # prefill entries are held to the same donation contract
    fs = audit_cache(FakeCache([dict(rec, key=("prefill",) + rec["key"][1:])]),
                     expect_donation=True)
    assert _rules(fs) == ["undonated-kv-cache"]
    # donated, not a decode entry, or donation not expected (CPU): clean
    assert audit_cache(FakeCache([dict(rec, donate_argnums=(1,))]),
                       expect_donation=True) == []
    assert audit_cache(FakeCache([dict(rec, key=("output",)
                                       + rec["key"][1:])]),
                       expect_donation=True) == []
    assert audit_cache(FakeCache([rec]), expect_donation=False) == []


def test_replicated_large_leaf_rule():
    """ISSUE 17: on a mesh whose shardings carry a `model` axis, any
    param leaf >= threshold bytes left fully replicated is an error —
    it re-caps per-chip memory at the single-chip bound."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    class FakeCache:
        def __init__(self, recs):
            self._recs = recs

        def audit_records(self):
            return list(self._recs)

    devs = np.asarray(jax.devices())
    if devs.size < 8:
        pytest.skip("needs the 8 forced host devices")
    mesh = Mesh(devs[:8].reshape(2, 4), ("batch", "model"))
    rep = NamedSharding(mesh, P())
    col = NamedSharding(mesh, P(None, "model"))

    def aval(shape, s):
        return jax.ShapeDtypeStruct(shape, jnp.float32, sharding=s)

    def rec(params_aval):
        return {"key": ("output", "fp", ((8, 4), "float32"),
                        ("mesh", ("batch", "model"), (2, 4))),
                "kind": "infer-cache",
                "build": lambda: (lambda p, x: x),
                "abstract": ({"W": params_aval},
                             aval((8, 4), NamedSharding(mesh,
                                                        P("batch")))),
                "donate_argnums": (), "mesh": True,
                "shardings": ({"W": rep}, rep)}

    # large replicated param on a model-axis mesh: flagged as error
    fs = audit_cache(FakeCache([rec(aval((16, 16), rep))]),
                     replicated_leaf_threshold=256)
    assert "replicated-large-leaf" in _rules(fs)
    assert any(f.severity == "error" for f in fs
               if f.rule == "replicated-large-leaf")
    # model-sharded leaf of the same size: clean
    fs = audit_cache(FakeCache([rec(aval((16, 16), col))]),
                     replicated_leaf_threshold=256)
    assert "replicated-large-leaf" not in _rules(fs)
    # below the threshold: clean (biases stay replicated by design)
    fs = audit_cache(FakeCache([rec(aval((16, 16), rep))]),
                     replicated_leaf_threshold=1 << 20)
    assert "replicated-large-leaf" not in _rules(fs)
    # no model axis anywhere in the shardings: rule stays silent
    one_d = Mesh(devs[:8], ("batch",))
    r = rec(aval((16, 16), NamedSharding(one_d, P())))
    r["shardings"] = ({"W": NamedSharding(one_d, P())},
                     NamedSharding(one_d, P("batch")))
    fs = audit_cache(FakeCache([r]), replicated_leaf_threshold=256)
    assert "replicated-large-leaf" not in _rules(fs)


def test_decode_structure_audit_is_clean():
    """The compiled decode step must stay [S,S]-free at a cache length
    where a full-scores materialization is unambiguous (the ISSUE 14
    correctness anchor: decode attends [B,1] queries against the cache,
    so scores carry ONE sequence axis)."""
    from deeplearning4j_tpu.analysis.program_audit import (
        audit_decode_structure)

    assert audit_decode_structure() == []


def test_real_step_cache_keeps_audit_records():
    from deeplearning4j_tpu.models.zoo import lenet5
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(lenet5(), seed=0).init()
    x = np.zeros((2, 1, 28, 28), np.float32)
    net.output(x)
    recs = net.infer_cache.audit_records()
    assert recs, "compiling a serve program must leave an audit record"
    assert audit_cache(net.infer_cache) == []


def test_jaxpr_walk_descends_into_scan():
    def scanned(x):
        def body(c, _):
            big = jnp.einsum("sd,td->st", c, c)  # [S,S] inside the scan
            return c + big[:, :1] * 0, None
        out, _ = jax.lax.scan(body, x, None, length=2)
        return out

    q = jax.ShapeDtypeStruct((600, 600), jnp.float32)
    closed = jax.make_jaxpr(scanned)(q)
    assert any(getattr(e.primitive, "name", "") == "scan"
               for e in closed.jaxpr.eqns)
    shapes = collect_shapes(closed.jaxpr)
    assert (600, 600) in shapes  # found through the scan body
    assert len(list(iter_eqns(closed.jaxpr))) > len(closed.jaxpr.eqns)


# -- repo linter: synthetic sources ------------------------------------------

def test_platform_sniff_rule():
    src = "import jax\nd = jax.devices()\n"
    assert _rules(lint_source(src, "parallel/x.py")) == ["platform-sniff"]
    assert lint_source(src, "nd/platform.py") == []          # the home
    waived = "import jax\nd = jax.devices()  # lint: allow(platform-sniff)\n"
    assert lint_source(waived, "parallel/x.py") == []


def test_wall_clock_rule_scoped_to_clocked_modules():
    src = "import time\nt = time.time()\n"
    assert _rules(lint_source(src, "serving/x.py")) == ["wall-clock"]
    assert _rules(lint_source(src, "reliability/x.py")) == ["wall-clock"]
    assert lint_source(src, "clustering/x.py") == []
    dt = "import datetime\nn = datetime.datetime.now()\n"
    assert _rules(lint_source(dt, "serving/x.py")) == ["wall-clock"]
    mono = "import time\nt = time.monotonic()\n"
    assert lint_source(mono, "serving/x.py") == []


def test_unbounded_network_call_rule_both_directions():
    # direction 1: a serving/ network call with no explicit bound hangs
    # the whole control plane on one dead peer — error
    src = ("import urllib.request\n"
           "r = urllib.request.urlopen(url)\n")
    fs = lint_source(src, "serving/x.py")
    assert _rules(fs) == ["unbounded-network-call"]
    assert fs[0].severity == "error"
    sock = "import socket\ns = socket.create_connection((host, port))\n"
    assert _rules(lint_source(sock, "serving/x.py")) == [
        "unbounded-network-call"]
    # direction 2: explicit timeouts (kwarg or positional), out-of-scope
    # modules, and waived calls are all clean
    bounded = ("import urllib.request\n"
               "r = urllib.request.urlopen(url, timeout=2.0)\n")
    assert lint_source(bounded, "serving/x.py") == []
    sock_kw = ("import socket\n"
               "s = socket.create_connection((host, port), timeout=1.0)\n")
    assert lint_source(sock_kw, "serving/x.py") == []
    sock_pos = ("import socket\n"
                "s = socket.create_connection((host, port), 1.0)\n")
    assert lint_source(sock_pos, "serving/x.py") == []
    assert lint_source(src, "cli/x.py") == []  # bench/CLI clients: out of scope
    waived = ("import urllib.request\n"
              "r = urllib.request.urlopen(url)"
              "  # lint: allow(unbounded-network-call)\n")
    assert lint_source(waived, "serving/x.py") == []


def test_f64_literal_and_default_dtype_rules():
    src = "import numpy as np\na = np.zeros((3,), np.float64)\n"
    fs = lint_source(src, "nn/x.py")
    assert _rules(fs) == ["f64-literal"]
    assert lint_source(src, "clustering/x.py") == []  # host analytics
    bare = "import numpy as np\na = np.zeros((3,))\n"
    fs = lint_source(bare, "optimize/x.py")
    assert _rules(fs) == ["np-default-dtype"]
    assert fs[0].severity == "warn"
    typed = "import numpy as np\na = np.zeros((3,), dtype=np.float32)\n"
    assert lint_source(typed, "optimize/x.py") == []
    kw = 'import numpy as np\na = np.asarray(x, dtype="float64")\n'
    assert _rules(lint_source(kw, "nd/x.py")) == ["f64-literal"]


def test_hardcoded_tunable_rule_both_directions():
    # direction 1: literals at known tunable sites are flagged (warn)
    const = "DEFAULT_TARGET_ROWS = 256\n"
    fs = lint_source(const, "serving/x.py")
    assert _rules(fs) == ["hardcoded-tunable"]
    assert fs[0].severity == "warn"
    table = "_BLOCK_TABLE = {(256, 64): (128, 128, 128, 128)}\n"
    assert _rules(lint_source(table, "nd/x.py")) == ["hardcoded-tunable"]
    call = "b = MicroBatcher(net, max_delay_ms=3.0)\n"
    assert _rules(lint_source(call, "serving/x.py")) == ["hardcoded-tunable"]
    sig = "def f(net, n_slots: int = 4):\n    pass\n"
    assert _rules(lint_source(sig, "serving/x.py")) == ["hardcoded-tunable"]
    # direction 2: the registry home, None-resolved defaults, variable
    # pass-through, and waived deliberate pins are all clean
    assert lint_source(const, "optimize/tunables.py") == []
    clean = ("def f(net, n_slots=None):\n"
             "    b = MicroBatcher(net, max_delay_ms=delay)\n")
    assert lint_source(clean, "serving/x.py") == []
    waived = ("b = ContinuousBatcher(net, n_slots=1)"
              "  # lint: allow(hardcoded-tunable)\n")
    assert lint_source(waived, "cli/x.py") == []


def test_hardcoded_tunable_repo_passes_clean_after_migration():
    # the migration moved every registry-owned constant into
    # optimize/tunables.py; any remaining pin is an explicit waiver
    from deeplearning4j_tpu.analysis.repo_lint import package_root
    fs, _ = lint_package(package_root())
    assert [f for f in fs if f.rule == "hardcoded-tunable"] == []


def test_fault_point_rule_directions():
    doc = {"a.b": "doc"}
    ok = 'from x import faults\nfaults.fire("a.b")\n'
    assert lint_source(ok, "serving/x.py", documented_points=doc) == []
    bad = 'from x import faults\nfaults.fire("zz.q")\n'
    fs = lint_source(bad, "serving/x.py", documented_points=doc)
    assert _rules(fs) == ["fault-point"] and fs[0].severity == "error"
    dyn = "from x import faults\nfaults.fire(name)\n"
    fs = lint_source(dyn, "serving/x.py", documented_points=doc)
    assert _rules(fs) == ["fault-point"] and fs[0].severity == "warn"


def test_fault_point_seeded_in_temp_module(tmp_path):
    mod = tmp_path / "chaos.py"
    mod.write_text(textwrap.dedent("""
        from deeplearning4j_tpu.reliability import faults
        def hot_path():
            faults.fire("totally.undocumented")
    """))
    fs = lint_file(str(mod))
    assert _rules(fs) == ["fault-point"]
    assert "totally.undocumented" in fs[0].message


def test_fault_point_unfired_direction_on_package_walk(tmp_path):
    (tmp_path / "only.py").write_text(
        'from deeplearning4j_tpu.reliability import faults\n'
        'faults.fire("compile")\n')
    fs, n = lint_package(root=str(tmp_path))
    from deeplearning4j_tpu.reliability.faults import DOCUMENTED_POINTS
    unfired = {f.message.split("'")[1] for f in fs
               if f.rule == "fault-point"}
    assert n == 1
    assert unfired == set(DOCUMENTED_POINTS) - {"compile"}


def test_registry_matches_real_fire_sites_both_ways():
    """Machine-readable conformance: the fire("...") sites in the
    package and DOCUMENTED_POINTS are the same set (satellite 3)."""
    import ast

    from deeplearning4j_tpu.analysis.repo_lint import (_fire_sites,
                                                       package_root)
    from deeplearning4j_tpu.reliability.faults import DOCUMENTED_POINTS

    fired = set()
    root = package_root()
    for dirpath, _, files in os.walk(root):
        for fn in files:
            if not fn.endswith(".py") or "faults.py" in fn:
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as fh:
                tree = ast.parse(fh.read())
            fired |= {p for p, _ in _fire_sites(tree, path)
                      if p is not None}
    assert fired == set(DOCUMENTED_POINTS)


def test_prom_family_rule():
    def metrics_src(body):
        return ('FAMILIES = {\n'
                '    "dl4j_x_total": ("counter", ("policy",)),\n'
                '    "dl4j_y": ("gauge", ()),\n'
                '}\n'
                'def emit(p, pol):\n' + textwrap.indent(body, "    "))

    clean = metrics_src('p.counter("dl4j_x_total", "h", 1, '
                        '{"policy": pol})\np.gauge("dl4j_y", "h", 2)\n')
    assert lint_source(clean, "serving/metrics.py") == []
    # direction 1: emitted but undeclared
    fs = lint_source(metrics_src(
        'p.counter("dl4j_x_total", "h", 1, {"policy": pol})\n'
        'p.gauge("dl4j_y", "h", 2)\n'
        'p.gauge("dl4j_rogue", "h", 3)\n'), "serving/metrics.py")
    assert _rules(fs) == ["prom-family"] and "dl4j_rogue" in fs[0].message
    # direction 2: declared but never emitted
    fs = lint_source(metrics_src(
        'p.counter("dl4j_x_total", "h", 1, {"policy": pol})\n'),
        "serving/metrics.py")
    assert _rules(fs) == ["prom-family"] and "never emitted" in \
        fs[0].message
    # type mismatch
    fs = lint_source(metrics_src(
        'p.gauge("dl4j_x_total", "h", 1, {"policy": pol})\n'
        'p.gauge("dl4j_y", "h", 2)\n'), "serving/metrics.py")
    assert any("declared counter" in f.message for f in fs)
    # label drift
    fs = lint_source(metrics_src(
        'p.counter("dl4j_x_total", "h", 1, {"zone": pol})\n'
        'p.gauge("dl4j_y", "h", 2)\n'), "serving/metrics.py")
    assert any("labels" in f.message and "zone" in f.message for f in fs)
    # rule only applies to the metrics module
    assert lint_source(clean, "serving/other.py") == []


def test_real_metrics_module_passes_and_registry_is_closed():
    from deeplearning4j_tpu.serving import metrics
    path = os.path.join(REPO_ROOT, "deeplearning4j_tpu", "serving",
                        "metrics.py")
    assert lint_file(path, os.path.join(REPO_ROOT,
                                        "deeplearning4j_tpu")) == []
    for name, (mtype, labels) in metrics.FAMILIES.items():
        assert mtype in ("counter", "gauge", "histogram")
        assert mtype != "counter" or name.endswith("_total")
        assert isinstance(labels, tuple)


def test_lock_order_cycle_rule():
    cyclic = textwrap.dedent("""
        class C:
            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
            def two(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """)
    fs = lint_source(cyclic, "serving/x.py")
    assert _rules(fs) == ["lock-order-cycle"]
    assert "C._a_lock" in fs[0].message
    acyclic = cyclic.replace("def two", "def _two_disabled").split(
        "def _two_disabled")[0]
    assert lint_source(acyclic, "serving/x.py") == []


def test_unguarded_shared_write_rule():
    src = textwrap.dedent("""
        import threading
        class D:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
            def bump(self):
                self._n += 1
            def ok(self):
                with self._lock:
                    self._n = 2
            def set_n_locked(self):
                self._n = 3
    """)
    fs = lint_source(src, "serving/x.py")
    assert _rules(fs) == ["unguarded-shared-write"]
    assert "bump" in fs[0].message and fs[0].severity == "warn"


def test_repo_is_lint_clean():
    """The invariant floor, in-process: zero findings of ANY severity
    over the whole package (the CLI gate re-checks this plus the zoo
    programs in a subprocess below)."""
    findings, n_files = lint_package()
    assert n_files > 100
    assert findings == []


# -- the CLI gate ------------------------------------------------------------

def test_cli_analyze_gate_json_schema():
    """`analyze --fail-on error --format json` exits 0 on this repo and
    emits the versioned report over the package + all four zoo models'
    compiled programs (the ISSUE 12 acceptance command)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "analyze",
         "--fail-on", "error", "--format", "json"],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env,
        timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["version"] == REPORT_VERSION
    assert set(rep["counts"]) == {"info", "warn", "error"}
    assert rep["counts"]["error"] == 0
    assert rep["checked"]["files"] > 100
    assert rep["checked"]["programs"] >= 10  # 4 models x (serve+step) + attn
    assert isinstance(rep["findings"], list)
    for f in rep["findings"]:
        assert set(f) == {"rule", "severity", "location", "message"}
