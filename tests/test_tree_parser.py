"""TreeParser -> RNTN end-to-end (hermetic treebank-path parity)."""

import pytest

from deeplearning4j_tpu.models.rntn import RNTN, tree_tokens
from deeplearning4j_tpu.text.tree_parser import TreeParser


def test_strategies_preserve_token_order():
    for strategy in ("right", "left", "balanced", "chunk"):
        parser = TreeParser(strategy=strategy)
        t = parser.parse("a b c d e")
        assert tree_tokens(t) == ["a", "b", "c", "d", "e"], strategy


def test_balanced_tree_is_shallow():
    def depth(t):
        return 0 if t.is_leaf else 1 + max(depth(t.left), depth(t.right))

    toks = " ".join(f"w{i}" for i in range(16))
    assert depth(TreeParser("balanced").parse(toks)) == 4
    assert depth(TreeParser("right").parse(toks)) == 15


def test_single_token_and_empty():
    parser = TreeParser()
    t = parser.parse("solo")
    assert t.is_leaf and t.word == "solo"
    assert parser.parse("   ") is None


def test_unknown_strategy_raises():
    with pytest.raises(ValueError, match="strategy"):
        TreeParser(strategy="bogus")


def _subtree_spans(t):
    """All internal-node (start, end) token spans of a binary tree."""
    out = set()

    def rec(n, s):
        if n.is_leaf:
            return s + 1
        mid = rec(n.left, s)
        e = rec(n.right, mid)
        out.add((s, e))
        return e

    rec(t, 0)
    return out


# Small gold-bracketing set (tagger-vocabulary sentences, hand-labeled
# NP/VP/PP constituent spans) — the PARSEVAL-style labeled set on which
# the PoS-driven chunk strategy must beat the shape-only baselines.
GOLD_BRACKETS = [
    ("the quick fox jumps over the lazy dog", {(0, 3), (4, 8), (5, 8)}),
    ("a small bird sleeps in the old tree", {(0, 3), (3, 8), (4, 8)}),
    ("the teacher explained the lesson clearly", {(0, 2), (3, 5)}),
    ("some farmers sold sweet apples at the market",
     {(0, 2), (3, 5), (5, 8), (6, 8)}),
    ("the cold wind blows from the north", {(0, 3), (4, 7), (5, 7)}),
    ("she bought three red tomatoes", {(2, 5)}),
    ("the children play in the park daily", {(0, 2), (3, 6), (4, 6)}),
    ("a strange man walks quickly", {(0, 3)}),
]


def _gold_recall(strategy: str) -> float:
    parser = TreeParser(strategy=strategy)
    hit = tot = 0
    for sent, gold in GOLD_BRACKETS:
        spans = _subtree_spans(parser.parse(sent))
        hit += len(gold & spans)
        tot += len(gold)
    return hit / tot


def test_chunk_strategy_beats_shape_baselines_on_gold_brackets():
    """VERDICT r3 next-#6: the HMM-PoS chunk strategy recovers the gold
    constituents of a labeled bracketing set; shape-only trees cannot
    (reference contrast: treeparser/TreeParser.java chunks with
    CRFsuite+UIMA; the shape strategies are its no-treebank fallback)."""
    chunk = _gold_recall("chunk")
    balanced = _gold_recall("balanced")
    right = _gold_recall("right")
    assert chunk >= 0.9, chunk
    assert chunk > balanced and chunk > right
    assert balanced <= 0.5 and right <= 0.5


def test_chunk_differs_from_balanced_structure():
    parser_c = TreeParser("chunk")
    parser_b = TreeParser("balanced")
    s = "the quick fox jumps over the lazy dog"
    assert _subtree_spans(parser_c.parse(s)) != \
        _subtree_spans(parser_b.parse(s))


def test_chunk_head_rules():
    """NP head = last noun, VP head = first verb: the head child's label
    propagates to the chunk root (head-word-finding analog)."""
    labels = {"fox": 3, "jumps": 4}
    parser = TreeParser("chunk", label_fn=lambda w: labels.get(w, 0))
    t = parser.parse("the quick fox jumps")
    # top fold is right-headed: root label comes from the VP chunk (jumps)
    assert t.label == 4
    # the NP subtree root carries the noun head's label
    np = t.left
    assert tree_tokens(np) == ["the", "quick", "fox"] and np.label == 3


def test_lexicon_span_labels_compose():
    """With lexicon=, every node is labeled by its span's aggregate
    polarity (the SentiWordNet phrase-supervision role)."""
    from deeplearning4j_tpu.text.sentiment_lexicon import SentimentLexicon

    lex = SentimentLexicon()
    for strategy in ("chunk", "balanced"):
        t = TreeParser(strategy, lexicon=lex).parse(
            "the broken gate ruined a beautiful garden")
        # root = sum of all leaf scores; 'broken'(-) + 'ruined'(-) +
        # 'beautiful'(+) is net negative in the bundled lexicon
        assert t.label == 0, strategy


def test_lexicon_negation_flips_span_labels():
    """ADVICE r4 #1: SWN3.scoreTokens flips polarity on negation words —
    'the movie was not good' must NOT get positive labels."""
    from deeplearning4j_tpu.text.sentiment_lexicon import SentimentLexicon

    lex = SentimentLexicon()
    parser = TreeParser("balanced", lexicon=lex)
    pos_root = parser.parse("the movie was good")
    neg_root = parser.parse("the movie was not good")
    assert pos_root.label == 1
    assert neg_root.label == 0


def test_lexicon_neutral_spans_unsupervised_in_binary():
    """ADVICE r4 #3: sentiment-free spans (function words, neutral
    phrases) are unsupervised (-1, masked by rntn_loss) in binary mode
    instead of defaulting to the negative class; an explicit
    neutral_label overrides."""
    from deeplearning4j_tpu.text.sentiment_lexicon import SentimentLexicon

    lex = SentimentLexicon()
    t = TreeParser("balanced", lexicon=lex).parse("the of and")
    assert t.label == -1 and t.left.label == -1
    t2 = TreeParser("balanced", lexicon=lex, neutral_label=0).parse(
        "the of and")
    assert t2.label == 0


def test_rntn_masks_unsupervised_nodes():
    """label=-1 nodes contribute nothing to the loss or accuracy."""
    import numpy as np

    from deeplearning4j_tpu.models.rntn import (
        RNTN, TreeNode, plan_tree, rntn_loss, stack_plans)

    leaf_a = TreeNode(label=1, word="good")
    leaf_b = TreeNode(label=-1, word="the")
    t = TreeNode(label=1, left=leaf_b, right=leaf_a)
    model = RNTN(dim=4, n_classes=2, max_nodes=8, seed=0)
    model.fit([t], epochs=1)
    plans = stack_plans([plan_tree(t, model.vocab, 8)])
    loss = rntn_loss(model.params, plans)
    assert np.isfinite(float(loss))
    # all-unsupervised tree: loss is 0 (no labeled node)
    t0 = TreeNode(label=-1, left=TreeNode(label=-1, word="a"),
                  right=TreeNode(label=-1, word="b"))
    plans0 = stack_plans([plan_tree(t0, model.vocab, 8)])
    assert float(rntn_loss(model.params, plans0, l2=0.0)) == 0.0


def test_rntn_sentiment_on_chunked_trees():
    """RNTN sentiment evaluation on chunk vs balanced trees (VERDICT r3
    next-#6): both converge on an in-vocabulary labeled set; the chunk
    trees must do at least as well at root classification."""
    from deeplearning4j_tpu.text.sentiment_lexicon import SentimentLexicon

    lex = SentimentLexicon()
    adjs = ["beautiful", "sweet", "good", "strong",
            "broken", "cold", "rough", "strange"]
    nouns = ["garden", "tree", "house", "movie", "music", "game"]
    tpls = ["the {n} was {a}", "a {a} {n}", "the {n} seems very {a}",
            "the {n} of the {n2} was {a}"]
    sents = []
    for ti, tpl in enumerate(tpls):
        for ai, a in enumerate(adjs):
            n = nouns[(ti + ai) % len(nouns)]
            n2 = nouns[(ti + ai + 1) % len(nouns)]
            sents.append(tpl.format(a=a, n=n, n2=n2))
    accs = {}
    for strategy in ("chunk", "balanced"):
        trees = TreeParser(strategy, lexicon=lex).get_trees(sents)
        model = RNTN(dim=8, n_classes=2, max_nodes=16, lr=0.1, seed=0)
        model.fit(trees, epochs=60)
        accs[strategy] = model.accuracy(trees, root_only=True)
    assert accs["chunk"] >= 0.9
    assert accs["chunk"] >= accs["balanced"]


def test_parser_feeds_rntn_training():
    pos_words = {"good", "great", "nice", "happy"}

    def label(tok):
        return 1 if tok in pos_words else 0

    parser = TreeParser(strategy="balanced", label_fn=label)
    pos = ["good great", "nice good happy", "great happy"]
    neg = ["bad awful", "poor bad sad", "awful sad"]
    trees = parser.get_trees(pos) + parser.get_trees(neg)
    model = RNTN(dim=8, n_classes=2, max_nodes=16, lr=0.1, seed=0)
    model.fit(trees, epochs=120)
    assert model.accuracy(trees, root_only=True) >= 5 / 6
