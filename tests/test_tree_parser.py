"""TreeParser -> RNTN end-to-end (hermetic treebank-path parity)."""

import pytest

from deeplearning4j_tpu.models.rntn import RNTN, tree_tokens
from deeplearning4j_tpu.text.tree_parser import TreeParser


def test_strategies_preserve_token_order():
    for strategy in ("right", "left", "balanced"):
        parser = TreeParser(strategy=strategy)
        t = parser.parse("a b c d e")
        assert tree_tokens(t) == ["a", "b", "c", "d", "e"], strategy


def test_balanced_tree_is_shallow():
    def depth(t):
        return 0 if t.is_leaf else 1 + max(depth(t.left), depth(t.right))

    toks = " ".join(f"w{i}" for i in range(16))
    assert depth(TreeParser("balanced").parse(toks)) == 4
    assert depth(TreeParser("right").parse(toks)) == 15


def test_single_token_and_empty():
    parser = TreeParser()
    t = parser.parse("solo")
    assert t.is_leaf and t.word == "solo"
    assert parser.parse("   ") is None


def test_unknown_strategy_raises():
    with pytest.raises(ValueError, match="strategy"):
        TreeParser(strategy="bogus")


def test_parser_feeds_rntn_training():
    pos_words = {"good", "great", "nice", "happy"}

    def label(tok):
        return 1 if tok in pos_words else 0

    parser = TreeParser(strategy="balanced", label_fn=label)
    pos = ["good great", "nice good happy", "great happy"]
    neg = ["bad awful", "poor bad sad", "awful sad"]
    trees = parser.get_trees(pos) + parser.get_trees(neg)
    model = RNTN(dim=8, n_classes=2, max_nodes=16, lr=0.1, seed=0)
    model.fit(trees, epochs=120)
    assert model.accuracy(trees, root_only=True) >= 5 / 6
