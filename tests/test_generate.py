"""Compiled KV-cache decode + continuous batching (ISSUE 14).

The correctness anchor: the compiled decode path (one prefill program +
one decode-step program with donated state) must reproduce the eager
per-token loop EXACTLY — same f32 ops, same PRNG key splits, so the
greedy token trajectory is equal token-for-token on both charLSTM and
charTransformer, and temperature sampling follows the same key stream.
Around that anchor: the continuous batcher's slot table (admission into
freed slots, no barrier on the longest sequence), the /v1/generate
chunked stream, and the chaos contract — a mid-generation fault ends
ONE stream cleanly while its neighbours keep decoding.

Tier-1: CPU-only, tiny models."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import char_lstm, char_transformer, mlp
from deeplearning4j_tpu.nn import decode as decode_mod
from deeplearning4j_tpu.nn.conf import LayerType
from deeplearning4j_tpu.nn.layers import get_layer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, network_output
from deeplearning4j_tpu.reliability import faults
from deeplearning4j_tpu.serving.batcher import (ContinuousBatcher,
                                                ServerOverloaded)

VOCAB = 13


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def lstm_net():
    return MultiLayerNetwork(char_lstm(VOCAB, hidden=16, n_layers=2),
                             seed=0).init()


@pytest.fixture(scope="module")
def transformer_net():
    return MultiLayerNetwork(
        char_transformer(VOCAB, d_model=16, n_blocks=2, n_heads=2,
                         max_seq_len=32), seed=0).init()


def _compiled_tokens(net, prompt, n_new, temperature=0.0, rng_seed=0,
                     max_seq=16, bucket=8):
    """Prompt -> n_new tokens through the compiled prefill + decode
    programs (the exact sequence ContinuousBatcher runs per slot)."""
    ic = net.infer_cache
    state = ic.init_decode_state(net.conf, 1, max_seq)
    pb = np.zeros((1, bucket), np.int32)
    pb[0, :len(prompt)] = prompt
    length = jnp.asarray([len(prompt)], jnp.int32)
    keys = jnp.asarray(np.asarray(jax.random.PRNGKey(rng_seed))[None])
    temps = jnp.full((1,), float(temperature), jnp.float32)
    tok, keys, state = ic.prefill(net.conf, net.params, state,
                                  jnp.asarray(pb), length, keys, temps)
    got = [int(tok[0])]
    pos = jnp.asarray([len(prompt)], jnp.int32)
    for _ in range(n_new - 1):
        tok, keys, state = ic.decode(net.conf, net.params, state, tok,
                                     pos, keys, temps)
        got.append(int(tok[0]))
        pos = pos + 1
    return got


def _eager_lstm_tokens(net, prompt, n_new, temperature=0.0, rng_seed=0):
    """The CharLSTM.sample() loop, verbatim: step the fused cell one
    one-hot char at a time, split the key before EVERY token."""
    confs = [net.conf.conf(i) for i in range(net.conf.n_layers)]
    stack = list(zip(confs, net.params))
    lstm = get_layer(LayerType.LSTM)
    out_conf, out_p = stack[-1]
    hs = [jnp.zeros((1, c.n_out), jnp.float32) for c, _ in stack[:-1]]
    cs = [jnp.zeros((1, c.n_out), jnp.float32) for c, _ in stack[:-1]]
    eye = np.eye(VOCAB, dtype=np.float32)
    key = jax.random.PRNGKey(rng_seed)

    def step(x, hs, cs):
        inp, h2, c2 = x, [], []
        for li, (c, p) in enumerate(stack[:-1]):
            inp, cc = lstm.step(p, c, inp, hs[li], cs[li])
            h2.append(inp)
            c2.append(cc)
        probs = get_layer(out_conf.layer_type).forward(out_p, out_conf, inp)
        return jnp.log(jnp.clip(probs, 1e-9, 1.0)), h2, c2

    logp = None
    for cid in prompt:
        logp, hs, cs = step(jnp.asarray(eye[cid][None]), hs, cs)
    toks = []
    for _ in range(n_new):
        key, sub = jax.random.split(key)
        if temperature <= 0:
            t = int(jnp.argmax(logp[0]))
        else:
            t = int(jax.random.categorical(sub, logp[0] / temperature))
        toks.append(t)
        logp, hs, cs = step(jnp.asarray(eye[t][None]), hs, cs)
    return toks


def _eager_transformer_tokens(net, prompt, n_new):
    """Greedy reference by full re-forward over the growing sequence —
    no cache at all, so agreement means the cached path IS the model."""
    seq, toks = list(prompt), []
    for _ in range(n_new):
        ids = jnp.asarray([seq], jnp.int32)
        probs = network_output(net.conf, net.params, ids)
        probs = probs.reshape(len(seq), VOCAB)
        toks.append(int(jnp.argmax(
            jnp.log(jnp.clip(probs[-1], 1e-9, 1.0)))))
        seq.append(toks[-1])
    return toks


# -- the correctness anchor: compiled == eager, f32 exact ---------------------

def test_greedy_parity_char_lstm(lstm_net):
    ref = _eager_lstm_tokens(lstm_net, [1, 2, 3], 8)
    got = _compiled_tokens(lstm_net, [1, 2, 3], 8)
    assert got == ref


def test_greedy_parity_char_transformer(transformer_net):
    ref = _eager_transformer_tokens(transformer_net, [1, 2, 3], 8)
    got = _compiled_tokens(transformer_net, [1, 2, 3], 8)
    assert got == ref


def test_temperature_trajectory_parity_char_lstm(lstm_net):
    """Sampling splits the same key stream on both paths, so even the
    stochastic trajectory is equal token-for-token."""
    ref = _eager_lstm_tokens(lstm_net, [2, 5], 10, temperature=0.7,
                             rng_seed=3)
    got = _compiled_tokens(lstm_net, [2, 5], 10, temperature=0.7,
                           rng_seed=3)
    assert got == ref


def test_charlstm_generate_matches_sample():
    """The model-level satellite: CharLSTM.generate() (compiled decode)
    equals CharLSTM.sample() (eager loop) for greedy AND temperature —
    both share `_encode` and the key-split discipline."""
    from deeplearning4j_tpu.models.char_lstm import CharLSTM

    text = "the quick brown fox jumps over the lazy dog " * 4
    m = CharLSTM(hidden=16, n_layers=1, seq_len=8, iterations=2).fit(text)
    assert (m.sample("the q", n=10, temperature=0.0)
            == m.generate("the q", n=10, temperature=0.0))
    assert (m.sample("dog", n=10, temperature=0.9, rng_seed=7)
            == m.generate("dog", n=10, temperature=0.9, rng_seed=7))


# -- decode state + cache mechanics -------------------------------------------

def test_check_generative_accepts_and_rejects():
    decode_mod.check_generative(char_lstm(8, hidden=4, n_layers=1))
    decode_mod.check_generative(
        char_transformer(8, d_model=8, n_blocks=1, n_heads=2,
                         max_seq_len=8))
    with pytest.raises(ValueError):
        decode_mod.check_generative(mlp(n_in=4, hidden=[4], n_out=2))


def test_init_state_shapes_and_embedding_bound(transformer_net):
    state = decode_mod.init_state(transformer_net.conf, 3, 16)
    k = state[1]["k"]  # layer 0 is the embedding
    assert k.shape == (3, 16, 16)
    with pytest.raises(ValueError):
        # max_seq beyond the learned positional table would index junk
        decode_mod.init_state(transformer_net.conf, 1, 64)


def test_decode_programs_compile_once_and_key_by_batch(lstm_net):
    ic = lstm_net.infer_cache
    before = ic.stats.misses
    _compiled_tokens(lstm_net, [1], 4)
    _compiled_tokens(lstm_net, [2], 4)  # same shapes: pure cache hits
    after_same = ic.stats.misses
    assert after_same - before <= 2  # decode + prefill at most once
    summary = ic.programs_summary()
    assert any(p["entry"] == "decode" for p in summary)
    assert any(p["entry"] == "prefill" for p in summary)


def test_decode_donation_matches_backend(lstm_net):
    """On CPU donation is a no-op (and the audit rule is gated the same
    way); off-CPU the decode/prefill records must donate arg 1."""
    from deeplearning4j_tpu.nd.platform import default_backend

    ic = lstm_net.infer_cache
    _compiled_tokens(lstm_net, [1], 2)
    recs = [r for r in ic.audit_records()
            if r["key"][0] in ("decode", "prefill")]
    assert recs
    want = (1,) if default_backend() != "cpu" else ()
    assert all(tuple(r["donate_argnums"]) == want for r in recs)


# -- continuous batcher -------------------------------------------------------

def test_batcher_generates_and_reports(lstm_net):
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,))
    try:
        ref = _compiled_tokens(lstm_net, [1, 2, 3], 6)
        got = cb.generate([1, 2, 3], max_new_tokens=6)
        assert got == ref
        s1 = cb.submit([1, 2], max_new_tokens=4)
        s2 = cb.submit([3, 4], max_new_tokens=4)
        assert len(list(s1.tokens(timeout=30.0))) == 4
        assert len(list(s2.tokens(timeout=30.0))) == 4
        assert s1.ttft_s is not None and s1.ttft_s >= 0.0
        st = cb.stats()
        assert st["streams"] == {"admitted": 3, "completed": 3,
                                 "failed": 0}
        assert st["tokens"] == 14
        assert st["slots"] == {"width": 2, "active": 0, "free": 2}
        h = st["ttft_hist_s"]
        assert sum(h["counts"]) + h["inf"] == h["count"] == 3
    finally:
        cb.stop()


def test_batcher_interleaves_admissions_without_barrier(lstm_net):
    """Continuous batching: a long stream keeps decoding while short
    ones are admitted into freed slots — more streams than slots
    complete even though the long one started first."""
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=32,
                           prompt_buckets=(8,))
    try:
        long = cb.submit([1], max_new_tokens=24)
        shorts = [cb.submit([2, 3], max_new_tokens=2) for _ in range(3)]
        for s in shorts:
            assert len(list(s.tokens(timeout=30.0))) == 2
        assert len(list(long.tokens(timeout=30.0))) == 24
        assert cb.stats()["streams"]["completed"] == 4
    finally:
        cb.stop()


def test_submit_validation_and_overload(lstm_net):
    cb = ContinuousBatcher(lstm_net, n_slots=1, max_seq=8,
                           prompt_buckets=(4,), max_pending=1,
                           auto_start=False)
    with pytest.raises(ValueError):
        cb.submit([], max_new_tokens=1)
    with pytest.raises(ValueError):
        cb.submit(list(range(8)), max_new_tokens=1)  # prompt fills cache
    cb.submit([1], max_new_tokens=2)
    with pytest.raises(ServerOverloaded):
        cb.submit([1], max_new_tokens=2)  # pending bound
    cb.stop()


def test_max_new_tokens_clamped_to_cache(lstm_net):
    cb = ContinuousBatcher(lstm_net, n_slots=1, max_seq=8,
                           prompt_buckets=(4,))
    try:
        toks = cb.generate([1, 2, 3], max_new_tokens=100)
        assert len(toks) == 8 - 3  # prompt + output fit max_seq exactly
    finally:
        cb.stop()


def test_sequential_mode_still_serves_everything(lstm_net):
    """continuous=False (the bench's barrier arm) trades throughput,
    not correctness: every queued stream still completes."""
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), continuous=False)
    try:
        streams = [cb.submit([1, 2], max_new_tokens=3) for _ in range(5)]
        for s in streams:
            assert len(list(s.tokens(timeout=30.0))) == 3
        assert cb.stats()["streams"]["completed"] == 5
    finally:
        cb.stop()


# -- chaos: fault isolation per stream ----------------------------------------

def test_decode_fault_fails_one_stream_others_decode_on(lstm_net):
    """Arm decode.step for slot A's traversal mid-generation: A's
    stream ends with the injected error, B runs to completion — the
    fault never crosses the slot boundary."""
    # armed BEFORE the first submission: the very first decode-table
    # traversal is slot 0 — the slot stream `a` (submitted first) is
    # admitted into — so the doomed stream is deterministic
    faults.arm("decode.step", "raise", nth=1)
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=32,
                           prompt_buckets=(8,))
    try:
        a = cb.submit([1, 2], max_new_tokens=20)
        b = cb.submit([3, 4], max_new_tokens=20)
        b_toks = list(b.tokens(timeout=30.0))
        assert len(b_toks) == 20
        with pytest.raises(faults.FaultInjected):
            list(a.tokens(timeout=30.0))
        st = cb.stats()
        assert st["streams"]["failed"] == 1
        assert st["streams"]["completed"] == 1
        # the failed slot was released: a new stream admits and finishes
        faults.disarm()
        assert len(cb.generate([5], max_new_tokens=3)) == 3
    finally:
        cb.stop()


def test_admit_fault_fails_only_the_admitted_stream(lstm_net):
    faults.arm("generate.admit", "raise", nth=1)
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,))
    try:
        doomed = cb.submit([1], max_new_tokens=4)
        with pytest.raises(faults.FaultInjected):
            list(doomed.tokens(timeout=30.0))
        # the registry disarms after firing once: next stream is fine
        assert len(cb.generate([2], max_new_tokens=4)) == 4
        assert cb.stats()["streams"]["failed"] == 1
    finally:
        cb.stop()


# -- HTTP: /v1/generate chunked streaming -------------------------------------

def _post_generate(url, body, timeout=30):
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, [json.loads(line) for line in
                             resp.read().decode().strip().splitlines()]


def test_http_generate_streams_tokens(lstm_net):
    lstm_net.warmup_generate(slots=2, max_seq=16, prompt_buckets=(8,))
    server = lstm_net.serve(generate=True, gen_slots=2, gen_max_seq=16,
                            gen_prompt_buckets=(8,))
    try:
        code, lines = _post_generate(server.url,
                                     {"prompt": [1, 2, 3],
                                      "max_new_tokens": 5})
        assert code == 200
        toks = [ln["token"] for ln in lines if "token" in ln]
        assert toks == _compiled_tokens(lstm_net, [1, 2, 3], 5)
        assert lines[-1]["done"] is True
        assert lines[-1]["tokens"] == 5
        assert lines[-1]["ttft_ms"] >= 0.0
        # stats carry the generation block
        st = json.loads(_httpget(server.url + "/v1/stats"))
        assert st["generation"]["streams"]["completed"] == 1
    finally:
        server.stop()


def _httpget(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def test_http_generate_error_envelope(lstm_net):
    server = lstm_net.serve(generate=True, gen_slots=1, gen_max_seq=8,
                            gen_prompt_buckets=(4,))
    try:
        # bad prompt: 400 before any stream starts
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_generate(server.url, {"prompt": "not a list"})
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_generate(server.url, {"prompt": list(range(8))})
        assert ei.value.code == 400  # prompt fills the whole cache
    finally:
        server.stop()


def test_http_generate_404_without_generator(lstm_net):
    server = lstm_net.serve()  # generate not enabled
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_generate(server.url, {"prompt": [1]})
        assert ei.value.code == 404
    finally:
        server.stop()


def test_http_admit_fault_is_clean_5xx_other_stream_unharmed(lstm_net):
    """The ISSUE 14 chaos contract over HTTP: stream B is decoding, a
    fault fires on stream A's admission — A gets a clean 5xx, B streams
    every one of its tokens."""
    lstm_net.warmup_generate(slots=2, max_seq=32, prompt_buckets=(8,))
    server = lstm_net.serve(generate=True, gen_slots=2, gen_max_seq=32,
                            gen_prompt_buckets=(8,))
    try:
        results = {}

        def run_b():
            results["b"] = _post_generate(
                server.url, {"prompt": [3, 4], "max_new_tokens": 24})

        tb = threading.Thread(target=run_b)
        tb.start()
        # wait until B was ADMITTED (not merely queued) before arming,
        # so the fault can only hit A's admission
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            gen = json.loads(
                _httpget(server.url + "/v1/stats"))["generation"]
            if gen["streams"]["admitted"] >= 1:
                break
            time.sleep(0.005)
        faults.arm("generate.admit", "raise", nth=1)
        code_a = None
        try:
            _post_generate(server.url, {"prompt": [1], "max_new_tokens": 4})
        except urllib.error.HTTPError as e:
            code_a = e.code
        assert code_a == 500
        tb.join(timeout=30.0)
        code_b, lines_b = results["b"]
        assert code_b == 200
        assert sum(1 for ln in lines_b if "token" in ln) == 24
        assert lines_b[-1]["done"] is True
    finally:
        server.stop()


# -- ISSUE 16: paged KV cache -------------------------------------------------

def _drain(streams, timeout=60.0):
    return [list(s.tokens(timeout=timeout)) for s in streams]


@pytest.mark.parametrize("which", ["lstm", "transformer"])
def test_paged_decode_token_parity(which, lstm_net, transformer_net):
    """page_size > 0 reroutes decode through the shared physical page
    pool — and changes NOTHING about the tokens, on both generative
    architectures."""
    net = lstm_net if which == "lstm" else transformer_net
    refs = [_compiled_tokens(net, p, 6, temperature=t, rng_seed=i)
            for i, (p, t) in enumerate(
                [([1, 2, 3], 0.0), ([4, 5], 0.8)])]
    cb = ContinuousBatcher(net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), page_size=4)
    try:
        streams = [cb.submit(p, max_new_tokens=6, temperature=t,
                             rng_seed=i)
                   for i, (p, t) in enumerate(
                       [([1, 2, 3], 0.0), ([4, 5], 0.8)])]
        assert _drain(streams) == refs
        pages = cb.stats()["kv_pages"]
        assert pages["page_size"] == 4
        assert pages["live"] == 0  # all streams done -> all pages freed
        assert pages["free"] == pages["total"]
    finally:
        cb.stop()


def test_paged_pool_frees_and_reuses_pages(lstm_net):
    """Live pages track live tokens while streams run, return to the
    free list on completion, and the same pool serves stream after
    stream without leaking."""
    cb = ContinuousBatcher(lstm_net, n_slots=1, max_seq=16,
                           prompt_buckets=(8,), page_size=4)
    try:
        for _ in range(3):
            assert len(cb.generate([1, 2, 3], max_new_tokens=4)) == 4
            pages = cb.stats()["kv_pages"]
            assert pages["live"] == 0 and pages["free"] == pages["total"]
    finally:
        cb.stop()


def test_paged_overcommit_admits_more_slots_than_pages_queue_drains(lstm_net):
    """An overcommitted pool (fewer pages than slots x max pages) still
    completes EVERY stream: admissions that cannot get pages wait in
    the queue and drain as finished streams free theirs — queue-or-503,
    never a crash."""
    # 4 slots x 4 pages/slot = 16 pages fully provisioned; give it 6:
    # at most one full-length stream plus one short one hold pages at
    # once, the rest queue
    cb = ContinuousBatcher(lstm_net, n_slots=4, max_seq=16,
                           prompt_buckets=(8,), page_size=4, n_pages=6)
    try:
        streams = [cb.submit([i + 1], max_new_tokens=10)
                   for i in range(6)]
        toks = _drain(streams, timeout=120.0)
        assert all(len(t) == 10 for t in toks)
        st = cb.stats()
        assert st["streams"]["completed"] == 6
        assert st["streams"]["failed"] == 0
        assert st["kv_pages"]["total"] == 6
        assert st["kv_pages"]["live"] == 0
    finally:
        cb.stop()


def test_page_pool_too_small_for_one_stream_rejected_at_construction(lstm_net):
    with pytest.raises(ValueError):
        ContinuousBatcher(lstm_net, n_slots=1, max_seq=16,
                          prompt_buckets=(8,), page_size=4, n_pages=3,
                          auto_start=False)


def test_page_alloc_fault_fails_one_stream_neighbour_decodes_on(lstm_net):
    """Armed decode.page_alloc mid-decode: the slot that needed a fresh
    page ends its stream with the injected error; the neighbour keeps
    its pages and finishes; the failed slot's pages return to the
    pool."""
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(4,), page_size=4)
    try:
        # both admissions allocate once each (traversals 1-2); doomed
        # decodes past its first page boundary first (prompt 3 tokens +
        # 2 tokens -> pos 4 crosses into page 2 at traversal 3)
        faults.arm("decode.page_alloc", "raise", nth=3)
        doomed = cb.submit([1, 2, 3], max_new_tokens=10)
        ok = cb.submit([4], max_new_tokens=2)
        assert len(list(ok.tokens(timeout=30.0))) == 2
        with pytest.raises(faults.FaultInjected):
            list(doomed.tokens(timeout=30.0))
        faults.disarm()
        st = cb.stats()
        assert st["streams"]["failed"] == 1
        assert st["kv_pages"]["live"] == 0  # doomed's pages were freed
        # the pool still serves new streams
        assert len(cb.generate([5], max_new_tokens=3)) == 3
    finally:
        cb.stop()


# -- ISSUE 16: prefix caching -------------------------------------------------

def test_prefix_cache_exact_hit_token_identical_and_counted(lstm_net):
    """A repeated prompt skips prefill (hit counter moves) and the
    trajectory is token-identical to the cold stream — including under
    temperature, where the stream's OWN key must drive sampling."""
    ref_greedy = _compiled_tokens(lstm_net, [1, 2, 3], 6)
    ref_temp = _compiled_tokens(lstm_net, [1, 2, 3], 6, temperature=0.7,
                                rng_seed=9)
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), prefix_cache=True)
    try:
        assert cb.generate([1, 2, 3], max_new_tokens=6) == ref_greedy
        assert cb.generate([1, 2, 3], max_new_tokens=6) == ref_greedy
        s = cb.submit([1, 2, 3], max_new_tokens=6, temperature=0.7,
                      rng_seed=9)
        assert list(s.tokens(timeout=30.0)) == ref_temp
        pc = cb.stats()["prefix_cache"]
        assert pc["misses"] == 1 and pc["hits"] == 2
    finally:
        cb.stop()


def test_prefix_cache_longest_match_parity(lstm_net):
    """prefix_match='longest': a longer prompt sharing a cached prefix
    enters decode at the match point and feeds the unmatched suffix
    through the table — tokens identical to a cold prefill of the full
    prompt."""
    ref = _compiled_tokens(lstm_net, [1, 2, 3, 4, 5, 6], 5, rng_seed=1)
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), prefix_cache=True,
                           prefix_match="longest")
    try:
        cb.generate([1, 2, 3, 4], max_new_tokens=3)  # seeds the cache
        s = cb.submit([1, 2, 3, 4, 5, 6], max_new_tokens=5, rng_seed=1)
        assert list(s.tokens(timeout=30.0)) == ref
        pc = cb.stats()["prefix_cache"]
        assert pc["hits"] == 1 and pc["misses"] == 1
    finally:
        cb.stop()


def test_prefix_lookup_fault_falls_back_to_cold_prefill(lstm_net):
    """Armed generate.prefix_lookup (a corrupt/missing cache entry):
    the probe degrades to a counted miss and a cold prefill — the
    stream completes with the exact cold tokens, and a neighbour stream
    admitted in the same window is untouched."""
    ref = _compiled_tokens(lstm_net, [1, 2, 3], 6)
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), prefix_cache=True)
    try:
        assert cb.generate([1, 2, 3], max_new_tokens=6) == ref
        faults.arm("generate.prefix_lookup", "raise", nth=1)
        a = cb.submit([1, 2, 3], max_new_tokens=6)      # probe blows up
        b = cb.submit([1, 2, 3], max_new_tokens=6)       # neighbour
        assert list(a.tokens(timeout=30.0)) == ref
        assert list(b.tokens(timeout=30.0)) == ref
        st = cb.stats()
        assert st["streams"]["failed"] == 0
        pc = st["prefix_cache"]
        assert pc["misses"] == 2  # the cold start + the faulted probe
        assert pc["hits"] == 1    # the neighbour probes clean and hits
    finally:
        cb.stop()


def test_prefix_cache_persists_through_disk_store(tmp_path, monkeypatch):
    """With a persistent program store attached, prefill state written
    by one batcher is a HIT for a fresh batcher over a fresh net — the
    restart story, same as compiled programs."""
    def fresh_net():
        net = MultiLayerNetwork(char_lstm(VOCAB, hidden=16, n_layers=2),
                                seed=0).init()
        net.set_compile_cache(str(tmp_path))
        return net

    ref = _compiled_tokens(fresh_net(), [1, 2, 3], 5)
    cb1 = ContinuousBatcher(fresh_net(), n_slots=1, max_seq=16,
                            prompt_buckets=(8,), prefix_cache=True)
    try:
        assert cb1.generate([1, 2, 3], max_new_tokens=5) == ref
    finally:
        cb1.stop()
    cb2 = ContinuousBatcher(fresh_net(), n_slots=1, max_seq=16,
                            prompt_buckets=(8,), prefix_cache=True)
    try:
        assert cb2.generate([1, 2, 3], max_new_tokens=5) == ref
        pc = cb2.stats()["prefix_cache"]
        assert pc["hits"] == 1 and pc["misses"] == 0
    finally:
        cb2.stop()


# -- ISSUE 16: speculative decoding -------------------------------------------

def _draft_net(agrees_with=None):
    """A draft model: `agrees_with` clones the target (full acceptance)
    while None builds a smaller, differently-seeded one (frequent
    rejection — the adversarial case for the rollback math)."""
    if agrees_with is not None:
        return MultiLayerNetwork(agrees_with.conf, seed=0).init()
    return MultiLayerNetwork(char_lstm(VOCAB, hidden=8, n_layers=1),
                             seed=1).init()


@pytest.mark.parametrize("which", ["lstm", "transformer"])
def test_spec_decode_greedy_parity_disagreeing_draft(which, lstm_net,
                                                     transformer_net):
    """Greedy speculative decode with a draft that frequently disagrees
    must still emit EXACTLY the sequential trajectory — acceptance cuts
    the chain where conditioning would diverge, and recurrent carries
    roll back to the accepted prefix."""
    net = lstm_net if which == "lstm" else transformer_net
    refs = [_compiled_tokens(net, p, 8, rng_seed=i)
            for i, p in enumerate([[1, 2, 3, 4], [5, 6, 7]])]
    cb = ContinuousBatcher(net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), draft_net=_draft_net(),
                           spec_k=3)
    try:
        streams = [cb.submit(p, max_new_tokens=8, rng_seed=i)
                   for i, p in enumerate([[1, 2, 3, 4], [5, 6, 7]])]
        assert _drain(streams) == refs
        spec = cb.stats()["speculative"]
        assert spec["rounds"] >= 1
        assert spec["accepted_hist"]["count"] >= 2
    finally:
        cb.stop()


def test_spec_decode_temperature_parity(lstm_net):
    """Sampled trajectories match sequential decode too: the verify
    step burns the exact key splits K sequential steps would, so
    acceptance never changes WHAT is sampled, only how many device
    calls produce it."""
    refs = [_compiled_tokens(lstm_net, [1, 2], 8, temperature=0.9,
                             rng_seed=s) for s in (3, 4)]
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), draft_net=_draft_net(),
                           spec_k=3)
    try:
        streams = [cb.submit([1, 2], max_new_tokens=8, temperature=0.9,
                             rng_seed=s) for s in (3, 4)]
        assert _drain(streams) == refs
    finally:
        cb.stop()


def test_spec_decode_agreeing_draft_accepts_chunks(lstm_net):
    """A draft that clones the target accepts whole chunks: more than
    one token per verify step, fewer device rounds than tokens."""
    ref = _compiled_tokens(lstm_net, [1, 2, 3], 9)
    cb = ContinuousBatcher(lstm_net, n_slots=1, max_seq=16,
                           prompt_buckets=(8,),
                           draft_net=_draft_net(agrees_with=lstm_net),
                           spec_k=3)
    try:
        assert cb.generate([1, 2, 3], max_new_tokens=9) == ref
        spec = cb.stats()["speculative"]
        assert spec["accepted_per_step"] > 1.0
    finally:
        cb.stop()


def test_spec_decode_rejects_invalid_configs(lstm_net, transformer_net):
    with pytest.raises(ValueError):  # spec_k < 2
        ContinuousBatcher(lstm_net, n_slots=1, max_seq=16,
                          prompt_buckets=(8,), draft_net=_draft_net(),
                          spec_k=1, auto_start=False)
    with pytest.raises(ValueError):  # attention draft (needs rollback-
        ContinuousBatcher(lstm_net, n_slots=1, max_seq=16,  # free state)
                          prompt_buckets=(8,),
                          draft_net=transformer_net, spec_k=2,
                          auto_start=False)


def test_all_flags_combined_token_parity(lstm_net):
    """Paged pool + prefix cache + speculation at once — the full
    accelerator stack is still token-identical to the plain path."""
    ref = _compiled_tokens(lstm_net, [1, 2, 3], 8)
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), page_size=4,
                           prefix_cache=True, draft_net=_draft_net(),
                           spec_k=3)
    try:
        assert cb.generate([1, 2, 3], max_new_tokens=8) == ref
        assert cb.generate([1, 2, 3], max_new_tokens=8) == ref  # hit
        st = cb.stats()
        assert st["prefix_cache"]["hits"] == 1
        assert st["kv_pages"]["live"] == 0
    finally:
        cb.stop()


# -- ISSUE 16: satellite guards ----------------------------------------------

def test_positional_bound_enforced_at_admission_config(transformer_net):
    """The silent positional-table overrun: a transformer's learned
    positional table has max_seq_len rows, and a decode table longer
    than it would gather out of bounds SILENTLY (clamped) — so the
    batcher refuses the geometry outright."""
    assert decode_mod.positional_bound(transformer_net.conf) == 32
    cb = ContinuousBatcher(transformer_net, n_slots=1, max_seq=32,
                           prompt_buckets=(8,), auto_start=False)  # ok
    cb.stop()
    with pytest.raises(ValueError):
        ContinuousBatcher(transformer_net, n_slots=1, max_seq=40,
                          prompt_buckets=(8,), auto_start=False)


def test_positional_bound_unbounded_for_recurrent(lstm_net):
    """One-hot recurrent stacks have no positional table — no bound."""
    assert decode_mod.positional_bound(lstm_net.conf) == 0
    cb = ContinuousBatcher(lstm_net, n_slots=1, max_seq=512,
                           prompt_buckets=(8,), auto_start=False)
    cb.stop()


def test_flags_off_compiles_only_the_pre_issue16_programs():
    """Flags off = byte-for-byte the ISSUE 14 serving path: the same
    two program kinds ('decode', 'prefill'), the same cache keys, no
    paged/verify/logp programs anywhere near the cache."""
    net = MultiLayerNetwork(char_lstm(VOCAB, hidden=16, n_layers=2),
                            seed=0).init()
    cb = ContinuousBatcher(net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,))
    try:
        assert len(cb.generate([1, 2], max_new_tokens=4)) == 4
        kinds = {r["entry"] for r in net.infer_cache.programs_summary()}
        assert kinds == {"decode", "prefill"}
        st = cb.stats()
        assert "kv_pages" not in st
        assert "prefix_cache" not in st
        assert "speculative" not in st
    finally:
        cb.stop()


def test_warmup_generate_covers_every_flag_combination():
    """warmup_generate with the accelerator flags precompiles exactly
    what a flag-enabled batcher runs: zero fresh compiles during
    traffic, for paged + prefix + speculative at once."""
    net = MultiLayerNetwork(char_lstm(VOCAB, hidden=16, n_layers=2),
                            seed=0).init()
    draft = _draft_net()
    net.warmup_generate(slots=2, max_seq=16, prompt_buckets=(8,),
                        page_size=4, prefix_cache=True, draft_net=draft,
                        spec_k=3)
    before = (net.infer_cache.stats.misses
              + draft.infer_cache.stats.misses)
    cb = ContinuousBatcher(net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), page_size=4,
                           prefix_cache=True, draft_net=draft, spec_k=3)
    try:
        assert len(cb.generate([1, 2, 3], max_new_tokens=6)) == 6
        after = (net.infer_cache.stats.misses
                 + draft.infer_cache.stats.misses)
        assert after == before  # fresh_compiles == 0 under traffic
        assert cb.stats()["fresh_compiles"] == after
    finally:
        cb.stop()
