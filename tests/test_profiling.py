"""Profiling/metrics aux subsystem."""

import time

import jax.numpy as jnp

from deeplearning4j_tpu.utils.profiling import (MetricsRegistry, StepTimer,
                                                ThroughputMeter,
                                                TimingIterationListener,
                                                Tracer)


def test_step_timer_summary():
    t = StepTimer("job")
    for _ in range(5):
        with t:
            time.sleep(0.002)
    s = t.summary()
    assert s["count"] == 5
    assert s["mean_ms"] >= 1.0
    assert s["min_ms"] <= s["p50_ms"] <= s["max_ms"]


def test_throughput_meter_blocks_on_device():
    m = ThroughputMeter()
    x = jnp.ones((64, 64))
    with m.measure(128) as meas:
        y = meas.block(x @ x)  # created inside the block, synced before stop
    assert m.samples == 128
    assert m.samples_per_sec > 0
    assert y.shape == (64, 64)


def test_metrics_registry_report():
    r = MetricsRegistry()
    r.increment("jobs")
    r.increment("jobs", 2)
    r.gauge("loss", 0.5)
    rep = r.report()
    assert rep["jobs"] == 3.0
    assert rep["loss"] == 0.5


def test_timing_listener_accumulates():
    r = MetricsRegistry()
    lst = TimingIterationListener(r)
    for i in range(3):
        lst.iteration_done(None, i, 1.0 - 0.1 * i)
    rep = r.report()
    assert rep["iterations"] == 3.0
    assert rep["last_score"] == 0.8


def test_tracer_annotation_usable():
    with Tracer.annotate("test-region"):
        _ = jnp.sum(jnp.arange(10))
