"""HTTP iterative-reduce parameter server (#22 protocol parity)."""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.scaleout.param_server import (ParameterServer,
                                                      ParameterServerWorker)


@pytest.fixture
def server():
    ps = ParameterServer(np.zeros(4, np.float32), n_workers=2, iterations=3)
    port = ps.serve(0)
    yield ps, f"http://127.0.0.1:{port}"
    ps.shutdown()


def test_startup_assigns_splits(server):
    ps, url = server
    a = ParameterServerWorker(url, "wA").startup()
    b = ParameterServerWorker(url, "wB").startup()
    assert {a["split_index"], b["split_index"]} == {0, 1}
    assert a["total_splits"] == 2 and a["iterations"] == 3


def test_update_round_gates_until_all_workers(server):
    ps, url = server
    wa = ParameterServerWorker(url, "wA")
    wb = ParameterServerWorker(url, "wB")
    wa.startup(), wb.startup()
    r = wa.update(np.full(4, 2.0, np.float32))
    assert r["round"] == 0          # still waiting on wB
    assert wa.waiting()["banked"] == 1
    r = wb.update(np.full(4, 4.0, np.float32))
    assert r["round"] == 1          # published: average of 2 and 4
    got = wa.fetch(1)
    np.testing.assert_allclose(got, np.full(4, 3.0))


def test_fetch_polls_until_published(server):
    ps, url = server
    wa = ParameterServerWorker(url, "wA", poll_interval_s=0.01)
    wb = ParameterServerWorker(url, "wB")
    wa.startup(), wb.startup()

    def late_update():
        import time

        time.sleep(0.1)
        wa.update(np.ones(4, np.float32))
        wb.update(np.ones(4, np.float32))

    t = threading.Thread(target=late_update)
    t.start()
    got = wa.fetch(1)  # blocks (409-poll) until the round lands
    t.join()
    np.testing.assert_allclose(got, 1.0)


def test_multi_round_bsp_training_loop(server):
    """Two workers do 3 BSP rounds of 'local training' (+1 / +3)."""
    ps, url = server

    def work(name, delta, out):
        w = ParameterServerWorker(url, name, poll_interval_s=0.01)
        w.startup()
        vec = np.zeros(4, np.float32)
        for r in range(1, 4):
            w.update(vec + delta)
            vec = w.fetch(r)
            w.progress(round=r)
        w.metrics_report({"steps": 3})
        w.complete()
        out[name] = vec

    out = {}
    ts = [threading.Thread(target=work, args=(n, d, out))
          for n, d in (("wA", 1.0), ("wB", 3.0))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # each round: average(vec+1, vec+3) = vec+2, three rounds -> 6
    np.testing.assert_allclose(out["wA"], 6.0)
    np.testing.assert_allclose(out["wB"], 6.0)
    assert ps.metrics["steps"] == 6.0
    assert ps.completed == {"wA", "wB"}


def test_error_reporting(server):
    ps, url = server
    w = ParameterServerWorker(url, "wX")
    w.startup()
    w.error("container lost")
    assert ps.errors["wX"] == "container lost"


@pytest.fixture
def async_server():
    ps = ParameterServer(np.zeros(4, np.float32), n_workers=2,
                         iterations=3, mode="async")
    port = ps.serve(0)
    yield ps, f"http://127.0.0.1:{port}"
    ps.shutdown()


def test_async_update_applies_immediately(async_server):
    """HogWild mode: a delta lands without waiting for other workers and
    fetch never 409s (ref HogWildWorkRouter vs IterativeReduceWorkRouter)."""
    ps, url = async_server
    w0 = ParameterServerWorker(url, "w0")
    assert w0.startup()["mode"] == "async"
    w0.update_delta(np.ones(4, np.float32))
    assert ps.round == 1  # applied with only 1 of 2 workers reporting
    np.testing.assert_array_equal(w0.fetch(0), np.ones(4))
    np.testing.assert_array_equal(w0.fetch(999), np.ones(4))  # never gated
    # a second delta accumulates
    w0.update_delta(2 * np.ones(4, np.float32))
    np.testing.assert_array_equal(w0.fetch(0), 3 * np.ones(4))


def test_async_straggler_does_not_gate(async_server):
    """A fast worker completes many updates while a slow one sleeps."""
    import time

    ps, url = async_server
    fast = ParameterServerWorker(url, "fast")
    slow = ParameterServerWorker(url, "slow")
    fast.startup(), slow.startup()

    def slow_loop():
        time.sleep(0.5)
        slow.update_delta(np.ones(4, np.float32))

    t = threading.Thread(target=slow_loop)
    t.start()
    for _ in range(10):  # all land before the slow worker's single one
        fast.update_delta(0.1 * np.ones(4, np.float32))
    assert ps.round >= 10  # never blocked on the straggler
    t.join()
    np.testing.assert_allclose(np.asarray(ps.fetch(0)),
                               2.0 * np.ones(4), rtol=1e-6)


def test_bsp_rejects_delta_updates(server):
    ps, url = server
    w = ParameterServerWorker(url, "w0")
    w.startup()
    import urllib.error
    with pytest.raises(urllib.error.HTTPError):
        w.update_delta(np.ones(4, np.float32))


def test_async_rejects_full_vector_updates(async_server):
    """ADVICE r3: a stray update(kind='vec') in async mode would silently
    last-writer-win over every concurrently applied delta; it must be
    rejected, mirroring the bsp delta rejection."""
    ps, url = async_server
    w = ParameterServerWorker(url, "w0")
    w.startup()
    w.update_delta(np.ones(4, np.float32))
    import urllib.error
    with pytest.raises(urllib.error.HTTPError):
        w.update(5 * np.ones(4, np.float32))  # default kind="vec"
    # fleet progress untouched by the rejected write
    np.testing.assert_array_equal(np.asarray(ps.fetch(0)), np.ones(4))
