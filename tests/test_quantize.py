"""Low-precision serving path (ISSUE 8 tentpole): the per-conf
serve-precision policy (optimize/quantize.py) — bf16 cast-on-load,
weight-only per-channel int8 with calibrated clip — threads through the
AOT infer cache as a cache-key dimension, persists the quantized-weight
artifact in the disk store, keeps the f32 path bitwise-identical, and
holds the declared accuracy budgets on all four zoo models.

Tier-1: CPU-only, tmpdir-backed; the two-subprocess disk-coexistence
check is the cross-process acceptance test.
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import PRECISION_ERROR_BUDGETS, mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import quantize
from deeplearning4j_tpu.optimize.persist import PersistentProgramStore

N_IN, N_OUT = 6, 3


def _net(seed=0):
    return MultiLayerNetwork(mlp(n_in=N_IN, hidden=[8], n_out=N_OUT,
                                 lr=0.05), seed=seed).init()


def _x(rows, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(rows, N_IN).astype(np.float32))


# -- quantization mechanics --------------------------------------------------

def test_validate_policy_rejects_unknown():
    for p in quantize.POLICIES:
        assert quantize.validate_policy(p) == p
    with pytest.raises(ValueError):
        quantize.validate_policy("fp8")


def test_quantize_leaf_per_channel_axes():
    """2-D dense weights quantize per output column (last axis); 4-D
    conv kernels per output channel (axis 0, OIHW)."""
    rng = np.random.RandomState(0)
    w2 = rng.randn(5, 7).astype(np.float32)
    q2 = quantize._quantize_leaf(w2, clip=1.0)
    assert q2["q"].dtype == np.int8 and q2["q"].shape == (5, 7)
    assert q2["scale"].shape == (1, 7)

    w4 = rng.randn(4, 3, 2, 2).astype(np.float32)
    q4 = quantize._quantize_leaf(w4, clip=1.0)
    assert q4["scale"].shape == (4, 1, 1, 1)
    # full-range clip keeps every column's max at the int8 rail
    deq = q2["q"].astype(np.float32) * q2["scale"]
    assert float(np.max(np.abs(deq - w2))) <= float(
        np.max(q2["scale"])) * 0.51


def test_quantize_params_only_touches_matrix_weights():
    net = _net()
    qparams = quantize.quantize_params_int8(net.params)
    for layer, qlayer in zip(net.params, qparams):
        for name, leaf in layer.items():
            if quantize._quantizable(name, leaf):
                assert set(qlayer[name]) == {"q", "scale"}
            else:
                np.testing.assert_array_equal(np.asarray(leaf),
                                              np.asarray(qlayer[name]))


def test_pack_unpack_roundtrip_exact():
    net = _net()
    qparams = quantize.quantize_params_int8(net.params, clip=0.995)
    report = {"clip": 0.995, "mse": 1.5e-6, "calibration_rows": 32}
    blob = quantize.pack_quantized(qparams, report)
    q2, r2 = quantize.unpack_quantized(blob)
    assert r2 == report
    for la, lb in zip(qparams, q2):
        assert set(la) == set(lb)
        for name in la:
            if isinstance(la[name], dict):
                np.testing.assert_array_equal(la[name]["q"], lb[name]["q"])
                np.testing.assert_array_equal(la[name]["scale"],
                                              lb[name]["scale"])
            else:
                np.testing.assert_array_equal(np.asarray(la[name]),
                                              np.asarray(lb[name]))


def test_calibration_picks_clip_minimizing_mse():
    net = _net()
    x = _x(32, seed=2)
    qparams, rep = quantize.calibrate_int8(net.conf, net.params, x)
    assert rep["clip"] in quantize.CLIP_GRID
    assert rep["calibration_rows"] == 32
    assert rep["rel_mse"] < 1e-2


# -- cache-key coexistence + f32 bitwise identity ----------------------------

def test_f32_key_is_the_pre_policy_4_tuple():
    """The f32 policy adds NO key suffix — pre-PR disk artifacts stay
    addressable and the f32 path is untouched."""
    net = _net()
    net.output(_x(4))
    keys = list(net.infer_cache._programs)
    assert keys and all(len(k) == 4 for k in keys)


def test_policies_coexist_and_flip_back_is_pure_hits():
    net = _net()
    x = _x(4, seed=1)
    ref = np.asarray(net.output(x))

    net.set_serve_precision("bf16")
    net.output(x)
    net.set_serve_precision("int8")
    net.output(x)

    summary = net.infer_cache.programs_summary()
    assert {row["policy"] for row in summary} == {"f32", "bf16", "int8"}
    assert {row["bucket"] for row in summary} == {4}

    misses = net.infer_cache.stats.misses
    net.set_serve_precision("f32")
    again = np.asarray(net.output(x))
    assert net.infer_cache.stats.misses == misses  # pure in-memory hit
    np.testing.assert_array_equal(ref, again)      # bitwise, not approx


def test_bf16_and_int8_outputs_stay_close_to_f32():
    net = _net()
    x = _x(16, seed=3)
    ref = np.asarray(net.output(x))
    for policy in ("bf16", "int8"):
        net.set_serve_precision(policy)
        out = np.asarray(net.output(x))
        assert out.dtype == np.float32  # programs cast back at the edge
        rel = float(np.mean((out - ref) ** 2) / max(
            float(np.mean(ref ** 2)), 1e-12))
        assert rel < 1e-3, (policy, rel)


def test_mesh_and_policy_compose_in_the_key():
    net = _net()
    x = _x(4, seed=4)
    net.set_serve_mesh()
    net.set_serve_precision("bf16")
    net.output(x)
    keys = list(net.infer_cache._programs)
    assert any(k[3][0] == "mesh" and k[4] == ("policy", "bf16")
               for k in keys), keys
    assert any(row["sharding"].startswith("mesh:") and row["policy"] == "bf16"
               for row in net.infer_cache.programs_summary())


# -- precision report --------------------------------------------------------

def test_set_serve_precision_reports_held_out_accuracy_delta():
    net = _net()
    rep = net.set_serve_precision("int8")
    assert rep["policy"] == "int8"
    assert rep["calibration"]["clip"] in quantize.CLIP_GRID
    delta = rep["accuracy_delta"]
    assert delta["policy"] == "int8" and delta["rows"] > 0
    assert 0.0 <= delta["top1_delta"] <= 1.0
    assert net.serve_precision_report is rep


def test_int8_without_artifact_or_calibration_data_defaults():
    """`set_serve_precision("int8")` with no calibration batch derives
    one from the conf — no user data required for the zero-config path."""
    net = _net()
    rep = net.set_serve_precision("int8", measure=False)
    assert "accuracy_delta" not in rep
    assert net.serve_precision == "int8"


# -- quantized-artifact persistence ------------------------------------------

def test_int8_artifact_round_trips_through_disk_store(tmp_path):
    net = _net()
    net.set_compile_cache(str(tmp_path))
    rep1 = net.set_serve_precision("int8", measure=False)
    store = net.infer_cache.persist
    assert store.writes >= 1  # the artifact write

    # a restarted process: same conf + params digest → artifact loads,
    # calibration is NOT recomputed (identical report, zero new writes)
    net2 = _net()
    net2.set_compile_cache(str(tmp_path))
    writes_before = net2.infer_cache.persist.writes
    rep2 = net2.set_serve_precision("int8", measure=False)
    assert rep2["calibration"] == rep1["calibration"]
    assert net2.infer_cache.persist.writes == writes_before


def test_store_bytes_checksum_and_kind_guard(tmp_path):
    store = PersistentProgramStore(str(tmp_path))
    key = ("quantized-weights", "int8", "fp", "digest")
    assert store.store_bytes(key, b"artifact-bytes")
    assert store.load_bytes(key) == b"artifact-bytes"

    # a program load of a bytes entry is a kind mismatch, not a crash
    assert store.load(key) is None
    assert store.corrupt_evicted == 1
    assert not os.path.exists(store.path_for(key))


def test_corrupt_artifact_is_evicted_and_recalibrated(tmp_path):
    net = _net()
    net.set_compile_cache(str(tmp_path))
    net.set_serve_precision("int8", measure=False)
    store = net.infer_cache.persist
    art_key = quantize.quantize_artifact_key(
        net.infer_cache._fingerprint(net.conf),
        quantize.params_digest(net.params))
    with open(store.path_for(art_key), "r+b") as f:
        f.seek(40)
        f.write(b"\xff\xff\xff\xff")

    net2 = _net()
    net2.set_compile_cache(str(tmp_path))
    rep = net2.set_serve_precision("int8", measure=False)
    assert rep["calibration"]["clip"] in quantize.CLIP_GRID
    assert net2.infer_cache.persist.corrupt_evicted == 1
    assert net2.infer_cache.persist.writes >= 1  # rewritten clean


# -- error budgets (acceptance criterion) ------------------------------------

def test_error_budgets_hold_on_all_four_zoo_models():
    """bf16 and int8 stay within the budgets declared in
    `zoo.PRECISION_ERROR_BUDGETS` for LeNet, char-LSTM, charTransformer,
    and the deep autoencoder (small variants; CPU-deterministic)."""
    report = quantize.error_budget_report(small=True)
    assert set(report) == set(PRECISION_ERROR_BUDGETS)
    for model, by_policy in report.items():
        for policy, row in by_policy.items():
            assert row["within_budget"], (model, policy, row)


# -- cross-process disk coexistence (acceptance criterion) -------------------

_CHILD = """\
import json, os
import numpy as np
import jax.numpy as jnp
from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

policy = os.environ["CHILD_POLICY"]
conf = mlp(n_in=6, hidden=[8], n_out=3, lr=0.05)
net = MultiLayerNetwork(conf, seed=0).init()
if policy != "f32":
    net.set_serve_precision(policy, measure=False)
rng = np.random.RandomState(1)
x = jnp.asarray(rng.randn(4, 6).astype(np.float32))
out = net.output(x)
st = net.infer_cache.stats.as_dict()
store = net.infer_cache.persist
print(json.dumps({"stats": st, "writes": store.writes,
                  "evictions": store.evictions,
                  "vanished": store.vanished,
                  "out0": float(np.asarray(out)[0, 0])}))
"""


def test_two_subprocess_f32_and_int8_share_one_disk_store(tmp_path):
    """Warm f32 then int8 into ONE `DL4J_COMPILE_CACHE` dir from two
    real OS processes, then reload both policies from two more: pure
    disk hits (`fresh_compiles == 0`), nothing evicted, nothing
    vanished — the policies coexist on disk, they don't thrash."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DL4J_COMPILE_CACHE=str(tmp_path))

    def run(policy):
        r = subprocess.run([sys.executable, "-c", _CHILD],
                           env=dict(env, CHILD_POLICY=policy),
                           capture_output=True, text=True, timeout=240)
        assert r.returncode == 0, r.stderr[-2000:]
        return json.loads(r.stdout.strip().splitlines()[-1])

    warm_f32 = run("f32")
    warm_int8 = run("int8")
    assert warm_f32["stats"]["misses"] == 1   # each warms its own program
    assert warm_int8["stats"]["misses"] == 1

    hit_f32 = run("f32")
    hit_int8 = run("int8")
    for hit in (hit_f32, hit_int8):
        assert hit["stats"]["misses"] == 0        # fresh_compiles == 0
        assert hit["stats"]["disk_hits"] == 1
        assert hit["evictions"] == 0
        assert hit["vanished"] == 0
    # int8 reload also reused the persisted artifact: no new writes
    assert hit_int8["writes"] == 0
    # f32 outputs are process-invariant (bitwise regression anchor)
    assert hit_f32["out0"] == warm_f32["out0"]
