"""Persistent on-disk compile cache (optimize/persist.py): round-trip
disk hits skip the compile, platform fingerprint mismatches recompile,
corrupt entries are evicted + recompiled, the LRU cap bounds the
directory, concurrent writers never clobber (atomic rename), and the
warmup / --compile-cache wiring fills the store for later processes.

Tier-1: CPU-only, tmpdir-backed."""

import json
import os
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.infer_cache import InferCache
from deeplearning4j_tpu.optimize.persist import (PersistentProgramStore,
                                                 platform_fingerprint,
                                                 platform_info)
from deeplearning4j_tpu.optimize.step_cache import TrainStepCache

KEY = jax.random.PRNGKey(7)


def _conf():
    return mlp(n_in=4, hidden=[6], n_out=3, lr=0.05)


def _data(n=8, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, 4).astype(np.float32))
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)])
    return x, y


def _exported(scale: float):
    """A tiny synthetic Exported for store-level tests."""
    from jax import export as jax_export

    return jax_export.export(jax.jit(lambda a: a * scale))(
        jax.ShapeDtypeStruct((4,), jnp.float32))


# -- round trip: disk hit skips the compile ---------------------------------

def test_train_step_round_trip_disk_hit_skips_compile(tmp_path):
    """Second cache (a restarted process) on the same store: zero fresh
    compiles, one disk hit, bitwise-identical step results."""
    conf, (x, y) = _conf(), _data()
    params0 = MultiLayerNetwork(conf, seed=0).init().params

    c1 = TrainStepCache(persist=PersistentProgramStore(str(tmp_path)))
    p1, s1 = c1.finetune(conf, params0, x, y, KEY)
    assert c1.stats.misses == 1 and c1.stats.disk_hits == 0
    assert c1.persist.writes == 1

    c2 = TrainStepCache(persist=PersistentProgramStore(str(tmp_path)))
    p2, s2 = c2.finetune(conf, params0, x, y, KEY)
    assert c2.stats.misses == 0 and c2.stats.disk_hits == 1
    assert c2.stats.deserialize_seconds > 0.0

    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    for la, lb in zip(p1, p2):
        for name in la:
            np.testing.assert_array_equal(np.asarray(la[name]),
                                          np.asarray(lb[name]),
                                          err_msg=name)


def test_infer_round_trip_disk_hit(tmp_path):
    conf, (x, _) = _conf(), _data()
    params = MultiLayerNetwork(conf, seed=0).init().params

    c1 = InferCache(persist=PersistentProgramStore(str(tmp_path)))
    out1 = c1.output(conf, params, x)
    assert c1.stats.misses == 1

    c2 = InferCache(persist=PersistentProgramStore(str(tmp_path)))
    out2 = c2.output(conf, params, x)
    assert c2.stats.misses == 0 and c2.stats.disk_hits == 1
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


# -- platform fingerprint ----------------------------------------------------

def test_platform_fingerprint_mismatch_is_a_plain_miss(tmp_path):
    """A foreign platform's artifact is invisible (filename hash) and,
    even if renamed into place, rejected by the header check — either
    way the caller just recompiles."""
    store = PersistentProgramStore(str(tmp_path))
    key = ("k", "fp", "(4,)f32")
    assert store.store(key, _exported(2.0))

    foreign = PersistentProgramStore(str(tmp_path))
    foreign._fingerprint = "0" * 16  # pretend we're another platform
    assert foreign.load(key) is None  # hashed filename differs: no file

    # defense in depth: force the header check by moving the real entry
    # to where the foreign fingerprint looks
    os.rename(store.path_for(key), foreign.path_for(key))
    assert foreign.load(key) is None
    assert foreign.corrupt_evicted == 1  # rejected entry was evicted
    assert not os.path.exists(foreign.path_for(key))


def test_fingerprint_covers_platform_facts():
    info = platform_info()
    assert {"format", "backend", "device_kind", "n_devices",
            "jax", "jaxlib"} <= set(info)
    other = dict(info, backend="definitely-not-a-backend")
    assert platform_fingerprint(info) != platform_fingerprint(other)


# -- corruption --------------------------------------------------------------

def test_corrupt_entry_evicted_and_recompiled(tmp_path):
    conf, (x, y) = _conf(), _data()
    params0 = MultiLayerNetwork(conf, seed=0).init().params
    TrainStepCache(persist=PersistentProgramStore(str(tmp_path))).finetune(
        conf, params0, x, y, KEY)

    (entry,) = [p for p in tmp_path.iterdir() if p.suffix == ".jxp"]
    entry.write_bytes(entry.read_bytes()[:64])  # truncate: bad checksum

    c2 = TrainStepCache(persist=PersistentProgramStore(str(tmp_path)))
    c2.finetune(conf, params0, x, y, KEY)
    assert c2.stats.misses == 1 and c2.stats.disk_hits == 0
    assert c2.persist.corrupt_evicted == 1
    assert c2.persist.writes == 1  # fresh compile rewrote the entry

    c3 = TrainStepCache(persist=PersistentProgramStore(str(tmp_path)))
    c3.finetune(conf, params0, x, y, KEY)
    assert c3.stats.disk_hits == 1  # the rewrite is loadable again


def test_garbage_file_is_evicted_on_load(tmp_path):
    store = PersistentProgramStore(str(tmp_path))
    key = ("garbage",)
    with open(store.path_for(key), "wb") as f:
        f.write(b"not a cache entry at all")
    assert store.load(key) is None
    assert store.corrupt_evicted == 1
    assert not os.path.exists(store.path_for(key))


# -- LRU size cap ------------------------------------------------------------

def test_lru_cap_evicts_least_recently_used(tmp_path):
    store = PersistentProgramStore(str(tmp_path), max_bytes=1 << 30)
    keys = [("lru", i) for i in range(3)]
    for i, k in enumerate(keys):
        assert store.store(k, _exported(float(i + 1)))
        # deterministic mtime ordering without sleeping
        os.utime(store.path_for(k), (1000.0 + i, 1000.0 + i))
    sizes = {k: os.path.getsize(store.path_for(k)) for k in keys}

    # cap to two entries: storing a fourth must drop the oldest (lru/0)
    store.max_bytes = sum(sizes.values()) - 1
    assert store.store(("lru", 3), _exported(9.0))
    assert not os.path.exists(store.path_for(keys[0]))
    assert store.evictions >= 1
    assert store.total_bytes() <= store.max_bytes
    assert store.load(("lru", 3)) is not None  # newest survives


def test_load_refreshes_recency(tmp_path):
    store = PersistentProgramStore(str(tmp_path))
    a, b = ("a",), ("b",)
    store.store(a, _exported(1.0))
    store.store(b, _exported(2.0))
    os.utime(store.path_for(a), (1000.0, 1000.0))
    os.utime(store.path_for(b), (2000.0, 2000.0))
    assert store.load(a) is not None  # touch: a becomes the hot entry
    store.max_bytes = os.path.getsize(store.path_for(a))
    store._enforce_cap()
    assert os.path.exists(store.path_for(a))
    assert not os.path.exists(store.path_for(b))


# -- concurrency -------------------------------------------------------------

def test_concurrent_writers_do_not_clobber(tmp_path):
    """Eight threads racing store() on the same key: atomic rename means
    the survivor is always a complete, loadable entry (and no tmp files
    leak)."""
    store = PersistentProgramStore(str(tmp_path))
    key = ("race",)
    exported = _exported(3.0)
    errs = []

    def write():
        try:
            assert store.store(key, exported)
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errs.append(e)

    threads = [threading.Thread(target=write) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert store.load(key) is not None
    assert len(store) == 1
    assert not [p for p in tmp_path.iterdir() if p.name.endswith(".tmp")]


# -- warmup + acceptance criterion -------------------------------------------

def test_warmup_then_fresh_process_zero_fresh_compiles(tmp_path):
    """The acceptance criterion, in-process: warmup fills the store, and
    a second network (fresh memory caches = a restarted process) executes
    its first train step AND first output() with disk_hits > 0 and
    misses == 0."""
    conf, (x, y) = _conf(), _data()

    net1 = MultiLayerNetwork(conf, seed=0).init()
    net1.set_compile_cache(str(tmp_path))
    summary = net1.warmup([8], entries=("output",), train=True)
    assert summary["step_cache"]["misses"] == 1
    assert summary["infer_cache"]["misses"] == 1
    assert summary["step_cache"]["steps"] == 0  # compile only, no execute

    net2 = MultiLayerNetwork(conf, seed=1).init()
    net2.set_compile_cache(str(tmp_path))
    net2.fit(x, y)
    net2.output(x)
    assert net2.step_cache.stats.misses == 0
    assert net2.step_cache.stats.disk_hits == 1
    assert net2.infer_cache.stats.misses == 0
    assert net2.infer_cache.stats.disk_hits == 1


def test_env_var_attaches_store(tmp_path, monkeypatch):
    monkeypatch.setenv("DL4J_COMPILE_CACHE", str(tmp_path))
    net = MultiLayerNetwork(_conf())
    assert net.step_cache.persist is not None
    assert net.step_cache.persist.directory == str(tmp_path)
    assert net.infer_cache.persist is net.step_cache.persist


@pytest.mark.slow
def test_second_os_process_zero_fresh_compiles(tmp_path):
    """The acceptance criterion across REAL processes: a child process
    pointed at the warmed --compile-cache dir reports misses == 0 and
    disk_hits > 0 for its first fit + output."""
    conf, (x, y) = _conf(), _data()
    net = MultiLayerNetwork(conf, seed=0).init()
    net.set_compile_cache(str(tmp_path))
    net.warmup([8], entries=("output",), train=True)

    child = (
        "import json, numpy as np, jax.numpy as jnp\n"
        "from deeplearning4j_tpu.models.zoo import mlp\n"
        "from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork\n"
        "conf = mlp(n_in=4, hidden=[6], n_out=3, lr=0.05)\n"
        "net = MultiLayerNetwork(conf, seed=5).init()\n"
        "rng = np.random.RandomState(0)\n"
        "x = jnp.asarray(rng.randn(8, 4).astype(np.float32))\n"
        "y = jnp.asarray(np.eye(3, dtype=np.float32)"
        "[rng.randint(0, 3, 8)])\n"
        "net.fit(x, y)\n"
        "net.output(x)\n"
        "print(json.dumps({'step': net.step_cache.stats.as_dict(),"
        " 'infer': net.infer_cache.stats.as_dict()}))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DL4J_COMPILE_CACHE=str(tmp_path))
    r = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    stats = json.loads(r.stdout.strip().splitlines()[-1])
    assert stats["step"]["misses"] == 0 and stats["step"]["disk_hits"] == 1
    assert stats["infer"]["misses"] == 0 and stats["infer"]["disk_hits"] == 1


# -- CLI wiring --------------------------------------------------------------

def _write_csv(path, n=24):
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for _ in range(n):
            row = list(rng.randn(4)) + [rng.randint(0, 3)]
            f.write(",".join(str(v) for v in row) + "\n")


def test_cli_train_and_warmup_emit_disk_cache_stats(tmp_path, capsys):
    from deeplearning4j_tpu.cli.driver import main as cli_main

    csv_path = tmp_path / "data.csv"
    _write_csv(str(csv_path))
    ckpt, cache = str(tmp_path / "ckpt"), str(tmp_path / "cache")

    rc = cli_main(["train", "--input", str(csv_path), "--zoo", "mlp:hidden=6",
                   "--output", ckpt, "--compile-cache", cache,
                   "--properties", "epochs=1"])
    assert rc == 0
    info = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert info["disk_cache"]["entries"] >= 1
    assert info["disk_cache"]["dir"] == os.path.abspath(cache)

    # warmup subcommand on the saved checkpoint, fresh cache dir
    cache2 = str(tmp_path / "cache2")
    rc = cli_main(["warmup", "--model", ckpt, "--compile-cache", cache2,
                   "--shapes", "8", "--entries", "output", "--train"])
    assert rc == 0
    info = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert info["disk_cache"]["entries"] >= 2
    assert info["step_cache"]["misses"] == 1

    # predict against the warmed dir: first output() is a disk hit
    rc = cli_main(["predict", "--input", str(csv_path), "--model", ckpt,
                   "--batch", "8", "--output", str(tmp_path / "preds.csv"),
                   "--compile-cache", cache2])
    assert rc == 0
    info = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert info["infer_cache_misses"] == 0
    assert info["disk_cache"]["disk_hits"] >= 1


# -- multi-process sibling writers (ISSUE 7 satellite) ------------------------

def test_sibling_eviction_is_a_plain_miss_not_a_crash(tmp_path):
    """Replica B evicts an entry replica A knows about: A's next load is
    a counted miss, never an exception, and A recompiles cleanly."""
    a = PersistentProgramStore(str(tmp_path))
    b = PersistentProgramStore(str(tmp_path))
    key = ("infer-cache", "fp", "output", "sig")
    assert a.store(key, _exported(2.0))
    assert b.load(key) is not None      # both see the shared entry
    b.evict(key)                        # sibling eviction
    assert a.load(key) is None          # plain miss
    assert a.io_errors == 0 and a.corrupt_evicted == 0
    assert a.store(key, _exported(2.0))  # rewrite works
    assert a.load(key) is not None


def test_enforce_cap_tolerates_vanished_entries(tmp_path):
    """LRU eviction over a stale snapshot (a sibling removed files
    between listdir and remove): vanished files count as `vanished`,
    not `evictions`, and the sweep completes."""
    store = PersistentProgramStore(str(tmp_path), max_bytes=1)
    keys = [("k", i) for i in range(3)]
    exported = _exported(1.5)
    for k in keys:
        store.store(k, exported)
    real = store._entries()
    assert len(real) >= 1
    ghost = os.path.join(store.directory, "0" * 40 + ".jxp")
    stale = [(ghost, 123, 0.0)] + real  # oldest entry no longer exists
    store.evictions = store.vanished = 0
    orig_entries = store._entries
    store._entries = lambda: stale
    try:
        store._enforce_cap()
    finally:
        store._entries = orig_entries
    assert store.vanished == 1
    assert store.evictions >= 1  # the real entries still got swept


def test_corrupt_entry_vanishing_under_eviction_counts_vanished(tmp_path):
    """A corrupt entry that a sibling removes between our read and our
    evict counts `vanished`, not `corrupt_evicted`."""
    store = PersistentProgramStore(str(tmp_path))
    key = ("k", "corrupt-race")
    store.store(key, _exported(3.0))
    path = store.path_for(key)
    with open(path, "r+b") as f:
        f.seek(30)
        f.write(b"\xff\xff\xff\xff")
    os_remove = os.remove

    def racing_remove(p):
        # the sibling wins the race just before our eviction
        os_remove(p)
        raise FileNotFoundError(p)

    import deeplearning4j_tpu.optimize.persist as persist_mod
    orig = persist_mod.os.remove
    persist_mod.os.remove = racing_remove
    try:
        assert store.load(key) is None
    finally:
        persist_mod.os.remove = orig
    assert store.vanished == 1
    assert store.corrupt_evicted == 0


def test_sibling_writers_same_key_converge(tmp_path):
    """Two stores hammering the same key concurrently: no torn reads, no
    exceptions, both converge on a loadable entry."""
    a = PersistentProgramStore(str(tmp_path))
    b = PersistentProgramStore(str(tmp_path))
    key = ("k", "shared")
    exported = _exported(4.0)
    errors = []

    def worker(store):
        try:
            for _ in range(10):
                store.store(key, exported)
                store.load(key)
        except BaseException as e:  # noqa: BLE001 — the assertion
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,))
               for s in (a, b, a, b)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert a.load(key) is not None and b.load(key) is not None
