"""Text infrastructure tests (SURVEY §4: tokenizer/vocab/vectorizer parity)."""

import numpy as np

from deeplearning4j_tpu.text.inverted_index import InvertedIndex
from deeplearning4j_tpu.text.sentence_iterator import (
    CollectionSentenceIterator, LabelAwareSentenceIterator,
    LineSentenceIterator)
from deeplearning4j_tpu.text.stopwords import is_stop_word
from deeplearning4j_tpu.text.tokenization import (DefaultTokenizerFactory,
                                                  NGramTokenizerFactory,
                                                  input_homogenization)
from deeplearning4j_tpu.text.vectorizers import (BagOfWordsVectorizer,
                                                 TfidfVectorizer)
from deeplearning4j_tpu.text.vocab import Huffman, VocabCache
from deeplearning4j_tpu.text.windows import moving_window_matrix, windows


def test_tokenizer_and_homogenization():
    tf = DefaultTokenizerFactory(preprocessor=input_homogenization)
    toks = tf.tokenize("Hello, World!  FOO-bar")
    assert toks == ["hello", "world", "foobar"]
    t = tf.create("a b c")
    assert t.count_tokens() == 3
    assert t.next_token() == "a" and t.has_more_tokens()


def test_ngram_tokenizer():
    tf = NGramTokenizerFactory(1, 2)
    toks = tf.tokenize("a b c")
    assert "a b" in toks and "b c" in toks and "a" in toks


def test_sentence_iterators(tmp_path):
    it = CollectionSentenceIterator(["one", "two"])
    assert list(it) == ["one", "two"]
    assert list(it) == ["one", "two"]  # reset works

    p = tmp_path / "s.txt"
    p.write_text("l1\nl2\nl3\n")
    li = LineSentenceIterator(str(p))
    assert list(li) == ["l1", "l2", "l3"]

    la = LabelAwareSentenceIterator(["x", "y"], ["A", "B"])
    la.reset()
    la.next_sentence()
    assert la.current_label() == "A"


def test_stopwords():
    assert is_stop_word("the") and not is_stop_word("tensor")


def test_vocab_and_huffman():
    cache = VocabCache(min_word_frequency=1).fit(
        [["a", "a", "a", "b", "b", "c"]])
    assert cache.num_words() == 3
    assert cache.word_at_index(0) == "a"  # most frequent first
    Huffman.build(cache)
    # Kraft equality for a complete prefix code: sum 2^-len == 1
    total = sum(2.0 ** -len(cache.word_for(w).codes) for w in cache.words())
    assert abs(total - 1.0) < 1e-9
    # most frequent word gets the shortest code
    lens = [len(cache.word_for(w).codes) for w in cache.words()]
    assert lens[0] == min(lens)
    codes, points, mask = Huffman.padded_arrays(cache)
    assert codes.shape == points.shape == mask.shape
    assert mask.sum() == sum(lens)
    # inner-node ids are valid syn1 rows
    assert points.max() < cache.num_words() - 1


def test_inverted_index():
    idx = InvertedIndex()
    idx.add_doc(["the", "cat"], label="pet")
    idx.add_doc(["the", "dog"])
    assert idx.num_documents() == 2
    assert idx.doc_frequency("the") == 2
    assert idx.documents_containing("cat") == [0]
    assert idx.label(0) == "pet"


def test_bow_and_tfidf():
    docs = ["cat sat mat", "dog sat log", "cat cat dog"]
    bow = BagOfWordsVectorizer(min_word_frequency=1).fit(docs)
    v = bow.transform("cat cat dog")
    assert v[bow.cache.index_of("cat")] == 2.0
    assert v[bow.cache.index_of("dog")] == 1.0

    tfidf = TfidfVectorizer(min_word_frequency=1).fit(docs)
    v2 = tfidf.transform("cat sat")
    # 'sat' appears in 2/3 docs, 'cat' in 2/3; both positive
    assert v2[tfidf.cache.index_of("cat")] > 0
    # rare words weigh more than common ones at equal tf
    docs2 = ["x common", "y common", "z common", "rare common"]
    tf2 = TfidfVectorizer(min_word_frequency=1).fit(docs2)
    r = tf2.transform("rare common")
    assert r[tf2.cache.index_of("rare")] > r[tf2.cache.index_of("common")]

    ds = BagOfWordsVectorizer(min_word_frequency=1).fit(
        docs, labels=["a", "b", "a"]).vectorize("cat sat", "a")
    assert ds.labels.shape == (1, 2)


def test_windows():
    ws = windows(["a", "b", "c"], window_size=3)
    assert len(ws) == 3
    assert ws[0].words == ["<s>", "a", "b"] and ws[0].focus_word() == "a"
    assert ws[2].words == ["b", "c", "</s>"]

    m = moving_window_matrix(np.arange(5), 3)
    assert m.shape == (3, 3)
    np.testing.assert_array_equal(m[0], [0, 1, 2])
