"""Text infrastructure tests (SURVEY §4: tokenizer/vocab/vectorizer parity)."""

import numpy as np

from deeplearning4j_tpu.text.inverted_index import InvertedIndex
from deeplearning4j_tpu.text.sentence_iterator import (
    CollectionSentenceIterator, LabelAwareSentenceIterator,
    LineSentenceIterator)
from deeplearning4j_tpu.text.stopwords import is_stop_word
from deeplearning4j_tpu.text.tokenization import (DefaultTokenizerFactory,
                                                  NGramTokenizerFactory,
                                                  input_homogenization)
from deeplearning4j_tpu.text.vectorizers import (BagOfWordsVectorizer,
                                                 TfidfVectorizer)
from deeplearning4j_tpu.text.vocab import Huffman, VocabCache
from deeplearning4j_tpu.text.windows import moving_window_matrix, windows


def test_tokenizer_and_homogenization():
    tf = DefaultTokenizerFactory(preprocessor=input_homogenization)
    toks = tf.tokenize("Hello, World!  FOO-bar")
    assert toks == ["hello", "world", "foobar"]
    t = tf.create("a b c")
    assert t.count_tokens() == 3
    assert t.next_token() == "a" and t.has_more_tokens()


def test_ngram_tokenizer():
    tf = NGramTokenizerFactory(1, 2)
    toks = tf.tokenize("a b c")
    assert "a b" in toks and "b c" in toks and "a" in toks


def test_sentence_iterators(tmp_path):
    it = CollectionSentenceIterator(["one", "two"])
    assert list(it) == ["one", "two"]
    assert list(it) == ["one", "two"]  # reset works

    p = tmp_path / "s.txt"
    p.write_text("l1\nl2\nl3\n")
    li = LineSentenceIterator(str(p))
    assert list(li) == ["l1", "l2", "l3"]

    la = LabelAwareSentenceIterator(["x", "y"], ["A", "B"])
    la.reset()
    la.next_sentence()
    assert la.current_label() == "A"


def test_stopwords():
    assert is_stop_word("the") and not is_stop_word("tensor")


def test_vocab_and_huffman():
    cache = VocabCache(min_word_frequency=1).fit(
        [["a", "a", "a", "b", "b", "c"]])
    assert cache.num_words() == 3
    assert cache.word_at_index(0) == "a"  # most frequent first
    Huffman.build(cache)
    # Kraft equality for a complete prefix code: sum 2^-len == 1
    total = sum(2.0 ** -len(cache.word_for(w).codes) for w in cache.words())
    assert abs(total - 1.0) < 1e-9
    # most frequent word gets the shortest code
    lens = [len(cache.word_for(w).codes) for w in cache.words()]
    assert lens[0] == min(lens)
    codes, points, mask = Huffman.padded_arrays(cache)
    assert codes.shape == points.shape == mask.shape
    assert mask.sum() == sum(lens)
    # inner-node ids are valid syn1 rows
    assert points.max() < cache.num_words() - 1


def test_inverted_index():
    idx = InvertedIndex()
    idx.add_doc(["the", "cat"], label="pet")
    idx.add_doc(["the", "dog"])
    assert idx.num_documents() == 2
    assert idx.doc_frequency("the") == 2
    assert idx.documents_containing("cat") == [0]
    assert idx.label(0) == "pet"


def test_disk_inverted_index_roundtrip(tmp_path):
    """DiskInvertedIndex (VERDICT r4 missing #3, LuceneInvertedIndex
    role): same query contract as the in-memory index, documents on
    disk, manifest reopen, and log-scan recovery without a manifest."""
    from deeplearning4j_tpu.text.inverted_index import DiskInvertedIndex

    d = str(tmp_path / "idx")
    idx = DiskInvertedIndex(d)
    idx.add_doc(["the", "cat"], label="pet")
    idx.add_doc(["the", "dog"])
    assert idx.num_documents() == 2
    assert idx.doc_frequency("the") == 2
    assert idx.documents_containing("cat") == [0]
    assert idx.document(1) == ["the", "dog"]
    assert idx.label(0) == "pet" and idx.label(1) is None
    assert list(idx.all_docs()) == [["the", "cat"], ["the", "dog"]]
    idx.save()
    idx.close()

    # manifest reopen
    idx2 = DiskInvertedIndex.load(d)
    assert idx2.num_documents() == 2
    assert idx2.document(0) == ["the", "cat"]
    assert idx2.documents_containing("dog") == [1]
    # appending after reopen keeps offsets consistent
    idx2.add_doc(["a", "cat", "again"])
    assert idx2.document(2) == ["a", "cat", "again"]
    assert idx2.documents_containing("cat") == [0, 2]
    idx2.close()

    # no manifest: rebuild by scanning the log
    import os

    os.remove(os.path.join(d, "index.json"))
    idx3 = DiskInvertedIndex(d)
    assert idx3.num_documents() == 3
    assert idx3.documents_containing("cat") == [0, 2]
    idx3.close()


def test_disk_index_stale_manifest_recovers(tmp_path):
    """Docs appended AFTER the last save() must survive a reopen: the
    manifest records the log size it covers, and a mismatch triggers a
    full log rebuild instead of silently dropping the tail."""
    from deeplearning4j_tpu.text.inverted_index import DiskInvertedIndex

    d = str(tmp_path / "stale")
    idx = DiskInvertedIndex(d)
    idx.add_doc(["a"])
    idx.save()
    idx.add_doc(["b"])  # durable in the log, NOT in the manifest
    idx._flush()
    idx.close()

    idx2 = DiskInvertedIndex(d)
    assert idx2.num_documents() == 2
    assert idx2.documents_containing("b") == [1]
    assert idx2.add_doc(["c"]) == 2
    assert idx2.document(2) == ["c"]
    idx2.close()


def test_in_memory_index_to_disk(tmp_path):
    from deeplearning4j_tpu.text.inverted_index import DiskInvertedIndex

    mem = InvertedIndex()
    mem.add_doc(["x", "y"], label="l")
    mem.add_doc(["y", "z"])
    disk = mem.to_disk(str(tmp_path / "d"))
    assert disk.num_documents() == 2
    assert disk.document(0) == ["x", "y"] and disk.label(0) == "l"
    assert disk.documents_containing("y") == [0, 1]
    disk.close()


def test_disk_index_streams_with_bounded_ram(tmp_path):
    """The point of the disk store: iterating the corpus must not pull
    it into RAM.  Python-allocation peak while streaming stays far below
    the on-disk corpus size."""
    import os
    import tracemalloc

    from deeplearning4j_tpu.text.inverted_index import DiskInvertedIndex

    d = str(tmp_path / "big")
    idx = DiskInvertedIndex(d)
    for i in range(4000):
        idx.add_doc([f"w{(i * 7 + j) % 997}" for j in range(40)])
    idx.save()
    idx.close()
    corpus_bytes = os.path.getsize(os.path.join(d, "docs.jsonl"))
    assert corpus_bytes > 1_000_000

    idx = DiskInvertedIndex(d)
    tracemalloc.start()
    base, _ = tracemalloc.get_traced_memory()
    n = tot = 0
    for doc in idx.all_docs():
        n += 1
        tot += len(doc)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    idx.close()
    assert n == 4000 and tot == 160_000
    assert peak - base < corpus_bytes / 10


def test_index_sentence_iterator(tmp_path):
    """`LuceneSentenceIterator` analog: sentences streamed from the
    corpus store (in-memory or disk), preprocessor applied, resettable."""
    from deeplearning4j_tpu.text import IndexSentenceIterator

    mem = InvertedIndex()
    mem.add_doc(["Hello", "world"])
    mem.add_doc(["second", "doc"])
    it = IndexSentenceIterator(mem, preprocessor=str.lower)
    assert list(it) == ["hello world", "second doc"]
    assert list(it) == ["hello world", "second doc"]  # reset works

    disk = mem.to_disk(str(tmp_path / "idx"))
    it2 = IndexSentenceIterator(disk)
    assert it2.has_next() and it2.next_sentence() == "Hello world"
    assert it2.next_sentence() == "second doc" and not it2.has_next()
    disk.close()


def test_word2vec_trains_from_disk_index(tmp_path):
    """End of VERDICT r4 next-#5: w2v trains from a corpus streamed off
    disk (re-iterable DiskDocs view; fit holds int32 ids, not text)."""
    import numpy as np

    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.text.inverted_index import DiskInvertedIndex

    rng = np.random.RandomState(3)
    idx = DiskInvertedIndex(str(tmp_path / "w2v"))
    for _ in range(60):
        idx.add_doc([f"tok{rng.randint(30)}" for _ in range(12)])
    w2v = Word2Vec(vector_length=16, window=3, negative=3,
                   min_word_frequency=1, epochs=1, seed=0, batch_size=64)
    w2v.fit(idx.docs())
    assert w2v.cache.num_words() >= 30
    assert np.isfinite(np.asarray(w2v.table.syn0)).all()
    idx.close()


def test_bow_and_tfidf():
    docs = ["cat sat mat", "dog sat log", "cat cat dog"]
    bow = BagOfWordsVectorizer(min_word_frequency=1).fit(docs)
    v = bow.transform("cat cat dog")
    assert v[bow.cache.index_of("cat")] == 2.0
    assert v[bow.cache.index_of("dog")] == 1.0

    tfidf = TfidfVectorizer(min_word_frequency=1).fit(docs)
    v2 = tfidf.transform("cat sat")
    # 'sat' appears in 2/3 docs, 'cat' in 2/3; both positive
    assert v2[tfidf.cache.index_of("cat")] > 0
    # rare words weigh more than common ones at equal tf
    docs2 = ["x common", "y common", "z common", "rare common"]
    tf2 = TfidfVectorizer(min_word_frequency=1).fit(docs2)
    r = tf2.transform("rare common")
    assert r[tf2.cache.index_of("rare")] > r[tf2.cache.index_of("common")]

    ds = BagOfWordsVectorizer(min_word_frequency=1).fit(
        docs, labels=["a", "b", "a"]).vectorize("cat sat", "a")
    assert ds.labels.shape == (1, 2)


def test_context_label_retriever():
    """ContextLabelRetriever parity: inline <LABEL> spans stripped into
    (label, tokens), unlabeled runs labeled NONE, malformed markup
    rejected."""
    import pytest

    from deeplearning4j_tpu.text.windows import string_with_labels

    stripped, spans = string_with_labels(
        "the <PER> john smith </PER> went to <LOC> paris </LOC> today")
    assert stripped == "the john smith went to paris today"
    assert spans == [("NONE", ["the"]), ("PER", ["john", "smith"]),
                     ("NONE", ["went", "to"]), ("LOC", ["paris"]),
                     ("NONE", ["today"])]
    with pytest.raises(ValueError):
        string_with_labels("<A> x </B>")
    with pytest.raises(ValueError):
        string_with_labels("x </A>")
    with pytest.raises(ValueError):
        string_with_labels("<A> x")


def test_windows():
    ws = windows(["a", "b", "c"], window_size=3)
    assert len(ws) == 3
    assert ws[0].words == ["<s>", "a", "b"] and ws[0].focus_word() == "a"
    assert ws[2].words == ["b", "c", "</s>"]

    m = moving_window_matrix(np.arange(5), 3)
    assert m.shape == (3, 3)
    np.testing.assert_array_equal(m[0], [0, 1, 2])


def test_embedded_markup_tags_rejected():
    """Non-whitespace-delimited markup (<PER>john) raises instead of
    silently leaking tag text into training tokens."""
    import pytest

    from deeplearning4j_tpu.text.windows import string_with_labels

    with pytest.raises(ValueError, match="whitespace-delimited"):
        string_with_labels("the <PER>john smith</PER> went home")
