"""Hessian-free solver: quadratic exactness, GN products, and net training."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf import (LayerType, NeuralNetConfiguration,
                                        OptimizationAlgorithm, list_builder)
from deeplearning4j_tpu.optimize import solver as solver_mod


def _conf(**kw):
    return NeuralNetConfiguration(
        optimization_algo=OptimizationAlgorithm.HESSIAN_FREE, **kw)


def test_hf_solves_quadratic_in_one_outer_iteration():
    # f(x) = 0.5 x^T A x - b^T x with SPD A: Newton step is exact, so HF
    # with enough CG iterations lands on the optimum immediately
    rng = np.random.RandomState(0)
    m = rng.randn(6, 6)
    A = jnp.asarray(m @ m.T + 6 * np.eye(6), jnp.float32)
    b = jnp.asarray(rng.randn(6), jnp.float32)

    obj = solver_mod.from_loss(
        lambda x, key: 0.5 * x @ A @ x - b @ x)
    conf = _conf(num_iterations=8, hf_cg_iterations=50,
                 hf_initial_lambda=1e-6)
    x, scores = solver_mod.optimize(obj, jnp.zeros(6), conf,
                                    jax.random.PRNGKey(0))
    x_star = jnp.linalg.solve(A, b)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x_star),
                               rtol=1e-3, atol=1e-3)


def test_gauss_newton_product_matches_dense():
    # predict(params) = M params (linear), loss = 0.5||z - y||^2:
    # GN = M^T M exactly
    rng = np.random.RandomState(1)
    M = jnp.asarray(rng.randn(5, 4), jnp.float32)
    y = jnp.asarray(rng.randn(5), jnp.float32)

    obj = solver_mod.from_predict_loss(
        lambda p, key: M @ p, lambda z: 0.5 * jnp.sum((z - y) ** 2))
    v = jnp.asarray(rng.randn(4), jnp.float32)
    gv = obj.gnvp(jnp.zeros(4), v, None)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(M.T @ (M @ v)),
                               rtol=1e-5, atol=1e-5)


def test_hf_trains_mlp_on_iris():
    from deeplearning4j_tpu.datasets.fetchers import IrisDataFetcher
    from deeplearning4j_tpu.evaluation import Evaluation
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    base = _conf(activation="tanh", num_iterations=30, lr=0.1, seed=3,
                 hf_cg_iterations=24)
    conf = (list_builder(base, 2).hidden_layer_sizes([12], n_in=4, n_out=3)
            .override(1, layer_type=LayerType.OUTPUT).build())
    data = IrisDataFetcher().fetch(150).normalize_zero_mean_unit_variance()
    net = MultiLayerNetwork(conf, seed=3).init()
    net.fit(data.features, data.labels)
    ev = Evaluation()
    ev.eval(data.labels, net.output(data.features))
    assert ev.accuracy() > 0.9, f"HF training underperformed: {ev.accuracy()}"
