"""Dataset-acquisition tests — `base/MnistFetcher.java` parity, hermetic.

A local `http.server` fixture stands in for the LeCun/UMass servers
(VERDICT r2 missing #1: the download *code path* is testable without
egress), covering download, checksum verification, corruption re-fetch,
atomicity, gunzip/untar, and the end-to-end "clean machine with a fixture
URL trains LeNet on downloaded data" flow.
"""

import gzip
import hashlib
import io
import os
import socket
import struct
import tarfile
import threading
from http.server import HTTPServer, SimpleHTTPRequestHandler

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.fetch import (ChecksumError, download_file,
                                               fetch_lfw, fetch_mnist,
                                               gunzip_file, sha256_of,
                                               untar_file)


def _idx_images(arr: np.ndarray) -> bytes:
    n, h, w = arr.shape
    return struct.pack(">IIII", 0x00000803, n, h, w) + arr.tobytes()


def _idx_labels(arr: np.ndarray) -> bytes:
    return struct.pack(">II", 0x00000801, len(arr)) + arr.tobytes()


def _make_mnist_files(rng) -> dict:
    """Tiny but structurally-valid MNIST .gz files (names match FILES)."""
    out = {}
    for prefix, n in (("train", 64), ("t10k", 32)):
        imgs = rng.randint(0, 256, (n, 28, 28)).astype(np.uint8)
        labels = rng.randint(0, 10, n).astype(np.uint8)
        out[f"{prefix}-images-idx3-ubyte.gz"] = gzip.compress(
            _idx_images(imgs))
        out[f"{prefix}-labels-idx1-ubyte.gz"] = gzip.compress(
            _idx_labels(labels))
    return out


@pytest.fixture()
def file_server(tmp_path):
    """Serve tmp_path/'srv' over a loopback HTTP server."""
    srv_dir = tmp_path / "srv"
    srv_dir.mkdir()

    class Handler(SimpleHTTPRequestHandler):
        def __init__(self, *a, **kw):
            super().__init__(*a, directory=str(srv_dir), **kw)

        def log_message(self, *a):  # keep pytest output clean
            pass

    httpd = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_port}/"
    try:
        yield srv_dir, base
    finally:
        httpd.shutdown()


def test_download_verifies_checksum_and_is_atomic(file_server, tmp_path):
    srv_dir, base = file_server
    payload = b"x" * 4096
    (srv_dir / "blob.bin").write_bytes(payload)
    good = hashlib.sha256(payload).hexdigest()
    dest = str(tmp_path / "out" / "blob.bin")

    p = download_file(base + "blob.bin", dest, sha256=good)
    assert sha256_of(p) == good
    assert not os.path.exists(dest + ".part")

    # wrong digest -> ChecksumError and no file left at dest
    dest2 = str(tmp_path / "out" / "blob2.bin")
    with pytest.raises(ChecksumError):
        download_file(base + "blob.bin", dest2, sha256="0" * 64)
    assert not os.path.exists(dest2)
    assert not os.path.exists(dest2 + ".part")


def test_download_refetches_corrupt_cache(file_server, tmp_path):
    srv_dir, base = file_server
    payload = b"fresh bytes"
    (srv_dir / "f.bin").write_bytes(payload)
    good = hashlib.sha256(payload).hexdigest()
    dest = str(tmp_path / "f.bin")
    with open(dest, "wb") as f:
        f.write(b"stale garbage")  # present but corrupt
    download_file(base + "f.bin", dest, sha256=good)
    assert open(dest, "rb").read() == payload


def test_download_missing_file_raises(file_server, tmp_path):
    _, base = file_server
    with pytest.raises(IOError):
        download_file(base + "nope.bin", str(tmp_path / "n.bin"), retries=2)


def test_fetch_mnist_end_to_end_trains_lenet(file_server, tmp_path,
                                             monkeypatch):
    """Clean MNIST_DIR + fixture URL -> download/verify/gunzip -> LeNet
    trains on the downloaded IDX data through the normal fetcher path."""
    srv_dir, base = file_server
    rng = np.random.RandomState(0)
    files = _make_mnist_files(rng)
    sums = {}
    for name, blob in files.items():
        (srv_dir / name).write_bytes(blob)
        sums[name] = hashlib.sha256(blob).hexdigest()

    cache = tmp_path / "mnist_cache"
    monkeypatch.setenv("MNIST_DIR", str(cache))
    monkeypatch.setenv("DL4J_MNIST_URL", base)

    d = fetch_mnist(checksums=sums)
    assert d == str(cache)
    for name in files:
        assert (cache / name).exists()            # .gz kept
        assert (cache / name[:-3]).exists()       # unpacked IDX

    # the stock fetcher path must now see real (downloaded) data
    from deeplearning4j_tpu.datasets import mnist as mnist_mod
    from deeplearning4j_tpu.datasets.fetchers import MnistDataFetcher

    assert mnist_mod.find_mnist_dir() == str(cache)
    ds = MnistDataFetcher(binarize=False).fetch(64)
    assert ds.features.shape == (64, 784)

    # ...and LeNet trains a step on it end-to-end
    from deeplearning4j_tpu.models.zoo import lenet5
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(lenet5(iterations=1), seed=0).init()
    net.fit(ds.features, ds.labels)
    assert np.isfinite(net.score(ds.features, ds.labels))


def test_fetch_mnist_second_call_hits_cache(file_server, tmp_path,
                                            monkeypatch):
    srv_dir, base = file_server
    files = _make_mnist_files(np.random.RandomState(1))
    sums = {}
    for name, blob in files.items():
        (srv_dir / name).write_bytes(blob)
        sums[name] = hashlib.sha256(blob).hexdigest()
    cache = tmp_path / "cache"
    monkeypatch.setenv("DL4J_MNIST_URL", base)
    fetch_mnist(cache_dir=str(cache), checksums=sums)
    # wipe the server: a second fetch must succeed purely from cache
    for name in files:
        (srv_dir / name).unlink()
    fetch_mnist(cache_dir=str(cache), checksums=sums)


def test_fetch_lfw_untar_and_record_reader(file_server, tmp_path,
                                           monkeypatch):
    """LFW path: download tarball, untar, read via ImageRecordReader."""
    from PIL import Image

    srv_dir, base = file_server
    rng = np.random.RandomState(2)
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for person, k in (("Alice_A", 3), ("Bob_B", 2)):
            for i in range(k):
                img = Image.fromarray(
                    rng.randint(0, 256, (62, 47), np.uint8).astype(np.uint8))
                ib = io.BytesIO()
                img.save(ib, format="JPEG")
                data = ib.getvalue()
                info = tarfile.TarInfo(f"lfw/{person}/{person}_{i:04d}.jpg")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
    blob = buf.getvalue()
    (srv_dir / "lfw.tgz").write_bytes(blob)

    cache = tmp_path / "lfw_cache"
    monkeypatch.setenv("LFW_DIR", str(cache))
    monkeypatch.setenv("DL4J_LFW_URL", base + "lfw.tgz")
    root = fetch_lfw(sha256=hashlib.sha256(blob).hexdigest())
    assert sorted(os.listdir(root)) == ["Alice_A", "Bob_B"]

    from deeplearning4j_tpu.datasets.fetchers import LFWDataFetcher

    ds = LFWDataFetcher().fetch(5)
    assert ds.features.shape == (5, 62 * 47)
    assert ds.labels.shape[1] == 2


def test_fetch_lfw_flat_preextracted_dir(tmp_path, monkeypatch):
    """VERDICT r3/r4 blemish: a valid pre-extracted LFW_DIR WITHOUT the
    lfw/ archive prefix (person-per-directory at the top level) must be
    used as real data — not silently fall through to synthetic."""
    from PIL import Image

    cache = tmp_path / "flat"
    rng = np.random.RandomState(4)
    for person, k in (("Carol_C", 2), ("Dan_D", 2)):
        d = cache / person
        d.mkdir(parents=True)
        for i in range(k):
            Image.fromarray(rng.randint(0, 256, (62, 47), np.uint8)
                            .astype(np.uint8)).save(
                str(d / f"{person}_{i:04d}.jpg"))
    monkeypatch.setenv("LFW_DIR", str(cache))
    monkeypatch.delenv("DL4J_LFW_URL", raising=False)
    # no network source configured: only the pre-extracted tree can serve
    root = fetch_lfw()
    assert root == str(cache)

    from deeplearning4j_tpu.datasets.fetchers import LFWDataFetcher

    ds = LFWDataFetcher().fetch(4)
    assert ds.features.shape == (4, 62 * 47)
    assert ds.labels.shape[1] == 2  # real 2-person tree, not synthetic


def test_untar_rejects_escaping_members(tmp_path):
    evil = tmp_path / "evil.tar"
    with tarfile.open(evil, "w") as tf:
        data = b"pwned"
        info = tarfile.TarInfo("../escape.txt")
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))
    with pytest.raises(IOError):
        untar_file(str(evil), str(tmp_path / "dest"))
    assert not (tmp_path / "escape.txt").exists()


def test_gunzip_file_idempotent(tmp_path):
    raw = b"hello idx"
    gz = tmp_path / "a.bin.gz"
    gz.write_bytes(gzip.compress(raw))
    out = gunzip_file(str(gz))
    assert open(out, "rb").read() == raw
    assert gunzip_file(str(gz)) == out  # second call reuses


# -- retry backoff (ISSUE 5 satellite): jittered exponential, partials
# cleaned per attempt, monkeypatchable sleep -------------------------------

def _flaky_opener(payload: bytes, fail_first: int):
    """urlopen stand-in that errors `fail_first` times, then serves."""
    calls = {"n": 0}

    class _Resp(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            self.close()

    def opener(url, timeout=None):
        calls["n"] += 1
        if calls["n"] <= fail_first:
            raise OSError(f"mirror down (attempt {calls['n']})")
        return _Resp(payload)

    return opener, calls


def test_download_retries_with_jittered_exponential_backoff(
        tmp_path, monkeypatch):
    from deeplearning4j_tpu.datasets import fetch

    payload = b"eventually consistent mirror"
    opener, calls = _flaky_opener(payload, fail_first=2)
    slept = []
    monkeypatch.setattr(fetch, "_sleep", slept.append)
    dest = str(tmp_path / "d.bin")
    out = download_file("http://mirror/d.bin", dest,
                        sha256=hashlib.sha256(payload).hexdigest(),
                        retries=4, opener=opener)
    assert out == dest and open(dest, "rb").read() == payload
    assert calls["n"] == 3
    # one backoff per failed attempt, inside the full-jitter envelope
    # (0, min(cap, base * 2**(n-1))]
    assert len(slept) == 2
    for n, delay in enumerate(slept, start=1):
        ceiling = min(fetch.BACKOFF_CAP_S,
                      fetch.BACKOFF_BASE_S * 2.0 ** (n - 1))
        assert 0.0 < delay <= ceiling, (n, delay, ceiling)
    assert not os.path.exists(dest + ".part")  # partials cleaned per attempt


def test_download_all_attempts_fail_leaves_no_partial(tmp_path, monkeypatch):
    from deeplearning4j_tpu.datasets import fetch

    opener, calls = _flaky_opener(b"", fail_first=99)
    slept = []
    monkeypatch.setattr(fetch, "_sleep", slept.append)
    dest = str(tmp_path / "never.bin")
    with pytest.raises(IOError):
        download_file("http://mirror/never.bin", dest, retries=3,
                      opener=opener)
    assert calls["n"] == 3
    assert len(slept) == 2  # no sleep after the terminal attempt
    assert not os.path.exists(dest) and not os.path.exists(dest + ".part")


def test_backoff_seconds_envelope_and_cap():
    from deeplearning4j_tpu.datasets.fetch import (BACKOFF_BASE_S,
                                                   BACKOFF_CAP_S,
                                                   backoff_seconds)

    assert backoff_seconds(1, rng=lambda: 1.0) == BACKOFF_BASE_S
    assert backoff_seconds(3, rng=lambda: 1.0) == BACKOFF_BASE_S * 4
    assert backoff_seconds(50, rng=lambda: 1.0) == BACKOFF_CAP_S  # capped
    assert backoff_seconds(4, rng=lambda: 0.0) > 0.0  # jitter floor > 0
    assert backoff_seconds(2, rng=lambda: 0.5) == pytest.approx(0.5)
