"""2-D tensor parallelism (ISSUE 17 acceptance): a transformer serves,
decodes, and trains on a `('batch', 'model')` mesh with params,
activations, and KV state sharded over the model axis — numerically
matching the single-chip programs, decoding token-identically, holding
fewer bytes per chip than the replicated layout, and round-tripping
per-shard checkpoints across topologies without materializing a global
leaf."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.zoo import char_transformer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import checkpoint
from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
from deeplearning4j_tpu.parallel.plan import ShardPlan, plan_mesh
from deeplearning4j_tpu.serving.batcher import ContinuousBatcher

VOCAB = 32

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8 forced host devices")


def _net():
    conf = char_transformer(VOCAB, d_model=16, n_blocks=2, n_heads=2,
                            max_seq_len=32)
    return MultiLayerNetwork(conf, seed=0).init()


def _greedy_tokens(net, prompt, n_new=8):
    net.warmup_generate(slots=2, max_seq=32, prompt_buckets=(8,))
    cb = ContinuousBatcher(net, n_slots=2, max_seq=32,
                           prompt_buckets=(8,))
    try:
        stream = cb.submit(prompt, max_new_tokens=n_new)
        return list(stream.tokens(timeout=120.0))
    finally:
        cb.stop()


class TestTwoDServe:
    def test_output_matches_single_chip(self):
        x = np.random.RandomState(0).randint(
            1, VOCAB, size=(8, 16)).astype(np.int32)
        ref = np.asarray(_net().output(x))
        net = _net()
        net.set_serve_mesh(spec="batch=2,model=4")
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_per_chip_bytes_shrink(self):
        net = _net()
        net.set_serve_mesh(spec="batch=2,model=4")
        x = np.ones((8, 16), np.int32)
        net.infer_cache.output(net.conf, net.params, x,
                               compile_only=True)
        rows = [r for r in net.infer_cache.program_memory()
                if r["entry"] == "output"]
        assert rows
        r = rows[0]
        assert r["per_device_argument_bytes"] < \
            r["replicated_argument_bytes"]


class TestTwoDDecode:
    def test_greedy_trajectory_identical_to_single_chip(self):
        prompt = [1, 7, 3]
        ref = _greedy_tokens(_net(), prompt)
        assert ref  # really decoded something
        net = _net()
        net.set_serve_mesh(spec="batch=1,model=4")
        assert _greedy_tokens(net, prompt) == ref

    def test_paged_greedy_trajectory_identical(self):
        prompt = [2, 5, 9]
        net_ref = _net()
        net_ref.warmup_generate(slots=2, max_seq=32, prompt_buckets=(8,),
                                page_size=8, n_pages=8)
        cb = ContinuousBatcher(net_ref, n_slots=2, max_seq=32,
                               prompt_buckets=(8,), page_size=8)
        try:
            ref = list(cb.submit(prompt, max_new_tokens=8)
                       .tokens(timeout=120.0))
        finally:
            cb.stop()
        net = _net()
        net.set_serve_mesh(spec="batch=1,model=4")
        net.warmup_generate(slots=2, max_seq=32, prompt_buckets=(8,),
                            page_size=8, n_pages=8)
        cb = ContinuousBatcher(net, n_slots=2, max_seq=32,
                               prompt_buckets=(8,), page_size=8)
        try:
            got = list(cb.submit(prompt, max_new_tokens=8)
                       .tokens(timeout=120.0))
        finally:
            cb.stop()
        assert got == ref

    def test_decode_state_sharded_over_model_axis(self):
        net = _net()
        net.set_serve_mesh(spec="batch=1,model=4")
        rows = 0
        net.warmup_generate(slots=2, max_seq=32, prompt_buckets=(8,))
        mem = [r for r in net.infer_cache.program_memory()
               if r["entry"] == "decode"]
        assert mem
        for r in mem:
            rows += 1
            assert r["per_device_argument_bytes"] < \
                r["replicated_argument_bytes"]
        assert rows


class TestPlanTrainer:
    def _batches(self, n_batches=2, bs=8, seed=0):
        rng = np.random.RandomState(seed)
        out = []
        for _ in range(n_batches):
            x = rng.randint(1, VOCAB, size=(bs, 16)).astype(np.int32)
            y = np.eye(VOCAB, dtype=np.float32)[
                rng.randint(0, VOCAB, bs * 16)]
            out.append((x, y))
        return out

    def test_two_d_plan_trains_with_zero1(self):
        plan = ShardPlan(mesh=plan_mesh({"batch": 2, "model": 4}))
        net = _net()
        t = DataParallelTrainer(net, zero1=True, plan=plan)
        t.fit(self._batches(), epochs=1)
        assert int(t.state.step) == 2
        # updater moments compose batch over the model split
        flat, _ = jax.tree_util.tree_flatten_with_path(t.state.updater)
        composed = [
            leaf.sharding.spec for path, leaf in flat
            if hasattr(leaf, "sharding")
            and getattr(leaf.sharding, "spec", None) is not None
            and tuple(leaf.sharding.spec) == ("batch", "model")]
        assert composed, "no updater leaf composed batch over model"
        # params stay tensor-sharded on the mesh after fit
        p_specs = {tuple(leaf.sharding.spec)
                   for leaf in jax.tree_util.tree_leaves(net.params)
                   if hasattr(leaf, "sharding")
                   and getattr(leaf.sharding, "spec", None) is not None}
        assert any("model" in s for s in p_specs)

    def test_remainder_batch_pads_and_masks(self):
        plan = ShardPlan(mesh=plan_mesh({"batch": 2, "model": 4}))
        batches = self._batches()
        x, y = self._batches(1, seed=9)[0]
        tail = (x[:6], y[:6 * 16])  # 6 prompt rows -> 96 label rows

        t_ref = DataParallelTrainer(_net(), zero1=True, plan=plan)
        t_ref.fit(batches, epochs=1)
        t = DataParallelTrainer(_net(), zero1=True, plan=plan)
        t.fit(batches, epochs=1)
        ref = jax.tree_util.tree_map(np.asarray, t_ref.state.params)
        got = jax.tree_util.tree_map(np.asarray, t.state.params)
        for a, b in zip(jax.tree_util.tree_leaves(ref),
                        jax.tree_util.tree_leaves(got)):
            assert np.array_equal(a, b)  # divisible prefix is bitwise
        t.fit([tail], epochs=1)  # 6 rows on a 2-row mesh: pad + mask
        assert int(t.state.step) == 3


class TestShardedCheckpoint:
    def test_round_trip_n_to_m_without_global_leaf(self, tmp_path):
        net = _net()
        plan_a = ShardPlan(mesh=plan_mesh({"batch": 2, "model": 4}))
        sharded = jax.tree_util.tree_map(
            jax.device_put, net.params, plan_a.param_shardings(net.params))
        d = str(tmp_path / "ckpt")
        checkpoint.save_sharded(d, sharded, conf=net.conf, step=7,
                                metadata={"note": "tp"})

        plan_b = ShardPlan(mesh=plan_mesh({"batch": 4, "model": 2}))
        like = net.params
        stats = {}
        params, upd, meta = checkpoint.load_sharded(
            d, like_params=like,
            params_shardings=plan_b.param_shardings(like), stats=stats)
        assert upd is None
        assert meta["step"] == 7
        for a, b in zip(jax.tree_util.tree_leaves(net.params),
                        jax.tree_util.tree_leaves(params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        # the working-set bound: no assembled region reached the size
        # of the largest global leaf
        biggest = max(
            int(np.prod(np.asarray(l.shape), dtype=np.int64)) * 4
            for l in jax.tree_util.tree_leaves(net.params))
        assert stats["max_region_bytes"] < biggest

    def test_plain_load_reads_sharded_layout(self, tmp_path):
        net = _net()
        plan = ShardPlan(mesh=plan_mesh({"batch": 2, "model": 4}))
        sharded = jax.tree_util.tree_map(
            jax.device_put, net.params, plan.param_shardings(net.params))
        d = str(tmp_path / "ckpt")
        checkpoint.save_sharded(d, sharded, conf=net.conf, step=3)
        params, _, meta = checkpoint.load(d, like_params=net.params)
        assert meta["step"] == 3
        for a, b in zip(jax.tree_util.tree_leaves(net.params),
                        jax.tree_util.tree_leaves(params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
