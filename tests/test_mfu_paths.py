"""Parity tests for the MFU-campaign hot paths.

Each optimized path is gated by a conf flag and claims BITWISE f32
identity (sparse labels, fused updater) or reference-tolerance identity
(flash block-skip) with the path it replaces — these tests are the
claim's enforcement.  Flag combinations are also exercised end-to-end
through `MultiLayerNetwork.finetune` (the compiled step-cache program),
so the parity holds through tracing, donation and the solver scan, not
just at the op level.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nd import losses
from deeplearning4j_tpu.nd.attention import full_attention
from deeplearning4j_tpu.nd.pallas_kernels import (flash_attention,
                                                  pick_attention_blocks)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.optimize.updater import (UpdaterState,
                                                 adjust_gradient,
                                                 adjust_gradient_auto,
                                                 adjust_gradient_flat,
                                                 flat_norm, flat_ravel,
                                                 flat_unravel, init_updater,
                                                 make_flat_spec, tree_norm)


def _assert_tree_bitwise(a, b, where=""):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb, f"tree structure mismatch {where}"
    for i, (x, y) in enumerate(zip(la, lb)):
        assert x.dtype == y.dtype and x.shape == y.shape, \
            f"leaf {i} meta mismatch {where}"
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            f"leaf {i} bits differ {where}"


# -- sparse-label loss path --------------------------------------------------

def _softmax_rows(key, rows, vocab):
    logits = jax.random.normal(key, (rows, vocab), jnp.float32)
    return jax.nn.softmax(logits, axis=-1)


def test_sparse_mcxent_bitwise_value_and_grad():
    key = jax.random.PRNGKey(0)
    rows, vocab = 40, 13
    p = _softmax_rows(key, rows, vocab)
    ids = jax.random.randint(jax.random.PRNGKey(1), (rows,), 0, vocab)
    one_hot = jax.nn.one_hot(ids, vocab, dtype=jnp.float32)

    dense = losses.mcxent_rows(one_hot, p)
    sparse = losses.mcxent_rows(ids.astype(jnp.int32), p)
    _assert_tree_bitwise(dense, sparse, "mcxent rows")

    g_dense = jax.grad(lambda o: jnp.mean(losses.mcxent_rows(one_hot, o)))(p)
    g_sparse = jax.grad(lambda o: jnp.mean(losses.mcxent_rows(ids, o)))(p)
    _assert_tree_bitwise(g_dense, g_sparse, "mcxent grad")


def test_sparse_mcxent_padded_tail_weighted_bitwise():
    """Pad rows carry class id 0 and weight 0.0 (`pad_batch` convention):
    the weighted loss and its gradient must match the one-hot path's
    all-zero pad rows bit for bit."""
    key = jax.random.PRNGKey(2)
    real, pad, vocab = 24, 8, 11
    p = _softmax_rows(key, real + pad, vocab)
    ids = np.zeros(real + pad, np.int32)
    ids[:real] = np.asarray(
        jax.random.randint(jax.random.PRNGKey(3), (real,), 0, vocab))
    one_hot = np.zeros((real + pad, vocab), np.float32)
    one_hot[np.arange(real), ids[:real]] = 1.0  # pad rows stay all-zero
    w = jnp.asarray(np.r_[np.ones(real), np.zeros(pad)].astype(np.float32))

    def weighted(labels, o):
        return jnp.dot(losses.mcxent_rows(labels, o), w) / jnp.sum(w)

    v_dense = weighted(jnp.asarray(one_hot), p)
    v_sparse = weighted(jnp.asarray(ids), p)
    _assert_tree_bitwise(v_dense, v_sparse, "weighted loss")
    g_dense = jax.grad(lambda o: weighted(jnp.asarray(one_hot), o))(p)
    g_sparse = jax.grad(lambda o: weighted(jnp.asarray(ids), o))(p)
    _assert_tree_bitwise(g_dense, g_sparse, "weighted grad")


def test_sparse_labels_rejected_outside_mcxent_family():
    ids = jnp.zeros(4, jnp.int32)
    out = jnp.ones((4, 3), jnp.float32) / 3.0
    for fn in ("mse", "xent", "squared_loss"):
        with pytest.raises(TypeError, match="sparse"):
            losses.get_rowwise(fn)(ids, out)
        with pytest.raises(TypeError, match="sparse"):
            losses.get_loss(fn)(ids, out)
    # the mcxent family accepts them
    losses.get_rowwise("mcxent")(ids, out)
    losses.get_loss("negativeloglikelihood")(ids, out)


# -- fused updater -----------------------------------------------------------

def _param_tree(key):
    """Odd, MXU-unfriendly shapes on purpose: strided slices into the flat
    buffer are exactly where a reduction could reorder its accumulation."""
    ks = jax.random.split(key, 4)
    return {"blk": {"W": jax.random.normal(ks[0], (13, 7), jnp.float32),
                    "b": jax.random.normal(ks[1], (7,), jnp.float32)},
            "out": {"W": jax.random.normal(ks[2], (7, 5), jnp.float32),
                    "b": jax.random.normal(ks[3], (5,), jnp.float32)}}


_UPDATER_OPTIONS = [
    {},
    {"gradient_clip_norm": 0.05},          # binding clip: norms on the path
    {"constrain_gradient_to_unit_norm": True},
    {"use_regularization": True, "l2": 1e-3},
    {"use_adagrad": True, "adagrad_reset_iterations": 2},
]


@pytest.mark.parametrize("which", ["", "sgd", "adagrad", "nesterov",
                                   "adam", "rmsprop"])
@pytest.mark.parametrize("opts", _UPDATER_OPTIONS,
                         ids=[",".join(o) or "plain"
                              for o in _UPDATER_OPTIONS])
def test_fused_updater_bitwise(which, opts):
    conf = NeuralNetConfiguration(lr=0.05, momentum=0.9, updater=which,
                                  **opts)
    params = _param_tree(jax.random.PRNGKey(10))
    spec = make_flat_spec(params)
    pbufs = flat_ravel(spec, params)
    state_t = init_updater(params)
    state_f = init_updater(pbufs)

    @jax.jit
    def both(it, grads):
        st, tree_state = adjust_gradient(conf, it, grads, params, state_t)
        sf, flat_state = adjust_gradient_flat(
            conf, it, flat_ravel(spec, grads), pbufs, state_f, spec)
        return st, tree_state, sf, flat_state

    for it in range(3):  # cross the adagrad reset boundary
        grads = jax.tree_util.tree_map(
            lambda p: jax.random.normal(jax.random.fold_in(
                jax.random.PRNGKey(20), it), p.shape, p.dtype) * 0.1, params)
        st, state_t, sf, state_f = both(jnp.asarray(it), grads)
        _assert_tree_bitwise(st, flat_unravel(spec, sf),
                             f"{which or 'legacy'} step it={it}")
        _assert_tree_bitwise(
            state_t,
            UpdaterState(
                adagrad_hist=flat_unravel(spec, state_f.adagrad_hist),
                velocity=flat_unravel(spec, state_f.velocity)),
            f"{which or 'legacy'} state it={it}")


def test_flat_norm_matches_tree_norm_bitwise():
    params = _param_tree(jax.random.PRNGKey(11))
    spec = make_flat_spec(params)
    a = jax.jit(lambda t: tree_norm(t))(params)
    b = jax.jit(lambda bufs: flat_norm(spec, bufs))(
        flat_ravel(spec, params))
    _assert_tree_bitwise(a, b, "global norm")


def test_adjust_gradient_auto_dispatch_bitwise():
    """The tree-in / tree-out fused dispatcher (what the dp train step
    calls) must reproduce the plain path exactly when the flag is on."""
    params = _param_tree(jax.random.PRNGKey(12))
    grads = jax.tree_util.tree_map(lambda p: 0.3 * p, params)
    state = init_updater(params)
    base = NeuralNetConfiguration(lr=0.01, momentum=0.9, updater="adam",
                                  gradient_clip_norm=0.05)
    # jit both sides: the claim is compiled-vs-compiled (how either path
    # runs in a train step); eager-vs-jit differs by ulps on any path
    ref_step, ref_state = jax.jit(
        lambda g, p, s: adjust_gradient(base, 0, g, p, s))(
        grads, params, state)
    fused_conf = base.replace(fused_updater=True)
    out_step, out_state = jax.jit(
        lambda g, p, s: adjust_gradient_auto(fused_conf, 0, g, p, s))(
        grads, params, state)
    _assert_tree_bitwise(ref_step, out_step, "auto step")
    _assert_tree_bitwise(ref_state, out_state, "auto state")


def test_flat_ravel_roundtrip_mixed_dtypes():
    tree = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "b": jnp.arange(4, dtype=jnp.bfloat16),
            "c": jnp.arange(3, dtype=jnp.float32) * 1.5}
    spec = make_flat_spec(tree)
    assert spec.group_dtypes == (jnp.dtype(jnp.float32),
                                 jnp.dtype(jnp.bfloat16))
    assert spec.group_sizes == (9, 4)
    _assert_tree_bitwise(tree, flat_unravel(spec, flat_ravel(spec, tree)),
                         "roundtrip")


# -- causal flash block-skip -------------------------------------------------

@pytest.mark.parametrize("seq,blocks", [(64, (16, 16)), (96, (32, 16)),
                                        (128, (32, 32))])
def test_block_skip_bitwise_vs_masked_flash(seq, blocks):
    """Skipping the mask on fully-unmasked tiles replaces a `where` by its
    identity branch — forward AND backward must be bitwise-identical to
    the all-masked kernel, at ragged (S, block) combinations where full
    and partial tiles mix."""
    bq, bk = blocks
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(4), 3)
    B, H, D = 2, 2, 8
    q = jax.random.normal(kq, (B, seq, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, seq, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, seq, H, D), jnp.float32)

    base = flash_attention(q, k, v, True, bq, bk, block_skip=False)
    skip = flash_attention(q, k, v, True, bq, bk, block_skip=True)
    _assert_tree_bitwise(base, skip, f"fwd S={seq}")

    g_base = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, True, bq, bk, block_skip=False) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_skip = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, True, bq, bk, block_skip=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    _assert_tree_bitwise(g_base, g_skip, f"bwd S={seq}")


def test_block_skip_matches_full_attention():
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(5), 3)
    B, S, H, D = 2, 64, 2, 8
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)
    ref = full_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, 16, 16, block_skip=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pick_attention_blocks_table_and_fallback():
    assert pick_attention_blocks(256, 32) == (128, 128)   # table hit
    assert pick_attention_blocks(2048, 128) == (256, 256)
    bq, bk = pick_attention_blocks(192, 48)               # fallback: divides
    assert 192 % bq == 0 and 192 % bk == 0
    assert pick_attention_blocks(100, 64) == (128, 128)   # indivisible S
    # bwd-aware picks: table hits return the (bwd_q, bwd_k) half, the
    # fallback caps one notch lower (more live VMEM per backward tile)
    assert pick_attention_blocks(256, 32, bwd=True) == (128, 128)
    assert pick_attention_blocks(4096, 128, bwd=True) == (128, 256)
    bq, bk = pick_attention_blocks(192, 48, bwd=True)
    assert 192 % bq == 0 and 192 % bk == 0 and bq <= 128 and bk <= 256
    assert pick_attention_blocks(100, 64, bwd=True) == (128, 128)


# -- fused flash backward ----------------------------------------------------
#
# The fused path (attention_fused_bwd) swaps the jax-level recompute VJP for
# three Pallas kernels fed by saved logsumexp residuals.  Claims enforced
# here: grads allclose (tight f32) to full_attention autodiff across
# causal/non-causal x block_skip x shapes, in interpret AND jit-compiled
# modes; the forward output is bitwise-unchanged by residual emission; every
# fallback (flag off, ragged S, auto-detected interpret mode) stays bitwise
# identical to the pre-fused recompute path; the flag never touches
# serving-cache keys; and no [S,S] intermediate appears in the lowering.

def _qkvg(seed, B, S, H, D):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    return [jax.random.normal(k, (B, S, H, D), jnp.float32) for k in ks]


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_skip", [False, True])
@pytest.mark.parametrize(
    "shape,fwd_blocks,bwd_blocks",
    [((2, 64, 2, 8), (32, 16), (16, 32)),    # asymmetric fwd vs bwd tiles
     ((1, 128, 2, 16), (32, 32), (32, 32))])
def test_fused_bwd_grad_parity_vs_full_attention(causal, block_skip, shape,
                                                 fwd_blocks, bwd_blocks):
    B, S, H, D = shape
    q, k, v, g = _qkvg(20, B, S, H, D)
    bq, bk = fwd_blocks
    bqb, bkb = bwd_blocks

    def loss_fused(q, k, v):
        o = flash_attention(q, k, v, causal, bq, bk, interpret=True,
                            block_skip=block_skip, fused_bwd=True,
                            block_q_bwd=bqb, block_k_bwd=bkb)
        return jnp.sum(o * g)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) * g)

    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for mode, fn in [("interpret", jax.grad(loss_fused, argnums=(0, 1, 2))),
                     ("compiled",
                      jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2))))]:
        got = fn(q, k, v)
        for name, a, b in zip("qkv", got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5,
                err_msg=f"d{name} {mode} causal={causal} "
                        f"skip={block_skip} S={S}")


def test_fused_bwd_forward_output_bitwise():
    """Emitting the logsumexp residual must not perturb o: the fused
    forward (under vjp, residuals saved) is bitwise the plain flash
    forward."""
    q, k, v, _ = _qkvg(21, 2, 64, 2, 8)
    plain = flash_attention(q, k, v, True, 32, 16, interpret=True,
                            block_skip=True)
    fused_primal = flash_attention(q, k, v, True, 32, 16, interpret=True,
                                   block_skip=True, fused_bwd=True)
    out_vjp, _ = jax.vjp(
        lambda q, k, v: flash_attention(q, k, v, True, 32, 16,
                                        interpret=True, block_skip=True,
                                        fused_bwd=True), q, k, v)
    _assert_tree_bitwise(plain, fused_primal, "primal")
    _assert_tree_bitwise(plain, out_vjp, "vjp forward")


@pytest.mark.parametrize("case", ["flag_off", "ragged_s", "auto_interpret"])
def test_fused_bwd_fallbacks_bitwise_vs_recompute(case):
    """Every fused-path degrade keeps the pre-PR backward bit for bit:
    flag off, ragged S (no Pallas block divides it), and auto-detected
    interpret mode (interpret=None off-TPU — the fused kernels are gated
    to real TPU lowerings or an explicit interpret pin)."""
    from deeplearning4j_tpu.nd.attention import blockwise_attention
    from deeplearning4j_tpu.nd.platform import is_tpu

    if case == "auto_interpret" and is_tpu():
        pytest.skip("auto-detect resolves to the real kernels on TPU")
    S = 70 if case == "ragged_s" else 64
    q, k, v, g = _qkvg(22, 2, S, 2, 8)
    kwargs = {"fused_bwd": case != "flag_off"}
    if case != "auto_interpret":
        kwargs["interpret"] = True

    def f(q, k, v):
        return flash_attention(q, k, v, True, 32, 16, **kwargs)

    _, vjp = jax.vjp(f, q, k, v)
    _, vjp_ref = jax.vjp(
        lambda q, k, v: blockwise_attention(q, k, v, block_size=16,
                                            causal=True), q, k, v)
    _assert_tree_bitwise(vjp(g), vjp_ref(g), case)
    # and under jit, as the train step runs it
    jg = jax.jit(jax.grad(lambda q, k, v: jnp.sum(f(q, k, v) * g),
                          argnums=(0, 1, 2)))(q, k, v)
    rg = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(blockwise_attention(
            q, k, v, block_size=16, causal=True) * g),
        argnums=(0, 1, 2)))(q, k, v)
    _assert_tree_bitwise(jg, rg, f"{case} jit")


# the recursive jaxpr walk that used to live here is library code now
# (analysis/program_audit.py) so the `analyze` gate and this test assert
# the exact same structural contract
from deeplearning4j_tpu.analysis.program_audit import (  # noqa: E402
    assert_no_materialized_scores as _assert_no_ss_lib)


def _assert_no_ss(fn, args, S, where):
    _assert_no_ss_lib(fn, args, seq_threshold=S, where=where)


@pytest.mark.parametrize("fused", [True, False])
def test_no_ss_intermediate_at_long_seq(fused):
    """The flash memory contract, asserted structurally: at S=1024 neither
    the forward nor the backward jaxpr (fused kernels or the blockwise
    recompute fallback) contains an intermediate with two S-sized dims.
    Trace-only — nothing executes."""
    S, D = 1024, 8
    q = jax.ShapeDtypeStruct((1, S, 1, D), jnp.float32)

    def fwd(q, k, v):
        return flash_attention(q, k, v, True, 256, 256, interpret=True,
                               block_skip=True, fused_bwd=fused,
                               block_q_bwd=256, block_k_bwd=256)

    _assert_no_ss(fwd, (q, q, q), S, f"forward fused={fused}")
    _assert_no_ss(
        jax.grad(lambda a, b, c: jnp.sum(fwd(a, b, c)), argnums=(0, 1, 2)),
        (q, q, q), S, f"backward fused={fused}")


def test_fused_bwd_flag_never_changes_infer_cache_key():
    """Serving programs are gradient-free: flipping attention_fused_bwd
    must not re-key (or invalidate on disk) any inference program — and
    the normalized fingerprint equals the flag-off fingerprint, so pre-PR
    artifacts stay live.  The training step cache, by contrast, must
    re-key."""
    from deeplearning4j_tpu.models.zoo import char_transformer
    from deeplearning4j_tpu.optimize.infer_cache import InferCache
    from deeplearning4j_tpu.optimize.step_cache import (CompiledProgramCache,
                                                        conf_fingerprint)

    conf_off = char_transformer(17, d_model=32, n_blocks=1, n_heads=2,
                                max_seq_len=16)
    conf_on = char_transformer(17, d_model=32, n_blocks=1, n_heads=2,
                               max_seq_len=16, attention_fused_bwd=True)
    ic = InferCache()
    assert ic._fingerprint(conf_on) == ic._fingerprint(conf_off)
    assert ic._fingerprint(conf_off) == conf_fingerprint(conf_off)
    base = CompiledProgramCache()
    assert base._fingerprint(conf_on) != base._fingerprint(conf_off)


def test_end_to_end_fused_bwd_through_step_cache():
    """char-transformer finetune through the compiled step cache with
    attention_impl pinned to flash and the fused-bwd flag flipped: params
    must agree at tight tolerance (the fused backward is allclose, not
    bitwise, by contract; on CPU the auto-interpret gate makes both runs
    take the recompute fallback, where agreement is exact)."""
    from deeplearning4j_tpu.models.zoo import char_transformer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    vocab, batch, seq = 17, 4, 16

    def train(fused):
        conf = char_transformer(vocab, d_model=32, n_blocks=1, n_heads=2,
                                max_seq_len=seq, iterations=2,
                                attention_fused_bwd=fused)
        conf = conf.replace(confs=tuple(
            c.replace(attention_impl="flash", attention_block_size=8)
            for c in conf.confs))
        net = MultiLayerNetwork(conf, seed=42).init()
        net.finetune(*_char_batch(vocab, batch, seq, False))
        return net.params

    ref, got = train(False), train(True)
    for i, (a, b) in enumerate(zip(jax.tree_util.tree_leaves(ref),
                                   jax.tree_util.tree_leaves(got))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"leaf {i}")


# -- end-to-end through the compiled train step ------------------------------

def _char_batch(vocab, batch, seq, sparse):
    rng = np.random.RandomState(7)
    ids = rng.randint(0, vocab, (batch, seq + 1))
    x = jnp.asarray(ids[:, :-1].astype(np.int32))
    if sparse:
        return x, jnp.asarray(ids[:, 1:].reshape(-1).astype(np.int32))
    return x, jnp.asarray(
        np.eye(vocab, dtype=np.float32)[ids[:, 1:].reshape(-1)])


def test_end_to_end_flag_combos_bitwise():
    """char-transformer `finetune` through the step cache: every flag
    combination must land on bitwise-identical parameters after the
    solver scan (donation, bucketing and fingerprinting included)."""
    from deeplearning4j_tpu.models.zoo import char_transformer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    vocab, batch, seq = 17, 4, 16

    def train(fused, sparse):
        conf = char_transformer(vocab, d_model=32, n_blocks=1, n_heads=2,
                                max_seq_len=seq, iterations=2,
                                fused_updater=fused, sparse_labels=sparse)
        net = MultiLayerNetwork(conf, seed=42).init()
        net.finetune(*_char_batch(vocab, batch, seq, sparse))
        return net.params

    ref = train(False, False)
    for combo in [(True, False), (False, True), (True, True)]:
        _assert_tree_bitwise(ref, train(*combo), f"combo {combo}")

def _dp_train(vocab, batch, seq, steps, sparse, fused):
    from deeplearning4j_tpu.models.zoo import char_transformer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    conf = char_transformer(vocab, d_model=32, n_blocks=1, n_heads=2,
                            max_seq_len=seq, sparse_labels=sparse,
                            fused_updater=fused)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, size=(steps, batch, seq)).astype(np.int32)
    net = MultiLayerNetwork(conf).init()
    tr = DataParallelTrainer(net, mesh=make_mesh({"dp": 8}))
    batches = []
    for i in range(steps):
        flat = ids[i].reshape(batch * seq)
        y = (jnp.asarray(flat, jnp.int32) if sparse
             else jnp.asarray(np.eye(vocab, dtype=np.float32)[flat]))
        batches.append((jnp.asarray(ids[i]), y))
    score = tr.fit(batches)
    return jax.device_get(tr.state.params), score


def test_dp_step_sparse_labels_bitwise():
    """8-way dp train, 3 batches: `sparse_labels` is fully bitwise in the
    dp step too — params AND reported score."""
    ref, ref_score = _dp_train(17, 16, 16, 3, sparse=False, fused=False)
    sp, sp_score = _dp_train(17, 16, 16, 3, sparse=True, fused=False)
    _assert_tree_bitwise(ref, sp, "sparse_labels dp")
    assert sp_score == ref_score


def test_dp_step_fused_updater_single_step_bitwise():
    """One 8-way dp step: the fused updater must land on bitwise-identical
    params even though tree- and flat-layout steps are separately
    compiled programs — a single application has no accumulated state for
    fusion-level rounding to amplify."""
    ref, ref_score = _dp_train(17, 16, 16, 1, sparse=False, fused=False)
    for sparse, fused in [(False, True), (True, True)]:
        got, score = _dp_train(17, 16, 16, 1, sparse=sparse, fused=fused)
        _assert_tree_bitwise(ref, got, f"dp 1-step combo {(sparse, fused)}")
        # the score is a mean over bitwise-identical per-row losses, but
        # the scalar reduce can fuse in a different summation order in a
        # reshaped program — a reporting value, not training state
        np.testing.assert_allclose(score, ref_score, rtol=1e-6,
                                   err_msg=f"combo {(sparse, fused)}")


def test_dp_step_fused_updater_iterated_close():
    """Iterated 8-way dp steps: across *separately compiled* tree- vs
    flat-layout programs XLA may duplicate the moment updates into the
    step fusion with different FMA contraction — a last-ulp seed the
    barriers in `adjust_gradient` cannot pin across layouts (see
    `adjust_gradient_auto`).  Adam's `m / (sqrt(v) + eps)` then amplifies
    that seed to step scale on coordinates whose moments sit near zero
    (observed: ~1e-10 absolute on weights, up to ~4e-5 on a handful of
    bias entries after 3 steps).  So the iterated claim is closeness at
    step-scale tolerance; the exactness claims live in the single-step
    and solver-path tests."""
    ref, _ = _dp_train(17, 16, 16, 3, sparse=False, fused=False)
    for sparse, fused in [(False, True), (True, True)]:
        got, _ = _dp_train(17, 16, 16, 3, sparse=sparse, fused=fused)
        for a, b in zip(jax.tree_util.tree_leaves(ref),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-4,
                err_msg=f"dp 3-step combo {(sparse, fused)}")
