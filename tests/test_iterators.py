"""Iterator-wrapper tests: ReconstructionDataSetIterator and
MovingWindowBaseDataSetIterator (VERDICT r3 missing #3)."""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, labels_to_one_hot
from deeplearning4j_tpu.datasets.iterator import (
    ListDataSetIterator, MovingWindowBaseDataSetIterator,
    ReconstructionDataSetIterator, moving_window_dataset)


def _ds(n=12, d=16, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return DataSet(rng.rand(n, d).astype(np.float32),
                   labels_to_one_hot(rng.randint(0, classes, n), classes))


def test_reconstruction_iterator_sets_labels_to_features():
    data = _ds()
    it = ReconstructionDataSetIterator(ListDataSetIterator(data, 5))
    batches = list(it)
    assert sum(b.num_examples() for b in batches) == 12
    for b in batches:
        np.testing.assert_array_equal(b.labels, b.features)
        assert b.labels is not b.features  # a copy, not an alias
    assert it.total_outcomes() == it.input_columns() == 16
    # reset replays identically
    it.reset()
    again = list(it)
    np.testing.assert_array_equal(again[0].features, batches[0].features)


def test_moving_window_tiles_and_rotations():
    # one 4x4 image with distinct quadrant values, 2x2 windows
    img = np.array([[1, 1, 2, 2],
                    [1, 1, 2, 2],
                    [3, 3, 4, 4],
                    [3, 3, 4, 4]], np.float32).reshape(1, 16)
    data = DataSet(img, labels_to_one_hot([1], 2))
    out = moving_window_dataset(data, 2, 2, rotate=False)
    # 4 tiles, each constant-valued (the MovingWindowMatrix.java docstring
    # example: 1 1 2 2 / 3 3 4 4 quadrants -> flattened windows)
    assert out.features.shape == (4, 4)
    tile_vals = sorted(set(out.features.ravel().tolist()))
    assert tile_vals == [1.0, 2.0, 3.0, 4.0]
    for row in out.features:
        assert len(set(row.tolist())) == 1
    # every window inherits the source label
    np.testing.assert_array_equal(out.labels,
                                  np.repeat(data.labels, 4, axis=0))

    # addRotate=true quadruples the windows (90/180/270 variants)
    rot = moving_window_dataset(data, 2, 2, rotate=True)
    assert rot.features.shape == (16, 4)


def test_moving_window_iterator_batches():
    rng = np.random.RandomState(1)
    data = DataSet(rng.rand(6, 36).astype(np.float32),
                   labels_to_one_hot(rng.randint(0, 2, 6), 2))
    it = MovingWindowBaseDataSetIterator(data, 3, 3, batch_size=8)
    total = it.total_examples()
    assert total == 6 * 4 * 4  # 4 tiles x 4 rotation variants per image
    served = sum(b.num_examples() for b in it)
    assert served == total
    assert it.input_columns() == 9


def test_moving_window_rejects_non_tiling_shapes():
    import pytest

    data = _ds(n=2, d=16)
    with pytest.raises(ValueError):
        moving_window_dataset(data, 3, 3)  # 4x4 doesn't tile into 3x3
    with pytest.raises(ValueError):
        moving_window_dataset(_ds(n=2, d=15), 3, 3)  # not square


# -- PrefetchIterator threading contract (serving gateway shares these
# idioms: bounded queue, timed waits + stop event, in-order error
# propagation, cross-thread shutdown) ------------------------------------

def _prefetch_items(n, rows=2):
    return [(np.full((rows, 3), i, np.float32),
             np.full((rows, 1), i, np.float32)) for i in range(n)]


def test_prefetch_concurrent_consumers_partition_the_stream():
    import threading

    from deeplearning4j_tpu.datasets.iterator import PrefetchIterator

    items = _prefetch_items(40)
    it = PrefetchIterator(items, buffer_batches=2, to_device=False)
    it.start()
    got, lock = [], threading.Lock()

    def consume():
        while True:
            try:
                feats, _ = it.pull()
            except StopIteration:
                return
            with lock:
                got.append(int(feats[0, 0]))

    threads = [threading.Thread(target=consume) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive(), "consumer failed to terminate"
    # every batch delivered exactly once across all consumers
    assert sorted(got) == list(range(40))
    it.close()


def test_prefetch_cross_thread_close_unblocks_parked_consumer():
    import threading
    import time

    from deeplearning4j_tpu.datasets.iterator import PrefetchIterator

    stall = threading.Event()

    def slow_gen():
        yield (np.zeros((1, 2), np.float32), np.zeros((1, 1), np.float32))
        stall.wait(timeout=30.0)  # producer hangs: consumer must park

    it = PrefetchIterator(slow_gen(), to_device=False)
    served = []

    def consume():
        for feats, _ in it:
            served.append(feats)

    t = threading.Thread(target=consume)
    t.start()
    deadline = time.time() + 5.0
    while not served and time.time() < deadline:
        time.sleep(0.01)
    assert served, "first batch never arrived"
    # close from another thread, while the consumer is parked on get and
    # the producer is still wedged: the consumer must be released and
    # close() must not block on the wedged worker
    it.close(join_timeout=0.2)
    t.join(timeout=5.0)
    assert not t.is_alive(), "close() stranded a blocked consumer"
    stall.set()


def test_prefetch_worker_error_releases_all_consumers():
    import threading

    from deeplearning4j_tpu.datasets.iterator import PrefetchIterator

    def bad_gen():
        yield (np.zeros((1, 2), np.float32), np.zeros((1, 1), np.float32))
        raise RuntimeError("boom")

    it = PrefetchIterator(bad_gen(), to_device=False)
    it.start()
    outcomes, lock = [], threading.Lock()

    def consume():
        try:
            while True:
                it.pull()
        except RuntimeError as e:
            with lock:
                outcomes.append(("error", str(e)))
        except StopIteration:
            with lock:
                outcomes.append(("stop", None))

    threads = [threading.Thread(target=consume) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive(), "worker error left a consumer blocked"
    # the error surfaces at exactly one consumer; the rest stop cleanly
    assert sorted(o[0] for o in outcomes) == ["error", "stop", "stop"]
    assert ("error", "boom") in outcomes
    it.close()


def test_prefetch_restarts_after_midstream_break():
    from deeplearning4j_tpu.datasets.iterator import PrefetchIterator

    data = _ds(n=12)
    it = PrefetchIterator(ListDataSetIterator(data, 4), to_device=False)
    first = next(iter(it))  # break mid-iteration (generator finalized)
    assert first.num_examples() == 4
    # a fresh iteration restarts from the top and serves everything
    assert sum(b.num_examples() for b in it) == 12
