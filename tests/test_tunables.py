"""Tunables registry, the TunedTable override layer, and the tune search.

Pins the ISSUE-18 contract: with no table installed every call site
behaves byte-identically to the pre-registry constants; a `cli tune` run
persists a table a fresh process inherits with ``fresh_tunes == 0``; a
table tuned for another device kind is never consulted; corrupt
artifacts checksum-evict and the caller re-tunes; the search is
deterministic under a fixed seed and an injected clock; and the
``tune.measure``/``tune.load`` fault points degrade, never block.
"""
import json
import logging
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import char_transformer, mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import tunables
from deeplearning4j_tpu.optimize import tune
from deeplearning4j_tpu.optimize.persist import PersistentProgramStore
from deeplearning4j_tpu.optimize.step_cache import conf_fingerprint
from deeplearning4j_tpu.reliability import faults


@pytest.fixture(autouse=True)
def _clean_tunables():
    tunables.clear()
    faults.reset()
    yield
    tunables.clear()
    faults.reset()


def _mlp_conf():
    return mlp(n_in=4, hidden=[6], n_out=3, lr=0.05)


def _transformer_conf(seq=16):
    return char_transformer(24, d_model=16, n_blocks=1, n_heads=2,
                            max_seq_len=seq)


# -- registry defaults == the legacy constants -------------------------------

def test_registry_defaults_match_legacy_constants():
    """The migrated constants resolve to exactly the values the call
    sites used to hard-code (the no-table byte-identity contract)."""
    from deeplearning4j_tpu.serving import batcher

    assert tunables.default("batcher.target_rows") == 256
    assert batcher.DEFAULT_TARGET_ROWS == 256
    assert tunables.default("batcher.max_delay_ms") == 3.0
    assert tunables.default("decode.slots") == 4
    assert tunables.default("decode.page_size") == 0
    assert tunables.default("data.prefetch_depth") == 2
    assert tunables.default("infer.bucket_ladder") == ()
    # flash-attention fwd/bwd defaults are None: the kernel layer falls
    # back to the measured table, which moved here verbatim
    assert tunables.default("attention.block_fwd") is None
    assert tunables.default("attention.block_bwd") is None


def test_block_table_rows_reach_pick_attention_blocks():
    from deeplearning4j_tpu.nd.pallas_kernels import pick_attention_blocks

    for (seq, hd), row in tunables.ATTENTION_BLOCK_TABLE.items():
        assert pick_attention_blocks(seq, hd) == row[:2]
        assert pick_attention_blocks(seq, hd, bwd=True) == row[2:]


def test_every_registry_entry_is_well_formed():
    for name, tun in tunables.REGISTRY.items():
        assert tun.name == name and "." in name
        assert tun.subsystem and tun.doc
        assert isinstance(tun.space, tuple) and tun.space


# -- resolve / install / clear -----------------------------------------------

def test_resolve_prefers_qualified_then_bare_then_default():
    assert tunables.resolve("batcher.target_rows") == 256
    tunables.install(tunables.TunedTable({
        "batcher.target_rows": 512,
        "attention.block_fwd": (128, 128),
        "attention.block_fwd@256x64": (256, 256),
    }, device_kind="cpu", fingerprint="f"))
    assert tunables.resolve("batcher.target_rows") == 512
    # qualified entry wins over the bare one ...
    assert tunables.resolve("attention.block_fwd", "256x64") == (256, 256)
    # ... and other qualifiers fall through to the bare entry
    assert tunables.resolve("attention.block_fwd", "512x64") == (128, 128)
    # untouched tunables keep their defaults
    assert tunables.resolve("decode.slots") == 4
    tunables.clear()
    assert tunables.resolve("batcher.target_rows") == 256
    assert tunables.active() is None


def test_tuned_blocks_flow_through_pick_attention_blocks():
    from deeplearning4j_tpu.nd.pallas_kernels import pick_attention_blocks

    tunables.install(tunables.TunedTable(
        {"attention.block_fwd@256x64": (256, 256)},
        device_kind="cpu", fingerprint="f"))
    assert pick_attention_blocks(256, 64) == (256, 256)
    # bwd has no tuned entry: the measured-table default stands
    assert pick_attention_blocks(256, 64, bwd=True) == \
        tunables.ATTENTION_BLOCK_TABLE[(256, 64)][2:]


def test_status_reports_table_and_fresh_counter():
    s = tunables.status()
    assert s == {"tuned_tables": 0, "fresh_tunes": 0, "entries": 0,
                 "device_kind": "", "source": ""}
    tunables.install(tunables.TunedTable({"decode.slots": 8},
                                         device_kind="cpu",
                                         fingerprint="f"), source="disk")
    tunables.note_fresh(3)
    s = tunables.status()
    assert s["tuned_tables"] == 1 and s["entries"] == 1
    assert s["fresh_tunes"] == 3 and s["source"] == "disk"
    assert s["device_kind"] == "cpu"


def test_table_serialization_round_trips_tuples():
    t = tunables.TunedTable(
        {"attention.block_fwd@1024x64": (256, 256),
         "infer.bucket_ladder": (8, 64, 256),
         "batcher.target_rows": 512},
        device_kind="cpu", fingerprint="abcd", meta={"rounds": 3})
    back = tunables.TunedTable.from_bytes(t.to_bytes())
    # JSON turns tuples into lists; from_bytes re-tuples recursively
    assert back.entries == t.entries
    assert back.device_kind == "cpu" and back.fingerprint == "abcd"
    assert back.meta == {"rounds": 3}


def test_schema_mismatch_rejected():
    payload = json.loads(tunables.TunedTable({}).to_bytes())
    payload["schema"] = tunables.SCHEMA_VERSION + 1
    with pytest.raises(ValueError):
        tunables.TunedTable.from_bytes(json.dumps(payload).encode())


# -- no-table byte-identity (the regression pin) -----------------------------

def test_no_table_disk_artifacts_byte_identical(tmp_path):
    """A warmup with no table and one with an EMPTY table produce the
    identical artifact set — resolve() with no entries is exactly the
    registry default, so cache keys and programs don't move."""
    conf = _mlp_conf()

    def warm(subdir, table):
        tunables.clear()
        if table is not None:
            tunables.install(table)
        net = MultiLayerNetwork(conf, seed=0).init()
        net.set_compile_cache(str(tmp_path / subdir))
        net.warmup([8], entries=("output",), train=True)
        return sorted(os.listdir(tmp_path / subdir))

    files_none = warm("none", None)
    files_empty = warm("empty", tunables.TunedTable(
        {}, device_kind="cpu", fingerprint=conf_fingerprint(conf)))
    assert files_none and files_none == files_empty


def test_empty_bucket_ladder_keeps_grow_on_demand():
    """The registry default () leaves bucket_rows byte-identical to the
    legacy grow-on-demand loop; a tuned ladder pre-seeds buckets."""
    from deeplearning4j_tpu.optimize.step_cache import CompiledProgramCache

    c = CompiledProgramCache()
    assert c.bucket_rows(5) == 5 and c.buckets == (5,)

    tunables.install(tunables.TunedTable(
        {"infer.bucket_ladder": (8, 32)}, device_kind="cpu",
        fingerprint="f"))
    c2 = CompiledProgramCache()
    assert c2.bucket_rows(5) == 8
    assert c2.bucket_rows(20) == 32
    assert set(c2.buckets) >= {8, 32}
    # fixed bucket sets never merge the ladder (declared policy wins)
    c3 = CompiledProgramCache(buckets=(16,))
    assert c3.bucket_rows(5) == 16 and c3.buckets == (16,)


def test_batcher_defaults_resolve_through_registry():
    from deeplearning4j_tpu.serving.batcher import MicroBatcher

    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    mb = MicroBatcher(net)
    try:
        assert mb.max_delay_s == pytest.approx(3.0 / 1e3)
    finally:
        mb.stop()
    tunables.install(tunables.TunedTable(
        {"batcher.max_delay_ms": 1.0}, device_kind="cpu", fingerprint="f"))
    mb2 = MicroBatcher(net)
    try:
        assert mb2.max_delay_s == pytest.approx(1.0 / 1e3)
        # an explicit argument still beats the table
        mb3 = MicroBatcher(net, max_delay_ms=5.0)
        try:
            assert mb3.max_delay_s == pytest.approx(5.0 / 1e3)
        finally:
            mb3.stop()
    finally:
        mb2.stop()


# -- persistence: device-kind isolation + corrupt artifacts ------------------

def test_save_load_round_trip_and_wrong_kind_isolated(tmp_path):
    store = PersistentProgramStore(str(tmp_path))
    kind = store.platform.get("device_kind", "none")
    fp = "feedc0de"
    table = tunables.TunedTable({"decode.slots": 8}, device_kind=kind,
                                fingerprint=fp)
    tunables.save_table(store, table)
    back = tunables.load_table(store, fp, kind)
    assert back is not None and back.entries == {"decode.slots": 8}
    # a table keyed for another kind is simply never found ...
    assert tunables.load_table(store, fp, "tpu-v9") is None
    # ... and a forged payload claiming another kind under this kind's
    # key is rejected (degrades to defaults, one warning)
    forged = tunables.TunedTable({"decode.slots": 16},
                                 device_kind="tpu-v9", fingerprint=fp)
    store.store_bytes(tunables.table_key(fp, kind), forged.to_bytes())
    assert tunables.load_table(store, fp, kind) is None


def test_corrupt_artifact_evicts_then_retune_persists(tmp_path):
    store = PersistentProgramStore(str(tmp_path))
    kind = store.platform.get("device_kind", "none")
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    fp = conf_fingerprint(net.conf)
    tunables.save_table(store, tunables.TunedTable(
        {"decode.slots": 8}, device_kind=kind, fingerprint=fp))
    path = store.path_for(tunables.table_key(fp, kind))
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF  # flip a payload byte: checksum must catch it
    open(path, "wb").write(bytes(blob))

    assert tunables.load_table(store, fp, kind) is None
    assert not os.path.exists(path)  # checksum-evicted, not left to rot

    # the caller re-tunes and the fresh table persists again
    report = tune.tune_and_store(net, store, groups=("serve",), rounds=1)
    assert report["tuning"]["tuned_tables"] == 1
    assert report["tuning"]["source"] == "fresh"
    assert tunables.load_table(store, fp, kind) is not None


def test_existing_table_inherited_without_search(tmp_path):
    """tune_and_store without --force inherits a stored table: zero
    candidates measured, fresh_tunes == 0, source == disk."""
    store = PersistentProgramStore(str(tmp_path))
    kind = store.platform.get("device_kind", "none")
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    fp = conf_fingerprint(net.conf)
    tunables.save_table(store, tunables.TunedTable(
        {"batcher.target_rows": 512}, device_kind=kind, fingerprint=fp))

    report = tune.tune_and_store(net, store)
    assert report["candidates_measured"] == 0
    assert report["entries"] == {"batcher.target_rows": 512}
    assert report["tuning"]["fresh_tunes"] == 0
    assert report["tuning"]["source"] == "disk"
    assert tunables.resolve("batcher.target_rows") == 512


# -- fault points ------------------------------------------------------------

def test_measure_fault_skips_candidate_search_completes():
    """An armed tune.measure failure skips that candidate (counted) and
    the search still completes with the surviving timings."""
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    faults.arm("tune.measure", "raise", nth=2)
    report = tune.tune_model(net, groups=("serve",), rounds=1)
    n_cands = len(sorted(set(
        tunables.REGISTRY["batcher.target_rows"].space) | {256}))
    assert report["measure_failures"] == 1
    assert report["candidates_measured"] == n_cands - 1
    # the faulted candidate is absent from the measured report
    measured = report["groups"]["serve"]["batcher.target_rows"]["candidates"]
    assert len(measured) == n_cands - 1


def test_load_fault_degrades_to_defaults_one_warning(tmp_path, caplog):
    """A failing table read degrades to registry defaults with ONE
    warning — serving never blocks on tuning."""
    store = PersistentProgramStore(str(tmp_path))
    kind = store.platform.get("device_kind", "none")
    tunables.save_table(store, tunables.TunedTable(
        {"decode.slots": 8}, device_kind=kind, fingerprint="fp"))
    faults.arm("tune.load", "ioerror", times=2)
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        assert tunables.load_and_install(store, "fp") is None
        assert tunables.load_and_install(store, "fp") is None
    warnings = [r for r in caplog.records
                if "tuned-table load failed" in r.getMessage()]
    assert len(warnings) == 1
    assert tunables.active() is None
    assert tunables.resolve("decode.slots") == 4  # registry default
    # once the fault clears, the same store serves the table again
    assert tunables.load_and_install(store, "fp") is not None


def test_tune_fault_points_are_documented():
    assert "tune.measure" in faults.DOCUMENTED_POINTS
    assert "tune.load" in faults.DOCUMENTED_POINTS


# -- the search itself -------------------------------------------------------

def test_prune_drops_analytically_bad_candidates():
    search = tune._Search(rounds=1, clock=lambda: 0.0)
    tun = tunables.Tunable("t", "s", 1, (1, 2, 3, 10),
                           lambda v, **_: float(v), "")
    kept = tune._prune(search, tun, [1, 2, 3, 10], 1)
    # cost >= 2x the incumbent's never compiles (10, 3, and 2 all are)
    assert kept == [1]
    assert search.candidates_pruned == 3
    # no cost hint: everything survives
    tun2 = tunables.Tunable("t2", "s", 1, (1, 2), None, "")
    assert tune._prune(search, tun2, [1, 2, 3], 1) == [1, 2, 3]


def test_attention_pruning_uses_profiling_cost_model():
    from deeplearning4j_tpu.optimize.profiling import attention_block_bytes

    # fewer q tiles restream K/V fewer times: block_q=256 moves less
    assert attention_block_bytes(1024, 64, 128, 128) > \
        attention_block_bytes(1024, 64, 256, 128)
    # the registry's cost hint is wired to this model
    hint = tunables.REGISTRY["attention.block_fwd"].cost_hint
    assert hint((128, 128), seq=1024, head_dim=64) == \
        attention_block_bytes(1024, 64, 128, 128)


def test_search_is_deterministic_under_seed_and_fake_clock():
    """Two runs with the same seed and an injected clock produce the
    byte-identical report — candidate order, timings, and winners."""
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()

    def mk_clock():
        state = [0.0]

        def clock():
            state[0] += 1.0
            return state[0]

        return clock

    r1 = tune.tune_model(net, groups=("serve",), rounds=1,
                         seed=7, clock=mk_clock())
    r2 = tune.tune_model(net, groups=("serve",), rounds=1,
                         seed=7, clock=mk_clock())
    assert r1["entries"] == r2["entries"]
    assert r1["groups"] == r2["groups"]
    assert r1["tune_seconds"] == r2["tune_seconds"]
    # under a constant-dt clock rows/s scales with rows: the serve
    # group deterministically picks the largest candidate
    g = r1["groups"]["serve"]["batcher.target_rows"]
    assert g["winner"] == max(
        tunables.REGISTRY["batcher.target_rows"].space)


def test_decode_group_skips_non_generative_confs():
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    report = tune.tune_model(net, groups=("decode",), rounds=1)
    assert report["entries"] == {}
    assert report["candidates_measured"] == 0


def test_winner_recorded_only_past_min_gain():
    """pick() keeps the default unless a challenger beats it by
    MIN_GAIN; a clear winner is recorded in entries."""
    search = tune._Search(rounds=1, clock=__import__("time").perf_counter)
    times = {1: 0.010, 2: 0.002}

    def run(c):
        __import__("time").sleep(times[c])

    winner = search.pick("g", "k", [1, 2], 1, run,
                         throughput=lambda c: 1.0)
    assert winner == 2 and search.entries["k"] == 2
    # a same-speed challenger never displaces the default
    search2 = tune._Search(rounds=1, clock=lambda: 0.0)
    fake = [0.0]

    def clock():
        fake[0] += 1.0
        return fake[0]

    search2.clock = clock
    assert search2.pick("g", "k", [1, 2], 1, lambda c: None) == 1
    assert "k" not in search2.entries


# -- end to end: cli tune -> fresh process inherits --------------------------

def test_cli_tune_then_fresh_warmup_inherits(tmp_path):
    """The acceptance loop across REAL processes: `cli tune` persists a
    table; a fresh `cli warmup` pointed at the same --compile-cache
    reports tuned_tables == 1 and fresh_tunes == 0."""
    conf_path = tmp_path / "conf.json"
    conf_path.write_text(_mlp_conf().to_json())
    cache = str(tmp_path / "cache")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    r1 = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "tune",
         "--model", str(conf_path), "--compile-cache", cache,
         "--groups", "serve", "--rounds", "1"],
        env=env, capture_output=True, text=True, timeout=240)
    assert r1.returncode == 0, r1.stderr[-2000:]
    rep = json.loads(r1.stdout.strip().splitlines()[-1])
    assert rep["tuning"]["tuned_tables"] == 1
    assert rep["tuning"]["source"] == "fresh"
    assert rep["tuning"]["fresh_tunes"] >= 1
    assert rep["candidates_measured"] > 0

    r2 = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "warmup",
         "--model", str(conf_path), "--compile-cache", cache,
         "--shapes", "8"],
        env=env, capture_output=True, text=True, timeout=240)
    assert r2.returncode == 0, r2.stderr[-2000:]
    summary = json.loads(r2.stdout.strip().splitlines()[-1])
    assert summary["tuning"]["tuned_tables"] == 1
    assert summary["tuning"]["fresh_tunes"] == 0
    assert summary["tuning"]["source"] == "disk"


def test_tune_and_store_transformer_all_groups(tmp_path):
    """Full three-group search on a tiny generative transformer: the
    report carries every group, the table persists, and re-running
    inherits it (fresh_tunes == 0)."""
    store = PersistentProgramStore(str(tmp_path))
    net = MultiLayerNetwork(_transformer_conf(), seed=0).init()
    report = tune.tune_and_store(net, store, rounds=1, max_seq=16)
    assert set(report["groups"]) == {"attention", "serve", "decode"}
    assert report["measure_failures"] == 0
    assert report["candidates_measured"] > 0
    assert report["tuning"]["source"] == "fresh"

    tunables.clear()
    again = tune.tune_and_store(net, store, rounds=1, max_seq=16)
    assert again["candidates_measured"] == 0
    assert again["tuning"]["fresh_tunes"] == 0
    assert again["tuning"]["source"] == "disk"
    assert again["entries"] == report["entries"]


# -- observability -----------------------------------------------------------

def test_metrics_families_strict_parse_and_monotonic():
    from deeplearning4j_tpu.serving.metrics import (FAMILIES,
                                                    parse_prometheus_text,
                                                    replica_metrics)

    assert FAMILIES["dl4j_tuning_table_info"] == ("gauge", ("device_kind",))
    assert FAMILIES["dl4j_tuning_fresh_tunes_total"] == ("counter", ())

    def render(fresh):
        stats = {"tuning": {"tuned_tables": 1, "fresh_tunes": fresh,
                            "entries": 3, "device_kind": "cpu",
                            "source": "disk"}}
        return replica_metrics(stats)

    parsed1 = parse_prometheus_text(render(2))  # raises on any bad line
    info = parsed1["dl4j_tuning_table_info"]
    assert info[(("device_kind", "cpu"),)] == 1
    fresh1 = parsed1["dl4j_tuning_fresh_tunes_total"][()]
    assert fresh1 == 2

    parsed2 = parse_prometheus_text(render(5))
    # the counter never moves backwards across scrapes
    assert parsed2["dl4j_tuning_fresh_tunes_total"][()] >= fresh1


def test_server_stats_carry_tuning_block():
    from deeplearning4j_tpu.serving.batcher import MicroBatcher

    tunables.install(tunables.TunedTable({"decode.slots": 8},
                                         device_kind="cpu",
                                         fingerprint="f"), source="disk")
    net = MultiLayerNetwork(_mlp_conf(), seed=0).init()
    mb = MicroBatcher(net)
    try:
        t = mb.stats()["tuning"]
        assert t["tuned_tables"] == 1 and t["source"] == "disk"
    finally:
        mb.stop()
