"""Clustering/geometry tests — reference test parity:
`clustering/{kdtree,vptree,quadtree,sptree}` tests + kmeans behavior."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    KDTree, KMeansClustering, QuadTree, SpTree, VPTree)


def _two_blobs(n=60, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(n // 2, 3) * 0.2 + np.array([0.0, 0.0, 0.0])
    b = rng.randn(n // 2, 3) * 0.2 + np.array([5.0, 5.0, 5.0])
    return np.vstack([a, b]).astype(np.float32)


class TestKMeans:
    def test_two_blobs_separate(self):
        x = _two_blobs()
        cs = KMeansClustering(k=2, seed=3).apply_to(x)
        assert len(cs.clusters) == 2
        # each blob lands in one cluster
        first_half = {cs.assignments[str(i)] for i in range(30)}
        second_half = {cs.assignments[str(i)] for i in range(30, 60)}
        assert len(first_half) == 1 and len(second_half) == 1
        assert first_half != second_half
        # centers near blob means
        centers = sorted(cs.centers.tolist())
        assert np.allclose(centers[0], [0, 0, 0], atol=0.5)
        assert np.allclose(centers[1], [5, 5, 5], atol=0.5)

    def test_nearest_cluster_and_stats(self):
        x = _two_blobs()
        cs = KMeansClustering(k=2, seed=1).apply_to(x)
        c = cs.nearest_cluster(np.array([5.0, 5.0, 5.0], np.float32))
        assert np.allclose(c.center, [5, 5, 5], atol=0.5)
        assert cs.average_point_distance_to_center() < 1.0

    def test_too_few_points_raises(self):
        with pytest.raises(ValueError):
            KMeansClustering(k=5).apply_to(np.zeros((3, 2), np.float32))


class TestStrategyFramework:
    """VERDICT r4 missing #4: the pluggable strategy/condition framework
    (`clustering/algorithm/BaseClusteringAlgorithm.java` + strategy/ +
    condition/) — strategies selectable from config, different stopping
    behavior, empty-cluster repair, optimisation phase."""

    def test_kmeans_setup_overloads(self):
        from deeplearning4j_tpu.clustering import (
            BaseClusteringAlgorithm, ConvergenceCondition,
            FixedIterationCountCondition)

        algo = KMeansClustering.setup(2, max_iterations=25)
        assert isinstance(algo, BaseClusteringAlgorithm)
        assert isinstance(algo.strategy.termination_condition,
                          FixedIterationCountCondition)
        algo2 = KMeansClustering.setup(
            2, min_distribution_variation_rate=0.01)
        assert isinstance(algo2.strategy.termination_condition,
                          ConvergenceCondition)

    def test_strategy_framework_clusters_blobs(self):
        from deeplearning4j_tpu.clustering import KMeansClustering as KM

        x = _two_blobs()
        cs = KM.setup(2, max_iterations=30, seed=3).apply_to(x)
        first = {cs.assignments[str(i)] for i in range(30)}
        second = {cs.assignments[str(i)] for i in range(30, 60)}
        assert len(first) == 1 and len(second) == 1 and first != second

    def test_fixed_vs_convergence_stopping_behavior(self):
        """The two termination conditions stop at different iteration
        counts on the same data (strategy objects actually steer)."""
        x = _two_blobs()
        fixed = KMeansClustering.setup(2, max_iterations=17, seed=0)
        fixed.apply_to(x)
        assert fixed.history.iteration_count == 17

        conv = KMeansClustering.setup(
            2, min_distribution_variation_rate=0.05, seed=0)
        conv.apply_to(x)
        # separable blobs converge almost immediately — far sooner than 17
        assert 2 <= conv.history.iteration_count < 10

    def test_variance_variation_condition(self):
        from deeplearning4j_tpu.clustering import (
            BaseClusteringAlgorithm, FixedClusterCountStrategy)

        strat = FixedClusterCountStrategy.setup(2) \
            .end_when_variance_variation_less_than(0.01, period=2)
        algo = BaseClusteringAlgorithm.setup(strat, seed=0)
        cs = algo.apply_to(_two_blobs())
        assert len(cs.clusters) == 2
        assert algo.history.iteration_count >= 3  # needs period+1 history

    def test_empty_cluster_split_restores_k(self):
        """FixedClusterCountStrategy with allow_empty_clusters=False:
        an empty cluster is reseeded by splitting the most spread-out
        cluster (`ClusterUtils.splitMostSpreadOutClusters`)."""
        rng = np.random.RandomState(0)
        # k=3 on 2 tight blobs: one center will go empty and must be
        # re-seeded so every cluster ends non-empty
        x = _two_blobs(n=40, seed=1)
        algo = KMeansClustering.setup(3, max_iterations=20, seed=5)
        cs = algo.apply_to(x)
        assert all(len(c.points) > 0 for c in cs.clusters)

    def test_optimisation_strategy_splits_wide_clusters(self):
        from deeplearning4j_tpu.clustering import (
            BaseClusteringAlgorithm, ClusteringOptimizationType,
            OptimisationStrategy)

        x = _two_blobs()
        strat = (OptimisationStrategy.setup(2)
                 .optimize(ClusteringOptimizationType
                           .MINIMIZE_AVERAGE_POINT_TO_CENTER_DISTANCE, 5.0)
                 .optimize_when_iteration_count_multiple_of(1))
        strat.end_when_iteration_count_equals(15)
        algo = BaseClusteringAlgorithm.setup(strat, seed=0)
        cs = algo.apply_to(x)
        # avg distance within each tight blob is << 5, so after the split
        # phase settles both clusters satisfy the optimisation target
        for c in cs.clusters:
            if c.points:
                d = np.mean([c.distance_to_center(p) for p in c.points])
                assert d < 5.0

    def test_manhattan_distance_function(self):
        x = _two_blobs()
        algo = KMeansClustering.setup(2, max_iterations=20,
                                      distance_fn="manhattan", seed=2)
        cs = algo.apply_to(x)
        first = {cs.assignments[str(i)] for i in range(30)}
        second = {cs.assignments[str(i)] for i in range(30, 60)}
        assert first != second


class TestKDTree:
    def test_knn_matches_bruteforce(self):
        rng = np.random.RandomState(0)
        data = rng.rand(200, 4)
        tree = KDTree.build(data)
        q = rng.rand(4)
        got = [i for _, _, i in tree.knn(q, 5)]
        want = np.argsort(np.linalg.norm(data - q, axis=1))[:5].tolist()
        assert got == want

    def test_insert_and_nn(self):
        tree = KDTree(2)
        pts = [[0, 0], [1, 1], [2, 2], [5, 5]]
        for p in pts:
            tree.insert(p)
        d, pt = tree.nn([1.1, 1.1])
        assert np.allclose(pt, [1, 1])
        assert d == pytest.approx(np.sqrt(2 * 0.1 ** 2), abs=1e-9)

    def test_range_query(self):
        data = np.array([[0.1, 0.1], [0.5, 0.5], [0.9, 0.9], [2.0, 2.0]])
        tree = KDTree.build(data)
        inside = tree.range([0.0, 0.0], [1.0, 1.0])
        assert sorted(i for _, i in inside) == [0, 1, 2]


class TestVPTree:
    def test_knn_matches_bruteforce(self):
        rng = np.random.RandomState(1)
        data = rng.rand(150, 8)
        tree = VPTree(data)
        q = rng.rand(8)
        got = tree.words_nearest(q, 7)
        want = np.argsort(np.linalg.norm(data - q, axis=1))[:7].tolist()
        assert got == want

    def test_cosine_metric(self):
        data = np.array([[1.0, 0.0], [0.0, 1.0], [1.0, 0.01], [-1.0, 0.0]])
        tree = VPTree(data, distance="cosine")
        got = tree.words_nearest(np.array([1.0, 0.0]), 2)
        assert set(got) == {0, 2}


class TestQuadTree:
    def test_center_of_mass_and_size(self):
        data = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0], [1.0, 1.0]])
        tree = QuadTree.build(data)
        assert tree.cum_size == 4
        assert np.allclose(tree.center_of_mass, [0.5, 0.5])

    def test_non_edge_forces_exact_when_theta_zero(self):
        rng = np.random.RandomState(2)
        data = rng.randn(30, 2)
        tree = QuadTree.build(data)
        # theta=0 forces exact all-pairs evaluation
        for i in [0, 7]:
            f = np.zeros(2)
            sum_q = tree.compute_non_edge_forces(data[i], 0.0, f)
            diff = data[i] - data
            d2 = (diff ** 2).sum(1)
            q = 1.0 / (1.0 + d2)
            mask = d2 > 0
            want_f = ((q ** 2)[mask, None] * diff[mask]).sum(0)
            assert np.allclose(f, want_f, atol=1e-9)
            assert sum_q == pytest.approx(q[mask].sum(), abs=1e-9)


class TestSpTree:
    def test_insert_counts(self):
        rng = np.random.RandomState(3)
        data = rng.randn(50, 3)
        tree = SpTree.build(data)
        assert tree.cum_size == 50
        assert np.allclose(tree.center_of_mass, data.mean(0), atol=1e-9)

    def test_non_edge_forces_exact_when_theta_zero(self):
        rng = np.random.RandomState(4)
        data = rng.randn(25, 3)
        tree = SpTree.build(data)
        f = np.zeros(3)
        sum_q = tree.compute_non_edge_forces(data[5], 0.0, f)
        diff = data[5] - data
        d2 = (diff ** 2).sum(1)
        q = 1.0 / (1.0 + d2)
        mask = d2 > 0
        assert np.allclose(f, ((q ** 2)[mask, None] * diff[mask]).sum(0),
                           atol=1e-9)
        assert sum_q == pytest.approx(q[mask].sum(), abs=1e-9)

    def test_theta_approximation_close(self):
        rng = np.random.RandomState(5)
        data = rng.randn(100, 2)
        tree = SpTree.build(data)
        exact = np.zeros(2)
        approx = np.zeros(2)
        tree.compute_non_edge_forces(data[0], 0.0, exact)
        tree.compute_non_edge_forces(data[0], 0.5, approx)
        assert np.linalg.norm(exact - approx) < 0.1 * max(
            np.linalg.norm(exact), 1e-9) + 0.05

    def test_edge_forces(self):
        data = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 2.0]])
        # P: point 0 attracted to 1 (val .6) and 2 (val .4)
        rows = np.array([0, 2, 2, 2])
        cols = np.array([1, 2])
        vals = np.array([0.6, 0.4])
        pos_f = SpTree.compute_edge_forces(data, rows, cols, vals)
        want0 = (0.6 / 2.0) * np.array([-1.0, 0.0]) + \
                (0.4 / 5.0) * np.array([0.0, -2.0])
        assert np.allclose(pos_f[0], want0)
        assert np.allclose(pos_f[1], 0) and np.allclose(pos_f[2], 0)


class TestStrategyFrameworkFixes:
    def test_multiple_of_condition_fires_periodically(self):
        """optimize_when_iteration_count_multiple_of(n) fires on every
        n-th iteration only — not on every iteration past n (the
        reference's own implementation quirk, deliberately not copied)."""
        from deeplearning4j_tpu.clustering.strategy import (
            IterationCountMultipleOfCondition, IterationHistory,
            IterationInfo)

        cond = IterationCountMultipleOfCondition(3)
        h = IterationHistory()
        fired = []
        for i in range(1, 10):
            h.infos.append(IterationInfo(index=i - 1,
                                         point_location_change=0,
                                         distance_variance=1.0,
                                         counts=np.zeros(2)))
            fired.append(cond.is_satisfied(h))
        assert fired == [False, False, True, False, False, True,
                         False, False, True]

    def test_degenerate_identical_points_terminate(self):
        """All points identical, k > 1: empty-cluster repair has no
        splittable source, must not mark strategy_applied forever — the
        fixed-iteration condition terminates on time."""
        from deeplearning4j_tpu.clustering import KMeansClustering

        x = np.ones((12, 3), np.float32)
        algo = KMeansClustering.setup(3, max_iterations=5, seed=0)
        cs = algo.apply_to(x)
        assert algo.history.iteration_count <= 6
        assert len(cs.clusters) == 3


def test_strategy_shared_between_algorithms_not_mutated():
    """BaseClusteringAlgorithm must not write its default termination
    into a shared strategy object; a condition satisfiable on an empty
    history must not crash the loop."""
    from deeplearning4j_tpu.clustering import (
        BaseClusteringAlgorithm, FixedClusterCountStrategy)

    strat = FixedClusterCountStrategy.setup(2)
    BaseClusteringAlgorithm.setup(strat)
    assert strat.termination_condition is None  # caller's object untouched

    strat0 = FixedClusterCountStrategy.setup(2) \
        .end_when_iteration_count_equals(0)
    algo = BaseClusteringAlgorithm.setup(strat0, seed=0)
    cs = algo.apply_to(_two_blobs())  # immediate termination, no crash
    assert algo.history.iteration_count == 0
    assert len(cs.clusters) == 2
