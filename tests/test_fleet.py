"""Self-healing fleet chaos suite (ISSUE 11): retry budget, hedged
requests, mutable router rotation, staleness, concurrent polling,
supervision (respawn/backoff/quarantine), autoscaling, the new
Prometheus families, and the CLI kill-and-heal acceptance smoke —
SIGKILL one of 2 replicas under load, every request gets a correct
answer or a clean 5xx, the supervisor restores the fleet from the warm
disk cache (fresh_compiles == 0), counters reconcile with what the
clients saw, SIGTERM drain exits 0.

Tier-1: CPU-only; in-process pieces are driven deterministically
(parked pollers, `tick()`/`evaluate_once()` by hand, injectable clocks
and backoff), the subprocess smoke uses short timeouts + a watchdog."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import checkpoint
from deeplearning4j_tpu.reliability import RetryBudget, faults
from deeplearning4j_tpu.serving import (AgentClient, Autoscaler,
                                        CacheFetcher, CacheServer,
                                        CircuitBreaker, FleetSupervisor,
                                        ReplicaAgent, Router,
                                        parse_prometheus_text,
                                        router_metrics)

N_IN, N_OUT = 6, 3


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _net(seed=0):
    net = MultiLayerNetwork(mlp(n_in=N_IN, hidden=[8], n_out=N_OUT,
                                lr=0.05), seed=seed).init()
    net.warmup([1, 2, 4])
    return net


def _x(rows, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(rows, N_IN).astype(np.float32)


def _http(url, body=None, timeout=30):
    req = urllib.request.Request(
        url, data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _start_fleet(n=2, poll_interval_s=3600.0, **router_kw):
    """N warmed in-process replicas behind a router whose background
    poller is parked (huge interval): health transitions are driven by
    poll_once(), deterministically."""
    servers = [_net(seed=0).serve(max_delay_ms=1.0) for _ in range(n)]
    router = Router([s.url for s in servers],
                    poll_interval_s=poll_interval_s, **router_kw).start()
    return servers, router


def _stop_all(router, servers):
    router.stop()
    for s in servers:
        s.stop()


class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


class _Handle:
    """In-process stand-in for `ReplicaProcess`: a real `ModelServer`
    with a settable exit code, so supervisor tests reap/respawn without
    subprocess spawn cost."""

    def __init__(self):
        self.server = _net(seed=0).serve(max_delay_ms=1.0)
        self._rc = None
        self.summary = {"url": self.server.url, "fresh_compiles": 0}

    @property
    def url(self):
        return self.server.url

    def wait_ready(self):
        return self.summary

    def poll(self):
        return self._rc

    def die(self, rc=-9):
        """SIGKILL equivalent: the server vanishes, the exit code shows
        up at the next supervisor poll."""
        self.server.stop()
        self._rc = rc

    def terminate(self):
        self.server.stop()  # ModelServer.stop == graceful drain
        self._rc = 0

    def kill(self):
        self.die(-9)

    def wait(self, timeout=None):
        return self._rc if self._rc is not None else 0


# -- retry budget ------------------------------------------------------------

def test_retry_budget_min_tokens_and_window():
    clk = _FakeClock()
    b = RetryBudget(ratio=0.1, min_tokens=2, window_s=10.0, clock=clk)
    # no traffic at all: the floor still allows min_tokens spends
    assert b.try_spend() and b.try_spend()
    assert not b.try_spend()
    assert b.stats()["exhausted_total"] == 1
    # the window slides: old spends age out and tokens come back
    clk.t += 11.0
    assert b.remaining() == 2.0
    assert b.try_spend()


def test_retry_budget_ratio_scales_with_traffic():
    clk = _FakeClock()
    b = RetryBudget(ratio=0.1, min_tokens=1, window_s=10.0, clock=clk)
    for _ in range(100):
        b.note_request()
    # 10% of 100 requests = 10 tokens
    assert b.remaining() == 10.0
    for _ in range(10):
        assert b.try_spend()
    assert not b.try_spend()
    st = b.stats()
    assert st["requests_in_window"] == 100
    assert st["spent_in_window"] == 10
    assert st["remaining"] == 0.0


# -- mutable rotation --------------------------------------------------------

def test_router_add_remove_replica_rotation_safe():
    servers, router = _start_fleet(n=1)
    extra = _net(seed=0).serve(max_delay_ms=1.0)
    try:
        assert router.healthy_count() == 1
        rep = router.add_replica(extra.url)
        assert rep.ready and router.healthy_count() == 2
        for i in range(4):
            code, _ = _http(router.url + "/v1/predict",
                            {"features": _x(1, seed=i).tolist()})
            assert code == 200
        router.poll_once()
        per = [r["stats"]["requests"] if r["stats"] else 0
               for r in router.stats()["replicas"]]
        assert all(n >= 1 for n in per), per  # both replicas served
        # removal is by URL and immediate; traffic keeps flowing
        assert router.remove_replica(servers[0].url) is not None
        assert len(router.replicas) == 1
        for i in range(2):
            code, _ = _http(router.url + "/v1/predict",
                            {"features": _x(1, seed=i).tolist()})
            assert code == 200
        assert router.remove_replica("http://127.0.0.1:1/none") is None
    finally:
        _stop_all(router, servers)
        extra.stop()


# -- hedging + budget --------------------------------------------------------

def test_hedge_fires_on_slow_replica_and_wins():
    servers, router = _start_fleet(n=2, hedge=True, hedge_floor_ms=20.0,
                                   hedge_ceil_ms=120.0)
    try:
        # first proxy attempt (the primary) stalls well past the hedge
        # delay; the hedge lands on the sibling and answers first
        faults.arm("router.proxy", "delay", delay_s=1.0)
        t0 = time.monotonic()
        code, body = _http(router.url + "/v1/predict",
                           {"features": _x(1).tolist()})
        elapsed = time.monotonic() - t0
        assert code == 200, body
        assert elapsed < 0.9, elapsed  # did NOT wait out the slow primary
        st = router.stats()
        assert st["hedges"] == 1
        assert st["hedge_wins"] == 1
        assert st["retry_budget"]["spent_total"] == 1  # the hedge paid
    finally:
        _stop_all(router, servers)


def test_hedge_respects_exhausted_budget():
    servers, router = _start_fleet(n=2, hedge=True, hedge_floor_ms=20.0,
                                   hedge_ceil_ms=60.0,
                                   retry_budget_ratio=0.0,
                                   retry_budget_min=0)
    try:
        faults.arm("router.proxy", "delay", delay_s=0.4)
        t0 = time.monotonic()
        code, _ = _http(router.url + "/v1/predict",
                        {"features": _x(1).tolist()})
        elapsed = time.monotonic() - t0
        # no token -> no hedge: the request rides out the slow primary
        assert code == 200
        assert elapsed >= 0.4
        st = router.stats()
        assert st["hedges"] == 0
        assert st["retry_budget"]["exhausted_total"] >= 1
    finally:
        _stop_all(router, servers)


def test_budget_exhaustion_degrades_to_single_attempt():
    """A dead replica still in rotation + zero budget: requests that
    draw the corpse get its 502 back (clean, single-attempt, no storm);
    requests that draw the live replica succeed — and the router's
    counters reconcile exactly with what the client saw."""
    servers, router = _start_fleet(n=2, retry_budget_ratio=0.0,
                                   retry_budget_min=0)
    try:
        router.poll_once()
        servers[0].stop()  # dead, but NOT re-polled: stays in rotation
        codes = []
        for i in range(4):
            code, _ = _http(router.url + "/v1/predict",
                            {"features": _x(1, seed=i).tolist()})
            codes.append(code)
        # round-robin alternates primaries: half hit the corpse
        assert sorted(codes) == [200, 200, 502, 502]
        st = router.stats()
        assert st["retries"] == 0                  # budget never allowed one
        assert st["unroutable"] == 2               # == client-observed 5xx
        assert st["retry_budget"]["exhausted_total"] == 2
        ok = sum(p["latency_hist_s"]["count"]
                 for p in st["priorities"].values())
        total = sum(p["requests"] for p in st["priorities"].values())
        assert ok == 2 and total == 4              # ok + unroutable == total
    finally:
        _stop_all(router, servers)


def test_default_budget_allows_failover_retry():
    servers, router = _start_fleet(n=2)
    try:
        router.poll_once()
        servers[0].stop()
        for i in range(4):
            code, body = _http(router.url + "/v1/predict",
                               {"features": _x(1, seed=i).tolist()})
            assert code == 200, body  # fail-over retry absorbed the corpse
        st = router.stats()
        assert st["retries"] >= 1
        assert st["unroutable"] == 0
    finally:
        _stop_all(router, servers)


# -- staleness ----------------------------------------------------------------

def test_stale_replica_excluded_from_fleet_aggregates():
    servers, router = _start_fleet(n=2, stats_staleness_s=0.25)
    try:
        for i in range(4):
            code, _ = _http(router.url + "/v1/predict",
                            {"features": _x(1, seed=i).tolist()})
            assert code == 200
        router.poll_once()
        st = router.stats()
        total_rows = st["rows_by_policy"]["f32"]
        assert total_rows == 4
        assert all(not r["stale"] for r in st["replicas"])
        servers[0].stop()
        time.sleep(0.3)        # replica 0's last good poll ages past bound
        router.poll_once()     # refreshes replica 1, fails on replica 0
        st = router.stats()
        by_idx = {r["index"]: r for r in st["replicas"]}
        assert by_idx[0]["stale"] is True
        assert by_idx[0]["last_ok_poll_age_s"] > 0.25
        assert by_idx[1]["stale"] is False
        # the dead replica's cached rows are history, not fleet state
        assert st["rows_by_policy"]["f32"] == (
            by_idx[1]["stats"]["rows"])
        assert st["rows_by_policy"]["f32"] < total_rows
        # ...and its serving families are gone from the /metrics page,
        # while the staleness age itself IS exported
        parsed = parse_prometheus_text(router_metrics(st))
        reps = {dict(lbl).get("replica")
                for lbl in parsed["dl4j_serving_rows_total"]}
        assert reps == {"1"}
        ages = {dict(lbl)["replica"]: v for lbl, v in
                parsed["dl4j_router_replica_stats_age_seconds"].items()}
        assert ages["0"] > 0.25
    finally:
        _stop_all(router, servers)


# -- concurrent polling -------------------------------------------------------

def test_concurrent_poll_is_not_serialized_by_a_wedged_replica():
    servers, router = _start_fleet(n=3)
    try:
        servers[2].stop()  # one dead sibling that must still get ejected
        # EVERY poll hangs 0.5s (router.poll fires once per replica):
        # serial polling would cost >= 3 x 0.5s, concurrent ~0.5s
        faults.arm("router.poll", "delay", delay_s=0.5, times=99)
        t0 = time.monotonic()
        healthy = router.poll_once()
        elapsed = time.monotonic() - t0
        assert elapsed < 1.2, f"polls serialized: {elapsed:.2f}s"
        assert healthy == 2  # the wedge did not mask the dead sibling
        assert faults.hits("router.poll") >= 3
    finally:
        _stop_all(router, servers)


def test_poll_raise_counts_as_unready():
    servers, router = _start_fleet(n=1)
    try:
        assert router.poll_once() == 1
        faults.arm("router.poll", "raise")
        assert router.poll_once() == 0   # injected failure = not ready
        assert router.poll_once() == 1   # one-shot plan: recovers after
    finally:
        _stop_all(router, servers)


# -- supervision --------------------------------------------------------------

def _fleet_with_supervisor(n=2, **kw):
    handles = [_Handle() for _ in range(n)]
    router = Router([h.url for h in handles],
                    poll_interval_s=3600.0).start()
    kw.setdefault("backoff_fn", lambda attempt: 0.0)
    sup = FleetSupervisor(spawn_fn=_Handle, router=router, initial=handles,
                          min_replicas=n, max_replicas=n, **kw)
    # not started: tests call tick() by hand for determinism
    return handles, router, sup


def test_supervisor_reaps_and_respawns_with_rereg():
    handles, router, sup = _fleet_with_supervisor(n=2)
    try:
        handles[0].die(rc=-9)
        sup.tick()                       # reap: out of rotation, backoff@0
        assert len(router.replicas) == 1
        st = sup.stats()
        assert st["states"]["running"] == 1
        sup.tick()                       # respawn due: new URL registered
        assert len(router.replicas) == 2
        assert router.poll_once() == 2
        st = sup.stats()
        assert st["restarts_total"] == 1
        assert st["states"]["running"] == 2
        # the healed slot re-registered its NEW ephemeral-port URL
        respawned = [s for s in st["slots"] if s["restarts"] == 1]
        assert respawned and router.find_replica(
            respawned[0]["url"]) is not None
        # traffic lands on the healed fleet
        for i in range(4):
            code, _ = _http(router.url + "/v1/predict",
                            {"features": _x(1, seed=i).tolist()})
            assert code == 200
    finally:
        sup.stop()
        router.stop()
        for h in sup.handles():
            h.terminate()


def test_supervisor_quarantines_crash_loop_then_probes():
    handles, router, sup = _fleet_with_supervisor(
        n=1, max_restarts=2, restart_window_s=100.0, quarantine_s=0.15)
    try:
        # every respawn fails at the spawn fault point: a deterministic
        # crash-loop. death 1 -> backoff; failed spawn = death 2 ->
        # quarantined (2 deaths in window), NOT hot-looped.
        faults.arm("supervisor.spawn", "raise", times=1)
        handles[0].die(rc=1)
        sup.tick()                       # reap -> backoff(0)
        sup.tick()                       # respawn attempt fails
        st = sup.stats()
        assert st["spawn_failures_total"] == 1
        assert st["states"]["quarantined"] == 1
        assert st["quarantines_total"] == 1
        sup.tick()                       # quarantine holds: no spawn yet
        assert sup.stats()["states"]["quarantined"] == 1
        time.sleep(0.2)                  # quarantine elapses
        sup.tick()                       # probe respawn (fault disarmed)
        st = sup.stats()
        assert st["states"]["running"] == 1
        assert st["restarts_total"] == 1
        assert router.poll_once() == 1
    finally:
        sup.stop()
        router.stop()
        for h in sup.handles():
            h.terminate()


def test_scale_down_drains_without_dropping_requests():
    handles, router, sup = _fleet_with_supervisor(n=2)
    sup.min_replicas = 1
    results = {"codes": [], "errors": 0}
    stop_load = threading.Event()

    def loader():
        i = 0
        while not stop_load.is_set():
            try:
                code, _ = _http(router.url + "/v1/predict",
                                {"features": _x(1, seed=i).tolist()},
                                timeout=10)
                results["codes"].append(code)
            except Exception:
                results["errors"] += 1
            i += 1

    threads = [threading.Thread(target=loader) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.2)                  # load in flight
        assert sup.scale_down() is True  # drain-then-stop the emptiest
        time.sleep(0.2)                  # load continues on the survivor
        stop_load.set()
        for t in threads:
            t.join(timeout=15.0)
        assert results["errors"] == 0
        assert results["codes"] and all(c == 200 for c in results["codes"])
        st = sup.stats()
        assert st["states"]["running"] == 1
        assert st["states"]["stopped"] == 1
        assert sup.scale_down() is False  # refuses below min_replicas
    finally:
        stop_load.set()
        sup.stop()
        router.stop()
        for h in sup.handles():
            h.terminate()


def test_scale_up_bounded_by_max():
    handles, router, sup = _fleet_with_supervisor(n=1)
    sup.max_replicas = 2
    try:
        assert sup.scale_up() is True
        assert len(router.replicas) == 2
        assert sup.stats()["states"]["running"] == 2
        assert sup.scale_up() is False   # at max
    finally:
        sup.stop()
        router.stop()
        for h in sup.handles():
            h.terminate()


# -- autoscaler ---------------------------------------------------------------

class _SupProbe:
    def __init__(self):
        self.min_replicas, self.max_replicas = 1, 4
        self.ups = 0
        self.downs = 0
        self.running = 2

    def scale_up(self):
        self.ups += 1
        self.running += 1
        return True

    def scale_down(self):
        self.downs += 1
        self.running -= 1
        return True

    def running_count(self):
        return self.running


class _RepProbe:
    def __init__(self, queue_depth=0, p99=10.0, breaker="closed",
                 degraded=0):
        self.ready = True
        self._st = {"priorities": {"interactive":
                                   {"queue_depth": queue_depth}},
                    "latency_ms": {"p99": p99},
                    "degraded_batches": degraded,
                    "breaker": {"state": breaker}}

    def stale(self, s):
        return False

    @property
    def last_stats(self):
        return self._st


class _RouterProbe:
    stats_staleness_s = 10.0

    def __init__(self, reps):
        self.replicas = reps


def test_autoscaler_hysteresis_and_cooldown():
    clk = _FakeClock()
    sup = _SupProbe()
    hot = _RouterProbe([_RepProbe(queue_depth=100), _RepProbe()])
    a = Autoscaler(hot, sup, slo_p99_ms=500.0, consecutive=3,
                   cooldown_s=30.0, clock=clk)
    # one spiky evaluation does nothing; the streak must persist
    assert a.evaluate_once() == "hold"
    assert a.evaluate_once() == "hold"
    assert a.evaluate_once() == "scale_up"
    assert sup.ups == 1
    # cooldown: the same raw signal cannot act again yet
    for _ in range(5):
        assert a.evaluate_once() == "hold"
    assert sup.ups == 1
    clk.t += 31.0                       # cooldown over; streak rebuilds
    assert a.evaluate_once() == "hold"
    assert a.evaluate_once() == "hold"
    assert a.evaluate_once() == "scale_up"
    assert sup.ups == 2
    st = a.stats()
    assert st["decisions"]["scale_up"] == 2
    assert st["signals"]["queue_depth"] == 100


def test_autoscaler_scales_down_idle_fleet_and_p99_breach_up():
    clk = _FakeClock()
    sup = _SupProbe()
    idle = _RouterProbe([_RepProbe(queue_depth=0, p99=5.0),
                         _RepProbe(queue_depth=0, p99=5.0)])
    a = Autoscaler(idle, sup, slo_p99_ms=500.0, consecutive=2,
                   cooldown_s=0.0, clock=clk)
    assert a.evaluate_once() == "hold"
    assert a.evaluate_once() == "scale_down"
    assert sup.downs == 1
    # p99 over the SLO is an up signal even with empty queues
    slow = _RouterProbe([_RepProbe(queue_depth=0, p99=900.0)])
    a2 = Autoscaler(slow, sup, slo_p99_ms=500.0, consecutive=1,
                    cooldown_s=0.0, clock=clk)
    assert a2.evaluate_once() == "scale_up"


class _PartSupProbe(_SupProbe):
    """Supervisor probe that also reports partitioned slots."""

    def __init__(self, partitioned=1):
        super().__init__()
        self.partitioned = partitioned

    def stats(self):
        return {"states": {"partitioned": self.partitioned}}


def test_autoscaler_holds_partitioned_capacity():
    clk = _FakeClock()
    sup = _PartSupProbe(partitioned=1)
    hot = _RouterProbe([_RepProbe(queue_depth=100), _RepProbe()])
    a = Autoscaler(hot, sup, slo_p99_ms=500.0, consecutive=2,
                   cooldown_s=30.0, clock=clk)
    assert a.evaluate_once() == "hold"            # streak building
    # streak satisfied, but partitioned capacity still exists on the far
    # side of the partition: the scale-up is REFUSED, not just delayed
    assert a.evaluate_once() == "hold_partitioned"
    assert sup.ups == 0
    # no cooldown was taken — the moment the partition resolves, the
    # already-built streak acts immediately
    sup.partitioned = 0
    assert a.evaluate_once() == "scale_up"
    assert sup.ups == 1
    assert a.stats()["decisions"]["hold_partitioned"] == 1


# -- replica agent: the per-host control plane (ISSUE 20) ---------------------

def _start_agents(n_agents=1, max_replicas=4):
    """In-process agents whose spawn_fn makes real in-process replicas
    (`_Handle` wraps a warmed `ModelServer`); returns (agents, spawned)."""
    spawned = []

    def spawn_fn(argv):
        assert argv and argv[0] == "serve"
        h = _Handle()
        spawned.append(h)
        return h

    agents = [ReplicaAgent(spawn_fn, max_replicas=max_replicas).start()
              for _ in range(n_agents)]
    return agents, spawned


def _stop_agents(agents):
    for a in agents:
        a.stop(terminate_children=True, drain_timeout_s=5.0)


def test_agent_control_plane_spawn_stop_and_clean_errors():
    agents, spawned = _start_agents(max_replicas=1)
    agent = agents[0]
    try:
        client = AgentClient(agent.url, timeout_s=5.0)
        h = client.spawn(["serve"])
        assert h.url and h.poll() is None
        assert h.wait_ready()["url"] == h.url
        assert agent.health()["replicas"] == 1
        # capacity bound: the agent is a bounded nursery, not a fork bomb
        with pytest.raises(RuntimeError, match="409"):
            client.spawn(["serve"])
        # only `serve` argv is accepted — the agent is not a remote shell
        code, text = _http(agent.url + "/a/spawn", {"argv": ["rm", "-rf"]})
        assert code == 400 and "error" in json.loads(text)
        # malformed JSON body -> clean 400, not a handler crash
        req = urllib.request.Request(
            agent.url + "/a/spawn", data=b"{not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 400
        # unknown replica id -> 404
        code, _ = _http(agent.url + "/a/stop", {"id": 99})
        assert code == 404
        # unknown path -> 404 JSON
        code, text = _http(agent.url + "/a/nope")
        assert code == 404 and "error" in json.loads(text)
        # graceful stop reports the drained exit code; the snapshot and
        # the remote handle's poll() see it
        out = client.stop(h.rid, wait=True)
        assert out["exit_code"] == 0
        assert h.poll() == 0
        recs = client.refresh()
        assert [r["alive"] for r in recs] == [False]
        assert agent.health()["replicas"] == 0
        # a vacated slot frees capacity again
        h2 = client.spawn(["serve"])
        assert h2.rid != h.rid
        assert agent.health()["spawns_total"] == 2
    finally:
        _stop_agents(agents)


def test_agent_serves_cache_entries_with_counters(tmp_path):
    (tmp_path / "deadbeef.jxp").write_bytes(b"jxp-bytes")
    agent = ReplicaAgent(lambda argv: _Handle(), cache_dir=str(tmp_path),
                         max_replicas=1).start()
    try:
        code, text = _http(agent.url + "/a/cache/deadbeef.jxp")
        assert code == 200 and text == "jxp-bytes"
        code, _ = _http(agent.url + "/a/cache/cafecafe.jxp")   # absent
        assert code == 404
        code, _ = _http(agent.url + "/a/cache/..%2Fetc%2Fpasswd")
        assert code == 404                                      # bad name
        h = agent.health()
        assert h["cache_requests_total"] == 3
        assert h["cache_hits_total"] == 1
    finally:
        agent.stop()


# -- lease-based remote supervision ------------------------------------------

def _remote_fleet(client, handle, **kw):
    router = Router([handle.url], poll_interval_s=3600.0).start()
    kw.setdefault("backoff_fn", lambda attempt: 0.0)
    sup = FleetSupervisor(spawn_fn=None, router=router, initial=[handle],
                          min_replicas=1, max_replicas=1,
                          agents=[client] if not isinstance(client, list)
                          else client,
                          remote_argv=["serve"], **kw)
    return router, sup


def test_remote_replica_death_respawns_through_agent():
    agents, spawned = _start_agents()
    agent = agents[0]
    router = sup = None
    try:
        client = AgentClient(agent.url, timeout_s=5.0)
        h = client.spawn(["serve"])
        router, sup = _remote_fleet(client, h)
        spawned[0].die(rc=-9)
        sup.tick()        # heartbeat refreshes the snapshot; reap
        st = sup.stats()
        assert st["states"]["backoff"] == 1
        assert st["slots"][0]["last_exit"] == -9
        assert len(router.replicas) == 0
        sup.tick()        # respawn goes THROUGH the agent
        st = sup.stats()
        assert st["states"]["running"] == 1
        assert st["restarts_total"] == 1
        assert st["slots"][0]["agent"] == client.url
        assert agent.health()["spawns_total"] == 2
        assert len(router.replicas) == 1
    finally:
        if sup:
            sup.stop()
        if router:
            router.stop()
        _stop_agents(agents)


def test_lease_partition_holds_slots_then_heal_adopts_no_double_spawn():
    agents, spawned = _start_agents()
    agent = agents[0]
    router = sup = None
    try:
        client = AgentClient(agent.url, timeout_s=5.0)
        h = client.spawn(["serve"])
        router, sup = _remote_fleet(client, h, lease_misses=2,
                                    agent_failover_s=1e9)
        sup.tick()                              # healthy lease
        assert sup.stats()["states"]["running"] == 1
        faults.arm("agent.partition", "raise", times=3)
        sup.tick()                              # miss 1: lease holds
        assert sup.stats()["states"]["running"] == 1
        sup.tick()                              # miss 2: partitioned
        st = sup.stats()
        assert st["states"]["partitioned"] == 1
        assert st["partitions_total"] == 1
        assert len(router.replicas) == 0        # out of rotation...
        sup.tick()                              # miss 3: held, no respawn
        assert sup.stats()["states"]["partitioned"] == 1
        assert agent.health()["spawns_total"] == 1   # ...but NOT respawned
        sup.tick()                              # plan exhausted: heal
        st = sup.stats()
        assert st["states"]["running"] == 1
        assert st["adopted_total"] == 1
        assert len(router.replicas) == 1
        # zero double-spawns: reconcile ADOPTED the live replica
        assert agent.health()["spawns_total"] == 1
        assert agent.health()["replicas"] == 1
        ag = st["agents"][0]
        assert ag["state"] == "leased" and ag["reconciles_total"] == 1
    finally:
        if sup:
            sup.stop()
        if router:
            router.stop()
        _stop_agents(agents)


class _FlakyClient(AgentClient):
    """AgentClient whose heartbeat can be switched off: a partition
    between supervisor and ONE healthy agent, injected per-client."""

    offline = False

    def refresh(self):
        if self.offline:
            raise OSError("injected partition")
        return super().refresh()


def test_partition_failover_lands_on_survivor_then_heal_stops_orphan():
    agents, spawned = _start_agents(n_agents=2)
    a0, a1 = agents
    router = sup = None
    try:
        clients = [_FlakyClient(a.url, timeout_s=5.0) for a in agents]
        clk = _FakeClock()
        h = clients[0].spawn(["serve"])
        router, sup = _remote_fleet(clients, h, lease_misses=1,
                                    agent_failover_s=30.0, clock=clk)
        clients[0].offline = True
        sup.tick()                      # 1 miss -> partitioned, held
        assert sup.stats()["states"]["partitioned"] == 1
        assert len(router.replicas) == 0
        assert a1.health()["spawns_total"] == 0
        clk.t += 31.0
        sup.tick()                      # past failover: respawn on survivor
        st = sup.stats()
        assert st["states"]["running"] == 1
        assert st["failovers_total"] == 1
        assert st["slots"][0]["agent"] == clients[1].url
        assert a1.health()["spawns_total"] == 1
        assert len(router.replicas) == 1
        # partition heals: the old child on agent0 is no longer intended
        # (its slot failed over) — reconcile stops the orphan
        clients[0].offline = False
        sup.tick()
        st = sup.stats()
        ag0 = next(a for a in st["agents"] if a["url"] == clients[0].url)
        assert ag0["state"] == "leased"
        assert ag0["orphans_stopped_total"] == 1
        assert a0.health()["replicas"] == 0
        # intent stayed at one replica: exactly one spawn per agent, ever
        assert a0.health()["spawns_total"] == 1
        assert a1.health()["spawns_total"] == 1
        assert st["states"]["running"] == 1
    finally:
        if sup:
            sup.stop()
        if router:
            router.stop()
        _stop_agents(agents)


# -- compile-cache distribution (serving/cachesync.py) ------------------------

def _warmed_net_with_store(cache_dir, shapes=(1, 2)):
    net = MultiLayerNetwork(mlp(n_in=N_IN, hidden=[8], n_out=N_OUT,
                                lr=0.05), seed=0).init()
    store = net.set_compile_cache(str(cache_dir))
    net.warmup(list(shapes))
    return net, store


def test_cold_store_warms_over_the_wire_and_corrupt_fetch_is_counted(
        tmp_path):
    warm_net, warm_store = _warmed_net_with_store(tmp_path / "warm")
    server = CacheServer(str(tmp_path / "warm")).start()
    try:
        # cold host, clean wire: every program arrives by fetch, zero
        # fresh compiles, and the answers match the warm host bitwise
        cold_net, cold_store = (
            MultiLayerNetwork(mlp(n_in=N_IN, hidden=[8], n_out=N_OUT,
                                  lr=0.05), seed=0).init(), None)
        cold_store = cold_net.set_compile_cache(str(tmp_path / "cold"))
        cold_store.set_remote(CacheFetcher([server.url], timeout_s=5.0))
        cold_net.warmup([1, 2])
        assert cold_store.fetch_hits > 0
        assert cold_store.fetch_corrupt == 0
        x = _x(2, seed=3)
        np.testing.assert_array_equal(np.asarray(cold_net.output(x)),
                                      np.asarray(warm_net.output(x)))
        # corrupted fetch: checksum validation rejects it, counts it,
        # and falls back to compiling — never a crash, never bad bytes
        cold2 = MultiLayerNetwork(mlp(n_in=N_IN, hidden=[8], n_out=N_OUT,
                                      lr=0.05), seed=0).init()
        store2 = cold2.set_compile_cache(str(tmp_path / "cold2"))
        fetcher = CacheFetcher([server.url], timeout_s=5.0)
        store2.set_remote(fetcher)
        faults.arm("agent.cache_fetch", "corrupt", times=1)
        cold2.warmup([1])
        assert store2.fetch_corrupt == 1
        np.testing.assert_array_equal(np.asarray(cold2.output(_x(1))),
                                      np.asarray(warm_net.output(_x(1))))
    finally:
        server.stop()


# -- failure-domain-aware hedging ---------------------------------------------

def test_hedge_and_retry_prefer_a_different_host():
    r1 = Router.__new__(Router)  # only _prefer_other_hosts is exercised
    mk = lambda host: type("R", (), {"host": host})()  # noqa: E731
    a, b, c, d = mk("h1"), mk("h1"), mk("h2"), mk("h2")
    # tail reordered: different-host replicas first, same-host last
    out = Router._prefer_other_hosts([a, b, c, d])
    assert [r.host for r in out] == ["h1", "h2", "h2", "h1"]
    # single-host fleet (or a 2-replica rotation): untouched
    assert Router._prefer_other_hosts([a, b]) == [a, b]
    same = [mk("h1"), mk("h1"), mk("h1")]
    assert Router._prefer_other_hosts(same) == same
    assert r1 is not None


def test_hedge_under_half_open_breaker_counts_probe_outcome_once():
    """Satellite 4: a hedge fired while the primary's breaker is
    HALF_OPEN must count the probe outcome exactly once — the hedge's
    outcome lands on the hedge replica's breaker, the slow probe's own
    success lands on the primary's, and neither double-transitions."""
    servers, router = _start_fleet(n=2, hedge=True, hedge_floor_ms=1.0,
                                   hedge_ceil_ms=50.0)
    try:
        assert router.poll_once() == 2
        primary = router.replicas[0]
        primary.breaker = CircuitBreaker(failure_threshold=3,
                                         reset_timeout_s=0.0,
                                         probe_prob=1.0)
        for _ in range(3):
            primary.breaker.record_failure()
        # reset_timeout 0: tripped, and already reporting HALF_OPEN
        assert primary.breaker.stats()["state"] == "half_open"
        assert primary.breaker.stats()["opens"] == 1
        # reset_timeout 0 + probe_prob 1: the next allow() is a half-open
        # probe, so the primary re-enters rotation exactly as a probe
        faults.arm("router.proxy", "delay", delay_s=0.4, nth=1, times=1)
        code, text = _http(router.url + "/v1/predict",
                           {"features": _x(1, seed=5).tolist()}, timeout=30)
        assert code == 200          # the hedge answered while the probe ran
        st = router.stats()
        assert st["hedges"] == 1 and st["hedge_wins"] == 1
        # the delayed probe eventually completes against its replica
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            bs = primary.breaker.stats()
            if bs["successes"] == 1:
                break
            time.sleep(0.02)
        bs = primary.breaker.stats()
        assert bs["successes"] == 1      # counted exactly once
        assert bs["state"] == "closed"   # probe success closes it...
        assert bs["opens"] == 1          # ...with no second transition
    finally:
        _stop_all(router, servers)


# -- Prometheus conformance ---------------------------------------------------

def test_new_metric_families_parse_and_stay_monotonic():
    handles, router, sup = _fleet_with_supervisor(n=2)
    a = Autoscaler(router, sup, clock=time.monotonic)
    router.attach_fleet(sup, a)
    try:
        a.evaluate_once()
        text1 = router_metrics(router.stats())
        parsed1 = parse_prometheus_text(text1)  # strict: raises on junk
        for fam in ("dl4j_router_hedges_total",
                    "dl4j_router_hedge_wins_total",
                    "dl4j_router_retry_budget_remaining",
                    "dl4j_router_retry_budget_exhausted_total",
                    "dl4j_fleet_restarts_total",
                    "dl4j_fleet_spawn_failures_total"):
            assert fam in parsed1, fam
        states = {dict(lbl)["state"]
                  for lbl in parsed1["dl4j_fleet_replicas"]}
        assert {"running", "backoff", "quarantined", "stopped"} <= states
        assert parsed1["dl4j_fleet_replicas"][(("state", "running"),)] == 2
        decisions = {dict(lbl)["decision"]
                     for lbl in parsed1["dl4j_autoscaler_decisions_total"]}
        assert decisions == {"scale_up", "scale_down", "hold",
                             "hold_partitioned"}
        assert "dl4j_autoscaler_target_replicas" in parsed1
        # traffic + a restart move the counters the right way only
        for i in range(2):
            _http(router.url + "/v1/predict",
                  {"features": _x(1, seed=i).tolist()})
        handles[0].die()
        sup.tick()
        sup.tick()
        a.evaluate_once()
        parsed2 = parse_prometheus_text(router_metrics(router.stats()))
        for fam, series in parsed1.items():
            if not fam.endswith("_total"):
                continue
            for lbl, v1 in series.items():
                v2 = parsed2.get(fam, {}).get(lbl)
                if v2 is not None:
                    assert v2 >= v1, (fam, lbl, v1, v2)
        assert parsed2["dl4j_fleet_restarts_total"][()] == 1
    finally:
        sup.stop()
        router.stop()
        for h in sup.handles():
            h.terminate()


# -- the acceptance smoke: CLI fleet, SIGKILL under load, heal, drain --------

def test_cli_fleet_sigkill_heals_with_warm_cache_and_clean_answers(tmp_path):
    """ISSUE 11 acceptance: SIGKILL one of 2 supervised replicas under
    load -> zero incorrect responses (every client sees a correct
    answer or a clean 5xx), the supervisor restores the fleet with
    fresh_compiles == 0 on the respawn (shared warm disk cache), router
    counters reconcile with client-observed outcomes, SIGTERM drain
    exits 0."""
    net = _net()
    ckpt = str(tmp_path / "model")
    cache = str(tmp_path / "cache")
    checkpoint.save(ckpt, net.params, conf=net.conf)
    x = _x(2, seed=1)
    expected = np.asarray(net.output(x))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "warmup",
         "--model", ckpt, "--compile-cache", cache, "--shapes", "1,2"],
        check=True, capture_output=True, cwd=repo, env=env, timeout=300)
    proc = subprocess.Popen(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "serve",
         "--model", ckpt, "--compile-cache", cache, "--shapes", "1,2",
         "--replicas", "2", "--min-replicas", "2", "--max-replicas", "2",
         "--hedge", "--port", "0", "--max-delay-ms", "2",
         "--drain-timeout", "10"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=repo, env=env)
    try:
        watchdog = threading.Timer(240.0, proc.kill)
        watchdog.start()
        try:
            summary = json.loads(proc.stdout.readline())
        finally:
            watchdog.cancel()
        url = summary["url"]
        assert summary["fresh_compiles"] == [0, 0]
        assert summary["hedge"] is True
        assert len(summary["replica_pids"]) == 2
        victim = summary["replica_pids"][0]

        # open-ish loop: 4 client threads hammer while the kill lands;
        # every answer must be bitwise-correct or a clean JSON 5xx
        outcomes = {"ok": 0, "err5xx": 0, "bad": []}
        lock = threading.Lock()
        stop = threading.Event()

        def client():
            body = {"features": x.tolist()}
            while not stop.is_set():
                try:
                    code, text = _http(url + "/v1/predict", body,
                                       timeout=30)
                except Exception as e:  # noqa: BLE001 — transport drop
                    with lock:
                        outcomes["bad"].append(f"transport: {e}")
                    continue
                if code == 200:
                    out = np.asarray(json.loads(text)["output"])
                    good = np.allclose(out, expected, atol=1e-5)
                    with lock:
                        if good:
                            outcomes["ok"] += 1
                        else:
                            outcomes["bad"].append("wrong output")
                elif 500 <= code < 600:
                    json.loads(text)  # clean structured error, not junk
                    with lock:
                        outcomes["err5xx"] += 1
                else:
                    with lock:
                        outcomes["bad"].append(f"code {code}")

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)                      # load established
        os.kill(victim, signal.SIGKILL)      # chaos
        healed = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                code, text = _http(url + "/v1/stats", timeout=10)
                st = json.loads(text)
            except Exception:  # noqa: BLE001
                time.sleep(0.2)
                continue
            fleet = st.get("fleet", {})
            if (st.get("healthy_replicas", 0) >= 2
                    and fleet.get("restarts_total", 0) >= 1):
                healed = st
                break
            time.sleep(0.2)
        time.sleep(0.3)                      # a little post-heal traffic
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        assert healed is not None, "fleet never healed within 60s"
        assert healed["fleet"]["restarts_total"] >= 1
        # the respawned replica came up from the warm shared disk cache
        respawned = [s for s in healed["fleet"]["slots"]
                     if s["restarts"] >= 1]
        assert respawned and all(s["fresh_compiles"] == 0
                                 for s in respawned), respawned
        # zero incorrect responses, and the clients actually worked
        assert outcomes["bad"] == [], outcomes["bad"][:5]
        assert outcomes["ok"] > 0

        # counters reconcile with client-observed outcomes: every
        # request is either in the ok-latency histogram or unroutable
        code, text = _http(url + "/v1/stats", timeout=10)
        st = json.loads(text)
        ok_count = sum(p["latency_hist_s"]["count"]
                       for p in st["priorities"].values())
        total = sum(p["requests"] for p in st["priorities"].values())
        assert ok_count == outcomes["ok"]
        assert st["unroutable"] == outcomes["err5xx"]
        assert total == ok_count + st["unroutable"]

        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=180)
        assert proc.returncode == 0, (out, err)
        drained = json.loads(out.strip().splitlines()[-1])
        assert drained["drained"] is True
        assert drained["restarts"] >= 1
        assert all(rc == 0 for rc in drained["replica_exit_codes"])
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


def test_cli_multihost_agent_sigkill_and_partition_heal_acceptance(tmp_path):
    """ISSUE 20 acceptance: the fleet lives on two loopback agent
    processes (cold caches, warming over the cachesync wire from the
    control-plane host).  SIGKILL one whole agent mid-load AND inject a
    lease partition (`agent.partition`) on the survivor's poll path.
    Every response is a bitwise-correct 200 or a clean JSON 5xx, the
    failover respawn reaches the survivor with fresh_compiles == 0 and
    cache_fetch_hits > 0 (warmed over the wire, never compiled), the
    reconcile never double-spawns (agent /a/replicas live count ==
    supervisor intent), and SIGTERM drain exits 0."""
    net = _net()
    ckpt = str(tmp_path / "model")
    warm = str(tmp_path / "warm")
    checkpoint.save(ckpt, net.params, conf=net.conf)
    x = _x(2, seed=1)
    expected = np.asarray(net.output(x))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "warmup",
         "--model", ckpt, "--compile-cache", warm, "--shapes", "1,2"],
        check=True, capture_output=True, cwd=repo, env=env, timeout=300)

    def start_agent(name):
        p = subprocess.Popen(
            [sys.executable, "-m", "deeplearning4j_tpu.cli", "agent",
             "--port", "0", "--compile-cache", str(tmp_path / name),
             "--max-replicas", "2"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=repo, env=env)
        watchdog = threading.Timer(120.0, p.kill)
        watchdog.start()
        try:
            startup = json.loads(p.stdout.readline())
        finally:
            watchdog.cancel()
        return p, startup["url"]

    agent_procs = []
    proc = None
    replica_pids = []
    try:
        a1, u1 = start_agent("cache-a")
        agent_procs.append(a1)
        a2, u2 = start_agent("cache-b")
        agent_procs.append(a2)
        # the armed partition plan lives in the SERVE process: the fault
        # point fires twice per supervisor tick (once per agent), so
        # hits 61..72 partition the survivor for ~6 consecutive beats a
        # few seconds into the run — long enough to trip the lease
        # (3 misses), short enough to heal before the failover deadline
        proc = subprocess.Popen(
            [sys.executable, "-m", "deeplearning4j_tpu.cli", "serve",
             "--model", ckpt, "--compile-cache", warm, "--shapes", "1,2",
             "--replicas", "2", "--min-replicas", "2",
             "--max-replicas", "2", "--agent", u1, "--agent", u2,
             "--agent-failover", "4", "--port", "0",
             "--max-delay-ms", "2", "--drain-timeout", "10"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=repo,
            env={**env, "DL4J_FAULT_PLAN": "agent.partition=raise@61x12"})
        watchdog = threading.Timer(240.0, proc.kill)
        watchdog.start()
        try:
            summary = json.loads(proc.stdout.readline())
        finally:
            watchdog.cancel()
        url = summary["url"]
        replica_pids = list(summary["replica_pids"])
        assert summary["agents"] == [u1, u2]
        # both initial replicas warmed over the wire from the control
        # plane's cache server: cold agent disks, zero fresh compiles
        assert summary["fresh_compiles"] == [0, 0]

        outcomes = {"ok": 0, "err5xx": 0, "bad": []}
        lock = threading.Lock()
        stop = threading.Event()

        def client():
            body = {"features": x.tolist()}
            while not stop.is_set():
                try:
                    code, text = _http(url + "/v1/predict", body,
                                       timeout=30)
                except Exception as e:  # noqa: BLE001 — transport drop
                    with lock:
                        outcomes["bad"].append(f"transport: {e}")
                    continue
                if code == 200:
                    out = np.asarray(json.loads(text)["output"])
                    good = np.allclose(out, expected, atol=1e-5)
                    with lock:
                        if good:
                            outcomes["ok"] += 1
                        else:
                            outcomes["bad"].append("wrong output")
                elif 500 <= code < 600:
                    json.loads(text)  # clean structured error, not junk
                    with lock:
                        outcomes["err5xx"] += 1
                else:
                    with lock:
                        outcomes["bad"].append(f"code {code}")

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.5)                      # load established
        a1.kill()                            # chaos 1: a whole host dies
        healed = None
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            try:
                code, text = _http(url + "/v1/stats", timeout=10)
                st = json.loads(text)
            except Exception:  # noqa: BLE001
                time.sleep(0.2)
                continue
            fleet = st.get("fleet", {})
            survivor = next((a for a in fleet.get("agents", [])
                             if a["url"] == u2), {})
            if (st.get("healthy_replicas", 0) >= 2
                    and fleet.get("failovers_total", 0) >= 1
                    and survivor.get("partitions_total", 0) >= 1
                    and survivor.get("state") == "leased"):
                healed = st
                break
            time.sleep(0.2)
        time.sleep(0.5)                      # post-heal traffic
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        assert healed is not None, \
            "fleet never healed from SIGKILL + partition within 120s"
        fleet = healed["fleet"]
        # chaos 2 (the armed plan) really fired AND healed: the survivor
        # was partitioned, re-leased, and reconciled its replicas back
        survivor = next(a for a in fleet["agents"] if a["url"] == u2)
        assert survivor["reconciles_total"] >= 1
        # the failover respawn warmed over the cachesync wire on the
        # cold surviving host: fetched, never compiled
        respawned = [s for s in fleet["slots"] if s["restarts"] >= 1]
        assert respawned, fleet["slots"]
        assert all(s["fresh_compiles"] == 0 for s in respawned), respawned
        assert all(s["cache_fetch_hits"] > 0 for s in respawned), respawned
        # zero double-spawns after reconcile: the survivor's ACTUAL live
        # replica count equals the supervisor's intent
        running = [s for s in fleet["slots"] if s["state"] == "running"]
        assert len(running) == 2
        assert all(s["agent"] == u2 for s in running), running
        code, text = _http(u2 + "/a/replicas", timeout=10)
        assert code == 200
        live = [r for r in json.loads(text)["replicas"] if r["alive"]]
        assert len(live) == len(running) == 2
        # every client saw a bitwise-correct answer or a clean 5xx
        assert outcomes["bad"] == [], outcomes["bad"][:5]
        assert outcomes["ok"] > 0

        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=180)
        assert proc.returncode == 0, (out, err)
        drained = json.loads(out.strip().splitlines()[-1])
        assert drained["drained"] is True
        assert all(rc == 0 for rc in drained["replica_exit_codes"])
        proc = None
        # the surviving agent drains cleanly too
        a2.send_signal(signal.SIGTERM)
        out2, err2 = a2.communicate(timeout=60)
        assert a2.returncode == 0, (out2, err2)
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.communicate()
        for p in agent_procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
        # the SIGKILLed agent's replica child outlives its parent: reap
        # it so nothing leaks past the test
        for pid in replica_pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
