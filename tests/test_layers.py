"""Layer forward/backward on fixed seeds (ref: RBMTests, LSTMTest, conv tests)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf import (
    LayerType, NeuralNetConfiguration, PoolingType, RBMUnit,
)
from deeplearning4j_tpu.nn.layers import get_layer
from deeplearning4j_tpu.nn.layers.autoencoder import AutoEncoder
from deeplearning4j_tpu.nn.layers.conv import ConvolutionLayer, SubsamplingLayer, pool2d
from deeplearning4j_tpu.nn.layers.lstm import LSTMLayer
from deeplearning4j_tpu.nn.layers.rbm import RBM

KEY = jax.random.PRNGKey(42)


def test_dense_forward_matches_manual():
    conf = NeuralNetConfiguration(n_in=3, n_out=2, activation="sigmoid")
    dense = get_layer(LayerType.DENSE)
    p = dense.init(KEY, conf)
    x = jnp.array([[1.0, 2.0, 3.0]])
    out = dense.forward(p, conf, x)
    manual = 1 / (1 + np.exp(-(np.asarray(x) @ np.asarray(p["W"]) + np.asarray(p["b"]))))
    np.testing.assert_allclose(out, manual, rtol=1e-5)


def test_output_layer_softmax_rows_sum_to_one():
    conf = NeuralNetConfiguration(layer_type=LayerType.OUTPUT, n_in=5, n_out=3)
    out_l = get_layer(LayerType.OUTPUT)
    p = out_l.init(KEY, conf)
    y = out_l.forward(p, conf, jax.random.normal(KEY, (7, 5)))
    np.testing.assert_allclose(np.asarray(y).sum(-1), np.ones(7), rtol=1e-5)


def test_autoencoder_pretrain_reduces_loss():
    conf = NeuralNetConfiguration(
        layer_type=LayerType.AUTOENCODER, n_in=10, n_out=6,
        corruption_level=0.0, lr=0.5, use_adagrad=False, momentum=0.0)
    p = AutoEncoder.init(KEY, conf)
    x = jax.random.uniform(KEY, (20, 10))
    k = jax.random.PRNGKey(0)
    g, s0 = AutoEncoder.pretrain_grad_and_score(p, conf, x, k)
    for _ in range(50):
        g, _ = AutoEncoder.pretrain_grad_and_score(p, conf, x, k)
        p = jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g)
    _, s1 = AutoEncoder.pretrain_grad_and_score(p, conf, x, k)
    assert float(s1) < float(s0)


def test_rbm_cd1_reduces_reconstruction_error():
    conf = NeuralNetConfiguration(
        layer_type=LayerType.RBM, n_in=12, n_out=8, k=1, lr=0.1)
    p = RBM.init(KEY, conf)
    x = (jax.random.uniform(KEY, (30, 12)) > 0.5).astype(jnp.float32)
    k = jax.random.PRNGKey(1)
    _, s0 = RBM.pretrain_grad_and_score(p, conf, x, k)
    for i in range(60):
        ki = jax.random.fold_in(k, i)
        g, _ = RBM.pretrain_grad_and_score(p, conf, x, ki)
        p = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)
    _, s1 = RBM.pretrain_grad_and_score(p, conf, x, k)
    assert float(s1) < float(s0)


def test_rbm_unit_types_all_finite():
    for vu in RBMUnit:
        for hu in RBMUnit:
            conf = NeuralNetConfiguration(
                layer_type=LayerType.RBM, n_in=6, n_out=4, k=1,
                visible_unit=vu, hidden_unit=hu)
            p = RBM.init(KEY, conf)
            x = jax.random.uniform(KEY, (5, 6))
            g, s = RBM.pretrain_grad_and_score(p, conf, x, jax.random.PRNGKey(2))
            assert np.isfinite(float(s)), (vu, hu)
            for leaf in jax.tree_util.tree_leaves(g):
                assert np.all(np.isfinite(np.asarray(leaf))), (vu, hu)


def test_lstm_shapes_and_grad():
    conf = NeuralNetConfiguration(layer_type=LayerType.LSTM, n_in=5, n_out=7)
    p = LSTMLayer.init(KEY, conf)
    x = jax.random.normal(KEY, (3, 11, 5))
    h = LSTMLayer.forward(p, conf, x)
    assert h.shape == (3, 11, 7)
    # single sequence (reference shape) works too
    h1 = LSTMLayer.forward(p, conf, x[0])
    # contraction order differs between batched and single-sequence matmuls,
    # so agreement is approximate in float32
    np.testing.assert_allclose(h1, h[0], rtol=0.2, atol=3e-3)
    # BPTT via jax.grad is finite
    g = jax.grad(lambda pp: jnp.sum(LSTMLayer.forward(pp, conf, x) ** 2))(p)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_conv_and_pooling_shapes():
    conf = NeuralNetConfiguration(
        layer_type=LayerType.CONVOLUTION, n_out=6, n_channels=1,
        kernel_size=(5, 5), activation="relu")
    p = ConvolutionLayer.init(KEY, conf)
    x = jax.random.normal(KEY, (2, 1, 28, 28))
    y = ConvolutionLayer.forward(p, conf, x)
    assert y.shape == (2, 6, 24, 24)
    # pooling modes (Transforms.maxPool/avgPooling/sumPooling parity)
    z = pool2d(y, PoolingType.MAX, (2, 2))
    assert z.shape == (2, 6, 12, 12)
    s = pool2d(jnp.ones((1, 1, 4, 4)), PoolingType.SUM, (2, 2))
    np.testing.assert_allclose(s, 4 * np.ones((1, 1, 2, 2)))
    a = pool2d(jnp.ones((1, 1, 4, 4)), PoolingType.AVG, (2, 2))
    np.testing.assert_allclose(a, np.ones((1, 1, 2, 2)))


def test_subsampling_layer():
    conf = NeuralNetConfiguration(
        layer_type=LayerType.SUBSAMPLING, kernel_size=(2, 2), stride=(2, 2),
        pooling=PoolingType.MAX)
    x = jnp.arange(16.0).reshape(1, 1, 4, 4)
    y = SubsamplingLayer.forward({}, conf, x)
    np.testing.assert_allclose(y[0, 0], [[5.0, 7.0], [13.0, 15.0]])


def test_batchnorm_nchw_channel_axis():
    """BatchNorm after conv normalizes per channel (NCHW), not per column."""
    import jax, jax.numpy as jnp, numpy as np
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, LayerType
    from deeplearning4j_tpu.nn.layers.base import BatchNormLayer

    conf = NeuralNetConfiguration(layer_type=LayerType.BATCH_NORM, n_in=3,
                                  n_out=3)
    p = BatchNormLayer.init(jax.random.PRNGKey(0), conf)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 3, 5, 5),
                    jnp.float32)
    y = BatchNormLayer.forward(p, conf, x, training=True)
    assert y.shape == x.shape
    # per-channel stats ~ (0, 1)
    m = np.asarray(jnp.mean(y, axis=(0, 2, 3)))
    v = np.asarray(jnp.var(y, axis=(0, 2, 3)))
    np.testing.assert_allclose(m, 0.0, atol=1e-5)
    np.testing.assert_allclose(v, 1.0, atol=1e-4)


def test_vgg_cifar_forward_shape():
    import jax, jax.numpy as jnp
    from deeplearning4j_tpu.models.zoo import vgg_cifar10
    from deeplearning4j_tpu.nn.multilayer import init_params, network_output

    conf = vgg_cifar10(width=8)
    params = init_params(conf, jax.random.PRNGKey(0))
    x = jnp.zeros((2, 3 * 32 * 32), jnp.float32)
    out = network_output(conf, params, x)
    assert out.shape == (2, 10)


def test_mixed_precision_compute_dtype():
    """bf16 compute with f32 params: outputs close to full f32, params f32."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nn.conf import LayerType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import get_layer

    conf = NeuralNetConfiguration(layer_type=LayerType.DENSE, n_in=32,
                                  n_out=16, activation="tanh")
    layer = get_layer(conf.layer_type)
    params = layer.init(jax.random.PRNGKey(0), conf)
    assert params["W"].dtype == jnp.float32
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32))
    y32 = layer.forward(params, conf, x)
    y16 = layer.forward(params, conf.replace(compute_dtype="bfloat16"), x)
    assert y16.dtype == jnp.float32  # cast back to the param dtype
    np.testing.assert_allclose(np.asarray(y16), np.asarray(y32),
                               rtol=2e-2, atol=2e-2)


def test_remat_matches_no_remat_loss_and_grads():
    """conf.remat wraps a layer in jax.checkpoint — backward recomputes
    activations but loss and gradients must be bitwise-identical to the
    stored-activation path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.models.zoo import char_transformer
    from deeplearning4j_tpu.nn.multilayer import (init_params,
                                                  network_rowwise_loss)

    conf = char_transformer(17, d_model=32, n_blocks=2, n_heads=4,
                            max_seq_len=8)
    conf_r = conf.replace(confs=tuple(c.replace(remat=True)
                                      for c in conf.confs))
    params = init_params(conf, jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.RandomState(0).randint(0, 17, (3, 8)),
                    jnp.int32)
    y = jnp.asarray(np.eye(17, dtype=np.float32)[
        np.random.RandomState(1).randint(0, 17, 24)])

    def loss(c):
        return lambda p: jnp.mean(network_rowwise_loss(c, p, x, y,
                                                       training=True))

    l0, g0 = jax.value_and_grad(loss(conf))(params)
    l1, g1 = jax.value_and_grad(loss(conf_r))(params)
    assert float(l0) == float(l1)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lstm_hoisted_scan_matches_stepwise():
    """The scan path hoists the input gate projection out of the loop
    (x@Wx once, h@Wh per step); it must match the naive per-step
    concat([x,h])@W recurrence to fp tolerance."""
    conf = NeuralNetConfiguration(layer_type=LayerType.LSTM, n_in=6, n_out=5,
                                  lstm_impl="scan")
    p = LSTMLayer.init(KEY, conf)
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 9, 6))
    out = LSTMLayer.forward(p, conf, x)

    h = jnp.zeros((3, 5))
    c = jnp.zeros((3, 5))
    naive = []
    for t in range(9):
        (h, c), _ = LSTMLayer._step(p, 5, (h, c), x[:, t, :])
        naive.append(h)
    naive = jnp.stack(naive, axis=1)
    assert jnp.allclose(out, naive, atol=1e-5)


def test_graves_lstm_peepholes_train_and_differ():
    """GRAVES_LSTM = LSTM + peephole connections (VERDICT r2 weak #7): at
    zero-init it matches the plain LSTM exactly; training moves the
    peephole weights, after which outputs diverge."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nn.conf import LayerType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import get_layer
    from deeplearning4j_tpu.nn.layers.lstm import GravesLSTMLayer, LSTMLayer

    assert get_layer(LayerType.GRAVES_LSTM) is GravesLSTMLayer
    conf = NeuralNetConfiguration(layer_type=LayerType.GRAVES_LSTM, n_in=6,
                                  n_out=8, lstm_impl="scan")
    params = GravesLSTMLayer.init(jax.random.PRNGKey(0), conf)
    assert set(params) == {"W", "b", "p_i", "p_f", "p_o"}
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 5, 6))
    # zero peepholes -> identical to the plain cell with the same W/b
    y_g = GravesLSTMLayer.forward(params, conf, x)
    y_p = LSTMLayer.forward({"W": params["W"], "b": params["b"]},
                            conf.replace(layer_type=LayerType.LSTM), x)
    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_p), atol=1e-6)

    # gradients reach the peephole weights (they train, not decoration)
    def loss(p):
        return jnp.sum(GravesLSTMLayer.forward(p, conf, x) ** 2)

    g = jax.grad(loss)(params)
    assert float(jnp.abs(g["p_i"]).sum()) > 0
    assert float(jnp.abs(g["p_o"]).sum()) > 0
    # non-zero peepholes change the output
    params2 = dict(params, p_o=jnp.ones_like(params["p_o"]))
    y2 = GravesLSTMLayer.forward(params2, conf, x)
    assert not np.allclose(np.asarray(y2), np.asarray(y_g))


def test_output_layer_f1_score_and_network_f1():
    """OutputLayer.score(examples, labels) = Evaluation F1
    (ref OutputLayer.java:183-188), plus the network-level surface."""
    import jax
    import numpy as np

    from deeplearning4j_tpu.models.zoo import mlp
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    rng = np.random.RandomState(0)
    x = rng.randn(60, 4).astype(np.float32)
    y_idx = (x[:, 0] > 0).astype(int)
    y = np.eye(3, dtype=np.float32)[y_idx]
    conf = mlp(4, [16], 3, lr=0.5)
    conf = conf.replace(confs=tuple(c.replace(num_iterations=60)
                                    for c in conf.confs))
    net = MultiLayerNetwork(conf, seed=0).init()
    f1_before = net.f1_score(x, y)
    net.fit(x, y)
    f1_after = net.f1_score(x, y)
    assert 0.0 <= f1_before <= 1.0 and 0.0 <= f1_after <= 1.0
    assert f1_after > 0.9 > f1_before or f1_after >= f1_before
    # layer-level call agrees with the network-level one on the last layer
    acts = net.feed_forward(x)
    h = np.asarray(acts[-2]) if len(acts) > 1 else x
    lf1 = OutputLayer.score(net.params[-1], conf.conf(conf.n_layers - 1),
                            h, y)
    assert abs(lf1 - f1_after) < 1e-6
