"""Serve-path AOT cache + prefetch pipeline: compile-once semantics,
bucketed-pad bit-exactness, iterator evaluation, async prefetch behavior,
cached Hessian-free parity, and the iterator num=0 regression."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (ListDataSetIterator,
                                                  PrefetchIterator)
from deeplearning4j_tpu.evaluation import Evaluation, evaluate
from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.conf import (LayerType, NeuralNetConfiguration,
                                        OptimizationAlgorithm, list_builder)
from deeplearning4j_tpu.nn.multilayer import (MultiLayerNetwork,
                                              network_output)


def _data(n, n_in=6, n_out=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.randint(0, n_out, n)]
    return x, y


def _net(seed=0, iters=2):
    conf = mlp(n_in=6, hidden=[8], n_out=3, lr=0.05)
    conf = conf.replace(confs=tuple(c.replace(num_iterations=iters)
                                    for c in conf.confs))
    return MultiLayerNetwork(conf, seed=seed).init()


# -- compile-once semantics (acceptance criterion) --------------------------

def test_repeated_output_compiles_once():
    net = _net()
    x, _ = _data(16)
    outs = [np.asarray(net.output(x)) for _ in range(5)]
    st = net.infer_cache.stats
    assert st.misses == 1, st
    assert st.hits == 4, st
    for o in outs[1:]:
        np.testing.assert_array_equal(outs[0], o)


def test_output_and_score_miss_once_per_entry_point():
    net = _net()
    x, y = _data(16)
    for _ in range(3):
        net.output(x)
        net.score(x, y)
    st = net.infer_cache.stats
    assert st.misses == 2, st          # one per entry point (output, loss)
    assert st.hits == 4, st
    assert len(net.infer_cache) == 2


def test_training_between_serves_does_not_retrace():
    """Params are jit ARGUMENTS: fit() between output() calls must hit."""
    net = _net()
    x, y = _data(16)
    net.output(x)
    net.fit(x, y)
    net.output(x)
    st = net.infer_cache.stats
    assert st.misses == 1 and st.hits == 1, st


def test_feed_forward_cached_matches_legacy():
    net = _net()
    x, _ = _data(12)
    cached = net.feed_forward(x)
    net.use_infer_cache = False
    legacy = net.feed_forward(x)
    net.use_infer_cache = True
    assert len(cached) == len(legacy)
    for c, l in zip(cached, legacy):
        np.testing.assert_array_equal(np.asarray(c), np.asarray(l))
    assert net.infer_cache.stats.misses == 1


def test_unbatched_input_falls_back_to_legacy():
    net = _net()
    x, _ = _data(1)
    out = net.output(x[0])             # 1-D input: no row axis to bucket
    assert np.asarray(out).shape == (3,)
    assert len(net.infer_cache) == 0


def test_infer_cache_never_donates():
    from deeplearning4j_tpu.optimize.infer_cache import InferCache

    assert InferCache(donate=True)._donate_argnums() == ()


# -- bucketed padding bit-exactness (acceptance criterion) ------------------

def test_padded_tail_output_bitexact_vs_unpadded():
    """A 10-row tail padded into the 16-bucket must produce bit-identical
    activations for the real rows (inference is row-independent)."""
    net = _net()
    x, _ = _data(16)
    net.output(x)                       # seed the 16 bucket
    tail = x[:10]
    padded = np.asarray(net.output(tail))
    assert net.infer_cache.stats.misses == 1  # tail reused the 16 program
    unpadded = np.asarray(network_output(net.conf, net.params,
                                         jnp.asarray(tail)))
    np.testing.assert_array_equal(padded, unpadded)


def test_padded_tail_score_bitexact_vs_unpadded():
    """Pad rows carry weight 0 and the mean is a gemm contraction, so the
    bucket-padded score equals the exactly-shaped score bit-for-bit."""
    net = _net()
    x, y = _data(16)
    net.score(x, y)                     # seed the 16 bucket
    padded = net.score(x[:10], y[:10])
    assert net.infer_cache.stats.misses == 1

    fresh = _net()                      # same seed: identical params
    unpadded = fresh.score(x[:10], y[:10])  # its own exact 10-row bucket
    assert padded == unpadded           # f32 bit-for-bit


def test_bucketed_evaluate_matches_single_call():
    x, y = _data(50)
    net = _net()
    whole = Evaluation()
    whole.eval(y, np.asarray(net.output(x)))

    bucketed = evaluate(net, DataSet(x, y), batch_size=16)
    assert bucketed.accuracy() == whole.accuracy()
    assert bucketed.f1() == whole.f1()
    np.testing.assert_array_equal(bucketed.confusion.to_array(),
                                  whole.confusion.to_array())
    # 50 rows @ 16 = three full batches + a 2-row tail padded into the
    # 16 bucket: ONE output program total
    assert net.infer_cache.stats.misses == 1


def test_net_evaluate_wraps_arrays_and_iterators():
    x, y = _data(30)
    net = _net()
    ev_arrays = net.evaluate(x, y, batch_size=8, prefetch=False)
    ev_iter = net.evaluate(ListDataSetIterator(DataSet(x, y), 8))
    assert ev_arrays.accuracy() == ev_iter.accuracy()


# -- prefetch pipeline (acceptance criterion: ordering, errors, shutdown) ---

def _batches(n_batches=4, rows=8):
    return [DataSet(*_data(rows, seed=i)) for i in range(n_batches)]


def test_prefetch_preserves_order_and_values():
    data = _batches()
    served = list(PrefetchIterator(data, to_device=False))
    assert len(served) == len(data)
    for d, s in zip(data, served):
        np.testing.assert_array_equal(d.features, s.features)
        np.testing.assert_array_equal(d.labels, s.labels)


def test_prefetch_device_put_yields_device_batches():
    served = list(PrefetchIterator(_batches(2)))
    for s in served:
        assert isinstance(s.features, jax.Array)
        assert s.num_examples() == 8


def test_prefetch_propagates_worker_exception_in_order():
    def gen():
        yield DataSet(*_data(4, seed=0))
        yield DataSet(*_data(4, seed=1))
        raise RuntimeError("source went away")

    it = PrefetchIterator(gen(), to_device=False)
    served = []
    with pytest.raises(RuntimeError, match="source went away"):
        for d in it:
            served.append(d)
    assert len(served) == 2             # batches before the error still serve
    assert it._thread is None           # worker joined by the finally-close


def test_prefetch_early_break_shuts_down_without_deadlock():
    it = PrefetchIterator(_batches(50), buffer_batches=1, to_device=False)
    for i, _ in enumerate(it):
        if i == 1:
            break                       # generator finalization -> close()
    t0 = time.perf_counter()
    it.close()                          # idempotent; must not hang
    assert time.perf_counter() - t0 < 5.0
    assert it._thread is None


def test_prefetch_restarts_after_exhaustion():
    base = ListDataSetIterator(DataSet(*_data(20)), 8)
    it = PrefetchIterator(base, to_device=False)
    first = [d.num_examples() for d in it]
    second = [d.num_examples() for d in it]   # close() + base.reset()
    assert first == second == [8, 8, 4]


def test_fit_accepts_prefetch_iterator():
    net = _net()
    data = _batches(3, rows=8)
    net.fit(PrefetchIterator(data))
    assert net.step_cache.stats.steps == 3
    assert net.step_cache.stats.misses == 1   # equal shapes: one program


# -- cached Hessian-free (satellite) ----------------------------------------

def _hf_net(seed=3):
    base = NeuralNetConfiguration(
        optimization_algo=OptimizationAlgorithm.HESSIAN_FREE,
        activation="tanh", num_iterations=4, lr=0.1, seed=seed,
        hf_cg_iterations=8)
    conf = (list_builder(base, 2).hidden_layer_sizes([8], n_in=6, n_out=3)
            .override(1, layer_type=LayerType.OUTPUT).build())
    return MultiLayerNetwork(conf, seed=seed).init()


def test_hf_cached_matches_legacy_numerics():
    x, y = _data(16, seed=5)
    cached, legacy = _hf_net(), _hf_net()
    legacy.use_step_cache = False
    cached.fit(x, y)
    legacy.fit(x, y)
    assert cached.step_cache.stats.misses == 1
    assert legacy.step_cache.stats.steps == 0
    for pc, pl in zip(cached.params, legacy.params):
        for k in pc:
            np.testing.assert_allclose(np.asarray(pc[k]), np.asarray(pl[k]),
                                       rtol=1e-5, atol=1e-6)


def test_hf_padded_tail_reuses_bucket_and_trains():
    net = _hf_net()
    x, y = _data(16, seed=5)
    before = net.score(x, y)
    net.fit(x, y)                       # seeds the 16 bucket
    net.fit(x[:11], y[:11])             # ragged tail pads into it
    assert net.step_cache.stats.misses == 1
    assert net.step_cache.stats.steps == 2
    assert net.score(x, y) < before


# -- iterator regressions (satellite) ---------------------------------------

def test_list_iterator_next_zero_returns_empty_batch():
    it = ListDataSetIterator(DataSet(*_data(10)), 4)
    empty = it.next(0)                  # falsy num must NOT mean "full batch"
    assert empty.num_examples() == 0
    assert it.cursor == 0
    assert it.next().num_examples() == 4


def test_list_iterator_ragged_tail_reports_true_length():
    it = ListDataSetIterator(DataSet(*_data(10)), 4)
    sizes = [it.next().num_examples() for _ in range(3)]
    assert sizes == [4, 4, 2]
    assert it.cursor == 10              # advanced by rows served, not by 12
    assert not it.has_next()
