"""End-to-end: MLP on Iris, pretrain+finetune DBN, LeNet on MNIST-like data.

Parity with reference `MultiLayerTest.java:55-110` (DBN on Iris with the
conf-override pattern) and the eval tests.
"""

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.fetchers import (
    IrisDataFetcher, MnistDataFetcher, iris_iterator,
)
from deeplearning4j_tpu.evaluation import Evaluation
from deeplearning4j_tpu.nd.losses import LossFunction
from deeplearning4j_tpu.nn.conf import (
    LayerType, NeuralNetConfiguration, OptimizationAlgorithm, PoolingType,
    list_builder,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _iris_mlp_conf(algo=OptimizationAlgorithm.CONJUGATE_GRADIENT, iters=60):
    base = NeuralNetConfiguration(
        activation="tanh", weight_init="vi", lr=0.1,
        optimization_algo=algo, num_iterations=iters, use_adagrad=True,
        seed=42)
    return (list_builder(base, 2)
            .hidden_layer_sizes([10], n_in=4, n_out=3)
            .override(1, layer_type=LayerType.OUTPUT,
                      loss_function=LossFunction.MCXENT)
            .build())


def test_mlp_learns_iris():
    data = IrisDataFetcher().fetch(150).normalize_zero_mean_unit_variance()
    net = MultiLayerNetwork(_iris_mlp_conf()).init()
    s0 = net.score(data.features, data.labels)
    net.fit(data.features, data.labels)
    s1 = net.score(data.features, data.labels)
    assert s1 < s0
    ev = Evaluation()
    ev.eval(data.labels, net.output(data.features))
    assert ev.accuracy() > 0.9, ev.stats()


def test_mlp_iris_iterator_and_sgd():
    conf = _iris_mlp_conf(OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT, 20)
    net = MultiLayerNetwork(conf).init()
    it = iris_iterator(batch_size=50, num_examples=150)
    for _ in range(3):
        it.reset()
        net.fit(it)
    ev = Evaluation()
    data = IrisDataFetcher().fetch(150)
    ev.eval(data.labels, net.output(data.features))
    assert ev.accuracy() > 0.7, ev.stats()


def test_dbn_pretrain_then_finetune():
    """RBM-stack DBN (ref MultiLayerTest DBN-on-Iris pattern)."""
    base = NeuralNetConfiguration(
        layer_type=LayerType.RBM, activation="sigmoid", lr=0.05,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        num_iterations=30, k=1, seed=7)
    conf = (list_builder(base, 3)
            .hidden_layer_sizes([12, 8], n_in=4, n_out=3)
            .override(2, layer_type=LayerType.OUTPUT,
                      loss_function=LossFunction.MCXENT, lr=0.1,
                      optimization_algo=OptimizationAlgorithm.CONJUGATE_GRADIENT,
                      num_iterations=60)
            .pretrain(True).backprop(True)
            .build())
    data = IrisDataFetcher().fetch(150)
    # scale features into [0,1] for the binary RBM visible units
    f = data.features
    f = (f - f.min(0)) / (f.max(0) - f.min(0) + 1e-6)
    net = MultiLayerNetwork(conf).init()
    net.fit(f, data.labels)
    ev = Evaluation()
    ev.eval(data.labels, net.output(f))
    assert ev.accuracy() > 0.85, ev.stats()


def test_lenet_on_mnist_like_data():
    """Conv -> pool -> conv -> pool -> dense -> output (LeNet shape)."""
    base = NeuralNetConfiguration(
        activation="relu", lr=0.02, use_adagrad=True, momentum=0.0,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        num_iterations=40, seed=3)
    conf = (list_builder(base, 6)
            .override(0, layer_type=LayerType.CONVOLUTION, n_channels=1,
                      n_out=6, kernel_size=(5, 5))
            .override(1, layer_type=LayerType.SUBSAMPLING, kernel_size=(2, 2),
                      stride=(2, 2), pooling=PoolingType.MAX)
            .override(2, layer_type=LayerType.CONVOLUTION, n_channels=6,
                      n_out=16, kernel_size=(5, 5))
            .override(3, layer_type=LayerType.SUBSAMPLING, kernel_size=(2, 2),
                      stride=(2, 2), pooling=PoolingType.MAX)
            .override(4, layer_type=LayerType.DENSE, n_in=16 * 4 * 4, n_out=84,
                      activation="tanh")
            .override(5, layer_type=LayerType.OUTPUT, n_in=84, n_out=10,
                      loss_function=LossFunction.MCXENT)
            .input_preprocessor(0, "ff_to_conv:1:28:28")
            .input_preprocessor(4, "conv_to_ff")
            .build())
    data = MnistDataFetcher(binarize=False).fetch(256)
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(data.features, data.labels)
    for _ in range(2):
        net.fit(data.features, data.labels)
    s1 = net.score(data.features, data.labels)
    assert s1 < s0
    ev = Evaluation()
    ev.eval(data.labels, net.output(data.features))
    assert ev.accuracy() > 0.5, ev.stats()


def test_params_flat_roundtrip():
    net = MultiLayerNetwork(_iris_mlp_conf()).init()
    flat = net.params_flat()
    net2 = MultiLayerNetwork(_iris_mlp_conf()).init()
    net2.set_params_flat(flat)
    x = np.random.RandomState(0).rand(5, 4).astype(np.float32)
    np.testing.assert_allclose(net.output(x), net2.output(x), rtol=1e-6)


def test_evaluation_metrics():
    ev = Evaluation()
    y = np.eye(3)[[0, 1, 2, 0, 1, 2]]
    p = np.eye(3)[[0, 1, 2, 0, 2, 2]]  # one mistake: actual 1 predicted 2
    ev.eval(y, p)
    assert ev.accuracy() == 5 / 6
    assert ev.confusion.count(1, 2) == 1
    assert 0 < ev.f1() <= 1


def test_dbn_zoo_config_trains_iris():
    """`zoo.dbn` — the reference's flagship DBN workflow as a one-call
    config: RBM-stack pretrain (CD-k) then CG finetune on Iris."""
    from deeplearning4j_tpu.models.zoo import dbn

    conf = dbn(4, [12, 8], 3, iterations=30, finetune_iterations=60)
    assert conf.pretrain and conf.backprop
    data = IrisDataFetcher().fetch(150)
    f = data.features
    f = (f - f.min(0)) / (f.max(0) - f.min(0) + 1e-6)
    net = MultiLayerNetwork(conf, seed=1).init()
    net.fit(f, data.labels)
    ev = Evaluation()
    ev.eval(data.labels, net.output(f))
    assert ev.accuracy() > 0.85, ev.stats()


def test_deep_autoencoder_zoo_on_curves(monkeypatch):
    """`zoo.deep_autoencoder` + `fit_deep_autoencoder` — the reference's
    Curves workflow, Hinton recipe: denoising-AE stack pretrain, decoder
    UNROLLED from the pretrained encoder (W.T/vb), reconstruction
    finetune; the trained net reconstructs far better than at init."""
    import numpy as np

    from deeplearning4j_tpu.datasets.fetchers import CurvesDataFetcher
    from deeplearning4j_tpu.models.zoo import (deep_autoencoder,
                                               fit_deep_autoencoder)

    # thresholds below are calibrated on the synthetic curves — don't let
    # a machine-local real corpus (CURVES_DIR) change the data under them
    monkeypatch.delenv("CURVES_DIR", raising=False)
    monkeypatch.delenv("DL4J_CURVES_URL", raising=False)
    data = CurvesDataFetcher().fetch(120)
    conf = deep_autoencoder(784, hidden=(64,), iterations=20,
                            finetune_iterations=100, lr=0.1)
    assert conf.pretrain and conf.backprop
    net = MultiLayerNetwork(conf, seed=1).init()
    recon0 = np.asarray(net.output(data.features))
    mse0 = float(np.mean((recon0 - data.features) ** 2))
    fit_deep_autoencoder(net, data.features)
    recon = np.asarray(net.output(data.features))
    assert recon.shape == data.features.shape
    mse = float(np.mean((recon - data.features) ** 2))
    var = float(np.var(data.features))
    # reconstruction beats the mean-predictor baseline (variance) by a
    # wide margin and vastly improves on the untrained net; the xent
    # SCORE has an entropy floor with soft [0,1] targets, so MSE is the
    # honest criterion
    assert mse < 0.5 * var, (mse, var)
    assert mse < 0.4 * mse0, (mse0, mse)


def test_deep_autoencoder_unroll_transposes_encoder():
    """Decoder layer p gets W_enc(L-1-p).T / vb after unrolling."""
    import numpy as np

    from deeplearning4j_tpu.models.zoo import (deep_autoencoder,
                                               unroll_autoencoder_stack)

    conf = deep_autoencoder(12, hidden=(8, 4))
    net = MultiLayerNetwork(conf, seed=0).init()
    params = unroll_autoencoder_stack(conf, net.params)
    # encoder: 0 (12->8), 1 (8->4); decoder: 2 (4->8 dense), 3 (8->12 out)
    np.testing.assert_allclose(np.asarray(params[2]["W"]),
                               np.asarray(net.params[1]["W"]).T)
    np.testing.assert_allclose(np.asarray(params[2]["b"]),
                               np.asarray(net.params[1]["vb"]))
    np.testing.assert_allclose(np.asarray(params[3]["W"]),
                               np.asarray(net.params[0]["W"]).T)
    import pytest

    with pytest.raises(ValueError):
        deep_autoencoder(10, hidden=())
