"""Config system: JSON round-trip, overrides, builder (ref conf-test parity)."""

from deeplearning4j_tpu.nd.losses import LossFunction
from deeplearning4j_tpu.nn.conf import (
    Distribution,
    LayerType,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OptimizationAlgorithm,
    list_builder,
)


def test_conf_json_roundtrip():
    c = NeuralNetConfiguration(
        layer_type=LayerType.RBM, n_in=784, n_out=500, lr=0.01,
        loss_function=LossFunction.RECONSTRUCTION_CROSSENTROPY,
        dist=Distribution(kind="normal", std=0.01),
        momentum_after=((100, 0.9),),
    )
    c2 = NeuralNetConfiguration.from_json(c.to_json())
    assert c2 == c


def test_multilayer_json_roundtrip_and_override():
    base = NeuralNetConfiguration(n_in=4, n_out=3)
    mlc = (list_builder(base, 3)
           .hidden_layer_sizes([8, 6], n_in=4, n_out=3)
           .override(2, layer_type=LayerType.OUTPUT,
                     loss_function=LossFunction.MCXENT)
           .pretrain(False).backprop(True).build())
    assert mlc.conf(0).n_in == 4 and mlc.conf(0).n_out == 8
    assert mlc.conf(1).n_in == 8 and mlc.conf(1).n_out == 6
    assert mlc.conf(2).layer_type == LayerType.OUTPUT
    mlc2 = MultiLayerConfiguration.from_json(mlc.to_json())
    assert mlc2 == mlc
    # per-layer override hook (ConfOverride parity)
    mlc3 = mlc.override(1, optimization_algo=OptimizationAlgorithm.LBFGS)
    assert mlc3.conf(1).optimization_algo == OptimizationAlgorithm.LBFGS
    assert mlc.conf(1).optimization_algo != OptimizationAlgorithm.LBFGS


def test_conf_hashable_for_jit_staticness():
    a = NeuralNetConfiguration(n_in=2, n_out=2)
    b = NeuralNetConfiguration(n_in=2, n_out=2)
    assert hash(a) == hash(b) and a == b
