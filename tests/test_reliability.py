"""Chaos suite (ISSUE 5): the fault-injection harness itself, and the
resilience behaviors it drives — circuit breaker open/recover with
degraded-but-correct serving, per-request deadlines (enqueue + post-
coalesce eviction), disk faults downgraded to cache misses, async
checkpoint error capture, prefetch worker-crash propagation, graceful
SIGTERM drain (in-process and via the real CLI subprocess), and
kill-and-resume bit-for-bit training.  Everything here is CPU-only and
deliberately small/fast."""

import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, network_output
from deeplearning4j_tpu.parallel import checkpoint
from deeplearning4j_tpu.reliability import (CircuitBreaker, DeadlineExceeded,
                                            TrainingInterrupted, faults)
from deeplearning4j_tpu.reliability.faults import (FaultInjected,
                                                   FaultPlanError)
from deeplearning4j_tpu.serving import MicroBatcher, ServerDraining

N_IN, N_OUT = 6, 3


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _net(seed=0):
    return MultiLayerNetwork(mlp(n_in=N_IN, hidden=[8], n_out=N_OUT,
                                 lr=0.05), seed=seed).init()


def _x(rows, seed):
    rng = np.random.RandomState(seed)
    return rng.randn(rows, N_IN).astype(np.float32)


def _http(url, body=None, timeout=30):
    req = urllib.request.Request(
        url, data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="GET" if body is None else "POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


# -- fault registry ----------------------------------------------------------

def test_fault_window_nth_times():
    faults.arm("demo.point", "raise", nth=2, times=2)
    faults.fire("demo.point")  # hit 1: before the window
    for _ in range(2):  # hits 2 and 3: inside [2, 4)
        with pytest.raises(FaultInjected):
            faults.fire("demo.point")
    faults.fire("demo.point")  # hit 4: past the window
    assert faults.hits("demo.point") == 4
    assert faults.stats()["armed"]["demo.point"]["fired"] == 2


def test_fault_arm_counts_from_current_hits():
    for _ in range(3):
        faults.fire("demo.mid")
    faults.arm("demo.mid", "raise")  # nth=1 relative to NOW -> hit 4
    with pytest.raises(FaultInjected):
        faults.fire("demo.mid")


def test_fault_actions_map_to_exception_types():
    faults.arm("demo.os", "oserror")
    with pytest.raises(OSError):
        faults.fire("demo.os")
    faults.arm("demo.to", "timeout")
    with pytest.raises(TimeoutError):
        faults.fire("demo.to")
    with pytest.raises(FaultPlanError):
        faults.arm("demo.bad", "explode")


def test_fault_corrupt_mutates_payload_and_rejects_payloadless_sites():
    faults.arm("demo.c", "corrupt", times=2)
    data = bytes(range(200))
    out = faults.fire("demo.c", data=data)
    assert out != data and len(out) == len(data)
    assert out[:64] == bytes(b ^ 0xFF for b in data[:64])
    assert out[64:] == data[64:]
    with pytest.raises(FaultInjected):  # corrupt armed, no bytes to corrupt
        faults.fire("demo.c")


def test_fault_delay_action_sleeps(monkeypatch):
    from deeplearning4j_tpu.reliability import faults as faults_mod

    slept = []
    monkeypatch.setattr(faults_mod, "_sleep", slept.append)
    faults.arm("demo.d", "delay", delay_s=0.25)
    assert faults.fire("demo.d", data="x") == "x"
    assert slept == [0.25]


def test_fault_env_plan_parsing_and_lazy_install(monkeypatch):
    n = faults.install_env_plan(
        "a.b=raise@3x2, c.d=oserror, e.f=delay:0.01")
    assert n == 3
    armed = faults.stats()["armed"]
    assert armed["a.b"] == {"action": "raise", "nth": 3, "times": 2,
                            "fired": 0}
    assert armed["c.d"]["action"] == "oserror"
    with pytest.raises(FaultPlanError):
        faults.install_env_plan("no_equals_sign")
    faults.reset()
    # exporting the variable arms the process on the first fire()
    monkeypatch.setenv("DL4J_FAULT_PLAN", "env.pt=raise@2")
    faults.fire("env.pt")
    with pytest.raises(FaultInjected):
        faults.fire("env.pt")


# -- circuit breaker ---------------------------------------------------------

def test_breaker_state_machine_with_fake_clock():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=2, reset_timeout_s=5.0,
                        probe_prob=1.0, clock=lambda: now[0])
    assert br.allow() and br.state == CircuitBreaker.CLOSED
    br.record_failure()
    assert br.state == CircuitBreaker.CLOSED  # 1 < threshold
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()
    now[0] = 5.1  # cooldown elapsed -> half-open, probe_prob=1 probes
    assert br.state == CircuitBreaker.HALF_OPEN
    assert br.allow()
    br.record_failure()  # failed probe: straight back to OPEN
    assert br.state == CircuitBreaker.OPEN and not br.allow()
    now[0] = 10.2
    assert br.allow()
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED and br.allow()
    st = br.stats()
    assert st["opens"] == 2 and st["probes"] == 2


def test_breaker_success_resets_consecutive_failures():
    br = CircuitBreaker(failure_threshold=3)
    for _ in range(5):  # failures interleaved with successes never open
        br.record_failure()
        br.record_failure()
        br.record_success()
    assert br.state == CircuitBreaker.CLOSED


# -- persist: disk faults are cache misses, corruption self-heals ------------

def test_persist_io_errors_downgrade_to_miss_with_one_warning(
        tmp_path, caplog):
    import logging

    net = _net()
    net.set_compile_cache(str(tmp_path / "cc"))
    faults.arm("persist.write", "oserror", times=10)
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        net.warmup([4, 8])  # both stores fail on "disk"; warmup succeeds
    out = np.asarray(net.output(_x(3, seed=1)))
    assert out.shape == (3, N_OUT)
    store = net.infer_cache.persist
    assert store.io_errors == 2
    assert net.infer_cache.stats.io_errors == 2
    assert "io_errors" in net.infer_cache.stats.as_dict()
    warns = [r for r in caplog.records if "treating as a cache miss" in
             r.getMessage()]
    assert len(warns) == 1  # warned ONCE, counted twice
    assert len(store) == 0  # nothing persisted


def test_persist_read_fault_is_a_miss_not_a_crash(tmp_path):
    cache = str(tmp_path / "cc")
    conf = mlp(n_in=N_IN, hidden=[8], n_out=N_OUT, lr=0.05)
    warm = MultiLayerNetwork(conf, seed=0).init()
    warm.set_compile_cache(cache)
    warm.warmup([4])
    net = MultiLayerNetwork(conf, seed=0).init()
    net.set_compile_cache(cache)
    faults.arm("persist.read", "oserror")
    net.warmup([4])  # read fails -> counted miss -> fresh compile
    assert net.infer_cache.persist.io_errors == 1
    assert net.infer_cache.stats.misses == 1
    assert net.infer_cache.stats.disk_hits == 0


def test_persist_corrupt_write_evicted_then_rewritten(tmp_path):
    cache = str(tmp_path / "cc")
    conf = mlp(n_in=N_IN, hidden=[8], n_out=N_OUT, lr=0.05)
    n1 = MultiLayerNetwork(conf, seed=0).init()
    n1.set_compile_cache(cache)
    faults.arm("persist.write", "corrupt")
    n1.warmup([4])  # persists a torn entry (checksum/magic broken)
    assert len(n1.infer_cache.persist) == 1

    n2 = MultiLayerNetwork(conf, seed=0).init()
    n2.set_compile_cache(cache)
    n2.warmup([4])  # bad entry evicted, recompiled, rewritten clean
    assert n2.infer_cache.persist.corrupt_evicted == 1
    assert n2.infer_cache.stats.misses == 1

    n3 = MultiLayerNetwork(conf, seed=0).init()
    n3.set_compile_cache(cache)
    n3.warmup([4])  # the rewrite restored durability
    assert n3.infer_cache.stats.disk_hits == 1
    np.testing.assert_array_equal(np.asarray(n1.output(_x(4, seed=2))),
                                  np.asarray(n3.output(_x(4, seed=2))))


# -- checkpoint: async error capture, resilient load -------------------------

def test_save_async_failure_surfaces_at_join(tmp_path):
    params = {"w": np.ones((2, 2), np.float32)}
    faults.arm("checkpoint.save", "oserror")
    checkpoint.save_async(str(tmp_path / "ck"), params)
    with pytest.raises(OSError):
        checkpoint.join_async(timeout=30.0)
    # the failure was consumed; the next save round-trips
    checkpoint.save_async(str(tmp_path / "ck"), params)
    checkpoint.join_async(timeout=30.0)
    loaded, _, _ = checkpoint.load(str(tmp_path / "ck"))
    np.testing.assert_array_equal(loaded["w"], params["w"])


def test_save_async_failure_surfaces_at_next_save(tmp_path):
    params = {"w": np.zeros((2,), np.float32)}
    faults.arm("checkpoint.save", "oserror")
    t = checkpoint.save_async(str(tmp_path / "ck"), params)
    t.join(30.0)
    with pytest.raises(OSError):
        checkpoint.save_async(str(tmp_path / "ck2"), params)
    checkpoint.join_async(timeout=30.0)


def test_load_resilient_falls_back_past_corrupt_checkpoint(tmp_path):
    d = str(tmp_path / "ck")
    params = {"w": np.arange(4, dtype=np.float32)}
    checkpoint.save(d, params, step=7)
    shutil.copytree(d, d + ".bak")
    with open(os.path.join(d, "arrays.npz"), "wb") as f:
        f.write(b"torn")  # main checkpoint corrupt; .bak intact
    got = checkpoint.load_resilient(d, like_params=params)
    assert got is not None
    loaded, _, meta = got
    np.testing.assert_array_equal(np.asarray(loaded["w"]), params["w"])
    assert meta["step"] == 7
    assert checkpoint.load_resilient(str(tmp_path / "absent")) is None


# -- prefetch: a crashed worker surfaces exactly once ------------------------

def test_prefetch_worker_fault_propagates_exactly_once():
    from deeplearning4j_tpu.datasets.iterator import PrefetchIterator

    items = [(_x(2, seed=i), _x(2, seed=i + 50)) for i in range(6)]
    faults.arm("prefetch.worker", nth=4)
    it = PrefetchIterator(items, to_device=False)
    it.start()
    outcomes, lock = [], threading.Lock()

    def consume():
        served = 0
        try:
            while True:
                it.pull()
                served += 1
        except FaultInjected:
            with lock:
                outcomes.append(("fault", served))
        except StopIteration:
            with lock:
                outcomes.append(("stop", served))

    threads = [threading.Thread(target=consume) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive(), "worker fault left a consumer blocked"
    it.close()
    kinds = sorted(k for k, _ in outcomes)
    assert kinds == ["fault", "stop", "stop", "stop"]  # exactly once
    assert sum(n for _, n in outcomes) == 3  # batches before the crash


# -- deadlines ---------------------------------------------------------------

def test_deadline_already_expired_rejected_at_enqueue():
    net = _net()
    batcher = MicroBatcher(net, auto_start=False)
    with pytest.raises(DeadlineExceeded):
        batcher.predict(_x(1, seed=0), deadline_ms=0)
    assert batcher.stats()["deadline_misses"] == 1


def test_deadline_evicts_queued_request_before_padding():
    net = _net()
    batcher = MicroBatcher(net, max_delay_ms=5000.0, auto_start=False)
    errs = []

    def client():
        try:
            batcher.predict(_x(1, seed=0), timeout=30.0, deadline_ms=40.0)
        except DeadlineExceeded as e:
            errs.append(e)

    t = threading.Thread(target=client)
    t.start()  # queued with the dispatcher not yet running
    deadline = time.time() + 5.0
    while batcher.queue_depth() < 1 and time.time() < deadline:
        time.sleep(0.005)
    time.sleep(0.08)  # let the 40ms deadline lapse in the queue
    batcher.start()  # first dispatch pass evicts before coalescing
    t.join(timeout=30.0)
    assert not t.is_alive()
    batcher.stop()
    assert len(errs) == 1
    st = batcher.stats()
    assert st["deadline_misses"] == 1 and st["errors"] == 1
    # nothing was executed for the dead request
    assert st["requests"] == 0


def test_deadline_met_when_dispatcher_is_live():
    net = _net()
    batcher = MicroBatcher(net, max_delay_ms=2.0)
    out = batcher.predict(_x(2, seed=3), timeout=30.0, deadline_ms=20000.0)
    batcher.stop()
    assert out.shape == (2, N_OUT)
    assert batcher.stats()["deadline_misses"] == 0


# -- circuit breaker in the gateway: chaos serve -----------------------------

def test_chaos_serve_breaker_opens_degrades_and_recovers():
    """32 closed-loop clients while dispatcher faults are armed: every
    response is either the correct activations or a clean exception — no
    hangs — the breaker opens, serving degrades to the eager path
    (bitwise-identical answers), and once the faults stop the breaker
    recovers to CLOSED."""
    net = _net()
    net.warmup([32])
    breaker = CircuitBreaker(failure_threshold=3, reset_timeout_s=0.05,
                             probe_prob=1.0)
    batcher = MicroBatcher(net, max_delay_ms=2.0, breaker=breaker)
    clients = 32
    xs = [_x(1 + i % 3, seed=i) for i in range(clients)]
    direct = [np.asarray(net.output(x)) for x in xs]
    # primary-path executions 2..7 fail: enough consecutive batch
    # failures to open the breaker (threshold 3), then half-open probes
    # burn through the rest of the window and the first clean probe
    # closes it again
    faults.arm("dispatcher.execute", "raise", nth=2, times=6)
    wrong, errors, lock = [], [], threading.Lock()

    def client(i):
        for _ in range(6):
            try:
                got = batcher.predict(xs[i], timeout=30.0)
                if not np.array_equal(direct[i], got):
                    with lock:
                        wrong.append(i)
            except Exception as e:  # noqa: BLE001 — clean failure is OK
                with lock:
                    errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
        assert not t.is_alive(), "chaos client hung"
    assert not wrong, f"degraded/primary responses diverged: {wrong}"
    # faults raise BEFORE the device call, so every faulted batch falls
    # back to the degraded path and still answers correctly
    assert not errors, errors[:3]

    # drive recovery: cooldown -> half-open probe (prob=1.0) -> success
    deadline = time.time() + 20.0
    while breaker.state != CircuitBreaker.CLOSED and time.time() < deadline:
        time.sleep(0.06)
        try:
            batcher.predict(xs[0], timeout=30.0)
        except Exception:  # noqa: BLE001 — a probe may still hit a fault
            pass
    st = batcher.stats()
    batcher.stop()
    assert st["breaker"]["state"] == CircuitBreaker.CLOSED
    assert st["breaker"]["opens"] >= 1, st["breaker"]
    assert st["degraded_batches"] >= 1, st
    assert st["degraded"] is False  # recovered


def test_degraded_output_is_bitwise_eager_network_output():
    net = _net()
    x = _x(5, seed=11)
    eager = np.asarray(network_output(net.conf, net.params, x))
    # a pre-opened breaker (long cooldown) forces the degraded path
    br = CircuitBreaker(failure_threshold=1, reset_timeout_s=600.0)
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    batcher = MicroBatcher(net, max_delay_ms=2.0, breaker=br)
    got = batcher.predict(x, timeout=30.0)
    st = batcher.stats()
    batcher.stop()
    np.testing.assert_array_equal(eager, got)
    assert st["degraded_batches"] == 1 and st["degraded"] is True
    assert faults.hits("dispatcher.execute") == 0  # primary never ran


# -- server: health endpoints + graceful drain -------------------------------

def test_healthz_readyz_and_drain_semantics():
    net = _net()
    server = net.serve(max_delay_ms=2.0)
    try:
        assert _http(server.url + "/healthz")[0] == 200
        code, body = _http(server.url + "/readyz")
        assert code == 200 and body["ready"] is True
        code, body = _http(server.url + "/v1/predict",
                           {"features": _x(2, seed=1).tolist(),
                            "deadline_ms": 20000})
        assert code == 200 and body["rows"] == 2
        _, st = _http(server.url + "/v1/stats")
        for key in ("ready", "draining", "inflight", "deadline_misses",
                    "errors", "degraded", "breaker", "drain_timeout_s"):
            assert key in st, key
        assert st["ready"] is True and st["draining"] is False
    finally:
        server.drain(5.0)
    assert not server.is_ready() and server.draining
    with pytest.raises(ServerDraining):
        server.predict(_x(1, seed=0))
    assert server.enter_request() is False
    server.drain(5.0)  # idempotent
    assert server.wait_for_stop(timeout=0.0)  # drain flagged the stop event


def test_expired_deadline_maps_to_http_504():
    net = _net()
    server = net.serve(max_delay_ms=2.0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _http(server.url + "/v1/predict",
                  {"features": _x(1, seed=0).tolist(), "deadline_ms": 0})
        assert ei.value.code == 504
        _, st = _http(server.url + "/v1/stats")
        assert st["deadline_misses"] == 1
    finally:
        server.stop()


def test_drain_under_load_answers_every_accepted_request():
    net = _net()
    net.warmup([8])
    server = net.serve(max_delay_ms=2.0)
    ok, refused, broken, lock = [], [], [], threading.Lock()
    stop = threading.Event()

    def client(i):
        while not stop.is_set():
            try:
                code, _ = _http(server.url + "/v1/predict",
                                {"features": _x(1, seed=i).tolist()},
                                timeout=10)
                with lock:
                    ok.append(code)
            except urllib.error.HTTPError as e:
                with lock:
                    refused.append(e.code)  # clean 503 during drain
                return
            except OSError:
                with lock:
                    broken.append(i)  # accept loop already closed
                return

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    deadline = time.time() + 5.0
    while len(ok) < 8 and time.time() < deadline:
        time.sleep(0.01)
    assert len(ok) >= 8  # every client got real answers pre-drain
    drain_thread = threading.Thread(target=server.drain, args=(10.0,))
    drain_thread.start()
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive(), "client hung across the drain"
    drain_thread.join(timeout=30.0)
    assert not drain_thread.is_alive()
    assert all(c == 200 for c in ok)
    assert all(c == 503 for c in refused)


# -- the real thing: CLI serve process, SIGTERM, exit 0 ----------------------

def test_cli_serve_sigterm_drains_and_exits_zero(tmp_path):
    net = _net()
    ckpt = str(tmp_path / "model")
    checkpoint.save(ckpt, net.params, conf=net.conf)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "serve",
         "--model", ckpt, "--shapes", "4", "--port", "0",
         "--max-delay-ms", "300", "--drain-timeout", "10"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=repo, env=env)
    try:
        watchdog = threading.Timer(180.0, proc.kill)
        watchdog.start()
        try:
            summary = json.loads(proc.stdout.readline())
        finally:
            watchdog.cancel()
        url = summary["url"]
        code, body = _http(url + "/v1/predict",
                           {"features": _x(2, seed=1).tolist()}, timeout=60)
        assert code == 200 and body["rows"] == 2

        # leave a request IN FLIGHT (300ms coalescing window) when the
        # SIGTERM lands: the drain must still answer it for real
        inflight = {}

        def straggler():
            try:
                inflight["resp"] = _http(
                    url + "/v1/predict",
                    {"features": _x(1, seed=2).tolist()}, timeout=30)
            except Exception as e:  # noqa: BLE001
                inflight["error"] = e

        t = threading.Thread(target=straggler)
        t.start()
        time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        t.join(timeout=60.0)
        assert not t.is_alive()
        assert "resp" in inflight, inflight.get("error")
        assert inflight["resp"][0] == 200

        out, err = proc.communicate(timeout=120)
        assert proc.returncode == 0, (out, err)
        drained = json.loads(out.strip().splitlines()[-1])
        assert drained["drained"] is True
        assert drained["requests"] >= 2
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


# -- crash-safe training: SIGTERM checkpoints, rerun resumes bit-for-bit -----

def _toy_stream(batch=8, n=40, seed=3):
    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     labels_to_one_hot)
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator

    rng = np.random.RandomState(seed)
    x = rng.randn(n, N_IN).astype(np.float32)
    y = labels_to_one_hot(rng.randint(0, N_OUT, n), N_OUT)
    return ListDataSetIterator(DataSet(x, y), batch)


class _SigtermAfter:
    """Listener that exercises the REAL installed SIGTERM handler after
    `after` batches (calling the handler in-process stands in for the
    kernel delivering the signal, without risking the test runner)."""

    def __init__(self, after):
        self.after, self.n = after, 0

    def iteration_done(self, model, iteration, score):
        self.n += 1
        if self.n == self.after:
            handler = signal.getsignal(signal.SIGTERM)
            assert callable(handler), "fit did not install a SIGTERM handler"
            handler(signal.SIGTERM, None)


def test_sigterm_checkpoints_then_rerun_resumes_bit_for_bit(tmp_path):
    conf = mlp(n_in=N_IN, hidden=[8], n_out=N_OUT, lr=0.05)
    ck = str(tmp_path / "ck")

    ref = MultiLayerNetwork(conf, seed=7).init()
    ref.fit(_toy_stream())  # uninterrupted 5-batch run
    ref_flat = np.asarray(ref.params_flat())

    n1 = MultiLayerNetwork(conf, seed=7).init()
    n1.set_listeners([_SigtermAfter(2)])
    with pytest.raises(TrainingInterrupted):
        n1.fit(_toy_stream(), checkpoint_dir=ck)
    _, _, meta = checkpoint.load(ck)
    assert meta["data_cursor"]["batches_done"] == 2
    assert "rng_key" in meta["metadata"]

    n2 = MultiLayerNetwork(conf, seed=7).init()  # fresh "process"
    n2.fit(_toy_stream(), checkpoint_dir=ck)  # auto-resumes at batch 2
    flat2 = np.asarray(n2.params_flat())
    assert ref_flat.dtype == np.float32
    assert np.array_equal(ref_flat, flat2), "resume is not bit-identical"
    # final checkpoint advanced to the full stream
    _, _, meta = checkpoint.load(ck)
    assert meta["data_cursor"]["batches_done"] == 5


def test_periodic_checkpoint_and_stop_flag(tmp_path):
    ck = str(tmp_path / "ck")
    net = _net(seed=1)
    net.fit(_toy_stream(), checkpoint_dir=ck, checkpoint_every_n_batches=2)
    _, _, meta = checkpoint.load(ck)
    assert meta["data_cursor"]["batches_done"] == 5
    assert os.path.isdir(ck) and not os.path.isdir(ck + ".bak")

    # request_stop_training (from a listener, i.e. mid-run) checkpoints
    # and raises after the current batch
    class _Stop:
        def iteration_done(self, model, iteration, score):
            model.request_stop_training()

    net2 = _net(seed=1)
    net2.set_listeners([_Stop()])
    with pytest.raises(TrainingInterrupted):
        net2.fit(_toy_stream(), checkpoint_dir=str(tmp_path / "ck2"),
                 auto_resume=False)
    _, _, meta = checkpoint.load(str(tmp_path / "ck2"))
    assert meta["data_cursor"]["batches_done"] == 1


# -- CLI flags ----------------------------------------------------------------

def test_cli_resilience_flags_parse():
    from deeplearning4j_tpu.cli.driver import build_parser

    args = build_parser().parse_args(
        ["serve", "--model", "m", "--drain-timeout", "3.5",
         "--request-timeout", "12", "--default-deadline-ms", "250"])
    assert args.drain_timeout == 3.5
    assert args.request_timeout == 12.0
    assert args.default_deadline_ms == 250.0
    args = build_parser().parse_args(["serve", "--model", "m"])
    assert args.drain_timeout == 10.0 and args.default_deadline_ms is None

    args = build_parser().parse_args(
        ["train", "--input", "d.csv", "--output", "o",
         "--zoo", "mlp", "--checkpoint-dir", "ckpts/run1"])
    assert args.checkpoint_dir == "ckpts/run1"
