"""Elastic, crash-resumable mesh training (ISSUE 10).

The contract under test: `DataParallelTrainer.fit(checkpoint_dir=...)`
checkpoints the COMPLETE cross-batch state (params, updater moments,
step, host RNG key, batch cursor) atomically; a rerun auto-resumes at
the cursor with a bit-identical trajectory on the same topology, and an
allclose trajectory on a DIFFERENT device count (elastic N->M resume —
only the f32 reduction grouping of the dp collectives changes).  Chaos
variant: a subprocess run is killed mid-epoch by the PR 5 fault
registry at N=4 forced devices and resumed at M=2 in a second
subprocess (`--xla_force_host_platform_device_count` pattern from
test_mesh_infer).
"""

import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (LayerType, NeuralNetConfiguration,
                                        OptimizationAlgorithm, list_builder)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import checkpoint as ckpt
from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.reliability import TrainingInterrupted, faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _mlp_conf(n_in=4, n_hidden=8, n_out=3, **kw):
    base = NeuralNetConfiguration(
        n_in=n_in, n_out=n_out, lr=0.1,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        num_iterations=5, **kw)
    return (list_builder(base, 2)
            .hidden_layer_sizes([n_hidden], n_in, n_out)
            .override(1, layer_type=LayerType.OUTPUT)
            .pretrain(False).backprop(True).build())


def _net(n_hidden=8):
    net = MultiLayerNetwork(_mlp_conf(n_hidden=n_hidden))
    net.init()
    return net


def _batches(n=48, bs=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, size=n)]
    return [(x[i:i + bs], y[i:i + bs]) for i in range(0, n, bs)]


def _mesh(n):
    return make_mesh({"dp": n}, devices=jax.devices()[:n])


class _Recorder:
    """Listener that collects the per-batch score trajectory."""

    def __init__(self):
        self.scores = []

    def iteration_done(self, model, iteration, score):
        self.scores.append(float(score))


def _gather(tree):
    return jax.tree_util.tree_map(
        lambda x: np.array(jax.device_get(x)), tree)


def _trees_equal(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    return len(fa) == len(fb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(fa, fb))


# -- tentpole: resume on the same and on a different topology ---------------

def test_same_topology_resume_is_bitwise(tmp_path):
    """Kill-free framing of the crash contract: train 3 batches with
    checkpointing, hand the dir to a FRESH trainer for the full run —
    final params must be bit-identical to an uninterrupted run."""
    batches = _batches()
    ck = str(tmp_path / "ck")

    t_ref = DataParallelTrainer(_net(), _mesh(4))
    ref_score = t_ref.fit(batches, epochs=2)
    ref_params = _gather(t_ref.state.params)

    t1 = DataParallelTrainer(_net(), _mesh(4))
    t1.fit(batches[:3], epochs=1, checkpoint_dir=ck,
           checkpoint_every_n_batches=1)
    assert t1.checkpoints_written >= 3

    t2 = DataParallelTrainer(_net(), _mesh(4))
    s2 = t2.fit(batches, epochs=2, checkpoint_dir=ck)
    assert t2.resumed_from_step == 3
    assert np.float32(s2) == np.float32(ref_score)
    assert _trees_equal(ref_params, _gather(t2.state.params))
    # updater moments resumed too, not just params
    assert _trees_equal(_gather(t_ref.state.updater),
                        _gather(t2.state.updater))


@pytest.mark.parametrize("n,m", [(4, 2), (1, 4)])
def test_elastic_resume_n_to_m(tmp_path, n, m):
    """A checkpoint written on an N-chip mesh resumes on M chips with the
    same loss trajectory (allclose: the dp reduction grouping changes)."""
    batches = _batches()
    ck = str(tmp_path / "ck")

    rec_ref = _Recorder()
    t_ref = DataParallelTrainer(_net(), _mesh(4))
    t_ref.listeners = [rec_ref]
    t_ref.fit(batches, epochs=2)

    t1 = DataParallelTrainer(_net(), _mesh(n))
    t1.fit(batches[:3], epochs=1, checkpoint_dir=ck,
           checkpoint_every_n_batches=1)

    rec = _Recorder()
    t2 = DataParallelTrainer(_net(), _mesh(m))
    t2.listeners = [rec]
    t2.fit(batches, epochs=2, checkpoint_dir=ck)
    assert t2.resumed_from_step == 3
    np.testing.assert_allclose(rec.scores, rec_ref.scores[3:],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.concatenate([np.asarray(x).ravel() for x in
                        jax.tree_util.tree_leaves(_gather(t2.state.params))]),
        np.concatenate([np.asarray(x).ravel() for x in
                        jax.tree_util.tree_leaves(_gather(t_ref.state.params))]),
        rtol=1e-5, atol=1e-6)


def test_checkpointing_off_is_bitwise_unchanged():
    """fit() without checkpoint_dir must be byte-for-byte the old path."""
    batches = _batches()
    t1 = DataParallelTrainer(_net(), _mesh(4))
    s1 = t1.fit(batches, epochs=2)
    t2 = DataParallelTrainer(_net(), _mesh(4))
    s2 = t2.fit(batches, epochs=2, checkpoint_dir=None)
    assert np.float32(s1) == np.float32(s2)
    assert _trees_equal(_gather(t1.state.params), _gather(t2.state.params))


def test_sigterm_checkpoints_then_raises(tmp_path):
    """SIGTERM mid-fit checkpoints the cursor and raises
    TrainingInterrupted (single-device trainer contract, PR 5)."""
    batches = _batches()
    ck = str(tmp_path / "ck")

    class KillAt:
        def __init__(self, n):
            self.n, self.c = n, 0

        def iteration_done(self, model, iteration, score):
            self.c += 1
            if self.c == self.n:
                os.kill(os.getpid(), signal.SIGTERM)

    t = DataParallelTrainer(_net(), _mesh(4))
    t.listeners = [KillAt(2)]
    with pytest.raises(TrainingInterrupted):
        t.fit(batches, epochs=2, checkpoint_dir=ck,
              checkpoint_every_n_batches=100)
    _, _, meta = ckpt.load(ck)
    assert meta["data_cursor"]["batches_done"] == 2

    t2 = DataParallelTrainer(_net(), _mesh(4))
    t2.fit(batches, epochs=2, checkpoint_dir=ck)
    assert t2.resumed_from_step == 2


# -- checkpoint format: version + mesh metadata -----------------------------

def test_checkpoint_meta_records_format_and_mesh(tmp_path):
    ck = str(tmp_path / "ck")
    t = DataParallelTrainer(_net(), _mesh(4))
    t.fit(_batches(), epochs=1, checkpoint_dir=ck)
    with open(os.path.join(ck, "meta.json")) as f:
        meta = json.load(f)
    assert meta["format_version"] == ckpt.FORMAT_VERSION == 1
    assert meta["mesh"] == {"axis_names": ["dp"], "shape": {"dp": 4},
                            "zero1": False}
    assert meta["data_cursor"]["batches_done"] == 6
    assert meta["metadata"]["rng_key"] is not None


def test_pre_pr_checkpoint_without_version_still_loads(tmp_path):
    """A pre-versioning checkpoint (no format_version, no mesh block)
    must keep loading — both raw load() and single-device auto-resume."""
    ck = str(tmp_path / "ck")
    net = _net()
    x, y = _batches(n=8, bs=8)[0]
    net.fit([(x, y)] * 3, checkpoint_dir=ck, checkpoint_every_n_batches=1)
    meta_path = os.path.join(ck, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["format_version"]
    del meta["mesh"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)

    params, _, meta2 = ckpt.load(ck, like_params=net.params)
    assert "format_version" not in meta2
    assert _trees_equal(params, net.params)
    # auto-resume path (load_resilient) tolerates it too
    net2 = _net()
    net2.fit([(x, y)] * 3, checkpoint_dir=ck)
    assert net2.resumed_from_batch == 3


def test_future_format_version_fails_with_one_line_error(tmp_path):
    ck = str(tmp_path / "ck")
    net = _net()
    ckpt.save(ck, net.params, step=1)
    meta_path = os.path.join(ck, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["format_version"] = 99
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ckpt.CheckpointFormatError, match="format_version=99"):
        ckpt.load(ck, like_params=net.params)
    # NOT corruption: load_resilient propagates instead of restarting
    # training from scratch
    with pytest.raises(ckpt.CheckpointFormatError):
        ckpt.load_resilient(ck, like_params=net.params)


def test_structurally_incompatible_tree_fails_actionably(tmp_path):
    ck = str(tmp_path / "ck")
    ckpt.save(ck, _net(n_hidden=8).params, step=1)
    # different layer width -> shape diagnosis, not a downstream explosion
    with pytest.raises(ckpt.CheckpointFormatError, match="shape"):
        ckpt.load(ck, like_params=_net(n_hidden=16).params)
    # params-only checkpoint restored with an updater template -> missing
    # leaves diagnosis (a single-device checkpoint fed to the mesh trainer)
    t = DataParallelTrainer(_net(), _mesh(2))
    with pytest.raises(ckpt.CheckpointFormatError, match="missing"):
        ckpt.load(ck, like_params=t.state.params,
                  like_updater=t.state.updater)


# -- zero1: sharded updater state round-trips elastically -------------------

def test_zero1_round_trip_updater_bitwise(tmp_path):
    """Gathered updater moments are bitwise equal across
    save -> reshard (4 chips -> 2) -> load -> save -> load."""
    batches = _batches()
    ck = str(tmp_path / "ck")

    t4 = DataParallelTrainer(_net(), _mesh(4), zero1=True)
    t4.fit(batches[:4], epochs=1, checkpoint_dir=ck)
    g4 = _gather(t4.state.updater)
    # the live updater state really is sharded over dp
    shardings = [x.sharding.spec for x in
                 jax.tree_util.tree_leaves(t4.state.updater)]
    assert any("dp" in str(s) for s in shardings)

    t2 = DataParallelTrainer(_net(), _mesh(2), zero1=True)
    assert t2.restore(ck) == 4
    assert _trees_equal(g4, _gather(t2.state.updater))
    ck2 = str(tmp_path / "ck2")
    t2._save_checkpoint(ck2, batches_done=4)

    t4b = DataParallelTrainer(_net(), _mesh(4), zero1=True)
    t4b.restore(ck2)
    assert _trees_equal(g4, _gather(t4b.state.updater))
    # and the resharded state still trains
    x, y = batches[4]
    t4b.fit([(x, y)], epochs=1)


def test_zero1_elastic_trajectory_matches_plain_dp(tmp_path):
    """zero1 resume across topologies follows the same loss trajectory
    as replicated dp (zero1 is a memory layout, not different math)."""
    batches = _batches()
    rec_ref = _Recorder()
    t_ref = DataParallelTrainer(_net(), _mesh(4))
    t_ref.listeners = [rec_ref]
    t_ref.fit(batches, epochs=2)

    ck = str(tmp_path / "ck")
    t1 = DataParallelTrainer(_net(), _mesh(4), zero1=True)
    t1.fit(batches[:3], epochs=1, checkpoint_dir=ck,
           checkpoint_every_n_batches=1)
    rec = _Recorder()
    t2 = DataParallelTrainer(_net(), _mesh(2), zero1=True)
    t2.listeners = [rec]
    t2.fit(batches, epochs=2, checkpoint_dir=ck)
    np.testing.assert_allclose(rec.scores, rec_ref.scores[3:],
                               rtol=1e-4, atol=1e-5)


def test_zero1_pads_and_masks_remainder_batches():
    """ISSUE 17 closes PR 10's guard: a non-dp-divisible batch in zero1
    mode pads-and-masks instead of raising, and updates on the divisible
    prefix stay bitwise identical to a run that never saw the tail."""
    batches = _batches(n=16, bs=8)
    x, y = _batches(n=8, bs=8, seed=9)[0]
    tail = (x[:6], y[:6])  # 6 rows on dp=4: pad to 8, mask 2

    t_ref = DataParallelTrainer(_net(), _mesh(4), zero1=True)
    t_ref.fit(batches, epochs=1)

    t = DataParallelTrainer(_net(), _mesh(4), zero1=True)
    t.fit(batches, epochs=1)
    prefix = _gather(t.state.params)
    assert _trees_equal(prefix, _gather(t_ref.state.params))

    t.fit([tail], epochs=1)  # must not raise
    assert int(t.state.step) == 3  # the remainder batch really stepped
    assert not _trees_equal(prefix, _gather(t.state.params))


def test_zero1_requires_sync_mode():
    with pytest.raises(ValueError, match="zero1"):
        DataParallelTrainer(_net(), _mesh(4), mode="async", zero1=True)


# -- satellites: donation race, load faults, corruption ---------------------

def test_async_save_then_immediate_step_donation_race(tmp_path):
    """save_async must snapshot to OWNED host copies before returning:
    the next train step donates the TrainState buffers, so a lazy
    device_get in the writer thread would read freed memory."""
    batches = _batches()
    ck = str(tmp_path / "ck")
    t = DataParallelTrainer(_net(), _mesh(4))
    t.fit(batches[:2], epochs=1)
    want_params = _gather(t.state.params)
    want_updater = _gather(t.state.updater)
    # slow the writer down so the donating step definitely races it
    faults.arm("checkpoint.save", "delay", delay_s=0.2)
    ckpt.save_async(ck, t.state.params, t.state.updater,
                    conf=t.net.conf, step=2)
    t.fit(batches[2:], epochs=1)  # donates the snapshotted buffers
    ckpt.join_async()
    params, updater, meta = ckpt.load(ck, like_params=t.state.params,
                                      like_updater=t.state.updater)
    assert meta["step"] == 2
    assert _trees_equal(params, want_params)
    assert _trees_equal(updater, want_updater)


def test_checkpoint_load_fault_point_falls_back(tmp_path):
    """An armed checkpoint.load fault is a torn read: load_resilient
    falls back to .bak on the first failure and returns None (never
    crashes) when both candidates fail."""
    import shutil

    ck = str(tmp_path / "ck")
    net = _net()
    ckpt.save(ck, net.params, step=7)
    shutil.copytree(ck, ck + ".bak")

    faults.arm("checkpoint.load", "raise", nth=1)
    params, _, meta = ckpt.load_resilient(ck, like_params=net.params)
    assert meta["step"] == 7 and _trees_equal(params, net.params)

    faults.arm("checkpoint.load", "raise", nth=1, times=2)
    assert ckpt.load_resilient(ck, like_params=net.params) is None


@pytest.mark.parametrize("damage", ["truncate_npz", "drop_meta"])
def test_corrupt_mesh_checkpoint_falls_back_to_bak(tmp_path, damage):
    """A torn mesh checkpoint (truncated arrays.npz / missing meta.json)
    is skipped in favor of .bak — auto-resume never crashes on it."""
    import shutil

    batches = _batches()
    ck = str(tmp_path / "ck")
    t = DataParallelTrainer(_net(), _mesh(4))
    t.fit(batches[:3], epochs=1, checkpoint_dir=ck,
          checkpoint_every_n_batches=1)
    # save() drops the .bak on success; recreate one from the good dir,
    # then tear the main dir
    shutil.copytree(ck, ck + ".bak")
    if damage == "truncate_npz":
        p = os.path.join(ck, "arrays.npz")
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
    else:
        os.remove(os.path.join(ck, "meta.json"))

    t2 = DataParallelTrainer(_net(), _mesh(2))
    t2.fit(batches, epochs=2, checkpoint_dir=ck)
    assert t2.resumed_from_step == 3  # resumed from the intact .bak


def test_checkpoint_listener_records_mesh_meta(tmp_path):
    """CheckpointListener on the mesh trainer stamps the topology into
    meta.json, like the trainer's own checkpoints."""
    from deeplearning4j_tpu.optimize.listeners import CheckpointListener

    ck = str(tmp_path / "ck")
    li = CheckpointListener(ck, save_every_n=1, asynchronous=False)
    t = DataParallelTrainer(_net(), _mesh(4))
    t.listeners = [li]
    t.fit(_batches()[:2], epochs=1)
    with open(os.path.join(ck, "meta.json")) as f:
        meta = json.load(f)
    assert meta["mesh"]["shape"] == {"dp": 4}
    assert meta["format_version"] == 1


# -- chaos: subprocess kill at N=4, resume at M=2 ---------------------------

_CHAOS_SCRIPT = """
import json, sys
import numpy as np
import jax
assert len(jax.devices()) == int(sys.argv[1]), jax.devices()
from deeplearning4j_tpu.nn.conf import (LayerType, NeuralNetConfiguration,
                                        OptimizationAlgorithm, list_builder)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
from deeplearning4j_tpu.parallel.mesh import make_mesh

base = NeuralNetConfiguration(
    n_in=4, n_out=3, lr=0.1,
    optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
    num_iterations=5)
conf = (list_builder(base, 2).hidden_layer_sizes([8], 4, 3)
        .override(1, layer_type=LayerType.OUTPUT)
        .pretrain(False).backprop(True).build())
net = MultiLayerNetwork(conf); net.init()
rng = np.random.RandomState(0)
x = rng.randn(48, 4).astype(np.float32)
y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, size=48)]
batches = [(x[i:i+8], y[i:i+8]) for i in range(0, 48, 8)]

scores = []
class Rec:
    def iteration_done(self, model, it, s):
        scores.append(float(s))

mesh = make_mesh({"dp": len(jax.devices())})
t = DataParallelTrainer(net, mesh)
t.listeners = [Rec()]
try:
    t.fit(batches, epochs=2, checkpoint_dir=sys.argv[2],
          checkpoint_every_n_batches=3)
finally:
    print("RESULT " + json.dumps(
        {"scores": scores, "resumed": t.resumed_from_step}), flush=True)
"""


def test_chaos_kill_n4_resume_m2_subprocess(tmp_path):
    """The acceptance chaos run: DL4J_FAULT_PLAN kills a 4-device mesh
    run mid-epoch (batch 8 of 12); a 2-device process auto-resumes from
    the batch-6 checkpoint and finishes with the reference trajectory."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ck = str(tmp_path / "ck")

    def run(n_dev, fault_plan=None):
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": " --xla_force_host_platform_device_count="
                            f"{n_dev}"}
        env.pop("DL4J_FAULT_PLAN", None)
        if fault_plan:
            env["DL4J_FAULT_PLAN"] = fault_plan
        return subprocess.run(
            [sys.executable, "-c", _CHAOS_SCRIPT, str(n_dev), ck],
            capture_output=True, text=True, cwd=repo, env=env, timeout=300)

    # in-process reference trajectory (uninterrupted, dp=4)
    rec = _Recorder()
    t_ref = DataParallelTrainer(_net(), _mesh(4))
    t_ref.listeners = [rec]
    t_ref.fit(_batches(), epochs=2)

    r1 = run(4, fault_plan="trainer.step=raise@8")
    assert r1.returncode != 0, (r1.stdout, r1.stderr)  # it really died
    out1 = json.loads(r1.stdout.split("RESULT ", 1)[1])
    assert out1["resumed"] is None and len(out1["scores"]) == 7
    _, _, meta = ckpt.load(ck)
    assert meta["data_cursor"]["batches_done"] == 6  # periodic save
    assert meta["mesh"]["shape"] == {"dp": 4}

    r2 = run(2)
    assert r2.returncode == 0, (r2.stdout, r2.stderr)
    out2 = json.loads(r2.stdout.split("RESULT ", 1)[1])
    assert out2["resumed"] == 6
    np.testing.assert_allclose(out2["scores"], rec.scores[6:],
                               rtol=1e-5, atol=1e-6)
    # the pre-kill prefix matched the reference bitwise (same topology)
    np.testing.assert_allclose(out1["scores"][:6], rec.scores[:6],
                               rtol=0, atol=0)


# -- CLI: mesh + checkpoint-dir + zero1 -------------------------------------

def test_cli_mesh_checkpoint_resume_and_zero1(tmp_path, capsys):
    from deeplearning4j_tpu.cli.driver import main

    out = str(tmp_path / "out")
    ck = str(tmp_path / "ck")
    argv = ["train", "--input", "iris:144", "--zoo", "mlp:hidden=8",
            "--output", out, "--runtime", "mesh", "--normalize",
            "--checkpoint-dir", ck,
            "--properties", "epochs=1,batch=16,checkpoint_every=3"]
    assert main(argv) == 0
    j = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert j["resumed_from_step"] is None
    assert j["checkpoint_write_seconds"] >= 0
    assert os.path.isdir(ck)

    assert main(argv) == 0  # rerun: resumes at the final cursor
    j2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert j2["resumed_from_step"] == 9  # 144 rows / 16 = 9 batches

    with pytest.raises(SystemExit, match="--runtime mesh"):
        main(["train", "--input", "iris:144", "--zoo", "mlp:hidden=8",
              "--output", out, "--zero1"])

    assert main(["train", "--input", "iris:144", "--zoo", "mlp:hidden=8",
                 "--output", out, "--runtime", "mesh", "--normalize",
                 "--zero1", "--properties", "epochs=1,batch=16"]) == 0
    j3 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert j3["score"] > 0
