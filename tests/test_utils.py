"""Utility tests — reference `util/` test parity (MathUtilsTest,
ViterbiTest behavior, DiskBasedQueue, collections)."""

import numpy as np
import pytest

from deeplearning4j_tpu.utils import (
    Counter, CounterMap, DiskBasedQueue, Index, MultiDimensionalMap,
    Viterbi, load_object, save_object)
from deeplearning4j_tpu.utils import math_utils as mu
from deeplearning4j_tpu.utils.string_grid import StringGrid, fingerprint
from deeplearning4j_tpu.utils.timeseries import (
    difference, lagged, moving_window_matrix)


class TestMathUtils:
    def test_normalize_and_discretize(self):
        assert mu.normalize(5.0, 0.0, 10.0) == 0.5
        assert mu.discretize(0.95, 0.0, 1.0, 10) == 9
        assert mu.discretize(-5.0, 0.0, 1.0, 10) == 0

    def test_entropy_information_gain(self):
        assert mu.entropy([0.5, 0.5]) == pytest.approx(np.log(2))
        assert mu.entropy([1.0]) == 0.0
        ig = mu.information_gain([0.5, 0.5], [[1.0], [1.0]], [0.5, 0.5])
        assert ig == pytest.approx(np.log(2))

    def test_log_add_matches_direct(self):
        a, b = np.log(0.3), np.log(0.4)
        assert mu.log_add(a, b) == pytest.approx(np.log(0.7))
        assert mu.log_sum([a, b, np.log(0.3)]) == pytest.approx(0.0, abs=1e-12)

    def test_stats(self):
        x = [1.0, 2.0, 3.0, 4.0]
        assert mu.mean(x) == 2.5
        assert mu.variance(x) == pytest.approx(np.var(x, ddof=1))
        assert mu.correlation(x, [2.0, 4.0, 6.0, 8.0]) == pytest.approx(1.0)
        assert mu.euclidean_distance([0, 0], [3, 4]) == 5.0

    def test_bernoullis(self):
        assert mu.bernoullis(0.5, 2, 1) == pytest.approx(0.5)


class TestViterbi:
    def test_decodes_most_likely_path(self):
        # 2 states; strong self-transitions; observations flip mid-sequence
        log_trans = np.log(np.array([[0.9, 0.1], [0.1, 0.9]]))
        v = Viterbi(2, log_init=np.log([0.5, 0.5]), log_trans=log_trans)
        probs = np.array([[0.9, 0.1], [0.8, 0.2], [0.9, 0.1],
                          [0.1, 0.9], [0.2, 0.8], [0.1, 0.9]])
        path, best = v.decode_from_probs(probs)
        assert path.tolist() == [0, 0, 0, 1, 1, 1]
        assert np.isfinite(best)

    def test_sticky_transitions_smooth_noise(self):
        # a single noisy observation should not flip the state
        log_trans = np.log(np.array([[0.99, 0.01], [0.01, 0.99]]))
        v = Viterbi(2, log_trans=log_trans)
        probs = np.array([[0.9, 0.1], [0.4, 0.6], [0.9, 0.1], [0.9, 0.1]])
        path, _ = v.decode_from_probs(probs)
        assert path.tolist() == [0, 0, 0, 0]


class TestCollections:
    def test_counter(self):
        c = Counter()
        c.increment_count("a", 2.0)
        c.increment_count("b")
        assert c.get_count("a") == 2.0
        assert c.arg_max() == "a"
        assert c.total_count() == 3.0
        c.normalize()
        assert c.get_count("b") == pytest.approx(1 / 3)
        assert c.keys_sorted_by_count() == ["a", "b"]

    def test_counter_map(self):
        cm = CounterMap()
        cm.increment_count("x", "y", 3.0)
        cm.increment_count("x", "z", 1.0)
        assert cm.get_count("x", "y") == 3.0
        assert cm.get_count("missing", "y") == 0.0
        assert cm.total_count() == 4.0

    def test_multidimensional_map(self):
        m = MultiDimensionalMap()
        m.put(1, "a", "v")
        assert m.get(1, "a") == "v"
        assert m.contains(1, "a") and not m.contains(1, "b")
        m.remove(1, "a")
        assert len(m) == 0

    def test_index(self):
        idx = Index()
        assert idx.add("w") == 0
        assert idx.add("w") == 0
        assert idx.add("v") == 1
        assert idx.index_of("v") == 1
        assert idx.index_of("missing") == -1
        assert idx.get(0) == "w"


class TestDiskQueue:
    def test_fifo_roundtrip(self, tmp_path):
        q = DiskBasedQueue(str(tmp_path / "q"))
        for i in range(5):
            q.add({"i": i, "arr": np.arange(3) * i})
        assert len(q) == 5
        assert q.peek()["i"] == 0
        got = [q.poll()["i"] for _ in range(5)]
        assert got == [0, 1, 2, 3, 4]
        assert q.poll() is None and q.is_empty()
        q.close()


class TestSerialization:
    def test_roundtrip(self, tmp_path):
        obj = {"a": np.arange(4), "b": [1, "two"]}
        p = str(tmp_path / "obj.pkl")
        save_object(obj, p)
        back = load_object(p)
        assert back["b"] == [1, "two"]
        assert np.array_equal(back["a"], obj["a"])


class TestStringGrid:
    def test_fingerprint_clusters_near_duplicates(self):
        assert fingerprint("The  Quick, Brown!") == fingerprint(
            "brown quick the")
        g = StringGrid.from_lines(
            ["Apple Inc.,1", "apple inc,2", "Banana,3"])
        clusters = g.cluster_column(0)
        assert sorted(map(len, clusters.values())) == [1, 2]
        assert len(g.dedup_by_column(0)) == 2

    def test_string_cluster(self):
        """`util/StringCluster.java` parity: variant counts per
        fingerprint, largest cluster first, canonical variant."""
        from deeplearning4j_tpu.utils.string_grid import StringCluster

        c = StringCluster(["Apple Inc.", "apple inc", "apple inc",
                           "Banana", "Cherry", "cherry!"])
        assert len(c) == 3
        assert c.clusters()[0] == {"Apple Inc.": 1, "apple inc": 2}
        assert c.canonical("APPLE, inc") == "apple inc"
        assert c.canonical("unknown thing") == "unknown thing"


class TestTimeSeries:
    def test_moving_window_matrix(self):
        x = np.arange(5)
        w = moving_window_matrix(x, 3)
        assert w.shape == (3, 3)
        assert w[0].tolist() == [0, 1, 2]
        assert w[-1].tolist() == [2, 3, 4]
        w2 = moving_window_matrix(x, 3, add_rotate=True)
        assert w2.shape == (6, 3)

    def test_lagged(self):
        m = lagged(np.array([1, 2, 3, 4]), 2)
        assert m.shape == (2, 3)
        assert m[0].tolist() == [3, 2, 1]

    def test_difference(self):
        assert difference([1, 4, 9]).tolist() == [3, 5]

    def test_window_too_large_raises(self):
        with pytest.raises(ValueError):
            moving_window_matrix(np.arange(3), 5)


class TestParallelization:
    def test_run_in_parallel_results_in_order(self):
        from deeplearning4j_tpu.utils.parallelization import (
            iterate_in_parallel, run_in_parallel)

        out = run_in_parallel([lambda i=i: i * i for i in range(20)])
        assert out == [i * i for i in range(20)]
        assert run_in_parallel([]) == []
        assert iterate_in_parallel([3, 1, 2], lambda v: v + 10) == [13, 11, 12]

    def test_exception_propagates(self):
        import pytest

        from deeplearning4j_tpu.utils.parallelization import run_in_parallel

        def boom():
            raise RuntimeError("task failed")

        with pytest.raises(RuntimeError, match="task failed"):
            run_in_parallel([lambda: 1, boom, lambda: 2])


class TestArchive:
    def test_all_formats_roundtrip(self, tmp_path):
        """`util/ArchiveUtils.unzipFileTo` parity: tar.gz / zip / gz all
        extract into the target dir; unknown formats raise."""
        import gzip
        import tarfile
        import zipfile

        from deeplearning4j_tpu.utils.archive import unzip_file_to

        src = tmp_path / "payload.txt"
        src.write_text("hello archives")

        tgz = tmp_path / "a.tar.gz"
        with tarfile.open(tgz, "w:gz") as t:
            t.add(src, arcname="inner/payload.txt")
        unzip_file_to(str(tgz), str(tmp_path / "out_tgz"))
        assert (tmp_path / "out_tgz/inner/payload.txt").read_text() \
            == "hello archives"

        zf = tmp_path / "a.zip"
        with zipfile.ZipFile(zf, "w") as z:
            z.write(src, "z/payload.txt")
        unzip_file_to(str(zf), str(tmp_path / "out_zip"))
        assert (tmp_path / "out_zip/z/payload.txt").exists()

        gz = tmp_path / "solo.txt.gz"
        with gzip.open(gz, "wb") as g:
            g.write(b"gz body")
        unzip_file_to(str(gz), str(tmp_path / "out_gz"))
        assert (tmp_path / "out_gz/solo.txt").read_bytes() == b"gz body"

        with pytest.raises(ValueError, match="unsupported"):
            unzip_file_to(str(src), str(tmp_path / "nope"))
