"""Dtype-policy guard (ISSUE 8 satellite): importing the whole package
must never flip `jax_enable_x64`, and no module may pin a float64 array
at module scope — the serve-precision policies (f32/bf16/int8) assume
float32 is the ceiling everywhere, and a stray x64 flip would silently
double every program's memory and invalidate the compile caches.

Tier-1: CPU-only, import-time checks.
"""

import importlib
import pkgutil

import jax
import numpy as np

import deeplearning4j_tpu


def _walk_modules():
    names = ["deeplearning4j_tpu"]
    for info in pkgutil.walk_packages(deeplearning4j_tpu.__path__,
                                      prefix="deeplearning4j_tpu."):
        names.append(info.name)
    return sorted(names)


def _import_all():
    mods = []
    for name in _walk_modules():
        try:
            mods.append(importlib.import_module(name))
        except ImportError:
            # optional-dependency module (gated native/plotting extras):
            # absent deps are fine, flipped dtype policy is not
            continue
        assert not jax.config.jax_enable_x64, (
            f"importing {name} flipped jax_enable_x64")
    return mods


def test_importing_every_module_leaves_x64_off():
    mods = _import_all()
    assert len(mods) > 30  # the walk actually covered the package
    assert not jax.config.jax_enable_x64


def test_no_module_level_float64_arrays():
    """Module-scope constants (lookup tables, init grids) must be
    float32 or narrower so they never widen a traced program."""
    def is_f64(v):
        return (isinstance(v, (np.ndarray, np.generic))
                and v.dtype == np.float64) or (
            isinstance(v, jax.Array) and v.dtype == jax.numpy.float64)

    offenders = []
    for mod in _import_all():
        for attr, value in vars(mod).items():
            if attr.startswith("__"):
                continue
            values = (list(value.values()) if isinstance(value, dict)
                      else list(value) if isinstance(value, (list, tuple))
                      else [value])
            for v in values:
                if is_f64(v):
                    offenders.append(f"{mod.__name__}.{attr}")
    assert not offenders, offenders
