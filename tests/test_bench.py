"""Orchestration tests for bench.py (r3 weak #1 regression guards).

Round 3 shipped zero metrics because one timeout discarded the child's
partial stdout and consumed the whole driver budget.  These tests pin the
fixed behavior: streamed partial metrics survive a killed child, retries
resume from the skip-list instead of restarting, and a full SMALL run
emits every metric with rc=0.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _env(**extra):
    env = dict(os.environ)
    env.update({"DL4J_BENCH_SMALL": "1", "JAX_PLATFORMS": "cpu",
                "DL4J_BENCH_PLATFORM": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    env.update(extra)
    return env


@pytest.mark.slow
def test_small_suite_emits_all_metrics_rc0():
    proc = subprocess.run([sys.executable, BENCH], env=_env(),
                          capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    metrics = {l["metric"] for l in lines}
    assert len(lines) == len(metrics), "duplicate metric lines"
    # every line is driver-parseable: metric/value/unit/vs_baseline keys
    for l in lines:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(l)
        assert "__done__" not in l
    # BASELINE five + heavyweights (north-star CLI emits two lines)
    expected_frags = ["LeNet5-MNIST", "charLSTM-PTB", "VGG-CIFAR10",
                      "Word2Vec", "all-reduce", "charLSTM-4layer",
                      "north-star CLI LeNet-MNIST",
                      "north-star CLI charLSTM-4layer", "charTransformer"]
    for frag in expected_frags:
        assert any(frag in m for m in metrics), f"missing metric: {frag}"


@pytest.mark.slow
def test_partial_metrics_survive_attempt_timeout():
    """Kill the child mid-suite: already-emitted metrics must still be on
    the parent's stdout (the exact r3 failure mode)."""
    # 45s per attempt: enough for the first bench or two in SMALL mode on
    # CPU, not the whole suite; single attempt so the run stays short
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=_env(DL4J_BENCH_ATTEMPT_S="45", DL4J_BENCH_PER_BENCH_S="40"),
        capture_output=True, text=True, timeout=300)
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    # whatever completed before the kill was forwarded, not discarded
    if lines:
        for l in lines:
            assert "metric" in l
    # resume across attempts is reported on stderr
    assert "benches done" in proc.stderr or proc.returncode == 0


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    bench_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_mod)
    return bench_mod


def test_skip_env_resumes_instead_of_restarting():
    """With every bench pre-marked done, the suite exits 0 instantly
    without claiming a device (proves the skip-list short-circuit)."""
    bench_mod = _load_bench()
    skip = ",".join(b.__name__ for b in bench_mod.BENCHES)
    proc = subprocess.run(
        [sys.executable, BENCH], env=_env(DL4J_BENCH_SKIP=skip),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    assert proc.stdout.strip() == ""


def test_claim_cap_timeout_arithmetic():
    """claim_cap_s: budget bound, remaining-minus-reserve bound, 60s
    floor on the remaining term, and the explicit-budget escape hatch
    the orchestration test below relies on."""
    bench_mod = _load_bench()
    cap = bench_mod.claim_cap_s
    reserve = bench_mod.CPU_FALLBACK_RESERVE_S
    # plentiful global budget: the claim budget binds
    assert cap(10_000.0, 460.0) == 460.0
    # tight global budget: the claim must leave the CPU-fallback reserve
    # (a wedge-kill with nothing left to relaunch on is the r05 blindness)
    assert cap(reserve + 120.0, 500.0) == 120.0
    # 60s floor on the remaining-based bound (a sub-minute window would
    # fail even an uncontended tunnel claim) — including exhausted budget
    assert cap(reserve + 10.0, 500.0) == 60.0
    assert cap(-5.0, 500.0) == 60.0
    # an explicit budget below the floor still wins: the DL4J_BENCH_CLAIM_S
    # knob must be able to shorten the watchdog for tests
    assert cap(10_000.0, 5.0) == 5.0
    # production default: claim cap + reserve fit inside the global budget
    assert cap(bench_mod.GLOBAL_BUDGET_S) + reserve <= bench_mod.GLOBAL_BUDGET_S


def test_claim_cap_default_budget_is_a_third_of_global():
    bench_mod = _load_bench()
    assert bench_mod.CLAIM_BUDGET_S == bench_mod.GLOBAL_BUDGET_S // 3
    assert bench_mod.claim_cap_s(1e9) == float(bench_mod.CLAIM_BUDGET_S)


def test_wedged_claim_killed_and_relaunched_on_cpu():
    """The BENCH_r05 failure mode: a device claim that blocks INSIDE
    jax.devices() never returns to the child's own retry-deadline check,
    so the cap used to be decorative (heartbeat ran to 1350s, 0/8
    benches).  The parent watchdog must kill the wedged child at
    claim cap + grace and relaunch it with the CPU fallback forced,
    and the relaunched child must get all the way to emitting metric
    lines tagged `backend: cpu_fallback` (r05's watchdog "worked" and
    still shipped an empty artifact — the end state that matters is
    >=1 _emit line, not the kill).  Deliberately NOT marked slow: this
    is the unblinding path and must run in tier-1."""
    bench_mod = _load_bench()
    # one cheap bench is enough to prove the relaunched child produces
    # tagged metrics; skip the rest to keep the test short
    skip = ",".join(b.__name__ for b in bench_mod.BENCHES
                    if b.__name__ != "bench_infer_latency")
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=_env(DL4J_BENCH_FAKE_CLAIM_HANG_S="3600",
                 DL4J_BENCH_CLAIM_S="5",
                 DL4J_BENCH_CLAIM_GRACE_S="2",
                 DL4J_BENCH_SKIP=skip),
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "claim cap (device claim wedged in backend init)" in proc.stderr
    assert "forcing tagged CPU fallback" in proc.stderr
    assert "CPU fallback forced by orchestrator" in proc.stderr
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    # end-to-end: the relaunched child reached at least one _emit line
    metric_lines = [l for l in lines if "metric" in l]
    assert metric_lines, proc.stderr[-2000:]
    for l in lines:
        assert l.get("backend") == "cpu_fallback", l


def test_claim_pending_kill_at_global_deadline_forces_cpu(capfd):
    """The branch r05 actually died on: the global budget expires while
    the claim is still pending (claim cap >= global deadline, e.g. a
    driver-configured DL4J_BENCH_CLAIM_S larger than the remaining
    budget).  The old code only flagged claim-cap kills for relaunch, so
    this kill returned claim_ok=True and no CPU fallback ever ran.  Any
    kill while the claim pends must now signal the relaunch."""
    bench_mod = _load_bench()
    env = _env(DL4J_BENCH_FAKE_CLAIM_HANG_S="3600")
    claim_ok = bench_mod._stream_attempt(
        env, set(), set(), time.time() + 3.0, force_cpu=False)
    err = capfd.readouterr().err
    assert "global budget (claim pending)" in err
    assert claim_ok is False, "unclaimed kill at the global deadline " \
                              "must trigger the forced-CPU relaunch"
