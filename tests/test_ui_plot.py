"""UI server + plotting tests — reference `deeplearning4j-ui` resource
behavior and `plot/NeuralNetPlotter`/`FilterRenderer` capability."""

import json
import os
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.plot.plotter import (
    FilterRenderer, NeuralNetPlotter, PlotIterationListener)
from deeplearning4j_tpu.ui import UiServer


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def server():
    s = UiServer().start()
    yield s
    s.stop()


class TestUiServer:
    def test_coords_roundtrip(self, server):
        coords = [[0.0, 1.0], [2.0, 3.0]]
        out = _post(server.url + "/api/coords",
                    {"coords": coords, "labels": ["a", "b"]})
        assert out["n"] == 2
        back = _get(server.url + "/api/coords")
        assert back["coords"] == coords
        assert back["labels"] == ["a", "b"]

    def test_nearest_neighbors(self, server):
        rng = np.random.RandomState(0)
        vecs = rng.randn(20, 8)
        vecs[3] = vecs[7] + 0.001  # make w3 ~ w7
        labels = [f"w{i}" for i in range(20)]
        _post(server.url + "/api/vectors",
              {"vectors": vecs.tolist(), "labels": labels})
        out = _get(server.url + "/api/nearest?word=w3&k=3")
        assert out["nearest"][0] == "w7"

    def test_nearest_unknown_word_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server.url + "/api/nearest?word=zzz")
        assert e.value.code == 404

    def test_server_side_tsne(self, server):
        rng = np.random.RandomState(1)
        vecs = np.vstack([rng.randn(10, 5), rng.randn(10, 5) + 4])
        _post(server.url + "/api/vectors", {"vectors": vecs.tolist(),
                                            "labels": []})
        out = _post(server.url + "/api/tsne",
                    {"iters": 150, "perplexity": 5.0})
        assert out["n"] == 20
        coords = _get(server.url + "/api/coords")["coords"]
        assert len(coords) == 20

    def test_weights_endpoint(self, server):
        _post(server.url + "/api/weights",
              {"0/W": np.random.RandomState(2).randn(10, 4).tolist()})
        out = _get(server.url + "/api/weights")
        assert "0/W" in out
        assert len(out["0/W"]["hist"]) == 30

    def test_html_view(self, server):
        with urllib.request.urlopen(server.url + "/", timeout=10) as r:
            assert b"canvas" in r.read()


class TestRendersEndpoint:
    """VERDICT r4 missing #5 / next-#7: `GET /api/renders` +
    image fetch serve what plot/plotter.py produced
    (reference `ui/renders/RendersResource.java` + RenderView)."""

    def test_renders_listing_and_fetch(self, tmp_path):
        p = NeuralNetPlotter(str(tmp_path))
        p.plot_weight_histograms(({"W": np.random.randn(6, 4)},))
        FilterRenderer(str(tmp_path)).render_filters(
            np.random.randn(16, 6), name="filters")
        s = UiServer(renders_dir=str(tmp_path)).start()
        try:
            listing = _get(s.url + "/api/renders")["images"]
            assert len(listing) >= 2
            assert any("filters" in n for n in listing)
            with urllib.request.urlopen(
                    s.url + "/api/renders/" + listing[0], timeout=10) as r:
                assert r.headers["Content-Type"].startswith("image/")
                assert len(r.read()) > 100
            with urllib.request.urlopen(s.url + "/render", timeout=10) as r:
                html = r.read().decode()
                assert listing[0] in html
        finally:
            s.stop()

    def test_renders_404_and_traversal_safe(self, tmp_path):
        (tmp_path / "secret.txt").write_text("x")
        s = UiServer(renders_dir=str(tmp_path)).start()
        try:
            assert _get(s.url + "/api/renders")["images"] == []
            for bad in ("/api/renders/nope.png",
                        "/api/renders/..%2Fsecret.txt"):
                with pytest.raises(urllib.error.HTTPError) as e:
                    _get(s.url + bad)
                assert e.value.code == 404
        finally:
            s.stop()

    def test_renders_empty_without_dir(self, server):
        assert _get(server.url + "/api/renders")["images"] == []


class TestPlotter:
    def test_weight_histograms(self, tmp_path):
        p = NeuralNetPlotter(str(tmp_path))
        params = ({"W": np.random.randn(10, 5), "b": np.zeros(5)},
                  {"W": np.random.randn(5, 2)})
        path = p.plot_weight_histograms(params)
        assert os.path.isfile(path) and os.path.getsize(path) > 0

    def test_filter_renderer_dense_and_conv(self, tmp_path):
        f = FilterRenderer(str(tmp_path))
        path = f.render_filters(np.random.randn(16, 6), name="dense")
        assert os.path.isfile(path)
        path = f.render_filters(np.random.randn(3, 3, 1, 8), name="conv")
        assert os.path.isfile(path)

    def test_filter_bad_shape_raises(self, tmp_path):
        with pytest.raises(ValueError):
            FilterRenderer(str(tmp_path)).render_filters(
                np.random.randn(7, 4))

    def test_plot_listener(self, tmp_path):
        class FakeModel:
            params = ({"W": np.random.randn(4, 3)},)

        li = PlotIterationListener(str(tmp_path), every=2)
        for i in range(4):
            li.iteration_done(FakeModel(), i, 1.0 / (i + 1))
        assert any(n.startswith("weights-") for n in os.listdir(tmp_path))
        assert os.path.isfile(os.path.join(tmp_path, "score.png"))
