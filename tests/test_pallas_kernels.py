"""Pallas kernels vs jax-level references (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nd.attention import full_attention
from deeplearning4j_tpu.nd.pallas_kernels import (flash_attention,
                                                  fused_lstm_step,
                                                  scatter_add_rows)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_full(causal):
    k = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(k, 3)
    B, S, H, D = 2, 32, 2, 8
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    kk_ = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)
    ref = full_attention(q, kk_, v, causal=causal)
    out = flash_attention(q, kk_, v, causal, 8, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_grads():
    k = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(k, 3)
    B, S, H, D = 1, 16, 2, 4
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    kk_ = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)

    g_fl = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, True, 8, 8) ** 2), argnums=(0, 1, 2))(
        q, kk_, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        full_attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(
        q, kk_, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_fused_lstm_step_matches_reference():
    key = jax.random.PRNGKey(2)
    ks = jax.random.split(key, 6)
    B, I, H = 4, 8, 16
    x = jax.random.normal(ks[0], (B, I))
    h = jax.random.normal(ks[1], (B, H))
    c = jax.random.normal(ks[2], (B, H))
    wx = jax.random.normal(ks[3], (I, 4 * H)) * 0.1
    wh = jax.random.normal(ks[4], (H, 4 * H)) * 0.1
    b = jax.random.normal(ks[5], (4 * H,)) * 0.1

    h_new, c_new = fused_lstm_step(x, h, c, wx, wh, b)

    z = x @ wx + h @ wh + b
    i, f, o, g = (jax.nn.sigmoid(z[:, :H]), jax.nn.sigmoid(z[:, H:2 * H]),
                  jax.nn.sigmoid(z[:, 2 * H:3 * H]), jnp.tanh(z[:, 3 * H:]))
    c_ref = f * c + i * g
    h_ref = o * jnp.tanh(c_ref)
    np.testing.assert_allclose(np.asarray(h_new), np.asarray(h_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c_new), np.asarray(c_ref),
                               rtol=1e-5, atol=1e-5)


def test_scatter_add_rows_with_duplicates():
    table = jnp.zeros((10, 4), jnp.float32)
    idx = jnp.array([1, 3, 1, 7], jnp.int32)
    upd = jnp.arange(16, dtype=jnp.float32).reshape(4, 4)
    out = scatter_add_rows(table, idx, upd)
    ref = np.zeros((10, 4), np.float32)
    for i, r in zip([1, 3, 1, 7], np.asarray(upd)):
        ref[i] += r
    np.testing.assert_allclose(np.asarray(out), ref)


def test_scatter_add_rows_ragged_padding():
    table = jnp.ones((6, 4), jnp.float32)
    idx = jnp.array([5, 0, 5], jnp.int32)  # 3 rows -> padded to 8 internally
    upd = jnp.ones((3, 4), jnp.float32)
    out = scatter_add_rows(table, idx, upd)
    ref = np.ones((6, 4), np.float32)
    ref[5] += 2.0
    ref[0] += 1.0
    np.testing.assert_allclose(np.asarray(out), ref)


def test_attention_layer_flash_impl():
    from deeplearning4j_tpu.nn.conf import LayerType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import get_layer

    conf = NeuralNetConfiguration(layer_type=LayerType.ATTENTION, n_in=16,
                                  n_out=16, n_heads=4, causal=True,
                                  attention_block_size=8,
                                  attention_impl="flash")
    layer = get_layer(conf.layer_type)
    params = layer.init(jax.random.PRNGKey(0), conf)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16))
    y = layer.forward(params, conf, x)
    conf_full = conf.replace(attention_impl="full")
    y_ref = layer.forward(params, conf_full, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=2e-5, atol=2e-5)


def test_lstm_layer_fused_matches_scan():
    from deeplearning4j_tpu.nn.conf import LayerType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import get_layer

    conf = NeuralNetConfiguration(layer_type=LayerType.LSTM, n_in=8,
                                  n_out=16, lstm_impl="scan")
    layer = get_layer(conf.layer_type)
    params = layer.init(jax.random.PRNGKey(0), conf)
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 7, 8))
    y_scan = layer.forward(params, conf, x)
    y_fused = layer.forward(params, conf.replace(lstm_impl="fused"), x)
    np.testing.assert_allclose(np.asarray(y_fused), np.asarray(y_scan),
                               rtol=1e-5, atol=1e-5)


def test_lstm_layer_fused_grads_match_scan():
    from deeplearning4j_tpu.nn.conf import LayerType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import get_layer

    conf = NeuralNetConfiguration(layer_type=LayerType.LSTM, n_in=4,
                                  n_out=8, lstm_impl="scan")
    layer = get_layer(conf.layer_type)
    params = layer.init(jax.random.PRNGKey(2), conf)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 5, 4))

    def loss(p, c):
        return jnp.sum(layer.forward(p, c, x) ** 2)

    g_scan = jax.grad(loss)(params, conf)
    g_fused = jax.grad(loss)(params, conf.replace(lstm_impl="fused"))
    for k in g_scan:
        np.testing.assert_allclose(np.asarray(g_fused[k]),
                                   np.asarray(g_scan[k]),
                                   rtol=1e-4, atol=1e-5)
