"""Mesh-sharded inference (ISSUE 7 tentpole a): the serve-path compile
cache shards coalesced rows across a `Mesh(('batch',))` with replicated
params, sharding joins the cache key so single-chip and mesh programs
coexist (memory AND disk), buckets round to mesh multiples, and mesh
outputs stay bitwise-identical to the single-chip path.

Tier-1: CPU-only (1-device fallback mesh); the 2-device subprocess
bitwise check is marked slow."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.infer_cache import InferCache
from deeplearning4j_tpu.optimize.persist import PersistentProgramStore
from deeplearning4j_tpu.parallel.mesh import (SERVE_AXIS, infer_shardings,
                                              serve_mesh)

N_IN, N_OUT = 6, 3


def _net(seed=0):
    return MultiLayerNetwork(mlp(n_in=N_IN, hidden=[8], n_out=N_OUT,
                                 lr=0.05), seed=seed).init()


def _x(rows, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(rows, N_IN).astype(np.float32))


# -- mesh helpers ------------------------------------------------------------

def test_serve_mesh_shape_and_shardings():
    mesh = serve_mesh()
    assert mesh.axis_names == (SERVE_AXIS,)
    assert mesh.devices.size == len(jax.devices())
    rep, bat = infer_shardings(mesh)
    assert rep.spec == jax.sharding.PartitionSpec()
    assert bat.spec == jax.sharding.PartitionSpec(SERVE_AXIS)


# -- bitwise parity ----------------------------------------------------------

def test_mesh_output_bitwise_identical_to_direct():
    """The acceptance bar: mesh-sharded rows == direct net.output()
    bit-for-bit (1-device CPU mesh; sharding is a cache-key dimension,
    not a numeric change)."""
    net = _net()
    x = _x(5, seed=1)
    direct = np.asarray(net.output(x))
    net.set_serve_mesh()
    mesh_out = np.asarray(net.output(x))
    np.testing.assert_array_equal(direct, mesh_out)


def test_mesh_feed_forward_and_loss_bitwise():
    net = _net()
    x = _x(4, seed=2)
    y = jnp.asarray(np.eye(N_OUT, dtype=np.float32)[
        np.random.RandomState(3).randint(0, N_OUT, 4)])
    direct_ff = [np.asarray(a) for a in net.feed_forward(x)]
    direct_loss = float(net.score(x, y))
    net.set_serve_mesh()
    mesh_ff = [np.asarray(a) for a in net.feed_forward(x)]
    mesh_loss = float(net.score(x, y))
    assert len(direct_ff) == len(mesh_ff)
    for a, b in zip(direct_ff, mesh_ff):
        np.testing.assert_array_equal(a, b)
    assert direct_loss == mesh_loss  # f32-bit-equal


# -- sharding as a cache-key dimension ---------------------------------------

def test_single_and_mesh_programs_coexist_without_eviction():
    """Same (entry, fingerprint, bucket) under both shardings: two cache
    entries, and flipping back re-HITS the original program instead of
    recompiling (no eviction thrash)."""
    net = _net()
    cache = net.infer_cache
    x = _x(4)
    net.output(x)
    assert cache.stats.misses == 1
    net.set_serve_mesh()
    net.output(x)
    assert cache.stats.misses == 2  # mesh program is its own entry
    tags = {k[-1] for k in cache._programs}
    assert InferCache.SINGLE in tags
    assert any(isinstance(t, tuple) and t[0] == "mesh" for t in tags)
    # flip back and forth: pure hits from here on
    cache.set_mesh(None)
    net.output(x)
    net.set_serve_mesh()
    net.output(x)
    assert cache.stats.misses == 2
    assert cache.stats.hits >= 2
    assert len(cache._programs) == 2


def test_sharding_tag_distinguishes_mesh_shapes():
    c = InferCache()
    assert c.sharding_tag() == InferCache.SINGLE
    c.set_mesh(serve_mesh())
    tag = c.sharding_tag()
    assert tag[0] == "mesh" and tag[1] == (SERVE_AXIS,)
    c.set_mesh(None)
    assert c.sharding_tag() == InferCache.SINGLE


# -- bucket rounding under a mesh --------------------------------------------

def test_serve_bucket_rounds_to_mesh_multiple(monkeypatch):
    """With m devices every bucket must split evenly: known divisible
    buckets are reused, otherwise the bucket grows to the next multiple
    of m (simulated 4-way mesh on 1 CPU device)."""
    c = InferCache()
    c.set_mesh(serve_mesh())
    monkeypatch.setattr(c, "_mesh_rows", lambda: 4)
    assert c._serve_bucket(5) == 8   # ceil(5/4)*4, registered
    assert 8 in c.buckets
    assert c._serve_bucket(3) == 8   # smallest known divisible bucket
    assert c._serve_bucket(8) == 8
    assert c._serve_bucket(9) == 12
    # single-chip calls still use plain bucket growth
    monkeypatch.setattr(c, "_mesh_rows", lambda: 1)
    assert c._serve_bucket(5) == 8


def test_fixed_buckets_respected_under_mesh(monkeypatch):
    c = InferCache(buckets=(4, 16))
    c.set_mesh(serve_mesh())
    monkeypatch.setattr(c, "_mesh_rows", lambda: 4)
    assert c._serve_bucket(5) == 16   # next fixed divisible bucket
    assert c._serve_bucket(17) == 20  # target beyond fixed list, not stored
    assert list(c.buckets) == [4, 16]


# -- disk persistence of mesh-keyed programs ---------------------------------

def test_mesh_programs_persist_and_disk_hit(tmp_path):
    """Mesh programs round-trip the disk store under their own key: a
    restarted process with the same mesh disk-hits, and the single-chip
    entry for the same bucket lives alongside it."""
    net = _net()
    store = PersistentProgramStore(str(tmp_path))
    net.infer_cache.set_persist(store)
    x = _x(4, seed=5)
    single = np.asarray(net.output(x))       # single-chip entry
    net.set_serve_mesh()
    meshed = np.asarray(net.output(x))       # mesh entry
    np.testing.assert_array_equal(single, meshed)
    assert store.writes == 2

    net2 = _net()
    net2.infer_cache.set_persist(PersistentProgramStore(str(tmp_path)))
    net2.set_serve_mesh()
    out2 = np.asarray(net2.output(x))
    assert net2.infer_cache.stats.misses == 0
    assert net2.infer_cache.stats.disk_hits == 1
    np.testing.assert_array_equal(meshed, out2)


# -- padding under mesh ------------------------------------------------------

def test_ragged_rows_pad_to_mesh_bucket_bitwise():
    """A ragged batch pads into a mesh-divisible bucket and the sliced
    rows still match the direct path bit-for-bit."""
    net = _net()
    for rows in (1, 3, 5, 7):
        x = _x(rows, seed=10 + rows)
        direct = np.asarray(net.output(x))
        net.set_serve_mesh()
        mesh_out = np.asarray(net.output(x))
        net.infer_cache.set_mesh(None)
        np.testing.assert_array_equal(direct, mesh_out,
                                      err_msg=f"rows={rows}")


# -- the real thing: 2 forced host devices, sharded execution ----------------

@pytest.mark.slow
def test_two_device_mesh_bitwise_subprocess():
    """On 2 forced host CPU devices the mesh program actually splits
    rows across devices — outputs must still be bitwise == direct."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = """
import numpy as np
from deeplearning4j_tpu.models.zoo import mlp
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
import jax
assert len(jax.devices()) == 2, jax.devices()
net = MultiLayerNetwork(mlp(n_in=6, hidden=[8], n_out=3, lr=0.05),
                        seed=0).init()
x = np.random.RandomState(0).randn(6, 6).astype("float32")
direct = np.asarray(net.output(x))
mesh = net.set_serve_mesh()
assert int(mesh.devices.size) == 2
out = np.asarray(net.output(x))
assert np.array_equal(direct, out)
print("OK")
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                         " --xla_force_host_platform_device_count=2")}
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=repo, env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "OK" in r.stdout
