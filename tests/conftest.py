"""Test configuration: run everything on a virtual 8-device CPU mesh.

This is the analog of the reference's in-JVM distributed test rig
(`BaseTestDistributed.java:34-98`, `IRUnitDriver.java:51`): distributed
logic is exercised against `xla_force_host_platform_device_count=8` virtual
devices so no TPU pod is needed (SURVEY §4 lesson).
"""

import os

# force CPU even when the ambient env selects a TPU platform: the virtual
# 8-device mesh only exists on the host platform
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env is set)

# the ambient axon/TPU plugin overrides JAX_PLATFORMS at import time;
# re-assert the host platform explicitly
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process integration tests (tens of seconds)")
