"""CharLSTM decode paths + recursive autoencoder."""

import numpy as np
import pytest

from deeplearning4j_tpu.models.char_lstm import CharLSTM
from deeplearning4j_tpu.models.recursive_autoencoder import (
    RecursiveAutoEncoder)


@pytest.fixture(scope="module")
def trained_lm():
    # deterministic cyclic corpus: "abcd" repeating
    return CharLSTM(hidden=32, seq_len=8, lr=0.2, iterations=120,
                    seed=0).fit("abcd" * 100)


def test_char_lstm_greedy_sampling_learns_cycle(trained_lm):
    out = trained_lm.sample("abc", n=8, temperature=0.0)
    assert out == "dabcdabc", out


def test_char_lstm_temperature_sampling_valid_chars(trained_lm):
    out = trained_lm.sample("ab", n=20, temperature=1.0, rng_seed=3)
    assert len(out) == 20
    assert set(out) <= set("abcd")


def test_char_lstm_beam_search_decodes_cycle(trained_lm):
    text, score = trained_lm.beam_search("abc", n=6, beam_width=3)
    assert text == "dabcda", (text, score)
    assert score <= 0.0  # total log-probability


def test_rae_learns_reconstruction():
    trees = ["(0 (0 a) (0 b))", "(0 (0 (0 a) (0 b)) (0 c))",
             "(0 (0 c) (0 (0 a) (0 d)))"]
    rae = RecursiveAutoEncoder(dim=8, max_nodes=16, lr=0.1, seed=0)
    loss_first = rae.fit(trees, epochs=1)
    loss_last = rae.fit(trees, epochs=150)
    assert loss_last < loss_first * 0.5, (loss_first, loss_last)


def test_rae_encodes_and_scores():
    trees = ["(0 (0 a) (0 b))", "(0 (0 b) (0 c))"]
    rae = RecursiveAutoEncoder(dim=8, max_nodes=8, lr=0.1, seed=1)
    rae.fit(trees, epochs=100)
    vec = rae.encode("(0 (0 a) (0 b))")
    assert vec.shape == (8,)
    assert np.isfinite(vec).all()
    seen = rae.reconstruction_error("(0 (0 a) (0 b))")
    assert np.isfinite(seen) and seen >= 0
