"""Regression tests for review findings (iterator epochs, BN stats, registry)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator, MultipleEpochsIterator
from deeplearning4j_tpu.nn.conf import (
    LayerType, MultiLayerConfiguration, NeuralNetConfiguration,
    OptimizationAlgorithm,
)
from deeplearning4j_tpu.nn.layers import get_layer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def test_multiple_epochs_iterator_exact_epochs():
    data = DataSet(np.arange(8).reshape(4, 2).astype(np.float32),
                   np.eye(4, dtype=np.float32))
    it = MultipleEpochsIterator(2, ListDataSetIterator(data, batch_size=2))
    batches = list(it)
    assert len(batches) == 4  # 2 epochs x 2 batches, not 6
    it.reset()
    assert len(list(it)) == 4


def test_recursive_autoencoder_registered():
    impl = get_layer(LayerType.RECURSIVE_AUTOENCODER)
    conf = NeuralNetConfiguration(
        layer_type=LayerType.RECURSIVE_AUTOENCODER, n_in=6, n_out=4)
    p = impl.init(jax.random.PRNGKey(0), conf)
    out = impl.forward(p, conf, jnp.ones((2, 6)))
    assert out.shape == (2, 4)


def test_batchnorm_ema_refreshed_after_fit():
    confs = (
        NeuralNetConfiguration(layer_type=LayerType.BATCH_NORM, n_in=4, n_out=4),
        NeuralNetConfiguration(layer_type=LayerType.OUTPUT, n_in=4, n_out=2,
                               num_iterations=5,
                               optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT),
    )
    conf = MultiLayerConfiguration(confs=confs)
    net = MultiLayerNetwork(conf).init()
    x = np.random.RandomState(0).rand(32, 4).astype(np.float32) * 5 + 3
    y = np.eye(2, dtype=np.float32)[np.random.RandomState(1).randint(0, 2, 32)]
    net.fit(x, y)
    p = net.params[0]
    ema_mean = np.asarray(p["ema_mean"]) / max(float(p["ema_w"]), 1e-8)
    assert np.all(np.abs(ema_mean - x.mean(0)) < 0.5)  # refreshed, not zeros


def test_output_layer_regression_head_honors_activation():
    from deeplearning4j_tpu.nd.losses import LossFunction
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    conf = NeuralNetConfiguration(layer_type=LayerType.OUTPUT, n_in=3, n_out=2,
                                  loss_function=LossFunction.MSE,
                                  activation="sigmoid")
    p = OutputLayer.init(jax.random.PRNGKey(0), conf)
    out = OutputLayer.forward(p, conf, jnp.array([[10.0, -10.0, 10.0]]))
    assert np.all(np.asarray(out) >= 0) and np.all(np.asarray(out) <= 1)


def test_seed_zero_distinct_from_default():
    conf = MultiLayerConfiguration(confs=(
        NeuralNetConfiguration(layer_type=LayerType.OUTPUT, n_in=4, n_out=2),))
    w0 = np.asarray(MultiLayerNetwork(conf, seed=0).init().params[0]["W"])
    w123 = np.asarray(MultiLayerNetwork(conf, seed=123).init().params[0]["W"])
    assert not np.allclose(w0, w123)


def test_word2vec_tiny_corpus_tail_padding():
    """ADVICE r1: 0 < n_pairs < batch_size must not crash fit() — the pad
    wraps cyclically (np.resize) instead of slicing past the end."""
    from deeplearning4j_tpu.models.word2vec import Word2Vec

    sents = [["alpha", "beta", "gamma", "delta"],
             ["alpha", "gamma", "beta", "delta"]]
    w2v = Word2Vec(vector_length=8, window=2, negative=2,
                   min_word_frequency=1, batch_size=512, epochs=1, seed=0)
    w2v.fit(sents)  # n_pairs << 512: must pad, not raise
    assert np.isfinite(np.asarray(w2v.vector("alpha"))).all()


def test_char_lstm_short_text_clear_error():
    """ADVICE r1: text shorter than seq_len+1 raises a clear ValueError,
    not an opaque reshape failure."""
    import pytest

    from deeplearning4j_tpu.models.char_lstm import CharLSTM

    lm = CharLSTM(hidden=8, seq_len=32, iterations=1)
    with pytest.raises(ValueError, match="too short"):
        lm.fit("abc")


def test_char_lstm_beam_width_clamped_to_vocab():
    """ADVICE r1: beam_width > vocab must not desync beams vs hs/cs rows."""
    from deeplearning4j_tpu.models.char_lstm import CharLSTM

    lm = CharLSTM(hidden=8, seq_len=4, iterations=2, n_layers=1)
    lm.fit("abab" * 8)  # vocab = {a, b} -> v=2
    text, score = lm.beam_search("ab", n=6, beam_width=10)
    assert len(text) == 6
    assert np.isfinite(score)


def test_hessian_free_score_trace_finite():
    """ADVICE r1: rejected first HF proposal must not report +inf."""
    from deeplearning4j_tpu.nn.conf import (Activation, LossFunction,
                                            WeightInit)
    from deeplearning4j_tpu.optimize.solver import optimize

    conf = NeuralNetConfiguration(
        layer_type=LayerType.DENSE, n_in=2, n_out=2,
        optimization_algo=OptimizationAlgorithm.HESSIAN_FREE,
        num_iterations=4, lr=0.5)

    from deeplearning4j_tpu.optimize.solver import from_loss

    objective = from_loss(lambda params, key: jnp.sum((params["w"] - 3.0) ** 2))
    params0 = {"w": jnp.zeros((2, 2))}
    _, scores = optimize(objective, params0, conf, jax.random.PRNGKey(0))
    assert np.isfinite(np.asarray(scores)).all()


def test_batchnorm_running_ema_not_dominated_by_last_batch():
    """VERDICT r1 #6: inference stats are a true running EMA across fit
    batches, not a recompute from whichever batch came last."""
    confs = (
        NeuralNetConfiguration(layer_type=LayerType.BATCH_NORM, n_in=4,
                               n_out=4),
        NeuralNetConfiguration(
            layer_type=LayerType.OUTPUT, n_in=4, n_out=2, num_iterations=2,
            optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT),
    )
    conf = MultiLayerConfiguration(confs=confs)
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    big = rng.rand(64, 4).astype(np.float32) + 3.0      # mean ~3.5
    tiny = rng.rand(2, 4).astype(np.float32) + 30.0     # shifted outlier
    y_big = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 64)]
    y_tiny = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 2)]
    batches = [(big, y_big)] * 10 + [(tiny, y_tiny)]
    net.fit(batches)
    p = net.params[0]
    mean = np.asarray(p["ema_mean"]) / max(float(p["ema_w"]), 1e-8)
    # old post-hoc refresh would sit at ~30.5; the running EMA stays near
    # the dominant distribution (tiny batch contributes ~10%)
    assert np.all(mean < 10.0), mean
    assert np.all(mean > 3.0), mean


def test_batchnorm_ema_updates_inside_dp_train_step():
    """BN running stats advance inside the compiled dp step (global-batch
    statistics via psum), including on masked remainder batches."""
    from deeplearning4j_tpu.parallel import DataParallelTrainer, make_mesh

    confs = (
        NeuralNetConfiguration(layer_type=LayerType.BATCH_NORM, n_in=4,
                               n_out=4),
        NeuralNetConfiguration(
            layer_type=LayerType.OUTPUT, n_in=4, n_out=2, num_iterations=1,
            optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT),
    )
    conf = MultiLayerConfiguration(confs=confs)
    rng = np.random.RandomState(0)
    x = (rng.rand(30, 4).astype(np.float32) * 2 + 5)  # 30 % 8 != 0: masked
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 30)]
    net = MultiLayerNetwork(conf, seed=1).init()
    trainer = DataParallelTrainer(net, make_mesh({"dp": 8}), mode="sync")
    trainer.fit([(x, y)])
    p = trainer.state.params[0]
    ema_w = float(p["ema_w"])
    assert ema_w > 0.0
    mean = np.asarray(p["ema_mean"]) / ema_w
    np.testing.assert_allclose(mean, x.mean(0), rtol=0.05, atol=0.1)
