"""Regression tests for review findings (iterator epochs, BN stats, registry)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator, MultipleEpochsIterator
from deeplearning4j_tpu.nn.conf import (
    LayerType, MultiLayerConfiguration, NeuralNetConfiguration,
    OptimizationAlgorithm,
)
from deeplearning4j_tpu.nn.layers import get_layer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def test_multiple_epochs_iterator_exact_epochs():
    data = DataSet(np.arange(8).reshape(4, 2).astype(np.float32),
                   np.eye(4, dtype=np.float32))
    it = MultipleEpochsIterator(2, ListDataSetIterator(data, batch_size=2))
    batches = list(it)
    assert len(batches) == 4  # 2 epochs x 2 batches, not 6
    it.reset()
    assert len(list(it)) == 4


def test_recursive_autoencoder_registered():
    impl = get_layer(LayerType.RECURSIVE_AUTOENCODER)
    conf = NeuralNetConfiguration(
        layer_type=LayerType.RECURSIVE_AUTOENCODER, n_in=6, n_out=4)
    p = impl.init(jax.random.PRNGKey(0), conf)
    out = impl.forward(p, conf, jnp.ones((2, 6)))
    assert out.shape == (2, 4)


def test_batchnorm_ema_refreshed_after_fit():
    confs = (
        NeuralNetConfiguration(layer_type=LayerType.BATCH_NORM, n_in=4, n_out=4),
        NeuralNetConfiguration(layer_type=LayerType.OUTPUT, n_in=4, n_out=2,
                               num_iterations=5,
                               optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT),
    )
    conf = MultiLayerConfiguration(confs=confs)
    net = MultiLayerNetwork(conf).init()
    x = np.random.RandomState(0).rand(32, 4).astype(np.float32) * 5 + 3
    y = np.eye(2, dtype=np.float32)[np.random.RandomState(1).randint(0, 2, 32)]
    net.fit(x, y)
    ema_mean = np.asarray(net.params[0]["ema_mean"])
    assert np.all(np.abs(ema_mean - x.mean(0)) < 0.5)  # refreshed, not zeros


def test_output_layer_regression_head_honors_activation():
    from deeplearning4j_tpu.nd.losses import LossFunction
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    conf = NeuralNetConfiguration(layer_type=LayerType.OUTPUT, n_in=3, n_out=2,
                                  loss_function=LossFunction.MSE,
                                  activation="sigmoid")
    p = OutputLayer.init(jax.random.PRNGKey(0), conf)
    out = OutputLayer.forward(p, conf, jnp.array([[10.0, -10.0, 10.0]]))
    assert np.all(np.asarray(out) >= 0) and np.all(np.asarray(out) <= 1)


def test_seed_zero_distinct_from_default():
    conf = MultiLayerConfiguration(confs=(
        NeuralNetConfiguration(layer_type=LayerType.OUTPUT, n_in=4, n_out=2),))
    w0 = np.asarray(MultiLayerNetwork(conf, seed=0).init().params[0]["W"])
    w123 = np.asarray(MultiLayerNetwork(conf, seed=123).init().params[0]["W"])
    assert not np.allclose(w0, w123)
