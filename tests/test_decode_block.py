"""Fused multi-step decode: K tokens per host dispatch (ISSUE 19).

The correctness anchor: the K-step fused block (`lax.scan` over the
decode step, sampling in-program, state donated) must reproduce the
K=1 loop EXACTLY — the per-slot PRNG key splits exactly once per
emitted token and a slot that exhausts its budget mid-block freezes
(its KV rows stop mutating, the block emits the sentinel) — so the
token trajectory is bitwise-identical for ANY K, greedy and seeded
temperature, dense and paged, on both generative zoo models.  Around
that anchor: the batcher's adaptive-K policy (pending admissions pin
K to 1 so TTFT semantics never change), the speculative-decoding
pin, the chaos contract inside a block, and warm-start coverage of
the whole K ladder.

Tier-1: CPU-only, tiny models."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from test_generate import _compiled_tokens, lstm_net, transformer_net  # noqa: F401
from deeplearning4j_tpu.models.zoo import char_lstm
from deeplearning4j_tpu.nn import decode as decode_mod
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import tunables
from deeplearning4j_tpu.reliability import faults
from deeplearning4j_tpu.serving.batcher import ContinuousBatcher

VOCAB = 13


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# -- token parity: any K, any sampler, dense and paged ------------------------

@pytest.mark.parametrize("model", ["lstm", "transformer"])
@pytest.mark.parametrize("temperature", [0.0, 0.8])
@pytest.mark.parametrize("k", [1, 4, 8])
@pytest.mark.parametrize("paged", [False, True])
def test_fused_block_token_parity(request, model, temperature, k, paged):
    """steps_per_dispatch is a THROUGHPUT knob, never a sampling
    change: for every K the batcher's trajectory equals the K=1
    compiled loop token-for-token, greedy and seeded temperature,
    dense and paged."""
    net = request.getfixturevalue(f"{model}_net")
    prompts = ([1, 2, 3], [4, 5])
    refs = [_compiled_tokens(net, list(p), 10, temperature=temperature,
                             rng_seed=i)
            for i, p in enumerate(prompts)]
    cb = ContinuousBatcher(net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,),
                           page_size=4 if paged else 0,
                           steps_per_dispatch=k)
    try:
        streams = [cb.submit(list(p), max_new_tokens=10,
                             temperature=temperature, rng_seed=i)
                   for i, p in enumerate(prompts)]
        got = [list(s.tokens(timeout=60.0)) for s in streams]
        assert got == refs
    finally:
        cb.stop()


def test_fused_block_reaches_kmax_and_reports_overhead(lstm_net):
    """A slot-stable table ramps to K_max (the block-size histogram
    shows a K=8 bucket) and the stats block reports the host-overhead
    fraction the fused dispatch exists to amortise."""
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=32,
                           prompt_buckets=(8,), steps_per_dispatch=8)
    try:
        streams = [cb.submit([1, 2], max_new_tokens=28, rng_seed=i)
                   for i in range(2)]
        for s in streams:
            assert len(list(s.tokens(timeout=60.0))) == 28
        st = cb.stats()
        assert st["steps_per_dispatch"] == 8
        h = st["decode_block_steps"]
        assert h["count"] > 0
        # bounds (1, 2, 4, 8, 16): the ramp reached the K=8 bucket
        assert h["counts"][3] > 0
        assert 0.0 <= st["host_overhead_fraction"] <= 1.0
        assert st["decode_host_seconds_total"] > 0.0
    finally:
        cb.stop()


# -- mid-block freeze ---------------------------------------------------------

def test_decode_block_freezes_exhausted_rows(lstm_net):
    """Program-level: a row whose remaining budget runs out mid-block
    emits the sentinel for the frozen steps, its token/key carry stops
    advancing, and the emitted prefix equals the K=1 trajectory."""
    conf, params = lstm_net.conf, lstm_net.params
    ic = lstm_net.infer_cache
    refs = [_compiled_tokens(lstm_net, [1, 2, 3], 9, rng_seed=0),
            _compiled_tokens(lstm_net, [4, 5], 9, rng_seed=1)]
    state = ic.init_decode_state(conf, 2, 16)
    pb = np.zeros((2, 8), np.int32)
    pb[0, :3] = [1, 2, 3]
    pb[1, :2] = [4, 5]
    length = jnp.asarray([3, 2], jnp.int32)
    keys = jnp.asarray(np.stack([np.asarray(jax.random.PRNGKey(0)),
                                 np.asarray(jax.random.PRNGKey(1))]))
    temps = jnp.zeros((2,), jnp.float32)
    tok, keys, state = ic.prefill(conf, params, state, jnp.asarray(pb),
                                  length, keys, temps)
    pos = jnp.asarray([3, 2], jnp.int32)
    # slot 0 has 3 steps of budget left, slot 1 has 8: one K=8 block
    rem = jnp.asarray([3, 8], jnp.int32)
    toks, tok, keys, state = ic.decode_multi(conf, params, state, tok,
                                             pos, keys, temps, rem, 8)
    toks = np.asarray(jax.device_get(toks))
    # emitted prefixes match the K=1 loop (prefill already emitted
    # refs[s][0]); the frozen tail is all sentinel
    assert list(toks[:3, 0]) == refs[0][1:4]
    assert list(toks[3:, 0]) == [decode_mod.BLOCK_SENTINEL] * 5
    assert list(toks[:, 1]) == refs[1][1:9]
    # the frozen row's carry stopped: its last token is the 3rd one
    assert int(jax.device_get(tok)[0]) == refs[0][3]


def test_batcher_mid_block_freeze_parity(lstm_net):
    """Batcher-level: two streams with different budgets inside one
    K=8 block both land exactly their K=1 trajectories — the short
    stream stops, the long one decodes on."""
    refs = [_compiled_tokens(lstm_net, [1, 2], 3, rng_seed=0),
            _compiled_tokens(lstm_net, [3, 4], 12, rng_seed=1)]
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), steps_per_dispatch=8)
    try:
        a = cb.submit([1, 2], max_new_tokens=3, rng_seed=0)
        b = cb.submit([3, 4], max_new_tokens=12, rng_seed=1)
        assert list(a.tokens(timeout=60.0)) == refs[0]
        assert list(b.tokens(timeout=60.0)) == refs[1]
    finally:
        cb.stop()


# -- adaptive K ---------------------------------------------------------------

def test_pending_admissions_pin_k_to_one(lstm_net):
    """Fused blocks never run while admissions wait: TTFT semantics
    are the K=1 loop's.  (Unit check on the eligibility gate — the
    decode thread is not running.)"""
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), steps_per_dispatch=8,
                           auto_start=False)
    try:
        assert cb._block_eligible()          # idle, no queue
        cb.submit([1, 2], max_new_tokens=4)
        assert not cb._block_eligible()      # pending admission -> K=1
    finally:
        cb.stop()


def test_admissions_mid_run_reset_the_ramp(lstm_net):
    """End-to-end: staggered arrivals force K=1 blocks (or the plain
    step path) around every admission, yet every stream still lands
    its exact K=1 trajectory."""
    refs = [_compiled_tokens(lstm_net, [i + 1], 12, rng_seed=i)
            for i in range(4)]
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), steps_per_dispatch=8)
    try:
        streams = [cb.submit([i + 1], max_new_tokens=12, rng_seed=i)
                   for i in range(4)]
        got = [list(s.tokens(timeout=60.0)) for s in streams]
        assert got == refs
        h = cb.stats()["decode_block_steps"]
        # the ramp restarted from K=1 after the mid-run admissions
        assert h["counts"][0] > 0
    finally:
        cb.stop()


def test_explicit_k_with_speculation_is_an_error(lstm_net):
    draft = MultiLayerNetwork(char_lstm(VOCAB, hidden=8, n_layers=1),
                              seed=1).init()
    with pytest.raises(ValueError, match="steps_per_dispatch=1"):
        ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                          prompt_buckets=(8,), draft_net=draft,
                          spec_k=3, steps_per_dispatch=4)


def test_tuned_k_with_speculation_silently_pins_to_one(lstm_net):
    """A tuned-table K must not break a speculative server: the
    batcher silently pins to 1 (speculation already advances multiple
    tokens per dispatch) instead of erroring on a fleet-shared
    table."""
    draft = MultiLayerNetwork(char_lstm(VOCAB, hidden=8, n_layers=1),
                              seed=1).init()
    tunables.install(tunables.TunedTable(
        {"decode.steps_per_dispatch": 8}, device_kind="test"))
    try:
        cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=16,
                               prompt_buckets=(8,), draft_net=draft,
                               spec_k=3)
        try:
            assert cb.k_max == 1
            ref = _compiled_tokens(lstm_net, [1, 2], 5)
            assert cb.generate([1, 2], max_new_tokens=5) == ref
        finally:
            cb.stop()
    finally:
        tunables.clear()


# -- chaos: a fault inside a block fails only its stream ----------------------

def test_block_fault_fails_one_stream_others_decode_on(lstm_net):
    """decode.step fires per slot per SCHEDULED position inside a
    block, so an nth-armed fault lands mid-ramp: the doomed stream
    ends with the injected error BEFORE its rows dispatch, its
    neighbour finishes the very same block with its exact K=1
    trajectory."""
    ref_b = _compiled_tokens(lstm_net, [3, 4], 20, rng_seed=1)
    # traversal order with two admitted slots: block1 (K=1) fires
    # slot0, slot1; block2 (K=2) fires slot0 twice -> nth=4 lands on
    # slot 0's second scheduled position INSIDE the K=2 block
    faults.arm("decode.step", "raise", nth=4)
    cb = ContinuousBatcher(lstm_net, n_slots=2, max_seq=32,
                           prompt_buckets=(8,), steps_per_dispatch=8,
                           auto_start=False)
    try:
        a = cb.submit([1, 2], max_new_tokens=20, rng_seed=0)
        b = cb.submit([3, 4], max_new_tokens=20, rng_seed=1)
        cb.start()
        assert list(b.tokens(timeout=60.0)) == ref_b
        with pytest.raises(faults.FaultInjected):
            list(a.tokens(timeout=60.0))
        st = cb.stats()
        assert st["streams"]["failed"] == 1
        assert st["streams"]["completed"] == 1
        # the failed slot was released: a new stream admits and finishes
        faults.disarm()
        assert len(cb.generate([5], max_new_tokens=3)) == 3
    finally:
        cb.stop()


# -- warm start: the whole K ladder compiles up front -------------------------

def test_warmup_covers_the_k_ladder():
    """A warmed batcher serves its first fused-decode streams with
    ZERO fresh compiles at the tuned K — every ladder value (K=1
    included: ramp resets dispatch the fused block at 1) was compiled
    by warmup_generate."""
    net = MultiLayerNetwork(char_lstm(VOCAB, hidden=16, n_layers=2),
                            seed=0).init()
    net.warmup_generate(slots=2, max_seq=16, prompt_buckets=(8,),
                        steps_per_dispatch=4)
    warmed = net.infer_cache.stats.misses
    cb = ContinuousBatcher(net, n_slots=2, max_seq=16,
                           prompt_buckets=(8,), steps_per_dispatch=4)
    try:
        streams = [cb.submit([i + 1, i + 2], max_new_tokens=12, rng_seed=i)
                   for i in range(2)]
        for s in streams:
            assert len(list(s.tokens(timeout=60.0))) == 12
        assert net.infer_cache.stats.misses == warmed
        assert cb.stats()["decode_block_steps"]["count"] > 0
    finally:
        cb.stop()
