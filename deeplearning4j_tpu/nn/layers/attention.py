"""Multi-head self-attention layer.

New-scope capability: the reference framework predates attention entirely
(its only sequence model is the scalar-loop LSTM, `LSTM.java:161-228`); this
layer plus `parallel/sequence.py` is the TPU-native long-context replacement.
Input/output shape [batch, seq, n_in]; params follow the framework's
dict-of-arrays convention ({"Wqkv", "bqkv", "Wo", "bo"}) so the layer
composes with `MultiLayerNetwork`, parameter averaging, and checkpoints like
any other layer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nd import random as ndr
from deeplearning4j_tpu.nd.platform import is_tpu
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.nn.layers.base import compute_dtype, mixed_matmul
from deeplearning4j_tpu.nd.attention import (blockwise_attention,
                                             full_attention)


def _dtype(conf):
    return jnp.dtype(conf.dtype)


class MultiHeadAttentionLayer:
    """Pre-LN multi-head self-attention with residual connection."""

    @staticmethod
    def init(key, conf):
        d = _dtype(conf)
        kq, ko = jax.random.split(key)
        dist = conf.dist.sampler() if conf.dist is not None else None
        n = conf.n_in
        if n % conf.n_heads != 0:
            raise ValueError(f"n_in={n} not divisible by n_heads={conf.n_heads}")
        if conf.n_out not in (0, n):
            raise ValueError(
                f"attention is residual: n_out must equal n_in={n} (or 0), "
                f"got {conf.n_out}")
        return {
            "Wqkv": init_weights(kq, (n, 3 * n), conf.weight_init, dist, d),
            "bqkv": jnp.zeros((3 * n,), d),
            "Wo": init_weights(ko, (n, n), conf.weight_init, dist, d),
            "bo": jnp.zeros((n,), d),
            "ln_g": jnp.ones((n,), d),
            "ln_b": jnp.zeros((n,), d),
        }

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        b, s, n = x.shape
        h = conf.n_heads
        hd = n // h
        cd = compute_dtype(conf)
        xn = _layer_norm(x, params["ln_g"], params["ln_b"])
        # projections AND the S^2 score/value matmuls run in compute_dtype
        # (bf16 feeds the MXU at full rate; f32 runs at half peak) — the
        # residual stream and layer norm stay in the param dtype
        qkv = mixed_matmul(xn, params["Wqkv"], conf) + params["bqkv"]
        q, k, v = jnp.split(qkv.astype(cd), 3, axis=-1)
        q = q.reshape(b, s, h, hd)
        k = k.reshape(b, s, h, hd)
        v = v.reshape(b, s, h, hd)
        o = MultiHeadAttentionLayer._attend(conf, q, k, v)
        o = mixed_matmul(o.reshape(b, s, n).astype(x.dtype),
                         params["Wo"], conf) + params["bo"]
        if training and conf.dropout > 0.0 and key is not None:
            o = o * ndr.dropout_mask(key, 1.0 - conf.dropout, o.shape, o.dtype)
        return x + o

    @staticmethod
    def _attend(conf, q, k, v):
        """Impl dispatch shared by `forward` and `prefill` — q/k/v are
        [b, s, h, hd] and the result matches elementwise whichever path
        produced the projections (prefill hidden states are bitwise equal
        to a plain forward over the same prompt)."""
        b, s, h, hd = q.shape
        blk = conf.attention_block_size
        skip = conf.attention_block_skip and conf.causal
        fused_bwd = conf.attention_fused_bwd
        impl = conf.attention_impl
        if impl == "auto":
            if is_tpu():
                # measured on v5e: XLA's dense attention (heads batched into
                # big MXU matmuls) beats the Pallas flash kernel up through
                # S=2048 (224 vs 432 ms/step at S=2048); beyond that the
                # [S,S] scores no longer fit HBM and flash is the only
                # option. The 8 GiB bound is the measured per-layer failure
                # boundary (S=2048/B=16/H=16 = 4.3 GiB trains, S=4096/B=8 =
                # 8.6 GiB OOMs); it is per-LAYER because XLA rematerializes
                # probs inside fusions rather than retaining one [B,H,S,S]
                # per block (8 blocks x 2 GiB at S=1024 runs fine), and b
                # here is the per-device batch under shard_map. Overrides:
                # conf.attention_impl pins an impl, conf.remat frees HBM.
                # Each flash-side improvement moves the crossover one
                # doubling earlier (halves the bound): the causal block-skip
                # halves the kernel's tile visits, and the fused backward
                # removes the flash path's forward recompute — dense
                # attention's bwd was ~2x flash-recompute's cost advantage,
                # so flash now wins a doubling sooner again.  Both shifts
                # are analytic off the same v5e sweep; bench.py's
                # bench_attention_crossover records the measured boundary
                # to check these bounds on the next chip run.
                scores_bytes = 4 * b * h * s * s  # f32 fwd scores
                bound = 8 << 30
                if skip:
                    bound >>= 1
                if fused_bwd:
                    bound >>= 1
                impl = "full" if scores_bytes <= bound else "flash"
            else:
                impl = "blockwise" if blk else "full"
        if impl == "flash":
            from deeplearning4j_tpu.nd.pallas_kernels import (
                flash_attention, pick_attention_blocks)
            bq, bk = (blk, blk) if blk else pick_attention_blocks(s, hd)
            # pinned conf block pins the bwd tiles too; 0 -> bwd-aware
            # autotune inside flash_attention
            o = flash_attention(q, k, v, conf.causal, bq, bk,
                                block_skip=skip, fused_bwd=fused_bwd,
                                block_q_bwd=blk, block_k_bwd=blk)
        elif impl == "blockwise":
            o = blockwise_attention(q, k, v, block_size=blk or 512,
                                    causal=conf.causal)
        else:
            o = full_attention(q, k, v, causal=conf.causal)
        return o

    @staticmethod
    def prefill(params, conf, x, k_cache, v_cache):
        """Prompt phase of KV-cache generation: run the normal causal
        forward over the whole prompt and record the projected K/V rows
        into the pre-allocated caches.

        x: [B, T, n]; caches: [B, max_S, n] (T <= max_S).  Returns
        (hidden [B, T, n], k_cache, v_cache).  Bucket padding beyond each
        row's true prompt length writes junk K/V at positions >= length,
        which is harmless: the causal mask hides them from every prompt
        position, and `decode_step` overwrites position `pos` before it
        ever attends to it.
        """
        b, s, n = x.shape
        h = conf.n_heads
        hd = n // h
        cd = compute_dtype(conf)
        xn = _layer_norm(x, params["ln_g"], params["ln_b"])
        qkv = mixed_matmul(xn, params["Wqkv"], conf) + params["bqkv"]
        q, k, v = jnp.split(qkv.astype(cd), 3, axis=-1)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0))
        q = q.reshape(b, s, h, hd)
        k = k.reshape(b, s, h, hd)
        v = v.reshape(b, s, h, hd)
        o = MultiHeadAttentionLayer._attend(conf, q, k, v)
        o = mixed_matmul(o.reshape(b, s, n).astype(x.dtype),
                         params["Wo"], conf) + params["bo"]
        return x + o, k_cache, v_cache

    @staticmethod
    def decode_step(params, conf, x, k_cache, v_cache, pos):
        """One generated token against the KV cache.

        x: [B, n] (current token's hidden row); caches: [B, max_S, n];
        pos: [B] int32, the sequence position each row is writing.  The
        new K/V row is scattered at `pos`, scores are [B, H, max_S] — one
        sequence-scaled axis, never [S, S] — and key positions > pos get
        the same additive -1e30 mask as `nd.attention.full_attention`,
        so a greedy decode reproduces the eager full-forward trajectory
        exactly in f32.
        """
        b, n = x.shape
        h = conf.n_heads
        hd = n // h
        cd = compute_dtype(conf)
        xn = _layer_norm(x, params["ln_g"], params["ln_b"])
        qkv = mixed_matmul(xn, params["Wqkv"], conf) + params["bqkv"]
        q, k, v = jnp.split(qkv.astype(cd), 3, axis=-1)
        rows = jnp.arange(b)
        k_cache = k_cache.at[rows, pos].set(k.astype(k_cache.dtype))
        v_cache = v_cache.at[rows, pos].set(v.astype(v_cache.dtype))
        max_s = k_cache.shape[1]
        qh = q.reshape(b, h, hd)
        kh = k_cache.astype(cd).reshape(b, max_s, h, hd)
        vh = v_cache.astype(cd).reshape(b, max_s, h, hd)
        s = jnp.einsum("bhd,bkhd->bhk", qh, kh) / jnp.sqrt(
            jnp.asarray(hd, qh.dtype))
        kpos = jnp.arange(max_s)[None, :]
        mask = jnp.where(kpos <= pos[:, None], 0.0, -1e30).astype(s.dtype)
        p = jax.nn.softmax(s + mask[:, None, :], axis=-1)
        o = jnp.einsum("bhk,bkhd->bhd", p, vh)
        o = mixed_matmul(o.reshape(b, n).astype(x.dtype),
                         params["Wo"], conf) + params["bo"]
        return x + o, k_cache, v_cache

    @staticmethod
    def decode_step_paged(params, conf, x, k_pool, v_pool, pos, page_table):
        """`decode_step` against a shared physical page pool.

        x: [B, n]; pools: [n_pages, page_size, n]; pos: [B] int32;
        page_table: [B, pages_per_slot] int32 of physical page ids.  The
        new K/V row is scattered at (page_table[b, pos // ps], pos % ps)
        and the row's pages are gathered back into one
        [B, pages_per_slot * ps, n] view before the identical masked
        score math as the dense step — unallocated table entries point
        at the host's scratch page, whose junk sits behind the additive
        mask (exp(-1e30 + ·) underflows to exactly 0.0), so paged and
        dense trajectories are token-identical.
        """
        b, n = x.shape
        h = conf.n_heads
        hd = n // h
        ps = k_pool.shape[1]
        cd = compute_dtype(conf)
        xn = _layer_norm(x, params["ln_g"], params["ln_b"])
        qkv = mixed_matmul(xn, params["Wqkv"], conf) + params["bqkv"]
        q, k, v = jnp.split(qkv.astype(cd), 3, axis=-1)
        rows = jnp.arange(b)
        phys = page_table[rows, pos // ps]
        off = pos % ps
        k_pool = k_pool.at[phys, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[phys, off].set(v.astype(v_pool.dtype))
        pp = page_table.shape[1]
        ctx = pp * ps
        qh = q.reshape(b, h, hd)
        kh = k_pool[page_table].reshape(b, ctx, h, hd).astype(cd)
        vh = v_pool[page_table].reshape(b, ctx, h, hd).astype(cd)
        s = jnp.einsum("bhd,bkhd->bhk", qh, kh) / jnp.sqrt(
            jnp.asarray(hd, qh.dtype))
        kpos = jnp.arange(ctx)[None, :]
        mask = jnp.where(kpos <= pos[:, None], 0.0, -1e30).astype(s.dtype)
        p = jax.nn.softmax(s + mask[:, None, :], axis=-1)
        o = jnp.einsum("bhk,bkhd->bhd", p, vh)
        o = mixed_matmul(o.reshape(b, n).astype(x.dtype),
                         params["Wo"], conf) + params["bo"]
        return x + o, k_pool, v_pool

    @staticmethod
    def verify_chunk(params, conf, x, k_cache, v_cache, pos):
        """Speculative verification: advance every row K tokens at once.

        x: [B, K, n] (chunk hidden rows); caches: [B, max_S, n]; pos:
        [B] int32, the position of each row's FIRST chunk token.  Token
        i is written at pos + i and attends causally at kpos <= pos + i
        — the same mask `decode_step` would apply i calls later — so the
        chunk's hidden rows match K sequential decode steps exactly.
        Mis-speculated suffixes need no rollback: the next call simply
        rewrites those positions before attending to them.
        """
        b, kk, n = x.shape
        h = conf.n_heads
        hd = n // h
        cd = compute_dtype(conf)
        xn = _layer_norm(x, params["ln_g"], params["ln_b"])
        qkv = mixed_matmul(xn, params["Wqkv"], conf) + params["bqkv"]
        q, k, v = jnp.split(qkv.astype(cd), 3, axis=-1)
        rows = jnp.arange(b)[:, None]
        idx = pos[:, None] + jnp.arange(kk)[None, :]
        k_cache = k_cache.at[rows, idx].set(k.astype(k_cache.dtype))
        v_cache = v_cache.at[rows, idx].set(v.astype(v_cache.dtype))
        max_s = k_cache.shape[1]
        qh = q.reshape(b, kk, h, hd)
        kh = k_cache.astype(cd).reshape(b, max_s, h, hd)
        vh = v_cache.astype(cd).reshape(b, max_s, h, hd)
        s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) / jnp.sqrt(
            jnp.asarray(hd, qh.dtype))
        kpos = jnp.arange(max_s)[None, None, :]
        mask = jnp.where(kpos <= idx[:, :, None], 0.0, -1e30).astype(s.dtype)
        p = jax.nn.softmax(s + mask[:, None, :, :], axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, vh)
        o = mixed_matmul(o.reshape(b, kk, n).astype(x.dtype),
                         params["Wo"], conf) + params["bo"]
        return x + o, k_cache, v_cache

    @staticmethod
    def verify_chunk_paged(params, conf, x, k_pool, v_pool, pos, page_table):
        """`verify_chunk` against the physical page pool — scatter each
        chunk token at its (page, offset) and gather the paged context
        once; mask semantics identical to the dense chunk."""
        b, kk, n = x.shape
        h = conf.n_heads
        hd = n // h
        ps = k_pool.shape[1]
        cd = compute_dtype(conf)
        xn = _layer_norm(x, params["ln_g"], params["ln_b"])
        qkv = mixed_matmul(xn, params["Wqkv"], conf) + params["bqkv"]
        q, k, v = jnp.split(qkv.astype(cd), 3, axis=-1)
        rows = jnp.arange(b)[:, None]
        idx = pos[:, None] + jnp.arange(kk)[None, :]
        phys = page_table[rows, idx // ps]
        off = idx % ps
        k_pool = k_pool.at[phys, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[phys, off].set(v.astype(v_pool.dtype))
        pp = page_table.shape[1]
        ctx = pp * ps
        qh = q.reshape(b, kk, h, hd)
        kh = k_pool[page_table].reshape(b, ctx, h, hd).astype(cd)
        vh = v_pool[page_table].reshape(b, ctx, h, hd).astype(cd)
        s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) / jnp.sqrt(
            jnp.asarray(hd, qh.dtype))
        kpos = jnp.arange(ctx)[None, None, :]
        mask = jnp.where(kpos <= idx[:, :, None], 0.0, -1e30).astype(s.dtype)
        p = jax.nn.softmax(s + mask[:, None, :, :], axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, vh)
        o = mixed_matmul(o.reshape(b, kk, n).astype(x.dtype),
                         params["Wo"], conf) + params["bo"]
        return x + o, k_pool, v_pool


def _layer_norm(x, g, b, eps: float = 1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


class TransformerFFNLayer:
    """Pre-LN residual MLP — the second half of a transformer block.

    Hidden width = conf.ffn_hidden, defaulting to 4*n_in.  Pairs with
    MultiHeadAttentionLayer to form [attention, ffn] blocks in a
    MultiLayerConfiguration stack.
    """

    @staticmethod
    def init(key, conf):
        d = _dtype(conf)
        n = conf.n_in
        if conf.n_out not in (0, n):
            raise ValueError(
                f"ffn is residual: n_out must equal n_in={n} (or 0), "
                f"got {conf.n_out}")
        h = conf.ffn_hidden or 4 * n
        k1, k2 = jax.random.split(key)
        dist = conf.dist.sampler() if conf.dist is not None else None
        return {
            "W1": init_weights(k1, (n, h), conf.weight_init, dist, d),
            "b1": jnp.zeros((h,), d),
            "W2": init_weights(k2, (h, n), conf.weight_init, dist, d),
            "b2": jnp.zeros((n,), d),
            "ln_g": jnp.ones((n,), d),
            "ln_b": jnp.zeros((n,), d),
        }

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        xn = _layer_norm(x, params["ln_g"], params["ln_b"])
        h = jax.nn.gelu(mixed_matmul(xn, params["W1"], conf) + params["b1"])
        o = mixed_matmul(h, params["W2"], conf) + params["b2"]
        if training and conf.dropout > 0.0 and key is not None:
            o = o * ndr.dropout_mask(key, 1.0 - conf.dropout, o.shape, o.dtype)
        return x + o
