"""Restricted Boltzmann Machine with CD-k contrastive divergence.

Parity: reference `nn/layers/feedforward/rbm/RBM.java:69-438` — CD-k Gibbs
chain (:121-201), 4 visible x 4 hidden unit types (:83-89 — BINARY,
GAUSSIAN, RECTIFIED (NReLU), SOFTMAX), propUp/propDown (:328-382), visible
bias `vb` via `PretrainParamInitializer`.

TPU-native design: the Gibbs chain is a static-k unrolled loop of dense
matmuls (MXU) with explicitly threaded PRNG keys; the CD gradient is formed
directly (CD-k is not the gradient of a tractable loss, so this layer
implements `pretrain_grad_and_score` natively rather than via jax.grad).
Score is mean reconstruction cross-entropy, as the reference reports.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nd import losses as L
from deeplearning4j_tpu.nd import random as ndr
from deeplearning4j_tpu.nn.conf import RBMUnit
from deeplearning4j_tpu.nn.layers.base import _dtype
from deeplearning4j_tpu.nn.layers.autoencoder import AutoEncoder
from deeplearning4j_tpu.nn.weights import init_weights


def _unit_mean(kind: RBMUnit, pre: jnp.ndarray) -> jnp.ndarray:
    kind = RBMUnit(str(kind))
    if kind == RBMUnit.BINARY:
        return jax.nn.sigmoid(pre)
    if kind == RBMUnit.GAUSSIAN:
        return pre
    if kind == RBMUnit.RECTIFIED:
        return jax.nn.relu(pre)
    if kind == RBMUnit.SOFTMAX:
        return jax.nn.softmax(pre, axis=-1)
    raise ValueError(kind)


def _unit_sample(kind: RBMUnit, key, pre: jnp.ndarray) -> jnp.ndarray:
    kind = RBMUnit(str(kind))
    if kind == RBMUnit.BINARY:
        p = jax.nn.sigmoid(pre)
        return jax.random.bernoulli(key, p).astype(pre.dtype)
    if kind == RBMUnit.GAUSSIAN:
        return pre + jax.random.normal(key, pre.shape, pre.dtype)
    if kind == RBMUnit.RECTIFIED:
        # NReLU (Nair & Hinton): max(0, pre + N(0, sigmoid(pre)))
        sigma = jnp.sqrt(jax.nn.sigmoid(pre))
        return jax.nn.relu(pre + sigma * jax.random.normal(key, pre.shape, pre.dtype))
    if kind == RBMUnit.SOFTMAX:
        # one sample per row from the softmax distribution, one-hot encoded
        idx = jax.random.categorical(key, pre, axis=-1)
        return jax.nn.one_hot(idx, pre.shape[-1], dtype=pre.dtype)
    raise ValueError(kind)


class RBM(AutoEncoder):
    @staticmethod
    def init(key, conf):
        dist = conf.dist.sampler() if conf.dist is not None else None
        return {
            "W": init_weights(key, (conf.n_in, conf.n_out), conf.weight_init,
                              dist, _dtype(conf)),
            "b": jnp.zeros((conf.n_out,), _dtype(conf)),   # hidden bias
            "vb": jnp.zeros((conf.n_in,), _dtype(conf)),   # visible bias
        }

    @staticmethod
    def prop_up(params, conf, v):
        return _unit_mean(conf.hidden_unit, v @ params["W"] + params["b"])

    @staticmethod
    def prop_down(params, conf, h):
        return _unit_mean(conf.visible_unit, h @ params["W"].T + params["vb"])

    @staticmethod
    def sample_h_given_v(params, conf, key, v):
        pre = v @ params["W"] + params["b"]
        return _unit_mean(conf.hidden_unit, pre), _unit_sample(conf.hidden_unit, key, pre)

    @staticmethod
    def sample_v_given_h(params, conf, key, h):
        pre = h @ params["W"].T + params["vb"]
        return _unit_mean(conf.visible_unit, pre), _unit_sample(conf.visible_unit, key, pre)

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        return RBM.prop_up(params, conf, x)

    @staticmethod
    def reconstruct(params, conf, x):
        return RBM.prop_down(params, conf, RBM.prop_up(params, conf, x))

    @staticmethod
    def gibbs(params, conf, key, v0, k: int):
        """k alternating Gibbs steps from v0; returns (v_k, h_k_mean)."""
        h_mean, h_sample = RBM.sample_h_given_v(
            params, conf, jax.random.fold_in(key, 0), v0)
        v = v0
        for i in range(k):
            kv = jax.random.fold_in(key, 2 * i + 1)
            kh = jax.random.fold_in(key, 2 * i + 2)
            v_mean, v = RBM.sample_v_given_h(params, conf, kv, h_sample)
            h_mean, h_sample = RBM.sample_h_given_v(params, conf, kh, v)
        return v, h_mean

    @staticmethod
    def pretrain_grad_and_score(params, conf, x, key):
        """CD-k gradient (as a minimization direction) + reconstruction score."""
        B = x.shape[0]
        h0_mean = RBM.prop_up(params, conf, x)
        vk, hk_mean = RBM.gibbs(params, conf, key, x, max(1, conf.k))
        # positive phase - negative phase, averaged over the batch
        wpos = x.T @ h0_mean
        wneg = vk.T @ hk_mean
        gW = -(wpos - wneg) / B
        gb = -jnp.mean(h0_mean - hk_mean, axis=0)
        gvb = -jnp.mean(x - vk, axis=0)
        if conf.use_regularization and conf.l2:
            gW = gW + conf.l2 * params["W"]
        if conf.sparsity > 0.0:
            gb = gb + (jnp.mean(h0_mean, axis=0) - conf.sparsity)
        recon = RBM.reconstruct(params, conf, x)
        if RBMUnit(str(conf.visible_unit)) == RBMUnit.GAUSSIAN:
            score = L.mse(x, recon)
        else:
            score = L.xent(jnp.clip(x, 0.0, 1.0), jnp.clip(recon, 1e-7, 1 - 1e-7))
        return {"W": gW, "b": gb, "vb": gvb}, score

    @staticmethod
    def pretrain_score(params, conf, x, key):
        """Score-only path (reconstruction error, no Gibbs chain/gradient)."""
        recon = RBM.reconstruct(params, conf, x)
        if RBMUnit(str(conf.visible_unit)) == RBMUnit.GAUSSIAN:
            return L.mse(x, recon)
        return L.xent(jnp.clip(x, 0.0, 1.0), jnp.clip(recon, 1e-7, 1 - 1e-7))

    @staticmethod
    def free_energy(params, conf, v):
        """Free energy F(v) = -v.vb - sum softplus(v.W + b) (binary units)."""
        wx_b = v @ params["W"] + params["b"]
        return -v @ params["vb"] - jnp.sum(jax.nn.softplus(wx_b), axis=-1)
