"""Input pre/post processors between layers.

Parity: reference `nn/conf/preprocessor/*` (reshape, binomial sampling,
zero-mean/unit-variance) and the convolution pre/post processors
(`nn/layers/convolution/preprocessor/*`).  A preprocessor is a named pure
function `(conf_of_next_layer, x) -> x'` applied before a layer's forward,
mirroring `MultiLayerNetwork.activationFromPrevLayer` (:472-481).

Names (as used in `MultiLayerConfiguration.input_preprocessors`):
  "ff_to_conv:<C>:<H>:<W>"  flat [B, C*H*W] -> [B, C, H, W]
  "conv_to_ff"              [B, C, H, W] -> [B, C*H*W]
  "rnn_to_ff"               [B, T, F] -> [B*T, F]
  "ff_to_rnn:<T>"           [B*T, F] -> [B, T, F]
  "unit_variance"           zero-mean / unit-variance per feature
  "binomial_sampling"       Bernoulli-sample the activations (needs host rng:
                            deterministic threshold 0.5 inside jit)
"""

from __future__ import annotations

import jax.numpy as jnp


def apply_preprocessor(name: str, x):
    if name is None:
        return x
    parts = str(name).split(":")
    kind = parts[0]
    if kind == "conv_to_ff":
        return x.reshape(x.shape[0], -1)
    if kind == "ff_to_conv":
        c, h, w = (int(p) for p in parts[1:4])
        return x.reshape(x.shape[0], c, h, w)
    if kind == "rnn_to_ff":
        return x.reshape(-1, x.shape[-1])
    if kind == "ff_to_rnn":
        t = int(parts[1])
        return x.reshape(-1, t, x.shape[-1])
    if kind == "unit_variance":
        mean = jnp.mean(x, axis=0, keepdims=True)
        std = jnp.std(x, axis=0, keepdims=True) + 1e-6
        return (x - mean) / std
    if kind == "binomial_sampling":
        return (x > 0.5).astype(x.dtype)
    raise ValueError(f"unknown preprocessor '{name}'")
