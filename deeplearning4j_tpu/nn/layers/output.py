"""Output layer — softmax (or configured activation) head + loss scoring.

Parity: reference `OutputLayer.java:54-356` — softmax output (:337-345),
per-loss-function scoring (:77-90).  The reference hand-derives weight
gradients per loss case (:126-158); here the gradient is `jax.grad` of
`loss(...)` end-to-end, which covers every registered loss identically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nd import losses as L
from deeplearning4j_tpu.nd.ops import activate
from deeplearning4j_tpu.nn.layers.base import DenseLayer


class OutputLayer(DenseLayer):
    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        # input dropout / dropconnect apply here exactly as in DenseLayer
        # (the reference's OutputLayer inherits BaseLayer's dropout path)
        kdrop = kdc = None
        if key is not None:
            kdrop, kdc = jax.random.split(key)
        if training and conf.dropout > 0.0 and kdrop is not None:
            from deeplearning4j_tpu.nd import random as ndr
            x = x * ndr.dropout_mask(kdrop, 1.0 - conf.dropout, x.shape,
                                     x.dtype)
        z = OutputLayer.preout(params, conf, x, kdc, training)
        loss = str(conf.loss_function).lower()
        # The head must match the loss (the reference's OutputLayer is a
        # softmax head; hidden-layer activations leaking into the output of a
        # classifier would let cross-entropy collapse degenerately): softmax
        # for multiclass CE, sigmoid for binary CE, linear for regression.
        if loss in ("mcxent", "negativeloglikelihood", "expll"):
            return activate("softmax", z)
        if loss in ("xent", "rmse_xent", "reconstruction_crossentropy"):
            return activate("sigmoid", z)
        # regression losses honor the configured activation (sigmoid head on
        # MSE is the reference's bounded-regression/AE-finetune shape)
        return activate(conf.activation, z)

    @staticmethod
    def loss(params, conf, x, labels, key=None, training=False):
        out = OutputLayer.forward(params, conf, x, key, training)
        l2n = jnp.sum(params["W"].astype(jnp.float32) ** 2)
        l2 = conf.l2 if conf.use_regularization else 0.0
        s = L.score(labels, conf.loss_function, out, l2, l2n)
        if conf.use_regularization and conf.l1:
            s = s + conf.l1 * jnp.sum(jnp.abs(params["W"].astype(jnp.float32)))
        return s

    @staticmethod
    def score(params, conf, examples, labels):
        """F1 of the layer's classifications on (examples, labels) —
        reference `OutputLayer.score(INDArray, INDArray)` (:183-188: build
        an Evaluation over labelProbabilities, return eval.f1()). Scale 0-1,
        higher is better — distinct from `loss`, which is the training
        objective (lower is better)."""
        from deeplearning4j_tpu.evaluation import Evaluation

        probs = OutputLayer.forward(params, conf, examples)
        ev = Evaluation()
        ev.eval(labels, probs)
        return float(ev.f1())

    @staticmethod
    def rowwise_loss(params, conf, x, labels, key=None, training=False):
        """Per-example loss vector, WITHOUT regularization terms (the caller
        owns those — they must be counted once per step, not per example).
        Backs sample-weighted / pad-masked training on remainder batches."""
        out = OutputLayer.forward(params, conf, x, key, training)
        return L.get_rowwise(conf.loss_function)(labels, out)
