"""Denoising autoencoder (+ recursive AE variant).

Parity: reference `nn/layers/feedforward/autoencoder/AutoEncoder.java:37-111`
(tied-weight encode/decode with visible bias `vb` from
`BasePretrainNetwork`/`PretrainParamInitializer`; binomial input corruption
from `BasePretrainNetwork.java:87-91`; reconstruction-cross-entropy score).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nd import losses as L
from deeplearning4j_tpu.nd import random as ndr
from deeplearning4j_tpu.nd.ops import activate
from deeplearning4j_tpu.nn.layers.base import DenseLayer, _dtype
from deeplearning4j_tpu.nn.weights import init_weights


class AutoEncoder(DenseLayer):
    @staticmethod
    def init(key, conf):
        kw, _ = jax.random.split(key)
        dist = conf.dist.sampler() if conf.dist is not None else None
        return {
            "W": init_weights(kw, (conf.n_in, conf.n_out), conf.weight_init,
                              dist, _dtype(conf)),
            "b": jnp.zeros((conf.n_out,), _dtype(conf)),
            "vb": jnp.zeros((conf.n_in,), _dtype(conf)),
        }

    @staticmethod
    def encode(params, conf, x):
        return activate(conf.activation, x @ params["W"] + params["b"])

    @staticmethod
    def decode(params, conf, h):
        return activate(conf.activation, h @ params["W"].T + params["vb"])

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        return AutoEncoder.encode(params, conf, x)

    @staticmethod
    def reconstruct(params, conf, x):
        return AutoEncoder.decode(params, conf, AutoEncoder.encode(params, conf, x))

    @staticmethod
    def pretrain_loss(params, conf, x, key):
        """Denoising reconstruction loss (corruption_level parity)."""
        if conf.corruption_level > 0.0 and key is not None:
            mask = ndr.binomial(key, 1.0 - conf.corruption_level, x.shape, x.dtype)
            xc = x * mask
        else:
            xc = x
        recon = AutoEncoder.decode(params, conf, AutoEncoder.encode(params, conf, xc))
        s = L.get_loss(conf.loss_function if str(conf.loss_function) != "mcxent"
                       else "reconstruction_crossentropy")(x, recon)
        if conf.use_regularization and conf.l2:
            s = s + 0.5 * conf.l2 * jnp.sum(params["W"].astype(jnp.float32) ** 2)
        if conf.sparsity > 0.0:
            h = AutoEncoder.encode(params, conf, xc)
            rho_hat = jnp.clip(jnp.mean(h, axis=0), 1e-6, 1 - 1e-6)
            rho = conf.sparsity
            s = s + jnp.sum(rho * jnp.log(rho / rho_hat)
                            + (1 - rho) * jnp.log((1 - rho) / (1 - rho_hat)))
        return s

    @staticmethod
    def pretrain_grad_and_score(params, conf, x, key):
        score, grads = jax.value_and_grad(
            lambda p: AutoEncoder.pretrain_loss(p, conf, x, key)
        )(params)
        return grads, score

    @staticmethod
    def pretrain_score(params, conf, x, key):
        """Score-only path for line-search probes (no gradient formed)."""
        return AutoEncoder.pretrain_loss(params, conf, x, key)
