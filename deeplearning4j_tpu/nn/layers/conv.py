"""Convolution + subsampling (pooling) layers.

Parity-plus: the reference's conv stack is half-stubbed
(`ConvolutionLayer.java:95-233` returns nulls; `ConvolutionDownSampleLayer.
java:38-146` does conv2d + pooling via ND4J `Transforms.maxPool/avgPooling/
sumPooling`; `SubsamplingLayer.java:43` downsample-by-stride).  Per SURVEY §7
hard-part 7, this module implements *real* forward+backward conv so LeNet /
VGG configs actually run.

TPU-native design: `lax.conv_general_dilated` in NCHW with filters
[out_ch, in_ch, kh, kw] — XLA tiles these straight onto the MXU — and
`lax.reduce_window` pooling.  Backward comes from `jax.grad` through these
primitives (XLA generates the transposed conv).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nd.ops import activate
from deeplearning4j_tpu.nn.conf import PoolingType
from deeplearning4j_tpu.nn.layers.base import _dtype
from deeplearning4j_tpu.nn.weights import init_weights


def conv2d(x, w, stride=(1, 1), padding=(0, 0), operand_dtype=None):
    """NCHW conv: x [B,C,H,W], w [O,C,kh,kw].

    `operand_dtype` (mixed precision): cast both operands (bf16 feeds the
    MXU at full rate) while accumulating in f32."""
    pad = [(padding[0], padding[0]), (padding[1], padding[1])]
    od = operand_dtype or w.dtype
    # both operands in od, output cast back: keeps the transpose (backward)
    # convs dtype-consistent; TPU bf16 convs accumulate in f32 on the MXU
    out = lax.conv_general_dilated(
        x.astype(od), w.astype(od), window_strides=tuple(stride),
        padding=pad, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return out.astype(w.dtype)


def pool2d(x, kind: PoolingType, window=(2, 2), stride=None):
    """max/avg/sum pooling over NCHW spatial dims (Transforms.* parity)."""
    kind = PoolingType(str(kind))
    if kind == PoolingType.NONE:
        return x
    stride = tuple(stride or window)
    dims = (1, 1) + tuple(window)
    strides = (1, 1) + stride
    if kind == PoolingType.MAX:
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, "VALID")
    s = lax.reduce_window(x, 0.0, lax.add, dims, strides, "VALID")
    if kind == PoolingType.SUM:
        return s
    return s / (window[0] * window[1])  # AVG


class ConvolutionLayer:
    """Conv2d + bias + activation.  Params: convweights [O,C,kh,kw], convbias [O]
    (name parity: `ConvolutionParamInitializer.java:37-67`)."""

    @staticmethod
    def init(key, conf):
        kh, kw = conf.kernel_size
        dist = conf.dist.sampler() if conf.dist is not None else None
        shape = (conf.n_out, conf.n_channels, kh, kw)
        fan_in = conf.n_channels * kh * kw
        fan_out = conf.n_out * kh * kw
        # VI/Glorot over the receptive field, not the raw first two dims
        r = jnp.sqrt(6.0 / (fan_in + fan_out))
        if str(conf.weight_init) == "distribution" and dist is not None:
            W = jnp.asarray(dist(key, shape), _dtype(conf))
        else:
            W = jax.random.uniform(key, shape, _dtype(conf), minval=-r, maxval=r)
        return {"W": W, "b": jnp.zeros((conf.n_out,), _dtype(conf))}

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        from deeplearning4j_tpu.nn.layers.base import compute_dtype
        z = conv2d(x, params["W"], conf.stride, conf.padding,
                   operand_dtype=compute_dtype(conf))
        z = z + params["b"][None, :, None, None]
        return activate(conf.activation, z)


class SubsamplingLayer:
    """Pooling-only layer (parity: `SubsamplingLayer.java:43`,
    `ConvolutionDownSampleLayer` pooling modes)."""

    @staticmethod
    def init(key, conf):
        return {}

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        return pool2d(x, conf.pooling, conf.kernel_size, conf.stride)
