"""LSTM layer — fused-gate, scan-based.

Parity: reference `nn/layers/recurrent/LSTM.java:53-531` (karpathy-style
char-LSTM with one concatenated weight matrix `iFog` of shape
[(n_in + n_hidden + 1) x 4*n_hidden] — :161-228 — and manual BPTT :83-157).

TPU-native design: the per-timestep Java loop becomes `lax.scan`; the four
gates stay fused in a single [(n_in + n_out) x 4*n_out] matmul so each step
is one MXU call; BPTT is `jax.grad` through the scan (no manual derivation);
batching is first-class (inputs are [batch, time, n_in], vs. the reference's
single-sequence [time, n_in]).  Decoding/sampling lives in
`models/char_lstm.py`, not the layer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import _dtype
from deeplearning4j_tpu.nn.weights import init_weights


class LSTMLayer:
    @staticmethod
    def init(key, conf):
        n_in, n_h = conf.n_in, conf.n_out
        dist = conf.dist.sampler() if conf.dist is not None else None
        # fused gate matrix [x;h] -> [i f o g], one bias vector
        W = init_weights(key, (n_in + n_h, 4 * n_h), conf.weight_init, dist,
                         _dtype(conf))
        b = jnp.zeros((4 * n_h,), _dtype(conf))
        # forget-gate bias init to 1 (standard practice; helps gradient flow)
        b = b.at[n_h:2 * n_h].set(1.0)
        return {"W": W, "b": b}

    @staticmethod
    def _step(params, n_h, carry, x_t):
        h, c = carry
        z = jnp.concatenate([x_t, h], axis=-1) @ params["W"] + params["b"]
        return LSTMLayer._gates(n_h, carry, z)

    @staticmethod
    def _gates(n_h, carry, z):
        """Gate math given the pre-activation z = xW_x + hW_h + b."""
        h, c = carry
        i = jax.nn.sigmoid(z[..., :n_h])
        f = jax.nn.sigmoid(z[..., n_h:2 * n_h])
        o = jax.nn.sigmoid(z[..., 2 * n_h:3 * n_h])
        g = jnp.tanh(z[..., 3 * n_h:])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    @staticmethod
    def _use_fused(conf) -> bool:
        # measured on v5e with host-synced timing: the Pallas cell beats
        # XLA's scan fusion ~25% (70.6 vs 94.4 ms/fwd at B=64 T=64
        # 256->512), so "auto" uses it on TPU; interpret-mode overhead
        # makes scan the right default elsewhere.  NOTE: that measurement
        # predates the hoisted input projection in the scan path below —
        # re-measure on chip (lstm_impl="scan" vs "fused") before trusting
        # "auto" for a new config.
        impl = getattr(conf, "lstm_impl", "auto")
        if impl == "auto":
            from deeplearning4j_tpu.nd.platform import is_tpu

            return is_tpu()
        return impl == "fused"

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        """x: [batch, time, n_in] -> hidden states [batch, time, n_out]."""
        if x.ndim == 2:  # single sequence [time, n_in] (reference shape)
            return LSTMLayer.forward(params, conf, x[None], key, training)[0]
        B, T, _ = x.shape
        n_h = conf.n_out
        n_in = conf.n_in
        # zeros_like(x, shape=...) so the carry inherits x's varying
        # manual axes: inside shard_map(check_vma=True) a plain zeros
        # carry is typed invariant and the scan rejects the dp-varying
        # output carry
        h0 = jnp.zeros_like(x, shape=(B, n_h))
        c0 = jnp.zeros_like(x, shape=(B, n_h))

        if LSTMLayer._use_fused(conf):
            # Pallas cell: one kernel per step (both matmuls + gates +
            # state update fused); W splits into input/recurrent halves
            from deeplearning4j_tpu.nd.pallas_kernels import fused_lstm_step

            wx, wh = params["W"][:n_in], params["W"][n_in:]

            def step(carry, x_t):
                h, c = carry
                h, c = fused_lstm_step(x_t, h, c, wx, wh, params["b"])
                return (h, c), h

            (_, _), hs = jax.lax.scan(step, (h0, c0),
                                      jnp.swapaxes(x, 0, 1))
            return jnp.swapaxes(hs, 0, 1)

        return LSTMLayer._hoisted_scan(
            params, n_in, x, h0, c0,
            lambda carry, z: LSTMLayer._gates(n_h, carry, z))

    @staticmethod
    def _hoisted_scan(params, n_in, x, h0, c0, gates):
        """Scan path shared by LSTM/GravesLSTM: hoist the input half of
        the fused gate matmul out of the loop — ONE [B*T, n_in]@[n_in, 4H]
        MXU matmul up front (plus the bias), leaving only the small
        recurrent h@W_h per step.  Identical math to concat([x,h])@W,
        reassociated.  `gates`: (carry, z) -> ((h, c), h)."""
        wh = params["W"][n_in:]
        z_x = x @ params["W"][:n_in] + params["b"]  # [B, T, 4H]

        def step(carry, zx_t):
            h, _ = carry
            return gates(carry, zx_t + h @ wh)

        (_, _), hs = jax.lax.scan(step, (h0, c0), jnp.swapaxes(z_x, 0, 1))
        return jnp.swapaxes(hs, 0, 1)

    @staticmethod
    def step(params, conf, x_t, h, c):
        """Single decode step (used by sampling / beam search)."""
        (h, c), _ = LSTMLayer._step(params, conf.n_out, (h, c), x_t)
        return h, c

    @classmethod
    def prefill(cls, params, conf, x, h0, c0, length):
        """Prompt phase of cached generation: scan the prompt through the
        per-step concat form (`cls._step`, the exact math `step()` runs
        one token at a time — NOT the reassociated `_hoisted_scan`), so
        the resulting carry is bitwise what repeated eager `step()` calls
        produce.  Rows are frozen once `t >= length[row]` so bucket
        padding never advances a carry.

        x: [B, T, n_in]; length: [B] int32.  Returns
        (hs [B, T, n_out], h [B, n_out], c [B, n_out]).
        """
        n_h = conf.n_out

        def scan_step(carry, inp):
            t, x_t = inp
            (h2, c2), _ = cls._step(params, n_h, carry, x_t)
            live = (t < length)[:, None]
            h2 = jnp.where(live, h2, carry[0])
            c2 = jnp.where(live, c2, carry[1])
            return (h2, c2), h2

        T = x.shape[1]
        (h, c), hs = jax.lax.scan(
            scan_step, (h0, c0), (jnp.arange(T), jnp.swapaxes(x, 0, 1)))
        return jnp.swapaxes(hs, 0, 1), h, c


class GravesLSTMLayer(LSTMLayer):
    """LSTM with peephole connections — what "Graves" means (Graves 2013,
    "Generating Sequences with RNNs" eq. 7-11): the input and forget gates
    see the PREVIOUS cell state and the output gate sees the NEW cell
    state, each through a diagonal (elementwise) peephole weight vector.

    The 2015 reference snapshot has no GravesLSTM class yet (its only
    recurrent layer is `LSTM.java`); this layer exists so the
    `GRAVES_LSTM` enum value is honest rather than an alias of the plain
    LSTM (VERDICT r2 weak #7). The fused [x;h] gate matmul stays one MXU
    call; peepholes add three VPU multiplies per step.
    """

    @staticmethod
    def init(key, conf):
        params = LSTMLayer.init(key, conf)
        n_h = conf.n_out
        d = _dtype(conf)
        # diagonal peepholes, zero-init: at init the layer computes exactly
        # the plain LSTM, and training learns how much cell state to leak
        params["p_i"] = jnp.zeros((n_h,), d)
        params["p_f"] = jnp.zeros((n_h,), d)
        params["p_o"] = jnp.zeros((n_h,), d)
        return params

    @staticmethod
    def _step(params, n_h, carry, x_t):
        h, c = carry
        z = jnp.concatenate([x_t, h], axis=-1) @ params["W"] + params["b"]
        return GravesLSTMLayer._gates(params, n_h, carry, z)

    @staticmethod
    def _gates(params, n_h, carry, z):
        h, c = carry
        i = jax.nn.sigmoid(z[..., :n_h] + params["p_i"] * c)
        f = jax.nn.sigmoid(z[..., n_h:2 * n_h] + params["p_f"] * c)
        g = jnp.tanh(z[..., 3 * n_h:])
        c_new = f * c + i * g
        o = jax.nn.sigmoid(z[..., 2 * n_h:3 * n_h] + params["p_o"] * c_new)
        h = o * jnp.tanh(c_new)
        return (h, c_new), h

    @staticmethod
    def _use_fused(conf) -> bool:
        return False  # the Pallas cell has no peephole terms

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        if x.ndim == 2:
            return GravesLSTMLayer.forward(params, conf, x[None], key,
                                           training)[0]
        B, T, _ = x.shape
        n_h = conf.n_out
        # carry inherits x's varying manual axes (see LSTMLayer.forward)
        h0 = jnp.zeros_like(x, shape=(B, n_h))
        c0 = jnp.zeros_like(x, shape=(B, n_h))
        return LSTMLayer._hoisted_scan(
            params, conf.n_in, x, h0, c0,
            lambda carry, z: GravesLSTMLayer._gates(params, n_h, carry, z))

    @staticmethod
    def step(params, conf, x_t, h, c):
        (h, c), _ = GravesLSTMLayer._step(params, conf.n_out, (h, c), x_t)
        return h, c
