"""Layer implementations + registry.

Parity with reference `nn/layers/*` + `nn/layers/factory/LayerFactories.java:32-47`
(layer class -> factory dispatch).  TPU-native design: a layer is a pair of
pure functions
    init(key, conf)                  -> params (dict pytree of jnp arrays)
    forward(params, conf, x, key=None, training=False) -> activations
registered by `LayerType`.  Pretrainable layers additionally expose
    pretrain_grad_and_score(params, conf, x, key) -> (grads, score)
replacing the reference's `Model.gradientAndScore` contract
(`nn/api/Model.java`) used by layer-wise pretraining.
"""

from deeplearning4j_tpu.nn.conf import LayerType
from deeplearning4j_tpu.nn.layers import (base, output, autoencoder, rbm, lstm,
                                          conv, attention)

_REGISTRY = {
    LayerType.DENSE: base.DenseLayer,
    LayerType.OUTPUT: output.OutputLayer,
    LayerType.AUTOENCODER: autoencoder.AutoEncoder,
    # recursive AE over tree structures is future scope; until then the
    # flat denoising AE provides the pretrain contract for this type
    LayerType.RECURSIVE_AUTOENCODER: autoencoder.AutoEncoder,
    LayerType.RBM: rbm.RBM,
    LayerType.LSTM: lstm.LSTMLayer,
    LayerType.GRAVES_LSTM: lstm.GravesLSTMLayer,
    LayerType.CONVOLUTION: conv.ConvolutionLayer,
    LayerType.SUBSAMPLING: conv.SubsamplingLayer,
    LayerType.BATCH_NORM: base.BatchNormLayer,
    LayerType.EMBEDDING: base.EmbeddingLayer,
    LayerType.ATTENTION: attention.MultiHeadAttentionLayer,
    LayerType.TRANSFORMER_FFN: attention.TransformerFFNLayer,
}


def get_layer(layer_type):
    """Layer factory dispatch (parity: `LayerFactories.getFactory`)."""
    return _REGISTRY[LayerType(str(layer_type).lower())]


def register_layer(layer_type, impl) -> None:
    _REGISTRY[LayerType(str(layer_type).lower())] = impl
