"""Dense / feed-forward layers.

Parity: reference `BaseLayer.java:46-408` — param table {"W","b"},
`activate() = f(x.W + b)` (:211-219), dropout (:250-262), dropconnect;
`merge` (parameter averaging, :271-273) is subsumed by pytree arithmetic in
`parallel/averaging.py`.  Plus BatchNorm and Embedding layers (capability the
reference's config enum gestures at via BASELINE config[2] "ConvolutionLayer
+ BatchNorm").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nd import random as ndr
from deeplearning4j_tpu.nd.ops import activate
from deeplearning4j_tpu.nn.weights import init_weights


def _dtype(conf):
    return jnp.dtype(conf.dtype)


def compute_dtype(conf):
    cd = getattr(conf, "compute_dtype", "")
    return jnp.dtype(cd) if cd else jnp.dtype(conf.dtype)


def mixed_matmul(x, W, conf):
    """x @ W with operands in conf.compute_dtype — bf16 feeds the MXU at
    full rate while params stay f32 (output cast back to the param dtype;
    TPU bf16 matmuls accumulate in f32 on the MXU)."""
    cd = compute_dtype(conf)
    return (x.astype(cd) @ W.astype(cd)).astype(W.dtype)


def rows_broadcast(v, n_rows, dtype=None):
    """Broadcast a feature vector v[F] over n_rows rows as `ones @ v[None]`
    (a rank-1 gemm) rather than a plain numpy-style broadcast.

    Value-identical (1.0 * v_j is exact), but the TRANSPOSE — the batch-dim
    reduction autodiff emits for the broadcast's backward pass — lowers as a
    gemm contraction, which XLA evaluates bit-identically whatever the batch
    size.  A plain broadcast transposes to `reduce_sum` over the batch dim,
    whose pairwise-split strategy is shape-dependent: a remainder batch
    zero-padded into a larger bucket would then drift from the unpadded run
    by ~1 ulp in bias / BN-affine gradients, breaking the step cache's
    bit-for-bit padding guarantee."""
    dt = dtype or v.dtype
    return jnp.ones((n_rows, 1), dt) @ v[None, :].astype(dt)


class DenseLayer:
    """f(x.W + b) with optional dropout/dropconnect."""

    @staticmethod
    def init(key, conf):
        kw, _ = jax.random.split(key)
        dist = conf.dist.sampler() if conf.dist is not None else None
        return {
            "W": init_weights(kw, (conf.n_in, conf.n_out), conf.weight_init,
                              dist, _dtype(conf)),
            "b": jnp.zeros((conf.n_out,), _dtype(conf)),
        }

    @staticmethod
    def preout(params, conf, x, key=None, training=False):
        W = params["W"]
        if training and conf.drop_connect and key is not None:
            W = W * ndr.dropout_mask(key, 0.5, W.shape, W.dtype)
        z = mixed_matmul(x, W, conf)
        if z.ndim == 2:  # gemm-broadcast the bias: pad-invariant bias grad
            return z + rows_broadcast(params["b"], z.shape[0], z.dtype)
        return z + params["b"]

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        kdrop = kdc = None
        if key is not None:
            kdrop, kdc = jax.random.split(key)
        if training and conf.dropout > 0.0 and kdrop is not None:
            x = x * ndr.dropout_mask(kdrop, 1.0 - conf.dropout, x.shape, x.dtype)
        z = DenseLayer.preout(params, conf, x, kdc, training)
        return activate(conf.activation, z)


class BatchNormLayer:
    """Batch normalization over the feature axis.

    Stateless-from-jit design: running stats live in params under "ema_*" and
    are updated outside jit by the training loop (or folded in via
    `forward(..., training=True)` which normalizes with batch stats).
    """

    @staticmethod
    def init(key, conf):
        n = conf.n_out or conf.n_in
        d = _dtype(conf)
        return {
            "gamma": jnp.ones((n,), d),
            "beta": jnp.zeros((n,), d),
            # bias-corrected running stats: raw EMA accumulators plus the
            # total EMA weight (1 - m^k); inference divides by ema_w so one
            # training batch already yields exact stats and the estimate is
            # never dominated by whichever batch came last
            "ema_mean": jnp.zeros((n,), d),
            "ema_var": jnp.zeros((n,), d),
            "ema_w": jnp.zeros((), d),
        }

    @staticmethod
    def _feature_axes(x):
        """Reduction axes: channel axis is 1 for NCHW conv outputs, -1 for
        dense features."""
        return (0, 2, 3) if x.ndim == 4 else tuple(range(x.ndim - 1))

    @staticmethod
    def moments(x, row_weights=None):
        """Raw batch moments (s1, s2, cnt) in f32, optionally row-weighted
        (pad rows of a masked remainder batch weigh 0 and are excluded).
        mean = s1/cnt, var = s2/cnt - mean^2.  Kept as raw sums so dp
        shards can psum them into GLOBAL-batch statistics."""
        axes = BatchNormLayer._feature_axes(x)
        xf = x.astype(jnp.float32)
        if x.ndim == 2:
            # express the batch-dim reductions as gemm contractions so the
            # moments (and their grads) are bit-invariant to zero-pad rows
            # — see `rows_broadcast` for why reduce_sum is not
            if row_weights is None:
                w1 = jnp.ones((1, x.shape[0]), jnp.float32)
            else:
                w1 = row_weights.reshape(1, -1).astype(jnp.float32)
            s1 = (w1 @ xf)[0]
            s2 = (w1 @ (xf * xf))[0]
            cnt = (w1 @ jnp.ones((x.shape[0], 1), jnp.float32))[0, 0]
            return s1, s2, cnt
        if x.ndim == 4:
            # NCHW conv activations: same gemm-contraction trick, with
            # channels as gemm rows and the flattened (n, h, w) positions
            # as the contraction axis.  n is the SLOWEST-varying column
            # index, so a batch zero-padded into a larger bucket only
            # appends trailing zero-weight columns — the contraction (and
            # its grads) stays bit-identical to the unpadded run.
            n, c = x.shape[0], x.shape[1]
            hw = x.shape[2] * x.shape[3]
            if row_weights is None:
                wv = jnp.ones((n * hw, 1), jnp.float32)
            else:
                # per-column weight = the column's batch-row weight,
                # expanded over h*w via an exact rank-1 product
                wv = (row_weights.reshape(-1, 1).astype(jnp.float32)
                      @ jnp.ones((1, hw), jnp.float32)).reshape(-1, 1)
            cols = xf.transpose(1, 0, 2, 3).reshape(c, n * hw)
            s1 = (cols @ wv)[:, 0]
            s2 = ((xf * xf).transpose(1, 0, 2, 3).reshape(c, n * hw)
                  @ wv)[:, 0]
            cnt = (jnp.ones((1, n * hw), jnp.float32) @ wv)[0, 0]
            return s1, s2, cnt
        if row_weights is None:
            cnt = jnp.asarray(float(np.prod([x.shape[a] for a in axes])),
                              jnp.float32)
            s1 = jnp.sum(xf, axis=axes)
            s2 = jnp.sum(xf * xf, axis=axes)
        else:
            w = row_weights.reshape((x.shape[0],) + (1,) * (x.ndim - 1))
            w = w.astype(jnp.float32)
            per_row = float(np.prod([x.shape[a] for a in axes if a != 0])
                            or 1.0)
            cnt = jnp.sum(w) * per_row
            s1 = jnp.sum(xf * w, axis=axes)
            s2 = jnp.sum(xf * xf * w, axis=axes)
        return s1, s2, cnt

    @staticmethod
    def stats_of(s1, s2, cnt):
        """(mean, var) from raw moments."""
        cnt = jnp.maximum(cnt, 1.0)
        mean = s1 / cnt
        var = jnp.maximum(s2 / cnt - mean * mean, 0.0)
        return mean, var

    @staticmethod
    def weighted_batch_stats(x, row_weights):
        """Batch mean/var over real rows only (pad rows weigh 0) — the
        masked remainder-batch path must not let zero padding skew the
        statistics the real rows are normalized with."""
        mean, var = BatchNormLayer.stats_of(
            *BatchNormLayer.moments(x, row_weights))
        return mean.astype(x.dtype), var.astype(x.dtype)

    @staticmethod
    def apply_stats(params, x, mean, var):
        """Normalize x with the given stats + the layer's affine."""
        eps = 1e-5
        if x.ndim == 4:
            # gemm-broadcast each per-channel vector over the (n, h, w)
            # positions: value-identical to a plain broadcast, but the
            # backward-pass batch reduction lowers as a gemm contraction
            # with trailing pad columns — pad-invariant gamma/beta (and
            # upstream mean/var) grads, mirroring the 2-D branch below
            n, h, w = x.shape[0], x.shape[2], x.shape[3]

            def bc(v):
                return (rows_broadcast(v, n * h * w, x.dtype)
                        .reshape(n, h, w, -1).transpose(0, 3, 1, 2))

            mean, var = bc(mean), bc(var)
            gamma, beta = bc(params["gamma"]), bc(params["beta"])
        elif x.ndim == 2:
            # gemm-broadcast every feature vector (pad-invariant grads for
            # gamma/beta and for whatever feeds mean/var — see rows_broadcast)
            n = x.shape[0]
            mean = rows_broadcast(mean, n, x.dtype)
            var = rows_broadcast(var, n, x.dtype)
            gamma = rows_broadcast(params["gamma"], n, x.dtype)
            beta = rows_broadcast(params["beta"], n, x.dtype)
        else:
            gamma, beta = params["gamma"], params["beta"]
        xn = (x - mean) / jnp.sqrt(var + eps)
        return xn * gamma + beta

    @staticmethod
    def forward(params, conf, x, key=None, training=False, row_weights=None):
        axes = BatchNormLayer._feature_axes(x)
        if training and row_weights is not None:
            mean, var = BatchNormLayer.weighted_batch_stats(x, row_weights)
        elif training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
        else:
            mean, var = params["ema_mean"], params["ema_var"]
            if "ema_w" in params:  # bias-corrected running estimate
                ema_w = params["ema_w"]
                denom = jnp.maximum(ema_w, 1e-8)
                mean = mean / denom
                # untrained (ema_w == 0): identity-ish normalization
                var = jnp.where(ema_w > 0, var / denom, jnp.ones_like(var))
        return BatchNormLayer.apply_stats(params, x, mean, var)


class EmbeddingLayer:
    """Integer ids -> embedding rows (gather; MXU-friendly one-hot matmul for
    tiny vocabularies is not worth it — XLA lowers gather well on TPU).

    With conf.max_seq_len > 0 a learned positional table is added over the
    sequence axis (transformer-LM input embedding)."""

    @staticmethod
    def init(key, conf):
        dist = conf.dist.sampler() if conf.dist is not None else None
        kw, kp = jax.random.split(key)
        params = {
            "W": init_weights(kw, (conf.n_in, conf.n_out), conf.weight_init,
                              dist, _dtype(conf)),
        }
        if conf.max_seq_len > 0:
            params["P"] = 0.02 * jax.random.normal(
                kp, (conf.max_seq_len, conf.n_out), _dtype(conf))
        return params

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        e = params["W"][x.astype(jnp.int32)]
        if "P" in params and e.ndim >= 2:
            s = e.shape[-2]
            if s > params["P"].shape[0]:
                raise ValueError(
                    f"sequence length {s} exceeds max_seq_len "
                    f"{params['P'].shape[0]}")
            e = e + params["P"][:s]
        return e
