"""Dense / feed-forward layers.

Parity: reference `BaseLayer.java:46-408` — param table {"W","b"},
`activate() = f(x.W + b)` (:211-219), dropout (:250-262), dropconnect;
`merge` (parameter averaging, :271-273) is subsumed by pytree arithmetic in
`parallel/averaging.py`.  Plus BatchNorm and Embedding layers (capability the
reference's config enum gestures at via BASELINE config[2] "ConvolutionLayer
+ BatchNorm").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nd import random as ndr
from deeplearning4j_tpu.nd.ops import activate
from deeplearning4j_tpu.nn.weights import init_weights


def _dtype(conf):
    return jnp.dtype(conf.dtype)


def compute_dtype(conf):
    cd = getattr(conf, "compute_dtype", "")
    return jnp.dtype(cd) if cd else jnp.dtype(conf.dtype)


def mixed_matmul(x, W, conf):
    """x @ W with operands in conf.compute_dtype — bf16 feeds the MXU at
    full rate while params stay f32 (output cast back to the param dtype;
    TPU bf16 matmuls accumulate in f32 on the MXU)."""
    cd = compute_dtype(conf)
    return (x.astype(cd) @ W.astype(cd)).astype(W.dtype)


class DenseLayer:
    """f(x.W + b) with optional dropout/dropconnect."""

    @staticmethod
    def init(key, conf):
        kw, _ = jax.random.split(key)
        dist = conf.dist.sampler() if conf.dist is not None else None
        return {
            "W": init_weights(kw, (conf.n_in, conf.n_out), conf.weight_init,
                              dist, _dtype(conf)),
            "b": jnp.zeros((conf.n_out,), _dtype(conf)),
        }

    @staticmethod
    def preout(params, conf, x, key=None, training=False):
        W = params["W"]
        if training and conf.drop_connect and key is not None:
            W = W * ndr.dropout_mask(key, 0.5, W.shape, W.dtype)
        return mixed_matmul(x, W, conf) + params["b"]

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        kdrop = kdc = None
        if key is not None:
            kdrop, kdc = jax.random.split(key)
        if training and conf.dropout > 0.0 and kdrop is not None:
            x = x * ndr.dropout_mask(kdrop, 1.0 - conf.dropout, x.shape, x.dtype)
        z = DenseLayer.preout(params, conf, x, kdc, training)
        return activate(conf.activation, z)


class BatchNormLayer:
    """Batch normalization over the feature axis.

    Stateless-from-jit design: running stats live in params under "ema_*" and
    are updated outside jit by the training loop (or folded in via
    `forward(..., training=True)` which normalizes with batch stats).
    """

    @staticmethod
    def init(key, conf):
        n = conf.n_out or conf.n_in
        d = _dtype(conf)
        return {
            "gamma": jnp.ones((n,), d),
            "beta": jnp.zeros((n,), d),
            "ema_mean": jnp.zeros((n,), d),
            "ema_var": jnp.ones((n,), d),
        }

    @staticmethod
    def _feature_axes(x):
        """Reduction axes: channel axis is 1 for NCHW conv outputs, -1 for
        dense features."""
        return (0, 2, 3) if x.ndim == 4 else tuple(range(x.ndim - 1))

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        eps = 1e-5
        axes = BatchNormLayer._feature_axes(x)
        if training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
        else:
            mean, var = params["ema_mean"], params["ema_var"]
        if x.ndim == 4:
            mean = mean[None, :, None, None]
            var = var[None, :, None, None]
            gamma = params["gamma"][None, :, None, None]
            beta = params["beta"][None, :, None, None]
        else:
            gamma, beta = params["gamma"], params["beta"]
        xn = (x - mean) / jnp.sqrt(var + eps)
        return xn * gamma + beta


class EmbeddingLayer:
    """Integer ids -> embedding rows (gather; MXU-friendly one-hot matmul for
    tiny vocabularies is not worth it — XLA lowers gather well on TPU).

    With conf.max_seq_len > 0 a learned positional table is added over the
    sequence axis (transformer-LM input embedding)."""

    @staticmethod
    def init(key, conf):
        dist = conf.dist.sampler() if conf.dist is not None else None
        kw, kp = jax.random.split(key)
        params = {
            "W": init_weights(kw, (conf.n_in, conf.n_out), conf.weight_init,
                              dist, _dtype(conf)),
        }
        if conf.max_seq_len > 0:
            params["P"] = 0.02 * jax.random.normal(
                kp, (conf.max_seq_len, conf.n_out), _dtype(conf))
        return params

    @staticmethod
    def forward(params, conf, x, key=None, training=False):
        e = params["W"][x.astype(jnp.int32)]
        if "P" in params and e.ndim >= 2:
            s = e.shape[-2]
            if s > params["P"].shape[0]:
                raise ValueError(
                    f"sequence length {s} exceeds max_seq_len "
                    f"{params['P'].shape[0]}")
            e = e + params["P"][:s]
        return e
