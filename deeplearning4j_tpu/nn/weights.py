"""Weight initialization — parity with reference `WeightInit` / `WeightInitUtil`.

Reference: `nn/weights/WeightInit.java:25-34` (enum `VI, ZERO, SIZE,
DISTRIBUTION, NORMALIZED, UNIFORM`) and `nn/weights/WeightInitUtil.java:74-107`:
  NORMALIZED:  U(0,1) - 0.5, divided by fan-in (shape[0])
  UNIFORM:     U(-1/fanIn, 1/fanIn)
  VI:          variance-normalized: U(-r, r) with r = sqrt(6)/sqrt(sum(shape)+1)
  DISTRIBUTION: sample the configured distribution
  SIZE:        uniform based on fan-in/fan-out (Glorot-uniform style)
  ZERO:        zeros

TPU-native: stateless — every initializer takes an explicit PRNG key.
Also adds the modern schemes (XAVIER/GLOROT, HE/RELU, LECUN) so new models
aren't limited to the 2015 set.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


class WeightInit(str, enum.Enum):
    VI = "vi"
    ZERO = "zero"
    SIZE = "size"
    DISTRIBUTION = "distribution"
    NORMALIZED = "normalized"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    RELU = "relu"
    LECUN = "lecun"

    def __str__(self) -> str:
        return self.value


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    scheme=WeightInit.VI,
    distribution=None,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """Create a weight tensor of `shape` under the named scheme.

    `distribution`, used by DISTRIBUTION, is a callable `(key, shape) -> array`
    (see `deeplearning4j_tpu.nn.conf.Distribution.sampler`).
    """
    shape = tuple(int(s) for s in shape)
    scheme = WeightInit(str(scheme).lower())
    fan_in = shape[0] if shape else 1
    fan_out = shape[1] if len(shape) > 1 else shape[0] if shape else 1

    if scheme == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if scheme == WeightInit.NORMALIZED:
        return ((jax.random.uniform(key, shape) - 0.5) / fan_in).astype(dtype)
    if scheme == WeightInit.UNIFORM:
        a = 1.0 / fan_in
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.VI:
        r = jnp.sqrt(6.0) / jnp.sqrt(sum(shape) + 1.0)
        return (jax.random.uniform(key, shape) * 2.0 * r - r).astype(dtype)
    if scheme == WeightInit.SIZE or scheme == WeightInit.XAVIER:
        r = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, minval=-r, maxval=r)
    if scheme == WeightInit.RELU:
        return (jax.random.normal(key, shape) * jnp.sqrt(2.0 / fan_in)).astype(dtype)
    if scheme == WeightInit.LECUN:
        return (jax.random.normal(key, shape) * jnp.sqrt(1.0 / fan_in)).astype(dtype)
    if scheme == WeightInit.DISTRIBUTION:
        if distribution is None:
            raise ValueError("WeightInit.DISTRIBUTION requires a distribution")
        return jnp.asarray(distribution(key, shape), dtype)
    raise ValueError(f"unknown weight init scheme {scheme}")
