"""MultiLayerNetwork — the stacked-network model container.

Parity: reference `nn/multilayer/MultiLayerNetwork.java:59-1530`:
  fit(iter)            -> pretrain (layer-wise) + finetune/backprop   (:928-992)
  feedForward/output   -> per-layer activate with InputPreProcessors  (:488-518, :1159)
  predict              -> row argmax                                   (:1069-1078)
  score                -> output-layer loss                            (OutputLayer.java:77-90)
  params()/setParams   -> flat parameter vector pack/unpack
  merge                -> parameter averaging (see parallel/averaging.py)

TPU-native design: the network is a frozen config + a params pytree (tuple of
per-layer dicts).  Training compiles ONE XLA program per (config, batch
shape): the configured solver (optimize.solver) runs its whole iteration
loop on-device.  Backprop is `jax.grad` through the stacked forward — there
is no hand-written `backWard`/delta algebra to maintain.  Layer-wise
pretraining drives each pretrainable layer's `pretrain_grad_and_score`
through the same solver machinery (`pretrain` flag parity).
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.nn.conf import (LayerType, MultiLayerConfiguration,
                                        OptimizationAlgorithm)
from deeplearning4j_tpu.nn.layers import get_layer
from deeplearning4j_tpu.nn.layers.preprocessor import apply_preprocessor
from deeplearning4j_tpu.optimize import solver as solver_mod
from deeplearning4j_tpu.optimize.infer_cache import InferCache
from deeplearning4j_tpu.optimize.listeners import dispatch as dispatch_listeners
from deeplearning4j_tpu.optimize.step_cache import TrainStepCache
from deeplearning4j_tpu.reliability import TrainingInterrupted

log = logging.getLogger("deeplearning4j_tpu")

_PRETRAINABLE = {LayerType.RBM, LayerType.AUTOENCODER,
                 LayerType.RECURSIVE_AUTOENCODER}


def init_params(conf: MultiLayerConfiguration, key) -> tuple:
    """Initialize every layer's params (ParamInitializer dispatch parity)."""
    keys = jax.random.split(key, max(1, conf.n_layers))
    return tuple(
        get_layer(c.layer_type).init(keys[i], c)
        for i, c in enumerate(conf.confs)
    )


def _layer_forward(impl, c, params, h, key, training):
    """One layer's forward, optionally under jax.checkpoint (conf.remat):
    activations inside the layer are recomputed during backward instead of
    stored, trading ~1/3 extra FLOPs for HBM capacity — the standard TPU
    trick for fitting larger batches (SURVEY §7 / scaling-book recipe).

    Training forwards go through jax.checkpoint with BOTH remat settings
    (remat=False saves every residual, so nothing is recomputed): the
    checkpoint boundary fixes the layer's backward to one
    linearize-then-transpose structure, whose input-cotangent summation
    order differs from plain trace-through autodiff by float noise.  One
    shared structure means flipping conf.remat changes memory, never a
    single grad bit."""
    if training:
        policy = (None if c.remat
                  else jax.checkpoint_policies.everything_saveable)
        return jax.checkpoint(
            lambda p, hh, kk: impl.forward(p, c, hh, kk, training),
            policy=policy)(params, h, key)
    return impl.forward(params, c, h, key, training)


def feed_forward(conf: MultiLayerConfiguration, params, x, key=None,
                 training=False, up_to: Optional[int] = None):
    """Activations after each layer (MultiLayerNetwork.feedForward parity).

    Returns the list of post-layer activations; `up_to` stops early (used by
    layer-wise pretraining to build a layer's input).
    """
    n = conf.n_layers if up_to is None else up_to
    acts = []
    keys = (jax.random.split(key, max(1, n)) if key is not None
            else [None] * max(1, n))
    for i in range(n):
        c = conf.conf(i)
        x = apply_preprocessor(conf.preprocessor(i), x)
        x = _layer_forward(get_layer(c.layer_type), c, params[i], x,
                           keys[i], training)
        acts.append(x)
    return acts


def network_output(conf, params, x, key=None, training=False):
    acts = feed_forward(conf, params, x, key, training)
    return acts[-1] if acts else x


def network_loss(conf: MultiLayerConfiguration, params, x, labels, key=None,
                 training=True):
    """End-to-end loss: hidden forward + OutputLayer loss (+ L2 across layers)."""
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    n = conf.n_layers
    keys = (jax.random.split(key, n) if key is not None else [None] * n)
    h = x
    for i in range(n - 1):
        c = conf.conf(i)
        h = apply_preprocessor(conf.preprocessor(i), h)
        h = _layer_forward(get_layer(c.layer_type), c, params[i], h,
                           keys[i], training)
    out_conf = conf.conf(n - 1)
    h = apply_preprocessor(conf.preprocessor(n - 1), h)
    loss = OutputLayer.loss(params[n - 1], out_conf, h, labels, keys[n - 1],
                            training)
    if out_conf.use_regularization and out_conf.l2:
        for i in range(n - 1):
            if "W" in params[i]:
                loss = loss + 0.5 * out_conf.l2 * jnp.sum(
                    params[i]["W"].astype(jnp.float32) ** 2)
    return loss


def network_rowwise_loss(conf: MultiLayerConfiguration, params, x, labels,
                         key=None, training=True, row_weights=None,
                         return_bn_stats=False):
    """Per-label-row loss vector, no regularization (see
    `network_regularization` for that half).  Row count follows `labels`'
    leading dim — e.g. B*T rows for a char-LSTM whose rnn_to_ff stage
    flattens time into the batch.

    row_weights (per feature row, pad rows = 0) keeps BATCH_NORM training
    statistics over real rows only — zero padding must neither skew the
    normalization nor the loss.

    return_bn_stats=True additionally returns the raw BN moments
    ((s1, s2, cnt) per BATCH_NORM layer, in layer order) computed during
    THIS forward, so train steps can maintain running inference stats
    without a second forward pass (`update_bn_ema_from_stats`)."""
    from deeplearning4j_tpu.nn.layers.base import BatchNormLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    n = conf.n_layers
    keys = (jax.random.split(key, n) if key is not None else [None] * n)
    h = x
    stats = []
    for i in range(n - 1):
        c = conf.conf(i)
        h = apply_preprocessor(conf.preprocessor(i), h)
        impl = get_layer(c.layer_type)
        is_bn = LayerType(str(c.layer_type)) == LayerType.BATCH_NORM
        if is_bn and training and (row_weights is not None
                                   or return_bn_stats):
            s1, s2, cnt = BatchNormLayer.moments(h, row_weights)
            if return_bn_stats:
                stats.append((s1, s2, cnt))
            mean, var = BatchNormLayer.stats_of(s1, s2, cnt)
            h = BatchNormLayer.apply_stats(params[i], h,
                                           mean.astype(h.dtype),
                                           var.astype(h.dtype))
        else:
            h = _layer_forward(impl, c, params[i], h, keys[i], training)
    out_conf = conf.conf(n - 1)
    h = apply_preprocessor(conf.preprocessor(n - 1), h)
    rows = OutputLayer.rowwise_loss(params[n - 1], out_conf, h, labels,
                                    keys[n - 1], training)
    if return_bn_stats:
        return rows, tuple(stats)
    return rows


def has_batchnorm(conf: MultiLayerConfiguration) -> bool:
    return any(LayerType(str(c.layer_type)) == LayerType.BATCH_NORM
               for c in conf.confs)


def _bn_ema_apply(c, p, mean, var):
    """One layer's EMA advance: ema = m*ema + (1-m)*batch, plus the total
    EMA weight used for bias correction at inference."""
    m = c.batch_norm_momentum
    p = dict(p)
    p["ema_mean"] = (m * p["ema_mean"].astype(jnp.float32)
                     + (1 - m) * mean).astype(p["ema_mean"].dtype)
    p["ema_var"] = (m * p["ema_var"].astype(jnp.float32)
                    + (1 - m) * var).astype(p["ema_var"].dtype)
    if "ema_w" in p:
        p["ema_w"] = (m * p["ema_w"].astype(jnp.float32)
                      + (1 - m)).astype(p["ema_w"].dtype)
    return p


def update_bn_ema_from_stats(conf: MultiLayerConfiguration, params, stats,
                             axis=None):
    """Advance every BATCH_NORM layer's running stats from the raw moments
    the loss forward already computed (`network_rowwise_loss(...,
    return_bn_stats=True)`) — no second forward pass.

    axis: shard_map collective axis — moments are psum'd across dp shards
    so every shard records GLOBAL-batch statistics.
    """
    from deeplearning4j_tpu.nn.layers.base import BatchNormLayer

    bn_idx = [i for i, c in enumerate(conf.confs)
              if LayerType(str(c.layer_type)) == LayerType.BATCH_NORM]
    new = list(params)
    for (s1, s2, cnt), i in zip(stats, bn_idx):
        if axis is not None:
            s1 = jax.lax.psum(s1, axis)
            s2 = jax.lax.psum(s2, axis)
            cnt = jax.lax.psum(cnt, axis)
        mean, var = BatchNormLayer.stats_of(s1, s2, cnt)
        new[i] = _bn_ema_apply(conf.conf(i), new[i], mean, var)
    return tuple(new)


def update_bn_ema(conf: MultiLayerConfiguration, params, x, axis=None,
                  row_weights=None):
    """Running-EMA update of every BATCH_NORM layer's inference stats from
    one training batch via a (partial) forward pass — for host-side training
    loops that can't thread the stats out of their loss forward (MLN.fit's
    solver scans).  Compiled train steps should prefer
    `update_bn_ema_from_stats` (zero extra forwards).

    axis:        shard_map collective axis name — batch stats are psum'd
                 across dp shards so every shard sees GLOBAL-batch stats.
    row_weights: optional per-feature-row weights (pad rows of a masked
                 remainder batch carry 0 — excluded from the stats AND from
                 the propagated activations' normalization).
    """
    if not has_batchnorm(conf):
        return params
    from deeplearning4j_tpu.nn.layers.base import BatchNormLayer

    last_bn = max(i for i, c in enumerate(conf.confs)
                  if LayerType(str(c.layer_type)) == LayerType.BATCH_NORM)
    new = list(params)
    h = x
    for i in range(last_bn + 1):
        c = conf.conf(i)
        h = apply_preprocessor(conf.preprocessor(i), h)
        is_bn = LayerType(str(c.layer_type)) == LayerType.BATCH_NORM
        if is_bn:
            s1, s2, cnt = BatchNormLayer.moments(h, row_weights)
            if axis is not None:
                s1 = jax.lax.psum(s1, axis)
                s2 = jax.lax.psum(s2, axis)
                cnt = jax.lax.psum(cnt, axis)
            mean, var = BatchNormLayer.stats_of(s1, s2, cnt)
            new[i] = _bn_ema_apply(c, new[i], mean, var)
        if i < last_bn:
            # propagate with batch stats (training=True) — downstream BN
            # layers must see the inputs training actually produces
            # (row-weighted so pad rows don't skew the propagation either)
            if is_bn:
                h = BatchNormLayer.forward(params[i], c, h, None,
                                           training=True,
                                           row_weights=row_weights)
            else:
                h = get_layer(c.layer_type).forward(params[i], c, h, None,
                                                    training=True)
    return tuple(new)


def make_finetune_loss(conf: MultiLayerConfiguration, collect_bn: bool = False):
    """Batched finetune loss `(params, x, y, w, key) -> (loss, stats)`.

    Loss = row-weighted mean of `network_rowwise_loss` over the real rows
    (w is the per-LABEL-row weight vector; pad rows carry 0) plus
    `network_regularization`.  This is the ONE loss definition shared by
    the compiled step-cache programs and the uncached comparison path, so
    cached and uncached training match bit-for-bit; a full batch is just
    w = ones.  stats is () unless collect_bn (then the raw BatchNorm
    moments of this forward, for `update_bn_ema_from_stats`)."""

    def loss_fn(params, x, y, w, key):
        # feature-row weights from label-row weights (label rows may be
        # B*T for sequence models)
        ratio = w.shape[0] // x.shape[0]
        wx = w.reshape(x.shape[0], ratio)[:, 0]
        out = network_rowwise_loss(conf, params, x, y, key, training=True,
                                   row_weights=wx,
                                   return_bn_stats=collect_bn)
        rows, stats = out if collect_bn else (out, ())
        # dot, not sum(rows * w): a gemm contraction over the batch dim is
        # bit-invariant to trailing zero-weight pad rows, while reduce_sum's
        # pairwise split is shape-dependent (see layers.base.rows_broadcast)
        loss = (jnp.dot(rows, w) / jnp.maximum(jnp.dot(w, jnp.ones_like(w)),
                                               1.0)
                + network_regularization(conf, params))
        return loss, stats

    return loss_fn


def network_regularization(conf: MultiLayerConfiguration, params):
    """The regularization half of `network_loss` (L2 across layers + the
    output layer's L2/L1), as one scalar counted once per step."""
    out_conf = conf.conf(conf.n_layers - 1)
    reg = jnp.asarray(0.0, jnp.float32)
    if not out_conf.use_regularization:
        return reg
    if out_conf.l2:
        for i in range(conf.n_layers):
            if "W" in params[i]:
                reg = reg + 0.5 * out_conf.l2 * jnp.sum(
                    params[i]["W"].astype(jnp.float32) ** 2)
    if out_conf.l1:
        reg = reg + out_conf.l1 * jnp.sum(
            jnp.abs(params[conf.n_layers - 1]["W"].astype(jnp.float32)))
    return reg


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration, seed: Optional[int] = None):
        self.conf = conf
        if seed is None:
            seed = conf.confs[0].seed if conf.confs else 123
        self._key = jax.random.PRNGKey(seed)
        self.params: Optional[tuple] = None
        self.listeners: List = []
        self._bn_ema_fn = None
        # compiled train-step cache: one AOT-compiled solver program per
        # (conf, algo, batch shape), reused across every fit batch.
        # use_step_cache=False restores the legacy retrace-per-batch path.
        self.step_cache = TrainStepCache()
        self.use_step_cache = True
        # serve-path sibling: one AOT-compiled program per (conf, entry
        # point, shape bucket) for output/score/feed_forward — repeated
        # serving calls at a seen shape never re-trace.
        # use_infer_cache=False restores the legacy retrace-per-call path.
        self.infer_cache = InferCache()
        self.use_infer_cache = True
        self._bn_in_step = False  # did the last finetune advance BN EMA?
        # SIGTERM/preemption flag: `fit(checkpoint_dir=...)` checks it
        # between batches and checkpoints-then-exits when set
        self._stop_training = threading.Event()
        # crash-resume bookkeeping, reported by the CLI train JSON
        self.resumed_from_batch: Optional[int] = None
        self.checkpoint_write_seconds = 0.0
        self.checkpoints_written = 0
        # persistent compile cache: DL4J_COMPILE_CACHE=<dir> attaches the
        # on-disk program store to every network in the process, so
        # restarts skip recompiles (the CLI's --compile-cache flag sets
        # the same thing explicitly)
        cache_dir = os.environ.get("DL4J_COMPILE_CACHE")
        if cache_dir:
            self.set_compile_cache(cache_dir)
        # serve-precision policy report (set_serve_precision): policy
        # name + calibration facts + measured accuracy delta — serving
        # has no labels, so the delta is measured here, once, and the
        # batcher/server/router surface it read-only
        self._serve_precision_report: dict = {"policy": "f32"}

    # -- lifecycle ---------------------------------------------------------
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def init(self) -> "MultiLayerNetwork":
        self.params = init_params(self.conf, self._next_key())
        return self

    def set_listeners(self, listeners) -> None:
        self.listeners = list(listeners)

    # -- persistent compile cache -------------------------------------------
    def set_compile_cache(self, directory, max_bytes=None):
        """Attach a persistent on-disk program store at `directory` to
        both the train-step and serve-path caches (shared store, one key
        schema): memory misses check disk before compiling, and fresh
        compiles write back, so a restarted process skips every compile
        a previous run already paid for.  Returns the store."""
        from deeplearning4j_tpu.optimize.persist import PersistentProgramStore

        kw = {} if max_bytes is None else {"max_bytes": max_bytes}
        store = PersistentProgramStore(directory, **kw)
        self.step_cache.set_persist(store)
        self.infer_cache.set_persist(store)
        # tuned-table inheritance (ISSUE 18): a table `cli tune` persisted
        # for this (conf fingerprint, device kind) installs process-wide
        # here, so replicas and future sessions serve with the tuned
        # constants and fresh_tunes == 0.  Missing/corrupt/wrong-kind
        # tables degrade to registry defaults inside load_and_install.
        from deeplearning4j_tpu.optimize import tunables
        from deeplearning4j_tpu.optimize.step_cache import conf_fingerprint

        if tunables.active() is None:
            tunables.load_and_install(store, conf_fingerprint(self.conf))
        return store

    def set_serve_mesh(self, mesh=None, spec=None):
        """Shard the serve path across a mesh.  With no arguments this
        is the 1-D pattern: `Mesh(('batch',))` over every visible
        device, rows split, params replicated, collectives inserted by
        jit (GSPMD).  `spec` takes a `--mesh`-style string instead
        ("batch=2,model=4", parsed by `parallel.plan.parse_mesh_spec`;
        "all" or "" = the 1-D default): a `model` axis tensor-shards
        params, activations, and decode KV state per the ShardPlan, so
        one model can exceed one chip's HBM.  Sharding is a cache-KEY
        dimension — single-chip, 1-D, and 2-D programs coexist in
        memory and on disk, and 1-D keys are byte-identical to their
        pre-plan form.  Returns the mesh."""
        from deeplearning4j_tpu.parallel.mesh import serve_mesh
        from deeplearning4j_tpu.parallel.plan import (parse_mesh_spec,
                                                      plan_mesh)

        if mesh is not None and spec is not None:
            raise ValueError("pass mesh= or spec=, not both")
        if spec is not None:
            mesh = plan_mesh(parse_mesh_spec(spec))
        elif mesh is None:
            mesh = serve_mesh()
        self.infer_cache.set_mesh(mesh)
        return mesh

    @property
    def serve_precision(self) -> str:
        """Active serve-path precision policy ("f32" until changed)."""
        return self.infer_cache.policy

    @property
    def serve_precision_report(self) -> dict:
        """The report `set_serve_precision` produced for the active
        policy (calibration facts + measured accuracy delta)."""
        return self._serve_precision_report

    def set_serve_precision(self, policy: str = "f32", calibration=None,
                            measure: bool = True) -> dict:
        """Serve every subsequent `output`/`feed_forward`/`score` call —
        and every program `warmup()` compiles from here on — under a
        precision policy (optimize/quantize.py): "f32" (default,
        bitwise-unchanged), "bf16" (params cast on load, bf16 compute),
        or "int8" (per-channel symmetric weight quantization, scales
        calibrated on `calibration` — a held-out batch; None builds a
        deterministic synthetic one shaped for the conf).

        The policy is a cache-KEY dimension like the serve mesh, so
        per-policy programs coexist in memory and in the disk store.
        With a persistent store attached, the int8 quantized weights are
        themselves persisted (checksummed, LRU'd) keyed by (conf
        fingerprint, params digest) — a restarted process reloads the
        exact same scales instead of recalibrating.  int8 quantizes a
        SNAPSHOT of the current params; after further training, call
        this again to requantize.

        Returns (and retains, see `serve_precision_report`) a report
        with the measured accuracy delta vs f32 on a held-out batch
        (`measure=False` skips the measurement forwards)."""
        from deeplearning4j_tpu.optimize import quantize

        quantize.validate_policy(policy)
        if self.params is None:
            self.init()
        qparams = cal_report = None
        if policy == "int8":
            if calibration is None:
                calibration = quantize.default_calibration(self.conf)
            calibration = jnp.asarray(calibration)
            store = self.infer_cache.persist
            art_key = quantize.quantize_artifact_key(
                self.infer_cache._fingerprint(self.conf),
                quantize.params_digest(self.params))
            blob = store.load_bytes(art_key) if store is not None else None
            if blob is not None:
                try:
                    qparams, cal_report = quantize.unpack_quantized(blob)
                except Exception:  # noqa: BLE001 — recalibrate instead
                    qparams = None
            if qparams is None:
                qparams, cal_report = quantize.calibrate_int8(
                    self.conf, self.params, calibration)
                if store is not None:
                    store.store_bytes(
                        art_key, quantize.pack_quantized(qparams, cal_report))
        self.infer_cache.set_policy(policy, qparams=qparams)
        report = {"policy": policy}
        if cal_report:
            report["calibration"] = cal_report
        if measure and policy != "f32":
            # held out from the calibration batch when that defaulted
            batch = (calibration if calibration is not None
                     else quantize.default_calibration(self.conf, seed=1))
            report["accuracy_delta"] = quantize.accuracy_delta(
                self.conf, self.params, jnp.asarray(batch), policy,
                qparams=qparams)
        self._serve_precision_report = report
        return report

    def warmup(self, shapes, entries=("output",), train=False):
        """Precompile the serve/train programs for the given batch shapes
        ahead of traffic, so the first real request is a cache hit.

        `shapes`: iterable of batch sizes (int → (b, n_in)), full input
        shapes (tuple), or example arrays.  `entries` picks the serve
        entry points ("output", "feed_forward", "loss"); `train=True`
        additionally compiles the train step for each shape.  With a
        persistent store attached (`set_compile_cache`), warmup populates
        the disk cache for every future process too.  Returns a summary
        dict with the per-cache stats."""
        if self.params is None:
            self.init()
        compiled = []
        for spec in shapes:
            if isinstance(spec, int):
                x = jnp.zeros((spec, self.conf.confs[0].n_in), jnp.float32)
            elif isinstance(spec, tuple):
                x = jnp.zeros(spec, jnp.float32)
            else:
                x = jnp.asarray(spec)
            y = None
            if train or "loss" in entries:
                out = jax.eval_shape(
                    lambda p, xx: network_output(self.conf, p, xx, key=None,
                                                 training=False),
                    self.params, x)
                y = jnp.zeros(out.shape, out.dtype)
            for entry in entries:
                if entry == "output":
                    self.infer_cache.output(self.conf, self.params, x,
                                            compile_only=True)
                elif entry == "feed_forward":
                    self.infer_cache.feed_forward(self.conf, self.params, x,
                                                  compile_only=True)
                elif entry == "loss":
                    self.infer_cache.loss(self.conf, self.params, x, y,
                                          compile_only=True)
                else:
                    raise ValueError(f"unknown warmup entry {entry!r}")
            if train:
                self.step_cache.finetune(self.conf, self.params, x, y,
                                         self._key, compile_only=True)
            compiled.append(tuple(x.shape))
        return {
            "shapes": compiled,
            "entries": list(entries),
            "train": bool(train),
            "step_cache": self.step_cache.stats.as_dict(),
            "infer_cache": self.infer_cache.stats.as_dict(),
        }

    def warmup_generate(self, slots: Optional[int] = None, max_seq: int = 64,
                        prompt_buckets: Sequence[int] = (8,),
                        page_size: Optional[int] = None, n_pages: int = 0,
                        prefix_cache: bool = False, draft_net=None,
                        spec_k: int = 0,
                        steps_per_dispatch: Optional[int] = None):
        """Precompile the autoregressive generation programs (ISSUE 14)
        ahead of traffic: ONE decode step over the `slots`-wide table
        plus one prefill program per prompt bucket (each admission
        prefills a single row, so prefill compiles at B=1).  The
        optional decode accelerators (ISSUE 16) each swap or add
        programs, and the warmup mirrors the serving batcher exactly so
        `fresh_compiles == 0` holds for ANY flag combination:
        `page_size > 0` warms the paged decode step over the shared
        page pool instead of the dense one; `prefix_cache` warms the
        logp-returning prefill the prefix cache records instead of the
        sampling prefill; `draft_net` + `spec_k` warm the batched
        verify step plus the draft model's own decode/prefill programs.
        With a persistent store attached the programs land on disk like
        every other warmup — a restarted serve process starts
        generating with `fresh_compiles == 0`.  Returns a summary with
        the cache stats."""
        if self.params is None:
            self.init()
        # None -> tunable-governed geometry, resolved exactly like
        # ContinuousBatcher's own defaults so warmup and serving compile
        # the same programs under a tuned table
        from deeplearning4j_tpu.optimize import tunables

        slots = int(tunables.resolve("decode.slots")
                    if slots is None else slots)
        page_size = (tunables.resolve("decode.page_size")
                     if page_size is None else page_size)
        if steps_per_dispatch is None:
            steps_per_dispatch = tunables.resolve("decode.steps_per_dispatch")
        k_max = int(steps_per_dispatch)
        if draft_net is not None and k_max > 1:
            # ContinuousBatcher pins speculative decoding to K=1; a
            # tunable-resolved K>1 silently yields there, so warm what
            # the batcher will actually run
            k_max = 1
        ic = self.infer_cache
        tok = jnp.zeros((slots,), jnp.int32)
        pos = jnp.zeros((slots,), jnp.int32)
        keys = jnp.zeros((slots, 2), jnp.uint32)
        temps = jnp.zeros((slots,), jnp.float32)
        rem = jnp.zeros((slots,), jnp.int32)
        page_size = int(page_size)
        page_table = None
        if page_size > 0:
            # identical pool geometry to ContinuousBatcher: physical
            # page 0 is the scratch page, so the pool holds n_pages + 1
            pages_per_slot = -(-int(max_seq) // page_size)
            pool_pages = int(n_pages) or int(slots) * pages_per_slot
            state = ic.init_paged_decode_state(
                self.conf, slots, pool_pages + 1, page_size)
            page_table = jnp.zeros((slots, pages_per_slot), jnp.int32)
            ic.decode_paged(self.conf, self.params, state, tok, pos,
                            keys, temps, page_table, compile_only=True)
            # the adaptive-K loop dispatches every ladder K up to k_max
            # while ramping — k=1 included (a ramp reset dispatches the
            # fused block at K=1, not the classic step) — so warm the
            # whole ladder
            if k_max > 1:
                for k in tunables.decode_k_ladder(k_max):
                    ic.decode_multi_paged(self.conf, self.params, state,
                                          tok, pos, keys, temps, rem,
                                          page_table, k, compile_only=True)
        else:
            state = ic.init_decode_state(self.conf, slots, max_seq)
            ic.decode(self.conf, self.params, state, tok, pos, keys,
                      temps, compile_only=True)
            if k_max > 1:
                for k in tunables.decode_k_ladder(k_max):
                    ic.decode_multi(self.conf, self.params, state, tok,
                                    pos, keys, temps, rem, k,
                                    compile_only=True)
        if draft_net is not None:
            if int(spec_k) < 2:
                raise ValueError("draft_net requires spec_k >= 2")
            toks = jnp.zeros((slots, int(spec_k)), jnp.int32)
            if page_size > 0:
                ic.verify_paged(self.conf, self.params, state, toks,
                                pos, keys, temps, page_table,
                                compile_only=True)
            else:
                ic.verify(self.conf, self.params, state, toks, pos,
                          keys, temps, compile_only=True)
            dic = draft_net.infer_cache
            dstate = dic.init_decode_state(draft_net.conf, slots, max_seq)
            dic.decode(draft_net.conf, draft_net.params, dstate, tok,
                       pos, keys, temps, compile_only=True)
        row = ic.init_decode_state(self.conf, 1, max_seq)
        buckets = sorted(int(b) for b in prompt_buckets)
        for tb in buckets:
            if tb > max_seq:
                raise ValueError(f"prompt bucket {tb} exceeds "
                                 f"max_seq={max_seq}")
            prompt = jnp.zeros((1, tb), jnp.int32)
            length = jnp.ones((1,), jnp.int32)
            if prefix_cache:
                ic.prefill_logp(self.conf, self.params, row, prompt,
                                length, compile_only=True)
            else:
                ic.prefill(self.conf, self.params, row, prompt, length,
                           keys[:1], temps[:1], compile_only=True)
            if draft_net is not None:
                drow = draft_net.infer_cache.init_decode_state(
                    draft_net.conf, 1, max_seq)
                draft_net.infer_cache.prefill(
                    draft_net.conf, draft_net.params, drow, prompt,
                    length, keys[:1], temps[:1], compile_only=True)
        return {
            "slots": int(slots),
            "max_seq": int(max_seq),
            "prompt_buckets": buckets,
            "page_size": page_size,
            "prefix_cache": bool(prefix_cache),
            "spec_k": int(spec_k) if draft_net is not None else 0,
            "steps_per_dispatch": k_max,
            "infer_cache": ic.stats.as_dict(),
        }

    # -- serving ------------------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0,
              max_delay_ms: Optional[float] = None, max_pending: int = 1024,
              max_batch_rows=None, batching: bool = True,
              request_timeout_s: float = 30.0,
              drain_timeout_s: float = 10.0,
              default_deadline_ms=None, breaker=None,
              generate: bool = False, gen_slots: Optional[int] = None,
              gen_max_seq: int = 64, gen_prompt_buckets=(8,),
              gen_max_pending: int = 64, gen_page_size: Optional[int] = None,
              gen_pages: int = 0, gen_prefix_cache: bool = False,
              gen_prefix_match: str = "exact", gen_draft=None,
              gen_spec_k: int = 0,
              gen_steps_per_dispatch: Optional[int] = None):
        """Start the micro-batching HTTP gateway over this network
        (`serving.ModelServer`): POST /v1/predict coalesces concurrent
        requests into one bucketed infer-cache call per flush, GET
        /v1/stats reports queue depth / batch histogram / latency
        percentiles / fresh-compile count / breaker state, GET
        /healthz + /readyz report liveness/readiness.  Call `warmup()`
        (or attach a warmed `set_compile_cache` dir) first so the first
        request is served without a fresh compile.  `generate=True`
        additionally runs the continuous-batching decode loop behind
        POST /v1/generate (call `warmup_generate()` with matching
        gen_* arguments first for the same zero-compile start).
        Returns the started server; `server.stop()` drains gracefully
        and shuts it down."""
        from deeplearning4j_tpu.serving.server import ModelServer

        if self.params is None:
            self.init()
        return ModelServer(self, host=host, port=port,
                           max_delay_ms=max_delay_ms,
                           max_pending=max_pending,
                           max_batch_rows=max_batch_rows,
                           batching=batching,
                           request_timeout_s=request_timeout_s,
                           drain_timeout_s=drain_timeout_s,
                           default_deadline_ms=default_deadline_ms,
                           breaker=breaker, generate=generate,
                           gen_slots=gen_slots, gen_max_seq=gen_max_seq,
                           gen_prompt_buckets=gen_prompt_buckets,
                           gen_max_pending=gen_max_pending,
                           gen_page_size=gen_page_size,
                           gen_pages=gen_pages,
                           gen_prefix_cache=gen_prefix_cache,
                           gen_prefix_match=gen_prefix_match,
                           gen_draft=gen_draft,
                           gen_spec_k=gen_spec_k,
                           gen_steps_per_dispatch=gen_steps_per_dispatch
                           ).start()

    # -- inference ---------------------------------------------------------
    def _serve_cached(self, x) -> bool:
        """Serve-path cache eligibility: batched input (axis 0 = rows is
        what bucketing pads) and the cache switched on."""
        return self.use_infer_cache and getattr(x, "ndim", 0) >= 2

    def feed_forward(self, x):
        x = jnp.asarray(x)
        if self._serve_cached(x):
            return self.infer_cache.feed_forward(self.conf, self.params, x)
        return feed_forward(self.conf, self.params, x)

    def output(self, x):
        x = jnp.asarray(x)
        if self._serve_cached(x):
            return self.infer_cache.output(self.conf, self.params, x)
        return network_output(self.conf, self.params, x)

    def predict(self, x):
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def score(self, x, labels) -> float:
        x, labels = jnp.asarray(x), jnp.asarray(labels)
        if self._serve_cached(x):
            return float(self.infer_cache.loss(self.conf, self.params, x,
                                               labels))
        return float(network_loss(self.conf, self.params, x, labels,
                                  key=None, training=False))

    def f1_score(self, x, labels) -> float:
        """Classification F1 on (x, labels) — the reference's
        `OutputLayer.score(examples, labels)` (OutputLayer.java:183-188),
        surfaced at network level: higher is better, 0..1."""
        from deeplearning4j_tpu.evaluation import Evaluation

        ev = Evaluation()
        ev.eval(jnp.asarray(labels), self.output(x))
        return float(ev.f1())

    def evaluate(self, data, labels=None, batch_size: int = 0,
                 prefetch: bool = True):
        """Bucketed, prefetched evaluation — see `evaluation.evaluate`."""
        from deeplearning4j_tpu.evaluation import evaluate

        if labels is not None:
            from deeplearning4j_tpu.datasets.dataset import DataSet

            data = DataSet(np.asarray(data), np.asarray(labels))
        return evaluate(self, data, batch_size=batch_size, prefetch=prefetch)

    # -- training ----------------------------------------------------------
    def _finetune_objective(self, x, labels):
        conf = self.conf

        def loss(params, key):
            return network_loss(conf, params, x, labels, key, training=True)

        objective = solver_mod.from_loss(loss)
        out_conf = conf.conf(conf.n_layers - 1)
        if OptimizationAlgorithm(str(out_conf.optimization_algo)) == \
                OptimizationAlgorithm.HESSIAN_FREE:
            # factor as predict+loss so HF gets Gauss-Newton products
            # (reference: computeDeltasR/feedForwardR R-op machinery,
            # MultiLayerNetwork.java:554-627,1407-1479)
            from deeplearning4j_tpu.nd.losses import get_loss
            loss_fn = get_loss(out_conf.loss_function)

            def predict(params, key):
                return network_output(conf, params, x)

            objective = objective._replace(
                gnvp=solver_mod.from_predict_loss(
                    predict, lambda z: loss_fn(labels, z)).gnvp)
        return objective

    def pretrain_layer(self, i: int, x) -> None:
        """Optimize layer i's unsupervised objective on its own inputs."""
        c = self.conf.conf(i)
        impl = get_layer(c.layer_type)
        x = jnp.asarray(x)

        def gs(p, key):
            return impl.pretrain_grad_and_score(p, c, x, key)

        def sc(p, key):
            return impl.pretrain_score(p, c, x, key)

        if self.use_step_cache:
            new_p, scores = self.step_cache.pretrain(
                c, i, impl, self.params[i], x, self._next_key())
        else:
            objective = solver_mod.Objective(grad_and_score=gs, score=sc)
            new_p, scores = solver_mod.optimize(objective, self.params[i],
                                                c, self._next_key())
        params = list(self.params)
        params[i] = new_p
        self.params = tuple(params)
        dispatch_listeners(self.listeners, self, scores)

    def pretrain(self, data) -> None:
        """Layer-wise pretraining (MultiLayerNetwork.pretrain :149-190)."""
        for batch in _as_batches(data):
            x = jnp.asarray(batch[0] if isinstance(batch, tuple) else batch)
            for i in range(self.conf.n_layers - 1):
                c = self.conf.conf(i)
                if LayerType(str(c.layer_type)) not in _PRETRAINABLE:
                    continue
                acts = feed_forward(self.conf, self.params, x, up_to=i)
                layer_in = acts[-1] if acts else x
                layer_in = apply_preprocessor(self.conf.preprocessor(i), layer_in)
                self.pretrain_layer(i, layer_in)

    def finetune(self, x, labels) -> None:
        """Supervised end-to-end optimization (finetune/backprop parity).

        Default path: the compiled step cache — batch data enters the
        solver program as jit arguments, so a (conf, batch-shape) pair
        compiles once and every further batch is a cache hit.  BatchNorm
        EMA advances inside the compiled step.  Hessian-free rides the
        same cache: its Gauss-Newton product threads the pad-row weight
        mask through the loss-of-outputs half
        (`solver.weighted_predict_loss`), so HF programs share the
        bucketed padding too."""
        x, labels = jnp.asarray(x), jnp.asarray(labels)
        out_conf = self.conf.conf(self.conf.n_layers - 1)
        if self.use_step_cache:
            self.params, scores = self.step_cache.finetune(
                self.conf, self.params, x, labels, self._next_key())
            self._bn_in_step = has_batchnorm(self.conf)
        else:
            objective = self._finetune_objective(x, labels)
            self.params, scores = solver_mod.optimize(
                objective, self.params, out_conf, self._next_key())
            self._bn_in_step = False
        dispatch_listeners(self.listeners, self, scores)

    def _fit_batch(self, x, y) -> None:
        """One fit step: pretrain/finetune/BN-EMA for a single batch."""
        self._bn_in_step = False
        if self.conf.pretrain:
            self.pretrain(jnp.asarray(x))
        if self.conf.backprop:
            self.finetune(x, y)
        if has_batchnorm(self.conf) and not self._bn_in_step:
            # legacy host path (cache disabled / backprop off): true
            # running EMA across every fit batch via an extra partial
            # forward.  The cached finetune already folded this into
            # the compiled step from the solver's own forward.
            if self._bn_ema_fn is None:
                self._bn_ema_fn = jax.jit(partial(update_bn_ema, self.conf))
            self.params = self._bn_ema_fn(self.params, jnp.asarray(x))

    def fit(self, data, labels=None, *, checkpoint_dir: Optional[str] = None,
            checkpoint_every_n_batches: int = 0,
            auto_resume: bool = True) -> None:
        """fit(DataSet/ndarray pair/iterator) — MultiLayerNetwork.fit parity.

        With `checkpoint_dir` the run is crash-safe (ISSUE 5): params +
        RNG key + batch cursor are checkpointed atomically every
        `checkpoint_every_n_batches` batches (and at the end), a SIGTERM
        checkpoints-then-raises `TrainingInterrupted`, and a rerun with
        the same `checkpoint_dir` and the same batch stream auto-resumes
        at the saved cursor — reaching bit-identical params to an
        uninterrupted run at the same total batch count.  (The compiled
        solver re-initializes its updater inside every per-batch
        program, so cross-batch training state is exactly params + RNG
        key; nothing else needs saving.)"""
        if self.params is None:
            self.init()
        if labels is not None:
            batches = [(data, labels)]
        else:
            batches = _as_batches(data)
        if checkpoint_dir is None:
            for batch in batches:
                x, y = batch if isinstance(batch, tuple) else (
                    batch.features, batch.labels)
                self._fit_batch(x, y)
            return
        self._fit_checkpointed(batches, checkpoint_dir,
                               int(checkpoint_every_n_batches), auto_resume)

    def request_stop_training(self) -> None:
        """Ask a running `fit(checkpoint_dir=...)` to checkpoint and
        raise `TrainingInterrupted` after the current batch (what the
        installed SIGTERM handler calls)."""
        self._stop_training.set()

    def _save_checkpoint(self, directory: str, batches_done: int) -> None:
        import time as _time

        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        t0 = _time.perf_counter()
        ckpt.save(directory, self.params, conf=self.conf,
                  step=batches_done,
                  data_cursor={"batches_done": int(batches_done)},
                  metadata={"rng_key": np.asarray(
                      jax.device_get(self._key)).tolist()})
        self.checkpoint_write_seconds += _time.perf_counter() - t0
        self.checkpoints_written += 1

    def _fit_checkpointed(self, batches, checkpoint_dir: str,
                          every_n: int, auto_resume: bool) -> None:
        from deeplearning4j_tpu.parallel import checkpoint as ckpt

        start_batch = 0
        if auto_resume:
            restored = ckpt.load_resilient(checkpoint_dir,
                                           like_params=self.params)
            if restored is not None:
                params, _, meta = restored
                self.params = params
                start_batch = int(
                    (meta.get("data_cursor") or {}).get("batches_done", 0))
                rng = (meta.get("metadata") or {}).get("rng_key")
                if rng is not None:
                    self._key = jnp.asarray(np.asarray(rng, dtype=np.uint32))
                self.resumed_from_batch = start_batch
                log.info("fit: auto-resumed %s at batch %d",
                         checkpoint_dir, start_batch)
        self._stop_training.clear()
        prev_handler, installed = None, False
        if threading.current_thread() is threading.main_thread():
            try:
                prev_handler = signal.signal(
                    signal.SIGTERM,
                    lambda signum, frame: self._stop_training.set())
                installed = True
            except ValueError:
                pass  # exotic embedding: no handler, explicit stop only
        n_done = 0
        try:
            for batch in batches:
                n_done += 1
                if n_done <= start_batch:
                    continue  # replaying the resumed prefix of the stream
                x, y = batch if isinstance(batch, tuple) else (
                    batch.features, batch.labels)
                self._fit_batch(x, y)
                if self._stop_training.is_set():
                    self._save_checkpoint(checkpoint_dir, n_done)
                    raise TrainingInterrupted(
                        f"stop requested: checkpointed {checkpoint_dir} "
                        f"at batch {n_done}")
                if every_n > 0 and n_done % every_n == 0:
                    self._save_checkpoint(checkpoint_dir, n_done)
            self._save_checkpoint(checkpoint_dir, n_done)
        finally:
            if installed:
                signal.signal(signal.SIGTERM, prev_handler)

    # -- parameter vector (distributed/averaging contract) -----------------
    def params_flat(self) -> jnp.ndarray:
        """Flat parameter vector (parity: `MultiLayerNetwork.params()`)."""
        flat, _ = ravel_pytree(self.params)
        return flat

    def set_params_flat(self, flat) -> None:
        _, unravel = ravel_pytree(self.params)
        self.params = unravel(jnp.asarray(flat))

    def clone(self) -> "MultiLayerNetwork":
        net = MultiLayerNetwork(self.conf)
        net.params = self.params
        return net


def _as_batches(data):
    """Normalize fit() inputs: iterator of DataSets, single DataSet, array."""
    if hasattr(data, "features") and hasattr(data, "labels"):
        return [(data.features, data.labels)]
    if hasattr(data, "__next__") or hasattr(data, "reset"):
        return ((d.features, d.labels) for d in data)
    if isinstance(data, (list,)):
        return [(d.features, d.labels) if hasattr(d, "features") else d
                for d in data]
    return [data]
