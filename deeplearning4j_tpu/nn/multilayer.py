"""MultiLayerNetwork — the stacked-network model container.

Parity: reference `nn/multilayer/MultiLayerNetwork.java:59-1530`:
  fit(iter)            -> pretrain (layer-wise) + finetune/backprop   (:928-992)
  feedForward/output   -> per-layer activate with InputPreProcessors  (:488-518, :1159)
  predict              -> row argmax                                   (:1069-1078)
  score                -> output-layer loss                            (OutputLayer.java:77-90)
  params()/setParams   -> flat parameter vector pack/unpack
  merge                -> parameter averaging (see parallel/averaging.py)

TPU-native design: the network is a frozen config + a params pytree (tuple of
per-layer dicts).  Training compiles ONE XLA program per (config, batch
shape): the configured solver (optimize.solver) runs its whole iteration
loop on-device.  Backprop is `jax.grad` through the stacked forward — there
is no hand-written `backWard`/delta algebra to maintain.  Layer-wise
pretraining drives each pretrainable layer's `pretrain_grad_and_score`
through the same solver machinery (`pretrain` flag parity).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.nn.conf import (LayerType, MultiLayerConfiguration,
                                        OptimizationAlgorithm)
from deeplearning4j_tpu.nn.layers import get_layer
from deeplearning4j_tpu.nn.layers.preprocessor import apply_preprocessor
from deeplearning4j_tpu.optimize import solver as solver_mod
from deeplearning4j_tpu.optimize.listeners import dispatch as dispatch_listeners

log = logging.getLogger("deeplearning4j_tpu")

_PRETRAINABLE = {LayerType.RBM, LayerType.AUTOENCODER,
                 LayerType.RECURSIVE_AUTOENCODER}


def init_params(conf: MultiLayerConfiguration, key) -> tuple:
    """Initialize every layer's params (ParamInitializer dispatch parity)."""
    keys = jax.random.split(key, max(1, conf.n_layers))
    return tuple(
        get_layer(c.layer_type).init(keys[i], c)
        for i, c in enumerate(conf.confs)
    )


def feed_forward(conf: MultiLayerConfiguration, params, x, key=None,
                 training=False, up_to: Optional[int] = None):
    """Activations after each layer (MultiLayerNetwork.feedForward parity).

    Returns the list of post-layer activations; `up_to` stops early (used by
    layer-wise pretraining to build a layer's input).
    """
    n = conf.n_layers if up_to is None else up_to
    acts = []
    keys = (jax.random.split(key, max(1, n)) if key is not None
            else [None] * max(1, n))
    for i in range(n):
        c = conf.conf(i)
        x = apply_preprocessor(conf.preprocessor(i), x)
        x = get_layer(c.layer_type).forward(params[i], c, x, keys[i], training)
        acts.append(x)
    return acts


def network_output(conf, params, x, key=None, training=False):
    acts = feed_forward(conf, params, x, key, training)
    return acts[-1] if acts else x


def network_loss(conf: MultiLayerConfiguration, params, x, labels, key=None,
                 training=True):
    """End-to-end loss: hidden forward + OutputLayer loss (+ L2 across layers)."""
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    n = conf.n_layers
    keys = (jax.random.split(key, n) if key is not None else [None] * n)
    h = x
    for i in range(n - 1):
        c = conf.conf(i)
        h = apply_preprocessor(conf.preprocessor(i), h)
        h = get_layer(c.layer_type).forward(params[i], c, h, keys[i], training)
    out_conf = conf.conf(n - 1)
    h = apply_preprocessor(conf.preprocessor(n - 1), h)
    loss = OutputLayer.loss(params[n - 1], out_conf, h, labels, keys[n - 1],
                            training)
    if out_conf.use_regularization and out_conf.l2:
        for i in range(n - 1):
            if "W" in params[i]:
                loss = loss + 0.5 * out_conf.l2 * jnp.sum(
                    params[i]["W"].astype(jnp.float32) ** 2)
    return loss


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration, seed: Optional[int] = None):
        self.conf = conf
        if seed is None:
            seed = conf.confs[0].seed if conf.confs else 123
        self._key = jax.random.PRNGKey(seed)
        self.params: Optional[tuple] = None
        self.listeners: List = []

    # -- lifecycle ---------------------------------------------------------
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def init(self) -> "MultiLayerNetwork":
        self.params = init_params(self.conf, self._next_key())
        return self

    def set_listeners(self, listeners) -> None:
        self.listeners = list(listeners)

    # -- inference ---------------------------------------------------------
    def feed_forward(self, x):
        return feed_forward(self.conf, self.params, jnp.asarray(x))

    def output(self, x):
        return network_output(self.conf, self.params, jnp.asarray(x))

    def predict(self, x):
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def score(self, x, labels) -> float:
        return float(network_loss(self.conf, self.params, jnp.asarray(x),
                                  jnp.asarray(labels), key=None, training=False))

    # -- training ----------------------------------------------------------
    def _finetune_objective(self, x, labels):
        conf = self.conf

        def loss(params, key):
            return network_loss(conf, params, x, labels, key, training=True)

        objective = solver_mod.from_loss(loss)
        out_conf = conf.conf(conf.n_layers - 1)
        if OptimizationAlgorithm(str(out_conf.optimization_algo)) == \
                OptimizationAlgorithm.HESSIAN_FREE:
            # factor as predict+loss so HF gets Gauss-Newton products
            # (reference: computeDeltasR/feedForwardR R-op machinery,
            # MultiLayerNetwork.java:554-627,1407-1479)
            from deeplearning4j_tpu.nd.losses import get_loss
            loss_fn = get_loss(out_conf.loss_function)

            def predict(params, key):
                return network_output(conf, params, x)

            objective = objective._replace(
                gnvp=solver_mod.from_predict_loss(
                    predict, lambda z: loss_fn(labels, z)).gnvp)
        return objective

    def pretrain_layer(self, i: int, x) -> None:
        """Optimize layer i's unsupervised objective on its own inputs."""
        c = self.conf.conf(i)
        impl = get_layer(c.layer_type)
        x = jnp.asarray(x)

        def gs(p, key):
            return impl.pretrain_grad_and_score(p, c, x, key)

        def sc(p, key):
            return impl.pretrain_score(p, c, x, key)

        objective = solver_mod.Objective(grad_and_score=gs, score=sc)
        new_p, scores = solver_mod.optimize(objective, self.params[i], c,
                                            self._next_key())
        params = list(self.params)
        params[i] = new_p
        self.params = tuple(params)
        dispatch_listeners(self.listeners, self, scores)

    def pretrain(self, data) -> None:
        """Layer-wise pretraining (MultiLayerNetwork.pretrain :149-190)."""
        for batch in _as_batches(data):
            x = jnp.asarray(batch[0] if isinstance(batch, tuple) else batch)
            for i in range(self.conf.n_layers - 1):
                c = self.conf.conf(i)
                if LayerType(str(c.layer_type)) not in _PRETRAINABLE:
                    continue
                acts = feed_forward(self.conf, self.params, x, up_to=i)
                layer_in = acts[-1] if acts else x
                layer_in = apply_preprocessor(self.conf.preprocessor(i), layer_in)
                self.pretrain_layer(i, layer_in)

    def finetune(self, x, labels) -> None:
        """Supervised end-to-end optimization (finetune/backprop parity)."""
        x, labels = jnp.asarray(x), jnp.asarray(labels)
        out_conf = self.conf.conf(self.conf.n_layers - 1)
        objective = self._finetune_objective(x, labels)
        self.params, scores = solver_mod.optimize(
            objective, self.params, out_conf, self._next_key())
        dispatch_listeners(self.listeners, self, scores)

    def fit(self, data, labels=None) -> None:
        """fit(DataSet/ndarray pair/iterator) — MultiLayerNetwork.fit parity."""
        if self.params is None:
            self.init()
        if labels is not None:
            batches = [(data, labels)]
        else:
            batches = _as_batches(data)
        x = None
        for batch in batches:
            x, y = batch if isinstance(batch, tuple) else (batch.features, batch.labels)
            if self.conf.pretrain:
                self.pretrain(jnp.asarray(x))
            if self.conf.backprop:
                self.finetune(x, y)
        if x is not None:
            self._refresh_batchnorm_stats(jnp.asarray(x))

    def _refresh_batchnorm_stats(self, x) -> None:
        """Recompute BATCH_NORM running (ema) stats from the last fit batch so
        inference (training=False) normalizes with data statistics rather
        than the init-time zeros/ones."""
        if not any(LayerType(str(c.layer_type)) == LayerType.BATCH_NORM
                   for c in self.conf.confs):
            return
        params = list(self.params)
        h = x
        for i, c in enumerate(self.conf.confs):
            h = apply_preprocessor(self.conf.preprocessor(i), h)
            if LayerType(str(c.layer_type)) == LayerType.BATCH_NORM:
                from deeplearning4j_tpu.nn.layers.base import BatchNormLayer
                axes = BatchNormLayer._feature_axes(h)
                p = dict(params[i])
                p["ema_mean"] = jnp.mean(h, axis=axes)
                p["ema_var"] = jnp.var(h, axis=axes)
                params[i] = p
            h = get_layer(c.layer_type).forward(params[i], c, h, None, False)
        self.params = tuple(params)

    # -- parameter vector (distributed/averaging contract) -----------------
    def params_flat(self) -> jnp.ndarray:
        """Flat parameter vector (parity: `MultiLayerNetwork.params()`)."""
        flat, _ = ravel_pytree(self.params)
        return flat

    def set_params_flat(self, flat) -> None:
        _, unravel = ravel_pytree(self.params)
        self.params = unravel(jnp.asarray(flat))

    def clone(self) -> "MultiLayerNetwork":
        net = MultiLayerNetwork(self.conf)
        net.params = self.params
        return net


def _as_batches(data):
    """Normalize fit() inputs: iterator of DataSets, single DataSet, array."""
    if hasattr(data, "features") and hasattr(data, "labels"):
        return [(data.features, data.labels)]
    if hasattr(data, "__next__") or hasattr(data, "reset"):
        return ((d.features, d.labels) for d in data)
    if isinstance(data, (list,)):
        return [(d.features, d.labels) if hasattr(d, "features") else d
                for d in data]
    return [data]
