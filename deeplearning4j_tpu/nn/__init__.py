"""nn — model core: configs, weight init, layers, the stacked network."""
