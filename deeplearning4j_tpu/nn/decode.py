"""Cache-aware autoregressive decoding over a stacked network.

The serving generation path (ISSUE 14) splits a generative forward into
two compiled programs instead of re-running the whole prefix every token:

  prefill      run the prompt once through the normal sequence forward,
               recording attention K/V rows into pre-allocated
               [B, max_S, n] caches (LSTM carries (h, c) the same way),
               and return the next-token log-probs at each row's last
               real prompt position.
  decode_step  advance every row by ONE token against the recorded
               state: attention scores are [B, H, max_S] — one
               sequence-scaled axis, never a materialized [S, S] — and
               the LSTM applies its per-step cell exactly as the eager
               `models/char_lstm.py` sampler does.

Both entries return `log(clip(probs, 1e-9, 1))` — byte-for-byte the
transform the eager sampler applies — so a greedy compiled decode
reproduces the eager token trajectory exactly in f32.  The compiled
wrappers (key schema, donation, sampling) live in
`optimize/infer_cache.py`; this module is pure layer math.

State layout: one dict per layer, in layer order, as a tuple —
  LSTM/GRAVES_LSTM  {"h": [B, H] f32, "c": [B, H] f32}
  ATTENTION         {"k": [B, max_S, n] compute_dtype, "v": same}
  everything else   {}
The tuple-of-dicts shape makes the whole state one donatable jit
argument whose leaves keep their shapes/dtypes across steps, so the
compiled step can alias its cache buffers in place.

Paged variant (ISSUE 16): `init_paged_state` replaces each ATTENTION
layer's dense [B, max_S, n] table with a shared physical page pool
  ATTENTION         {"k": [n_pages, page_size, n], "v": same}
addressed through a per-call `page_table` [B, pages_per_slot] int32 of
physical page ids — cache memory scales with LIVE pages, not
slots x max_seq.  `decode_step_paged` scatters the new K/V row at
(page_table[b, pos // page_size], pos % page_size) and gathers the
slot's pages back into one [B, pages_per_slot * page_size, n] view
before the same masked [B, H, ctx] score math as the dense step —
positions the slot has not written yet sit behind the additive mask, so
junk in unallocated pages is inert and the paged trajectory is
token-identical to the dense one.  The host (serving/batcher.py) owns
the free list and keeps physical page 0 as a scratch page every
inactive slot's table rows point at.

`verify_chunk` (speculative decoding) advances every row K tokens in
ONE program — the target-model verification step: token i of the chunk
attends causally at position pos + i against the cache, LSTM carries
step K times in-graph, and the returned [B, K, vocab] log-probs are
what greedy acceptance compares draft tokens against.  Rows re-walk a
mis-speculated suffix by simply rewriting those positions next call —
the cache never needs a rollback because `decode_step`/`verify_chunk`
always overwrite position `pos` before attending to it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import LayerType, MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import get_layer
from deeplearning4j_tpu.nn.layers.base import compute_dtype
from deeplearning4j_tpu.nn.layers.output import OutputLayer

#: hidden layer types the decode path knows how to step one token at a time
GENERATIVE_HIDDEN = (LayerType.LSTM, LayerType.GRAVES_LSTM,
                     LayerType.ATTENTION, LayerType.TRANSFORMER_FFN)

_RECURRENT = (LayerType.LSTM, LayerType.GRAVES_LSTM)

#: token emitted by `decode_block` for scan steps a row sat frozen
#: (its `rem` budget exhausted mid-block) — never a valid token id
BLOCK_SENTINEL = -1


def check_generative(conf: MultiLayerConfiguration):
    """Validate that `conf` is a decodable generative stack and return
    its layer types: optional leading EMBEDDING, then
    LSTM/GRAVES_LSTM/ATTENTION/TRANSFORMER_FFN hidden layers (causal
    attention only), then a final OUTPUT layer; the only preprocessor
    allowed is the trailing rnn_to_ff (which the per-token decode skips —
    its activations are already [B, n])."""
    n = conf.n_layers
    if n < 2:
        raise ValueError("generation needs at least one hidden layer "
                         "and an OUTPUT layer")
    types = [LayerType(str(conf.conf(i).layer_type)) for i in range(n)]
    if types[-1] != LayerType.OUTPUT:
        raise ValueError(f"last layer must be OUTPUT, got {types[-1]}")
    start = 1 if types[0] == LayerType.EMBEDDING else 0
    for i, t in enumerate(types[start:-1], start):
        if t not in GENERATIVE_HIDDEN:
            raise ValueError(
                f"layer {i} ({t}) has no single-token decode path; "
                f"generative stacks may use {[str(x) for x in GENERATIVE_HIDDEN]}")
        if t == LayerType.ATTENTION and not conf.conf(i).causal:
            raise ValueError(
                f"layer {i}: only causal attention can decode "
                f"autoregressively")
    for idx, name in conf.input_preprocessors:
        if not (idx == n - 1 and name == "rnn_to_ff"):
            raise ValueError(
                f"preprocessor {name!r} at layer {idx} is incompatible "
                f"with token decoding (only the trailing rnn_to_ff is)")
    return types


def positional_bound(conf: MultiLayerConfiguration) -> int:
    """Hard sequence-length ceiling imposed by a learned positional
    table, or 0 when the stack has none (one-hot / recurrent stacks
    decode unbounded).  `params[0]["P"][pos]` clamps silently under jit
    past this bound, so admission (serving/batcher.py) must enforce it
    on the host — `init_state` only covers the dense-table path."""
    types = check_generative(conf)
    if types[0] == LayerType.EMBEDDING:
        return int(conf.conf(0).max_seq_len or 0)
    return 0


def init_state(conf: MultiLayerConfiguration, batch: int, max_seq: int):
    """Fresh decode state for `batch` rows and a `max_seq`-token table."""
    types = check_generative(conf)
    if types[0] == LayerType.EMBEDDING:
        table = conf.conf(0).max_seq_len
        if table and max_seq > table:
            raise ValueError(
                f"max_seq={max_seq} exceeds the learned positional table "
                f"(max_seq_len={table})")
    state = []
    for i, t in enumerate(types):
        c = conf.conf(i)
        if t in _RECURRENT:
            # f32 like the eager sampler's zeros-init carries
            state.append({"h": jnp.zeros((batch, c.n_out), jnp.float32),
                          "c": jnp.zeros((batch, c.n_out), jnp.float32)})
        elif t == LayerType.ATTENTION:
            cd = compute_dtype(c)
            state.append({"k": jnp.zeros((batch, max_seq, c.n_in), cd),
                          "v": jnp.zeros((batch, max_seq, c.n_in), cd)})
        else:
            state.append({})
    return tuple(state)


def init_paged_state(conf: MultiLayerConfiguration, batch: int,
                     n_pages: int, page_size: int):
    """Fresh paged decode state: recurrent carries stay per-slot
    [batch, H], but each ATTENTION layer's K/V become one shared
    physical pool [n_pages, page_size, n] addressed through the
    per-call page table — memory scales with pages, not
    batch x max_seq."""
    types = check_generative(conf)
    state = []
    for i, t in enumerate(types):
        c = conf.conf(i)
        if t in _RECURRENT:
            state.append({"h": jnp.zeros((batch, c.n_out), jnp.float32),
                          "c": jnp.zeros((batch, c.n_out), jnp.float32)})
        elif t == LayerType.ATTENTION:
            cd = compute_dtype(c)
            state.append({"k": jnp.zeros((n_pages, page_size, c.n_in), cd),
                          "v": jnp.zeros((n_pages, page_size, c.n_in), cd)})
        else:
            state.append({})
    return tuple(state)


def token_embed(conf: MultiLayerConfiguration, params, tok, pos):
    """Embed one token id per row: EMBEDDING stacks gather W[tok]
    (+ P[pos] rowwise when a positional table exists — NOT
    EmbeddingLayer.forward, whose P[:s] convention would misread a [B]
    id vector as a length-B sequence); one-hot stacks build the same
    f32 rows the eager sampler feeds (`eye[cid]`)."""
    c0 = conf.conf(0)
    if LayerType(str(c0.layer_type)) == LayerType.EMBEDDING:
        e = params[0]["W"][tok]
        if "P" in params[0]:
            e = e + params[0]["P"][pos]
        return e
    return jax.nn.one_hot(tok, c0.n_in, dtype=jnp.float32)


def decode_step(conf: MultiLayerConfiguration, params, state, tok, pos):
    """Advance every row one token: tok [B] int32 (the row's current
    token), pos [B] int32 (the sequence position that token occupies).
    Returns (logp [B, vocab] — log(clip(probs)) for the NEXT token —
    and the updated state tuple)."""
    types = check_generative(conf)
    x = token_embed(conf, params, tok, pos)
    new_state = []
    for i, t in enumerate(types[:-1]):
        c = conf.conf(i)
        impl = get_layer(c.layer_type)
        if t in _RECURRENT:
            h, cc = impl.step(params[i], c, x, state[i]["h"], state[i]["c"])
            new_state.append({"h": h, "c": cc})
            x = h
        elif t == LayerType.ATTENTION:
            x, kc, vc = impl.decode_step(params[i], c, x, state[i]["k"],
                                         state[i]["v"], pos)
            new_state.append({"k": kc, "v": vc})
        elif t == LayerType.TRANSFORMER_FFN:
            x = impl.forward(params[i], c, x)
            new_state.append({})
        else:  # EMBEDDING — consumed by token_embed above
            new_state.append({})
    out_conf = conf.conf(len(types) - 1)
    probs = OutputLayer.forward(params[len(types) - 1], out_conf, x)
    new_state.append({})
    return jnp.log(jnp.clip(probs, 1e-9, 1.0)), tuple(new_state)


def decode_step_paged(conf: MultiLayerConfiguration, params, state, tok,
                      pos, page_table):
    """`decode_step` over paged ATTENTION state: page_table
    [B, pages_per_slot] int32 routes each row's cache reads/writes
    through the shared physical pool.  Token-identical to the dense
    step (see layers/attention.py:decode_step_paged)."""
    types = check_generative(conf)
    x = token_embed(conf, params, tok, pos)
    new_state = []
    for i, t in enumerate(types[:-1]):
        c = conf.conf(i)
        impl = get_layer(c.layer_type)
        if t in _RECURRENT:
            h, cc = impl.step(params[i], c, x, state[i]["h"], state[i]["c"])
            new_state.append({"h": h, "c": cc})
            x = h
        elif t == LayerType.ATTENTION:
            x, kc, vc = impl.decode_step_paged(
                params[i], c, x, state[i]["k"], state[i]["v"], pos,
                page_table)
            new_state.append({"k": kc, "v": vc})
        elif t == LayerType.TRANSFORMER_FFN:
            x = impl.forward(params[i], c, x)
            new_state.append({})
        else:  # EMBEDDING
            new_state.append({})
    out_conf = conf.conf(len(types) - 1)
    probs = OutputLayer.forward(params[len(types) - 1], out_conf, x)
    new_state.append({})
    return jnp.log(jnp.clip(probs, 1e-9, 1.0)), tuple(new_state)


def decode_block(conf: MultiLayerConfiguration, params, state, tok, pos,
                 keys, temps, rem, k: int, sample, page_table=None):
    """Fused multi-step decode (ISSUE 19): advance every row up to `k`
    tokens in ONE program — a `lax.scan` whose body is exactly
    `decode_step` (or `decode_step_paged` when `page_table` is given)
    followed by the injected `sample(logp, keys, temps) -> (tok, keys)`
    on-device sampler.  One host dispatch per K tokens instead of per
    token; the token trajectory is bitwise-identical to K sequential
    one-step calls for any K.

    rem [B] int32 is each row's remaining token budget.  A row whose
    budget hits 0 mid-block FREEZES: its tok/pos/key and recurrent
    carries stop advancing (cheap [B]-shaped `where`s — no full-cache
    select), and its scan outputs turn into `BLOCK_SENTINEL`.  Its K/V
    cache needs no mask at all: with tok and pos frozen, the step
    rewrites the SAME cache cell with bitwise-identical values
    (deterministic math over identical inputs), so "stops mutating"
    holds value-for-value, and for released paged rows the host's
    page table already points every write at the inert scratch page.

    The key-split discipline matches the one-step path exactly: the
    sampler runs over the full batch every scan step, but a frozen
    row's advanced key is discarded, so its key splits precisely once
    per token it actually emitted — the same count K=1 decoding burns.

    Returns (toks [k, B] int32 scan outputs, tok [B] (last real token
    per row), keys [B, 2], state) — state LAST, the donation/TP
    contract every decode-family program shares."""
    types = check_generative(conf)

    def body(carry, _):
        st, t, p, ks, r = carry
        active = r > 0
        if page_table is None:
            logp, st2 = decode_step(conf, params, st, t, p)
        else:
            logp, st2 = decode_step_paged(conf, params, st, t, p,
                                          page_table)
        t2, ks2 = sample(logp, ks, temps)
        frozen = []
        for i, lt in enumerate(types):
            if lt in _RECURRENT:
                frozen.append(
                    {"h": jnp.where(active[:, None], st2[i]["h"],
                                    st[i]["h"]),
                     "c": jnp.where(active[:, None], st2[i]["c"],
                                    st[i]["c"])})
            else:
                frozen.append(st2[i])
        out = jnp.where(active, t2, jnp.int32(BLOCK_SENTINEL))
        t3 = jnp.where(active, t2, t)
        ks3 = jnp.where(active[:, None], ks2, ks)
        p3 = jnp.where(active, p + 1, p)
        r3 = jnp.where(active, r - 1, r)
        return (tuple(frozen), t3, p3, ks3, r3), out

    carry, toks = jax.lax.scan(
        body, (state, tok, pos, keys, rem), xs=None, length=int(k))
    state, tok, _, keys, _ = carry
    return toks, tok, keys, state


def _verify_chunk_impl(conf, params, state, toks, pos, page_table):
    """Shared body of `verify_chunk` / `verify_chunk_paged`: advance
    every row K tokens in one pass and return per-position log-probs.

    toks [B, K] int32 — toks[:, 0] is the row's current token, the rest
    are draft continuations; pos [B] int32 is the position of
    toks[:, 0].  Returns (logp [B, K, vocab], new_state, carries):
    logp[:, i] is the next-token distribution AFTER consuming
    toks[:, :i+1], exactly what `decode_step` would return on the i-th
    of K sequential calls.  `carries` holds, per recurrent layer, the
    INTERMEDIATE carries {"h"/"c": [B, K, hidden]} after each of the K
    steps ({} for every other layer): attention state self-heals on
    mis-speculation (rejected positions are rewritten before they are
    read) but a recurrent carry does not, so the caller must roll the
    returned final state back to carry index e-1 when it accepts only
    e < K tokens.
    """
    types = check_generative(conf)
    b, kk = toks.shape
    idx = pos[:, None] + jnp.arange(kk)[None, :]
    x = token_embed(conf, params, toks, idx)  # [B, K, n]
    new_state = []
    carries = []
    for i, t in enumerate(types[:-1]):
        c = conf.conf(i)
        impl = get_layer(c.layer_type)
        if t in _RECURRENT:
            h, cc = state[i]["h"], state[i]["c"]
            outs, hs, cs = [], [], []
            for j in range(kk):  # K is small and static — unrolled
                h, cc = impl.step(params[i], c, x[:, j], h, cc)
                outs.append(h)
                hs.append(h)
                cs.append(cc)
            new_state.append({"h": h, "c": cc})
            carries.append({"h": jnp.stack(hs, axis=1),
                            "c": jnp.stack(cs, axis=1)})
            x = jnp.stack(outs, axis=1)
        elif t == LayerType.ATTENTION:
            if page_table is None:
                x, kc, vc = impl.verify_chunk(
                    params[i], c, x, state[i]["k"], state[i]["v"], pos)
            else:
                x, kc, vc = impl.verify_chunk_paged(
                    params[i], c, x, state[i]["k"], state[i]["v"], pos,
                    page_table)
            new_state.append({"k": kc, "v": vc})
            carries.append({})
        elif t == LayerType.TRANSFORMER_FFN:
            x = impl.forward(params[i], c, x)
            new_state.append({})
            carries.append({})
        else:  # EMBEDDING
            new_state.append({})
            carries.append({})
    out_conf = conf.conf(len(types) - 1)
    probs = OutputLayer.forward(params[len(types) - 1], out_conf,
                                x.reshape(b * kk, -1))
    probs = probs.reshape(b, kk, -1)
    new_state.append({})
    carries.append({})
    return (jnp.log(jnp.clip(probs, 1e-9, 1.0)), tuple(new_state),
            tuple(carries))


def verify_chunk(conf: MultiLayerConfiguration, params, state, toks, pos):
    """Speculative verification over dense decode state (see
    `_verify_chunk_impl`)."""
    return _verify_chunk_impl(conf, params, state, toks, pos, None)


def verify_chunk_paged(conf: MultiLayerConfiguration, params, state, toks,
                       pos, page_table):
    """Speculative verification over paged decode state (see
    `_verify_chunk_impl`)."""
    return _verify_chunk_impl(conf, params, state, toks, pos, page_table)


def prefill(conf: MultiLayerConfiguration, params, state, prompt, length):
    """Fill the decode state from a prompt bucket: prompt [B, T] int32
    (zero-padded past each row's true `length`), length [B] int32 >= 1.
    Returns (logp [B, vocab] at each row's LAST real prompt position —
    what the first generated token samples from — and the filled state).

    Padding is inert by construction: LSTM carries freeze at
    t >= length, attention's causal mask hides later positions from
    every real one, and `decode_step` overwrites cache position `pos`
    before attending to it."""
    types = check_generative(conf)
    c0 = conf.conf(0)
    if types[0] == LayerType.EMBEDDING:
        x = get_layer(c0.layer_type).forward(params[0], c0, prompt)
    else:
        x = jax.nn.one_hot(prompt, c0.n_in, dtype=jnp.float32)
    new_state = []
    for i, t in enumerate(types[:-1]):
        c = conf.conf(i)
        impl = get_layer(c.layer_type)
        if t in _RECURRENT:
            x, h, cc = impl.prefill(params[i], c, x, state[i]["h"],
                                    state[i]["c"], length)
            new_state.append({"h": h, "c": cc})
        elif t == LayerType.ATTENTION:
            x, kc, vc = impl.prefill(params[i], c, x, state[i]["k"],
                                     state[i]["v"])
            new_state.append({"k": kc, "v": vc})
        elif t == LayerType.TRANSFORMER_FFN:
            x = impl.forward(params[i], c, x)
            new_state.append({})
        else:  # EMBEDDING
            new_state.append({})
    b = prompt.shape[0]
    last = x[jnp.arange(b), length - 1]
    out_conf = conf.conf(len(types) - 1)
    probs = OutputLayer.forward(params[len(types) - 1], out_conf, last)
    new_state.append({})
    return jnp.log(jnp.clip(probs, 1e-9, 1.0)), tuple(new_state)
