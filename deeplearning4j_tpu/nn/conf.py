"""Configuration system — parity with reference `nn/conf/*`.

Reference: `NeuralNetConfiguration.java:52-115` (~40 per-layer hyperparameter
fields, fluent Builder at :880-1145, Jackson JSON serde at :809-878) and
`MultiLayerConfiguration.java:34-46` (layer list, `pretrain`, `backward`,
per-layer `ConfOverride` hooks at :235+, `InputPreProcessor` map).

TPU-native design: frozen dataclasses.  Frozen ⇒ hashable ⇒ usable as static
arguments to `jax.jit`; "builder" chaining is `dataclasses.replace`, and the
reference's `ConfOverride` per-layer hooks become `override(i, **kwargs)`.
JSON round-trip is capability parity with `toJson/fromJson`.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu.nd.losses import LossFunction
from deeplearning4j_tpu.nd.ops import Activation
from deeplearning4j_tpu.nn.weights import WeightInit


class OptimizationAlgorithm(str, enum.Enum):
    """Parity: `nn/api/OptimizationAlgorithm` + `Solver.java:54-70` dispatch."""

    GRADIENT_DESCENT = "gradient_descent"          # line-searched GD
    ITERATION_GRADIENT_DESCENT = "iteration_gradient_descent"  # plain SGD steps
    CONJUGATE_GRADIENT = "conjugate_gradient"
    LBFGS = "lbfgs"
    HESSIAN_FREE = "hessian_free"

    def __str__(self) -> str:
        return self.value


class LayerType(str, enum.Enum):
    DENSE = "dense"
    OUTPUT = "output"
    AUTOENCODER = "autoencoder"
    RBM = "rbm"
    RECURSIVE_AUTOENCODER = "recursive_autoencoder"
    LSTM = "lstm"
    GRAVES_LSTM = "graves_lstm"
    CONVOLUTION = "convolution"
    SUBSAMPLING = "subsampling"
    BATCH_NORM = "batch_norm"
    EMBEDDING = "embedding"
    ATTENTION = "attention"
    TRANSFORMER_FFN = "transformer_ffn"

    def __str__(self) -> str:
        return self.value


class RBMUnit(str, enum.Enum):
    """RBM visible/hidden unit types — parity: `RBM.java:83-89` (4 x 4)."""

    BINARY = "binary"
    GAUSSIAN = "gaussian"
    RECTIFIED = "rectified"
    SOFTMAX = "softmax"

    def __str__(self) -> str:
        return self.value


class PoolingType(str, enum.Enum):
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    NONE = "none"

    def __str__(self) -> str:
        return self.value


@dataclass(frozen=True)
class Distribution:
    """Weight-init distribution spec (parity: `nn/conf/distribution`)."""

    kind: str = "normal"  # normal | uniform | binomial
    mean: float = 0.0
    std: float = 1.0
    lo: float = -1.0
    hi: float = 1.0
    p: float = 0.5

    def sampler(self):
        from deeplearning4j_tpu.nd import random as ndr

        if self.kind == "normal":
            return lambda key, shape: ndr.normal(key, self.mean, self.std, shape)
        if self.kind == "uniform":
            return lambda key, shape: ndr.uniform(key, self.lo, self.hi, shape)
        if self.kind == "binomial":
            return lambda key, shape: ndr.binomial(key, self.p, shape)
        raise ValueError(f"unknown distribution kind {self.kind}")


@dataclass(frozen=True)
class NeuralNetConfiguration:
    """Per-layer hyperparameters (reference `NeuralNetConfiguration.java:52-115`)."""

    layer_type: LayerType = LayerType.DENSE
    n_in: int = 0
    n_out: int = 0

    activation: Activation = Activation.SIGMOID
    weight_init: WeightInit = WeightInit.VI
    dist: Optional[Distribution] = None
    loss_function: LossFunction = LossFunction.MCXENT

    # optimization
    optimization_algo: OptimizationAlgorithm = OptimizationAlgorithm.CONJUGATE_GRADIENT
    lr: float = 1e-1
    num_iterations: int = 100
    momentum: float = 0.5
    momentum_after: Tuple[Tuple[int, float], ...] = ()  # (iteration, momentum) schedule
    l1: float = 0.0
    l2: float = 0.0
    use_regularization: bool = False
    use_adagrad: bool = True
    adagrad_reset_iterations: int = 0  # 0 = never reset (ref: resetAdaGradIterations)
    constrain_gradient_to_unit_norm: bool = False
    gradient_clip_norm: float = 0.0  # 0 = off (new capability)
    minimize: bool = True
    step_function: str = "default"  # default | gradient | negative_default
                                    # | negative_gradient (stepfunctions/*)
    # pluggable termination conditions (ref optimize/terminations/*):
    # any of "eps" (EpsTermination), "norm2" (Norm2Termination),
    # "zero_direction" (ZeroDirection); empty tuple = run all iterations
    termination_conditions: Tuple[str, ...] = ("eps", "norm2")
    termination_eps: float = 1e-6
    termination_norm2: float = 1e-8
    # updater selection: "" = legacy chain (use_adagrad flag + momentum),
    # or one of sgd | adagrad | nesterov | adam | rmsprop (parity-plus:
    # the reference stops at AdaGrad/momentum, GradientAdjustment.java:159)
    updater: str = ""
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    rmsprop_decay: float = 0.95
    num_line_search_iterations: int = 20
    lbfgs_memory: int = 4          # two-loop history (LBFGS.java m=4)
    hf_cg_iterations: int = 32     # inner CG trip count (Martens HF)
    hf_initial_lambda: float = 1.0  # initial LM damping (HF)

    # stochastic regularization
    dropout: float = 0.0
    drop_connect: bool = False

    # pretrain-layer knobs
    corruption_level: float = 0.3   # denoising AE
    sparsity: float = 0.0
    k: int = 1                      # CD-k Gibbs steps (RBM.java:121-201)
    visible_unit: RBMUnit = RBMUnit.BINARY
    hidden_unit: RBMUnit = RBMUnit.BINARY

    # attention knobs (new scope — no attention in the 2015 reference)
    n_heads: int = 4
    causal: bool = False
    attention_block_size: int = 0  # 0 = full attention; >0 = blockwise/flash
    attention_impl: str = "auto"   # auto | full | blockwise | flash (pallas)
    # skip the mask arithmetic on fully-unmasked causal flash tiles (MFU
    # campaign leg d; value-identical, gated for A/B benching)
    attention_block_skip: bool = False
    ffn_hidden: int = 0            # transformer FFN width (0 = 4*n_in)
    max_seq_len: int = 0           # >0: learned positional embedding table
    lstm_impl: str = "auto"        # auto | scan | fused (pallas cell)

    # MFU campaign hot-path flags (each bitwise-f32-identical to the path
    # it replaces; parity-tested in tests/test_mfu_paths.py)
    sparse_labels: bool = False    # int class-id labels: gather mcxent, no
                                   # [rows, vocab] one-hot gemm
    fused_updater: bool = False    # flat-buffer updater step instead of
                                   # O(leaves) per-leaf tree_maps
    attention_fused_bwd: bool = False  # flash bwd via fused Pallas kernels
                                   # over saved logsumexp residuals (no
                                   # fwd recompute); only consulted when
                                   # the flash impl dispatches — training-
                                   # only, never an infer-cache key
                                   # (allclose, not bitwise, vs recompute)

    # batch-norm running-stat decay (ema = m*ema + (1-m)*batch)
    batch_norm_momentum: float = 0.9

    # conv knobs (NCHW)
    kernel_size: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    n_channels: int = 1
    pooling: PoolingType = PoolingType.MAX

    # misc
    batch_size: int = 0             # 0 = whatever the iterator yields
    seed: int = 123
    dtype: str = "float32"          # params (master-weight) dtype
    compute_dtype: str = ""         # matmul/conv operand dtype ("" = dtype);
                                    # "bfloat16" = mixed precision: bf16 MXU
                                    # inputs, f32 accumulation, f32 params
    remat: bool = False             # jax.checkpoint this layer's forward:
                                    # recompute activations in backward,
                                    # trading FLOPs for HBM (big batches)

    def replace(self, **kwargs) -> "NeuralNetConfiguration":
        return dataclasses.replace(self, **kwargs)

    # --- JSON serde (parity: toJson/fromJson, NeuralNetConfiguration.java:809-878)
    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        for k, v in d.items():
            if isinstance(v, enum.Enum):
                d[k] = v.value
        if d.get("dist") is not None and isinstance(self.dist, Distribution):
            d["dist"] = dataclasses.asdict(self.dist)
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NeuralNetConfiguration":
        d = dict(d)
        conv = {
            "layer_type": LayerType,
            "activation": Activation,
            "weight_init": WeightInit,
            "loss_function": LossFunction,
            "optimization_algo": OptimizationAlgorithm,
            "visible_unit": RBMUnit,
            "hidden_unit": RBMUnit,
            "pooling": PoolingType,
        }
        for k, e in conv.items():
            if k in d and d[k] is not None:
                d[k] = e(d[k])
        if d.get("dist") is not None:
            d["dist"] = Distribution(**d["dist"])
        for k in ("momentum_after",):
            if k in d and d[k] is not None:
                d[k] = tuple(tuple(x) for x in d[k])
        for k in ("kernel_size", "stride", "padding",
                  "termination_conditions"):
            if k in d and d[k] is not None:
                d[k] = tuple(d[k])
        known = {f.name for f in dataclasses.fields(cls)}
        d = {k: v for k, v in d.items() if k in known}
        return cls(**d)

    @classmethod
    def from_json(cls, s: str) -> "NeuralNetConfiguration":
        return cls.from_dict(json.loads(s))


@dataclass(frozen=True)
class MultiLayerConfiguration:
    """Stacked-network config (reference `MultiLayerConfiguration.java:34-46`).

    `confs` is one `NeuralNetConfiguration` per layer (the last is normally an
    OUTPUT layer).  `pretrain`/`backprop` gate the phases of
    `MultiLayerNetwork.fit` exactly as the reference's `pretrain`/`backward`
    flags do (`MultiLayerNetwork.java:928-992`).  `input_preprocessors` maps
    layer index -> preprocessor name (see nn/layers/preprocessor.py).
    """

    confs: Tuple[NeuralNetConfiguration, ...] = ()
    pretrain: bool = False
    backprop: bool = True
    use_drop_connect: bool = False
    damping_factor: float = 10.0
    input_preprocessors: Tuple[Tuple[int, str], ...] = ()

    @property
    def n_layers(self) -> int:
        return len(self.confs)

    def conf(self, i: int) -> NeuralNetConfiguration:
        return self.confs[i]

    def preprocessor(self, i: int) -> Optional[str]:
        for idx, name in self.input_preprocessors:
            if idx == i:
                return name
        return None

    def override(self, i: int, **kwargs) -> "MultiLayerConfiguration":
        """Per-layer override hook (parity: `ConfOverride`, builder :235+)."""
        confs = list(self.confs)
        confs[i] = confs[i].replace(**kwargs)
        return dataclasses.replace(self, confs=tuple(confs))

    def replace(self, **kwargs) -> "MultiLayerConfiguration":
        return dataclasses.replace(self, **kwargs)

    def with_compute_dtype(self, compute_dtype: str) -> "MultiLayerConfiguration":
        """Every layer's matmul/conv compute dtype flipped at once (the
        `layers.base.mixed_matmul` lever) — params/master dtype stays
        put.  The serve-precision policy and the mixed-precision bench
        both derive their bf16 confs through this."""
        return self.replace(confs=tuple(
            c.replace(compute_dtype=compute_dtype) for c in self.confs))

    def to_json(self) -> str:
        return json.dumps(
            {
                "confs": [c.to_dict() for c in self.confs],
                "pretrain": self.pretrain,
                "backprop": self.backprop,
                "use_drop_connect": self.use_drop_connect,
                "damping_factor": self.damping_factor,
                "input_preprocessors": [list(x) for x in self.input_preprocessors],
            },
            indent=2,
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        return cls(
            confs=tuple(NeuralNetConfiguration.from_dict(c) for c in d["confs"]),
            pretrain=d.get("pretrain", False),
            backprop=d.get("backprop", True),
            use_drop_connect=d.get("use_drop_connect", False),
            damping_factor=d.get("damping_factor", 10.0),
            input_preprocessors=tuple(
                (int(i), str(n)) for i, n in d.get("input_preprocessors", [])
            ),
        )


class ListBuilder:
    """Fluent multi-layer builder — parity with the reference's
    `new NeuralNetConfiguration.Builder()....list(n).override(...).build()`
    idiom (`MultiLayerConfiguration.Builder`, `MultiLayerTest.java:55-110`).
    """

    def __init__(self, base: NeuralNetConfiguration, n_layers: int):
        self._confs = [base] * n_layers
        self._pretrain = False
        self._backprop = True
        self._preprocessors: Dict[int, str] = {}

    def hidden_layer_sizes(self, sizes, n_in: int, n_out: int) -> "ListBuilder":
        """Set n_in/n_out per layer from input dim, hidden sizes, output dim."""
        dims = [n_in] + list(sizes) + [n_out]
        for i in range(len(self._confs)):
            self._confs[i] = self._confs[i].replace(
                n_in=dims[i], n_out=dims[i + 1]
            )
        return self

    def override(self, i: int, **kwargs) -> "ListBuilder":
        self._confs[i] = self._confs[i].replace(**kwargs)
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def backprop(self, flag: bool) -> "ListBuilder":
        self._backprop = flag
        return self

    def input_preprocessor(self, i: int, name: str) -> "ListBuilder":
        self._preprocessors[i] = name
        return self

    def build(self) -> MultiLayerConfiguration:
        return MultiLayerConfiguration(
            confs=tuple(self._confs),
            pretrain=self._pretrain,
            backprop=self._backprop,
            input_preprocessors=tuple(sorted(self._preprocessors.items())),
        )


def list_builder(base: NeuralNetConfiguration, n_layers: int) -> ListBuilder:
    return ListBuilder(base, n_layers)
