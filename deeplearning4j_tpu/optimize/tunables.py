"""Central registry of performance tunables + the TunedTable override layer.

Every hand-tuned constant that governs a hot path declares itself here:
name, owning subsystem, default value (exactly the constant the call site
used to hard-code), legal search space, and an analytic cost hint tied to
the flops/bytes model in `optimize/profiling.py`.  Call sites resolve
through :func:`resolve`, which consults the process-wide installed
:class:`TunedTable` first and falls back to the registry default — so with
no table installed behavior is byte-identical to the pre-registry code
(same programs, same cache keys, same disk artifacts; regression-pinned in
tests/test_tunables.py).

Tuned tables are produced by `optimize/tune.py` (the `cli tune`
subcommand), keyed per (conf fingerprint, device kind), and persisted in
the shared disk compile cache via the same `store_bytes`/`load_bytes`
payload path as int8 calibration artifacts — replicas and future sessions
inherit them at `set_compile_cache` time with ``fresh_tunes == 0``.  A
table tuned for a different device kind is never consulted; a corrupt
artifact checksum-evicts in the persist layer and the caller re-tunes.

This module imports only the stdlib and `reliability.faults` (cost hints
lazy-import profiling) so it is safe to import from the kernel layer.
"""
from __future__ import annotations

import json
import logging
import threading
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

log = logging.getLogger("deeplearning4j_tpu")

#: bump when the serialized table layout changes — old artifacts are then
#: simply never looked up (new key), not mis-parsed
SCHEMA_VERSION = 1


class Tunable(NamedTuple):
    """One registered tunable: its identity, default, and search space."""
    name: str          # dotted id, e.g. "batcher.target_rows"
    subsystem: str     # owning subsystem, for docs/reporting
    default: Any       # value call sites get with no table installed
    space: Tuple       # legal candidates (grid of values or of ladders)
    cost_hint: Optional[Callable]  # (value, **ctx) -> relative cost, or None
    doc: str


# Measured block-size table for the Pallas flash kernels, keyed by
# (seq, head_dim) -> (fwd_q, fwd_k, bwd_q, bwd_k).  Moved verbatim from
# nd/pallas_kernels.py (provenance: TPU v5 lite sweeps at BENCH_r02
# shapes); these are now the *defaults* the kernel layer resolves through
# the tuned-table override.
ATTENTION_BLOCK_TABLE = {
    (256, 32): (128, 128, 128, 128),
    (256, 64): (128, 128, 128, 128),
    (512, 64): (128, 256, 128, 128),
    (1024, 64): (128, 256, 128, 256),
    (1024, 128): (128, 256, 128, 128),
    (2048, 64): (256, 256, 128, 256),
    (2048, 128): (256, 256, 128, 128),
    (4096, 128): (256, 512, 128, 256),
}


def _attention_cost(value, seq: int = 1024, head_dim: int = 64, **_):
    """Analytic bytes moved by the flash kernel at (bq, bk) — the pruning
    signal: candidates >= 2x the incumbent's traffic are never compiled."""
    from deeplearning4j_tpu.optimize.profiling import attention_block_bytes
    bq, bk = value
    return attention_block_bytes(seq, head_dim, bq, bk)


REGISTRY: Dict[str, Tunable] = {}


def _register(name, subsystem, default, space, cost_hint, doc):
    REGISTRY[name] = Tunable(name, subsystem, default, tuple(space),
                             cost_hint, doc)


_register(
    "attention.block_fwd", "nd/pallas_kernels", None,
    ((128, 128), (128, 256), (256, 256), (256, 512)),
    _attention_cost,
    "Forward flash-attention (block_q, block_k); None -> the measured "
    "ATTENTION_BLOCK_TABLE row or the power-of-two heuristic. Qualified "
    "per '{seq}x{head_dim}'.")
_register(
    "attention.block_bwd", "nd/pallas_kernels", None,
    ((128, 128), (128, 256), (256, 256)),
    _attention_cost,
    "Backward flash-attention (block_q, block_k) — caps one notch lower "
    "(two [bq, bk] f32 intermediates live per tile). Qualified per "
    "'{seq}x{head_dim}'.")
_register(
    "infer.bucket_ladder", "optimize/infer_cache", (),
    ((8, 64, 256), (8, 32, 128, 512), (16, 64, 256, 1024)),
    None,
    "Row buckets pre-seeded into the infer cache's grow-on-demand list; "
    "() keeps pure grow-on-demand (today's behavior).")
_register(
    "batcher.target_rows", "serving/batcher", 256,
    (64, 128, 256, 512, 1024),
    None,
    "MicroBatcher coalescing target when no infer-cache bucket exists "
    "yet (was DEFAULT_TARGET_ROWS).")
_register(
    "batcher.max_delay_ms", "serving/batcher", 3.0,
    (0.5, 1.0, 2.0, 3.0, 5.0, 8.0),
    None,
    "MicroBatcher flush deadline: how long a partial batch waits for "
    "co-riders before dispatch.")
_register(
    "decode.slots", "serving/batcher", 4,
    (1, 2, 4, 8, 16),
    None,
    "ContinuousBatcher decode-table width (concurrent generation "
    "streams per step).")
_register(
    "decode.page_size", "serving/batcher", 0,
    (0, 8, 16, 32),
    None,
    "KV-cache page size in tokens; 0 = contiguous [slots, max_seq] "
    "table (today's default).")
_register(
    "decode.steps_per_dispatch", "serving/batcher", 1,
    (1, 2, 4, 8, 16),
    None,
    "Fused decode block size K: tokens generated per host dispatch "
    "(lax.scan over the decode step). 1 = one program per token "
    "(today's default); >1 amortizes the host loop over K tokens.")
_register(
    "data.prefetch_depth", "datasets/iterator", 2,
    (1, 2, 4, 8),
    None,
    "PrefetchIterator buffer depth (batches staged ahead of the "
    "training step).")


def decode_k_ladder(k_max: int) -> Tuple[int, ...]:
    """Ascending block sizes the adaptive-K decode loop may dispatch for
    a ceiling of `k_max`: every power of two below it, plus `k_max`
    itself.  Warmup compiles exactly this ladder, so a warmed batcher
    ramping 1 -> 2 -> 4 -> ... -> k_max never fresh-compiles."""
    k_max = max(1, int(k_max))
    ladder = []
    v = 1
    while v < k_max:
        ladder.append(v)
        v *= 2
    ladder.append(k_max)
    return tuple(ladder)


class TunedTable:
    """A set of tuned overrides for one (conf fingerprint, device kind).

    ``entries`` maps ``"tunable.name"`` or ``"tunable.name@qualifier"``
    (e.g. ``"attention.block_fwd@1024x64"``) to the winning value.  Only
    names present in :data:`REGISTRY` are ever resolved; unknown entries
    are carried but inert, so newer tables degrade gracefully on older
    code.
    """

    def __init__(self, entries: Optional[Dict[str, Any]] = None,
                 device_kind: str = "", fingerprint: str = "",
                 meta: Optional[dict] = None):
        self.entries = dict(entries or {})
        self.device_kind = device_kind
        self.fingerprint = fingerprint
        self.meta = dict(meta or {})

    # -- serialization ------------------------------------------------------
    def to_bytes(self) -> bytes:
        payload = {
            "schema": SCHEMA_VERSION,
            "device_kind": self.device_kind,
            "fingerprint": self.fingerprint,
            "entries": {k: v for k, v in sorted(self.entries.items())},
            "meta": self.meta,
        }
        return json.dumps(payload, sort_keys=True).encode("utf-8")

    @classmethod
    def from_bytes(cls, blob: bytes) -> "TunedTable":
        payload = json.loads(blob.decode("utf-8"))
        if payload.get("schema") != SCHEMA_VERSION:
            raise ValueError("tuned-table schema %r != %d"
                             % (payload.get("schema"), SCHEMA_VERSION))
        entries = {k: _tupled(v) for k, v in payload["entries"].items()}
        return cls(entries, payload.get("device_kind", ""),
                   payload.get("fingerprint", ""), payload.get("meta"))


def _tupled(v):
    """JSON round-trips tuples as lists; tuned values are tuples."""
    if isinstance(v, list):
        return tuple(_tupled(x) for x in v)
    return v


# -- process-wide active table ----------------------------------------------

_LOCK = threading.Lock()
_ACTIVE: Optional[TunedTable] = None
_SOURCE: str = ""
_FRESH_TUNES = 0
_LOAD_WARNED = False


def default(name: str):
    """The registry default for ``name`` (KeyError on unknown names)."""
    return REGISTRY[name].default


def resolve(name: str, qualifier: Optional[str] = None):
    """The effective value of a tunable: installed-table override
    (qualified entry first, then bare) falling back to the registry
    default.  No table or no entry ⇒ exactly the registry default, so
    call sites behave byte-identically to the pre-registry code."""
    tun = REGISTRY[name]
    with _LOCK:
        table = _ACTIVE
    if table is not None:
        if qualifier is not None:
            hit = table.entries.get("%s@%s" % (name, qualifier))
            if hit is not None:
                return hit
        hit = table.entries.get(name)
        if hit is not None:
            return hit
    return tun.default


def install(table: TunedTable, source: str = "manual") -> None:
    """Make ``table`` the process-wide override layer."""
    global _ACTIVE, _SOURCE
    with _LOCK:
        _ACTIVE = table
        _SOURCE = source


def active() -> Optional[TunedTable]:
    with _LOCK:
        return _ACTIVE


def clear() -> None:
    """Drop the installed table and reset counters (tests, detach)."""
    global _ACTIVE, _SOURCE, _FRESH_TUNES, _LOAD_WARNED
    with _LOCK:
        _ACTIVE = None
        _SOURCE = ""
        _FRESH_TUNES = 0
        _LOAD_WARNED = False


def note_fresh(n: int = 1) -> None:
    """Count tunables whose value was freshly searched (not inherited) in
    this process — warm inherit shows ``fresh_tunes == 0``."""
    global _FRESH_TUNES
    with _LOCK:
        _FRESH_TUNES += int(n)


def status() -> dict:
    """The observability block surfaced in warmup/serve/tune JSON,
    ``/v1/stats``, and the Prometheus families."""
    with _LOCK:
        table, source, fresh = _ACTIVE, _SOURCE, _FRESH_TUNES
    return {
        "tuned_tables": 0 if table is None else 1,
        "fresh_tunes": fresh,
        "entries": 0 if table is None else len(table.entries),
        "device_kind": "" if table is None else table.device_kind,
        "source": source,
    }


# -- persistence (disk compile cache payload path) ---------------------------

def table_key(fingerprint: str, device_kind: str) -> Tuple:
    """Disk-cache key for a tuned table — keyed like any other artifact
    (the store folds its platform fingerprint into the filename; device
    kind rides in the key too so a forged store dir still can't cross
    kinds)."""
    return ("tuned", fingerprint, device_kind, SCHEMA_VERSION)


def save_table(store, table: TunedTable) -> None:
    """Persist via the store's opaque-payload path (checksummed; corrupt
    artifacts evict on read and the caller re-tunes)."""
    store.store_bytes(table_key(table.fingerprint, table.device_kind),
                      table.to_bytes())


def load_table(store, fingerprint: str,
               device_kind: str) -> Optional[TunedTable]:
    """Load a tuned table, degrading to None (registry defaults) on any
    failure with one warning — serving never blocks on tuning."""
    global _LOAD_WARNED
    from deeplearning4j_tpu.reliability import faults
    try:
        faults.fire("tune.load")
        blob = store.load_bytes(table_key(fingerprint, device_kind))
        if blob is None:
            return None
        table = TunedTable.from_bytes(blob)
        if table.device_kind != device_kind:
            raise ValueError("tuned table is for device kind %r, not %r"
                             % (table.device_kind, device_kind))
        return table
    except Exception as e:  # noqa: BLE001 - degrade, never block serving
        with _LOCK:
            warned, _LOAD_WARNED = _LOAD_WARNED, True
        if not warned:
            log.warning("tuned-table load failed (%s: %s); using registry "
                        "defaults", type(e).__name__, e)
        return None


def load_and_install(store, fingerprint: str) -> Optional[TunedTable]:
    """The `set_compile_cache` hook: consult the store for a table tuned
    for *this* device kind and install it if found."""
    kind = store.platform.get("device_kind", "none")
    table = load_table(store, fingerprint, kind)
    if table is not None:
        install(table, source="disk")
    return table
