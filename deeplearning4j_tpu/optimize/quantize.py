"""Per-conf inference precision policy: f32 / bf16 / int8 serving.

Training stays bit-exact f32; *serving* is where the TPU paper's
arithmetic actually lives — the systolic MXU is an 8-bit design (Jouppi
et al., 2017), and quantized serving is the economics the Gemma-on-TPU
report works through.  This module is the policy layer ROADMAP item 3
names:

  "f32"   the default — nothing changes, outputs stay bitwise-identical
          to the pre-policy serve path (the f32 cache key is unchanged).
  "bf16"  cast-on-load: float params cast to bfloat16 ONCE on the host,
          every layer's matmul/conv compute dtype flipped to bf16 (the
          `mixed_matmul` lever in nn/layers/base.py), program output
          cast back to f32.  Halves weight memory/bandwidth.
  "int8"  weight-only per-channel symmetric quantization: W-leaves live
          in HBM as int8 + a per-channel f32 scale, the compiled program
          dequantizes to bf16 IN-GRAPH right before the matmul (the
          weight-streaming recipe: int8 over the wire, bf16 in the MXU),
          activations stay bf16/f32.  Scales are calibrated on a
          held-out batch by a small clip-ratio grid search minimizing
          output MSE against the f32 reference.

The policy is a cache-key *dimension* (see optimize/infer_cache.py): it
joins (entry, conf fingerprint, bucket, sharding) so f32/bf16/int8
programs coexist in memory and in the persist.py disk store, and
`quantize_artifact_key` names the quantized-weights blob persisted
alongside the exported StableHLO.

`error_budget_report()` is the eval harness: every zoo model under
every policy, asserted against the declared per-model budgets
(models/zoo.py `PRECISION_ERROR_BUDGETS`) — the speedup never ships
blind.
"""

from __future__ import annotations

import hashlib
import io
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: the serve-path precision policies, weakest-to-strongest compression
POLICIES = ("f32", "bf16", "int8")

#: clip ratios the int8 calibration grid tries (1.0 = pure abs-max)
CLIP_GRID = (1.0, 0.999, 0.995, 0.98)


def validate_policy(policy: str) -> str:
    if policy not in POLICIES:
        raise ValueError(f"unknown precision policy {policy!r} "
                         f"(choose one of {', '.join(POLICIES)})")
    return policy


def serve_conf(conf, policy: str):
    """The conf low-precision programs are built against: every layer's
    compute dtype flipped to bfloat16.  The ORIGINAL conf's fingerprint
    stays in the cache key — the policy tag is its own key dimension —
    so the derived conf never leaks into key identity."""
    if policy == "f32":
        return conf
    return conf.with_compute_dtype("bfloat16")


def _is_float(leaf) -> bool:
    return jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)


def cast_params_bf16(params) -> tuple:
    """Cast-on-load: every float leaf to bfloat16 (done ONCE on the
    host; the cast tree is then an ordinary jit argument)."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16) if _is_float(a) else a, params)


# -- int8 weight-only quantization ------------------------------------------

def _quantizable(name: str, leaf) -> bool:
    """Weight-only rule: leaves named W* with >= 2 dims — Dense/LSTM/
    Embedding/conv `W`, attention `Wqkv`/`Wo`, FFN `W1`/`W2`.  Biases,
    LN/BN vectors and the positional table `P` stay float."""
    return (name.startswith("W") and getattr(leaf, "ndim", 0) >= 2
            and _is_float(leaf))


def _channel_axis(w: np.ndarray) -> int:
    """Per-channel axis: output channels — axis 0 for 4-D conv kernels
    (OIHW layout), the last axis everywhere else (n_in, n_out)."""
    return 0 if w.ndim == 4 else w.ndim - 1


def _quantize_leaf(leaf, clip: float) -> Dict[str, jnp.ndarray]:
    w = np.asarray(leaf, np.float32)
    axis = _channel_axis(w)
    reduce_axes = tuple(a for a in range(w.ndim) if a != axis)
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = np.where(amax > 0.0, amax * clip / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {"q": jnp.asarray(q), "scale": jnp.asarray(scale)}


def quantize_params_int8(params, clip: float = 1.0) -> tuple:
    """Symmetric per-channel weight quantization at a fixed clip ratio:
    quantizable leaves become `{"q": int8, "scale": f32}` sub-dicts (an
    ordinary pytree — the cache and mesh placement machinery see
    nothing special), everything else passes through untouched."""
    out = []
    for layer in params:
        out.append({name: (_quantize_leaf(leaf, clip)
                           if _quantizable(name, leaf) else leaf)
                    for name, leaf in layer.items()})
    return tuple(out)


def runtime_params(params, policy: str):
    """Params as the compiled program consumes them.  f32 and bf16 pass
    through (bf16 leaves were cast once on the host); int8 sub-dicts are
    dequantized IN-GRAPH to bf16 — int8 is what crosses HBM, bf16 is
    what the MXU multiplies — and the remaining float leaves (biases,
    LN) join them in bf16 so the whole forward computes uniformly."""
    if policy != "int8":
        return params
    cd = jnp.bfloat16
    out = []
    for layer in params:
        d = {}
        for name, leaf in layer.items():
            if isinstance(leaf, dict) and "q" in leaf and "scale" in leaf:
                d[name] = leaf["q"].astype(cd) * leaf["scale"].astype(cd)
            elif _is_float(leaf):
                d[name] = leaf.astype(cd)
            else:
                d[name] = leaf
        out.append(d)
    return tuple(out)


def policy_output(conf, params, x, policy: str):
    """Eager (uncached) forward under `policy`, output cast back to
    f32.  `params` must already be policy-transformed for bf16/int8 —
    the calibration/eval reference path, deliberately bypassing the
    infer cache so measurement never pollutes it."""
    from deeplearning4j_tpu.nn.multilayer import network_output

    out = network_output(serve_conf(conf, policy),
                         runtime_params(params, policy), x,
                         key=None, training=False)
    return jnp.asarray(out, jnp.float32)


def calibrate_int8(conf, params, x,
                   clip_grid: Tuple[float, ...] = CLIP_GRID):
    """Grid-search the clip ratio on held-out batch `x`: quantize under
    each candidate, score output MSE against the f32 reference, keep
    the argmin.  Returns (qparams, calibration report)."""
    ref = np.asarray(policy_output(conf, params, x, "f32"))
    denom = float(np.mean(ref ** 2)) or 1.0
    best = None
    for clip in clip_grid:
        q = quantize_params_int8(params, clip)
        out = np.asarray(policy_output(conf, q, x, "int8"))
        mse = float(np.mean((out - ref) ** 2))
        if best is None or mse < best[1]:
            best = (q, mse, clip)
    qparams, mse, clip = best
    return qparams, {"clip": clip, "mse": mse, "rel_mse": mse / denom,
                     "calibration_rows": int(x.shape[0])}


# -- persistence --------------------------------------------------------------

def params_digest(params) -> str:
    """Content digest of a params tree (shapes, dtypes, bytes): ties a
    persisted quantized artifact to the exact weights it came from."""
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(params):
        a = np.asarray(leaf)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def quantize_artifact_key(conf_fingerprint: str, digest: str) -> tuple:
    """Disk-store key for a persisted int8 weight artifact — same
    keyspace as the exported-StableHLO entries, distinct leading tag."""
    return ("quantized-weights", "int8", conf_fingerprint, digest)


def pack_quantized(qparams, report: Optional[dict] = None) -> bytes:
    """Serialize a quantized params tree (+ its calibration report) to
    one npz blob for `PersistentProgramStore.store_bytes`."""
    arrays = {}
    for i, layer in enumerate(qparams):
        for name, leaf in layer.items():
            if isinstance(leaf, dict) and "q" in leaf and "scale" in leaf:
                arrays[f"{i}|{name}|q"] = np.asarray(leaf["q"])
                arrays[f"{i}|{name}|s"] = np.asarray(leaf["scale"])
            else:
                arrays[f"{i}|{name}|f"] = np.asarray(leaf)
    import json

    buf = io.BytesIO()
    np.savez(buf, n_layers=np.asarray(len(qparams), np.int64),
             report=np.frombuffer(
                 json.dumps(report or {}).encode(), np.uint8),
             **arrays)
    return buf.getvalue()


def unpack_quantized(blob: bytes):
    """Inverse of `pack_quantized`: (qparams tree, calibration report)."""
    import json

    with np.load(io.BytesIO(blob)) as z:
        n = int(z["n_layers"])
        report = json.loads(bytes(z["report"].tobytes()).decode() or "{}")
        layers = [dict() for _ in range(n)]
        for key in z.files:
            if "|" not in key:
                continue
            i, name, kind = key.split("|")
            d = layers[int(i)]
            if kind == "f":
                d[name] = jnp.asarray(z[key])
            else:
                slot = d.setdefault(name, {})
                slot["q" if kind == "q" else "scale"] = jnp.asarray(z[key])
    return tuple(layers), report


# -- calibration data + eval harness -----------------------------------------

def default_calibration(conf, rows: int = 32, seed: int = 0):
    """Deterministic held-out batch shaped for the conf's first layer:
    integer token ids for EMBEDDING stacks, [rows, T, n_in] for
    recurrent stacks, flat [rows, n_in] otherwise (a leading
    `ff_to_conv` preprocessor names the flat width for conv stacks)."""
    from deeplearning4j_tpu.nn.conf import LayerType

    rng = np.random.RandomState(seed)
    c0 = conf.conf(0)
    lt = LayerType(str(c0.layer_type))
    if lt == LayerType.EMBEDDING:
        seq = min(int(getattr(c0, "max_seq_len", 0) or 16), 32)
        return jnp.asarray(rng.randint(0, c0.n_in, size=(rows, seq)),
                           jnp.int32)
    if lt in (LayerType.LSTM, LayerType.GRAVES_LSTM):
        return jnp.asarray(rng.rand(rows, 8, c0.n_in), jnp.float32)
    n_in = int(c0.n_in)
    pre = dict(conf.input_preprocessors or ())
    spec = str(pre.get(0, ""))
    if spec.startswith("ff_to_conv"):
        dims = [int(d) for d in spec.split(":")[1:]]
        n_in = int(np.prod(dims)) if dims else n_in
    return jnp.asarray(rng.rand(rows, n_in), jnp.float32)


def accuracy_delta(conf, params, x, policy: str, qparams=None) -> dict:
    """Measured delta between a policy's outputs and the f32 reference
    on batch `x`: top-1 agreement (classifiers) plus (relative) MSE —
    reconstruction heads budget on rel_mse, softmax heads on
    top1_delta."""
    validate_policy(policy)
    ref = np.asarray(policy_output(conf, params, x, "f32"))
    if policy == "f32":
        out = ref
    elif policy == "bf16":
        out = np.asarray(
            policy_output(conf, cast_params_bf16(params), x, "bf16"))
    else:
        if qparams is None:
            qparams, _ = calibrate_int8(conf, params, x)
        out = np.asarray(policy_output(conf, qparams, x, "int8"))
    mse = float(np.mean((out - ref) ** 2))
    denom = float(np.mean(ref ** 2)) or 1.0
    agree = float(np.mean(out.argmax(-1) == ref.argmax(-1)))
    return {"policy": policy, "rows": int(x.shape[0]),
            "top1_agreement": agree, "top1_delta": round(1.0 - agree, 6),
            "mse": mse, "rel_mse": mse / denom,
            "max_abs_err": float(np.max(np.abs(out - ref)))}


def error_budget_report(small: bool = True, seed: int = 0,
                        policies: Tuple[str, ...] = ("bf16", "int8")) -> dict:
    """The eval harness: every zoo model in `precision_eval_confs`
    under every policy, measured against the declared per-model budgets
    (`zoo.PRECISION_ERROR_BUDGETS`).  Deterministic on CPU — seeded
    init, seeded data, eager forwards only (the infer cache is never
    touched)."""
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    report = {}
    for name, conf in zoo.precision_eval_confs(small=small).items():
        net = MultiLayerNetwork(conf, seed=seed).init()
        # calibration and eval batches are disjoint (held-out scales)
        x_eval = default_calibration(conf, rows=64 if small else 256,
                                     seed=seed + 1)
        budgets = zoo.PRECISION_ERROR_BUDGETS.get(name, {})
        entry = {}
        for policy in policies:
            qparams = None
            if policy == "int8":
                qparams, _ = calibrate_int8(
                    conf, net.params,
                    default_calibration(conf, rows=32, seed=seed + 2))
            delta = accuracy_delta(conf, net.params, x_eval, policy,
                                   qparams=qparams)
            budget = dict(budgets.get(policy, {}))
            delta["budget"] = budget
            delta["within_budget"] = all(delta[k] <= v
                                         for k, v in budget.items())
            entry[policy] = delta
        report[name] = entry
    return report


def assert_error_budgets(report: Optional[dict] = None) -> dict:
    """Raise if any model/policy pair exceeds its declared budget."""
    if report is None:
        report = error_budget_report()
    bad = []
    for model, entry in report.items():
        for policy, delta in entry.items():
            if not delta["within_budget"]:
                bad.append(f"{model}/{policy}: budget {delta['budget']} "
                           f"vs top1_delta={delta['top1_delta']:.4f} "
                           f"rel_mse={delta['rel_mse']:.3e}")
    if bad:
        raise AssertionError("precision error budget exceeded:\n"
                             + "\n".join(bad))
    return report
