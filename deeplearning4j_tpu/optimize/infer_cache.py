"""Serve-path AOT compile cache — inference compiles once, serves many.

PR 1 (`optimize/step_cache.py`) gave the *training* step compile-once
semantics, but the serve path still re-traced `network_output` /
`network_loss` on every `output()` / `score()` call and every shape —
exactly the per-call graph construction cost TensorFlow (Abadi et al.,
2016) and the TPU datacenter analysis (Jouppi et al., 2017) identify as
the dominant non-compute overhead of accelerator inference.

`InferCache` reuses the `CompiledProgramCache` machinery:

  key schema    (entry point in {output, loss, feed_forward},
                 conf fingerprint, arg shapes/dtypes, sharding tag)
                 -> AOT executable.
  batch args    (params, x[, y, w]) are explicit jit arguments — params
                 can keep training between serve calls without retraces.
  bucketing     ragged final batches zero-pad up to the smallest known
                 row bucket; `output`/`feed_forward` slice the pad rows
                 back off (inference is row-independent, so real rows
                 are bit-identical), and `loss` masks pad rows out of
                 the weighted mean via the same gemm-contraction form as
                 training (`dot(rows, w)` is bit-invariant to trailing
                 zero-weight rows) — padded evaluation matches unpadded
                 evaluation bit-for-bit in f32.
  mesh sharding `set_mesh(Mesh(('batch',)))` shards the padded batch's
                 rows across the mesh with params replicated (the GSPMD
                 pattern: jit inserts the collectives, the same code
                 runs on 1 chip or a pod).  The sharding is a KEY
                 dimension, so single-chip and mesh programs for the
                 same (entry, fingerprint, bucket) coexist in memory and
                 in the disk cache; buckets round up to a multiple of
                 the mesh size so every shard gets equal rows.  Row
                 independence makes mesh outputs bitwise-identical to
                 the single-chip program's.
  precision     `set_policy("bf16"|"int8")` (optimize/quantize.py) adds
                 a `("policy", name)` element to the key — f32 keys are
                 UNCHANGED (and so stay valid against pre-policy disk
                 stores and stay bitwise-identical in behavior), while
                 bf16/int8 programs coexist per policy in memory and on
                 disk, composing with the sharding tag.  bf16 params
                 are cast once on the host (memoized per tree); int8
                 serves the fixed quantized snapshot installed with the
                 policy and dequantizes to bf16 in-graph.
  no donation   unlike the train cache, inference programs NEVER donate
                 their params buffer: the same params serve every call.
  observability `cache.stats` (hits / misses / steps / compile seconds)
                 sits alongside the train cache's stats; the CLI
                 `test`/`predict` commands report it in their JSON.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.optimize.step_cache import (CompiledProgramCache,
                                                    arg_signature,
                                                    conf_fingerprint)


def pad_rows(x, bucket: int):
    """Zero-pad `x` with rows up to `bucket` (feature rows = axis 0)."""
    pad = bucket - x.shape[0]
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
    return x


def truncate_rows(arr, bucket: int, n: int):
    """Slice a program output back to the `n` real input rows.

    Activations may carry `bucket` rows or a whole multiple (B*T rows
    for sequence stages whose rnn_to_ff preprocessor flattened time into
    the batch); pad batch entries occupy the trailing block either way.
    Outputs whose leading dim is not tied to the batch pass through."""
    if getattr(arr, "ndim", 0) and arr.shape[0] and arr.shape[0] % bucket == 0:
        ratio = arr.shape[0] // bucket
        return arr[: n * ratio]
    return arr


class InferCache(CompiledProgramCache):
    """Keyed AOT-compile cache for the inference entry points."""

    kind = "infer-cache"

    #: key element for programs compiled without a mesh
    SINGLE = "single"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._mesh = None
        self._replicated = None       # params sharding under the mesh
        self._batch_sharding = None   # row sharding under the mesh
        # memoized replicated placement of the last-served params tree
        # (holds the original tree so identity can't be recycled)
        self._placed_params: Tuple = (None, None)
        # serve-precision policy (optimize/quantize.py): a cache-key
        # dimension, so per-policy programs coexist like mesh ones do
        self._policy = "f32"
        self._qparams = None          # int8: fixed quantized snapshot
        # memoized bf16 cast of the last-served params tree (same
        # identity discipline as _placed_params)
        self._policy_params: Tuple = (None, None)

    def _donate_argnums(self) -> Tuple[int, ...]:
        # serve-path params are reused by every subsequent call (and by
        # training) — donation would invalidate live buffers
        return ()

    def _fingerprint(self, conf) -> str:
        # attention_fused_bwd only changes the backward pass: serving
        # programs are gradient-free, so the flag is normalized out of the
        # inference fingerprint.  Flipping it for training therefore never
        # re-keys (or invalidates on-disk) serving programs — the training
        # step cache keeps the base fingerprint and re-keys as it should.
        with self._lock:
            fp = self._fingerprints.get(id(conf))
            if fp is None:
                norm = conf
                confs = getattr(conf, "confs", None)
                if confs and any(c.attention_fused_bwd for c in confs):
                    norm = conf.replace(confs=tuple(
                        c.replace(attention_fused_bwd=False)
                        for c in confs))
                elif getattr(conf, "attention_fused_bwd", False):
                    norm = conf.replace(attention_fused_bwd=False)
                fp = conf_fingerprint(norm)
                self._fingerprints[id(conf)] = fp
            return fp

    # -- mesh / plan ---------------------------------------------------------
    def set_mesh(self, mesh) -> None:
        """Shard every subsequent serve call's rows across `mesh`:
        `Mesh(('batch',))` keeps params replicated (`parallel.mesh.
        serve_mesh()` builds it), a 2-D `Mesh(('batch','model'))`
        additionally tensor-shards params — and decode state — per the
        plan's per-leaf specs; None reverts to single-chip programs.
        Already-compiled programs stay cached under their own sharding
        tag, so flipping back and forth never evicts or recompiles."""
        from deeplearning4j_tpu.parallel.mesh import infer_shardings

        with self._lock:
            self._mesh = mesh
            self._placed_params = (None, None)
            if mesh is None:
                self._replicated = self._batch_sharding = None
            else:
                self._replicated, self._batch_sharding = infer_shardings(mesh)

    @property
    def mesh(self):
        return self._mesh

    @property
    def plan(self):
        """The cache's current `ShardPlan` — derived from (mesh,
        policy) so there is exactly one source of truth.  Every cache
        key element (`sharding_tag`, policy suffix, decode tag) and
        every placement routes through it."""
        from deeplearning4j_tpu.parallel.plan import ShardPlan

        with self._lock:
            return ShardPlan(mesh=self._mesh, policy=self._policy)

    def set_plan(self, plan) -> None:
        """Install a `ShardPlan` wholesale: mesh and precision policy in
        one call.  int8 plans need the quantized tree installed first
        via `set_policy` (the plan carries the policy NAME, not the
        snapshot)."""
        if plan.policy != self._policy:
            self.set_policy(plan.policy,
                            qparams=self._qparams
                            if plan.policy == "int8" else None)
        self.set_mesh(plan.mesh)

    # -- precision policy ---------------------------------------------------
    def set_policy(self, policy: str, qparams=None) -> None:
        """Serve every subsequent call under `policy` ("f32" | "bf16" |
        "int8").  int8 needs the prepared quantized tree (quantization +
        calibration are the caller's job — `MultiLayerNetwork.
        set_serve_precision` owns that, including disk persistence).
        Like `set_mesh`, already-compiled programs stay cached under
        their own policy tag: flipping between policies re-hits, never
        evicts or recompiles."""
        from deeplearning4j_tpu.optimize.quantize import validate_policy

        validate_policy(policy)
        if policy == "int8" and qparams is None:
            raise ValueError("int8 policy needs the quantized params tree "
                             "(use MultiLayerNetwork.set_serve_precision)")
        with self._lock:
            self._policy = policy
            self._qparams = qparams if policy == "int8" else None
            self._policy_params = (None, None)
            self._placed_params = (None, None)

    @property
    def policy(self) -> str:
        return self._policy

    def _policy_suffix(self) -> Tuple:
        """Cache-key elements the policy contributes (the plan's
        `policy_suffix`).  f32 contributes NOTHING — its keys (and
        therefore its disk-store paths and its outputs) are
        byte-identical to the pre-policy serve path."""
        return self.plan.policy_suffix()

    def _serve_params(self, params):
        """The params tree the policy's programs take as argument: f32
        passes through; bf16 is a memoized cast-on-load of the incoming
        tree (tracks training — a new tree re-casts); int8 is the fixed
        snapshot `set_policy` installed (requantization is deliberate,
        never implicit)."""
        policy = self._policy
        if policy == "f32":
            return params
        if policy == "int8":
            return self._qparams
        with self._lock:
            held, cast = self._policy_params
        if held is not params:
            from deeplearning4j_tpu.optimize.quantize import cast_params_bf16

            cast = cast_params_bf16(params)
            with self._lock:
                self._policy_params = (params, cast)
        return cast

    def programs_summary(self):
        """Resident compiled programs as (entry, bucket, sharding,
        policy) rows — the `/v1/stats` `programs` block operators use to
        verify warmup coverage across every cache-key dimension."""
        with self._lock:
            keys = list(self._programs)
        rows = []
        for k in keys:
            entry, _, sig, tag = k[0], k[1], k[2], k[3]
            policy = k[4][1] if len(k) > 4 else "f32"
            bucket = int(sig[0][0][0]) if sig and sig[0] and sig[0][0] else 0
            sharding = (tag if isinstance(tag, str)
                        else "mesh:" + "x".join(str(d) for d in tag[2]))
            rows.append({"entry": entry, "bucket": bucket,
                         "sharding": sharding, "policy": policy})
        return sorted(rows, key=lambda r: (r["entry"], r["bucket"],
                                           r["sharding"], r["policy"]))

    def _mesh_rows(self) -> int:
        """Row-divisibility the current plan demands (1 = no mesh; 2-D
        meshes only need the BATCH axis to divide the rows)."""
        return self.plan.rows

    def sharding_tag(self):
        """The sharding dimension of the cache key (the plan's
        `sharding_tag`): 'single' or a (mesh, axis names, mesh shape)
        tuple.  Distinct tags can never alias — single-chip and mesh
        programs coexist."""
        return self.plan.sharding_tag()

    def _decode_tag(self):
        """Sharding key element for decode/prefill/verify entries (the
        plan's `decode_tag`): generation stays single-chip — and its
        keys stay byte-identical to pre-plan disk artifacts — unless
        the plan carries a `model` axis, which genuinely re-keys the
        programs (sharded KV tables, jit-inserted collectives)."""
        return self.plan.decode_tag()

    def _serve_bucket(self, n: int) -> int:
        """Bucket for `n` rows.  Under a mesh the bucket must divide
        evenly across the 'batch' axis, so pick the smallest known
        divisible bucket >= n, else grow a new one at the next multiple
        (single-chip buckets stay visible to mesh calls only when they
        happen to divide — no eviction, just separate buckets)."""
        m = self._mesh_rows()
        if m == 1:
            return self.bucket_rows(n)
        target = -(-n // m) * m
        with self._lock:
            for b in self._buckets:
                if b >= n and b % m == 0:
                    return b
            if not self._fixed_buckets:
                self._buckets.append(target)
                self._buckets.sort()
            return target

    def _shardings(self, sp, n_batch_args: int) -> Optional[Tuple]:
        """(params sharding(s), batch shardings...) under the mesh; None
        single-chip.  1-D meshes replicate params (one Sharding covers
        the whole subtree — the pre-plan placement, byte-identical
        keys); a `model` axis switches the params entry to the plan's
        per-leaf sharding tree."""
        if self._mesh is None:
            return None
        plan = self.plan
        if plan.has_model_axis:
            return ((plan.param_shardings(sp),)
                    + (plan.batch_sharding(),) * int(n_batch_args))
        from deeplearning4j_tpu.parallel.mesh import serve_placements

        return serve_placements(self._mesh, n_batch_args)

    def _place_params(self, params):
        """Mesh placement of the params tree, memoized per tree
        identity (serving reuses one tree for every request):
        replicated under a 1-D plan, per-leaf tensor-sharded under a
        `model` axis."""
        with self._lock:
            held, placed = self._placed_params
            if held is params:
                return placed
        plan = self.plan
        if plan.has_model_axis:
            placed = jax.tree_util.tree_map(
                jax.device_put, params, plan.param_shardings(params))
        else:
            placed = jax.device_put(params, self._replicated)
        with self._lock:
            self._placed_params = (params, placed)
        return placed

    def _place(self, params, *batch_args) -> Tuple:
        """Device placement for execution under the mesh: params per
        `_place_params`, batch args row-sharded."""
        if self._mesh is None:
            return (params,) + batch_args
        return (self._place_params(params),) + tuple(
            jax.device_put(a, self._batch_sharding) for a in batch_args)

    # -- entry points -------------------------------------------------------
    def output(self, conf, params, x, compile_only: bool = False):
        """`network_output` through the cache: returns the output
        activations for the `x.shape[0]` real rows.  compile_only=True
        (warmup) registers the bucket and compiles — or disk-restores —
        the program without executing it."""
        n = int(x.shape[0])
        bucket = self._serve_bucket(n)
        xp = pad_rows(x, bucket)
        policy, sp = self._policy, self._serve_params(params)
        key = ("output", self._fingerprint(conf), arg_signature(xp),
               self.sharding_tag()) + self._policy_suffix()
        fn = self._get(key, lambda: _output_program(conf, policy), (sp, xp),
                       shardings=self._shardings(sp, 1))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return truncate_rows(fn(*self._place(sp, xp)), bucket, n)

    def feed_forward(self, conf, params, x, compile_only: bool = False):
        """`feed_forward` through the cache: the per-layer activation
        list, each sliced back to the real rows."""
        n = int(x.shape[0])
        bucket = self._serve_bucket(n)
        xp = pad_rows(x, bucket)
        policy, sp = self._policy, self._serve_params(params)
        key = ("feed_forward", self._fingerprint(conf), arg_signature(xp),
               self.sharding_tag()) + self._policy_suffix()
        fn = self._get(key, lambda: _feed_forward_program(conf, policy),
                       (sp, xp), shardings=self._shardings(sp, 1))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return [truncate_rows(a, bucket, n)
                for a in fn(*self._place(sp, xp))]

    # -- autoregressive generation (ISSUE 14) --------------------------------
    def _decode_donate(self) -> Tuple[int, ...]:
        """Decode-entry donation: the state tuple (arg 1) is consumed
        every step — its K/V caches and LSTM carries keep their shapes
        and dtypes, so jit aliases them in place instead of allocating a
        fresh [B, max_S, n] table per token.  Params (arg 0) are NEVER
        donated (shared with every other serve call).  CPU skips
        donation like the train cache does (buffer donation is a no-op
        warning there)."""
        from deeplearning4j_tpu.nd.platform import default_backend

        return (1,) if default_backend() != "cpu" else ()

    def _decode_shardings(self, sp, state, n_rest: int) -> Optional[Tuple]:
        """Per-arg shardings for a decode-family program under a
        tensor-parallel plan: params and KV state per the plan's
        per-leaf specs, the small host args (tok/pos/keys/temps/
        page_table) replicated.  None without a `model` axis —
        generation stays a single-chip program exactly as before."""
        plan = self.plan
        if not plan.has_model_axis:
            return None
        rep = plan.replicated()
        return ((plan.param_shardings(sp), plan.state_shardings(state))
                + (rep,) * int(n_rest))

    def _decode_place(self, sp, state, *rest) -> Tuple:
        """Execution placement for a TP decode call: params memoized
        per-leaf, state leaves pinned to the plan's specs (a no-op for
        the steady-state loop — the program's output constraint keeps
        the donated state on-spec), host args replicated."""
        plan = self.plan
        if not plan.has_model_axis:
            return (sp, state) + rest
        rep = plan.replicated()
        state = jax.tree_util.tree_map(jax.device_put, state,
                                       plan.state_shardings(state))
        return (self._place_params(sp), state) + tuple(
            jax.device_put(a, rep) for a in rest)

    def _tp_build(self, build):
        """Wrap a decode-family program builder for a tensor-parallel
        plan: the returned program pins its (donated, state-last)
        output state to the plan's per-leaf specs with
        `with_sharding_constraint` INSIDE the traced function — so the
        compiled executable's output layout provably matches its input
        layout and the next step's call is a pure hit, never a
        reshard."""
        plan = self.plan
        if not plan.has_model_axis:
            return build
        mesh = plan.mesh

        def wrapped():
            base = build()

            def program(*args):
                out = base(*args)
                *rest, st = out
                st = jax.tree_util.tree_map(
                    lambda a, s: jax.lax.with_sharding_constraint(
                        a, jax.sharding.NamedSharding(mesh, s)),
                    st, plan.state_pspecs(st))
                return tuple(rest) + (st,)

            return program

        return wrapped

    def _place_decode_state(self, state):
        """Plan placement for a fresh decode state (no-op without a
        `model` axis)."""
        plan = self.plan
        if not plan.has_model_axis:
            return state
        return jax.tree_util.tree_map(jax.device_put, state,
                                      plan.state_shardings(state))

    def init_decode_state(self, conf, batch: int, max_seq: int):
        """Fresh decode state shaped for the active policy's programs,
        placed per the active plan (a `model` axis shards the K/V
        feature dims so the cache itself can exceed one chip's HBM)."""
        from deeplearning4j_tpu.nn import decode as decode_mod

        return self._place_decode_state(
            decode_mod.init_state(_policy_conf(conf, self._policy),
                                  batch, max_seq))

    def decode(self, conf, params, state, tok, pos, keys, temps,
               compile_only: bool = False):
        """One compiled KV-cache decode step over the whole slot table:
        tok/pos [B] int32, keys [B, 2] uint32 per-row PRNG keys, temps
        [B] f32 (<= 0 rows decode greedily).  Returns (next_tok [B]
        int32, advanced keys, new state); the state argument is donated
        off-CPU.  Under a 1-D (or no) mesh generation is single-chip and
        the key carries the SINGLE tag exactly as before; a plan with a
        `model` axis re-keys the program by its sharding tag and shards
        params + KV state per the plan."""
        policy, sp = self._policy, self._serve_params(params)
        key = ("decode", self._fingerprint(conf),
               arg_signature(tok, pos, keys, temps,
                             *jax.tree_util.tree_leaves(state)),
               self._decode_tag()) + self._policy_suffix()
        fn = self._get(key,
                       self._tp_build(lambda: _decode_program(conf, policy)),
                       (sp, state, tok, pos, keys, temps),
                       donate=self._decode_donate(),
                       shardings=self._decode_shardings(sp, state, 4))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._decode_place(sp, state, tok, pos, keys, temps))

    # -- paged decode + speculative verification (ISSUE 16) ------------------
    def init_paged_decode_state(self, conf, batch: int, n_pages: int,
                                page_size: int):
        """Fresh paged decode state (shared K/V page pool) shaped for
        the active policy's programs, placed per the active plan (the
        page pool's feature dim shards over a `model` axis by head)."""
        from deeplearning4j_tpu.nn import decode as decode_mod

        return self._place_decode_state(decode_mod.init_paged_state(
            _policy_conf(conf, self._policy), batch, n_pages, page_size))

    def decode_paged(self, conf, params, state, tok, pos, keys, temps,
                     page_table, compile_only: bool = False):
        """`decode` over the paged state: page_table [B, pages_per_slot]
        int32 is a tiny per-call host argument routing each row through
        the shared physical pool.  Same donation contract as `decode`
        (the pool is arg 1, donated off-CPU); its key entry is
        "decode-paged" so paged and dense programs coexist."""
        policy, sp = self._policy, self._serve_params(params)
        key = ("decode-paged", self._fingerprint(conf),
               arg_signature(tok, pos, keys, temps, page_table,
                             *jax.tree_util.tree_leaves(state)),
               self._decode_tag()) + self._policy_suffix()
        fn = self._get(
            key, self._tp_build(lambda: _decode_paged_program(conf, policy)),
            (sp, state, tok, pos, keys, temps, page_table),
            donate=self._decode_donate(),
            shardings=self._decode_shardings(sp, state, 5))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._decode_place(sp, state, tok, pos, keys, temps,
                                      page_table))

    def decode_multi(self, conf, params, state, tok, pos, keys, temps,
                     rem, k: int, compile_only: bool = False):
        """Fused K-step decode (ISSUE 19): ONE program advances every
        row up to `k` tokens — `lax.scan` over the decode step with
        in-program sampling, bitwise the trajectory `k` sequential
        `decode` calls produce.  rem [B] int32 is each row's remaining
        token budget; rows exhausting it mid-block freeze and emit
        `nn.decode.BLOCK_SENTINEL`.  Returns (toks [k, B] int32,
        tok_last [B], keys [B, 2], new state).  K is folded into the
        key's ENTRY name ("decode-multi[k]") so the (entry, sig, tag,
        policy) key layout every summary/audit consumer parses is
        unchanged.  Same donation/sharding contract as `decode`."""
        policy, sp = self._policy, self._serve_params(params)
        key = ("decode-multi[%d]" % int(k), self._fingerprint(conf),
               arg_signature(tok, pos, keys, temps, rem,
                             *jax.tree_util.tree_leaves(state)),
               self._decode_tag()) + self._policy_suffix()
        fn = self._get(
            key,
            self._tp_build(lambda: _decode_multi_program(conf, policy, k)),
            (sp, state, tok, pos, keys, temps, rem),
            donate=self._decode_donate(),
            shardings=self._decode_shardings(sp, state, 5))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._decode_place(sp, state, tok, pos, keys, temps,
                                      rem))

    def decode_multi_paged(self, conf, params, state, tok, pos, keys,
                           temps, rem, page_table, k: int,
                           compile_only: bool = False):
        """`decode_multi` over the paged state ("decode-multi-paged[k]"
        key entry): the page_table rides the whole block, so the host
        must have allocated pages for all `k` positions up front."""
        policy, sp = self._policy, self._serve_params(params)
        key = ("decode-multi-paged[%d]" % int(k), self._fingerprint(conf),
               arg_signature(tok, pos, keys, temps, rem, page_table,
                             *jax.tree_util.tree_leaves(state)),
               self._decode_tag()) + self._policy_suffix()
        fn = self._get(
            key,
            self._tp_build(
                lambda: _decode_multi_paged_program(conf, policy, k)),
            (sp, state, tok, pos, keys, temps, rem, page_table),
            donate=self._decode_donate(),
            shardings=self._decode_shardings(sp, state, 6))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._decode_place(sp, state, tok, pos, keys, temps,
                                      rem, page_table))

    def verify(self, conf, params, state, toks, pos, keys, temps,
               compile_only: bool = False):
        """Speculative verification step: toks [B, K] int32 (column 0 is
        each row's current token, columns 1..K-1 the draft
        continuations), pos [B] int32 the position of column 0.  One
        program advances every row K positions and chain-samples K
        tokens with the row's key stream — exactly the splits K
        sequential `decode` calls would burn — returning (sampled
        [B, K] int32, keys_after [B, K, 2] uint32 (the key state after
        accepting 1..K tokens), new state).  The host accepts the
        longest prefix where draft and sample agree; mis-speculated
        cache rows are rewritten by the next call before being read, so
        rollback is free."""
        policy, sp = self._policy, self._serve_params(params)
        key = ("verify", self._fingerprint(conf),
               arg_signature(toks, pos, keys, temps,
                             *jax.tree_util.tree_leaves(state)),
               self._decode_tag()) + self._policy_suffix()
        fn = self._get(key,
                       self._tp_build(lambda: _verify_program(conf, policy)),
                       (sp, state, toks, pos, keys, temps),
                       donate=self._decode_donate(),
                       shardings=self._decode_shardings(sp, state, 4))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._decode_place(sp, state, toks, pos, keys, temps))

    def verify_paged(self, conf, params, state, toks, pos, keys, temps,
                     page_table, compile_only: bool = False):
        """`verify` over the paged state ("verify-paged" key entry)."""
        policy, sp = self._policy, self._serve_params(params)
        key = ("verify-paged", self._fingerprint(conf),
               arg_signature(toks, pos, keys, temps, page_table,
                             *jax.tree_util.tree_leaves(state)),
               self._decode_tag()) + self._policy_suffix()
        fn = self._get(
            key, self._tp_build(lambda: _verify_paged_program(conf, policy)),
            (sp, state, toks, pos, keys, temps, page_table),
            donate=self._decode_donate(),
            shardings=self._decode_shardings(sp, state, 5))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._decode_place(sp, state, toks, pos, keys, temps,
                                      page_table))

    def prefill(self, conf, params, state, prompt, length, keys, temps,
                compile_only: bool = False):
        """Compiled prompt prefill: prompt [B, T_bucket] int32
        (zero-padded), length [B] int32.  Fills the decode state and
        samples each row's FIRST generated token (time-to-first-token is
        one program execution).  Same donation/key contract as
        `decode`; one program per (fingerprint, rows, prompt bucket,
        max_seq) via the state leaves in the signature."""
        policy, sp = self._policy, self._serve_params(params)
        key = ("prefill", self._fingerprint(conf),
               arg_signature(prompt, length, keys, temps,
                             *jax.tree_util.tree_leaves(state)),
               self._decode_tag()) + self._policy_suffix()
        fn = self._get(key,
                       self._tp_build(lambda: _prefill_program(conf, policy)),
                       (sp, state, prompt, length, keys, temps),
                       donate=self._decode_donate(),
                       shardings=self._decode_shardings(sp, state, 4))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._decode_place(sp, state, prompt, length, keys, temps))

    def prefill_logp(self, conf, params, state, prompt, length,
                     compile_only: bool = False):
        """Prefix-cacheable prompt prefill: fills the state exactly like
        `prefill` but returns (logp [B, vocab] f32, state) WITHOUT
        sampling — the serving layer caches the pair by prompt digest
        and samples each stream's first token on the host with the
        stream's own key (the eager sampler's discipline, which the
        compiled samplers reproduce exactly), so one cold prefill serves
        every later stream sharing the prompt regardless of key or
        temperature.  Only the prefix-cache flag routes admissions here;
        with the flag off this program is never built."""
        policy, sp = self._policy, self._serve_params(params)
        key = ("prefill-logp", self._fingerprint(conf),
               arg_signature(prompt, length,
                             *jax.tree_util.tree_leaves(state)),
               self._decode_tag()) + self._policy_suffix()
        fn = self._get(
            key, self._tp_build(lambda: _prefill_logp_program(conf, policy)),
            (sp, state, prompt, length),
            donate=self._decode_donate(),
            shardings=self._decode_shardings(sp, state, 2))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._decode_place(sp, state, prompt, length))

    def loss(self, conf, params, x, y, compile_only: bool = False):
        """`network_loss(training=False)` through the cache: the
        row-weighted mean loss over the real rows plus regularization.
        Pad rows carry weight 0 and the mean is a gemm contraction, so a
        bucket-padded tail scores bit-identically to the unpadded batch."""
        n = int(x.shape[0])
        bucket = self._serve_bucket(n)
        xp, yp, w = self.pad_batch(x, y, bucket)
        policy, sp = self._policy, self._serve_params(params)
        key = ("loss", self._fingerprint(conf), arg_signature(xp, yp, w),
               self.sharding_tag()) + self._policy_suffix()
        fn = self._get(key, lambda: _loss_program(conf, policy),
                       (sp, xp, yp, w), shardings=self._shardings(sp, 3))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._place(sp, xp, yp, w))


def _policy_conf(conf, policy: str):
    """The conf a policy's programs trace against (f32: the original —
    byte-for-byte the pre-policy program)."""
    if policy == "f32":
        return conf
    from deeplearning4j_tpu.optimize.quantize import serve_conf

    return serve_conf(conf, policy)


def _policy_args(params, policy: str):
    """In-graph view of the program's params argument: int8 sub-dicts
    dequantize to bf16 right here, inside the traced program."""
    if policy == "f32":
        return params
    from deeplearning4j_tpu.optimize.quantize import runtime_params

    return runtime_params(params, policy)


def _sample_tokens(logp, keys, temps):
    """On-device sampling with the eager sampler's exact PRNG
    discipline: every row splits its key once per step (`key, sub =
    split(key)`), rows with temperature <= 0 take argmax, the rest draw
    `categorical(sub, logp / temperature)`.  Returns (tok [B] int32,
    advanced keys [B, 2])."""
    ks = jax.vmap(jax.random.split)(keys)          # [B, 2, 2]
    new_keys, subs = ks[:, 0], ks[:, 1]
    greedy = jnp.argmax(logp, axis=-1).astype(jnp.int32)
    safe = jnp.where(temps > 0, temps, jnp.ones_like(temps))
    sampled = jax.vmap(jax.random.categorical)(
        subs, logp / safe[:, None]).astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy), new_keys


def _sample_chain(logp, keys, temps):
    """Chain-sample one token per chunk position: position i consumes
    logp[:, i] with the key state left by position i-1 — the identical
    split sequence K sequential `_sample_tokens` calls would produce, so
    an accepted chunk's tokens AND advanced keys match sequential decode
    exactly.  Returns (toks [B, K] int32, keys_after [B, K, 2])."""
    toks, keys_after = [], []
    for i in range(logp.shape[1]):
        t, keys = _sample_tokens(logp[:, i], keys, temps)
        toks.append(t)
        keys_after.append(keys)
    return jnp.stack(toks, axis=1), jnp.stack(keys_after, axis=1)


def _decode_paged_program(conf, policy: str = "f32") -> Callable:
    from deeplearning4j_tpu.nn import decode as decode_mod

    pconf = _policy_conf(conf, policy)

    def program(params, state, tok, pos, keys, temps, page_table):
        logp, state = decode_mod.decode_step_paged(
            pconf, _policy_args(params, policy), state, tok, pos,
            page_table)
        if policy != "f32":
            logp = logp.astype(jnp.float32)
        tok2, keys2 = _sample_tokens(logp, keys, temps)
        return tok2, keys2, state

    return program


def _accepted_len(toks, sampled):
    """Acceptance length per row, in-program: e = 1 + the number of
    leading draft proposals toks[:, 1:] that equal the target's own
    chain samples sampled[:, :-1] (the guaranteed first token plus the
    agreeing prefix).  Integer comparisons — bit-identical to the host
    loop the serving batcher runs on the fetched arrays."""
    b, kk = toks.shape
    if kk == 1:
        return jnp.ones((b,), jnp.int32)
    agree = (toks[:, 1:] == sampled[:, :-1]).astype(jnp.int32)
    return 1 + jnp.sum(jnp.cumprod(agree, axis=1), axis=1)


def _rollback_carries(state, carries, e):
    """Replace each recurrent layer's final carry in `state` with the
    intermediate carry after the e-th verified token (index e-1 of the
    [B, K, hidden] stacks): attention K/V self-heals on mis-speculation
    (rejected positions are overwritten before they are read) but a
    recurrent carry advanced past the accepted prefix would poison
    every later token."""
    rows = jnp.arange(e.shape[0])
    out = []
    for lay, car in zip(state, carries):
        if car:
            out.append({k: v[rows, e - 1] for k, v in car.items()})
        else:
            out.append(lay)
    return tuple(out)


def _verify_program(conf, policy: str = "f32") -> Callable:
    from deeplearning4j_tpu.nn import decode as decode_mod

    pconf = _policy_conf(conf, policy)

    def program(params, state, toks, pos, keys, temps):
        logp, state, carries = decode_mod.verify_chunk(
            pconf, _policy_args(params, policy), state, toks, pos)
        if policy != "f32":
            logp = logp.astype(jnp.float32)
        sampled, keys_after = _sample_chain(logp, keys, temps)
        state = _rollback_carries(state, carries,
                                  _accepted_len(toks, sampled))
        return sampled, keys_after, state

    return program


def _verify_paged_program(conf, policy: str = "f32") -> Callable:
    from deeplearning4j_tpu.nn import decode as decode_mod

    pconf = _policy_conf(conf, policy)

    def program(params, state, toks, pos, keys, temps, page_table):
        logp, state, carries = decode_mod.verify_chunk_paged(
            pconf, _policy_args(params, policy), state, toks, pos,
            page_table)
        if policy != "f32":
            logp = logp.astype(jnp.float32)
        sampled, keys_after = _sample_chain(logp, keys, temps)
        state = _rollback_carries(state, carries,
                                  _accepted_len(toks, sampled))
        return sampled, keys_after, state

    return program


def _decode_program(conf, policy: str = "f32") -> Callable:
    from deeplearning4j_tpu.nn import decode as decode_mod

    pconf = _policy_conf(conf, policy)

    def program(params, state, tok, pos, keys, temps):
        logp, state = decode_mod.decode_step(
            pconf, _policy_args(params, policy), state, tok, pos)
        if policy != "f32":
            logp = logp.astype(jnp.float32)
        tok2, keys2 = _sample_tokens(logp, keys, temps)
        return tok2, keys2, state

    return program


def _decode_multi_program(conf, policy: str = "f32", k: int = 1) -> Callable:
    from deeplearning4j_tpu.nn import decode as decode_mod

    pconf = _policy_conf(conf, policy)

    def sample(logp, keys, temps):
        if policy != "f32":
            logp = logp.astype(jnp.float32)
        return _sample_tokens(logp, keys, temps)

    def program(params, state, tok, pos, keys, temps, rem):
        return decode_mod.decode_block(
            pconf, _policy_args(params, policy), state, tok, pos, keys,
            temps, rem, k, sample)

    return program


def _decode_multi_paged_program(conf, policy: str = "f32",
                                k: int = 1) -> Callable:
    from deeplearning4j_tpu.nn import decode as decode_mod

    pconf = _policy_conf(conf, policy)

    def sample(logp, keys, temps):
        if policy != "f32":
            logp = logp.astype(jnp.float32)
        return _sample_tokens(logp, keys, temps)

    def program(params, state, tok, pos, keys, temps, rem, page_table):
        return decode_mod.decode_block(
            pconf, _policy_args(params, policy), state, tok, pos, keys,
            temps, rem, k, sample, page_table=page_table)

    return program


def _prefill_program(conf, policy: str = "f32") -> Callable:
    from deeplearning4j_tpu.nn import decode as decode_mod

    pconf = _policy_conf(conf, policy)

    def program(params, state, prompt, length, keys, temps):
        logp, state = decode_mod.prefill(
            pconf, _policy_args(params, policy), state, prompt, length)
        if policy != "f32":
            logp = logp.astype(jnp.float32)
        tok0, keys2 = _sample_tokens(logp, keys, temps)
        return tok0, keys2, state

    return program


def _prefill_logp_program(conf, policy: str = "f32") -> Callable:
    from deeplearning4j_tpu.nn import decode as decode_mod

    pconf = _policy_conf(conf, policy)

    def program(params, state, prompt, length):
        logp, state = decode_mod.prefill(
            pconf, _policy_args(params, policy), state, prompt, length)
        return logp.astype(jnp.float32), state

    return program


def _output_program(conf, policy: str = "f32") -> Callable:
    # local import: nn.multilayer imports this module at top level
    from deeplearning4j_tpu.nn.multilayer import network_output

    pconf = _policy_conf(conf, policy)

    def program(params, x):
        out = network_output(pconf, _policy_args(params, policy), x,
                             key=None, training=False)
        # low-precision programs hand back f32 so every caller — the
        # batcher, eval, bitwise tests — sees one output contract
        return out if policy == "f32" else out.astype(jnp.float32)

    return program


def _feed_forward_program(conf, policy: str = "f32") -> Callable:
    from deeplearning4j_tpu.nn.multilayer import feed_forward

    pconf = _policy_conf(conf, policy)

    def program(params, x):
        acts = feed_forward(pconf, _policy_args(params, policy), x,
                            key=None, training=False)
        if policy != "f32":
            acts = [a.astype(jnp.float32) for a in acts]
        return tuple(acts)

    return program


def _loss_program(conf, policy: str = "f32") -> Callable:
    from deeplearning4j_tpu.nn.multilayer import (network_regularization,
                                                  network_rowwise_loss)

    pconf = _policy_conf(conf, policy)

    def program(params, x, y, w):
        p = _policy_args(params, policy)
        rows = network_rowwise_loss(pconf, p, x, y, key=None,
                                    training=False)
        reg = network_regularization(pconf, p)
        if policy != "f32":
            rows, reg = rows.astype(jnp.float32), reg.astype(jnp.float32)
        # dot, not mean: bit-invariant to trailing zero-weight pad rows
        # (see make_finetune_loss / layers.base.rows_broadcast)
        return (jnp.dot(rows, w)
                / jnp.maximum(jnp.dot(w, jnp.ones_like(w)), 1.0)
                + reg)

    return program
