"""Serve-path AOT compile cache — inference compiles once, serves many.

PR 1 (`optimize/step_cache.py`) gave the *training* step compile-once
semantics, but the serve path still re-traced `network_output` /
`network_loss` on every `output()` / `score()` call and every shape —
exactly the per-call graph construction cost TensorFlow (Abadi et al.,
2016) and the TPU datacenter analysis (Jouppi et al., 2017) identify as
the dominant non-compute overhead of accelerator inference.

`InferCache` reuses the `CompiledProgramCache` machinery:

  key schema    (entry point in {output, loss, feed_forward},
                 conf fingerprint, arg shapes/dtypes) -> AOT executable.
  batch args    (params, x[, y, w]) are explicit jit arguments — params
                 can keep training between serve calls without retraces.
  bucketing     ragged final batches zero-pad up to the smallest known
                 row bucket; `output`/`feed_forward` slice the pad rows
                 back off (inference is row-independent, so real rows
                 are bit-identical), and `loss` masks pad rows out of
                 the weighted mean via the same gemm-contraction form as
                 training (`dot(rows, w)` is bit-invariant to trailing
                 zero-weight rows) — padded evaluation matches unpadded
                 evaluation bit-for-bit in f32.
  no donation   unlike the train cache, inference programs NEVER donate
                 their params buffer: the same params serve every call.
  observability `cache.stats` (hits / misses / steps / compile seconds)
                 sits alongside the train cache's stats; the CLI
                 `test`/`predict` commands report it in their JSON.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.optimize.step_cache import (CompiledProgramCache,
                                                    arg_signature)


def pad_rows(x, bucket: int):
    """Zero-pad `x` with rows up to `bucket` (feature rows = axis 0)."""
    pad = bucket - x.shape[0]
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
    return x


def truncate_rows(arr, bucket: int, n: int):
    """Slice a program output back to the `n` real input rows.

    Activations may carry `bucket` rows or a whole multiple (B*T rows
    for sequence stages whose rnn_to_ff preprocessor flattened time into
    the batch); pad batch entries occupy the trailing block either way.
    Outputs whose leading dim is not tied to the batch pass through."""
    if getattr(arr, "ndim", 0) and arr.shape[0] and arr.shape[0] % bucket == 0:
        ratio = arr.shape[0] // bucket
        return arr[: n * ratio]
    return arr


class InferCache(CompiledProgramCache):
    """Keyed AOT-compile cache for the inference entry points."""

    kind = "infer-cache"

    def _donate_argnums(self) -> Tuple[int, ...]:
        # serve-path params are reused by every subsequent call (and by
        # training) — donation would invalidate live buffers
        return ()

    # -- entry points -------------------------------------------------------
    def output(self, conf, params, x, compile_only: bool = False):
        """`network_output` through the cache: returns the output
        activations for the `x.shape[0]` real rows.  compile_only=True
        (warmup) registers the bucket and compiles — or disk-restores —
        the program without executing it."""
        n = int(x.shape[0])
        bucket = self.bucket_rows(n)
        xp = pad_rows(x, bucket)
        key = ("output", self._fingerprint(conf), arg_signature(xp))
        args = (params, xp)
        fn = self._get(key, lambda: _output_program(conf), args)
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return truncate_rows(fn(*args), bucket, n)

    def feed_forward(self, conf, params, x, compile_only: bool = False):
        """`feed_forward` through the cache: the per-layer activation
        list, each sliced back to the real rows."""
        n = int(x.shape[0])
        bucket = self.bucket_rows(n)
        xp = pad_rows(x, bucket)
        key = ("feed_forward", self._fingerprint(conf), arg_signature(xp))
        args = (params, xp)
        fn = self._get(key, lambda: _feed_forward_program(conf), args)
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return [truncate_rows(a, bucket, n) for a in fn(*args)]

    def loss(self, conf, params, x, y, compile_only: bool = False):
        """`network_loss(training=False)` through the cache: the
        row-weighted mean loss over the real rows plus regularization.
        Pad rows carry weight 0 and the mean is a gemm contraction, so a
        bucket-padded tail scores bit-identically to the unpadded batch."""
        n = int(x.shape[0])
        bucket = self.bucket_rows(n)
        xp, yp, w = self.pad_batch(x, y, bucket)
        key = ("loss", self._fingerprint(conf), arg_signature(xp, yp, w))
        args = (params, xp, yp, w)
        fn = self._get(key, lambda: _loss_program(conf), args)
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*args)


def _output_program(conf) -> Callable:
    # local import: nn.multilayer imports this module at top level
    from deeplearning4j_tpu.nn.multilayer import network_output

    def program(params, x):
        return network_output(conf, params, x, key=None, training=False)

    return program


def _feed_forward_program(conf) -> Callable:
    from deeplearning4j_tpu.nn.multilayer import feed_forward

    def program(params, x):
        return tuple(feed_forward(conf, params, x, key=None, training=False))

    return program


def _loss_program(conf) -> Callable:
    from deeplearning4j_tpu.nn.multilayer import (network_regularization,
                                                  network_rowwise_loss)

    def program(params, x, y, w):
        rows = network_rowwise_loss(conf, params, x, y, key=None,
                                    training=False)
        # dot, not mean: bit-invariant to trailing zero-weight pad rows
        # (see make_finetune_loss / layers.base.rows_broadcast)
        return (jnp.dot(rows, w)
                / jnp.maximum(jnp.dot(w, jnp.ones_like(w)), 1.0)
                + network_regularization(conf, params))

    return program
