"""Serve-path AOT compile cache — inference compiles once, serves many.

PR 1 (`optimize/step_cache.py`) gave the *training* step compile-once
semantics, but the serve path still re-traced `network_output` /
`network_loss` on every `output()` / `score()` call and every shape —
exactly the per-call graph construction cost TensorFlow (Abadi et al.,
2016) and the TPU datacenter analysis (Jouppi et al., 2017) identify as
the dominant non-compute overhead of accelerator inference.

`InferCache` reuses the `CompiledProgramCache` machinery:

  key schema    (entry point in {output, loss, feed_forward},
                 conf fingerprint, arg shapes/dtypes, sharding tag)
                 -> AOT executable.
  batch args    (params, x[, y, w]) are explicit jit arguments — params
                 can keep training between serve calls without retraces.
  bucketing     ragged final batches zero-pad up to the smallest known
                 row bucket; `output`/`feed_forward` slice the pad rows
                 back off (inference is row-independent, so real rows
                 are bit-identical), and `loss` masks pad rows out of
                 the weighted mean via the same gemm-contraction form as
                 training (`dot(rows, w)` is bit-invariant to trailing
                 zero-weight rows) — padded evaluation matches unpadded
                 evaluation bit-for-bit in f32.
  mesh sharding `set_mesh(Mesh(('batch',)))` shards the padded batch's
                 rows across the mesh with params replicated (the GSPMD
                 pattern: jit inserts the collectives, the same code
                 runs on 1 chip or a pod).  The sharding is a KEY
                 dimension, so single-chip and mesh programs for the
                 same (entry, fingerprint, bucket) coexist in memory and
                 in the disk cache; buckets round up to a multiple of
                 the mesh size so every shard gets equal rows.  Row
                 independence makes mesh outputs bitwise-identical to
                 the single-chip program's.
  no donation   unlike the train cache, inference programs NEVER donate
                 their params buffer: the same params serve every call.
  observability `cache.stats` (hits / misses / steps / compile seconds)
                 sits alongside the train cache's stats; the CLI
                 `test`/`predict` commands report it in their JSON.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.optimize.step_cache import (CompiledProgramCache,
                                                    arg_signature)


def pad_rows(x, bucket: int):
    """Zero-pad `x` with rows up to `bucket` (feature rows = axis 0)."""
    pad = bucket - x.shape[0]
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
    return x


def truncate_rows(arr, bucket: int, n: int):
    """Slice a program output back to the `n` real input rows.

    Activations may carry `bucket` rows or a whole multiple (B*T rows
    for sequence stages whose rnn_to_ff preprocessor flattened time into
    the batch); pad batch entries occupy the trailing block either way.
    Outputs whose leading dim is not tied to the batch pass through."""
    if getattr(arr, "ndim", 0) and arr.shape[0] and arr.shape[0] % bucket == 0:
        ratio = arr.shape[0] // bucket
        return arr[: n * ratio]
    return arr


class InferCache(CompiledProgramCache):
    """Keyed AOT-compile cache for the inference entry points."""

    kind = "infer-cache"

    #: key element for programs compiled without a mesh
    SINGLE = "single"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._mesh = None
        self._replicated = None       # params sharding under the mesh
        self._batch_sharding = None   # row sharding under the mesh
        # memoized replicated placement of the last-served params tree
        # (holds the original tree so identity can't be recycled)
        self._placed_params: Tuple = (None, None)

    def _donate_argnums(self) -> Tuple[int, ...]:
        # serve-path params are reused by every subsequent call (and by
        # training) — donation would invalidate live buffers
        return ()

    # -- mesh ----------------------------------------------------------------
    def set_mesh(self, mesh) -> None:
        """Shard every subsequent serve call's rows across `mesh`
        (`Mesh(('batch',))`, params replicated — `parallel.mesh.
        serve_mesh()` builds it); None reverts to single-chip programs.
        Already-compiled programs stay cached under their own sharding
        tag, so flipping back and forth never evicts or recompiles."""
        from deeplearning4j_tpu.parallel.mesh import infer_shardings

        with self._lock:
            self._mesh = mesh
            self._placed_params = (None, None)
            if mesh is None:
                self._replicated = self._batch_sharding = None
            else:
                self._replicated, self._batch_sharding = infer_shardings(mesh)

    @property
    def mesh(self):
        return self._mesh

    def _mesh_rows(self) -> int:
        """Row-divisibility the current sharding demands (1 = no mesh)."""
        return 1 if self._mesh is None else int(self._mesh.devices.size)

    def sharding_tag(self):
        """The sharding dimension of the cache key: 'single' or a
        (mesh, axis names, mesh shape) tuple.  Distinct tags can never
        alias — single-chip and mesh programs coexist."""
        if self._mesh is None:
            return self.SINGLE
        return ("mesh", tuple(self._mesh.axis_names),
                tuple(int(d) for d in self._mesh.devices.shape))

    def _serve_bucket(self, n: int) -> int:
        """Bucket for `n` rows.  Under a mesh the bucket must divide
        evenly across the 'batch' axis, so pick the smallest known
        divisible bucket >= n, else grow a new one at the next multiple
        (single-chip buckets stay visible to mesh calls only when they
        happen to divide — no eviction, just separate buckets)."""
        m = self._mesh_rows()
        if m == 1:
            return self.bucket_rows(n)
        target = -(-n // m) * m
        with self._lock:
            for b in self._buckets:
                if b >= n and b % m == 0:
                    return b
            if not self._fixed_buckets:
                self._buckets.append(target)
                self._buckets.sort()
            return target

    def _shardings(self, n_batch_args: int) -> Optional[Tuple]:
        """(params sharding, batch shardings...) under the mesh; None
        single-chip."""
        if self._mesh is None:
            return None
        return (self._replicated,) + (self._batch_sharding,) * n_batch_args

    def _place(self, params, *batch_args) -> Tuple:
        """Device placement for execution under the mesh: params
        replicated once per tree (memoized — serving reuses one tree for
        every request), batch args row-sharded."""
        if self._mesh is None:
            return (params,) + batch_args
        with self._lock:
            held, placed = self._placed_params
            if held is params:
                params_placed = placed
            else:
                params_placed = None
        if params_placed is None:
            params_placed = jax.device_put(params, self._replicated)
            with self._lock:
                self._placed_params = (params, params_placed)
        return (params_placed,) + tuple(
            jax.device_put(a, self._batch_sharding) for a in batch_args)

    # -- entry points -------------------------------------------------------
    def output(self, conf, params, x, compile_only: bool = False):
        """`network_output` through the cache: returns the output
        activations for the `x.shape[0]` real rows.  compile_only=True
        (warmup) registers the bucket and compiles — or disk-restores —
        the program without executing it."""
        n = int(x.shape[0])
        bucket = self._serve_bucket(n)
        xp = pad_rows(x, bucket)
        key = ("output", self._fingerprint(conf), arg_signature(xp),
               self.sharding_tag())
        fn = self._get(key, lambda: _output_program(conf), (params, xp),
                       shardings=self._shardings(1))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return truncate_rows(fn(*self._place(params, xp)), bucket, n)

    def feed_forward(self, conf, params, x, compile_only: bool = False):
        """`feed_forward` through the cache: the per-layer activation
        list, each sliced back to the real rows."""
        n = int(x.shape[0])
        bucket = self._serve_bucket(n)
        xp = pad_rows(x, bucket)
        key = ("feed_forward", self._fingerprint(conf), arg_signature(xp),
               self.sharding_tag())
        fn = self._get(key, lambda: _feed_forward_program(conf), (params, xp),
                       shardings=self._shardings(1))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return [truncate_rows(a, bucket, n)
                for a in fn(*self._place(params, xp))]

    def loss(self, conf, params, x, y, compile_only: bool = False):
        """`network_loss(training=False)` through the cache: the
        row-weighted mean loss over the real rows plus regularization.
        Pad rows carry weight 0 and the mean is a gemm contraction, so a
        bucket-padded tail scores bit-identically to the unpadded batch."""
        n = int(x.shape[0])
        bucket = self._serve_bucket(n)
        xp, yp, w = self.pad_batch(x, y, bucket)
        key = ("loss", self._fingerprint(conf), arg_signature(xp, yp, w),
               self.sharding_tag())
        fn = self._get(key, lambda: _loss_program(conf), (params, xp, yp, w),
                       shardings=self._shardings(3))
        if compile_only:
            return None
        with self._lock:
            self.stats.steps += 1
        return fn(*self._place(params, xp, yp, w))


def _output_program(conf) -> Callable:
    # local import: nn.multilayer imports this module at top level
    from deeplearning4j_tpu.nn.multilayer import network_output

    def program(params, x):
        return network_output(conf, params, x, key=None, training=False)

    return program


def _feed_forward_program(conf) -> Callable:
    from deeplearning4j_tpu.nn.multilayer import feed_forward

    def program(params, x):
        return tuple(feed_forward(conf, params, x, key=None, training=False))

    return program


def _loss_program(conf) -> Callable:
    from deeplearning4j_tpu.nn.multilayer import (network_regularization,
                                                  network_rowwise_loss)

    def program(params, x, y, w):
        rows = network_rowwise_loss(conf, params, x, y, key=None,
                                    training=False)
        # dot, not mean: bit-invariant to trailing zero-weight pad rows
        # (see make_finetune_loss / layers.base.rows_broadcast)
        return (jnp.dot(rows, w)
                / jnp.maximum(jnp.dot(w, jnp.ones_like(w)), 1.0)
                + network_regularization(conf, params))

    return program
