"""Iteration listeners + termination constants.

Parity: reference `optimize/api/IterationListener.java`,
`listeners/ScoreIterationListener.java:31-46` (print score every N
iterations), `optimize/terminations/*`.

Solvers run fully inside XLA, so listeners are invoked host-side over the
returned per-iteration score array after each `fit` — same observable
behavior (score every N iterations) without breaking compilation.
"""

from __future__ import annotations

import logging
from typing import Sequence

import numpy as np

log = logging.getLogger("deeplearning4j_tpu")


class IterationListener:
    def iteration_done(self, model, iteration: int, score: float) -> None:
        raise NotImplementedError


class ScoreIterationListener(IterationListener):
    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration, score):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration, score)


class CheckpointListener(IterationListener):
    """Persist the current model every N iterations (`ModelSavingActor`
    parity — it saved `stateTracker.getCurrent()` on every MoreWorkMessage;
    plus optimizer state + step, which the reference never checkpointed).

    Works with anything dispatch() hands it: a `DataParallelTrainer`
    (saves state.params + updater + step) or a `MultiLayerNetwork`
    (saves params + conf).  Writes are async by default, like the actor.
    """

    def __init__(self, directory: str, save_every_n: int = 10,
                 asynchronous: bool = True):
        self.directory = directory
        self.save_every_n = max(1, save_every_n)
        self.asynchronous = asynchronous
        self.saves = 0
        self._last_thread = None

    def iteration_done(self, model, iteration, score):
        if iteration % self.save_every_n != 0:
            return
        from deeplearning4j_tpu.parallel import checkpoint

        state = getattr(model, "state", None)
        net = getattr(model, "net", model)
        conf = getattr(net, "conf", None)
        meta = {"score": float(score)}
        if state is not None:
            args = (self.directory, state.params, state.updater)
            kw = dict(conf=conf, step=int(state.step), metadata=meta)
            mesh_meta = getattr(model, "mesh_meta", None)
            if callable(mesh_meta):
                # record the writing topology so a loader can detect an
                # elastic (N->M device) resume instead of guessing
                kw["mesh"] = mesh_meta()
        else:
            args = (self.directory, net.params, None)
            kw = dict(conf=conf, step=int(iteration), metadata=meta)
        if self.asynchronous:
            self._last_thread = checkpoint.save_async(*args, **kw)
        else:
            checkpoint.save(*args, **kw)
        self.saves += 1

    def wait(self) -> None:
        """Block until the last async save has landed."""
        if self._last_thread is not None:
            self._last_thread.join()


class ComposableIterationListener(IterationListener):
    def __init__(self, listeners: Sequence[IterationListener]):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration, score):
        for l in self.listeners:
            l.iteration_done(model, iteration, score)


def dispatch(listeners, model, scores) -> None:
    """Replay per-iteration scores from a finished solver run.

    Cost discipline: with no listeners attached this returns before
    touching the score array, so train steps stay fully async on the
    device; with listeners the whole trace crosses device->host in ONE
    `np.asarray` transfer, never one sync per iteration.

    Early-terminated runs are handled explicitly: the solvers carry a
    `done` flag and freeze the score once a termination condition trips,
    so the trace ends in a run of exactly-equal values.  Only the first
    element of such a trailing run (the real final iteration) is
    replayed — listeners don't see masked post-termination iterations as
    if they were live ones.  Non-finite scores are skipped (reference
    `ScoreIterationListener` contract).
    """
    if not listeners:
        return
    scores = np.asarray(scores)  # the single device->host transfer
    end = len(scores)
    if end > 1:
        last = scores[-1]
        i = end - 1
        while i > 0 and scores[i - 1] == last:  # nan-safe: nan != nan
            i -= 1
        if end - i >= 2:  # a run of >= 2 equal scores = frozen tail
            end = i + 1
    for i in range(end):
        s = scores[i]
        if not np.isfinite(s):
            continue
        for l in listeners:
            l.iteration_done(model, i, float(s))
