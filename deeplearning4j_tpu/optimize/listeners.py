"""Iteration listeners + termination constants.

Parity: reference `optimize/api/IterationListener.java`,
`listeners/ScoreIterationListener.java:31-46` (print score every N
iterations), `optimize/terminations/*`.

Solvers run fully inside XLA, so listeners are invoked host-side over the
returned per-iteration score array after each `fit` — same observable
behavior (score every N iterations) without breaking compilation.
"""

from __future__ import annotations

import logging
from typing import Sequence

log = logging.getLogger("deeplearning4j_tpu")


class IterationListener:
    def iteration_done(self, model, iteration: int, score: float) -> None:
        raise NotImplementedError


class ScoreIterationListener(IterationListener):
    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration, score):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration, score)


class ComposableIterationListener(IterationListener):
    def __init__(self, listeners: Sequence[IterationListener]):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration, score):
        for l in self.listeners:
            l.iteration_done(model, iteration, score)


def dispatch(listeners, model, scores) -> None:
    """Replay per-iteration scores from a finished solver run."""
    import numpy as np

    scores = np.asarray(scores)
    for i, s in enumerate(scores):
        if not np.isfinite(s):
            continue
        for l in listeners:
            l.iteration_done(model, i, float(s))
