"""Solver dispatch + the optimizer programs.

Parity: reference `optimize/Solver.java:54-70` (algorithm dispatch),
`BaseOptimizer.java:129-206` (iterate: gradientAndScore -> adjust -> line
search -> listeners -> termination), `ConjugateGradient.java:47-122`
(Polak-Ribiere), `LBFGS.java:152-266` (two-loop recursion, m=4),
`GradientAscent.java` (line-searched descent),
`IterationGradientDescent.java` (plain stepped descent), terminations
(`EpsTermination`/`Norm2Termination`/`ZeroDirection`), and
`StochasticHessianFree.java:44-262` (Martens HF: Gauss-Newton products via
the R-operator + damped inner CG — the reference pairs it with
`MultiLayerNetwork.computeDeltasR/feedForwardR` at
`MultiLayerNetwork.java:554-627,1407-1479`).

TPU-native design: each solver is ONE jit-compiled `lax.scan` over a fixed
iteration count with a carried `done` flag implementing the reference's
data-dependent termination conditions (XLA needs static trip counts; a
tripped termination masks further updates).  Flat-vector algebra via
`ravel_pytree`; inner Armijo line search via `linesearch.backtrack`.
Hessian-free replaces the reference's hand-written R-op machinery with
jvp-over-grad (exact HVP) or jvp->loss-Hessian->vjp (Gauss-Newton, when the
objective factors as predict+loss), plus Levenberg-Marquardt damping.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.nn.conf import OptimizationAlgorithm
from deeplearning4j_tpu.optimize.linesearch import backtrack
from deeplearning4j_tpu.optimize.updater import adjust_gradient, init_updater

EPS_TERMINATION = 1e-6   # |score - old_score| tolerance (EpsTermination parity)
NORM2_TERMINATION = 1e-8  # gradient-norm tolerance (Norm2Termination parity)


class Objective(NamedTuple):
    """What a solver optimizes — the `Model.gradientAndScore` contract.

    grad_and_score(params, key) -> (grads_pytree, scalar_score)
    score(params, key) -> scalar_score
    gnvp (optional): (params, v_pytree, key) -> pytree — Gauss-Newton
        curvature-vector product for Hessian-free; when absent HF uses the
        exact Hessian-vector product (jvp of the gradient).
    """

    grad_and_score: Callable
    score: Callable
    gnvp: Optional[Callable] = None


def from_loss(loss_fn: Callable) -> Objective:
    """Build an Objective from a pure loss `(params, key) -> scalar`."""

    def gs(params, key):
        s, g = jax.value_and_grad(loss_fn)(params, key)
        return g, s

    return Objective(grad_and_score=gs, score=loss_fn)


def from_predict_loss(predict: Callable, loss_of_out: Callable) -> Objective:
    """Objective from `predict(params, key) -> outputs` and
    `loss_of_out(outputs) -> scalar`, with a Gauss-Newton product
    G v = J^T (H_loss (J v)) — the TPU replacement for the reference's
    R-operator machinery (`StochasticHessianFree.java:89-262`)."""

    def loss_fn(params, key):
        return loss_of_out(predict(params, key))

    def gs(params, key):
        s, g = jax.value_and_grad(loss_fn)(params, key)
        return g, s

    def gnvp(params, v, key):
        z, jz = jax.jvp(lambda p: predict(p, key), (params,), (v,))
        hl_jz = jax.jvp(jax.grad(loss_of_out), (z,), (jz,))[1]
        return jax.vjp(lambda p: predict(p, key), params)[1](hl_jz)[0]

    return Objective(grad_and_score=gs, score=loss_fn, gnvp=gnvp)


def make_termination(conf):
    """Build the termination predicate from conf (pluggable parity with
    `optimize/terminations/*`: EpsTermination, Norm2Termination,
    ZeroDirection).  An empty `termination_conditions` tuple never
    terminates early (all iterations run)."""
    conds = tuple(getattr(conf, "termination_conditions", ("eps", "norm2"))
                  or ())
    eps = getattr(conf, "termination_eps", EPS_TERMINATION)
    n2 = getattr(conf, "termination_norm2", NORM2_TERMINATION)

    def terminated(score, old_score, gnorm, dnorm=None):
        done = jnp.asarray(False)
        if "eps" in conds:
            done = jnp.logical_or(done, jnp.abs(score - old_score) < eps)
        if "norm2" in conds:
            done = jnp.logical_or(done, gnorm < n2)
        if "zero_direction" in conds and dnorm is not None:
            done = jnp.logical_or(done, dnorm < 1e-12)
        return done

    return terminated


def apply_step(conf, x, d, alpha):
    """Pluggable step application (parity: `optimize/stepfunctions/*`) —
    default: x + alpha*d; gradient: x + d; negative variants flip the sign."""
    sf = (getattr(conf, "step_function", "default") or "default").lower()
    if sf == "gradient":
        return x + d
    if sf == "negative_gradient":
        return x - d
    if sf == "negative_default":
        return x - alpha * d
    return x + alpha * d


def _terminated(score, old_score, gnorm):
    """Module-default predicate (eps + norm2) — kept for callers without a
    conf in scope."""
    return jnp.logical_or(
        jnp.abs(score - old_score) < EPS_TERMINATION,
        gnorm < NORM2_TERMINATION,
    )


def _sgd(objective: Objective, params0, conf, key):
    """ITERATION_GRADIENT_DESCENT: updater-chain steps, no line search."""
    upd0 = init_updater(params0)
    terminated = make_termination(conf)

    def step(carry, it):
        params, upd, k, done, old_score = carry
        k, sub = jax.random.split(k)
        grads, score = objective.grad_and_score(params, sub)
        adj, upd_new = adjust_gradient(conf, it, grads, params, upd)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                             for x in jax.tree_util.tree_leaves(grads)))
        dnorm = jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                             for x in jax.tree_util.tree_leaves(adj)))
        # direction is -adj (a descent step), alpha fixed at 1 — the
        # configured step function still applies (stepfunctions parity)
        new_params = jax.tree_util.tree_map(
            lambda p, a: apply_step(conf, p, -a.astype(p.dtype), 1.0),
            params, adj)
        # masked update once terminated
        params = jax.tree_util.tree_map(
            lambda old, new: jnp.where(done, old, new), params, new_params)
        upd = jax.tree_util.tree_map(
            lambda old, new: jnp.where(done, old, new), upd, upd_new)
        done = jnp.logical_or(done, terminated(score, old_score, gnorm,
                                               dnorm))
        return (params, upd, k, done, score), score

    init = (params0, upd0, key, jnp.asarray(False), jnp.inf)
    (params, _, _, _, _), scores = jax.lax.scan(
        step, init, jnp.arange(conf.num_iterations))
    return params, scores


def _line_searched(objective: Objective, params0, conf, key, algo):
    """GD / CG / LBFGS over the flat parameter vector with Armijo search."""
    x0, unravel = ravel_pytree(params0)
    n = x0.shape[0]
    m = conf.lbfgs_memory

    def score_flat(x, k):
        return objective.score(unravel(x), k)

    def grad_flat(x, k):
        g, s = objective.grad_and_score(unravel(x), k)
        return ravel_pytree(g)[0], s

    is_cg = algo == OptimizationAlgorithm.CONJUGATE_GRADIENT
    is_lbfgs = algo == OptimizationAlgorithm.LBFGS
    terminated = make_termination(conf)

    def step(carry, it):
        (x, x_prev, g_prev, d_prev, s_hist, y_hist, hist_n, k, done,
         old_score, prev_alpha) = carry
        k, kg = jax.random.split(k)
        g, score = grad_flat(x, kg)
        gnorm = jnp.linalg.norm(g)

        if is_lbfgs:
            # push the completed curvature pair (s,y) = (x_t - x_{t-1},
            # g_t - g_{t-1}) before computing this iteration's direction
            s_vec = x - x_prev
            y_vec = g - g_prev
            have_pair = jnp.logical_and(it > 0, jnp.vdot(s_vec, y_vec) > 1e-10)
            s_hist = jnp.where(have_pair,
                               jnp.roll(s_hist, -1, axis=0).at[m - 1].set(s_vec),
                               s_hist)
            y_hist = jnp.where(have_pair,
                               jnp.roll(y_hist, -1, axis=0).at[m - 1].set(y_vec),
                               y_hist)
            hist_n = jnp.where(have_pair, jnp.minimum(hist_n + 1, m), hist_n)

        if is_cg:
            # Polak-Ribiere: beta = max(0, g.(g - g_prev) / g_prev.g_prev)
            denom = jnp.vdot(g_prev, g_prev)
            beta = jnp.where(denom > 0,
                             jnp.maximum(0.0, jnp.vdot(g, g - g_prev) / denom),
                             0.0)
            d = -g + beta * d_prev
            # restart on non-descent directions
            d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
        elif is_lbfgs:
            # two-loop recursion; valid pairs live at indices m-hist_n..m-1,
            # newest at m-1 (rolling append)
            q = g
            alphas = []
            for i in range(m - 1, -1, -1):  # newest -> oldest
                valid = i >= m - hist_n
                rho = jnp.where(valid,
                                1.0 / (jnp.vdot(y_hist[i], s_hist[i]) + 1e-10),
                                0.0)
                a_i = rho * jnp.vdot(s_hist[i], q)
                q = q - jnp.where(valid, a_i, 0.0) * y_hist[i]
                alphas.append((i, a_i, rho, valid))
            # initial Hessian scaling gamma = s.y / y.y of the newest pair
            sy = jnp.vdot(s_hist[m - 1], y_hist[m - 1])
            yy = jnp.vdot(y_hist[m - 1], y_hist[m - 1])
            gamma = jnp.where(jnp.logical_and(hist_n > 0, yy > 0), sy / yy, 1.0)
            r = gamma * q
            for i, a_i, rho, valid in reversed(alphas):  # oldest -> newest
                b_i = rho * jnp.vdot(y_hist[i], r)
                r = r + jnp.where(valid, a_i - b_i, 0.0) * s_hist[i]
            d = -r
            d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
        else:  # plain line-searched gradient descent
            d = -g

        # adaptive initial trial: grow from the last accepted step (the
        # reference's BaseOptimizer similarly carries `step` across
        # iterations) so flat regions don't pin progress to tiny steps
        # probes reuse kg: f0 and f(x + alpha*d) must see the SAME noise
        # realization (dropout mask / corruption) or Armijo compares noise,
        # not step quality, and stochastic objectives spuriously terminate
        trial = jnp.clip(prev_alpha * 2.0, 1e-3, 1e6)
        alpha, new_score = backtrack(
            lambda xx: score_flat(xx, kg), x, d, g, score,
            max_iters=conf.num_line_search_iterations,
            initial_step=trial)
        x_new = apply_step(conf, x, d, alpha)

        progressed = alpha > 0
        done_new = jnp.logical_or(
            done,
            jnp.logical_or(~progressed,
                           terminated(new_score, old_score, gnorm,
                                      jnp.linalg.norm(d))))

        x_prev_out = jnp.where(done, x_prev, x)
        x_out = jnp.where(done, x, x_new)
        g_prev = jnp.where(done, g_prev, g)
        d_prev = jnp.where(done, d_prev, d)
        out_score = jnp.where(done, old_score, new_score)
        prev_alpha = jnp.where(jnp.logical_or(done, alpha == 0.0),
                               prev_alpha, alpha)
        return (x_out, x_prev_out, g_prev, d_prev, s_hist, y_hist, hist_n, k,
                done_new, out_score, prev_alpha), out_score

    init = (x0, x0, jnp.zeros_like(x0), jnp.zeros_like(x0),
            jnp.zeros((m, n), x0.dtype), jnp.zeros((m, n), x0.dtype),
            jnp.asarray(0), key, jnp.asarray(False), jnp.inf,
            jnp.asarray(0.5, x0.dtype))
    (xf, *_), scores = jax.lax.scan(step, init, jnp.arange(conf.num_iterations))
    return unravel(xf), scores


def _hessian_free(objective: Objective, params0, conf, key):
    """Martens Hessian-free: damped inner CG on curvature-vector products.

    Parity: `StochasticHessianFree.java:44-262` — Gauss-Newton products
    (via `Objective.gnvp` when available, else exact HVP by jvp-over-grad),
    CG warm-started from the previous solution (decayed), and
    Levenberg-Marquardt lambda adaptation from the reduction ratio rho.
    """
    x0, unravel = ravel_pytree(params0)
    terminated = make_termination(conf)

    def grad_flat(x, k):
        g, s = objective.grad_and_score(unravel(x), k)
        return ravel_pytree(g)[0], s

    def score_flat(x, k):
        return objective.score(unravel(x), k)

    def bvp(x, v, lam, k):
        """Damped curvature-vector product (B + lam I) v."""
        if objective.gnvp is not None:
            hv = ravel_pytree(objective.gnvp(unravel(x), unravel(v), k))[0]
        else:
            hv = jax.jvp(lambda xx: grad_flat(xx, k)[0], (x,), (v,))[1]
        return hv + lam * v

    cg_iters = conf.hf_cg_iterations

    def cg_solve(x, g, lam, d0, k):
        """CG on (B + lam I) d = -g, warm start d0; fixed trip count with a
        converged mask (static shapes for XLA)."""

        def mv(v):
            return bvp(x, v, lam, k)

        r0 = -g - mv(d0)
        rs0 = jnp.vdot(r0, r0)

        def body(carry, _):
            d, r, p, rs = carry
            ap = mv(p)
            denom = jnp.vdot(p, ap)
            live = jnp.logical_and(rs > 1e-16, denom > 1e-20)
            alpha = jnp.where(live, rs / jnp.where(denom == 0, 1.0, denom), 0.0)
            d = d + alpha * p
            r = r - alpha * ap
            rs_new = jnp.vdot(r, r)
            beta = jnp.where(live, rs_new / jnp.where(rs == 0, 1.0, rs), 0.0)
            p = jnp.where(live, r + beta * p, p)
            return (d, r, p, jnp.where(live, rs_new, rs)), None

        (d, *_), _ = jax.lax.scan(body, (d0, r0, r0, rs0), None,
                                  length=cg_iters)
        return d

    def step(carry, it):
        x, d_prev, lam, k, done, old_score = carry
        k, kg = jax.random.split(k)
        g, score = grad_flat(x, kg)
        gnorm = jnp.linalg.norm(g)
        d = cg_solve(x, g, lam, 0.95 * d_prev, kg)
        # quadratic-model reduction for the LM rho test
        qm = jnp.vdot(g, d) + 0.5 * jnp.vdot(d, bvp(x, d, lam, kg))
        proposal = apply_step(conf, x, d, 1.0)  # stepfunctions parity
        new_score = score_flat(proposal, kg)
        rho = (new_score - score) / jnp.where(qm >= 0, -1e-10, qm)
        lam = jnp.where(rho > 0.75, lam * (2.0 / 3.0),
                        jnp.where(rho < 0.25, lam * 1.5, lam))
        accept = new_score < score
        x_new = jnp.where(jnp.logical_or(done, ~accept), x, proposal)
        d_prev = jnp.where(done, d_prev, d)
        # rejected iterations report the evaluated score at x (not
        # old_score, which starts at +inf and would leak into the trace)
        out_score = jnp.where(done, old_score,
                              jnp.where(accept, new_score, score))
        done = jnp.logical_or(done, terminated(new_score, old_score, gnorm,
                                               jnp.linalg.norm(d)))
        return (x_new, d_prev, lam, k, done, out_score), out_score

    init = (x0, jnp.zeros_like(x0), jnp.asarray(conf.hf_initial_lambda),
            key, jnp.asarray(False), jnp.inf)
    (xf, *_), scores = jax.lax.scan(step, init,
                                    jnp.arange(conf.num_iterations))
    return unravel(xf), scores


def optimize(objective: Objective, params0, conf, key):
    """Run the configured solver; returns (params, per-iteration scores).

    Dispatch parity: `Solver.java:54-70`.
    """
    algo = OptimizationAlgorithm(str(conf.optimization_algo))
    if algo == OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT:
        return _sgd(objective, params0, conf, key)
    if algo == OptimizationAlgorithm.HESSIAN_FREE:
        return _hessian_free(objective, params0, conf, key)
    return _line_searched(objective, params0, conf, key, algo)


class Solver:
    """OO facade mirroring the reference `Solver` builder usage."""

    def __init__(self, conf, objective: Objective):
        self.conf = conf
        self.objective = objective

    def optimize(self, params, key):
        return optimize(self.objective, params, self.conf, key)
