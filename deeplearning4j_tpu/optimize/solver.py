"""Solver dispatch + the optimizer programs.

Parity: reference `optimize/Solver.java:54-70` (algorithm dispatch),
`BaseOptimizer.java:129-206` (iterate: gradientAndScore -> adjust -> line
search -> listeners -> termination), `ConjugateGradient.java:47-122`
(Polak-Ribiere), `LBFGS.java:152-266` (two-loop recursion, m=4),
`GradientAscent.java` (line-searched descent),
`IterationGradientDescent.java` (plain stepped descent), terminations
(`EpsTermination`/`Norm2Termination`/`ZeroDirection`), and
`StochasticHessianFree.java:44-262` (Martens HF: Gauss-Newton products via
the R-operator + damped inner CG — the reference pairs it with
`MultiLayerNetwork.computeDeltasR/feedForwardR` at
`MultiLayerNetwork.java:554-627,1407-1479`).

TPU-native design: each solver is ONE jit-compiled `lax.scan` over a fixed
iteration count with a carried `done` flag implementing the reference's
data-dependent termination conditions (XLA needs static trip counts; a
tripped termination masks further updates).  Flat-vector algebra via
`ravel_pytree`; inner Armijo line search via `linesearch.backtrack`.
Hessian-free replaces the reference's hand-written R-op machinery with
jvp-over-grad (exact HVP) or jvp->loss-Hessian->vjp (Gauss-Newton, when the
objective factors as predict+loss), plus Levenberg-Marquardt damping.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.nn.conf import OptimizationAlgorithm
from deeplearning4j_tpu.optimize.linesearch import backtrack
from deeplearning4j_tpu.optimize.updater import (adjust_gradient,
                                                 adjust_gradient_flat,
                                                 flat_ravel, flat_unravel,
                                                 flat_norm, init_updater,
                                                 make_flat_spec, tree_norm)

EPS_TERMINATION = 1e-6   # |score - old_score| tolerance (EpsTermination parity)
NORM2_TERMINATION = 1e-8  # gradient-norm tolerance (Norm2Termination parity)
# consecutive sub-eps (or failed-line-search) iterations before terminating.
# An f32 score's ulp near a large loss value dwarfs EPS_TERMINATION, so a
# single exactly-equal score pair is a rounding coin-flip, not convergence —
# solvers crossing a flat valley would otherwise freeze or survive it
# depending on 1-ulp differences in how their loss happened to be lowered.
STALL_PATIENCE = 2


class Objective(NamedTuple):
    """What a solver optimizes — the `Model.gradientAndScore` contract.

    grad_and_score(params, key) -> (grads_pytree, scalar_score)
    score(params, key) -> scalar_score
    gnvp (optional): (params, v_pytree, key) -> pytree — Gauss-Newton
        curvature-vector product for Hessian-free; when absent HF uses the
        exact Hessian-vector product (jvp of the gradient).
    grad_score_aux (optional): (params, key) -> (grads, score, aux_pytree)
        — a side channel for byproducts of the gradient forward (e.g.
        BatchNorm batch moments) that the caller wants back without paying
        a second forward pass.  Solvers carry the aux of the LAST live
        iteration through their scan (frozen once terminated) and
        `optimize_with_aux` returns it alongside the result.
    """

    grad_and_score: Callable
    score: Callable
    gnvp: Optional[Callable] = None
    grad_score_aux: Optional[Callable] = None


class BatchedObjective(NamedTuple):
    """An Objective whose callables take the batch explicitly —
    `(params, x, y, key)` instead of closing over the batch arrays.

    This is the contract the compiled train-step cache
    (`optimize/step_cache.py`) needs: with (x, y) as jit ARGUMENTS the
    solver program compiles once per (conf, shapes) and is reused for
    every batch, instead of baking each batch in as constants and
    re-tracing the whole `lax.scan` per `fit` call.
    """

    grad_and_score: Callable                 # (params, x, y, key) -> (g, s)
    score: Callable                          # (params, x, y, key) -> s
    gnvp: Optional[Callable] = None          # (params, v, x, y, key) -> pytree
    grad_score_aux: Optional[Callable] = None  # (params, x, y, key) -> (g, s, aux)

    def bind(self, x, y) -> "Objective":
        """Close over one batch (concrete arrays or jit tracers)."""
        return Objective(
            grad_and_score=lambda p, k: self.grad_and_score(p, x, y, k),
            score=lambda p, k: self.score(p, x, y, k),
            gnvp=(None if self.gnvp is None
                  else lambda p, v, k: self.gnvp(p, v, x, y, k)),
            grad_score_aux=(None if self.grad_score_aux is None
                            else lambda p, k: self.grad_score_aux(p, x, y, k)))


def batched_from_loss(loss_fn: Callable) -> BatchedObjective:
    """BatchedObjective from a pure loss `(params, x, y, key) -> scalar`."""

    def gs(params, x, y, key):
        s, g = jax.value_and_grad(loss_fn)(params, x, y, key)
        return g, s

    return BatchedObjective(grad_and_score=gs, score=loss_fn)


def from_loss(loss_fn: Callable) -> Objective:
    """Build an Objective from a pure loss `(params, key) -> scalar`."""

    def gs(params, key):
        s, g = jax.value_and_grad(loss_fn)(params, key)
        return g, s

    return Objective(grad_and_score=gs, score=loss_fn)


def from_predict_loss(predict: Callable, loss_of_out: Callable) -> Objective:
    """Objective from `predict(params, key) -> outputs` and
    `loss_of_out(outputs) -> scalar`, with a Gauss-Newton product
    G v = J^T (H_loss (J v)) — the TPU replacement for the reference's
    R-operator machinery (`StochasticHessianFree.java:89-262`)."""

    def loss_fn(params, key):
        return loss_of_out(predict(params, key))

    def gs(params, key):
        s, g = jax.value_and_grad(loss_fn)(params, key)
        return g, s

    def gnvp(params, v, key):
        z, jz = jax.jvp(lambda p: predict(p, key), (params,), (v,))
        hl_jz = jax.jvp(jax.grad(loss_of_out), (z,), (jz,))[1]
        return jax.vjp(lambda p: predict(p, key), params)[1](hl_jz)[0]

    return Objective(grad_and_score=gs, score=loss_fn, gnvp=gnvp)


def weighted_predict_loss(predict, rowwise_loss: Callable, labels,
                          row_weights) -> Objective:
    """`from_predict_loss` with a pad-row weight mask threaded through the
    Gauss-Newton product (ROADMAP: cached Hessian-free).

    loss_of_out is the row-weighted mean of `rowwise_loss(labels, z)` as a
    gemm contraction (`dot(rows, w)`), the same bit-exact-under-padding
    form `make_finetune_loss` uses: a pad row's weight is exactly 0, so
    its contribution to the loss Hessian — and therefore to the curvature
    cotangent entering the predict vjp — is an exact float zero, and a
    zero-padded bucket batch produces the same Gauss-Newton products as
    the unpadded batch."""

    def loss_of_out(z):
        rows = rowwise_loss(labels, z)
        return jnp.dot(rows, row_weights) / jnp.maximum(
            jnp.dot(row_weights, jnp.ones_like(row_weights)), 1.0)

    return from_predict_loss(predict, loss_of_out)


def make_termination(conf):
    """Build the termination predicate from conf (pluggable parity with
    `optimize/terminations/*`: EpsTermination, Norm2Termination,
    ZeroDirection).  An empty `termination_conditions` tuple never
    terminates early (all iterations run)."""
    conds = tuple(getattr(conf, "termination_conditions", ("eps", "norm2"))
                  or ())
    eps = getattr(conf, "termination_eps", EPS_TERMINATION)
    n2 = getattr(conf, "termination_norm2", NORM2_TERMINATION)

    def terminated(score, old_score, gnorm, dnorm=None):
        """(stall, hard): `stall` is the eps plateau condition — callers
        terminate only after STALL_PATIENCE consecutive stalls; `hard`
        conditions (norm2 / zero_direction) terminate immediately."""
        stall = jnp.asarray(False)
        hard = jnp.asarray(False)
        if "eps" in conds:
            stall = jnp.logical_or(stall, jnp.abs(score - old_score) < eps)
        if "norm2" in conds:
            hard = jnp.logical_or(hard, gnorm < n2)
        if "zero_direction" in conds and dnorm is not None:
            hard = jnp.logical_or(hard, dnorm < 1e-12)
        return stall, hard

    return terminated


def apply_step(conf, x, d, alpha):
    """Pluggable step application (parity: `optimize/stepfunctions/*`) —
    default: x + alpha*d; gradient: x + d; negative variants flip the sign."""
    sf = (getattr(conf, "step_function", "default") or "default").lower()
    if sf == "gradient":
        return x + d
    if sf == "negative_gradient":
        return x - d
    if sf == "negative_default":
        return x - alpha * d
    return x + alpha * d


def _terminated(score, old_score, gnorm):
    """Module-default predicate (eps + norm2) — kept for callers without a
    conf in scope."""
    return jnp.logical_or(
        jnp.abs(score - old_score) < EPS_TERMINATION,
        gnorm < NORM2_TERMINATION,
    )


def _aux_zeros(objective: Objective, params0, key):
    """Initial aux carry: a zero pytree shaped like the objective's aux
    output (abstract eval only — no FLOPs spent)."""
    if objective.grad_score_aux is None:
        return ()
    shapes = jax.eval_shape(objective.grad_score_aux, params0, key)[2]
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def _grad_score_aux(objective: Objective, params, key):
    """(grads, score, aux) whichever channel the objective provides."""
    if objective.grad_score_aux is not None:
        return objective.grad_score_aux(params, key)
    g, s = objective.grad_and_score(params, key)
    return g, s, ()


def _sgd(objective: Objective, params0, conf, key):
    """ITERATION_GRADIENT_DESCENT: updater-chain steps, no line search.

    With `conf.fused_updater` the scan carries params/grads/updater state as
    a few contiguous same-dtype buffers (raveled once before the scan,
    unraveled once after — reshape/slice views, so jit-level donation is
    untouched): the whole updater chain plus the step application run as a
    handful of full-width kernels instead of O(leaves x ops) small ones.
    The gradient itself is still computed on the unraveled tree (same
    leaves, same shapes), and the norms reduce per original leaf, so every
    carried bit matches the tree path (see tests/test_mfu_paths.py).
    """
    fused = getattr(conf, "fused_updater", False)
    if fused:
        spec = make_flat_spec(params0)
        carry_p0 = flat_ravel(spec, params0)

        def to_tree(p):
            return flat_unravel(spec, p)

        def ravel_grads(g):
            return flat_ravel(spec, g)

        def norm(t):
            return flat_norm(spec, t)

        def adjust(it, g, p, u):
            return adjust_gradient_flat(conf, it, g, p, u, spec)
    else:
        carry_p0 = params0

        def to_tree(p):
            return p

        def ravel_grads(g):
            return g

        norm = tree_norm

        def adjust(it, g, p, u):
            return adjust_gradient(conf, it, g, p, u)

    # init_updater is tree_map(zeros_like): shapes the state like whatever
    # container the carry uses (leaf trees or flat buffer tuples)
    upd0 = init_updater(carry_p0)
    terminated = make_termination(conf)
    aux0 = _aux_zeros(objective, params0, key)

    def step(carry, it):
        params, upd, k, done, old_score, stall_n, aux = carry
        k, sub = jax.random.split(k)
        grads, score, aux_new = _grad_score_aux(objective, to_tree(params),
                                                sub)
        grads = ravel_grads(grads)
        adj, upd_new = adjust(it, grads, params, upd)
        gnorm = norm(grads)
        dnorm = norm(adj)
        # direction is -adj (a descent step), alpha fixed at 1 — the
        # configured step function still applies (stepfunctions parity)
        new_params = jax.tree_util.tree_map(
            lambda p, a: apply_step(conf, p, -a.astype(p.dtype), 1.0),
            params, adj)
        # masked update once terminated
        params = jax.tree_util.tree_map(
            lambda old, new: jnp.where(done, old, new), params, new_params)
        upd = jax.tree_util.tree_map(
            lambda old, new: jnp.where(done, old, new), upd, upd_new)
        aux = jax.tree_util.tree_map(
            lambda old, new: jnp.where(done, old, new), aux, aux_new)
        stall, hard = terminated(score, old_score, gnorm, dnorm)
        stall_n = jnp.where(done, stall_n,
                            jnp.where(stall, stall_n + 1, 0))
        done = jnp.logical_or(done, jnp.logical_or(
            hard, stall_n >= STALL_PATIENCE))
        return (params, upd, k, done, score, stall_n, aux), score

    init = (carry_p0, upd0, key, jnp.asarray(False), jnp.inf,
            jnp.asarray(0), aux0)
    (params, _, _, _, _, _, aux), scores = jax.lax.scan(
        step, init, jnp.arange(conf.num_iterations))
    return to_tree(params), scores, aux


def _line_searched(objective: Objective, params0, conf, key, algo):
    """GD / CG / LBFGS over the flat parameter vector with Armijo search."""
    x0, unravel = ravel_pytree(params0)
    n = x0.shape[0]
    m = conf.lbfgs_memory

    def score_flat(x, k):
        return objective.score(unravel(x), k)

    def grad_flat(x, k):
        g, s, aux = _grad_score_aux(objective, unravel(x), k)
        return ravel_pytree(g)[0], s, aux

    is_cg = algo == OptimizationAlgorithm.CONJUGATE_GRADIENT
    is_lbfgs = algo == OptimizationAlgorithm.LBFGS
    terminated = make_termination(conf)
    aux0 = _aux_zeros(objective, params0, key)

    def step(carry, it):
        (x, x_prev, g_prev, d_prev, s_hist, y_hist, hist_n, k, done,
         old_score, prev_alpha, stall_n, aux) = carry
        k, kg = jax.random.split(k)
        g, score, aux_new = grad_flat(x, kg)
        aux = jax.tree_util.tree_map(
            lambda old, new: jnp.where(done, old, new), aux, aux_new)
        gnorm = jnp.linalg.norm(g)

        if is_lbfgs:
            # push the completed curvature pair (s,y) = (x_t - x_{t-1},
            # g_t - g_{t-1}) before computing this iteration's direction
            s_vec = x - x_prev
            y_vec = g - g_prev
            have_pair = jnp.logical_and(it > 0, jnp.vdot(s_vec, y_vec) > 1e-10)
            s_hist = jnp.where(have_pair,
                               jnp.roll(s_hist, -1, axis=0).at[m - 1].set(s_vec),
                               s_hist)
            y_hist = jnp.where(have_pair,
                               jnp.roll(y_hist, -1, axis=0).at[m - 1].set(y_vec),
                               y_hist)
            hist_n = jnp.where(have_pair, jnp.minimum(hist_n + 1, m), hist_n)

        if is_cg:
            # Polak-Ribiere: beta = max(0, g.(g - g_prev) / g_prev.g_prev)
            denom = jnp.vdot(g_prev, g_prev)
            beta = jnp.where(denom > 0,
                             jnp.maximum(0.0, jnp.vdot(g, g - g_prev) / denom),
                             0.0)
            d = -g + beta * d_prev
            # restart on non-descent directions
            d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
        elif is_lbfgs:
            # two-loop recursion; valid pairs live at indices m-hist_n..m-1,
            # newest at m-1 (rolling append)
            q = g
            alphas = []
            for i in range(m - 1, -1, -1):  # newest -> oldest
                valid = i >= m - hist_n
                rho = jnp.where(valid,
                                1.0 / (jnp.vdot(y_hist[i], s_hist[i]) + 1e-10),
                                0.0)
                a_i = rho * jnp.vdot(s_hist[i], q)
                q = q - jnp.where(valid, a_i, 0.0) * y_hist[i]
                alphas.append((i, a_i, rho, valid))
            # initial Hessian scaling gamma = s.y / y.y of the newest pair
            sy = jnp.vdot(s_hist[m - 1], y_hist[m - 1])
            yy = jnp.vdot(y_hist[m - 1], y_hist[m - 1])
            gamma = jnp.where(jnp.logical_and(hist_n > 0, yy > 0), sy / yy, 1.0)
            r = gamma * q
            for i, a_i, rho, valid in reversed(alphas):  # oldest -> newest
                b_i = rho * jnp.vdot(y_hist[i], r)
                r = r + jnp.where(valid, a_i - b_i, 0.0) * s_hist[i]
            d = -r
            d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
        else:  # plain line-searched gradient descent
            d = -g

        # adaptive initial trial: grow from the last accepted step (the
        # reference's BaseOptimizer similarly carries `step` across
        # iterations) so flat regions don't pin progress to tiny steps
        # probes reuse kg: f0 and f(x + alpha*d) must see the SAME noise
        # realization (dropout mask / corruption) or Armijo compares noise,
        # not step quality, and stochastic objectives spuriously terminate
        trial = jnp.clip(prev_alpha * 2.0, 1e-3, 1e6)
        alpha, new_score = backtrack(
            lambda xx: score_flat(xx, kg), x, d, g, score,
            max_iters=conf.num_line_search_iterations,
            initial_step=trial)
        x_new = apply_step(conf, x, d, alpha)

        progressed = alpha > 0
        stall, hard = terminated(new_score, old_score, gnorm,
                                 jnp.linalg.norm(d))
        # a failed line search is a soft stall too: the next iteration
        # retries with a fresh direction (CG restarts to -g) before the
        # run is declared converged
        stall = jnp.logical_or(stall, ~progressed)
        stall_n = jnp.where(done, stall_n,
                            jnp.where(stall, stall_n + 1, 0))
        done_new = jnp.logical_or(done, jnp.logical_or(
            hard, stall_n >= STALL_PATIENCE))

        x_prev_out = jnp.where(done, x_prev, x)
        x_out = jnp.where(done, x, x_new)
        g_prev = jnp.where(done, g_prev, g)
        d_prev = jnp.where(done, d_prev, d)
        out_score = jnp.where(done, old_score, new_score)
        prev_alpha = jnp.where(jnp.logical_or(done, alpha == 0.0),
                               prev_alpha, alpha)
        return (x_out, x_prev_out, g_prev, d_prev, s_hist, y_hist, hist_n, k,
                done_new, out_score, prev_alpha, stall_n, aux), out_score

    init = (x0, x0, jnp.zeros_like(x0), jnp.zeros_like(x0),
            jnp.zeros((m, n), x0.dtype), jnp.zeros((m, n), x0.dtype),
            jnp.asarray(0), key, jnp.asarray(False), jnp.inf,
            jnp.asarray(0.5, x0.dtype), jnp.asarray(0), aux0)
    carry, scores = jax.lax.scan(step, init, jnp.arange(conf.num_iterations))
    return unravel(carry[0]), scores, carry[-1]


def _hessian_free(objective: Objective, params0, conf, key):
    """Martens Hessian-free: damped inner CG on curvature-vector products.

    Parity: `StochasticHessianFree.java:44-262` — Gauss-Newton products
    (via `Objective.gnvp` when available, else exact HVP by jvp-over-grad),
    CG warm-started from the previous solution (decayed), and
    Levenberg-Marquardt lambda adaptation from the reduction ratio rho.
    """
    x0, unravel = ravel_pytree(params0)
    terminated = make_termination(conf)
    aux0 = _aux_zeros(objective, params0, key)

    def grad_flat(x, k):
        g, s, _ = _grad_score_aux(objective, unravel(x), k)
        return ravel_pytree(g)[0], s

    def grad_flat_aux(x, k):
        g, s, aux = _grad_score_aux(objective, unravel(x), k)
        return ravel_pytree(g)[0], s, aux

    def score_flat(x, k):
        return objective.score(unravel(x), k)

    def bvp(x, v, lam, k):
        """Damped curvature-vector product (B + lam I) v."""
        if objective.gnvp is not None:
            hv = ravel_pytree(objective.gnvp(unravel(x), unravel(v), k))[0]
        else:
            hv = jax.jvp(lambda xx: grad_flat(xx, k)[0], (x,), (v,))[1]
        return hv + lam * v

    cg_iters = conf.hf_cg_iterations

    def cg_solve(x, g, lam, d0, k):
        """CG on (B + lam I) d = -g, warm start d0; fixed trip count with a
        converged mask (static shapes for XLA)."""

        def mv(v):
            return bvp(x, v, lam, k)

        r0 = -g - mv(d0)
        rs0 = jnp.vdot(r0, r0)

        def body(carry, _):
            d, r, p, rs = carry
            ap = mv(p)
            denom = jnp.vdot(p, ap)
            live = jnp.logical_and(rs > 1e-16, denom > 1e-20)
            alpha = jnp.where(live, rs / jnp.where(denom == 0, 1.0, denom), 0.0)
            d = d + alpha * p
            r = r - alpha * ap
            rs_new = jnp.vdot(r, r)
            beta = jnp.where(live, rs_new / jnp.where(rs == 0, 1.0, rs), 0.0)
            p = jnp.where(live, r + beta * p, p)
            return (d, r, p, jnp.where(live, rs_new, rs)), None

        (d, *_), _ = jax.lax.scan(body, (d0, r0, r0, rs0), None,
                                  length=cg_iters)
        return d

    def step(carry, it):
        x, d_prev, lam, k, done, old_score, stall_n, aux = carry
        k, kg = jax.random.split(k)
        g, score, aux_new = grad_flat_aux(x, kg)
        aux = jax.tree_util.tree_map(
            lambda old, new: jnp.where(done, old, new), aux, aux_new)
        gnorm = jnp.linalg.norm(g)
        d = cg_solve(x, g, lam, 0.95 * d_prev, kg)
        # quadratic-model reduction for the LM rho test
        qm = jnp.vdot(g, d) + 0.5 * jnp.vdot(d, bvp(x, d, lam, kg))
        proposal = apply_step(conf, x, d, 1.0)  # stepfunctions parity
        new_score = score_flat(proposal, kg)
        rho = (new_score - score) / jnp.where(qm >= 0, -1e-10, qm)
        lam = jnp.where(rho > 0.75, lam * (2.0 / 3.0),
                        jnp.where(rho < 0.25, lam * 1.5, lam))
        accept = new_score < score
        x_new = jnp.where(jnp.logical_or(done, ~accept), x, proposal)
        d_prev = jnp.where(done, d_prev, d)
        # rejected iterations report the evaluated score at x (not
        # old_score, which starts at +inf and would leak into the trace)
        out_score = jnp.where(done, old_score,
                              jnp.where(accept, new_score, score))
        stall, hard = terminated(new_score, old_score, gnorm,
                                 jnp.linalg.norm(d))
        stall_n = jnp.where(done, stall_n,
                            jnp.where(stall, stall_n + 1, 0))
        done = jnp.logical_or(done, jnp.logical_or(
            hard, stall_n >= STALL_PATIENCE))
        return (x_new, d_prev, lam, k, done, out_score, stall_n,
                aux), out_score

    init = (x0, jnp.zeros_like(x0), jnp.asarray(conf.hf_initial_lambda),
            key, jnp.asarray(False), jnp.inf, jnp.asarray(0), aux0)
    carry, scores = jax.lax.scan(step, init,
                                 jnp.arange(conf.num_iterations))
    return unravel(carry[0]), scores, carry[-1]


def _optimize_impl(objective: Objective, params0, conf, key):
    algo = OptimizationAlgorithm(str(conf.optimization_algo))
    if algo == OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT:
        return _sgd(objective, params0, conf, key)
    if algo == OptimizationAlgorithm.HESSIAN_FREE:
        return _hessian_free(objective, params0, conf, key)
    return _line_searched(objective, params0, conf, key, algo)


def optimize(objective: Objective, params0, conf, key):
    """Run the configured solver; returns (params, per-iteration scores).

    Dispatch parity: `Solver.java:54-70`.
    """
    params, scores, _ = _optimize_impl(objective, params0, conf, key)
    return params, scores


def optimize_with_aux(objective: Objective, params0, conf, key):
    """Like `optimize`, but also returns the aux pytree from the last live
    iteration's `grad_score_aux` call (an empty tuple when the objective
    has no aux channel).  This is how compiled train steps get BatchNorm
    batch moments out of the solver without a second forward pass."""
    return _optimize_impl(objective, params0, conf, key)


class Solver:
    """OO facade mirroring the reference `Solver` builder usage."""

    def __init__(self, conf, objective: Objective):
        self.conf = conf
        self.objective = objective

    def optimize(self, params, key):
        return optimize(self.objective, params, self.conf, key)
