"""Per-op cost accounting for the MFU campaign.

MFU alone says *that* a train step is slow, not *where*.  This module
splits a transformer train step's cost into the op categories the
campaign's hot-path work targets —

  matmul         weight GEMMs (qkv / proj / ffn / logits), fwd + bwd
  attention_fwd  the S x S score + value products per head, forward
  attention_bwd  the grad products (dQ/dK/dV) — split from fwd so the
                 fused-bwd campaign leg shows up as its own line, and so
                 the jax-level recompute path's extra forward is charged
                 where it belongs
  elementwise    layernorm / gelu / softmax / residual traffic
  updater        the optimizer chain over every parameter
  transfer       host -> device batch bytes per step

— from two independent sources that cross-check each other:

  1. analytic counts from the model dimensions alone
     (`transformer_step_costs`), exact for matmul/attention (the standard
     6*P*tokens + 12*S*d per token per block accounting) and coarse,
     coefficient-documented estimates for the rest;
  2. XLA's own totals for the AOT-compiled executable
     (`compiled_totals` via `compiled.cost_analysis()`), available on
     TPU *and* CPU, so the breakdown ships in every bench artifact even
     when the device claim falls back.

`breakdown` reconciles the two: per-category flops/bytes plus the
`unattributed` remainder of the measured total the analytic model does
not cover (fusion overheads, reductions, masking...).  A large
unattributed share is itself a finding — it means the step is burning
FLOPs outside the modelled hot paths.

On TPU, `maybe_trace` additionally captures a real `jax.profiler` trace
(op-level timeline, Perfetto-loadable) around the timed loop; off-TPU it
is a no-op so the bench path never forks.
"""

from __future__ import annotations

import contextlib
from typing import NamedTuple

CATEGORIES = ("matmul", "attention_fwd", "attention_bwd", "elementwise",
              "updater", "transfer")

#: analytic attention-backward flop multiples of the forward's 4*S*d per
#: token per block, by backward implementation (see
#: `transformer_step_costs`)
ATTENTION_BWD_MODES = ("dense", "fused", "recompute")


class OpCost(NamedTuple):
    flops: float
    bytes: float


def attention_block_bytes(seq: int, head_dim: int, block_q: int,
                          block_k: int, dtype_bytes: int = 4) -> float:
    """HBM traffic of one flash-attention head at (block_q, block_k):
    each of the S/bq Q tiles streams the full K and V ([S, D] each), Q
    itself and the output are read/written once, and every (q, k) tile
    pair touches a [bq, bk] f32 scores tile in VMEM.  This is the
    autotuner's pruning signal (`optimize/tunables.py` cost hints):
    relative cost across candidate blocks, not an absolute roofline —
    halving block_q doubles the K/V streaming term, which is exactly the
    2x the pruner cuts on."""
    q_tiles = max(1, -(-seq // block_q))
    stream = q_tiles * 2 * seq * head_dim           # K + V per Q tile
    once = 2 * seq * head_dim                       # Q in, O out
    scores = q_tiles * max(1, -(-seq // block_k)) * block_q * block_k
    return float(dtype_bytes) * (stream + once + scores)


def transformer_step_costs(*, batch: int, seq: int, d_model: int,
                           n_blocks: int, vocab: int, n_params: int,
                           dtype_bytes: int = 2,
                           sparse_labels: bool = False,
                           attention_bwd_mode: str = "dense") -> dict:
    """Analytic per-category costs for ONE char-transformer train step.

    Exact pieces (standard dense-transformer accounting):
      matmul GEMM params  P_mm = 12*d^2 per block (qkv 3d^2 + proj d^2 +
      ffn up/down 8d^2) + d*vocab logits; fwd+bwd = 6 * P_mm * tokens.
      attention_fwd = 4 * u where u = n_blocks * tokens * seq * d_model
      (scores 2*S*d + values 2*S*d per token per block).
      attention_bwd depends on the backward implementation
      (`attention_bwd_mode`):
        "dense"     8 * u — XLA autodiff of full/blockwise attention: the
                    four grad products (dV, dP, dS->dK, dS->dQ) with the
                    probabilities retained from the forward;
        "fused"     10 * u — the fused Pallas backward
                    (`attention_fused_bwd`): same four grad products plus
                    one in-kernel score recompute (2*u), which is the
                    price of never materializing [S,S];
        "recompute" 12 * u — the jax-level fallback VJP: the 8*u autodiff
                    products plus a full forward re-run (4*u).  This is
                    the term the fused path eliminates; pre-split
                    accounting lumped attention at 12*u total and silently
                    undercounted this path, inflating `unattributed`.

    Coarse pieces (coefficients below, documented not derived):
      elementwise: ~60 flops per activation element per block fwd+bwd
      (2 layernorms ~20, gelu ~16, softmax ~8, residuals/bias ~4, x2 bwd).
      updater: ~12 flops/param (chain: decay, moment updates, scale,
      clip norms), f32 traffic = 4 reads (param, grad, 2 state) +
      3 writes (param, 2 state).

    transfer counts the per-step host->device batch bytes: int32 ids for
    x, and labels either int32 ids (sparse) or a one-hot [tokens, vocab]
    row matrix — the whole point of `sparse_labels` is this vocab-fold
    reduction plus the gathered (never materialized) one-hot in the loss.
    """
    if attention_bwd_mode not in ATTENTION_BWD_MODES:
        raise ValueError(f"attention_bwd_mode={attention_bwd_mode!r} not in "
                         f"{ATTENTION_BWD_MODES}")
    tokens = batch * seq
    p_mm = 12 * n_blocks * d_model * d_model + d_model * vocab
    matmul = OpCost(6.0 * p_mm * tokens,
                    3.0 * p_mm * dtype_bytes)  # weights read fwd+bwd+gradw
    attn_unit = float(n_blocks * tokens * seq * d_model)
    # q/k/v/scores read+write per block: 1x the per-block traffic fwd,
    # 2x bwd (grads flow back through both products)
    attn_traffic = (3 * tokens * d_model + batch * seq * seq) * dtype_bytes
    bwd_mult = {"dense": 8.0, "fused": 10.0, "recompute": 12.0}
    attention_fwd = OpCost(4.0 * attn_unit, 1.0 * n_blocks * attn_traffic)
    attention_bwd = OpCost(bwd_mult[attention_bwd_mode] * attn_unit,
                           2.0 * n_blocks * attn_traffic)
    elementwise = OpCost(60.0 * n_blocks * tokens * d_model,
                         6.0 * n_blocks * tokens * d_model * dtype_bytes)
    updater = OpCost(12.0 * n_params, 7.0 * n_params * 4)
    label_bytes = tokens * (4 if sparse_labels else vocab * dtype_bytes)
    transfer = OpCost(0.0, tokens // max(seq, 1) * seq * 4 + label_bytes)
    return {"matmul": matmul, "attention_fwd": attention_fwd,
            "attention_bwd": attention_bwd,
            "elementwise": elementwise, "updater": updater,
            "transfer": transfer}


def compiled_totals(compiled) -> dict | None:
    """XLA's flop/byte totals for an AOT-compiled executable, or None
    when the backend doesn't expose `cost_analysis` (never raises — the
    bench must emit a breakdown even on exotic backends)."""
    try:
        cost = compiled.cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops = float(cost.get("flops", 0.0))
        nbytes = float(cost.get("bytes accessed", 0.0))
        if flops <= 0.0 and nbytes <= 0.0:
            return None
        return {"flops": flops, "bytes": nbytes}
    except Exception:
        return None


def breakdown(analytic: dict, totals: dict | None = None,
              step_seconds: float | None = None) -> dict:
    """Reconcile analytic per-category costs against measured totals.

    Returns a JSON-ready dict: per category {flops, bytes, flop_fraction}
    (fractions of the MEASURED total when available, else of the analytic
    sum), the measured totals, and the `unattributed` remainder — measured
    minus modelled, floored at 0.  With `step_seconds`, each category also
    gets its implied TFLOP/s so the hot spot reads directly off the JSON.
    """
    modelled_flops = sum(c.flops for c in analytic.values())
    total_flops = (totals or {}).get("flops") or modelled_flops
    out = {"categories": {}, "modelled_flops": modelled_flops}
    for name in CATEGORIES:
        c = analytic.get(name)
        if c is None:
            continue
        entry = {"flops": c.flops, "bytes": c.bytes,
                 "flop_fraction": round(c.flops / total_flops, 4)
                 if total_flops else 0.0}
        if step_seconds:
            entry["tflops_per_sec"] = round(c.flops / step_seconds / 1e12, 3)
        out["categories"][name] = entry
    if totals:
        out["measured_flops"] = totals["flops"]
        out["measured_bytes"] = totals["bytes"]
        out["unattributed_flops"] = max(0.0,
                                        totals["flops"] - modelled_flops)
        out["unattributed_fraction"] = round(
            out["unattributed_flops"] / totals["flops"], 4) \
            if totals["flops"] else 0.0
    return out


@contextlib.contextmanager
def maybe_trace(trace_dir: str | None = None):
    """`jax.profiler.trace` around the body when a dir is given AND the
    backend is a real TPU; a no-op otherwise (CPU traces of a bench loop
    are all host callback noise — not worth the artifact bytes)."""
    from deeplearning4j_tpu.nd.platform import is_tpu

    if trace_dir and is_tpu():
        import jax

        with jax.profiler.trace(trace_dir):
            yield
    else:
        yield
