"""Compiled train-step cache — compile once, execute many.

The core lesson of both the TPU paper (Jouppi et al.) and TensorFlow's
dataflow design (Abadi et al.) is that each (config, batch-shape) pair
should lower to ONE XLA program reused for the whole run.  Before this
module the single-chip path violated that: `MultiLayerNetwork.finetune`
closed a fresh loss over each batch's arrays and handed it to
`solver_mod.optimize`, so the entire solver `lax.scan` was re-traced and
re-compiled per batch with the batch data baked in as constants.

Design:

  key schema    (kind, conf-fingerprint, algorithm, arg shapes/dtypes,
                 pretrain-layer index) -> AOT-compiled XLA executable.
                 The fingerprint is a sha1 of the frozen config's
                 canonical JSON, so config edits can never alias a stale
                 program.
  batch args    batch data (x, labels, row weights) are explicit jit
                 ARGUMENTS of the compiled program (see
                 `solver.BatchedObjective`), never closure constants.
  donation      params are donated to the step (`donate_argnums=(0,)`) on
                 accelerator backends, so the single-chip path stops
                 double-buffering parameters in HBM.  Donation is skipped
                 on CPU, where XLA would only warn.  Caveat: a donated
                 params buffer is dead after the call — `clone()`d
                 networks sharing params with a training net must copy
                 first on TPU (`parallel.data_parallel.init_train_state`
                 already does).
  bucketing     remainder batches are zero-padded up to the smallest
                 already-known bucket that fits (buckets grow on demand
                 from the full-batch sizes actually seen), and pad rows
                 carry row-weight 0 through the existing
                 `network_rowwise_loss(..., row_weights=...)` machinery —
                 masked out of the loss, the gradients AND the BatchNorm
                 batch statistics.  A full epoch therefore compiles at
                 most n_buckets programs instead of one per tail shape.
  observability `cache.stats` tracks hits, misses, steps executed and
                 per-key compile seconds; every miss is logged so
                 retraces are observable instead of silent.

Hessian-free finetune joins the cache too: its Gauss-Newton product is
built from `solver.weighted_predict_loss`, which threads the pad-row
weight mask through the loss-of-outputs half of the product — pad rows
carry exact-zero curvature cotangents, so HF programs share the bucketed
padding (and its bit-exactness guarantee) with every other algorithm.

The serve-path sibling of this module is `optimize/infer_cache.py`
(`InferCache`): it reuses the `CompiledProgramCache` machinery below for
the inference entry points (output / loss / feed_forward).
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nd.platform import default_backend
from deeplearning4j_tpu.optimize import solver as solver_mod
from deeplearning4j_tpu.reliability import faults

log = logging.getLogger("deeplearning4j_tpu")


def conf_fingerprint(conf) -> str:
    """Stable fingerprint of a frozen config: sha1 of its canonical JSON
    (sorted keys), truncated — collision-safe far beyond any realistic
    number of configs per process."""
    return hashlib.sha1(conf.to_json().encode("utf-8")).hexdigest()[:16]


def arg_signature(*arrays) -> Tuple:
    """(shape, dtype) tuple per array — the shape part of the cache key."""
    return tuple(
        None if a is None else (tuple(a.shape), str(jnp.asarray(a).dtype))
        for a in arrays)


class StepCacheStats:
    """Counters exposed on the cache object (ISSUE: observability).

    The memory-vs-disk-vs-compile split: `hits` are in-memory program
    reuses, `disk_hits` are programs restored from the persistent store
    (trace/lower skipped, deserialize+compile paid — see
    `deserialize_seconds`), `misses` are fresh trace+compiles
    (`compile_seconds`); `disk_write_seconds` is the write-back cost of
    persisting fresh compiles."""

    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.steps = 0                      # compiled-step executions
        self.compile_seconds: Dict[Tuple, float] = {}  # key -> seconds
        self.disk_hits = 0
        self.disk_write_seconds = 0.0
        self.deserialize_seconds = 0.0
        self.io_errors = 0  # disk faults downgraded to misses (persist)
        self.fetch_hits = 0     # entries warmed over the wire (persist)
        self.fetch_corrupt = 0  # fetched bytes failing re-validation

    @property
    def total_compile_seconds(self) -> float:
        return float(sum(self.compile_seconds.values()))

    def as_dict(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "steps": self.steps, "entries": len(self.compile_seconds),
                "compile_seconds": round(self.total_compile_seconds, 3),
                "disk_hits": self.disk_hits,
                "disk_write_seconds": round(self.disk_write_seconds, 3),
                "deserialize_seconds": round(self.deserialize_seconds, 3),
                "io_errors": self.io_errors,
                "fetch_hits": self.fetch_hits,
                "fetch_corrupt": self.fetch_corrupt}

    def __repr__(self):
        return f"StepCacheStats({self.as_dict()})"


class CompiledProgramCache:
    """Shared compile-once machinery: keyed AOT programs, grow-on-demand
    shape buckets, and hit/miss/compile-seconds stats.

    `TrainStepCache` (below) and the serve-path `InferCache`
    (`optimize/infer_cache.py`) are both thin entry-point layers over
    this class — same key schema, same bucket policy, same
    observability, different programs.

    donate: None = donate params on accelerator backends only (CPU XLA
    ignores donation with a warning); True/False force it.
    buckets: optional fixed iterable of allowed batch-row buckets; by
    default buckets grow on demand from the batch sizes seen (full
    batches come first in practice, tails then pad up into them).
    persist: optional `optimize.persist.PersistentProgramStore` — memory
    misses check the on-disk store before compiling (disk hit: the
    trace/lower cost is skipped, `stats.disk_hits`/`deserialize_seconds`
    grow), and fresh compiles write back (`stats.disk_write_seconds`).
    """

    #: label used in miss logs so train/infer retraces are distinguishable
    kind = "program-cache"

    def __init__(self, donate: Optional[bool] = None,
                 buckets: Optional[Tuple[int, ...]] = None,
                 persist=None):
        self._programs: Dict[Tuple, Callable] = {}
        self._fingerprints: Dict[int, str] = {}  # id(conf) memo
        self._buckets: List[int] = sorted(buckets) if buckets else []
        self._fixed_buckets = buckets is not None
        self._donate = donate
        self._persist = persist
        # per-key audit records (builder, abstract args, donation) so the
        # program auditor (analysis/program_audit.py) can re-trace and
        # inspect every program this cache ever compiled
        self._audit_records: Dict[Tuple, dict] = {}
        self.stats = StepCacheStats()
        # the serving gateway (and its batching-off control arm) reaches
        # this cache from many threads at once: lookup, bucket growth and
        # stats mutate under one lock (program EXECUTION does not — jax
        # dispatch is thread-safe and must overlap)
        self._lock = threading.RLock()

    # -- persistence --------------------------------------------------------
    @property
    def persist(self):
        return self._persist

    def set_persist(self, store) -> None:
        """Attach (or detach with None) a `PersistentProgramStore` —
        already-compiled in-memory programs stay valid either way."""
        with self._lock:
            self._persist = store

    # -- bucket policy ------------------------------------------------------
    def bucket_rows(self, n: int) -> int:
        """Smallest known bucket >= n; otherwise n becomes a new bucket
        (fixed bucket sets never grow — an oversize batch runs unpadded
        as its own bucket, logged).  A tuned `infer.bucket_ladder`
        (optimize/tunables.py) pre-seeds the grow-on-demand list; the
        registry default is the empty ladder, which leaves this loop
        byte-identical to the pre-registry behavior."""
        from deeplearning4j_tpu.optimize import tunables

        with self._lock:
            if not self._fixed_buckets:
                for b in tunables.resolve("infer.bucket_ladder"):
                    if int(b) not in self._buckets:
                        self._buckets.append(int(b))
                        self._buckets.sort()
            for b in self._buckets:
                if b >= n:
                    return b
            if self._fixed_buckets and self._buckets:
                log.info("%s: batch of %d rows exceeds the fixed "
                         "buckets %s; running unpadded", self.kind, n,
                         self._buckets)
            else:
                self._buckets.append(n)
                self._buckets.sort()
            return n

    @property
    def buckets(self) -> Tuple[int, ...]:
        return tuple(self._buckets)

    # -- program lookup -----------------------------------------------------
    def _fingerprint(self, conf) -> str:
        with self._lock:
            fp = self._fingerprints.get(id(conf))
            if fp is None:
                fp = conf_fingerprint(conf)
                self._fingerprints[id(conf)] = fp
            return fp

    def _donate_argnums(self) -> Tuple[int, ...]:
        donate = self._donate
        if donate is None:
            donate = default_backend() != "cpu"
        return (0,) if donate else ()

    def audit_records(self) -> List[dict]:
        """Snapshot of the per-program audit records (one per compiled
        or disk-restored key): {key, kind, build, abstract,
        donate_argnums, mesh, shardings}.
        `analysis.program_audit.audit_cache` re-traces each builder
        against its abstract args to inspect the jaxpr without
        executing anything; the `shardings` entry (per-arg Sharding or
        per-leaf pytree, None single-chip) feeds the
        replicated-large-leaf rule."""
        with self._lock:
            return list(self._audit_records.values())

    def program_memory(self) -> List[dict]:
        """Per-program per-device argument-memory estimate, one row per
        audit record: `per_device_argument_bytes` sums each abstract
        leaf's shard size under its recorded sharding (the bytes ONE
        chip holds), `replicated_argument_bytes` the unsharded total —
        the pair that proves a tensor-parallel plan fits where a
        replicated one cannot.  When the backend exposes it, the
        compiled executable's `memory_analysis()` is attached verbatim
        under `memory_analysis` (argument/output/temp/generated-code
        sizes); backends without it (CPU) leave it None, which is why
        the estimate is computed from the avals and always present."""
        import numpy as np

        with self._lock:
            recs = list(self._audit_records.values())
            programs = dict(self._programs)
        rows = []
        for rec in recs:
            per_dev = total = 0
            for leaf in jax.tree_util.tree_leaves(rec["abstract"]):
                shape = tuple(getattr(leaf, "shape", ()) or ())
                nbytes = int(np.prod(shape, dtype=np.int64)
                             * np.dtype(leaf.dtype).itemsize)
                total += nbytes
                s = getattr(leaf, "sharding", None)
                if s is not None:
                    shard = tuple(s.shard_shape(shape))
                    per_dev += int(np.prod(shard, dtype=np.int64)
                                   * np.dtype(leaf.dtype).itemsize)
                else:
                    per_dev += nbytes
            analysis = None
            fn = programs.get(rec["key"])
            try:
                mem = fn.memory_analysis() if fn is not None else None
                if mem is not None:
                    analysis = {
                        k: int(getattr(mem, k))
                        for k in ("argument_size_in_bytes",
                                  "output_size_in_bytes",
                                  "temp_size_in_bytes",
                                  "generated_code_size_in_bytes")
                        if hasattr(mem, k)}
            except Exception:  # noqa: BLE001 — backend without analysis
                analysis = None
            rows.append({"key": rec["key"],
                         "entry": rec["key"][0] if rec["key"] else None,
                         "per_device_argument_bytes": int(per_dev),
                         "replicated_argument_bytes": int(total),
                         "memory_analysis": analysis})
        return rows

    def _get(self, key: Tuple, build: Callable[[], Callable], args: Tuple,
             shardings: Optional[Tuple] = None,
             donate: Optional[Tuple[int, ...]] = None):
        """Return the compiled executable for `key`: memory hit, else
        disk hit (persistent store attached), else a timed fresh
        trace+compile with disk write-back.  Serialized under the cache
        lock: two threads racing a miss would otherwise compile (and
        persist) the same program twice.

        shardings: optional per-arg shardings (None = default
        single-device placement).  Each entry is either ONE
        `jax.sharding.Sharding` applied to every leaf of the matching
        arg subtree (replicated params, row-sharded batch — the 1-D
        serve pattern), or a PYTREE of shardings matching the arg
        leaf-for-leaf (tensor-parallel plans place each param / KV
        leaf differently).  Either way the program compiles with
        jit-inserted collectives — the caller must fold the sharding
        into `key`.

        donate: optional per-program donate_argnums override (None =
        the cache-wide `_donate_argnums()` policy).  Lets an entry with
        a different aliasing contract — e.g. the KV-cache decode step,
        which donates its state buffers but never its params — coexist
        with the cache's default entries."""
        with self._lock:
            return self._get_locked(key, build, args, shardings, donate)

    def _get_locked(self, key: Tuple, build: Callable[[], Callable],
                    args: Tuple, shardings: Optional[Tuple] = None,
                    donate: Optional[Tuple[int, ...]] = None):
        fn = self._programs.get(key)
        if fn is not None:
            self.stats.hits += 1
            return fn
        if shardings is None:
            abstract = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                               jnp.asarray(a).dtype), args)
        else:
            def _abs(a, _s):
                return jax.ShapeDtypeStruct(jnp.shape(a),
                                            jnp.asarray(a).dtype,
                                            sharding=_s)

            abstract = tuple(
                jax.tree_util.tree_map(lambda a, _s=s: _abs(a, _s), arg)
                if isinstance(s, jax.sharding.Sharding)
                else jax.tree_util.tree_map(_abs, arg, s)
                for arg, s in zip(args, shardings))
        donate = self._donate_argnums() if donate is None else tuple(donate)
        self._audit_records[key] = {
            "key": key, "kind": self.kind, "build": build,
            "abstract": abstract, "donate_argnums": donate,
            "mesh": shardings is not None, "shardings": shardings}
        if self._persist is not None:
            fn = self._load_from_disk(key, abstract, donate)
            self._sync_persist_counters()
            if fn is not None:
                return fn
        # armed 'compile' faults fire here: the one place every fresh
        # trace+compile (train or infer) funnels through
        faults.fire("compile", kind=self.kind, key=repr(key))
        self.stats.misses += 1
        t0 = time.perf_counter()
        exported = None
        if self._persist is not None:
            # fresh compiles ALSO route through jax.export: the executed
            # module is the exact module a later disk hit restores, so
            # cold and warm-disk runs match bit-for-bit — and the trace
            # happens once (export), never again for this artifact
            try:
                from jax import export as jax_export

                exported = jax_export.export(jax.jit(build()))(*abstract)
                fn = jax.jit(exported.call,
                             donate_argnums=donate).lower(*abstract).compile()
            except Exception as e:  # noqa: BLE001 — non-exportable program
                log.warning("%s: program %s is not exportable (%s); "
                            "compiling without persistence", self.kind, key, e)
                exported, fn = None, None
        if exported is None:
            jitted = jax.jit(build(), donate_argnums=donate)
            fn = jitted.lower(*abstract).compile()
        dt = time.perf_counter() - t0
        self.stats.compile_seconds[key] = dt
        log.info("%s miss: compiled %s in %.2fs (entry %d)",
                 self.kind, key, dt, len(self._programs) + 1)
        if exported is not None:
            tw = time.perf_counter()
            self._persist.store(key, exported)
            self.stats.disk_write_seconds += time.perf_counter() - tw
            self._sync_persist_counters()
        self._programs[key] = fn
        return fn

    def _sync_persist_counters(self) -> None:
        """Mirror the store's entry-health counters onto the stats the
        serving surfaces expose (stores can be shared across caches, so
        the store owns the truth and the cache snapshots it)."""
        self.stats.io_errors = self._persist.io_errors
        self.stats.fetch_hits = getattr(self._persist, "fetch_hits", 0)
        self.stats.fetch_corrupt = getattr(self._persist,
                                           "fetch_corrupt", 0)

    def _load_from_disk(self, key: Tuple, abstract, donate):
        """Disk half of `_get`: deserialize + AOT-compile a persisted
        program.  Any failure (corrupt entry already evicted by the
        store, platform drift the fingerprint missed) returns None and
        the caller recompiles."""
        t0 = time.perf_counter()
        exported = self._persist.load(key)
        if exported is None:
            return None
        try:
            fn = jax.jit(exported.call,
                         donate_argnums=donate).lower(*abstract).compile()
        except Exception as e:  # noqa: BLE001 — treat as corrupt: evict
            log.warning("%s: persisted entry for %s failed to compile "
                        "(%s); evicting and recompiling", self.kind, key, e)
            self._persist.evict(key)
            return None
        dt = time.perf_counter() - t0
        self.stats.disk_hits += 1
        self.stats.deserialize_seconds += dt
        log.info("%s disk hit: restored %s in %.2fs (entry %d)",
                 self.kind, key, dt, len(self._programs) + 1)
        self._programs[key] = fn
        return fn

    def track_jit(self, base_key: Tuple, jitted) -> Callable:
        """Wrap an already-jitted program (e.g. a shard_map'd dp train
        step) so its per-shape AOT compiles are timed and counted in
        this cache's stats like every single-chip program.  lower() runs
        on the REAL args of the triggering call, so GSPMD/mesh shardings
        are preserved; entries are keyed by `base_key` + the flattened
        arg signature + the arg SHARDINGS — a compiled executable only
        accepts the exact layouts it was built for, and dp params really
        do change layout once (host-resident at step 0, mesh-replicated
        after), which is a genuine second program, not a re-trace.  No
        disk persistence (multi-device layouts are process-topology-
        bound; the platform fingerprint would thrash)."""

        def wrapped(*args):
            leaves = jax.tree_util.tree_leaves(args)
            shards = tuple(str(getattr(l, "sharding", None))
                           for l in leaves)
            key = tuple(base_key) + (arg_signature(*leaves), shards)
            fn = self._programs.get(key)
            if fn is None:
                self.stats.misses += 1
                t0 = time.perf_counter()
                fn = jitted.lower(*args).compile()
                dt = time.perf_counter() - t0
                self.stats.compile_seconds[key] = dt
                log.info("%s miss: compiled %s in %.2fs (entry %d)",
                         self.kind, key, dt, len(self._programs) + 1)
                self._programs[key] = fn
            else:
                self.stats.hits += 1
            self.stats.steps += 1
            return fn(*args)

        # callers that AOT-compile explicitly (bench MFU) reach through
        wrapped.lower = jitted.lower
        wrapped.__wrapped__ = jitted
        return wrapped

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()
            self._audit_records.clear()
            self._buckets = (sorted(self._buckets) if self._fixed_buckets
                             else [])
            self.stats = StepCacheStats()

    def __len__(self):
        return len(self._programs)

    # -- padding ------------------------------------------------------------
    @staticmethod
    def pad_batch(x, y, bucket: int):
        """Zero-pad (x, y) up to `bucket` feature rows and build the
        per-label-row weight vector (pad rows weigh 0).  Label rows may
        be a multiple of feature rows (B*T for sequence models)."""
        b = x.shape[0]
        ratio = max(1, y.shape[0] // max(1, b))
        pad = bucket - b
        w = jnp.concatenate([jnp.ones(b * ratio, jnp.float32),
                             jnp.zeros(pad * ratio, jnp.float32)])
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
            y = jnp.concatenate(
                [y, jnp.zeros((pad * ratio,) + y.shape[1:], y.dtype)])
        return x, y, w


class TrainStepCache(CompiledProgramCache):
    """Memoizes AOT-compiled solver programs (the training entry points
    over `CompiledProgramCache`)."""

    kind = "step-cache"

    # -- network train steps ------------------------------------------------
    def finetune(self, conf, params, x, y, key, compile_only: bool = False):
        """One cached supervised solver run (`MultiLayerNetwork.finetune`
        body): pads (x, y) to the bucket, fetches/compiles the program
        for (conf, algo, shapes) and executes it.

        Returns (new_params, per-iteration scores).  BatchNorm running
        stats are advanced INSIDE the program from the last solver
        iteration's batch moments (`update_bn_ema_from_stats`) — no
        second forward pass.

        compile_only=True (warmup) registers the bucket and compiles —
        or disk-restores — the program without executing a step; params
        are untouched and None is returned."""
        from deeplearning4j_tpu.nn.multilayer import has_batchnorm

        out_conf = conf.conf(conf.n_layers - 1)
        bucket = self.bucket_rows(int(x.shape[0]))
        x, y, w = self.pad_batch(x, y, bucket)
        collect_bn = has_batchnorm(conf)
        cache_key = ("finetune", self._fingerprint(conf),
                     str(out_conf.optimization_algo),
                     arg_signature(x, y, w))
        args = (params, x, y, w, key)
        fn = self._get(cache_key,
                       lambda: _finetune_program(conf, collect_bn), args)
        if compile_only:
            return None
        self.stats.steps += 1
        return fn(*args)

    def pretrain(self, layer_conf, layer_idx: int, impl, layer_params, x,
                 key):
        """One cached layer-wise pretraining solver run
        (`MultiLayerNetwork.pretrain_layer` body).  Pretraining
        objectives take no row weights, so batches are NOT bucketed —
        each distinct input shape compiles its own program (keyed by the
        pretrain-layer index)."""
        cache_key = ("pretrain", layer_idx, self._fingerprint(layer_conf),
                     str(layer_conf.optimization_algo), arg_signature(x))
        args = (layer_params, x, key)
        fn = self._get(cache_key,
                       lambda: _pretrain_program(layer_conf, impl), args)
        self.stats.steps += 1
        return fn(*args)


def _finetune_program(conf, collect_bn: bool) -> Callable:
    """Build the (uncompiled) finetune step: run the configured solver
    over explicit batch args, then fold the BatchNorm EMA advance into
    the same program.  Hessian-free additionally gets a Gauss-Newton
    product with the pad-row weight mask threaded through its
    loss-of-outputs half (`solver.weighted_predict_loss`), so HF shares
    the bucketed padding instead of the legacy closure path."""
    # local import: nn.multilayer imports this module at top level
    from deeplearning4j_tpu.nn.conf import OptimizationAlgorithm
    from deeplearning4j_tpu.nn.multilayer import (make_finetune_loss,
                                                  network_output,
                                                  update_bn_ema_from_stats)

    out_conf = conf.conf(conf.n_layers - 1)
    loss_and_stats = make_finetune_loss(conf, collect_bn=collect_bn)
    is_hf = (OptimizationAlgorithm(str(out_conf.optimization_algo))
             == OptimizationAlgorithm.HESSIAN_FREE)

    def program(params, x, y, w, key):
        if collect_bn:
            def gsa(p, k):
                (s, stats), g = jax.value_and_grad(
                    lambda pp, kk: loss_and_stats(pp, x, y, w, kk),
                    has_aux=True)(p, k)
                return g, s, stats

            objective = solver_mod.Objective(
                grad_and_score=lambda p, k: gsa(p, k)[:2],
                score=lambda p, k: loss_and_stats(p, x, y, w, k)[0],
                grad_score_aux=gsa)
        else:
            objective = solver_mod.from_loss(
                lambda p, k: loss_and_stats(p, x, y, w, k)[0])
        if is_hf:
            # factor as predict+loss for Gauss-Newton products (the
            # reference's computeDeltasR R-op machinery); pad rows enter
            # the product with weight 0 — exact-zero curvature cotangents
            objective = objective._replace(
                gnvp=solver_mod.weighted_predict_loss(
                    lambda p, k: network_output(conf, p, x),
                    _rowwise_output_loss(out_conf), y, w).gnvp)
        new_params, scores, aux = solver_mod.optimize_with_aux(
            objective, params, out_conf, key)
        if collect_bn:
            new_params = update_bn_ema_from_stats(conf, new_params, aux)
        return new_params, scores

    return program


def _rowwise_output_loss(out_conf):
    """The output layer's per-row loss `(labels, outputs) -> [rows]` for
    the Gauss-Newton factorization."""
    from deeplearning4j_tpu.nd.losses import get_rowwise

    return get_rowwise(out_conf.loss_function)


def _pretrain_program(layer_conf, impl) -> Callable:
    """Build the (uncompiled) layer-pretraining step over explicit x."""

    def program(layer_params, x, key):
        objective = solver_mod.Objective(
            grad_and_score=lambda p, k: impl.pretrain_grad_and_score(
                p, layer_conf, x, k),
            score=lambda p, k: impl.pretrain_score(p, layer_conf, x, k))
        return solver_mod.optimize(objective, layer_params, layer_conf, key)

    return program
