"""Backtracking line search.

Parity: reference `optimize/solvers/BackTrackLineSearch.java:57-294` (ported
there from MALLET) — sufficient-decrease constant `ALF = 1e-4` (:72), max
step clamp `stpmax` (:159-162), bounded iteration count.

TPU-native design: a bounded `lax.while_loop` over (alpha, f_alpha, iters)
so the search jit-compiles inside the surrounding solver program.  Uses
geometric backtracking (factor 0.5) rather than MALLET's polynomial
interpolation — same guarantee (Armijo condition), fewer data-dependent
branches for XLA.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

ALF = 1e-4  # sufficient-decrease constant (BackTrackLineSearch.java:72)
STPMAX = 100.0


def backtrack(score_fn, x, direction, grad, f0, max_iters=20, initial_step=1.0):
    """Find alpha s.t. f(x + alpha*d) <= f0 + ALF*alpha*<g,d>.

    score_fn: flat-vector -> scalar loss.  Returns (alpha, f_new).
    If no step satisfies Armijo within max_iters, returns (0, f0) — the
    caller then keeps the old params (reference behavior: failed search
    leaves the step at 0).
    """
    dnorm = jnp.linalg.norm(direction)
    xnorm = jnp.maximum(jnp.linalg.norm(x), 1.0)
    stpmax = STPMAX * xnorm
    # clamp overlong directions (BackTrackLineSearch.java:159-162)
    direction = jnp.where(dnorm > stpmax, direction * (stpmax / dnorm), direction)
    slope = jnp.vdot(grad, direction)

    def cond(state):
        alpha, f_alpha, it = state
        armijo = f_alpha <= f0 + ALF * alpha * slope
        return jnp.logical_and(~armijo, it < max_iters)

    def body(state):
        alpha, _, it = state
        alpha = alpha * 0.5
        return alpha, score_fn(x + alpha * direction), it + 1

    a0 = jnp.asarray(initial_step, x.dtype)
    f_a0 = score_fn(x + a0 * direction)
    alpha, f_alpha, _ = jax.lax.while_loop(cond, body, (a0, f_a0, 0))
    ok = f_alpha <= f0 + ALF * alpha * slope
    alpha = jnp.where(ok, alpha, 0.0)
    f_alpha = jnp.where(ok, f_alpha, f0)
    return alpha, f_alpha
