"""optimize — solvers, updater chain, line search, listeners, terminations.

Parity with reference `optimize/*` (SURVEY §1 L2): `Solver` dispatch keyed by
`OptimizationAlgorithm`, the `BaseOptimizer` loop, `BackTrackLineSearch`,
CG / LBFGS / gradient-descent solvers, and the `GradientAdjustment` updater
(AdaGrad, momentum + schedule, L2, unit-norm, batch scaling).

TPU-native design: every solver is a pure JAX program — the optimization
loop is `lax.while_loop` over a flat parameter vector (`ravel_pytree`), the
line search is a bounded inner `lax.while_loop`, so an entire `fit` call
compiles to a single XLA executable with zero host round-trips.
"""

from deeplearning4j_tpu.optimize.solver import Solver, optimize
from deeplearning4j_tpu.optimize.updater import UpdaterState, init_updater, adjust_gradient
from deeplearning4j_tpu.optimize.step_cache import TrainStepCache
from deeplearning4j_tpu.optimize.infer_cache import InferCache
