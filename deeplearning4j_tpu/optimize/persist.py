"""Persistent on-disk compile cache — programs survive the process.

PR 1/2 made every (config, shape-bucket) pair lower to exactly one XLA
program per process, but the programs died with the process: every
restart of a trainer, eval job, or serving CLI re-paid the full
trace+compile cost before its first batch.  Both the TPU paper (Jouppi
et al., 2017) and TensorFlow's dataflow design (Abadi et al., 2016)
treat the compiled program as a durable artifact reused across runs —
this module makes the shared `CompiledProgramCache` do the same.

Design:

  export format  one entry = one file holding a small JSON header plus
                 the `jax.export` serialization of the traced program
                 (StableHLO).  Loading deserializes and AOT-compiles the
                 exported module — the trace/lower cost (the dominant
                 Python-side share of a cold start) is skipped entirely,
                 and the executed program is byte-identical to what a
                 fresh compile of the same key would run, because fresh
                 compiles ALSO go through export (see
                 `CompiledProgramCache._get`): disk-hit and fresh-compile
                 steps match bit-for-bit.
  key schema     entries reuse the caches' existing (kind, conf
                 fingerprint, algorithm/entry, shapes/dtypes) key,
                 extended with a PLATFORM fingerprint — backend, device
                 kind, device count, jax/jaxlib versions, format version
                 — folded into the filename hash AND revalidated from
                 the header on load, so a stale or foreign artifact can
                 never load: a mismatch is a plain miss that recompiles.
  atomicity      writes go to a tmpfile in the cache directory and
                 `os.replace` into place — concurrent writers (several
                 serving processes warming the same directory) can never
                 expose a torn file; last writer wins with identical
                 content.
  corruption     every header carries a sha256 of the blob; any
                 unreadable/truncated/mismatched entry is evicted and
                 the caller falls back to a fresh compile (and rewrites
                 the entry).
  bounded size   a size-capped LRU keeps the directory under
                 `max_bytes`: loads touch the file's mtime, writes evict
                 oldest-read entries until the total fits.
  multi-writer   several processes (serving replicas) share one
                 directory with no coordination: every path between
                 listdir/stat and open/remove tolerates the entry
                 vanishing under a sibling's eviction — a vanished file
                 is a counted miss (`vanished`), never an exception, and
                 double-evictions count once.

The store is shared by `TrainStepCache` and `InferCache` (one key
schema, one export format); see `MultiLayerNetwork.set_compile_cache`
and the CLI's `--compile-cache DIR` / `warmup` subcommand for the
user-facing wiring.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import tempfile
import time
from typing import Optional, Tuple

from deeplearning4j_tpu.reliability import faults

log = logging.getLogger("deeplearning4j_tpu")

#: bump to invalidate every existing artifact on a format change
FORMAT_VERSION = 1

_MAGIC = b"DL4JJXP1"
_SUFFIX = ".jxp"

#: default directory cap; override per-store or via env
DEFAULT_MAX_BYTES = int(os.environ.get("DL4J_COMPILE_CACHE_MAX_BYTES",
                                       str(1 << 30)))


def platform_info() -> dict:
    """The platform facts an XLA executable is only valid for: backend,
    device kind, visible-device topology, and the jax/jaxlib pair that
    produced the StableHLO.  Kept as a dict (stored in every header) so
    a mismatch is diagnosable, fingerprinted for the fast path."""
    import jax
    import jaxlib

    from deeplearning4j_tpu.nd import platform

    devs = platform.devices()
    return {
        "format": FORMAT_VERSION,
        "backend": platform.default_backend(),
        "device_kind": devs[0].device_kind if devs else "none",
        "n_devices": len(devs),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
    }


def platform_fingerprint(info: Optional[dict] = None) -> str:
    """Stable fingerprint of `platform_info` (sha1 of canonical JSON)."""
    info = platform_info() if info is None else info
    blob = json.dumps(info, sort_keys=True).encode("utf-8")
    return hashlib.sha1(blob).hexdigest()[:16]


def canonical_key(key: Tuple) -> str:
    """Deterministic string form of a cache key (tuples of str/int/None
    nest arbitrarily; repr is stable for those)."""
    return repr(key)


class PersistentProgramStore:
    """Versioned on-disk store for `jax.export`-serialized programs.

    load/store never raise on entry-level problems — a bad entry is
    evicted and reported via the counters, and the caller recompiles.
    Directory-level problems (unwritable path) raise at construction.
    """

    def __init__(self, directory: str,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self.directory = os.path.abspath(os.path.expanduser(directory))
        self.max_bytes = int(max_bytes)
        os.makedirs(self.directory, exist_ok=True)
        self._platform = platform_info()
        self._fingerprint = platform_fingerprint(self._platform)
        # entry-level health counters (the per-cache timing/hit split
        # lives on StepCacheStats — stores can be shared across caches)
        self.writes = 0
        self.evictions = 0
        self.corrupt_evicted = 0
        self.io_errors = 0       # OSErrors downgraded to cache misses
        self.vanished = 0        # entries a sibling process removed first
        self.fetch_hits = 0      # entries warmed over the wire
        self.fetch_corrupt = 0   # fetched bytes failing re-validation
        self.fetch_misses = 0    # remote lookups no peer could serve
        self._io_warned = False  # warn ONCE, then count quietly
        # optional remote fallback (serving/cachesync.CacheFetcher):
        # entry filename -> container bytes or None
        self._remote_fetch = None

    def set_remote(self, fetch_fn) -> None:
        """Install a remote fetch fallback: on a locally-absent entry,
        `fetch_fn(filename)` is asked for the container bytes before the
        caller falls back to compiling.  Fetched bytes go through the
        SAME magic/header/checksum validation as disk reads — a corrupt
        or foreign fetch is a counted miss (`fetch_corrupt`), never a
        crash.  Fetches are served from memory, not written through to
        disk: the local directory stays this host's own compile record."""
        self._remote_fetch = fetch_fn

    @property
    def platform(self) -> dict:
        """The platform facts this store's entries are valid for (copy;
        tuned-table keying reads `device_kind` from here)."""
        return dict(self._platform)

    def _note_io_error(self, op: str, path: str, exc: BaseException) -> None:
        """Count a disk-level failure (full disk, yanked NFS) that was
        downgraded to a plain cache miss.  One warning per store — a
        dying disk would otherwise flood the log at request rate."""
        self.io_errors += 1
        if not self._io_warned:
            self._io_warned = True
            log.warning(
                "compile-cache: disk %s failed (%s: %r); treating as a "
                "cache miss — further I/O errors counted in "
                "cache.stats['io_errors'] without logging", op, path, exc)

    # -- paths --------------------------------------------------------------
    def path_for(self, key: Tuple) -> str:
        name = hashlib.sha256(
            (self._fingerprint + "|" + canonical_key(key))
            .encode("utf-8")).hexdigest()[:40]
        return os.path.join(self.directory, name + _SUFFIX)

    # -- load ---------------------------------------------------------------
    def _evict_bad(self, path: str, reason) -> None:
        """Evict a bad entry (or count a sibling replica beating us to
        it) — the rewrite is clean either way."""
        if self._remove(path):
            self.corrupt_evicted += 1
            log.warning("compile-cache: evicting bad entry %s (%s)",
                        os.path.basename(path), reason)
        else:
            # a sibling replica evicted (or rewrote) it between our
            # read and remove — their problem resolved it; plain miss
            self.vanished += 1

    def _validate(self, raw: bytes, key: Tuple, payload_kind: str) -> bytes:
        """Blob from a container's raw bytes, raising on ANY defect —
        the one validation path for disk reads and remote fetches alike
        (the export format doubles as the cachesync wire format)."""
        if raw[:8] != _MAGIC:
            raise ValueError("bad magic")
        (hlen,) = struct.unpack(">I", raw[8:12])
        header = json.loads(raw[12:12 + hlen].decode("utf-8"))
        blob = raw[12 + hlen:]
        if header.get("platform_fingerprint") != self._fingerprint:
            # foreign artifact (filename hash should prevent this;
            # header check is defense in depth) — never load it
            raise ValueError("platform fingerprint mismatch")
        if header.get("key") != canonical_key(key):
            raise ValueError("key collision/mismatch")
        # pre-payload-field entries are all StableHLO programs
        if header.get("payload", "stablehlo") != payload_kind:
            raise ValueError("payload kind mismatch")
        if (header.get("blob_sha256")
                != hashlib.sha256(blob).hexdigest()):
            raise ValueError("blob checksum mismatch")
        return blob

    def _fetch_remote(self, key: Tuple, payload_kind: str):
        """Remote fallback for a locally-absent entry: ask the
        configured fetcher for the container by filename and re-validate
        on arrival.  A peer miss is `fetch_misses`, corrupt/foreign
        bytes are `fetch_corrupt` — both plain misses, never a crash."""
        if self._remote_fetch is None:
            return None
        name = os.path.basename(self.path_for(key))
        try:
            raw = self._remote_fetch(name)
        except Exception as e:  # noqa: BLE001 — fetcher contract says
            # never raise, but a broken peer must still read as a miss
            log.warning("compile-cache: remote fetch of %s failed (%s)",
                        name, e)
            self.fetch_misses += 1
            return None
        if raw is None:
            self.fetch_misses += 1
            return None
        try:
            blob = self._validate(raw, key, payload_kind)
        except Exception as e:  # noqa: BLE001 — corrupt fetch: a miss
            self.fetch_corrupt += 1
            log.warning("compile-cache: fetched entry %s failed "
                        "re-validation (%s); counted miss", name, e)
            return None
        self.fetch_hits += 1
        return blob

    def _load_payload(self, key: Tuple, payload_kind: str):
        """Checksum-validated raw blob for `key`, or None.

        None covers every miss flavor: absent file (after the remote
        fallback also missed), foreign platform, format bump,
        payload-kind mismatch, checksum mismatch — the last three also
        evict the entry so the rewrite is clean."""
        path = self.path_for(key)
        try:
            faults.fire("persist.read", path=path)
            with open(path, "rb") as f:
                raw = f.read()
        except (FileNotFoundError, IsADirectoryError):
            # locally absent: a cold host may still warm over the wire
            return self._fetch_remote(key, payload_kind)
        except OSError as e:
            self._note_io_error("read", path, e)
            return None
        try:
            blob = self._validate(raw, key, payload_kind)
        except Exception as e:  # noqa: BLE001 — any bad entry: evict
            self._evict_bad(path, e)
            return None
        # LRU touch: loads refresh recency so hot serve-path entries
        # outlive cold ones under the size cap
        try:
            os.utime(path, None)
        except OSError:
            pass
        return blob

    def load(self, key: Tuple):
        """Deserialized `jax.export.Exported` for `key`, or None (an
        undeserializable blob is evicted like any other bad entry)."""
        blob = self._load_payload(key, "stablehlo")
        if blob is None:
            return None
        try:
            from jax import export as jax_export

            return jax_export.deserialize(bytearray(blob))
        except Exception as e:  # noqa: BLE001 — any bad entry: evict
            self._evict_bad(self.path_for(key), e)
            return None

    def load_bytes(self, key: Tuple) -> Optional[bytes]:
        """Opaque byte artifact stored with `store_bytes`, or None —
        same validation, eviction, and LRU-touch path as programs."""
        return self._load_payload(key, "bytes")

    # -- store --------------------------------------------------------------
    def store(self, key: Tuple, exported) -> bool:
        """Atomically persist an `Exported` under `key`; returns success.

        tmpfile + `os.replace` in the same directory: readers never see
        a torn entry, concurrent writers of the same key converge on one
        winner with identical content."""
        try:
            blob = bytes(exported.serialize())
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            log.warning("compile-cache: failed to persist %s (%s)", key, e)
            return False
        return self._store_payload(key, blob, "stablehlo")

    def store_bytes(self, key: Tuple, blob: bytes) -> bool:
        """Atomically persist an opaque byte artifact (e.g. the int8
        quantized-weights blob that rides alongside a conf's exported
        programs) under the same header/checksum/atomic-replace/LRU
        machinery as program entries."""
        return self._store_payload(key, bytes(blob), "bytes")

    def _store_payload(self, key: Tuple, blob: bytes,
                       payload_kind: str) -> bool:
        path = self.path_for(key)
        try:
            header = json.dumps({
                "format": FORMAT_VERSION,
                "platform_fingerprint": self._fingerprint,
                "platform": self._platform,
                "key": canonical_key(key),
                "payload": payload_kind,
                "created": time.time(),
                "blob_sha256": hashlib.sha256(blob).hexdigest(),
            }, sort_keys=True).encode("utf-8")
            payload = _MAGIC + struct.pack(">I", len(header)) + header + blob
            # a 'corrupt' plan mutates the payload here, so the torn-write
            # → checksum-evict → recompile loop is testable end to end
            payload = faults.fire("persist.write", data=payload, path=path)
            fd, tmp = tempfile.mkstemp(dir=self.directory,
                                       suffix=_SUFFIX + ".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                self._remove(tmp)
                raise
        except OSError as e:  # full disk / yanked mount: a counted miss
            self._note_io_error("write", path, e)
            return False
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            log.warning("compile-cache: failed to persist %s (%s)", key, e)
            return False
        self.writes += 1
        self._enforce_cap(keep=path)
        return True

    def evict(self, key: Tuple) -> None:
        self._remove(self.path_for(key))

    # -- size cap -----------------------------------------------------------
    def _entries(self):
        """[(path, size, mtime)] for every cache entry currently on disk."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            p = os.path.join(self.directory, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((p, st.st_size, st.st_mtime))
        return out

    def total_bytes(self) -> int:
        return sum(size for _, size, _ in self._entries())

    def _enforce_cap(self, keep: Optional[str] = None) -> None:
        """Evict least-recently-used entries until the directory fits
        `max_bytes`.  The just-written entry (`keep`) is preferred even
        if it alone exceeds the cap — an empty cache is strictly worse.

        Concurrency: `entries` is a snapshot; a sibling replica may have
        evicted any of them already.  Either way the bytes are gone from
        the directory, so the freed size counts toward the cap, but only
        an ACTUAL removal counts as our eviction — a lost race is
        `vanished`, so two replicas never double-count one entry."""
        entries = self._entries()
        total = sum(size for _, size, _ in entries)
        if total <= self.max_bytes:
            return
        for p, size, _ in sorted(entries, key=lambda e: e[2]):
            if total <= self.max_bytes:
                break
            if p == keep:
                continue
            if self._remove(p):
                self.evictions += 1
                log.info("compile-cache: LRU-evicted %s (%d bytes)",
                         os.path.basename(p), size)
            else:
                self.vanished += 1
            total -= size

    @staticmethod
    def _remove(path: str) -> bool:
        """Best-effort unlink; True iff THIS process removed the file
        (False: already gone — typically a sibling replica's eviction)."""
        try:
            os.remove(path)
            return True
        except FileNotFoundError:
            return False
        except OSError:
            return False

    def __len__(self):
        return len(self._entries())

    def __repr__(self):
        return (f"PersistentProgramStore({self.directory!r}, "
                f"entries={len(self)}, bytes={self.total_bytes()}, "
                f"platform={self._fingerprint})")
