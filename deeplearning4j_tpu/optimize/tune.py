"""Search-based autotuning over the compiled-program space (ROADMAP 6).

TVM-style flow: for each tunable group, enumerate the registry's declared
search space, prune candidates whose analytic cost (the flops/bytes model
in `optimize/profiling.py`) is >= 2x the incumbent's *before* compiling
anything, then compile and measure the survivors as real programs through
the existing step-cache/infer-cache machinery — warm call outside the
timed region, min-of-rounds with an injectable clock.  Winners beat the
incumbent by a margin (default 2%) or the default stands, so a tuned
table is never slower than stock (the CPU no-slower criterion in
`bench_tune`).

The winning :class:`~deeplearning4j_tpu.optimize.tunables.TunedTable` is
keyed per (conf fingerprint, device kind) and persisted through the disk
compile cache's opaque-payload path, so replicas and future sessions
inherit it at `set_compile_cache` time with ``fresh_tunes == 0``.

Fault points: ``tune.measure`` (per candidate measurement — a failure
skips the candidate, counted, and the search completes) and ``tune.load``
(table read — a failure degrades to registry defaults with one warning;
serving never blocks on tuning).
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.optimize import tunables
from deeplearning4j_tpu.optimize.step_cache import conf_fingerprint
from deeplearning4j_tpu.reliability import faults

#: candidates whose analytic cost is >= this multiple of the incumbent's
#: are never compiled (TVM's "don't measure the obviously bad" pruning)
PRUNE_RATIO = 2.0

#: a challenger must beat the incumbent by this fraction or the default
#: stands — guarantees tuned >= default within noise (ties keep defaults)
MIN_GAIN = 0.02


class _Search:
    """Bookkeeping shared by every group: measured/pruned/failed counts
    plus the winning entries."""

    def __init__(self, rounds: int, clock):
        self.rounds = max(1, int(rounds))
        self.clock = clock
        self.entries = {}
        self.groups = {}
        self.candidates_measured = 0
        self.candidates_pruned = 0
        self.measure_failures = 0

    def measure(self, step) -> Optional[float]:
        """Min-of-rounds seconds for `step()`, or None when the
        measurement faulted (candidate skipped, search continues)."""
        try:
            faults.fire("tune.measure")
            step()  # warm: compile + first dispatch outside the timed region
            best = None
            for _ in range(self.rounds):
                t0 = self.clock()
                step()
                dt = self.clock() - t0
                best = dt if best is None or dt < best else best
            self.candidates_measured += 1
            return best
        except Exception:  # noqa: BLE001 — one bad candidate never ends a search
            self.measure_failures += 1
            return None

    def pick(self, group, key, candidates, default_value, run,
             throughput=None):
        """Measure `run(c)` for each candidate; record the winner under
        `key` iff it beats the default by MIN_GAIN.  `candidates` must
        include the default (the incumbent baseline).  `throughput(c)`
        converts each candidate's time to a rows/s-style figure for the
        report (higher is better); without it, lower seconds win."""
        timings = {}
        for cand in candidates:
            t = self.measure(lambda c=cand: run(c))
            if t is None:
                continue
            timings[cand] = t
        report = {"candidates": {repr(c): t for c, t in timings.items()},
                  "default": default_value, "winner": default_value}
        self.groups.setdefault(group, {})[key or group] = report
        if not timings:
            return default_value

        def score(c):
            # higher is better
            return throughput(c) / timings[c] if throughput \
                else 1.0 / timings[c]

        base = score(default_value) if default_value in timings else None
        winner = max(timings, key=score)
        if base is None or score(winner) > base * (1.0 + MIN_GAIN):
            report["winner"] = winner
            if winner != default_value:
                self.entries[key] = winner
        return report["winner"]


def _prune(search, tun, candidates, incumbent, **ctx):
    """Drop candidates whose analytic cost hint is >= PRUNE_RATIO x the
    incumbent's (never compiled); groups without hints keep everything."""
    if tun.cost_hint is None or incumbent is None:
        return list(candidates)
    base = tun.cost_hint(incumbent, **ctx)
    kept = []
    for c in candidates:
        if c != incumbent and tun.cost_hint(c, **ctx) >= PRUNE_RATIO * base:
            search.candidates_pruned += 1
        else:
            kept.append(c)
    return kept


def _attention_shapes(conf):
    """(seq, head_dim) pairs the conf's attention layers run at."""
    from deeplearning4j_tpu.nn.conf import LayerType
    seq = max([int(c.max_seq_len) for c in conf.confs
               if getattr(c, "max_seq_len", 0)] or [0])
    shapes = []
    for c in conf.confs:
        if c.layer_type == LayerType.ATTENTION and seq > 0:
            hd = int(c.n_in) // max(1, int(c.n_heads))
            if (seq, hd) not in shapes:
                shapes.append((seq, hd))
    return shapes


def _tune_attention(net, search, rng):
    """Per-(seq, head_dim) flash block sweep — fwd and bwd tables.

    Measured through the real Pallas entry point (interpret mode off-TPU,
    where candidates tie and the measured defaults stand — the table only
    moves on hardware where blocks genuinely differ)."""
    import jax

    from deeplearning4j_tpu.nd.pallas_kernels import (flash_attention,
                                                      pick_attention_blocks)
    for seq, hd in _attention_shapes(net.conf):
        q = np.asarray(rng.standard_normal((1, seq, 2, hd)), np.float32)
        k = np.asarray(rng.standard_normal((1, seq, 2, hd)), np.float32)
        v = np.asarray(rng.standard_normal((1, seq, 2, hd)), np.float32)
        qualifier = "%dx%d" % (seq, hd)
        for name, bwd in (("attention.block_fwd", False),
                          ("attention.block_bwd", True)):
            tun = tunables.REGISTRY[name]
            incumbent = pick_attention_blocks(seq, hd, bwd=bwd)
            cands = [c for c in tun.space
                     if seq % c[0] == 0 and seq % c[1] == 0]
            if incumbent not in cands:
                cands.insert(0, incumbent)
            cands = _prune(search, tun, cands, incumbent,
                           seq=seq, head_dim=hd)

            def run(c, bwd=bwd):
                if bwd:
                    fn = jax.grad(lambda a: flash_attention(
                        a, k, v, True, fused_bwd=True, block_q_bwd=c[0],
                        block_k_bwd=c[1]).sum())
                    jax.block_until_ready(fn(q))
                else:
                    jax.block_until_ready(
                        flash_attention(q, k, v, True, c[0], c[1]))

            search.pick("attention", "%s@%s" % (name, qualifier), cands,
                        incumbent, run)
            tunables.note_fresh()


def _serve_input(conf, rows, rng):
    """A well-formed serve batch for the conf's input layer: int token
    ids [rows, seq] for embedding-first models (seq capped by the
    learned positional table), float features [rows, n_in] otherwise."""
    from deeplearning4j_tpu.nn.conf import LayerType
    c0 = conf.confs[0]
    if c0.layer_type == LayerType.EMBEDDING:
        seq = int(getattr(c0, "max_seq_len", 0)) or 16
        return rng.integers(0, int(c0.n_in),
                            size=(rows, seq)).astype(np.int32)
    return np.asarray(rng.standard_normal((rows, int(c0.n_in))), np.float32)


def _tune_serve(net, search, rng):
    """Row-count sweep through the infer cache: rows/s at each candidate
    target picks `batcher.target_rows`; the measured ladder up to the
    winner becomes `infer.bucket_ladder` so warm processes pre-seed the
    same buckets.  Ascending order so each candidate compiles at its own
    exact bucket (`bucket_rows` grows on demand)."""
    tun = tunables.REGISTRY["batcher.target_rows"]
    incumbent = tun.default
    cands = sorted(set(tun.space) | {incumbent})

    def run(rows):
        np.asarray(net.output(_serve_input(net.conf, rows, rng)))

    winner = search.pick("serve", "batcher.target_rows", cands, incumbent,
                         run, throughput=lambda rows: float(rows))
    tunables.note_fresh()
    measured = search.groups["serve"]["batcher.target_rows"]["candidates"]
    ladder = tuple(c for c in cands if repr(c) in measured and c <= winner)
    if winner != incumbent and ladder:
        search.entries["infer.bucket_ladder"] = ladder


def _tune_decode(net, search, max_seq):
    """Slot-width sweep through the compiled decode step: tokens/s at
    each table width picks `decode.slots` (every live slot yields one
    token per step, so wider tables win until the step time grows
    faster than the width)."""
    from deeplearning4j_tpu.nn import decode as decode_mod
    try:
        decode_mod.check_generative(net.conf)
    except Exception:  # noqa: BLE001 — non-generative conf: nothing to tune
        return
    bound = decode_mod.positional_bound(net.conf)
    if bound:
        max_seq = min(int(max_seq), int(bound))
    if net.params is None:
        net.init()
    ic = net.infer_cache
    tun = tunables.REGISTRY["decode.slots"]
    incumbent = tun.default
    cands = sorted(set(tun.space) | {incumbent})

    def run(slots):
        import jax.numpy as jnp
        state = ic.init_decode_state(net.conf, slots, max_seq)
        tok = jnp.zeros((slots,), jnp.int32)
        pos = jnp.zeros((slots,), jnp.int32)
        keys = jnp.zeros((slots, 2), jnp.uint32)
        temps = jnp.zeros((slots,), jnp.float32)
        # decode donates its state buffers: thread the returned state
        for _ in range(4):
            tok, keys, state = ic.decode(net.conf, net.params, state,
                                         tok, pos, keys, temps)
            pos = pos + 1
        np.asarray(tok)

    search.pick("decode", "decode.slots", cands, incumbent, run,
                throughput=lambda slots: float(slots))
    tunables.note_fresh()
    _tune_decode_steps(net, search, max_seq)


def _tune_decode_steps(net, search, max_seq):
    """K sweep through the fused decode block: tokens/s at each
    steps-per-dispatch picks `decode.steps_per_dispatch` (each dispatch
    advances every slot K tokens and costs ONE host round-trip, so
    bigger K wins until per-step device time dominates the amortised
    host overhead)."""
    import jax
    import jax.numpy as jnp

    ic = net.infer_cache
    slots = 2
    tun = tunables.REGISTRY["decode.steps_per_dispatch"]
    incumbent = tun.default
    cands = sorted(k for k in set(tun.space) | {incumbent}
                   if k <= max_seq)

    def run(k):
        state = ic.init_decode_state(net.conf, slots, max_seq)
        tok = jnp.zeros((slots,), jnp.int32)
        pos = jnp.zeros((slots,), jnp.int32)
        keys = jnp.zeros((slots, 2), jnp.uint32)
        temps = jnp.zeros((slots,), jnp.float32)
        steps = 0
        while steps + k <= max_seq:
            rem = jnp.full((slots,), k, jnp.int32)
            _, tok, keys, state = ic.decode_multi(
                net.conf, net.params, state, tok, pos, keys, temps,
                rem, k)
            pos = pos + k
            steps += k
        jax.device_get(tok)

    # every candidate decodes (about) the same token count, so the
    # tokens-per-run numerator is the actual work done, not K itself
    search.pick("decode", "decode.steps_per_dispatch", cands, incumbent,
                run, throughput=lambda k: float(slots * (max_seq // k) * k))
    tunables.note_fresh()


def tune_model(net, groups: Sequence[str] = ("attention", "serve",
                                             "decode"),
               rounds: int = 3, seed: int = 0, clock=time.perf_counter,
               max_seq: int = 64) -> dict:
    """Search the registry's config space for `net` and return the report
    (winning entries + counters).  Deterministic under a fixed seed and
    an injected clock: candidate order is fixed and data comes from the
    seeded rng."""
    t0 = clock()
    if net.params is None:
        net.init()
    rng = np.random.default_rng(seed)
    search = _Search(rounds, clock)
    if "attention" in groups:
        _tune_attention(net, search, rng)
    if "serve" in groups:
        _tune_serve(net, search, rng)
    if "decode" in groups:
        _tune_decode(net, search, max_seq)
    fp = conf_fingerprint(net.conf)
    report = {
        "fingerprint": fp,
        "groups": search.groups,
        "entries": {k: v for k, v in sorted(search.entries.items())},
        "candidates_measured": search.candidates_measured,
        "candidates_pruned": search.candidates_pruned,
        "measure_failures": search.measure_failures,
        "rounds": search.rounds,
        "seed": int(seed),
        "tune_seconds": clock() - t0,
    }
    return report


def tune_and_store(net, store=None, force: bool = False, **kw) -> dict:
    """The `cli tune` entry point: inherit an existing valid table from
    the store (``fresh_tunes == 0``) unless `force`, else search, persist
    the winners, and install the table process-wide.  Returns the report
    with the `tuning` status block attached."""
    fp = conf_fingerprint(net.conf)
    kind = store.platform.get("device_kind", "none") if store is not None \
        else _device_kind()
    if store is not None and not force:
        existing = tunables.load_table(store, fp, kind)
        if existing is not None:
            tunables.install(existing, source="disk")
            return {
                "fingerprint": fp,
                "device_kind": kind,
                "entries": dict(existing.entries),
                "candidates_measured": 0,
                "candidates_pruned": 0,
                "measure_failures": 0,
                "tune_seconds": 0.0,
                "tuning": tunables.status(),
            }
    report = tune_model(net, **kw)
    table = tunables.TunedTable(report["entries"], device_kind=kind,
                                fingerprint=fp,
                                meta={"rounds": report["rounds"],
                                      "seed": report["seed"]})
    if store is not None:
        tunables.save_table(store, table)
    tunables.install(table, source="fresh")
    report["device_kind"] = kind
    report["tuning"] = tunables.status()
    return report


def _device_kind() -> str:
    from deeplearning4j_tpu.optimize.persist import platform_info
    return platform_info().get("device_kind", "none")
