"""Gradient adjustment (the updater chain).

Parity: reference `optimize/GradientAdjustment.java:159-226` — per-variable
AdaGrad with optional periodic reset, else plain lr scaling; momentum with a
scheduled `momentumAfter` map; L2 weight decay; unit-norm constraint.
(The reference also divides by batch size; here losses are already batch
means, so that scaling is built into the gradient itself.)

TPU-native design: a pure `(conf, iteration, grads, params, state) ->
(adjusted, state)` transform over pytrees — the functional equivalent of
optax transforms, kept self-contained so the solver loop can live entirely
inside one XLA program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class UpdaterState(NamedTuple):
    adagrad_hist: object   # pytree like params
    velocity: object       # pytree like params


def init_updater(params) -> UpdaterState:
    # two distinct zero trees: sharing one would alias buffers, which
    # breaks donation (same buffer donated twice) in jitted train steps
    return UpdaterState(
        adagrad_hist=jax.tree_util.tree_map(jnp.zeros_like, params),
        velocity=jax.tree_util.tree_map(jnp.zeros_like, params))


def _momentum_at(conf, iteration):
    """Scheduled momentum (parity: `momentumAfter` map)."""
    m = jnp.asarray(conf.momentum, jnp.float32)
    for it, mom in conf.momentum_after:
        m = jnp.where(iteration >= it, jnp.asarray(mom, jnp.float32), m)
    return m


def adjust_gradient(conf, iteration, grads, params, state: UpdaterState):
    """Apply the updater chain; returns (step_direction, new_state).

    The returned value is the *scaled step* (lr folded in), to be subtracted
    from params — matching how `GradientAdjustment` rewrites the raw gradient
    in place before the step function applies it.
    """
    eps = 1e-8
    lr = conf.lr

    # L2 weight decay on the raw gradient (before adaptive scaling)
    if conf.use_regularization and conf.l2:
        grads = jax.tree_util.tree_map(
            lambda g, p: g + conf.l2 * p.astype(g.dtype), grads, params)

    hist = state.adagrad_hist
    if conf.use_adagrad:
        new_hist = jax.tree_util.tree_map(lambda h, g: h + g * g, hist, grads)
        if conf.adagrad_reset_iterations > 0:
            resetting = (iteration % conf.adagrad_reset_iterations) == 0
            new_hist = jax.tree_util.tree_map(
                lambda h, g: jnp.where(resetting, g * g, h), new_hist, grads)
        scaled = jax.tree_util.tree_map(
            lambda g, h: lr * g / (jnp.sqrt(h) + eps), grads, new_hist)
        hist = new_hist
    else:
        scaled = jax.tree_util.tree_map(lambda g: lr * g, grads)

    mom = _momentum_at(conf, iteration)
    vel = jax.tree_util.tree_map(
        lambda v, s: mom.astype(s.dtype) * v + s, state.velocity, scaled)
    step = vel

    if conf.gradient_clip_norm > 0.0:
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                          for x in jax.tree_util.tree_leaves(step)))
        scale = jnp.minimum(1.0, conf.gradient_clip_norm / (gn + eps))
        step = jax.tree_util.tree_map(lambda x: x * scale.astype(x.dtype), step)

    if conf.constrain_gradient_to_unit_norm:
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                          for x in jax.tree_util.tree_leaves(step)))
        step = jax.tree_util.tree_map(
            lambda x: x / (gn + eps).astype(x.dtype), step)

    return step, UpdaterState(adagrad_hist=hist, velocity=vel)
