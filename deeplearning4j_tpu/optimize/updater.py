"""Gradient adjustment (the updater chain).

Parity: reference `optimize/GradientAdjustment.java:159-226` — per-variable
AdaGrad with optional periodic reset, else plain lr scaling; momentum with a
scheduled `momentumAfter` map; L2 weight decay; unit-norm constraint.
(The reference also divides by batch size; here losses are already batch
means, so that scaling is built into the gradient itself.)

TPU-native design: a pure `(conf, iteration, grads, params, state) ->
(adjusted, state)` transform over pytrees — the functional equivalent of
optax transforms, kept self-contained so the solver loop can live entirely
inside one XLA program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class UpdaterState(NamedTuple):
    adagrad_hist: object   # pytree like params
    velocity: object       # pytree like params


def init_updater(params) -> UpdaterState:
    # two distinct zero trees: sharing one would alias buffers, which
    # breaks donation (same buffer donated twice) in jitted train steps
    return UpdaterState(
        adagrad_hist=jax.tree_util.tree_map(jnp.zeros_like, params),
        velocity=jax.tree_util.tree_map(jnp.zeros_like, params))


def _momentum_at(conf, iteration):
    """Scheduled momentum (parity: `momentumAfter` map)."""
    m = jnp.asarray(conf.momentum, jnp.float32)
    for it, mom in conf.momentum_after:
        m = jnp.where(iteration >= it, jnp.asarray(mom, jnp.float32), m)
    return m


# -- flat-buffer (fused) layout ---------------------------------------------
#
# The tree_map chain above launches O(leaves x ops) small kernels per step
# (~30 tree_maps for a 2-block transformer).  `conf.fused_updater` runs the
# same chain over a few contiguous same-dtype buffers instead: every updater
# op is elementwise, so concatenating the leaves changes kernel *count*, not
# any computed bit.  The two global norms are the only reductions — those are
# computed per original leaf (slice + reshape to the leaf's shape) so the
# f32 reduction shapes and summation order match the tree path bitwise.

class FlatSpec(NamedTuple):
    treedef: object      # tree structure of the param pytree
    shapes: tuple        # per leaf, original shape
    leaf_slices: tuple   # per leaf: (group index, offset, size)
    group_dtypes: tuple  # per dtype group
    group_sizes: tuple


def make_flat_spec(params) -> FlatSpec:
    """Group param leaves by dtype into contiguous 1-D buffer layouts."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    group_of = {}   # dtype -> group index, first-seen order
    offsets = []
    slices = []
    for leaf in leaves:
        dt = jnp.asarray(leaf).dtype
        if dt not in group_of:
            group_of[dt] = len(group_of)
            offsets.append(0)
        g = group_of[dt]
        size = int(leaf.size)
        slices.append((g, offsets[g], size))
        offsets[g] += size
    return FlatSpec(treedef=treedef,
                    shapes=tuple(leaf.shape for leaf in leaves),
                    leaf_slices=tuple(slices),
                    group_dtypes=tuple(group_of),
                    group_sizes=tuple(offsets))


def flat_ravel(spec: FlatSpec, tree):
    """Pytree -> tuple of contiguous 1-D buffers (one per dtype group).

    Each leaf enters the buffer through an `optimization_barrier`: without
    it XLA fuses the reshape+concatenate into the leaf's PRODUCER, which
    re-vectorizes that producer over the flat iteration space — and
    vectorized transcendentals (sin/exp/tanh in a backward pass) are only
    ulp-reproducible within one loop shape, so raveled gradients would
    differ in their last bit from the tree path's (observed on CPU: a
    handful of boundary elements per leaf).  Barriered, the producer
    keeps the leaf-shaped loop the tree path compiles, and only the
    already-materialized bits are copied."""
    leaves = jax.tree_util.tree_leaves(tree)
    parts = [[] for _ in spec.group_sizes]
    for leaf, (g, _, _) in zip(leaves, spec.leaf_slices):
        parts[g].append(jnp.reshape(jax.lax.optimization_barrier(leaf),
                                    (-1,)))
    return tuple(p[0] if len(p) == 1 else jnp.concatenate(p)
                 for p in parts)


def flat_unravel(spec: FlatSpec, bufs):
    """Inverse of `flat_ravel` — slices are views XLA fuses into consumers."""
    leaves = [bufs[g][o:o + n].reshape(shape)
              for (g, o, n), shape in zip(spec.leaf_slices, spec.shapes)]
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def flat_norm(spec: FlatSpec, bufs):
    """sqrt of the global squared norm, reduced per ORIGINAL leaf shape so
    the result is bitwise-identical to the tree path's
    `sqrt(sum(jnp.sum(square(leaf)) for leaf in tree_leaves(t)))`.

    The optimization_barrier matters: without it XLA fuses the slice +
    reshape into the reduction and emits a strided accumulation whose f32
    summation order differs from a reduction over a materialized leaf by
    a few ulps (observed on CPU).  Barriered, the reduce sees the same
    contiguous leaf-shaped input as the tree path and the bits match."""
    return jnp.sqrt(sum(
        jnp.sum(jnp.square(
            jax.lax.optimization_barrier(bufs[g][o:o + n].reshape(shape))
            .astype(jnp.float32)))
        for (g, o, n), shape in zip(spec.leaf_slices, spec.shapes)))


def tree_norm(t):
    """sqrt of the summed per-leaf squared f32 norms (solver's norm form)."""
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in jax.tree_util.tree_leaves(t)))


def adjust_gradient(conf, iteration, grads, params, state: UpdaterState,
                    _norm_fn=tree_norm):
    """Apply the updater chain; returns (step_direction, new_state).

    The returned value is the *scaled step* (lr folded in), to be subtracted
    from params — matching how `GradientAdjustment` rewrites the raw gradient
    in place before the step function applies it.

    `conf.updater` selects the algorithm; "" keeps the reference chain
    (AdaGrad flag + scheduled momentum, `GradientAdjustment.java:159-226`),
    while adam / nesterov / rmsprop are parity-plus (the 2015 reference
    predates them).  Adam reuses the two state trees: velocity = first
    moment, adagrad_hist = second moment.

    Every op in the chain is elementwise over the pytree except the two
    global norms, so the same code body serves the fused flat-buffer path
    (`adjust_gradient_flat`), which only swaps `_norm_fn`.

    The entry barrier pins WHICH gradient bits the chain consumes: when a
    gradient has a cheap fused producer (elementwise tail of a backward
    pass), XLA likes to duplicate that producer into each updater
    consumer, and a duplicated transcendental re-vectorized over a
    different loop shape returns ulp-different values — so the chain
    would see gradient bits that differ from (and between!) its
    consumers.  The same goes for the mid-chain barriers on the updated
    moments and the exit barrier on the returned step.  Caveat: XLA is
    still free to drop a barrier late in its pipeline and re-duplicate
    (observed on CPU, where the flat-layout step fusion recomputes the
    moments inline), so across two *separately compiled* programs of
    different layouts the barriers reduce drift to isolated last-ulp
    elements rather than guaranteeing zero — see `adjust_gradient_auto`
    for how the parity claims are scoped per train path.
    """
    eps = 1e-8
    lr = conf.lr
    which = (getattr(conf, "updater", "") or "").lower()
    grads = jax.tree_util.tree_map(jax.lax.optimization_barrier, grads)

    # L2 weight decay on the raw gradient (before adaptive scaling)
    if conf.use_regularization and conf.l2:
        grads = jax.tree_util.tree_map(
            lambda g, p: g + conf.l2 * p.astype(g.dtype), grads, params)

    hist = state.adagrad_hist
    vel = state.velocity
    if which == "adam":
        b1, b2 = conf.adam_beta1, conf.adam_beta2
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        vel = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g, vel, grads)
        hist = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1 - b2) * g * g, hist, grads)
        # pin the moment bits: vel/hist are both outputs and step inputs,
        # and an unpinned multiply-add would be duplicated into the step
        # fusion where contraction (FMA) can round differently per layout
        vel, hist = jax.lax.optimization_barrier((vel, hist))
        c1 = 1.0 - jnp.power(b1, t)
        c2 = 1.0 - jnp.power(b2, t)
        step = jax.tree_util.tree_map(
            lambda m, v: lr * (m / c1.astype(m.dtype))
            / (jnp.sqrt(v / c2.astype(v.dtype)) + conf.adam_eps),
            vel, hist)
    elif which == "rmsprop":
        rho = conf.rmsprop_decay
        hist = jax.tree_util.tree_map(
            lambda h, g: rho * h + (1 - rho) * g * g, hist, grads)
        hist = jax.lax.optimization_barrier(hist)
        step = jax.tree_util.tree_map(
            lambda g, h: lr * g / (jnp.sqrt(h) + eps), grads, hist)
    elif which == "nesterov":
        mom = _momentum_at(conf, iteration)
        vel = jax.tree_util.tree_map(
            lambda v, g: mom.astype(g.dtype) * v + g, vel, grads)
        vel = jax.lax.optimization_barrier(vel)
        # look-ahead step: lr * (g + mu * v_new)
        step = jax.tree_util.tree_map(
            lambda g, v: lr * (g + mom.astype(g.dtype) * v), grads, vel)
    elif which in ("", "sgd", "adagrad"):
        # legacy reference chain; "sgd"/"adagrad" force the flag either way
        use_adagrad = (conf.use_adagrad if which == ""
                       else which == "adagrad")
        if use_adagrad:
            new_hist = jax.tree_util.tree_map(lambda h, g: h + g * g, hist,
                                              grads)
            if conf.adagrad_reset_iterations > 0:
                resetting = (iteration % conf.adagrad_reset_iterations) == 0
                new_hist = jax.tree_util.tree_map(
                    lambda h, g: jnp.where(resetting, g * g, h), new_hist,
                    grads)
            new_hist = jax.lax.optimization_barrier(new_hist)
            scaled = jax.tree_util.tree_map(
                lambda g, h: lr * g / (jnp.sqrt(h) + eps), grads, new_hist)
            hist = new_hist
        else:
            scaled = jax.tree_util.tree_map(lambda g: lr * g, grads)

        mom = _momentum_at(conf, iteration)
        vel = jax.tree_util.tree_map(
            lambda v, s: mom.astype(s.dtype) * v + s, vel, scaled)
        vel = jax.lax.optimization_barrier(vel)
        step = vel
    else:
        raise ValueError(
            f"unknown updater {which!r}: expected one of "
            "'' | sgd | adagrad | nesterov | adam | rmsprop")

    if conf.gradient_clip_norm > 0.0:
        gn = _norm_fn(step)
        scale = jnp.minimum(1.0, conf.gradient_clip_norm / (gn + eps))
        step = jax.tree_util.tree_map(lambda x: x * scale.astype(x.dtype), step)

    if conf.constrain_gradient_to_unit_norm:
        gn = _norm_fn(step)
        step = jax.tree_util.tree_map(
            lambda x: x / (gn + eps).astype(x.dtype), step)

    # exit barrier, same reason as the entry one: unbarriered, the chain's
    # trailing multiply fuses into the caller's `params - step` and may
    # contract to an FMA there (rounding once) while the other layout
    # rounds twice
    step = jax.tree_util.tree_map(jax.lax.optimization_barrier, step)
    return step, UpdaterState(adagrad_hist=hist, velocity=vel)


def adjust_gradient_flat(conf, iteration, grad_bufs, param_bufs,
                         state: UpdaterState, spec: FlatSpec):
    """Fused updater chain over `flat_ravel`ed buffers.

    `grad_bufs`/`param_bufs` and the state fields are tuples of contiguous
    same-dtype 1-D buffers; the whole chain then runs as a handful of
    full-width kernels instead of O(leaves x ops) small ones.  Elementwise
    math on a concatenation is bitwise-identical per element, and the norms
    reduce per original leaf via `flat_norm`, so the result unravels to
    exactly the tree path's bits (parity-tested for all five algorithms).
    """
    return adjust_gradient(conf, iteration, grad_bufs, param_bufs, state,
                           _norm_fn=lambda t: flat_norm(spec, t))


def adjust_gradient_auto(conf, iteration, grads, params,
                         state: UpdaterState):
    """`adjust_gradient` that honours `conf.fused_updater`, keeping the
    tree-shaped calling convention.

    When the flag is set, grads/params/state are flat-raveled at the
    boundary, the chain runs fused, and the step + new state unravel
    back to trees, so train-step code (the dp / sharded steps) can stay
    layout-agnostic.  Parity scope: within one compiled program the two
    layouts are bitwise-identical (`test_fused_updater_bitwise`), and so
    is the whole single-device solver path end to end
    (`test_end_to_end_flag_combos_bitwise`).  Across *separately
    compiled* tree- vs flat-layout programs — the dp train step — XLA
    may duplicate a producer into a consumer fusion with different FMA
    contraction, leaving isolated last-ulp differences the barriers in
    `adjust_gradient` cannot pin; the dp parity test therefore asserts
    ≤1-ulp closeness there, not equality.  NOTE: callers whose updater
    state is mesh-sharded (ZeRO-1, local-SGD) keep the tree path —
    raveling would regather the shards."""
    if not getattr(conf, "fused_updater", False):
        return adjust_gradient(conf, iteration, grads, params, state)
    spec = make_flat_spec(params)
    fstate = UpdaterState(
        adagrad_hist=flat_ravel(spec, state.adagrad_hist),
        velocity=flat_ravel(spec, state.velocity))
    adj, new = adjust_gradient_flat(conf, iteration,
                                    flat_ravel(spec, grads),
                                    flat_ravel(spec, params), fstate, spec)
    return (flat_unravel(spec, adj),
            UpdaterState(adagrad_hist=flat_unravel(spec, new.adagrad_hist),
                         velocity=flat_unravel(spec, new.velocity)))
