"""Gradient adjustment (the updater chain).

Parity: reference `optimize/GradientAdjustment.java:159-226` — per-variable
AdaGrad with optional periodic reset, else plain lr scaling; momentum with a
scheduled `momentumAfter` map; L2 weight decay; unit-norm constraint.
(The reference also divides by batch size; here losses are already batch
means, so that scaling is built into the gradient itself.)

TPU-native design: a pure `(conf, iteration, grads, params, state) ->
(adjusted, state)` transform over pytrees — the functional equivalent of
optax transforms, kept self-contained so the solver loop can live entirely
inside one XLA program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class UpdaterState(NamedTuple):
    adagrad_hist: object   # pytree like params
    velocity: object       # pytree like params


def init_updater(params) -> UpdaterState:
    # two distinct zero trees: sharing one would alias buffers, which
    # breaks donation (same buffer donated twice) in jitted train steps
    return UpdaterState(
        adagrad_hist=jax.tree_util.tree_map(jnp.zeros_like, params),
        velocity=jax.tree_util.tree_map(jnp.zeros_like, params))


def _momentum_at(conf, iteration):
    """Scheduled momentum (parity: `momentumAfter` map)."""
    m = jnp.asarray(conf.momentum, jnp.float32)
    for it, mom in conf.momentum_after:
        m = jnp.where(iteration >= it, jnp.asarray(mom, jnp.float32), m)
    return m


def adjust_gradient(conf, iteration, grads, params, state: UpdaterState):
    """Apply the updater chain; returns (step_direction, new_state).

    The returned value is the *scaled step* (lr folded in), to be subtracted
    from params — matching how `GradientAdjustment` rewrites the raw gradient
    in place before the step function applies it.

    `conf.updater` selects the algorithm; "" keeps the reference chain
    (AdaGrad flag + scheduled momentum, `GradientAdjustment.java:159-226`),
    while adam / nesterov / rmsprop are parity-plus (the 2015 reference
    predates them).  Adam reuses the two state trees: velocity = first
    moment, adagrad_hist = second moment.
    """
    eps = 1e-8
    lr = conf.lr
    which = (getattr(conf, "updater", "") or "").lower()

    # L2 weight decay on the raw gradient (before adaptive scaling)
    if conf.use_regularization and conf.l2:
        grads = jax.tree_util.tree_map(
            lambda g, p: g + conf.l2 * p.astype(g.dtype), grads, params)

    hist = state.adagrad_hist
    vel = state.velocity
    if which == "adam":
        b1, b2 = conf.adam_beta1, conf.adam_beta2
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        vel = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g, vel, grads)
        hist = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1 - b2) * g * g, hist, grads)
        c1 = 1.0 - jnp.power(b1, t)
        c2 = 1.0 - jnp.power(b2, t)
        step = jax.tree_util.tree_map(
            lambda m, v: lr * (m / c1.astype(m.dtype))
            / (jnp.sqrt(v / c2.astype(v.dtype)) + conf.adam_eps),
            vel, hist)
    elif which == "rmsprop":
        rho = conf.rmsprop_decay
        hist = jax.tree_util.tree_map(
            lambda h, g: rho * h + (1 - rho) * g * g, hist, grads)
        step = jax.tree_util.tree_map(
            lambda g, h: lr * g / (jnp.sqrt(h) + eps), grads, hist)
    elif which == "nesterov":
        mom = _momentum_at(conf, iteration)
        vel = jax.tree_util.tree_map(
            lambda v, g: mom.astype(g.dtype) * v + g, vel, grads)
        # look-ahead step: lr * (g + mu * v_new)
        step = jax.tree_util.tree_map(
            lambda g, v: lr * (g + mom.astype(g.dtype) * v), grads, vel)
    elif which in ("", "sgd", "adagrad"):
        # legacy reference chain; "sgd"/"adagrad" force the flag either way
        use_adagrad = (conf.use_adagrad if which == ""
                       else which == "adagrad")
        if use_adagrad:
            new_hist = jax.tree_util.tree_map(lambda h, g: h + g * g, hist,
                                              grads)
            if conf.adagrad_reset_iterations > 0:
                resetting = (iteration % conf.adagrad_reset_iterations) == 0
                new_hist = jax.tree_util.tree_map(
                    lambda h, g: jnp.where(resetting, g * g, h), new_hist,
                    grads)
            scaled = jax.tree_util.tree_map(
                lambda g, h: lr * g / (jnp.sqrt(h) + eps), grads, new_hist)
            hist = new_hist
        else:
            scaled = jax.tree_util.tree_map(lambda g: lr * g, grads)

        mom = _momentum_at(conf, iteration)
        vel = jax.tree_util.tree_map(
            lambda v, s: mom.astype(s.dtype) * v + s, vel, scaled)
        step = vel
    else:
        raise ValueError(
            f"unknown updater {which!r}: expected one of "
            "'' | sgd | adagrad | nesterov | adam | rmsprop")

    if conf.gradient_clip_norm > 0.0:
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                          for x in jax.tree_util.tree_leaves(step)))
        scale = jnp.minimum(1.0, conf.gradient_clip_norm / (gn + eps))
        step = jax.tree_util.tree_map(lambda x: x * scale.astype(x.dtype), step)

    if conf.constrain_gradient_to_unit_norm:
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                          for x in jax.tree_util.tree_leaves(step)))
        step = jax.tree_util.tree_map(
            lambda x: x / (gn + eps).astype(x.dtype), step)

    return step, UpdaterState(adagrad_hist=hist, velocity=vel)
