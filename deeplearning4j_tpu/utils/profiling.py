"""Profiling, tracing, and metrics — §5 aux-subsystem parity, TPU-native.

The reference's observability is wall-clock job timing
(`WorkerActor.java:199-203` "Job took X ms"), iteration listeners
(`ScoreIterationListener.java:43-46`), named counters in the state tracker
(`StateTracker.increment/count`, `StateTracker.java:54-56`), and the YARN
`metricsReport(map<string,long>)` RPC (`IterativeReduceService.java:28`).

TPU-native upgrade: the same surface plus real XLA traces via
`jax.profiler` (start/stop trace + annotations viewable in
TensorBoard/Perfetto) and a throughput meter that blocks on device results
so timings measure compute, not dispatch.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Dict, Optional

log = logging.getLogger("deeplearning4j_tpu")


class StepTimer:
    """Wall-clock step timing ("Job took X ms" parity) with summary stats."""

    def __init__(self, name: str = "step", log_each: bool = False):
        self.name = name
        self.log_each = log_each
        self.times_ms = []
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt_ms = (time.perf_counter() - self._t0) * 1e3
        self.times_ms.append(dt_ms)
        if self.log_each:
            log.info("%s took %.2f ms", self.name, dt_ms)
        return False

    @property
    def mean_ms(self) -> float:
        return sum(self.times_ms) / len(self.times_ms) if self.times_ms else 0.0

    def summary(self) -> Dict[str, float]:
        ts = sorted(self.times_ms)
        if not ts:
            return {"count": 0}
        return {
            "count": len(ts),
            "mean_ms": self.mean_ms,
            "min_ms": ts[0],
            "p50_ms": ts[len(ts) // 2],
            "max_ms": ts[-1],
        }


class ThroughputMeter:
    """samples/sec over device-blocking steps (timings measure compute).

    The with-body registers its device result via `block(...)` so the
    timer can synchronize on work created *inside* the block (JAX dispatch
    is async; without the sync only dispatch latency would be measured):

        with meter.measure(batch) as m:
            m.block(step(params, x))
    """

    class _Measurement:
        def __init__(self):
            self._results = []

        def block(self, result):
            """Register a device value to synchronize on; returns it."""
            self._results.append(result)
            return result

    def __init__(self):
        self.samples = 0
        self.seconds = 0.0

    @contextlib.contextmanager
    def measure(self, batch_size: int):
        m = self._Measurement()
        t0 = time.perf_counter()
        yield m
        if m._results:
            import jax

            jax.block_until_ready(m._results)
        self.seconds += time.perf_counter() - t0
        self.samples += batch_size

    @property
    def samples_per_sec(self) -> float:
        return self.samples / self.seconds if self.seconds > 0 else 0.0


class Tracer:
    """XLA trace capture (TensorBoard/Perfetto) + named annotations."""

    def __init__(self, trace_dir: str = "/tmp/dl4j_tpu_trace"):
        self.trace_dir = trace_dir
        self._active = False

    def start(self) -> None:
        import jax

        if not self._active:
            jax.profiler.start_trace(self.trace_dir)
            self._active = True

    def stop(self) -> None:
        import jax

        if self._active:
            jax.profiler.stop_trace()
            self._active = False

    @contextlib.contextmanager
    def trace(self):
        self.start()
        try:
            yield self
        finally:
            self.stop()

    @staticmethod
    def annotate(name: str):
        """Named region visible in the trace viewer."""
        import jax

        return jax.profiler.TraceAnnotation(name)


class MetricsRegistry:
    """Named counters + gauges (StateTracker.increment / YARN
    metricsReport parity), thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}

    def increment(self, name: str, by: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + by

    def count(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def report(self) -> Dict[str, float]:
        """metricsReport(map<string,long>) parity — one flat dict."""
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            return out


METRICS = MetricsRegistry()  # process-global default registry


class TimingIterationListener:
    """IterationListener recording inter-iteration wall time into METRICS."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or METRICS
        self._last: Optional[float] = None

    def iteration_done(self, model, iteration: int, score: float) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self.registry.increment("iteration_ms_total",
                                    (now - self._last) * 1e3)
        self.registry.increment("iterations")
        self.registry.gauge("last_score", score)
        self._last = now
