"""Object serialization helpers.

Parity: reference `util/SerializationUtils.java` — one-call save/load of
models and intermediate state (the reference uses Java serialization; here
pickle with atomic writes). Structured training checkpoints (params +
updater + step) live in `parallel/checkpoint.py`; this module is the
generic small-object path (vocab caches, iterators, host-side state).
"""

from __future__ import annotations

import os
import pickle
from typing import Any


def save_object(obj: Any, path: str) -> None:
    """Atomically pickle obj to path (`SerializationUtils.saveObject`)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)


def load_object(path: str) -> Any:
    """Unpickle from path (`SerializationUtils.readObject`)."""
    with open(path, "rb") as f:
        return pickle.load(f)
