"""Collection utilities.

Parity: reference `util/MultiDimensionalMap.java`/`MultiDimensionalSet`,
`util/Index.java` (word index), and the vendored Berkeley NLP collections
(`berkeley/Counter.java`, `berkeley/CounterMap.java`) the NLP stack uses
for vocab statistics.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Generic, Hashable, Iterator, List, Optional, Tuple, TypeVar

K = TypeVar("K", bound=Hashable)
K2 = TypeVar("K2", bound=Hashable)
V = TypeVar("V")


class Counter(Generic[K]):
    """Real-valued counter with normalize/argmax (`berkeley/Counter`)."""

    def __init__(self):
        self._counts: Dict[K, float] = defaultdict(float)

    def increment_count(self, key: K, amount: float = 1.0) -> None:
        self._counts[key] += amount

    def set_count(self, key: K, value: float) -> None:
        self._counts[key] = value

    def get_count(self, key: K) -> float:
        return self._counts.get(key, 0.0)

    def total_count(self) -> float:
        return sum(self._counts.values())

    def normalize(self) -> None:
        total = self.total_count()
        if total != 0:
            for k in self._counts:
                self._counts[k] /= total

    def arg_max(self) -> Optional[K]:
        if not self._counts:
            return None
        return max(self._counts, key=self._counts.get)

    def remove_key(self, key: K) -> None:
        self._counts.pop(key, None)

    def keys_sorted_by_count(self, descending: bool = True) -> List[K]:
        return sorted(self._counts, key=self._counts.get,
                      reverse=descending)

    def key_set(self):
        return self._counts.keys()

    def items(self):
        return self._counts.items()

    def __len__(self) -> int:
        return len(self._counts)

    def __contains__(self, key: K) -> bool:
        return key in self._counts


class CounterMap(Generic[K, K2]):
    """Two-level counter: key -> Counter (`berkeley/CounterMap`)."""

    def __init__(self):
        self._maps: Dict[K, Counter] = {}

    def increment_count(self, key: K, sub: K2, amount: float = 1.0) -> None:
        self.get_counter(key).increment_count(sub, amount)

    def get_count(self, key: K, sub: K2) -> float:
        c = self._maps.get(key)
        return 0.0 if c is None else c.get_count(sub)

    def get_counter(self, key: K) -> Counter:
        if key not in self._maps:
            self._maps[key] = Counter()
        return self._maps[key]

    def total_count(self) -> float:
        return sum(c.total_count() for c in self._maps.values())

    def normalize(self) -> None:
        for c in self._maps.values():
            c.normalize()

    def key_set(self):
        return self._maps.keys()

    def __len__(self) -> int:
        return len(self._maps)


class MultiDimensionalMap(Generic[K, K2, V]):
    """Map keyed by a (first, second) pair (`util/MultiDimensionalMap`)."""

    def __init__(self):
        self._backing: Dict[Tuple[K, K2], V] = {}

    def put(self, first: K, second: K2, value: V) -> None:
        self._backing[(first, second)] = value

    def get(self, first: K, second: K2, default: Optional[V] = None):
        return self._backing.get((first, second), default)

    def contains(self, first: K, second: K2) -> bool:
        return (first, second) in self._backing

    def remove(self, first: K, second: K2) -> None:
        self._backing.pop((first, second), None)

    def values(self):
        return self._backing.values()

    def entry_set(self):
        return self._backing.items()

    def __len__(self) -> int:
        return len(self._backing)


class Index:
    """Bidirectional word <-> id index (`util/Index.java`)."""

    def __init__(self):
        self._objects: List = []
        self._indexes: Dict = {}

    def add(self, obj) -> int:
        if obj in self._indexes:
            return self._indexes[obj]
        self._indexes[obj] = len(self._objects)
        self._objects.append(obj)
        return len(self._objects) - 1

    def index_of(self, obj) -> int:
        return self._indexes.get(obj, -1)

    def get(self, i: int):
        return self._objects[i]

    def __len__(self) -> int:
        return len(self._objects)

    def __iter__(self) -> Iterator:
        return iter(self._objects)
