"""Math/stats helpers.

Parity: reference `util/MathUtils.java` (1,293 LoC) — the subset actually
used elsewhere in the reference (normalization, entropy/information gain,
correlation, distances, rounding, sampling odds) plus the
`berkeley/SloppyMath.java` log-space helpers. Vectorized numpy throughout;
anything hot enough for a device belongs in `nd/ops.py` instead.
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np


def normalize(val: float, min_v: float, max_v: float) -> float:
    """Squash val from [min, max] into [0, 1] (`MathUtils.normalize`)."""
    if max_v == min_v:
        return 0.0
    return (val - min_v) / (max_v - min_v)


def clamp(val: float, lo: float, hi: float) -> float:
    return max(lo, min(hi, val))


def round_to_n_decimals(x: float, n: int) -> float:
    return float(np.round(x, n))


def entropy(probs: Sequence[float]) -> float:
    """Shannon entropy in nats over a probability vector."""
    p = np.asarray(probs, np.float64)
    p = p[p > 0]
    return float(-(p * np.log(p)).sum())


def information_gain(parent: Sequence[float],
                     children: Sequence[Sequence[float]],
                     weights: Sequence[float]) -> float:
    """Entropy(parent) - sum_i w_i * Entropy(child_i)."""
    return entropy(parent) - sum(
        w * entropy(c) for w, c in zip(weights, children))


def ssum(x: Sequence[float]) -> float:
    return float(np.sum(np.asarray(x, np.float64)))


def sum_of_squares(x: Sequence[float]) -> float:
    a = np.asarray(x, np.float64)
    return float((a * a).sum())


def mean(x: Sequence[float]) -> float:
    return float(np.mean(np.asarray(x, np.float64)))


def variance(x: Sequence[float]) -> float:
    """Sample variance (n-1 denominator, `MathUtils.variance` parity)."""
    a = np.asarray(x, np.float64)
    if len(a) < 2:
        return 0.0
    return float(a.var(ddof=1))


def correlation(a: Sequence[float], b: Sequence[float]) -> float:
    """Pearson correlation coefficient."""
    return float(np.corrcoef(np.asarray(a, np.float64),
                             np.asarray(b, np.float64))[0, 1])


def euclidean_distance(a, b) -> float:
    return float(np.linalg.norm(np.asarray(a, np.float64) -
                                np.asarray(b, np.float64)))


def manhattan_distance(a, b) -> float:
    return float(np.abs(np.asarray(a, np.float64) -
                        np.asarray(b, np.float64)).sum())


def sigmoid(x: float) -> float:
    return 1.0 / (1.0 + math.exp(-x))


def log_add(log_a: float, log_b: float) -> float:
    """log(exp(a) + exp(b)) without overflow (`SloppyMath.logAdd`)."""
    if log_a == -np.inf:
        return log_b
    if log_b == -np.inf:
        return log_a
    m = max(log_a, log_b)
    return m + math.log(math.exp(log_a - m) + math.exp(log_b - m))


def log_sum(log_values: Sequence[float]) -> float:
    a = np.asarray(log_values, np.float64)
    if len(a) == 0:
        return -np.inf
    m = a.max()
    if m == -np.inf:
        return -np.inf
    return float(m + np.log(np.exp(a - m).sum()))


def bernoullis(success_prob: float, trials: int, successes: int) -> float:
    """Binomial pmf P(successes | trials, p) (`MathUtils.bernoullis`)."""
    return float(math.comb(trials, successes) *
                 success_prob ** successes *
                 (1 - success_prob) ** (trials - successes))


def discretize(value: float, lo: float, hi: float, bins: int) -> int:
    """Map value in [lo, hi] to a bin index (`MathUtils.discretize`)."""
    if hi == lo:
        return 0
    frac = (value - lo) / (hi - lo)
    return int(clamp(math.floor(frac * bins), 0, bins - 1))


def next_power_of_2(n: int) -> int:
    return 1 if n <= 1 else 2 ** math.ceil(math.log2(n))


def xor_sample(shape, rng: np.random.RandomState):
    """XOR toy dataset (`MathUtils.xorData` parity): returns (x, y)."""
    x = rng.randint(0, 2, shape).astype(np.float32)
    y = (x.sum(axis=-1) % 2).astype(np.float32)
    return x, y
