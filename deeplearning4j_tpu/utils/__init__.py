"""Shared utilities.

Parity: reference `util/` (28 files / 6,000 LoC — `MathUtils.java`,
`SerializationUtils.java`, `DiskBasedQueue.java`, `MultiDimensionalMap`,
`Viterbi.java`, `TimeSeriesUtils`, `StringGrid`/`FingerPrintKeyer`) and the
vendored `berkeley/` collections (`Counter`, `CounterMap`, `Pair`,
`SloppyMath`).
"""

from deeplearning4j_tpu.utils.collections import (
    Counter, CounterMap, Index, MultiDimensionalMap)
from deeplearning4j_tpu.utils.disk_queue import DiskBasedQueue
from deeplearning4j_tpu.utils.serialization import (
    load_object, save_object)
from deeplearning4j_tpu.utils.viterbi import Viterbi

__all__ = [
    "Counter", "CounterMap", "Index", "MultiDimensionalMap",
    "DiskBasedQueue", "load_object", "save_object", "Viterbi",
]
