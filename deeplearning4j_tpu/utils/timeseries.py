"""Time-series helpers.

Parity: reference `util/TimeSeriesUtils.java` and
`util/MovingWindowMatrix.java` — lagged matrices and sliding windows over
a sequence. Vectorized via stride tricks so downstream batching feeds the
MXU with one contiguous array (no per-window Python loop).
"""

from __future__ import annotations

import numpy as np


def moving_window_matrix(x: np.ndarray, window: int,
                         add_rotate: bool = False) -> np.ndarray:
    """All contiguous windows of length `window` over flat x ->
    (n_windows, window). With add_rotate, also append the windows of the
    circularly-rotated sequence (`MovingWindowMatrix` parity)."""
    x = np.asarray(x).ravel()
    if window > len(x):
        raise ValueError(f"window {window} > sequence length {len(x)}")
    out = np.lib.stride_tricks.sliding_window_view(x, window).copy()
    if add_rotate:
        rot = np.roll(x, -1)
        out = np.vstack(
            [out, np.lib.stride_tricks.sliding_window_view(rot, window)])
    return out


def lagged(x: np.ndarray, lag: int) -> np.ndarray:
    """(T,) -> (T-lag, lag+1) matrix of [x_t, x_{t-1}, ..., x_{t-lag}]
    (`TimeSeriesUtils.getTimeSeries` style lag embedding)."""
    x = np.asarray(x).ravel()
    if lag >= len(x):
        raise ValueError(f"lag {lag} >= sequence length {len(x)}")
    win = np.lib.stride_tricks.sliding_window_view(x, lag + 1)
    return win[:, ::-1].copy()


def difference(x: np.ndarray, order: int = 1) -> np.ndarray:
    return np.diff(np.asarray(x).ravel(), n=order)
