"""Archive extraction helpers.

Parity: reference `util/ArchiveUtils.java` — unpack .tar.gz/.tgz/.zip/.gz
into a target directory (used by the dataset downloaders).
"""

from __future__ import annotations

import gzip
import os
import shutil
import tarfile
import zipfile


def unzip_file_to(archive_path: str, dest_dir: str) -> None:
    """Extract any supported archive into dest_dir
    (`ArchiveUtils.unzipFileTo`)."""
    os.makedirs(dest_dir, exist_ok=True)
    if archive_path.endswith((".tar.gz", ".tgz", ".tar")):
        mode = "r:gz" if archive_path.endswith(("gz",)) else "r"
        with tarfile.open(archive_path, mode) as t:
            t.extractall(dest_dir, filter="data")
    elif archive_path.endswith(".zip"):
        with zipfile.ZipFile(archive_path) as z:
            z.extractall(dest_dir)
    elif archive_path.endswith(".gz"):
        out = os.path.join(
            dest_dir, os.path.basename(archive_path)[:-3])
        with gzip.open(archive_path, "rb") as src, open(out, "wb") as dst:
            shutil.copyfileobj(src, dst)
    else:
        raise ValueError(f"unsupported archive format: {archive_path}")
