"""Thread-pool map/foreach helper.

Parity: reference `parallel/Parallelization.java` — run a collection of
tasks (`runInParallel`) or apply a function to every item
(`iterateInParallel` with `RunnableWithParams`) on a bounded pool.  Host-
side only: device work goes through vmap/pmap/shard_map, but data prep,
IO fan-out, and coordinator plumbing still want a simple parallel map.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, List, Optional, Sequence, TypeVar

E = TypeVar("E")
R = TypeVar("R")


def run_in_parallel(tasks: Iterable[Callable[[], R]],
                    max_workers: Optional[int] = None) -> List[R]:
    """Run zero-arg callables on a pool sized to the CPU count
    (`Parallelization.runInParallel`); blocks until all complete and
    returns their results in task order.  The first raised exception
    propagates after the pool drains."""
    tasks = list(tasks)
    if not tasks:
        return []
    workers = max_workers or min(len(tasks), os.cpu_count() or 1)
    with ThreadPoolExecutor(max_workers=workers) as pool:
        return [f.result() for f in [pool.submit(t) for t in tasks]]


def iterate_in_parallel(items: Sequence[E], fn: Callable[[E], R],
                        max_workers: Optional[int] = None) -> List[R]:
    """Apply `fn` to every item in parallel
    (`Parallelization.iterateInParallel` / RunnableWithParams), returning
    results in item order."""
    return run_in_parallel([lambda it=it: fn(it) for it in items],
                           max_workers=max_workers)
