"""String table clustering/dedup.

Parity: reference `util/StringGrid.java` (row/column string table with
fingerprint-based duplicate clustering) and `util/FingerPrintKeyer.java`
(OpenRefine-style key collision method: lowercase, strip punctuation,
unique sorted tokens).
"""

from __future__ import annotations

import re
import string
from collections import defaultdict
from typing import Dict, List

_PUNCT = re.compile("[" + re.escape(string.punctuation) + "]")


def fingerprint(s: str) -> str:
    """Canonical key: trim, lowercase, strip punctuation, unique sorted
    whitespace-split tokens re-joined (`FingerPrintKeyer.key`)."""
    s = _PUNCT.sub("", s.strip().lower())
    return " ".join(sorted(set(s.split())))


class StringCluster(dict):
    """`util/StringCluster.java` parity: {fingerprint -> {variant ->
    count}} over a list of strings, with clusters ordered by size and a
    canonical (most frequent) variant per cluster."""

    def __init__(self, strings: List[str] = ()):
        super().__init__()
        for s in strings:
            self.add(s)

    def add(self, s: str) -> None:
        m = self.setdefault(fingerprint(s), {})
        m[s] = m.get(s, 0) + 1

    def clusters(self) -> List[Dict[str, int]]:
        """Variant maps (copies — mutating them cannot corrupt this
        cluster), largest cluster first (StringCluster.getClusters +
        sort)."""
        return [dict(m) for m in
                sorted(self.values(), key=lambda m: -sum(m.values()))]

    def canonical(self, s: str) -> str:
        """The most frequent variant in s's cluster (ties: lexical)."""
        m = self.get(fingerprint(s))
        if not m:
            return s
        return max(sorted(m), key=lambda v: m[v])


class StringGrid:
    """A list of string rows with fingerprint clustering on a column."""

    def __init__(self, sep: str = ",", rows: List[List[str]] = None):
        self.sep = sep
        self.rows: List[List[str]] = rows or []

    @staticmethod
    def from_lines(lines: List[str], sep: str = ",") -> "StringGrid":
        return StringGrid(sep, [line.split(sep) for line in lines])

    def add_row(self, row: List[str]) -> None:
        self.rows.append(row)

    def get_column(self, col: int) -> List[str]:
        return [r[col] for r in self.rows]

    def cluster_column(self, col: int) -> Dict[str, List[int]]:
        """Row indices grouped by column fingerprint — rows in the same
        group are near-duplicates (`StringGrid.combineColumns` use case)."""
        groups: Dict[str, List[int]] = defaultdict(list)
        for i, r in enumerate(self.rows):
            groups[fingerprint(r[col])].append(i)
        return dict(groups)

    def dedup_by_column(self, col: int) -> "StringGrid":
        """Keep the first row of each fingerprint cluster."""
        keep = sorted(idx[0] for idx in self.cluster_column(col).values())
        return StringGrid(self.sep, [self.rows[i] for i in keep])

    def filter_rows_containing(self, col: int, text: str) -> "StringGrid":
        return StringGrid(
            self.sep, [r for r in self.rows if text in r[col]])

    def __len__(self) -> int:
        return len(self.rows)
